"""On-chip A/B of the fused one-pass GroupNorm kernel vs the XLA two-pass
path, at the bench working point.

Standalone microbenchmarks are unreliable on this harness (~200 ms
first-measurement bias through the TPU tunnel — .claude/skills/verify); the
ground truth is in-forward op time from an xplane trace. This driver runs a
short cached fast edit (the headline program) once per GroupNorm
implementation, traces both, and prints the per-family device-time tables
side by side plus the wall-clock of the measured call.

Usage:
  PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
      PYTHONPATH=/root/repo python tools/bench_groupnorm.py [steps]
"""

from __future__ import annotations

import collections
import os
import re
import shutil
import sys
import tempfile
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (_REPO, os.path.dirname(os.path.abspath(__file__))):
    if p not in sys.path:
        sys.path.insert(0, p)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _family(op_name: str) -> str:
    n = op_name.lower()
    # the GN kernel is ALSO a Pallas custom call — it carries an explicit
    # name= (ops/groupnorm.py pallas_call) precisely so this A/B can split
    # it from the attention kernel's custom calls
    if "fused_group_norm" in n:
        return "groupnorm (kernel)"
    if "custom-call" in n or "attn" in n and "fusion" not in n:
        return "attn (custom-call)"
    if n.startswith("convert") or "convert" in n.split(".")[0]:
        return "convert"
    if n.startswith("copy"):
        return "copy"
    if "convolution" in n:
        return "convolution"
    if n.startswith("fusion") or re.match(r".*fusion", n.split(".")[0] or ""):
        return "fusion"
    if n.startswith("while"):
        return "while (wrapper)"
    return "other"


def run_one(group_norm: str, steps: int):
    import bench

    wp = bench.build_fast_edit_working_point(
        num_frames=8, num_steps=steps, cached=True, group_norm=group_norm
    )
    # warm on a different input (server-side memoization; see verify skill)
    bench.hard_block(wp.e2e_cached(wp.params, wp.x_warm))
    tdir = tempfile.mkdtemp(prefix=f"gn_ab_{group_norm}_")
    opts = jax.profiler.ProfileOptions()
    opts.enable_hlo_proto = False
    opts.host_tracer_level = 0
    opts.python_tracer_level = 0
    jax.profiler.start_trace(tdir, profiler_options=opts)
    t0 = time.time()
    bench.hard_block(wp.e2e_cached(wp.params, wp.x0))
    wall = time.time() - t0
    jax.profiler.stop_trace()

    from profile_xplane import iter_device_events, module_device_span_seconds

    fams = collections.Counter()
    for name, ps in iter_device_events(tdir):
        fams[_family(name)] += ps
    span = module_device_span_seconds(tdir)
    shutil.rmtree(tdir, ignore_errors=True)
    del wp
    jax.clear_caches()
    return wall, span, {k: v / 1e12 for k, v in fams.items()}


def main() -> None:
    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(__doc__.strip())
        return
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    results = {}
    for impl in ("xla", "auto"):
        wall, span, fams = run_one(impl, steps)
        results[impl] = (wall, span, fams)
        print(f"\n=== group_norm={impl!r}: wall {wall:.3f}s, device span "
              f"{span:.3f}s ===")
        body = {k: v for k, v in fams.items() if k != "while (wrapper)"}
        total = sum(body.values())
        for fam, s in sorted(body.items(), key=lambda kv: -kv[1]):
            print(f"  {s:7.3f} s  {100 * s / max(total, 1e-9):5.1f} %  {fam}")

    if len(results) == 2:
        w_x, s_x, f_x = results["xla"]
        w_a, s_a, f_a = results["auto"]
        print(f"\nA/B at {steps} steps: xla {s_x:.3f}s → fused {s_a:.3f}s "
              f"device span ({100 * (s_x - s_a) / max(s_x, 1e-9):+.1f} % "
              f"saved); convert family "
              f"{f_x.get('convert', 0):.3f} → {f_a.get('convert', 0):.3f} s")


if __name__ == "__main__":
    main()
