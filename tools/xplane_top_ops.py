"""Top individual XLA ops by device time from an xplane trace dir (see
profile_xplane.py, which writes the trace and owns the proto walk — now
the stdlib wire-format reader in videop2p_tpu/obs/trace.py, so no
tensorflow install or protobuf env var is needed). Helps attribute
convert/copy time to specific tensors before optimizing."""

from __future__ import annotations

import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from profile_xplane import iter_device_events  # noqa: E402


def top_ops(trace_dir: str, top: int = 40) -> None:
    ops = collections.Counter()
    counts = collections.Counter()
    for name, ps in iter_device_events(trace_dir):
        ops[name] += ps
        counts[name] += 1
    total = sum(ops.values())
    print(f"total device op time: {total/1e12:.3f} s ({len(ops)} distinct ops)")
    for name, ps in ops.most_common(top):
        print(f"  {ps/1e12:8.3f} s  x{counts[name]:<5d} {name[:140]}")


def main(argv) -> int:
    if any(a in ("-h", "--help") for a in argv[1:]):
        print(__doc__.strip())
        return 0
    trace_dir = argv[1] if len(argv) > 1 else "/tmp/xplane_trace"
    if not os.path.isdir(trace_dir):
        print(f"xplane_top_ops: no trace dir {trace_dir!r}", file=sys.stderr)
        return 2
    top_ops(trace_dir, int(argv[2]) if len(argv) > 2 else 40)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
