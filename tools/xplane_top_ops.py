"""Top individual XLA ops by device time from an xplane trace dir (see
profile_xplane.py, which writes the trace). Helps attribute convert/copy time
to specific tensors before optimizing."""

from __future__ import annotations

import collections
import glob
import os
import sys

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def main(trace_dir: str, top: int = 40) -> None:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    ops = collections.Counter()
    counts = collections.Counter()
    for path in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True):
        xspace = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xspace.ParseFromString(f.read())
        for plane in xspace.planes:
            if "TPU" not in plane.name and "/device" not in plane.name.lower():
                continue
            ev_names = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    name = ev_names.get(ev.metadata_id, "?")
                    ops[name] += ev.duration_ps
                    counts[name] += 1
    total = sum(ops.values())
    print(f"total device op time: {total/1e12:.3f} s ({len(ops)} distinct ops)")
    for name, ps in ops.most_common(top):
        print(f"  {ps/1e12:8.3f} s  x{counts[name]:<5d} {name[:140]}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/xplane_trace",
         int(sys.argv[2]) if len(sys.argv) > 2 else 40)
