"""Render an incident bundle as a self-contained HTML post-mortem.

Usage:  python tools/incident_report.py <bundle_dir> [--out report.html]
                                        [--title TITLE] [--events N]

The incident plane's human face (ISSUE 18): reads the atomic bundle an
:class:`videop2p_tpu.obs.incident.IncidentManager` trigger wrote —
``manifest.json`` + the ``flight.jsonl`` ring dump + the ``series.npz``
tsdb snapshot + ``targets.json`` probe snapshots (+ ``crash.txt`` for
crash triggers) — and renders:

  * **the trigger** — kind, detail, wall/monotonic anchors, debounce
    accounting (suppressed repeats), ProgramSpec fingerprints, git sha,
    and the measured flight-recorder overhead (recorded, not asserted);
  * **timeline** — the flight ring's final events wall-ordered by the
    ledger's monotonic ``t``, with faults / breaker transitions / burn
    alerts / incidents highlighted so the minutes before the trigger
    read as a story;
  * **exemplar traces** — the reservoir ``p99_trace_id``/``max_trace_id``
    exemplars from the manifest joined against the ring's ``span``
    events into parent/child trees (a local re-join — the bundle is
    self-contained, no live ledger needed);
  * **series** — every tsdb series in the snapshot as a sparkline with
    the trigger instant marked;
  * **targets** — each registered target's ``/healthz``+``/metrics``
    snapshot at capture time (a dead target renders its error: the
    outage IS the evidence).

Everything is inline (CSS + SVG, no external assets) — the output ships
in a bug report. Tolerates partial bundles (no series → no sparklines,
no spans → no trace section).

stdlib+numpy+videop2p_tpu only — the import-guard test walks this file.
"""

from __future__ import annotations

import html
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.report import (  # noqa: E402
    _CSS,
    _fmt,
    _table,
)
from videop2p_tpu.obs.spans import SPAN_SEGMENTS  # noqa: E402
from videop2p_tpu.obs.tsdb import load_series_sidecar  # noqa: E402

# timeline rows that get the red highlight: the event kinds that usually
# ARE the story of an incident
_HOT_EVENTS = ("fault", "breaker", "incident", "stream_window_retry",
               "crash")


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Bundle JSONL → event dicts, skipping torn/blank lines."""
    events: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict):
                    events.append(ev)
    except OSError:
        pass
    return events


def _short(e: Dict[str, Any], limit: int = 140) -> str:
    """One event's payload as a compact k=v string for the timeline."""
    parts = []
    for k, v in e.items():
        if k in ("event", "t"):
            continue
        s = str(v)
        if len(s) > 48:
            s = s[:45] + "..."
        parts.append(f"{k}={s}")
    text = " ".join(parts)
    return text if len(text) <= limit else text[:limit - 3] + "..."


def _is_hot(e: Dict[str, Any]) -> bool:
    kind = str(e.get("event", ""))
    if any(kind.startswith(h) for h in _HOT_EVENTS):
        return True
    if kind == "fleet_signals" and e.get("burn_alert"):
        return True
    return bool(kind == "span" and e.get("status")
                not in ("ok", "cached", None))


def _timeline(events: Sequence[Dict[str, Any]], *, last_n: int) -> str:
    """The ring's final ``last_n`` events as a wall-ordered table; hot
    rows (faults, breaker flips, burn alerts, failed spans) highlighted."""
    tail = list(events)[-max(int(last_n), 1):]
    rows, classes = [], []
    for e in tail:
        rows.append([_fmt(e.get("t", "")), str(e.get("event", "?")),
                     _short(e)])
        classes.append("bad" if _is_hot(e) else "")
    note = (f"<p class=meta>last {len(tail)} of {len(events)} ring "
            "event(s); highlighted rows are faults / breaker transitions "
            "/ burn alerts / failed spans.</p>")
    return note + _table(rows, ["t (s)", "event", "detail"], classes)


# ---- exemplar trace join (local — the bundle must stand alone) ----------


def _trace_tree(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Parent/child join over one trace's spans: roots are spans whose
    ``parent_id`` is absent from the id set (an orphan — its parent
    scrolled off the ring — still renders, flagged)."""
    ids = {s.get("span_id") for s in spans}
    children: Dict[Any, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for s in sorted(spans, key=lambda s: (s.get("wall_ns") or 0)):
        pid = s.get("parent_id")
        if pid and pid in ids:
            children.setdefault(pid, []).append(s)
        else:
            s = dict(s)
            s["_orphan"] = bool(pid)
            roots.append(s)
    for s in roots:
        s.setdefault("_orphan", False)
    return [_attach(r, children) for r in roots]


def _attach(span: Dict[str, Any],
            children: Dict[Any, List[Dict[str, Any]]]) -> Dict[str, Any]:
    node = dict(span)
    node["_children"] = [_attach(c, children)
                         for c in children.get(span.get("span_id"), [])]
    return node


def _render_node(node: Dict[str, Any]) -> str:
    name = str(node.get("name", "?"))
    seg = SPAN_SEGMENTS.get(name)
    status = str(node.get("status", ""))
    bad = status not in ("ok", "cached", "")
    label = (f"<code>{html.escape(name)}</code>"
             + (f" <span class=meta>[{html.escape(seg)}]</span>" if seg else "")
             + f" {_fmt(node.get('duration_s'))}s"
             + (f" <span class=regressed>{html.escape(status)}</span>"
                if bad else f" <span class=meta>{html.escape(status)}</span>")
             + (" <span class=meta>(orphan — parent scrolled off the "
                "ring)</span>" if node.get("_orphan") else ""))
    kids = "".join(f"<li>{_render_node(c)}</li>"
                   for c in node.get("_children", []))
    return label + (f"<ul>{kids}</ul>" if kids else "")


def _exemplar_section(manifest: Dict[str, Any],
                      events: Sequence[Dict[str, Any]]) -> str:
    exemplars = manifest.get("exemplars") or {}
    if not exemplars:
        return ""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("event") == "span" and e.get("trace_id"):
            by_trace.setdefault(str(e["trace_id"]), []).append(e)
    out: List[str] = ["<h2>Exemplar traces</h2>",
                      "<p class=meta>the reservoir's p99/max trace-id "
                      "exemplars per program, joined against the ring's "
                      "span events (a trace with no spans left in the "
                      "ring lists id-only).</p>"]
    rows = []
    seen: List[str] = []
    for program, ex in sorted(exemplars.items()):
        for which in ("p99_trace_id", "max_trace_id"):
            tid = (ex or {}).get(which)
            rows.append([program, which.replace("_trace_id", ""),
                         tid or "-",
                         len(by_trace.get(str(tid), [])) if tid else 0])
            if tid and str(tid) not in seen:
                seen.append(str(tid))
    out.append(_table(rows, ["program", "exemplar", "trace_id",
                             "spans in ring"]))
    for tid in seen:
        spans = by_trace.get(tid)
        if not spans:
            continue
        out.append(f"<h3><code>{html.escape(tid)}</code> — "
                   f"{len(spans)} span(s)</h3>")
        out.append("<ul>" + "".join(
            f"<li>{_render_node(n)}</li>"
            for n in _trace_tree(spans)) + "</ul>")
    return "".join(out)


# ---- series sparklines with the trigger instant marked ------------------


def _spark_marked(pts: List[Tuple[float, float]], *, mark_t: Optional[float],
                  label: str, w: int = 260, h: int = 42) -> str:
    """Time-axis sparkline (non-finite points dropped, leaving holes)
    with a vertical tick at the trigger instant when it falls inside the
    series' span."""
    finite = [(t, v) for t, v in pts if math.isfinite(v)]
    if not finite:
        return f"<span class=meta>(no finite points) {html.escape(label)}</span>"
    t_lo, t_hi = pts[0][0], pts[-1][0]
    t_span = (t_hi - t_lo) or 1.0
    vals = [v for _, v in finite]
    lo, hi = min(vals), max(vals)
    v_span = (hi - lo) or 1.0
    coords = []
    for t, v in finite:
        x = 2 + (t - t_lo) / t_span * (w - 4)
        y = h - 3 - (v - lo) / v_span * (h - 6)
        coords.append(f"{x:.1f},{y:.1f}")
    mark = ""
    if mark_t is not None and t_lo <= mark_t <= t_hi:
        x = 2 + (mark_t - t_lo) / t_span * (w - 4)
        mark = (f'<line x1="{x:.1f}" y1="0" x2="{x:.1f}" y2="{h}" '
                f'stroke="#b22222" stroke-dasharray="3,2">'
                "<title>trigger instant</title></line>")
    return (f'<svg width="{w}" height="{h}">'
            f'<polyline fill="none" stroke="#7a4df0" stroke-width="1.5" '
            f'points="{" ".join(coords)}"/>{mark}</svg>'
            f"<span class=meta> {html.escape(label)}</span>")


def _series_section(manifest: Dict[str, Any], bundle: str) -> str:
    path = os.path.join(bundle, "series.npz")
    if not os.path.isfile(path):
        return ""
    try:
        series = load_series_sidecar(path)
    except Exception:  # noqa: BLE001 — a torn sidecar skips sparklines
        return "<h2>Series</h2><p class=meta>(series.npz unreadable)</p>"
    mark_t = manifest.get("monotonic_s")
    mark_t = float(mark_t) if isinstance(mark_t, (int, float)) else None
    out = ["<h2>Series</h2>",
           "<p class=meta>the tsdb snapshot captured with the bundle; "
           "the dashed red tick is the trigger instant (shown when the "
           "series and the trigger share a clock — the in-process "
           "collector's case).</p>"]
    for key in sorted(series):
        pts = series[key]
        vals = [v for _, v in pts]
        gaps = sum(1 for v in vals if not math.isfinite(v))
        label = (f"{key} — {len(vals)} pts"
                 + (f", {gaps} gaps" if gaps else ""))
        out.append("<div class=row>"
                   + _spark_marked(pts, mark_t=mark_t, label=label)
                   + "</div>")
    return "".join(out)


# ---- targets ------------------------------------------------------------


def _targets_section(bundle: str) -> str:
    path = os.path.join(bundle, "targets.json")
    if not os.path.isfile(path):
        return ""
    try:
        with open(path) as f:
            snaps = json.load(f)
    except (OSError, ValueError):
        return "<h2>Targets</h2><p class=meta>(targets.json unreadable)</p>"
    if not isinstance(snaps, dict) or not snaps:
        return ""
    rows, classes = [], []
    for name, snap in sorted(snaps.items()):
        if not isinstance(snap, dict) or "error" in snap:
            err = snap.get("error") if isinstance(snap, dict) else snap
            rows.append([name, "unreachable", str(err), "-", "-"])
            classes.append("bad")
            continue
        hz = snap.get("healthz") or {}
        mt = snap.get("metrics") or {}
        status = str(hz.get("status", "?"))
        rows.append([
            name, status,
            "ok" if hz.get("ok") else "NOT ok",
            _fmt(mt.get("queue_depth", "-")),
            _fmt(mt.get("in_flight", "-")),
        ])
        classes.append("" if hz.get("ok") and status == "ok" else "bad")
    return ("<h2>Targets</h2>"
            "<p class=meta>/healthz + /metrics from every registered "
            "target at capture time; an unreachable target is evidence, "
            "not an omission.</p>"
            + _table(rows, ["target", "status", "healthz", "queue",
                            "in_flight"], classes))


# ---- the page -----------------------------------------------------------


def render_report(bundle: str, *, title: Optional[str] = None,
                  last_n: int = 120) -> str:
    """One self-contained HTML post-mortem from a bundle directory."""
    try:
        with open(os.path.join(bundle, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = {}
    events = _read_jsonl(os.path.join(bundle, "flight.jsonl"))
    trigger = str(manifest.get("trigger", "?"))
    title = title or f"Incident: {trigger}"
    body: List[str] = [
        f"<h1>{html.escape(title)}</h1>",
        f"<p class=meta>bundle <code>{html.escape(os.path.basename(os.path.abspath(bundle)))}</code>"
        f" · id <code>{html.escape(str(manifest.get('bundle_id', '?')))}</code>"
        f" · {html.escape(str(manifest.get('wall_time', '?')))}"
        f" · generated by tools/incident_report.py (stdlib+numpy, all "
        "assets inline)</p>",
    ]
    if manifest.get("detail"):
        body.append(f"<p><b>{html.escape(str(manifest['detail']))}</b></p>")
    flight = manifest.get("flight") or {}
    rows = [[k, _fmt(v)] for k, v in (
        ("trigger", trigger),
        ("suppressed since last bundle",
         manifest.get("suppressed_since_last")),
        ("cooldown (s)", manifest.get("cooldown_s")),
        ("pid / host", f"{manifest.get('pid')} / "
                       f"{manifest.get('hostname')}"),
        ("git sha", manifest.get("git_sha")),
        ("ring buffered / seen / dropped",
         f"{flight.get('buffered')} / {flight.get('seen')} / "
         f"{flight.get('dropped')}"),
        ("flight record cost (ns, measured)",
         manifest.get("flight_record_ns")),
    ) if v is not None]
    body.append("<h2>Trigger</h2>" + _table(rows, ["field", "value"]))
    ctx = manifest.get("context") or {}
    if ctx:
        body.append("<h3>Context</h3>" + _table(
            [[k, _fmt(v)] for k, v in sorted(ctx.items())],
            ["key", "value"]))
    fps = manifest.get("fingerprints") or {}
    if fps:
        body.append("<h3>ProgramSpec fingerprints</h3>" + _table(
            [[k, _fmt(v)] for k, v in sorted(fps.items())],
            ["spec", "fingerprint"]))
    crash_path = os.path.join(bundle, "crash.txt")
    if os.path.isfile(crash_path):
        try:
            with open(crash_path) as f:
                crash = f.read()
        except OSError:
            crash = "(crash.txt unreadable)"
        body.append("<h2>Crash</h2><pre style='white-space:pre-wrap;"
                    "font-size:.8em;background:#fde4e1;padding:.6em'>"
                    + html.escape(crash[:20000]) + "</pre>")
    if events:
        body.append("<h2>Timeline</h2>" + _timeline(events, last_n=last_n))
    else:
        body.append("<h2>Timeline</h2><p class=meta>(flight.jsonl empty "
                    "or missing — the ring had no events)</p>")
    body.append(_exemplar_section(manifest, events))
    body.append(_series_section(manifest, bundle))
    body.append(_targets_section(bundle))
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>{_CSS}</style>"
            "</head><body>" + "".join(b for b in body if b)
            + "</body></html>")


def write_report(bundle: str, out_path: Optional[str] = None,
                 *, title: Optional[str] = None, last_n: int = 120) -> str:
    """Render a bundle dir into a self-contained HTML file inside it."""
    bundle = str(bundle).rstrip("/")
    if not os.path.isfile(os.path.join(bundle, "manifest.json")):
        raise OSError(f"{bundle}: not an incident bundle "
                      "(no manifest.json)")
    out_path = out_path or os.path.join(bundle, "report.html")
    text = render_report(bundle, title=title, last_n=last_n)
    with open(out_path, "w") as f:
        f.write(text)
    return out_path


def main(argv: List[str]) -> int:
    if any(a in ("-h", "--help") for a in argv[1:]):
        print(__doc__.strip())
        return 0
    args = list(argv[1:])
    out: Optional[str] = None
    title: Optional[str] = None
    last_n = 120
    rest: List[str] = []
    i = 0
    while i < len(args):
        if args[i] == "--out" and i + 1 < len(args):
            out = args[i + 1]
            i += 2
        elif args[i] == "--title" and i + 1 < len(args):
            title = args[i + 1]
            i += 2
        elif args[i] == "--events" and i + 1 < len(args):
            last_n = int(args[i + 1])
            i += 2
        else:
            rest.append(args[i])
            i += 1
    if len(rest) != 1:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    try:
        path = write_report(rest[0], out, title=title, last_n=last_n)
    except OSError as e:
        print(f"incident_report: {e}", file=sys.stderr)
        return 2
    print(path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
