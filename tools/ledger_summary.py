"""Render a run-ledger JSONL (videop2p_tpu/obs/ledger.py) as tables.

Usage:  python tools/ledger_summary.py <ledger.jsonl>

Prints the run header (run_id / git sha / jax / backend), a per-phase
wall-clock table, a per-program compile-vs-execute table (compile events
attributed by program label, program_call dispatch times with cache
hit/miss counts), telemetry summaries with a loss-curve sparkline for the
fused null-text program, training-metric and memory-snapshot digests.
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.ledger import read_ledger  # noqa: E402
from videop2p_tpu.obs.telemetry import sparkline  # noqa: E402


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    lines = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(str(c).ljust(w) for c, w in zip(r, widths))
              for r in rows]
    return "\n".join(lines)


def render(events: List[Dict]) -> str:
    """The full summary as one string (pure — tests feed synthetic events)."""
    out: List[str] = []
    start = next((e for e in events if e.get("event") == "run_start"), {})
    out.append(
        f"run {start.get('run_id', '?')}  "
        f"sha={start.get('git_sha', '?')}  jax={start.get('jax_version', '?')}  "
        f"backend={start.get('backend', '?')}×{start.get('device_count', '?')}  "
        f"mesh={start.get('mesh')}  at={start.get('wall_time', '?')}"
    )

    phases: Dict[str, List[float]] = defaultdict(list)
    for e in events:
        if e.get("event") == "phase":
            phases[e.get("name", "?")].append(float(e.get("seconds", 0.0)))
    if phases:
        rows = [[name, len(ts), f"{sum(ts):.2f}", f"{ts[-1]:.2f}"]
                for name, ts in phases.items()]
        rows.sort(key=lambda r: -float(r[2]))
        out += ["", "phases:", _table(rows, ["phase", "calls", "total_s", "last_s"])]

    compiles: Dict[str, List[float]] = defaultdict(list)
    calls: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"n": 0, "miss": 0, "dispatch_s": 0.0}
    )
    for e in events:
        if e.get("event") == "compile":
            compiles[e.get("program") or "(unattributed)"].append(
                float(e.get("seconds", 0.0))
            )
        elif e.get("event") == "program_call":
            c = calls[e.get("program") or "(unattributed)"]
            c["n"] += 1
            c["miss"] += 1 if e.get("cache_miss") else 0
            c["dispatch_s"] += float(e.get("dispatch_s", 0.0))
    if compiles or calls:
        rows = []
        for prog in sorted(set(compiles) | set(calls)):
            cs, c = compiles.get(prog, []), calls.get(prog)
            rows.append([
                prog, len(cs), f"{sum(cs):.2f}",
                int(c["n"]) if c else 0,
                int(c["miss"]) if c else 0,
                f"{c['dispatch_s']:.2f}" if c else "-",
            ])
        out += ["", "programs (compile vs execute):",
                _table(rows, ["program", "compiles", "compile_s",
                              "calls", "misses", "execute_s"])]

    tel_lines: List[str] = []
    for e in events:
        if e.get("event") != "telemetry":
            continue
        prog = e.get("program", "?")
        if e.get("loss_curve"):
            tel_lines.append(
                f"  {prog}: loss {sparkline(e['loss_curve'])} "
                f"(final {e.get('loss_final')}), inner steps "
                f"{e.get('inner_steps_total')} total"
            )
        summary = e.get("summary") or e.get("latent")
        if summary:
            nan = summary.get("nan_total", 0)
            tel_lines.append(
                f"  {prog}: abs_max peak {summary.get('abs_max_peak')} / "
                f"final {summary.get('abs_max_final')}, NaN {nan}"
                + (f" (FIRST AT STEP {summary.get('first_nan_step')})"
                   if nan else "")
            )
        if e.get("telemetry_overhead_pct") is not None:
            tel_lines.append(
                f"  {prog}: telemetry overhead "
                f"{e['telemetry_overhead_pct']}% "
                f"({e.get('telemetry_off_s')}s off → "
                f"{e.get('telemetry_on_s')}s on)"
            )
    if tel_lines:
        out += ["", "telemetry:"] + tel_lines

    metric_events = [e for e in events if e.get("event") == "metric"]
    if metric_events:
        last = metric_events[-1]
        curve = [e["train_loss"] for e in metric_events if "train_loss" in e]
        line = (f"  {len(metric_events)} steps logged, final "
                + ", ".join(f"{k}={v}" for k, v in last.items()
                            if k not in ("event", "t")))
        out += ["", "train metrics:", line]
        if curve:
            out.append(f"  loss {sparkline(curve)}")

    mems = [e for e in events if e.get("event") == "memory" and e.get("supported")]
    if mems:
        peak = max(
            (d.get("peak_bytes_in_use") or 0)
            for e in mems for d in e.get("devices", [])
        )
        out += ["", f"memory: {len(mems)} snapshots, peak "
                f"{peak / 2**30:.2f} GiB in use"]

    end = next((e for e in events if e.get("event") == "run_end"), None)
    if end is not None:
        out += ["", f"run ended at t={end.get('t')}s "
                f"({end.get('compile_events', 0)} compile events)"]
    return "\n".join(out)


def main(argv: List[str]) -> int:
    if len(argv) != 2:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    print(render(read_ledger(argv[1])))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
