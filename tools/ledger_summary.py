"""Render a run-ledger JSONL (videop2p_tpu/obs/ledger.py) as tables.

Usage:  python tools/ledger_summary.py <ledger.jsonl>

Prints the run header (run_id / git sha / jax / backend), a per-phase
wall-clock table, a per-program compile-vs-execute table (compile events
attributed by program label, program_call dispatch times with cache
hit/miss counts), a per-program XLA cost/memory-analysis table
(``program_analysis`` events — flops, bytes, temp/peak HBM, HLO
fingerprint) with a predicted-vs-measured peak-HBM line when memory
snapshots exist, telemetry summaries with a loss-curve sparkline for the
fused null-text program, training-metric and memory-snapshot digests.
Distributed runs additionally get a collective-communication table
(``comm_analysis`` events — obs/comm.py per-kind counts/bytes), per-device
telemetry lines with the cross-replica divergence (must be 0.0),
``program_analysis_skipped`` reasons, and a per-host phase-skew table when
``host_phase`` events exist (multi-host straggler visibility). Time-domain
runs (``--latency`` / ``--trace_analysis``) additionally get a per-program
execute-timing table (blocked-latency p50/p95/p99/max with the
dispatch-vs-blocked async-overlap split) and a trace-analysis table
(device/compute/collective seconds, the compute-collective overlap
fraction, idle time, op families). Ledgers written before these events
existed render exactly as before — the sections simply don't appear.

Tolerates empty ledgers and truncated/partial JSONL lines (a killed run's
torn tail): malformed events render as far as their fields allow instead
of crashing the renderer. Diff two ledgers with ``tools/obs_diff.py``.
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.ledger import read_ledger  # noqa: E402
from videop2p_tpu.obs.telemetry import sparkline  # noqa: E402


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    lines = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(str(c).ljust(w) for c, w in zip(r, widths))
              for r in rows]
    return "\n".join(lines)


def _f(v, default: float = 0.0) -> float:
    """Float, tolerating the junk a torn/partial JSONL line can carry."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _mb(v) -> str:
    return f"{_f(v) / 2**20:.1f}M"


def render(events: List[Dict]) -> str:
    """The full summary as one string (pure — tests feed synthetic events).
    Tolerant of empty event lists and partial events: every field access
    degrades to a placeholder rather than raising."""
    events = [e for e in events if isinstance(e, dict)]
    if not events:
        return "(empty ledger — no events)"
    out: List[str] = []
    start = next((e for e in events if e.get("event") == "run_start"), {})
    out.append(
        f"run {start.get('run_id', '?')}  "
        f"sha={start.get('git_sha', '?')}  jax={start.get('jax_version', '?')}  "
        f"backend={start.get('backend', '?')}×{start.get('device_count', '?')}  "
        f"mesh={start.get('mesh')}  at={start.get('wall_time', '?')}"
    )

    phases: Dict[str, List[float]] = defaultdict(list)
    for e in events:
        if e.get("event") == "phase":
            phases[e.get("name") or "?"].append(_f(e.get("seconds")))
    if phases:
        rows = [[name, len(ts), f"{sum(ts):.2f}", f"{ts[-1]:.2f}"]
                for name, ts in phases.items()]
        rows.sort(key=lambda r: -float(r[2]))
        out += ["", "phases:", _table(rows, ["phase", "calls", "total_s", "last_s"])]

    compiles: Dict[str, List[float]] = defaultdict(list)
    calls: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"n": 0, "miss": 0, "dispatch_s": 0.0}
    )
    for e in events:
        if e.get("event") == "compile":
            compiles[e.get("program") or "(unattributed)"].append(
                _f(e.get("seconds"))
            )
        elif e.get("event") == "program_call":
            c = calls[e.get("program") or "(unattributed)"]
            c["n"] += 1
            c["miss"] += 1 if e.get("cache_miss") else 0
            c["dispatch_s"] += _f(e.get("dispatch_s"))
    if compiles or calls:
        rows = []
        for prog in sorted(set(compiles) | set(calls)):
            cs, c = compiles.get(prog, []), calls.get(prog)
            rows.append([
                prog, len(cs), f"{sum(cs):.2f}",
                int(c["n"]) if c else 0,
                int(c["miss"]) if c else 0,
                f"{c['dispatch_s']:.2f}" if c else "-",
            ])
        out += ["", "programs (compile vs execute):",
                _table(rows, ["program", "compiles", "compile_s",
                              "calls", "misses", "execute_s"])]

    # program_analysis: what XLA built per program (obs/introspect.py)
    analyses: Dict[str, Dict] = {}
    for e in events:
        if e.get("event") == "program_analysis":
            analyses[e.get("program") or "(unattributed)"] = e
    if analyses:
        rows = [[
            prog,
            f"{_f(a.get('flops')) / 1e9:.2f}G",
            _mb(a.get("bytes_accessed")),
            _mb(a.get("temp_bytes")),
            _mb(a.get("peak_hbm_bytes")),
            str(a.get("hlo_instructions", "-")),
            str(a.get("hlo_fingerprint", "-")),
        ] for prog, a in sorted(analyses.items())]
        out += ["", "program analysis (XLA cost/memory of the compiled "
                "programs):",
                _table(rows, ["program", "flops", "bytes", "temp",
                              "peak_hbm", "instrs", "hlo_fingerprint"])]

    # comm_analysis: collective accounting of the sharded programs
    # (obs/comm.py) — static per-module counts/bytes, keyed like the
    # program-analysis table
    comms: Dict[str, Dict] = {}
    for e in events:
        if e.get("event") == "comm_analysis":
            comms[e.get("program") or "(unattributed)"] = e
    if comms:
        rows = []
        for prog, c in sorted(comms.items()):
            per_kind = c.get("per_kind") or {}
            kinds = ", ".join(
                f"{k}×{v.get('count', '?')}" for k, v in sorted(per_kind.items())
                if isinstance(v, dict)
            ) or "-"
            rows.append([
                prog, str(c.get("num_partitions", "-")),
                str(c.get("collective_count", "-")),
                _mb(c.get("collective_bytes")), kinds,
            ])
        out += ["", "collectives (comm_analysis — static counts/bytes of "
                "the partitioned programs):",
                _table(rows, ["program", "partitions", "collectives",
                              "bytes", "per-kind"])]

    # execute_timing: per-dispatch latency distributions (obs/timing.py
    # reservoirs behind --latency) — the serving-SLO view of each program
    timing: Dict[str, Dict] = {}
    for e in events:
        if e.get("event") == "execute_timing":
            timing[e.get("program") or "(unattributed)"] = e
    if timing:
        rows = []
        for prog, t in sorted(timing.items()):
            rows.append([
                prog, str(t.get("count", "-")),
                f"{_f(t.get('blocked_p50_s')) * 1e3:.1f}",
                f"{_f(t.get('blocked_p95_s')) * 1e3:.1f}",
                f"{_f(t.get('blocked_p99_s')) * 1e3:.1f}",
                f"{_f(t.get('blocked_max_s')) * 1e3:.1f}",
                f"{_f(t.get('dispatch_fraction')):.2f}",
            ])
        out += ["", "execute timing (blocked latency ms per dispatch; "
                "dispatch/blocked ~0 = async overlap working):",
                _table(rows, ["program", "calls", "p50", "p95", "p99",
                              "max", "disp/blk"])]

    # trace_analysis: mined device traces (obs/trace.py stdlib xplane
    # reader) — where device time actually went during the traced window
    trace_rows = []
    trace_extra: List[str] = []
    for e in events:
        if e.get("event") != "trace_analysis":
            continue
        ov = e.get("overlap_fraction")
        trace_rows.append([
            e.get("name", "?"),
            f"{_f(e.get('device_total_s')):.3f}",
            f"{_f(e.get('compute_s')):.3f}",
            f"{_f(e.get('collective_s')):.3f}",
            "-" if ov is None else f"{_f(ov):.2f}",
            f"{_f(e.get('idle_s')):.3f}",
            str(e.get("num_events", "-")),
        ])
        fams = e.get("families") or {}
        if isinstance(fams, dict) and fams:
            top = sorted(fams.items(), key=lambda kv: -_f(kv[1]))[:6]
            trace_extra.append(
                f"  {e.get('name', '?')} families: "
                + ", ".join(f"{k}={_f(v):.3f}s" for k, v in top)
            )
    if trace_rows:
        out += ["", "trace analysis (device time during traced windows; "
                "overlap = collective time hidden under compute):",
                _table(trace_rows, ["window", "total_s", "compute_s",
                                    "collective_s", "overlap", "idle_s",
                                    "events"])] + trace_extra

    skipped: Dict[str, str] = {}
    for e in events:
        if e.get("event") == "program_analysis_skipped":
            skipped[e.get("program") or "(unattributed)"] = str(
                e.get("reason", "?")
            )
    if skipped:
        out += ["", "program analysis skipped:"] + [
            f"  {prog}: {reason}" for prog, reason in sorted(skipped.items())
        ]

    dev_lines: List[str] = []
    for e in events:
        if e.get("event") != "device_telemetry":
            continue
        div = e.get("divergence_max")
        peaks = e.get("per_device_abs_max_peak") or []
        line = (f"  {e.get('program', '?')}: {e.get('devices', '?')} devices"
                f", divergence max {div} / final {e.get('divergence_final')}"
                f", NaN {e.get('nan_total', 0)}")
        if peaks:
            line += (f", abs_max peak spread "
                     f"[{min(map(_f, peaks)):.4g}, {max(map(_f, peaks)):.4g}]")
        if _f(div):
            line += "  <-- REPLICAS DIVERGED (must be 0.0)"
        dev_lines.append(line)
    for e in events:
        if e.get("event") != "divergence":
            continue
        val = _f(e.get("value"))
        dev_lines.append(
            f"  {e.get('label', '?')}: divergence {e.get('value')}"
            + ("  <-- REPLICAS DIVERGED (must be 0.0)" if val else "")
        )
    if dev_lines:
        out += ["", "per-device telemetry / replica divergence:"] + dev_lines

    host_phases = [e for e in events if e.get("event") == "host_phase"]
    if host_phases:
        # the skew math lives next to the event producer
        from videop2p_tpu.parallel.distributed import phase_skew

        rows = [[name, s["hosts"], f"{s['min_s']:.2f}", f"{s['max_s']:.2f}",
                 f"{s['skew_s']:.2f}", s["slowest_process"]]
                for name, s in sorted(phase_skew(host_phases).items())]
        out += ["", "per-host phase skew (straggler visibility):",
                _table(rows, ["phase", "hosts", "min_s", "max_s",
                              "skew_s", "slowest_proc"])]

    tel_lines: List[str] = []
    for e in events:
        if e.get("event") != "telemetry":
            continue
        prog = e.get("program", "?")
        if e.get("loss_curve"):
            try:
                spark = sparkline(e["loss_curve"])
            except (TypeError, ValueError):
                spark = "?"
            tel_lines.append(
                f"  {prog}: loss {spark} "
                f"(final {e.get('loss_final')}), inner steps "
                f"{e.get('inner_steps_total')} total"
            )
        summary = e.get("summary") or e.get("latent")
        if isinstance(summary, dict):
            nan = summary.get("nan_total", 0)
            tel_lines.append(
                f"  {prog}: abs_max peak {summary.get('abs_max_peak')} / "
                f"final {summary.get('abs_max_final')}, NaN {nan}"
                + (f" (FIRST AT STEP {summary.get('first_nan_step')})"
                   if nan else "")
            )
        if e.get("telemetry_overhead_pct") is not None:
            tel_lines.append(
                f"  {prog}: telemetry overhead "
                f"{e['telemetry_overhead_pct']}% "
                f"({e.get('telemetry_off_s')}s off → "
                f"{e.get('telemetry_on_s')}s on)"
            )
    if tel_lines:
        out += ["", "telemetry:"] + tel_lines

    # edit-quality events (obs/quality.py) — the semantic numbers next to
    # the perf ones; arrays live in the .npz sidecar the event references
    for e in events:
        if e.get("event") != "quality":
            continue
        vals = ", ".join(
            f"{k}={v}" for k, v in e.items()
            if k not in ("event", "t", "program", "sidecar")
            and isinstance(v, (int, float))
        )
        out += ["", f"quality ({e.get('program', '?')}): {vals}"]
    attn_evs = [e for e in events if e.get("event") == "attn_maps"]
    if attn_evs:
        out += ["", "attention capture:"]
        for e in attn_evs:
            out.append(
                f"  {e.get('scope', '?')}: {e.get('steps', '?')} steps, "
                f"heat {e.get('heat_shape')}, "
                f"{len(e.get('sites') or [])} sites "
                f"(sidecar {e.get('sidecar', '-')})"
            )
    trace_evs = [e for e in events if e.get("event") == "trace"]
    if trace_evs:
        out += ["", "device traces:"] + [
            f"  {e.get('name', '?')} → {e.get('trace_dir', '?')}"
            for e in trace_evs
        ]

    metric_events = [e for e in events if e.get("event") == "metric"]
    if metric_events:
        last = metric_events[-1]
        curve = [e["train_loss"] for e in metric_events if "train_loss" in e]
        line = (f"  {len(metric_events)} steps logged, final "
                + ", ".join(f"{k}={v}" for k, v in last.items()
                            if k not in ("event", "t")))
        out += ["", "train metrics:", line]
        if curve:
            try:
                out.append(f"  loss {sparkline(curve)}")
            except (TypeError, ValueError):
                pass

    mems = [e for e in events if e.get("event") == "memory" and e.get("supported")]
    if mems:
        peak = max(
            (_f(d.get("peak_bytes_in_use")) for e in mems
             for d in (e.get("devices") or []) if isinstance(d, dict)),
            default=0.0,
        )
        out += ["", f"memory: {len(mems)} snapshots, peak "
                f"{peak / 2**30:.2f} GiB in use"]
        # per-device residency: worst peak per device id across snapshots
        # (sharded runs — one line only when >1 device reported stats)
        per_dev: Dict[str, float] = {}
        for e in mems:
            for d in e.get("devices") or []:
                if isinstance(d, dict) and d.get("peak_bytes_in_use") is not None:
                    key = f"device{d.get('device')}"
                    per_dev[key] = max(per_dev.get(key, 0.0),
                                       _f(d.get("peak_bytes_in_use")))
        if len(per_dev) > 1:
            out.append("  per-device peak: " + ", ".join(
                f"{k}={v / 2**30:.2f}G" for k, v in sorted(per_dev.items())
            ))
        # predicted-vs-measured: the largest per-program peak-HBM estimate
        # (XLA memory_analysis) against the device's measured peak — the
        # HBM-gate sanity line (predicted covers ONE program's residency;
        # measured can exceed it when executables/buffers coexist)
        if analyses:
            pred_prog, pred = max(
                ((p, _f(a.get("peak_hbm_bytes"))) for p, a in analyses.items()),
                key=lambda kv: kv[1],
            )
            if pred > 0 and peak > 0:
                out.append(
                    f"  predicted peak-HBM (largest program, {pred_prog}): "
                    f"{pred / 2**30:.2f} GiB vs measured {peak / 2**30:.2f} "
                    f"GiB ({peak / pred:.2f}× predicted)"
                )

    # fleet telemetry plane (ISSUE 17): fleet_signals evaluations from
    # the collector's signal engine + the fleet_series tsdb snapshot
    sig_evs = [e for e in events if e.get("event") == "fleet_signals"]
    if sig_evs:
        last = sig_evs[-1]
        advice_seq = "".join(
            {"grow": "G", "hold": ".", "shrink": "s"}.get(
                str(e.get("scale_advice")), "?")
            for e in sig_evs
        )
        out += ["", "fleet signals (obs/signals.py over the scraped tsdb):",
                f"  {len(sig_evs)} evaluations, burn alerts "
                f"{last.get('burn_alerts', 0)}, advice timeline [{advice_seq}]"
                f" (G=grow .=hold s=shrink)",
                f"  last: burn fast={_f(last.get('burn_fast')):.2f} "
                f"slow={_f(last.get('burn_slow')):.2f}  "
                f"saturation={_f(last.get('saturation')):.2f}  "
                f"queue_slope={_f(last.get('queue_slope')):.4f}/s  "
                f"replicas {last.get('replicas_up', '?')}/"
                f"{last.get('replicas_total', '?')} up  "
                f"scrape_errors={_f(last.get('scrape_errors')):.0f} "
                f"(rate {_f(last.get('scrape_error_rate')):.3f})  "
                f"advice={last.get('scale_advice', '?')}"]
        for reason in last.get("reasons") or []:
            out.append(f"    reason: {reason}")
        tenants = last.get("tenants")
        if isinstance(tenants, dict) and tenants:
            rows = [[t,
                     f"{_f(v.get('submitted_rate')):.3f}",
                     f"{_f(v.get('served_rate')):.3f}",
                     f"{_f(v.get('shed_rate')):.3f}",
                     f"{_f(v.get('device_seconds')):.3f}"]
                    for t, v in sorted(tenants.items())
                    if isinstance(v, dict)]
            out += ["", "  per-tenant demand (rates over the slow window):",
                    _table(rows, ["tenant", "submit/s", "served/s",
                                  "shed/s", "device_s"])]
    for e in events:
        if e.get("event") != "fleet_series":
            continue
        out += ["", f"fleet series ({e.get('label', '?')}): "
                f"{e.get('series', '?')} series / {e.get('samples', '?')} "
                f"samples, {e.get('gaps', 0)} gaps, {e.get('dropped', 0)} "
                f"dropped, span [{e.get('t_first')}, {e.get('t_last')}]s "
                f"(sidecar {e.get('sidecar', '-')})"]

    end = next((e for e in events if e.get("event") == "run_end"), None)
    if end is not None:
        out += ["", f"run ended at t={end.get('t')}s "
                f"({end.get('compile_events', 0)} compile events)"]
    return "\n".join(out)


def main(argv: List[str]) -> int:
    if any(a in ("-h", "--help") for a in argv[1:]):
        print(__doc__.strip())
        return 0
    if len(argv) != 2:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    try:
        events = read_ledger(argv[1])
    except OSError as e:
        print(f"ledger_summary: cannot read {argv[1]}: {e}", file=sys.stderr)
        return 2
    print(render(events))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
