"""Synthesize example video clips for the shipped configs.

The reference ships 8-frame 512x512 jpg sequences under data/<scene>/1..8.jpg
(/root/reference/data; tiger & bird_forest are referenced by configs but not
shipped). Real footage cannot be redistributed here, so this tool draws
deterministic moving-shape clips with the same layout — enough to drive every
config end-to-end (tuning, inversion, editing) and to eyeball temporal
coherence in the output GIFs.

Run:  python tools/make_example_data.py [--size 512] [--frames 8] [--out data]
"""

from __future__ import annotations

import argparse
import os
import zlib

import numpy as np
from PIL import Image, ImageDraw

SCENES = {
    # scene dir -> (sky/top colour, ground/bottom colour, subject colour)
    "rabbit": ((150, 200, 255), (90, 170, 80), (230, 230, 225)),
    "car": ((170, 190, 210), (110, 110, 115), (200, 40, 40)),
    "tiger": ((60, 90, 50), (80, 120, 60), (235, 140, 40)),
    "motorbike": ((70, 100, 60), (100, 90, 70), (40, 60, 200)),
    "penguin_ice": ((190, 220, 240), (235, 240, 250), (30, 30, 40)),
    "bird_forest": ((120, 170, 220), (40, 80, 45), (90, 60, 130)),
}


def draw_frame(scene: str, t: int, num_frames: int, size: int) -> Image.Image:
    top, bottom, subject = SCENES[scene]
    img = Image.new("RGB", (size, size))
    d = ImageDraw.Draw(img)
    horizon = int(size * 0.6)
    d.rectangle([0, 0, size, horizon], fill=top)
    d.rectangle([0, horizon, size, size], fill=bottom)
    # textured background stripes so inversion has structure to reconstruct
    rng = np.random.default_rng(zlib.crc32(scene.encode()))
    for _ in range(12):
        x = int(rng.uniform(0, size))
        w = int(rng.uniform(8, 30))
        shade = tuple(int(c * rng.uniform(0.75, 1.1)) for c in bottom)
        d.rectangle([x, horizon, x + w, size], fill=shade)
    # the subject sweeps left→right with a bob, like a walking/jumping animal
    frac = t / max(num_frames - 1, 1)
    cx = int(size * (0.25 + 0.5 * frac))
    cy = int(horizon - size * 0.08 * abs(np.sin(np.pi * 2 * frac)))
    r = size // 8
    d.ellipse([cx - r, cy - r, cx + r, cy + r], fill=subject)
    d.ellipse(
        [cx + r // 2, cy - r - r // 2, cx + r + r // 2, cy - r // 2], fill=subject
    )  # head
    return img


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=512)
    ap.add_argument("--frames", type=int, default=8)
    ap.add_argument("--out", type=str, default="data")
    args = ap.parse_args()
    for scene in SCENES:
        out_dir = os.path.join(args.out, scene)
        os.makedirs(out_dir, exist_ok=True)
        for t in range(args.frames):
            frame = draw_frame(scene, t, args.frames, args.size)
            frame.save(os.path.join(out_dir, f"{t + 1}.jpg"), quality=92)
        print(f"wrote {args.frames} frames to {out_dir}")


if __name__ == "__main__":
    main()
