"""Join span ledgers into causal trace trees (the tracing CLI, ISSUE 14).

Reads one or MANY run ledgers (a loadgen ledger, a router ledger, N
replica ledgers — rotation chains included, ``read_ledger`` follows
them), collects every ``span`` event, groups by ``trace_id`` and renders
each trace as an ASCII tree ordered by the spans' wall-clock anchors:

    python tools/trace_view.py loadgen.jsonl router_ledger.jsonl \\
        serve_out/replica*/ledger.jsonl

    trace 3f2a...  spans=6  ledgers=3  duration=2.104s
      critical path: queue 0.412s | resolve 1.203s | dispatch 0.377s | decode 0.093s
      loadgen.request  2.104s  ok  [loadgen.jsonl]
        router.submit  0.009s  ok  replica=replica1  [router_ledger.jsonl]
          serve.request  2.080s  done  rid=ab12...  [replica1/ledger.jsonl]
            serve.queue  0.412s  ok
            serve.resolve  1.203s  ok  store=disk
            ...

Spans whose parent lives in a DIFFERENT ledger join transparently — the
trace_id+parent_id links are the join keys; no shared clock or process
state is assumed. A span whose parent was never recorded (a replica
ledger viewed alone) renders as a root marked ``(orphan)`` rather than
vanishing.

``--json`` emits one machine-readable document (per-trace span lists +
the per-segment critical-path split + per-segment aggregate p50/p99
across traces) for CI. ``--trace ID`` filters to one trace. Exit codes:
0 = rendered (even zero spans — a tracing-off ledger is empty, not
broken), 2 = an input file was unreadable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.ledger import read_ledger  # noqa: E402
from videop2p_tpu.obs.spans import SPAN_SEGMENTS  # noqa: E402
from videop2p_tpu.obs.timing import percentile  # noqa: E402

# span attributes worth showing inline in the tree (identity/topology —
# not the timing fields, which get their own columns)
_ATTR_KEYS = ("rid", "replica", "tenant", "index", "batch_id",
              "batch_size", "store_source", "steps", "attempts", "cached")


def load_spans(paths: List[str]) -> List[Dict[str, Any]]:
    """Every ``span`` event across the ledgers, tagged with its source
    ledger's basename. Raises OSError/ValueError on an unreadable path."""
    spans: List[Dict[str, Any]] = []
    for path in paths:
        if not os.path.exists(path):
            raise OSError(f"no such ledger: {path}")
        label = os.path.basename(os.path.dirname(path) or "")
        label = (f"{label}/{os.path.basename(path)}" if label
                 else os.path.basename(path))
        for e in read_ledger(path):
            if e.get("event") == "span" and e.get("trace_id"):
                s = dict(e)
                s["_ledger"] = label
                spans.append(s)
    return spans


def group_traces(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-trace documents: the span list (wall-ordered), the root forest
    (children resolved across ledgers), and the critical-path segment
    split from :data:`SPAN_SEGMENTS`."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_trace.setdefault(str(s["trace_id"]), []).append(s)
    traces = []
    for tid in sorted(by_trace,
                      key=lambda t: min(int(s.get("wall_ns") or 0)
                                        for s in by_trace[t])):
        tr_spans = sorted(by_trace[tid],
                          key=lambda s: (int(s.get("wall_ns") or 0),
                                         str(s.get("span_id"))))
        ids = {s.get("span_id") for s in tr_spans}
        roots, children = [], {}
        for s in tr_spans:
            parent = s.get("parent_id")
            if parent and parent in ids:
                children.setdefault(parent, []).append(s)
            else:
                s = dict(s)
                s["_orphan"] = bool(parent)  # parent named but not seen
                roots.append(s)
        segments: Dict[str, float] = {}
        for s in tr_spans:
            seg = SPAN_SEGMENTS.get(s.get("name"))
            if seg is not None:
                try:
                    segments[seg] = (segments.get(seg, 0.0)
                                     + float(s.get("duration_s") or 0.0))
                except (TypeError, ValueError):
                    pass
        walls = [int(s.get("wall_ns") or 0) for s in tr_spans]
        durations = [float(s.get("duration_s") or 0.0) for s in tr_spans]
        span_s = 0.0
        if walls:
            ends = [w / 1e9 + d for w, d in zip(walls, durations)]
            span_s = max(ends) - min(walls) / 1e9
        traces.append({
            "trace_id": tid,
            "spans": tr_spans,
            "roots": roots,
            "children": children,
            "segments": {k: round(v, 6) for k, v in sorted(segments.items())},
            "ledgers": sorted({s["_ledger"] for s in tr_spans}),
            "duration_s": round(max(span_s, 0.0), 6),
        })
    return traces


def segment_percentiles(traces: List[Dict[str, Any]]
                        ) -> Dict[str, Dict[str, float]]:
    """Aggregate p50/p99 of each critical-path segment ACROSS traces —
    the same numbers obs/history.py extracts into the `segments` section,
    recomputed here from the joined view."""
    samples: Dict[str, List[float]] = {}
    for tr in traces:
        for seg, total in tr["segments"].items():
            samples.setdefault(seg, []).append(total)
    return {
        seg: {
            "count": float(len(vals)),
            "p50_s": round(percentile(vals, 50), 6),
            "p99_s": round(percentile(vals, 99), 6),
            "max_s": round(max(vals), 6),
        }
        for seg, vals in sorted(samples.items())
    }


def _span_line(s: Dict[str, Any], depth: int) -> str:
    dur = float(s.get("duration_s") or 0.0)
    attrs = " ".join(f"{k}={s[k]}" for k in _ATTR_KEYS
                     if s.get(k) not in (None, ""))
    parts = ["  " * depth + str(s.get("name")),
             f"{dur:.3f}s", str(s.get("status") or "ok")]
    if attrs:
        parts.append(attrs)
    parts.append(f"[{s['_ledger']}]")
    if s.get("_orphan"):
        parts.append("(orphan)")
    return "  ".join(parts)


def render_trace(tr: Dict[str, Any]) -> str:
    lines = [
        f"trace {tr['trace_id']}  spans={len(tr['spans'])}  "
        f"ledgers={len(tr['ledgers'])}  duration={tr['duration_s']:.3f}s"
    ]
    if tr["segments"]:
        split = " | ".join(f"{k} {v:.3f}s"
                           for k, v in tr["segments"].items())
        lines.append(f"  critical path: {split}")

    def walk(span: Dict[str, Any], depth: int) -> None:
        lines.append("  " + _span_line(span, depth))
        for child in tr["children"].get(span.get("span_id"), []):
            walk(child, depth + 1)

    for root in tr["roots"]:
        walk(root, 0)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("ledgers", nargs="+",
                    help="run ledger JSONL paths (router + replicas + "
                         "loadgen — any mix; traces join on trace_id)")
    ap.add_argument("--trace", type=str, default=None,
                    help="render only this trace id (prefix match)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output for CI")
    args = ap.parse_args(argv)
    try:
        spans = load_spans(args.ledgers)
    except OSError as e:
        print(f"[trace_view] {e}", file=sys.stderr)
        return 2
    traces = group_traces(spans)
    if args.trace:
        traces = [t for t in traces
                  if t["trace_id"].startswith(args.trace.lower())]
    if args.json:
        doc = {
            "ledgers": args.ledgers,
            "traces": [{k: v for k, v in tr.items()
                        if k not in ("roots", "children")}
                       for tr in traces],
            "segment_percentiles": segment_percentiles(traces),
        }
        print(json.dumps(doc, default=str))
        return 0
    if not traces:
        print("no spans found (tracing off, or no matching trace id)")
        return 0
    for tr in traces:
        print(render_trace(tr))
        print()
    agg = segment_percentiles(traces)
    if agg:
        print(f"segments across {len(traces)} trace(s):")
        for seg, rec in agg.items():
            print(f"  {seg:10s} p50 {rec['p50_s']:.3f}s  "
                  f"p99 {rec['p99_s']:.3f}s  max {rec['max_s']:.3f}s  "
                  f"n={int(rec['count'])}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
