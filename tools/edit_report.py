"""Render a self-contained HTML edit report from a run ledger + sidecar.

Usage:  python tools/edit_report.py <ledger.jsonl> [-o report.html]
                                    [--sidecar obs_sidecar.npz]

Renders the LAST run of the ledger (ledger files append across
invocations): per-word cross-attention heatmap grids across DDIM steps,
LocalBlend mask overlays on the edited frames, the null-text loss
sparkline, the edit-quality table (PSNR/SSIM), the "Where time goes"
section (execute-latency distributions + device-trace breakdowns —
``trace`` events whose directory still exists are auto-mined with the
stdlib xplane reader, no tensorflow), and the regression verdicts —
everything base64-embedded in one HTML file. The sidecar ``.npz`` is
located from the ledger's ``attn_maps``/``quality`` events when not
given explicitly.

stdlib + numpy only (tests/test_bench_guard.py pins the import closure)
— runs on any box the ledger was copied to, no plotting stack, no
accelerator, no repo checkout beyond this package.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.report import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
