"""Frame-attention kernel shootout at the SD-1.5 hot shape.

Times every ops/attention.py implementation (plus head-dim-padded Pallas
variants) at the 64²-site working point of the fast edit — B=3 streams,
F=8 frames, H=8 heads, N=4096 tokens, d=40 — the op family that pins the
edit step at 277 ms (MFU 0.36) in round 2.

Measurement per impl: warm on a fresh input, then time a CHAIN of calls
where each input depends on the previous output (the axon tunnel memoizes
repeated identical executions server-side and has acked dispatches early;
a value-chain defeats both), ending with a device→host value fetch.

Usage: PYTHONPATH=/root/repo python tools/bench_attention.py [reps]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from videop2p_tpu.ops.attention import (  # noqa: E402
    chunked_frame_attention,
    dense_frame_attention,
    flash_frame_attention,
    flash_rect_frame_attention,
    fused_frame_attention,
)

B, F, H, N, D = 3, 8, 8, 4096, 40


def padded(fn, d_pad: int):
    """Zero-pad the head dim before a kernel: scores are unchanged (extra
    dims contribute 0 to q·k), V's extra columns are zero — slice them off.
    Tests whether the Pallas kernel's d→128 tile padding is the loss."""

    def wrapped(q, k, v):
        pad = [(0, 0)] * (q.ndim - 1) + [(0, d_pad - q.shape[-1])]
        pad_kv = [(0, 0)] * (k.ndim - 1) + [(0, d_pad - k.shape[-1])]
        out = fn(
            jnp.pad(q, pad),
            jnp.pad(k, pad_kv),
            jnp.pad(v, pad_kv),
        )
        return out[..., : q.shape[-1]]

    return wrapped


def scaled_pad(fn, d_pad: int):
    """Pad variant with exact softmax scale: the kernel scales by
    d_pad**-0.5, so pre-multiplying q by (d_pad/d)**0.5 restores the true
    d**-0.5 — (d_pad/d)**0.5 · d_pad**-0.5 = d**-0.5."""

    def wrapped(q, k, v):
        q = q * (d_pad / q.shape[-1]) ** 0.5
        return padded(fn, d_pad)(q, k, v)

    return wrapped


def measure(name, fn, reps: int = 8):
    key = jax.random.key(time.time_ns() % (2**31))
    kq, kk, kv, kw = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, F, H, N, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, H, N, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, H, N, D), jnp.bfloat16)

    jfn = jax.jit(fn)
    try:
        out = jfn(jax.random.normal(kw, q.shape, q.dtype), k, v)  # compile+warm
        jax.block_until_ready(out)
        float(out.ravel()[0].astype(jnp.float32))

        t0 = time.time()
        for _ in range(reps):
            out = jfn(q, k, v)
            # chain: next q depends on this output — no two calls share args
            q = q + 0.001 * out
        jax.block_until_ready(out)
        float(out.ravel()[0].astype(jnp.float32))
        dt = (time.time() - t0) / reps
    except Exception as e:  # noqa: BLE001
        print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:120]}")
        return None, None

    # FLOPs: QK^T + PV = 2 * 2 * B*F*H*N*N*D
    flops = 4 * B * F * H * N * N * D
    # numerical parity vs dense at a small shape (full-shape dense scores
    # are ~13 GB and OOM the chip outside the fused forward)
    ks = jax.random.split(jax.random.key(7), 3)
    qs = jax.random.normal(ks[0], (1, 2, 2, 1024, D), jnp.bfloat16)
    kk2 = jax.random.normal(ks[1], (1, 2, 1024, D), jnp.bfloat16)
    vs = jax.random.normal(ks[2], (1, 2, 1024, D), jnp.bfloat16)
    small = jax.jit(fn)(qs, kk2, vs)
    ref = jax.jit(dense_frame_attention)(qs, kk2, vs)
    err = float(jnp.max(jnp.abs((small - ref).astype(jnp.float32))))
    print(f"{name:28s} {dt*1e3:8.2f} ms   {flops/dt/1e12:6.1f} TF/s  max|d|={err:.4f}")
    return dt, out


def main():
    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(__doc__.strip())
        return 0
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    print(f"shape: q=({B},{F},{H},{N},{D})  reps={reps}  "
          f"device={jax.devices()[0].device_kind}")
    measure("fused(256)", functools.partial(fused_frame_attention, q_blk=256), reps)
    measure("fused(512)", functools.partial(fused_frame_attention, q_blk=512), reps)
    measure("fused(1024)", functools.partial(fused_frame_attention, q_blk=1024), reps)
    measure("dense", dense_frame_attention, reps)
    measure("chunked(512)", functools.partial(chunked_frame_attention, q_chunk=512), reps)
    measure("chunked(1024)", functools.partial(chunked_frame_attention, q_chunk=1024), reps)
    measure("flash d40", flash_frame_attention, reps)
    measure("flash_rect d40", flash_rect_frame_attention, reps)
    measure("flash pad64", scaled_pad(flash_frame_attention, 64), reps)
    measure("flash_rect pad64", scaled_pad(flash_rect_frame_attention, 64), reps)
    measure("flash pad128", scaled_pad(flash_frame_attention, 128), reps)
    measure("flash_rect pad128", scaled_pad(flash_rect_frame_attention, 128), reps)


if __name__ == "__main__":
    main()
