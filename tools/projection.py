"""v5e-4 projection model for the fast edit: compute + ICI-collective budget.

Round 2 projected the 4-chip wall-clock with a bare 0.8 efficiency constant
whose justification lived in prose. This module derives the projection
mechanically, so it is reproducible from repo contents (VERDICT r2 item 5):

* **Traffic table** — for the (dp=1, sp=4, tp=1) sequence-parallel mesh the
  CLI ships (``--mesh 1,4,1``; frames shard over chips), the per-step ICI
  bytes are enumerated from the UNet's attention-site shapes:
  - *frame-0 KV broadcast*: every frame-attention site needs frame 0's
    keys/values (reference semantics, tuneavideo/models/attention.py:296-302)
    — each non-owner chip ingests the full (B, H, N_s, D) K and V in bf16.
  - *temporal all-gather*: Stage-2 temporal sites are CONTROLLED (P2P edits
    their f×f maps), so each chip gathers the full frame axis for its local
    spatial shard — (B, N_s/sp, F, C_s) K and V in bf16 per site.
* **Compute scaling** — every per-frame op (convs, FF, norms, frame-attn
  queries) divides by sp; the single-chip step time is the measured input.
* **Bandwidth model** — ingress-bound collectives at ``ici_gbps`` effective
  per-chip bandwidth, no compute/communication overlap assumed (both
  conservative). v5e chips have 4 ICI links; public specs put per-chip
  aggregate bandwidth at ~400 GB/s (bidirectional); 100 GB/s effective
  ingress is the deliberately conservative default.

Run ``python tools/projection.py`` to (re)generate ``docs/PROJECTION.md``
with the traffic table and the sensitivity over ICI bandwidths; ``bench.py``
calls :func:`project` with its measured phase times so the recorded
``projected_v5e4_s`` is always derived from this model, not a constant.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# SD-1.5 UNet attention sites at 512² (64×64 latents): (N_spatial, channels,
# heads, head_dim, count) per level — 2 transformer layers per down level,
# 3 per up level, 1 mid (models/unet.py sd15 topology; verified against the
# round-3 xplane trace: five N=4096 frame-attn fusions per forward).
SD15_SITES: List[Tuple[int, int, int, int, int]] = [
    (64 * 64, 320, 8, 40, 5),   # down0 ×2 + up3 ×3
    (32 * 32, 640, 8, 80, 5),   # down1 ×2 + up2 ×3
    (16 * 16, 1280, 8, 160, 5),  # down2 ×2 + up1 ×3
    (8 * 8, 1280, 8, 160, 1),   # mid
]


def traffic_table(batch: int, frames: int, sp: int) -> List[Dict]:
    """Per-step ICI bytes per attention site for the sp-way frame shard."""
    rows = []
    for n_s, ch, heads, d, count in SD15_SITES:
        kv_broadcast = 2 * batch * heads * n_s * d * 2  # K+V, bf16
        # controlled temporal sites: all-gather K+V over the frame axis for
        # the chip's local spatial shard (queries stay local)
        temporal_gather = 2 * batch * (n_s // sp) * frames * ch * 2 * (sp - 1)
        rows.append({
            "site": f"{int(n_s ** 0.5)}x{int(n_s ** 0.5)}",
            "instances": count,
            "kv_broadcast_mb": round(kv_broadcast / 1e6, 2),
            "temporal_gather_mb_per_chip": round(temporal_gather / sp / 1e6, 2),
            "total_mb_per_chip_per_step": round(
                count * (kv_broadcast + temporal_gather / sp) / 1e6, 2
            ),
        })
    return rows


def project(
    inv_s: float,
    edit_s: float,
    *,
    steps: int = 50,
    frames: int = 8,
    sp: int = 4,
    ici_gbps: float = 100.0,
    shard_inv_s: Optional[float] = None,
    shard_edit_s: Optional[float] = None,
    edit_streams: int = 3,
    efficiency: float = 1.0,
) -> Dict:
    """Project the 4-chip fast-edit wall-clock from measured single-chip
    phase times. Returns the projection plus its full evidence.
    ``edit_streams``: 3 for the live fast edit, 2 for the cached-source mode
    (whose capture trees shard over frames with no extra collectives —
    tests/test_parallel.py pins sharded==unsharded for it).

    ``shard_inv_s`` / ``shard_edit_s``: MEASURED single-chip wall-clock of
    the frames/sp-frame working point — exactly the per-chip compute of the
    sharded mesh (minus collectives), capturing the small-batch efficiency
    loss that a bare /sp would hide. bench.py measures these in its extended
    phases; without them the model falls back to linear scaling. (Caveat:
    the F/sp proxy runs temporal attention at (F/sp)² instead of the sharded
    N/sp×F² — a few ms/step either way at F≤8 since temporal sites are tiny.)
    """
    t_inv = traffic_table(1, frames, sp)   # inversion: 1 cond stream
    t_edit = traffic_table(edit_streams, frames, sp)
    inv_mb = sum(r["total_mb_per_chip_per_step"] for r in t_inv)
    edit_mb = sum(r["total_mb_per_chip_per_step"] for r in t_edit)
    coll_inv = inv_mb * 1e6 / (ici_gbps * 1e9) * steps
    coll_edit = edit_mb * 1e6 / (ici_gbps * 1e9) * steps
    # "is not None": a legitimate 0.0 shard reading must not silently fall
    # back to linear scaling
    use_shard = shard_inv_s is not None and shard_edit_s is not None
    proj_inv = (shard_inv_s if use_shard else inv_s / sp / efficiency) + coll_inv
    proj_edit = (shard_edit_s if use_shard else edit_s / sp / efficiency) + coll_edit
    total = proj_inv + proj_edit

    # Uncertainty band (VERDICT r4 item 6: the point estimate moved 20 % in
    # one round when the compute model switched from linear-in-sp to the
    # measured shard proxy — so the record carries BOTH models at both
    # bandwidth extremes, not three significant figures of one of them).
    #   optimistic  = linear compute scaling (ignores small-batch loss; the
    #                 r3 model) at 2× the default effective ICI bandwidth;
    #   pessimistic = the measured F/sp shard proxy (includes small-batch
    #                 loss AND the harness's tunnel timing noise — the
    #                 proxy phases are 2-4 s where ±0.3 s is ~15 %) at half
    #                 the default bandwidth.
    # The true 4-chip number should land inside; quote the range.
    candidates = []
    for bw in (ici_gbps / 2, ici_gbps, ici_gbps * 2):
        ci = inv_mb * 1e6 / (bw * 1e9) * steps
        ce = edit_mb * 1e6 / (bw * 1e9) * steps
        candidates.append(inv_s / sp + ci + edit_s / sp + ce)  # linear, ideal
        if efficiency < 1.0:
            # derated linear — the compute model configs without their own
            # shard proxy actually use; without this the point estimate
            # could sit outside its own range
            candidates.append(
                inv_s / sp / efficiency + ci + edit_s / sp / efficiency + ce
            )
        if use_shard:
            candidates.append(shard_inv_s + ci + shard_edit_s + ce)
    lo, hi = min(candidates), max(candidates)

    return {
        "projected_v5e4_s": round(total, 2),
        "projected_v5e4_range_s": [round(lo, 1), round(hi, 1)],
        "parallel_efficiency": round((inv_s + edit_s) / (sp * total), 3),
        "assumptions": {
            "sp": sp,
            "ici_effective_gbps": ici_gbps,
            "overlap": "none (conservative)",
            "compute_scaling": (
                "measured: single-chip F/sp-frame phases stand in for the "
                "per-chip shard" if use_shard
                else "linear in sp (per-frame ops shard cleanly; "
                     "tests/test_parallel.py proves sharded==unsharded)"),
        },
        "inversion": {
            "single_chip_s": inv_s,
            "collective_s": round(coll_inv, 3),
            "projected_s": round(proj_inv, 2),
            "traffic_per_step": t_inv,
        },
        "edit": {
            "single_chip_s": edit_s,
            "collective_s": round(coll_edit, 3),
            "projected_s": round(proj_edit, 2),
            "traffic_per_step": t_edit,
        },
    }


def project_official(
    inv_s: float,
    null_s: float,
    off_edit_s: float,
    *,
    steps: int = 50,
    frames: int = 8,
    inner_steps: int = 3,
    sp: int = 4,
    ici_gbps: float = 100.0,
    efficiency: float = 1.0,
) -> Dict:
    """Project the official-mode edit (inversion + null-text + full-CFG
    controlled edit) onto the sp-chip frame-sharded mesh.

    Null-text is per-frame UNet work (forwards + a remat backward on the
    uncond branch) and shards over frames like everything else; its
    per-outer-step collective volume is the 1-stream traffic times the
    forward-equivalent count ``2 + 3·inner`` (backward ≈ 2 forwards of
    traffic — conservative). ``efficiency`` (≤1) derates the per-chip
    compute for small-batch loss, measured via the F/sp shard proxy.
    """
    t1 = traffic_table(1, frames, sp)
    t4 = traffic_table(4, frames, sp)
    mb1 = sum(r["total_mb_per_chip_per_step"] for r in t1)
    mb4 = sum(r["total_mb_per_chip_per_step"] for r in t4)
    coll_inv = mb1 * 1e6 / (ici_gbps * 1e9) * steps
    coll_null = mb1 * 1e6 / (ici_gbps * 1e9) * steps * (2 + 3 * inner_steps)
    coll_off = mb4 * 1e6 / (ici_gbps * 1e9) * steps
    proj = (
        (inv_s / sp / efficiency + coll_inv)
        + (null_s / sp / efficiency + coll_null)
        + (off_edit_s / sp / efficiency + coll_off)
    )
    single = inv_s + null_s + off_edit_s
    return {
        "projected_v5e4_s": round(proj, 2),
        "single_chip_s": round(single, 2),
        "parallel_efficiency": round(single / (sp * proj), 3),
        "phases": {
            "inversion_s": round(inv_s / sp / efficiency + coll_inv, 2),
            "null_text_s": round(null_s / sp / efficiency + coll_null, 2),
            "official_edit_s": round(off_edit_s / sp / efficiency + coll_off, 2),
        },
        "assumptions": {
            "sp": sp, "ici_effective_gbps": ici_gbps,
            "compute_efficiency": round(efficiency, 3),
            "null_traffic_fwd_equivalents_per_outer": 2 + 3 * inner_steps,
            "null_variant": f"fixed {inner_steps} inner steps (stable record)",
        },
    }


def project_long(
    e2e_s: float,
    *,
    steps: int = 50,
    frames: int = 24,
    sp: int = 4,
    ici_gbps: float = 100.0,
    efficiency: float = 1.0,
) -> Dict:
    """Project the 24-frame fast edit (BASELINE config 3) onto sp chips:
    frames/sp = 6 frames per chip; inversion (1 stream) + live fast edit
    (3 streams) collectives at the 24-frame site shapes."""
    mb = sum(
        r["total_mb_per_chip_per_step"]
        for t in (traffic_table(1, frames, sp), traffic_table(3, frames, sp))
        for r in t
    )
    coll = mb * 1e6 / (ici_gbps * 1e9) * steps
    proj = e2e_s / sp / efficiency + coll
    return {
        "projected_v5e4_s": round(proj, 2),
        "single_chip_s": round(e2e_s, 2),
        "parallel_efficiency": round(e2e_s / (sp * proj), 3),
        "collective_s": round(coll, 3),
        "assumptions": {
            "sp": sp, "ici_effective_gbps": ici_gbps,
            "frames_per_chip": frames // sp,
            "compute_efficiency": round(efficiency, 3),
        },
    }


def main() -> None:
    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(__doc__.strip())
        return
    # measured single-chip phase times from the committed record; the
    # headline inversion_s/edit_s are the CACHED-mode pair — the projection
    # models the live sharded path, so prefer the live A/B readings
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "bench_details.json")) as f:
        bd = json.load(f)["breakdown"]
    inv_s = bd.get("inversion_live_s", bd["inversion_s"])
    edit_s = bd.get("edit_live_s", bd["edit_s"])
    shard_kw = {}
    if "shard2_inversion_s" in bd and "shard2_edit_s" in bd:
        shard_kw = dict(shard_inv_s=bd["shard2_inversion_s"],
                        shard_edit_s=bd["shard2_edit_s"])

    lines = [
        "# v5e-4 fast-edit projection (generated by tools/projection.py)",
        "",
        f"Measured single-chip phases (bench_details.json): inversion "
        f"{inv_s} s, edit {edit_s} s.",
        "",
        "Mesh: `--mesh 1,4,1` — 8 frames shard over 4 chips (sequence"
        " parallel); per-frame compute divides by 4; the two collective"
        " families below ride ICI. No compute/communication overlap is"
        " assumed (conservative).",
        "",
        "## Per-step ICI traffic per chip (edit batch, 3 streams)",
        "",
        "| site | instances | frame-0 KV broadcast | temporal all-gather/chip | total/chip/step |",
        "|---|---|---|---|---|",
    ]
    for r in traffic_table(3, 8, 4):
        lines.append(
            f"| {r['site']} | {r['instances']} | {r['kv_broadcast_mb']} MB "
            f"| {r['temporal_gather_mb_per_chip']} MB "
            f"| {r['total_mb_per_chip_per_step']} MB |"
        )
    lines += ["", "## Projection vs ICI bandwidth", "",
              "| effective ICI GB/s | projected e2e | parallel efficiency |",
              "|---|---|---|"]
    for bw in (50.0, 100.0, 200.0):
        p = project(inv_s, edit_s, ici_gbps=bw, **shard_kw)
        lines.append(
            f"| {bw:.0f} | {p['projected_v5e4_s']} s "
            f"| {p['parallel_efficiency']:.2f} |"
        )
    p = project(inv_s, edit_s, **shard_kw)
    lines += [
        "",
        "## Uncertainty: why the point estimate moved between rounds, and",
        "the range that replaces it",
        "",
        "The recorded efficiency swung 0.948 (r3) → 0.765 (r4) when the",
        "per-chip compute model switched from *linear-in-sp* (single-chip",
        "time ÷ 4 — assumes zero small-batch loss) to the *measured shard",
        "proxy* (the F/4-frame working point run on one chip — includes",
        "real small-batch loss AND the harness's tunnel timing noise: the",
        "proxy phases are 2–4 s, where the observed ±0.3 s run-to-run",
        "wobble is ~15 %). Neither model is wrong; they bracket the truth:",
        "linear is the optimistic bound (a real mesh hides some per-chip",
        "overhead under collectives), the proxy is the pessimistic bound",
        "(tunnel noise inflates short readings, and the proxy cannot",
        "overlap what a real mesh overlaps). The projection of record is",
        "therefore a RANGE over {both compute models} × {0.5×, 1×, 2× the",
        "conservative 100 GB/s effective ICI bandwidth}, and claims should",
        "quote the range, not three significant figures of either point:",
        "",
        f"**Range: {p['projected_v5e4_range_s'][0]}–"
        f"{p['projected_v5e4_range_s'][1]} s** for the live fast edit.",
        "",
        "North-star check (BASELINE.md: <10 s on v5e-4): evaluated at the",
        f"PESSIMISTIC end of the range — {p['projected_v5e4_range_s'][1]} s "
        + ("satisfies" if p["projected_v5e4_range_s"][1] < 10 else "MISSES")
        + " the target.",
    ]
    lines += [
        "",
        f"**Recorded projection (100 GB/s): {p['projected_v5e4_s']} s, "
        f"efficiency {p['parallel_efficiency']:.2f}"
        + (" — per-chip compute MEASURED via the 2-frame working point"
           f" (inversion {shard_kw['shard_inv_s']} s, edit"
           f" {shard_kw['shard_edit_s']} s)" if shard_kw else
           " — per-chip compute modeled as single-chip/4") + ".**",
        "",
        "Evidence trail: per-site shapes are the SD-1.5 topology"
        " (models/unet.py); the five N=4096 frame-attention instances per"
        " forward are visible in the xplane op table"
        " (tools/xplane_top_ops.py); sharded==unsharded correctness is"
        " tests/test_parallel.py; the sharded 32-frame controlled edit runs"
        " in the driver's multichip dryrun (__graft_entry__.py). The sharded"
        " path runs the SAME fused Pallas kernel per shard"
        " (parallel/mesh.py make_sharded_frame_attention_fn), so the 2-frame"
        " single-chip proxy measures the per-chip compute of the mesh"
        " faithfully.",
    ]
    docs = os.path.join(root, "docs")
    os.makedirs(docs, exist_ok=True)
    out_md = os.path.join(docs, "PROJECTION.md")
    with open(out_md, "w") as f:
        f.write("\n".join(lines) + "\n")

    # measured small-batch efficiency from the shard proxy: the ratio of the
    # ideal per-chip time (single-chip/sp) to the MEASURED F/sp-frame time;
    # reused to derate the configs that have no dedicated proxy
    eff = 1.0
    if shard_kw:
        ideal = (inv_s + edit_s) / 4
        measured = shard_kw["shard_inv_s"] + shard_kw["shard_edit_s"]
        if measured > 0:
            eff = min(1.0, ideal / measured)

    out = {"fast_edit_live": p}
    # the CLI's default fast path: cached-source (2-stream edit). No shard
    # proxy exists for it, so per-chip compute is linear-in-sp derated by
    # the efficiency the LIVE proxy measured; collectives use the 2-stream
    # traffic — the capture trees shard over frames, so base-map reads stay
    # chip-local (tests/test_parallel.py pins sharded==unsharded)
    if "inversion_s" in bd and "edit_s" in bd and "inversion_live_s" in bd:
        # true measured single-chip times in; the derate applies only to the
        # per-chip compute division inside project(), so single_chip_s and
        # parallel_efficiency in the evidence stay honest
        out["fast_edit_cached"] = project(
            bd["inversion_s"], bd["edit_s"], edit_streams=2, efficiency=eff,
        )
        out["fast_edit_cached"]["assumptions"]["compute_scaling"] = (
            f"linear in sp derated by the live shard proxy's measured "
            f"efficiency {eff:.2f}"
        )
    if "null_text_fixed3_s" in bd and "official_edit_s" in bd:
        out["official_edit"] = project_official(
            inv_s, bd["null_text_fixed3_s"], bd["official_edit_s"],
            efficiency=eff,
        )
    # r5 renamed the measured key (the 10-step extrapolation was retired);
    # keep the fallback so pre-r5 records still project
    long_s = bd.get("long24_fast_edit_e2e_s",
                    bd.get("long24_fast_edit_e2e_s_extrapolated"))
    if long_s is not None:
        out["long24_fast_edit"] = project_long(long_s, efficiency=eff)
        if "long24_mode" in bd:
            out["long24_fast_edit"]["assumptions"]["measured_mode"] = bd["long24_mode"]
    if "shard2_samples" in bd:
        out["shard_proxy_samples"] = bd["shard2_samples"]
    with open(os.path.join(docs, "projection_v5e4.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {out_md}")
    print(json.dumps({k: p[k] for k in ("projected_v5e4_s", "parallel_efficiency")}))


if __name__ == "__main__":
    main()
