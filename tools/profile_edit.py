"""One-off profiling harness: where does the fast-edit wall-clock go?

Measures on the attached accelerator: (a) single UNet forward at the
inversion batch (cond-only, P=1) and the edit CFG batch (2P=4) with and
without control, (b) the jitted 50-step inversion and edit scans, and
(c) XLA's own FLOP estimate per executable for an MFU readout.
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from videop2p_tpu.control import make_controller
from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.models.attention import AttnControl
from videop2p_tpu.pipelines import ddim_inversion, edit_sample, make_unet_fn
from videop2p_tpu.utils.tokenizers import WordTokenizer

V5E_PEAK_FLOPS = 197e12  # bf16


def timed(fn, *args, n=3):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / n


def flops_of(jitted, *args):
    try:
        an = jitted.lower(*args).compile().cost_analysis()
        if isinstance(an, list):
            an = an[0]
        return float(an.get("flops", 0.0))
    except Exception as e:  # pragma: no cover
        print("cost_analysis failed:", e)
        return 0.0


def main():
    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(__doc__.strip())
        return 0
    cfg = UNet3DConfig.sd15()
    model = UNet3DConditionModel(config=cfg, dtype=jnp.bfloat16)
    F, STEPS = 8, 50
    x0 = jax.random.normal(jax.random.key(0), (1, F, 64, 64, 4), jnp.bfloat16)
    cond = jax.random.normal(jax.random.key(1), (2, 77, 768), jnp.bfloat16)
    uncond = jnp.zeros((77, 768), jnp.bfloat16)
    params = jax.jit(model.init)(jax.random.key(2), x0, jnp.asarray(10), cond[:1])
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()

    ctx = make_controller(
        ["a rabbit is jumping on the grass", "a origami rabbit is jumping on the grass"],
        WordTokenizer(),
        num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.2,
        self_replace_steps=0.5,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )

    # --- single forwards -------------------------------------------------
    t = jnp.asarray(801)
    fwd1 = jax.jit(lambda p, x: fn(p, x, t, cond[:1])[0])
    x4 = jnp.concatenate([x0, x0, x0, x0], axis=0)
    text4 = jnp.concatenate([uncond[None], uncond[None], cond], axis=0)
    fwd4 = jax.jit(lambda p, x: fn(p, x, t, text4)[0])
    ctl = AttnControl(ctx=ctx, step_index=jnp.asarray(5))
    fwd4c = jax.jit(lambda p, x: fn(p, x, t, text4, ctl)[0])
    x3 = x4[:3]
    text3 = jnp.concatenate([uncond[None], cond], axis=0)
    fwd3 = jax.jit(lambda p, x: fn(p, x, t, text3)[0])

    ctl3 = AttnControl(ctx=ctx, step_index=jnp.asarray(5), num_uncond=1)
    fwd3c = jax.jit(lambda p, x: fn(p, x, t, text3, ctl3)[0])

    # frame-attention impl ablation at the edit batch
    abl = []
    if "ablate" in sys.argv:
        # correctness: the rectangular flash fold must match dense
        from videop2p_tpu.ops.attention import (
            dense_frame_attention,
            flash_rect_frame_attention,
        )

        qs = jax.random.normal(jax.random.key(11), (2, 4, 8, 1024, 64), jnp.bfloat16)
        ks = jax.random.normal(jax.random.key(12), (2, 8, 1024, 64), jnp.bfloat16)
        vs = jax.random.normal(jax.random.key(13), (2, 8, 1024, 64), jnp.bfloat16)
        o_dense = jax.jit(dense_frame_attention)(qs, ks, vs)
        o_rect = jax.jit(flash_rect_frame_attention)(qs, ks, vs)
        import numpy as np
        err = float(jnp.max(jnp.abs(o_dense.astype(jnp.float32) - o_rect.astype(jnp.float32))))
        print(f"flash_rect vs dense max|Δ| = {err:.2e}")
        assert err < 0.05, "flash_rect mismatch"

        for impl in ("flash", "flash_rect", "chunked"):
            m2 = UNet3DConditionModel(
                config=UNet3DConfig.sd15(frame_attention=impl), dtype=jnp.bfloat16
            )
            f2 = make_unet_fn(m2)
            abl.append((f"fwd b4 [{impl}]", jax.jit(lambda p, x, f2=f2: f2(p, x, t, text4)[0]), x4))

    for name, f, xin in [
        ("fwd b1 (inversion step)", fwd1, x0),
        ("fwd b3", fwd3, x3),
        ("fwd b3 + control", fwd3c, x3),
        ("fwd b4 (edit step)", fwd4, x4),
        ("fwd b4 + control", fwd4c, x4),
    ] + abl:
        dt = timed(f, params, xin)
        fl = flops_of(f, params, xin)
        mfu = fl / dt / V5E_PEAK_FLOPS if dt else 0.0
        print(f"{name:28s}: {dt*1e3:8.2f} ms  {fl/1e12:7.2f} TF  MFU {mfu*100:5.1f}%")

    if "phases" not in sys.argv:
        return

    # --- full phases (FLOPs estimated as 50 × single-step) ---------------
    invert = jax.jit(
        lambda p, x: ddim_inversion(fn, p, sched, x, cond[:1], num_inference_steps=STEPS)
    )
    edit = jax.jit(
        lambda p, xt: edit_sample(
            fn, p, sched, xt, cond, uncond,
            num_inference_steps=STEPS, ctx=ctx, source_uses_cfg=False,
        )
    )
    t0 = time.time()
    traj = invert(params, x0)
    jax.block_until_ready(traj)
    print(f"inversion compile+run: {time.time()-t0:.1f} s")
    xt = traj[-1]
    t0 = time.time()
    out = edit(params, xt)
    jax.block_until_ready(out)
    print(f"edit compile+run: {time.time()-t0:.1f} s")
    for name, f, xin in [
        ("inversion 50 (b1)", invert, x0),
        ("edit 50 (b4, ctrl+blend)", edit, xt),
    ]:
        dt = timed(f, params, xin, n=1)
        print(f"{name:28s}: {dt:8.3f} s   per-step {dt/STEPS*1e3:7.1f} ms")


if __name__ == "__main__":
    main()
