"""Diff two run ledgers (or latest-vs-history) and exit nonzero on regression.

Usage:
  python tools/obs_diff.py BASE.jsonl NEW.jsonl
  python tools/obs_diff.py --history DIR NEW.jsonl
  python tools/obs_diff.py --history DIR            # latest vs its baseline

Compares the ``program_analysis`` events (XLA cost/memory analysis, HLO
fingerprints — obs/introspect.py), per-program compile seconds, phase
wall-clock, collective-communication accounting (``comm_analysis`` events
— obs/comm.py: per-kind collective counts and byte volumes of the sharded
programs), per-device peak-HBM residency (``memory`` snapshots), cross-replica
divergence (must be 0.0 — the zero-noise-floor invariant), per-program
execute-latency distributions (``execute_timing`` events — obs/timing.py:
blocked p50/p99 regress by growing), mined device traces
(``trace_analysis`` events — obs/trace.py: device-total seconds regress
by growing, the compute/collective overlap fraction by DROPPING), and
serving reliability (``serve_health`` events — serve/faults.py + the
engine: error rate, load-shed rate, breaker trips and deadline expiries
regress by appearing/growing, gated by ``FAULT_RULES``), and streaming
long-video jobs (``stream_health`` events — stream/driver.py: window-seam
adjacent-frame PSNR regresses by DROPPING, window failures/passthroughs
and manifest corruption by appearing, ``src_err_max`` must be exactly 0 —
gated by ``SEAM_RULES``), request-trace critical-path segments (``span``
events — obs/spans.py: per-segment queue/resolve/dispatch/decode p50/p99
regress by growing, gated by ``SEGMENT_RULES``), and SLO compliance
(``slo_report`` events — obs/slo.py: per-objective error-budget burn
regresses by growing, a compliant→violating flip always fails — gated by
``SLO_RULES``), and captured incidents (``incident`` events —
obs/incident.py: ANY increase in bundle or suppressed-capture counts,
overall or per trigger kind, regresses — gated by ``INCIDENT_RULES``),
and cost & capacity (``cost_attribution`` events — obs/cost.py:
per-engine/tenant/program device-second attribution; cost-per-request
and padding/idle waste regress by growing, utilization by dropping —
gated by ``COST_RULES``), and correctness probes (``probe`` /
``probe_audit`` events — obs/probe.py + serve/prober.py: known-answer
success rates regress by DROPPING, ANY new cross-replica answer-audit
divergence regresses, probe latency p99 by growing — gated by
``PROBE_RULES``)
between a baseline run and a new run, renders per-program tables,
evaluates the declarative regression rules (obs/history.py DEFAULT_RULES;
scale every threshold with ``--threshold-scale``), and:

  exit 0 — no rule regressed (a ledger compared against itself is always 0)
  exit 1 — at least one regression verdict
  exit 2 — usage / unreadable input

``--json`` additionally prints the machine-readable verdict object on
stdout (the tables move to stderr). CPU-runnable — this is the tier-1 CI
gate for "did this change make the compiled programs bigger".
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.history import (  # noqa: E402
    DEFAULT_RULES,
    RegressionRule,
    RunHistory,
    evaluate_rules,
    extract_run,
    split_runs,
)
from videop2p_tpu.obs.ledger import read_ledger  # noqa: E402


def _fmt(v: float) -> str:
    """Human-scaled number: bytes/flops get unit suffixes, small floats stay
    plain."""
    if v is None:
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.4g}"


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    lines = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(str(c).ljust(w) for c, w in zip(r, widths))
              for r in rows]
    return "\n".join(lines)


def render_diff(base: Dict, new: Dict, result: Dict) -> str:
    """The per-program / per-phase comparison tables plus the verdict list,
    as one string (pure — tests feed synthetic records)."""
    out: List[str] = [
        f"base: run {base.get('run_id', '?')} at {base.get('wall_time', '?')}"
        + (f"  ({base.get('source')})" if base.get("source") else ""),
        f"new:  run {new.get('run_id', '?')} at {new.get('wall_time', '?')}"
        + (f"  ({new.get('source')})" if new.get("source") else ""),
    ]

    progs = sorted(set(base.get("programs", {})) | set(new.get("programs", {})))
    if progs:
        rows = []
        for label in progs:
            b = base.get("programs", {}).get(label, {})
            n = new.get("programs", {}).get(label, {})
            fp_b, fp_n = b.get("hlo_fingerprint"), n.get("hlo_fingerprint")

            def cell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                pct = (nv / bv - 1.0) * 100.0 if bv else float("inf")
                return f"{_fmt(bv)} → {_fmt(nv)} ({pct:+.1f}%)"

            rows.append([
                label, cell("flops"), cell("bytes_accessed"),
                cell("temp_bytes"), cell("peak_hbm_bytes"),
                cell("hlo_instructions"),
                ("same" if fp_b == fp_n else "CHANGED") if fp_b and fp_n
                else "-",
            ])
        out += ["", "programs (XLA cost/memory analysis):",
                _table(rows, ["program", "flops", "bytes", "temp",
                              "peak_hbm", "instrs", "hlo"])]

    names = sorted(set(base.get("phases", {})) | set(new.get("phases", {})))
    if names:
        rows = []
        for name in names:
            b = base.get("phases", {}).get(name, {}).get("seconds")
            n = new.get("phases", {}).get(name, {}).get("seconds")
            delta = (f"{(n / b - 1.0) * 100.0:+.1f}%"
                     if b and n is not None else "-")
            rows.append([name,
                         "-" if b is None else f"{b:.2f}",
                         "-" if n is None else f"{n:.2f}", delta])
        out += ["", "phases (wall-clock s):",
                _table(rows, ["phase", "base", "new", "delta"])]

    # distributed sections (obs/comm.py) — absent/empty for pre-PR-5
    # ledgers and single-device runs, in which case the tables are omitted
    comms = sorted(set(base.get("comm") or {}) | set(new.get("comm") or {}))
    if comms:
        rows = []
        for label in comms:
            b = (base.get("comm") or {}).get(label, {})
            n = (new.get("comm") or {}).get(label, {})

            def ccell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                pct = (nv / bv - 1.0) * 100.0 if bv else float("inf")
                return f"{_fmt(bv)} → {_fmt(nv)} ({pct:+.1f}%)"

            rows.append([label, ccell("num_partitions"),
                         ccell("collective_count"), ccell("collective_bytes")])
        out += ["", "collectives (comm_analysis — static per-module "
                "counts/bytes):",
                _table(rows, ["program", "partitions", "collectives",
                              "bytes"])]

    devmem = sorted(set(base.get("device_memory") or {})
                    | set(new.get("device_memory") or {}))
    if devmem:
        rows = []
        for dev in devmem:
            b = (base.get("device_memory") or {}).get(dev)
            n = (new.get("device_memory") or {}).get(dev)
            delta = (f"{(n / b - 1.0) * 100.0:+.1f}%"
                     if b and n is not None else "-")
            rows.append([dev, _fmt(b), _fmt(n), delta])
        out += ["", "per-device peak HBM (memory snapshots):",
                _table(rows, ["device", "base", "new", "delta"])]

    divs = sorted(set(base.get("divergence") or {})
                  | set(new.get("divergence") or {}))
    if divs:
        rows = []
        for label in divs:
            b = (base.get("divergence") or {}).get(label)
            n = (new.get("divergence") or {}).get(label)
            rows.append([label, _fmt(b), _fmt(n),
                         "ok" if n in (None, 0.0) else "DIVERGED"])
        out += ["", "replica divergence (must be 0.0):",
                _table(rows, ["label", "base", "new", "verdict"])]

    # time-domain sections (obs/timing.py reservoirs + obs/trace.py
    # trace mining) — absent/empty for pre-PR-6 ledgers, tables omitted
    timing = sorted(set(base.get("timing") or {}) | set(new.get("timing") or {}))
    if timing:
        rows = []
        for label in timing:
            b = (base.get("timing") or {}).get(label, {})
            n = (new.get("timing") or {}).get(label, {})

            def tcell(metric, scale=1e3, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return f"{nv * scale:.2f}"
                pct = (nv / bv - 1.0) * 100.0 if bv else float("inf")
                return f"{bv * scale:.2f} → {nv * scale:.2f} ({pct:+.1f}%)"

            cnt_b, cnt_n = b.get("count"), n.get("count")
            cnt = (_fmt(cnt_n) if cnt_b == cnt_n
                   else f"{_fmt(cnt_b)} → {_fmt(cnt_n)}")
            rows.append([label, cnt,
                         tcell("blocked_p50_s"), tcell("blocked_p99_s"),
                         tcell("blocked_max_s")])
        out += ["", "execute timing (blocked-latency ms per dispatch):",
                _table(rows, ["program", "calls", "p50", "p99", "max"])]

    traces = sorted(set(base.get("trace") or {}) | set(new.get("trace") or {}))
    if traces:
        rows = []
        for label in traces:
            b = (base.get("trace") or {}).get(label, {})
            n = (new.get("trace") or {}).get(label, {})

            def rcell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                pct = (nv / bv - 1.0) * 100.0 if bv else float("inf")
                return f"{_fmt(bv)} → {_fmt(nv)} ({pct:+.1f}%)"

            rows.append([label, rcell("device_total_s"),
                         rcell("collective_s"), rcell("overlap_fraction"),
                         rcell("idle_s")])
        out += ["", "trace analysis (device seconds; overlap regresses "
                "by dropping):",
                _table(rows, ["window", "device_total_s", "collective_s",
                              "overlap", "idle_s"])]

    # reliability section (serve_health events — serve/faults.py, ISSUE 9):
    # absent/empty for pre-PR-9 ledgers and non-serving runs, table omitted
    rel = sorted(set(base.get("reliability") or {})
                 | set(new.get("reliability") or {}))
    if rel:
        rows = []
        for label in rel:
            b = (base.get("reliability") or {}).get(label, {})
            n = (new.get("reliability") or {}).get(label, {})

            def fcell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                return f"{_fmt(bv)} → {_fmt(nv)}"

            rows.append([label, fcell("requests"), fcell("error_rate"),
                         fcell("shed"), fcell("shed_rate"),
                         fcell("breaker_trips"), fcell("deadline_exceeded"),
                         fcell("retries")])
        out += ["", "reliability (serve_health — error/shed rates, breaker "
                "trips):",
                _table(rows, ["label", "requests", "error_rate", "sheds",
                              "shed_rate", "breaker_trips",
                              "deadline_exceeded", "retries"])]

    # streaming section (stream_health events — stream/driver.py, ISSUE
    # 12): absent/empty for pre-PR-12 ledgers and non-streaming runs
    stream = sorted(set(base.get("stream") or {})
                    | set(new.get("stream") or {}))
    if stream:
        rows = []
        for label in stream:
            b = (base.get("stream") or {}).get(label, {})
            n = (new.get("stream") or {}).get(label, {})

            def scell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                return f"{_fmt(bv)} → {_fmt(nv)}"

            rows.append([label, scell("windows_total"), scell("windows_done"),
                         scell("windows_passthrough"), scell("windows_failed"),
                         scell("seam_min_psnr"), scell("seam_mean_psnr"),
                         scell("src_err_max")])
        out += ["", "streaming (stream_health — seam PSNR regresses by "
                "dropping; src_err_max must be 0):",
                _table(rows, ["label", "windows", "done", "passthrough",
                              "failed", "seam_min", "seam_mean",
                              "src_err_max"])]

    # critical-path segments (span events — obs/spans.py, ISSUE 14):
    # absent/empty for tracing-off ledgers, table omitted
    segs = sorted(set(base.get("segments") or {})
                  | set(new.get("segments") or {}))
    if segs:
        rows = []
        for label in segs:
            b = (base.get("segments") or {}).get(label, {})
            n = (new.get("segments") or {}).get(label, {})

            def gcell(metric, scale=1e3, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return f"{nv * scale:.2f}"
                pct = (nv / bv - 1.0) * 100.0 if bv else float("inf")
                return f"{bv * scale:.2f} → {nv * scale:.2f} ({pct:+.1f}%)"

            cnt_b, cnt_n = b.get("count"), n.get("count")
            cnt = (_fmt(cnt_n) if cnt_b == cnt_n
                   else f"{_fmt(cnt_b)} → {_fmt(cnt_n)}")
            rows.append([label, cnt, gcell("p50_s"), gcell("p99_s"),
                         gcell("max_s")])
        out += ["", "trace segments (critical-path ms per request — queue/"
                "resolve/dispatch/decode):",
                _table(rows, ["segment", "spans", "p50", "p99", "max"])]

    # SLO section (slo_report events — obs/slo.py, ISSUE 14): budget burn
    # regresses by growing; compliant regresses by flipping to 0
    slos = sorted(set(base.get("slo") or {}) | set(new.get("slo") or {}))
    if slos:
        rows = []
        for name in slos:
            b = (base.get("slo") or {}).get(name, {})
            n = (new.get("slo") or {}).get(name, {})

            def ocell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                return f"{_fmt(bv)} → {_fmt(nv)}"

            verdict = "-"
            if n:
                verdict = "ok" if n.get("compliant") else "VIOLATED"
            rows.append([name, ocell("target"), ocell("actual"),
                         ocell("budget_burn"), verdict])
        out += ["", "SLOs (slo_report — budget burn regresses by growing):",
                _table(rows, ["objective", "target", "actual", "burn",
                              "verdict"])]

    # fleet-signal section (fleet_signals/fleet_series events —
    # obs/signals.py over the collector's tsdb, ISSUE 17): absent/empty
    # for pre-PR-17 ledgers and collector-off runs, table omitted
    sigs = sorted(set(base.get("signals") or {})
                  | set(new.get("signals") or {}))
    if sigs:
        rows = []
        for label in sigs:
            b = (base.get("signals") or {}).get(label, {})
            n = (new.get("signals") or {}).get(label, {})

            def ncell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                return f"{_fmt(bv)} → {_fmt(nv)}"

            advice = "-"
            if n:
                advice = next((a for a in ("grow", "hold", "shrink")
                               if n.get(f"advice_{a}") == 1.0), "-")
            rows.append([label, ncell("burn_fast"), ncell("burn_slow"),
                         ncell("burn_alerts"), ncell("saturation"),
                         ncell("scrape_error_rate"),
                         ncell("replicas_up"), advice])
        out += ["", "fleet signals (fleet_signals — any new burn alert "
                "regresses; saturation/scrape errors by growing):",
                _table(rows, ["label", "burn_fast", "burn_slow", "alerts",
                              "saturation", "scrape_err_rate", "up",
                              "advice"])]

    # cost section (cost_attribution events — obs/cost.py, ISSUE 19):
    # absent/empty for pre-PR-19 ledgers and cost-off runs, table omitted;
    # cost_per_request and padding/idle waste regress by growing,
    # busy_fraction (utilization) by DROPPING — gated by COST_RULES
    costs = sorted(set(base.get("cost") or {}) | set(new.get("cost") or {}))
    if costs:
        rows = []
        for label in costs:
            b = (base.get("cost") or {}).get(label, {})
            n = (new.get("cost") or {}).get(label, {})

            def kcell(metric, b=b, n=n):
                bv, nv = b.get(metric), n.get(metric)
                if bv is None and nv is None:
                    return "-"
                if bv is None or nv is None:
                    return f"{_fmt(bv)} → {_fmt(nv)}"
                if bv == nv:
                    return _fmt(nv)
                return f"{_fmt(bv)} → {_fmt(nv)}"

            rows.append([label, kcell("requests"), kcell("device_seconds"),
                         kcell("cost_per_request_s"), kcell("busy_fraction"),
                         kcell("padding_waste"), kcell("idle_fraction"),
                         kcell("saved_device_seconds")])
        out += ["", "cost & capacity (cost_attribution — cost_per_request/"
                "padding/idle regress by growing, utilization by dropping):",
                _table(rows, ["label", "requests", "device_s",
                              "cost_per_req_s", "busy_frac", "padding_waste",
                              "idle_frac", "saved_device_s"])]

    # incident section (incident events — obs/incident.py, ISSUE 18):
    # the overall "incident" label is seeded at zero on every run, so the
    # table only renders when either side actually captured something
    incs = sorted(set(base.get("incidents") or {})
                  | set(new.get("incidents") or {}))
    inc_rows = []
    for label in incs:
        b = (base.get("incidents") or {}).get(label, {})
        n = (new.get("incidents") or {}).get(label, {})
        if not (b.get("count") or n.get("count")
                or b.get("suppressed") or n.get("suppressed")):
            continue
        inc_rows.append([
            label,
            f"{_fmt(b.get('count', 0.0))} → {_fmt(n.get('count', 0.0))}",
            f"{_fmt(b.get('suppressed', 0.0))} → "
            f"{_fmt(n.get('suppressed', 0.0))}",
            f"{_fmt(b.get('events', 0.0))} → {_fmt(n.get('events', 0.0))}",
        ])
    if inc_rows:
        out += ["", "incidents (incident events — ANY increase in "
                "captured or suppressed bundles regresses):",
                _table(inc_rows, ["label", "bundles", "suppressed",
                                  "ring_events"])]

    # correctness section (probe / probe_audit events — obs/probe.py,
    # ISSUE 20): the overall "probe" label is seeded perfect on every
    # run, so the table only renders when either side actually probed
    # (or audited a divergence)
    probes = sorted(set(base.get("probes") or {})
                    | set(new.get("probes") or {}))
    probe_rows = []
    for label in probes:
        b = (base.get("probes") or {}).get(label, {})
        n = (new.get("probes") or {}).get(label, {})
        if not (b.get("count") or n.get("count")
                or b.get("divergences") or n.get("divergences")):
            continue
        probe_rows.append([
            label,
            f"{_fmt(b.get('count', 0.0))} → {_fmt(n.get('count', 0.0))}",
            f"{_fmt(b.get('success_rate', 1.0))} → "
            f"{_fmt(n.get('success_rate', 1.0))}",
            f"{_fmt(b.get('failures', 0.0))} → "
            f"{_fmt(n.get('failures', 0.0))}",
            f"{_fmt(b.get('divergences', 0.0))} → "
            f"{_fmt(n.get('divergences', 0.0))}",
        ])
    if probe_rows:
        out += ["", "correctness probes (probe/probe_audit — success "
                "rate regresses by dropping; ANY new answer-audit "
                "divergence regresses):",
                _table(probe_rows, ["label", "probes", "success_rate",
                                    "failures", "divergences"])]

    comp = sorted(set(base.get("compiles", {})) | set(new.get("compiles", {})))
    if comp:
        rows = []
        for label in comp:
            b = base.get("compiles", {}).get(label, {}).get("seconds")
            n = new.get("compiles", {}).get(label, {}).get("seconds")
            rows.append([label,
                         "-" if b is None else f"{b:.2f}",
                         "-" if n is None else f"{n:.2f}"])
        out += ["", "compile seconds:",
                _table(rows, ["program", "base", "new"])]

    regs = result["regressions"]
    if regs:
        out += ["", f"REGRESSIONS ({len(regs)}):"]
        for v in regs:
            pct = ("new" if v["delta_pct"] is None else f"{v['delta_pct']:+.1f}%")
            note = (" [HLO fingerprint changed — XLA built a different program]"
                    if v.get("fingerprint_changed") else "")
            out.append(
                f"  {v['rule']}  {v['program']}: "
                f"{_fmt(v['base'])} → {_fmt(v['new'])} ({pct}){note}"
            )
    else:
        out += ["", "no regressions"]
    return "\n".join(out)


def _load_run(path: str) -> Optional[Dict]:
    """LAST run in a ledger file (files append across invocations)."""
    try:
        runs = split_runs(read_ledger(path))
    except OSError as e:
        print(f"obs_diff: cannot read {path}: {e}", file=sys.stderr)
        return None
    if not runs:
        print(f"obs_diff: {path} holds no events", file=sys.stderr)
        return None
    return extract_run(runs[-1], source=path)


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="obs_diff.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("ledgers", nargs="*",
                        help="BASE.jsonl NEW.jsonl — or just NEW.jsonl with "
                             "--history")
    parser.add_argument("--history", type=str, default=None,
                        help="directory of ledger JSONLs; the baseline is "
                             "the most recent prior run sharing program "
                             "labels with the new run")
    parser.add_argument("--threshold-scale", type=float, default=1.0,
                        help="multiply every rule threshold (2.0 = twice as "
                             "tolerant)")
    parser.add_argument("--json", action="store_true",
                        help="print the machine-readable verdict object on "
                             "stdout (tables go to stderr)")
    args = parser.parse_args(argv[1:])

    if args.history is not None:
        if len(args.ledgers) > 1:
            parser.print_usage(sys.stderr)
            return 2
        try:
            hist = RunHistory.scan(args.history)
        except OSError as e:
            print(f"obs_diff: cannot scan {args.history}: {e}", file=sys.stderr)
            return 2
        new = _load_run(args.ledgers[0]) if args.ledgers else hist.latest()
        if new is None:
            print("obs_diff: no new run to compare", file=sys.stderr)
            return 2
        base = hist.baseline_for(new)
        if base is None:
            print("obs_diff: history holds no baseline run — nothing to "
                  "compare (pass)", file=sys.stderr)
            return 0
    else:
        if len(args.ledgers) != 2:
            parser.print_usage(sys.stderr)
            print(__doc__.strip(), file=sys.stderr)
            return 2
        base = _load_run(args.ledgers[0])
        new = _load_run(args.ledgers[1])
        if base is None or new is None:
            return 2

    # dataclasses.replace keeps every other field — notably `direction`:
    # rebuilding by hand once dropped it, silently flipping the
    # decrease-direction quality rules and the nonzero divergence invariant
    # back to increase-threshold semantics
    rules = tuple(
        dataclasses.replace(
            r, threshold_pct=r.threshold_pct * args.threshold_scale
        )
        for r in DEFAULT_RULES
    )
    result = evaluate_rules(base, new, rules)
    text = render_diff(base, new, result)
    if args.json:
        print(text, file=sys.stderr)
        print(json.dumps(result))
    else:
        print(text)
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
