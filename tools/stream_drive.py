"""Closed-loop streaming long-video driver (CPU CI harness, ISSUE 12).

Builds a deterministic synthetic N-window clip, a tiny (or real) warm
in-process engine, and runs one resumable streaming edit job through
``videop2p_tpu.stream.run_stream_job`` — the CI-sized twin of
``python -m videop2p_tpu.cli.stream``. Everything the job observes lands
in ONE run ledger (the engine's): per-window ``stream_window`` events +
``stream_window_e2e`` latency reservoirs, per-boundary ``stream_seam``
records, the engine's ``serve_health``, and the job-level
``stream_health`` summary — so two drive ledgers diff and GATE through
``tools/obs_diff.py`` (``SEAM_RULES`` + ``FAULT_RULES`` + ``TIMING_RULES``)
like any bench run:

    python tools/stream_drive.py --frames 14 --video_len 4 --overlap 1 \\
        --steps 2 --job_dir /tmp/jobA --ledger drive_a.jsonl
    python tools/stream_drive.py --frames 14 --video_len 4 --overlap 1 \\
        --steps 2 --job_dir /tmp/jobB --ledger drive_b.jsonl
    python tools/obs_diff.py drive_a.jsonl drive_b.jsonl

Chaos drills ride the same deterministic plans as the serving tier:
``--faults fail@2`` exercises the engine's transient-retry path under a
window; ``--faults 'unavail@2-99'`` (with ``--max_retries 0``) poisons
windows into recorded passthroughs; ``--faults corrupt:manifest`` tears
every manifest write so the NEXT run must detect and recover. A SIGKILL
at any point leaves a resumable job: rerun with the same ``--job_dir``
and the completed windows are skipped (the kill-and-resume acceptance in
``tests/test_stream.py`` pins bit-identical output).

Exit status: 0 on a fully-edited clip; 1 when any window failed or
degraded to passthrough (``--allow_passthrough`` tolerates degradations —
chaos drills expect them) or when ``--min_seam_psnr`` is set and the
worst seam falls below it; 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--frames", type=int, default=14,
                    help="synthetic clip length (frames)")
    ap.add_argument("--overlap", type=int, default=1)
    ap.add_argument("--job_dir", type=str, default="stream_drive_job")
    ap.add_argument("--ledger", type=str, default=None,
                    help="run-ledger path (default <job_dir>/stream_ledger"
                         ".jsonl)")
    ap.add_argument("--no_resume", action="store_true")
    ap.add_argument("--window_retries", type=int, default=2)
    ap.add_argument("--max_inflight", type=int, default=4)
    ap.add_argument("--prompt", type=str, default="a rabbit is jumping")
    ap.add_argument("--edit_prompt", type=str,
                    default="a origami rabbit is jumping")
    ap.add_argument("--seed", type=int, default=0)
    # tiny-engine knobs (CI defaults)
    ap.add_argument("--tiny", action="store_true", default=None)
    ap.add_argument("--checkpoint", type=str, default=None)
    ap.add_argument("--video_len", type=int, default=4,
                    help="frames per window (the warm programs' geometry)")
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--max_batch", type=int, default=4)
    ap.add_argument("--scheduler", type=str, default="continuous",
                    choices=["drain", "continuous", "fair"])
    ap.add_argument("--max_retries", type=int, default=2,
                    help="engine-level transient dispatch retries")
    ap.add_argument("--dispatch_timeout_s", type=float, default=None)
    # chaos + gates
    ap.add_argument("--faults", type=str, default=None,
                    help="deterministic chaos plan (serve/faults.py DSL + "
                         "corrupt:manifest)")
    ap.add_argument("--allow_passthrough", action="store_true",
                    help="degraded (passthrough) windows do not fail the "
                         "drive — chaos drills expect them")
    ap.add_argument("--min_seam_psnr", type=float, default=None,
                    help="exit 1 when the worst window-seam adjacent-frame "
                         "PSNR falls below this (dB)")
    args = ap.parse_args(argv)

    from videop2p_tpu.cli.common import enable_compile_cache

    enable_compile_cache()
    from videop2p_tpu.serve import EditEngine, FaultPlan, ProgramSpec
    from videop2p_tpu.stream import run_stream_job, synthetic_clip

    tiny = True if args.tiny is None else args.tiny
    spec = ProgramSpec(checkpoint=args.checkpoint, tiny=tiny,
                       width=args.width, video_len=args.video_len,
                       steps=args.steps, seed=args.seed)
    resolved = spec.resolved()
    frames = synthetic_clip(args.frames, resolved.width, seed=args.seed)
    faults = FaultPlan.parse(args.faults) if args.faults else None
    os.makedirs(args.job_dir, exist_ok=True)
    engine = EditEngine(
        spec,
        out_dir=os.path.join(args.job_dir, "serve_out"),
        persist_dir=os.path.join(args.job_dir, "inv_store"),
        max_batch=args.max_batch,
        scheduler=args.scheduler,
        max_retries=args.max_retries,
        dispatch_timeout_s=args.dispatch_timeout_s,
        ledger_path=(args.ledger
                     or os.path.join(args.job_dir, "stream_ledger.jsonl")),
        keep_videos=True,
        faults=faults,
    )
    prompts = [args.prompt, args.edit_prompt]
    engine.warm(tuple(prompts), batch_sizes=(min(2, args.max_batch),))
    try:
        result = run_stream_job(
            engine, frames, prompts,
            job_dir=args.job_dir,
            overlap=args.overlap,
            seed=args.seed,
            window_retries=args.window_retries,
            max_inflight=args.max_inflight,
            resume=not args.no_resume,
            faults=faults,
        )
    finally:
        engine.close()
    record = {
        "stream_health": result.health,
        "seams": result.seams,
        "windows": result.windows,
        "ledger": engine.ledger.path,
        "final": (os.path.join(args.job_dir, "final.npy")
                  if result.complete else None),
    }
    print(json.dumps(record, default=str))
    health = result.health
    if not result.complete:
        print("[stream_drive] job incomplete", file=sys.stderr)
        return 1
    degraded = health["windows_failed"] or health["windows_passthrough"]
    if degraded and not args.allow_passthrough:
        print(f"[stream_drive] {health['windows_passthrough']} window(s) "
              "degraded to passthrough "
              f"({health['windows_failed']} poisoned)", file=sys.stderr)
        return 1
    if (args.min_seam_psnr is not None
            and health["seam_min_psnr"] < args.min_seam_psnr):
        print(f"[stream_drive] seam_min_psnr {health['seam_min_psnr']} < "
              f"required {args.min_seam_psnr}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
