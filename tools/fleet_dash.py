"""Render a fleet-collector ledger as a self-contained HTML dashboard.

Usage:  python tools/fleet_dash.py <collector_ledger.jsonl> [--out dash.html]
                                   [--title TITLE]

The telemetry plane's human face (ISSUE 17): reads the ``fleet_signals``
evaluations and the ``fleet_series`` tsdb snapshot a
:class:`videop2p_tpu.serve.collector.FleetCollector` run left behind
(``tools/serve_loadgen.py --collector`` wires one up) and renders:

  * **burn gauges** — the last evaluation's fast/slow-window burn rates
    as bars against the alert threshold, plus the burn history;
  * **scale-advice timeline** — one colored cell per evaluation
    (grow/hold/shrink) so a degraded window is visible at a glance;
  * **per-series sparklines** — every series in the ``fleet_series``
    ``.npz`` sidecar (queue depth, in-flight, latency percentiles,
    per-status request counters, per-tenant meters, scrape health) with
    gap markers preserved — a dead replica's outage shows as a hole,
    never an interpolated line;
  * **per-tenant demand table** — submitted/served/shed rates and
    device-seconds per lane from the last evaluation;
  * **cost panel (ISSUE 19)** — engine utilization/padding-waste and the
    per-tenant attributed device-seconds from the run's
    ``cost_attribution`` chargeback rows (full showback:
    ``tools/cost_report.py``);
  * **correctness panel (ISSUE 20)** — per-target known-answer probe
    verdicts and every cross-replica answer-audit divergence with its
    content-hash pair (full report: ``tools/probe_report.py``).

Everything is inline (CSS + SVG, no external assets) — the output ships
in a bug report. Tolerates signal-only ledgers (no snapshot event → no
sparkline section) and pre-PR-17 ledgers (renders an empty-state page).

stdlib+numpy+videop2p_tpu only — the import-guard test walks this file.
"""

from __future__ import annotations

import html
import math
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.ledger import read_ledger  # noqa: E402
from videop2p_tpu.obs.report import (  # noqa: E402
    _CSS,
    _fmt,
    _last_run,
    _svg_spark,
    _table,
)
from videop2p_tpu.obs.tsdb import load_series_sidecar  # noqa: E402

_ADVICE_COLOR = {"grow": "#b22222", "hold": "#999999", "shrink": "#2a7ab8"}


def _burn_gauge(label: str, burn: float, threshold: float,
                w: int = 320, h: int = 22) -> str:
    """One horizontal burn bar: fill is burn relative to 3x threshold,
    red past the threshold tick."""
    burn = max(float(burn or 0.0), 0.0)
    thr = max(float(threshold), 1e-9)
    cap = 3.0 * thr
    frac = min(burn / cap, 1.0)
    tick = min(thr / cap, 1.0)
    color = "#b22222" if burn > thr else "#2a7a2a"
    return (
        f'<div class=row><svg width="{w}" height="{h}">'
        f'<rect x="0" y="3" width="{w - 70}" height="{h - 6}" '
        f'fill="#eee" stroke="#ccc"/>'
        f'<rect x="0" y="3" width="{frac * (w - 70):.1f}" height="{h - 6}" '
        f'fill="{color}"/>'
        f'<line x1="{tick * (w - 70):.1f}" y1="0" '
        f'x2="{tick * (w - 70):.1f}" y2="{h}" stroke="#333" '
        f'stroke-dasharray="2,2"/>'
        f'<text x="{w - 64}" y="{h - 7}" font-size="11">{burn:.2f}x</text>'
        f'</svg><span class=meta> {html.escape(label)} '
        f'(alert past {threshold:g})</span></div>'
    )


def _advice_timeline(sigs: Sequence[Dict[str, Any]], w_cell: int = 14,
                     h: int = 26,
                     incidents: Sequence[Dict[str, Any]] = ()) -> str:
    if not sigs:
        return ""
    cells = []
    for i, e in enumerate(sigs):
        advice = str(e.get("scale_advice", "?"))
        color = _ADVICE_COLOR.get(advice, "#e0c040")
        title = (f"eval {i}: {advice}"
                 + (f" — {'; '.join(map(str, e.get('reasons') or []))}"
                    if e.get("reasons") else ""))
        cells.append(
            f'<rect x="{i * w_cell}" y="2" width="{w_cell - 2}" '
            f'height="{h - 4}" fill="{color}">'
            f"<title>{html.escape(title)}</title></rect>")
    # incident markers (ISSUE 18): a red diamond over the evaluation the
    # capture landed in (matched by ledger `t`), tooltip carrying trigger
    # kind + bundle path — the dashboard names the evidence directory
    for inc in incidents:
        it = inc.get("t")
        idx = len(sigs) - 1
        if isinstance(it, (int, float)):
            idx = next((i for i, e in enumerate(sigs)
                        if isinstance(e.get("t"), (int, float))
                        and e["t"] >= it), len(sigs) - 1)
        cx = idx * w_cell + (w_cell - 2) / 2
        title = (f"incident {inc.get('trigger', '?')}: "
                 f"{inc.get('detail', '')} — bundle "
                 f"{inc.get('bundle', '?')}")
        cells.append(
            f'<path d="M {cx:.1f} 0 l 5 6 l -5 6 l -5 -6 z" '
            f'fill="#b22222" stroke="#fff" stroke-width="1">'
            f"<title>{html.escape(title)}</title></path>")
    w = len(sigs) * w_cell
    legend = " ".join(
        f'<span style="color:{c}">■</span> {a}'
        for a, c in _ADVICE_COLOR.items())
    if incidents:
        legend += (' <span style="color:#b22222">◆</span> '
                   f"incident ({len(incidents)})")
    return (f'<div class=row><svg width="{w}" height="{h}">'
            + "".join(cells) + f"</svg><span class=meta> {legend}</span></div>")


def _series_sparklines(series: Dict[str, List[Tuple[float, float]]]) -> str:
    """One sparkline per stored series, NaN gaps preserved as breaks
    (``_svg_spark`` drops non-finite points, leaving a visible hole)."""
    out: List[str] = []
    for key in sorted(series):
        pts = series[key]
        vals = [v for _, v in pts]
        finite = [v for v in vals if not math.isnan(v)]
        gaps = len(vals) - len(finite)
        label = (f"{key} — {len(vals)} pts"
                 + (f", {gaps} gaps" if gaps else "")
                 + (f", last {finite[-1]:.4g}" if finite else ""))
        out.append("<div class=row>" + _svg_spark(vals, label=label)
                   + "</div>")
    return "".join(out)


def render_dash(events: Sequence[Dict[str, Any]],
                series: Optional[Dict[str, List[Tuple[float, float]]]] = None,
                *, title: str = "Fleet dashboard") -> str:
    """One self-contained HTML page from a collector run's events (+ the
    decoded ``fleet_series`` sidecar when available)."""
    events = [e for e in events if isinstance(e, dict)]
    start = next((e for e in events if e.get("event") == "run_start"), {})
    sigs = [e for e in events if e.get("event") == "fleet_signals"]
    incidents = [e for e in events if e.get("event") == "incident"]
    costs = [e for e in events if e.get("event") == "cost_attribution"]
    probes = [e for e in events if e.get("event") == "probe"]
    audits = [e for e in events if e.get("event") == "probe_audit"]
    snap = next((e for e in reversed(events)
                 if e.get("event") == "fleet_series"), None)
    body: List[str] = [
        f"<h1>{html.escape(title)}</h1>",
        f"<p class=meta>run <code>"
        f"{html.escape(str(start.get('run_id', '?')))}</code> · "
        f"{len(sigs)} signal evaluation(s) · generated by "
        f"tools/fleet_dash.py (stdlib+numpy, all assets inline)</p>",
    ]
    if not sigs and snap is None:
        body.append("<p class=meta>(no fleet_signals / fleet_series events "
                    "— run the collector: tools/serve_loadgen.py "
                    "--collector)</p>")
    if sigs:
        last = sigs[-1]
        threshold = 1.0
        body.append("<h2>Burn gauges</h2>")
        body.append("<p class=meta>error-rate burn per trailing window "
                    f"(window_scale {_fmt(last.get('window_scale'))}: fast "
                    f"{_fmt(last.get('fast_window_s'))}s / slow "
                    f"{_fmt(last.get('slow_window_s'))}s); the page-worthy "
                    "alert needs BOTH windows past the tick.</p>")
        body.append(_burn_gauge("fast window",
                                last.get("burn_fast") or 0.0, threshold))
        body.append(_burn_gauge("slow window",
                                last.get("burn_slow") or 0.0, threshold))
        body.append("<div class=row>" + _svg_spark(
            [e.get("burn_fast") for e in sigs],
            label=f"fast-burn history, alerts fired "
                  f"{_fmt(last.get('burn_alerts'))}") + "</div>")
        body.append("<h2>Scale advice</h2>")
        body.append(_advice_timeline(sigs, incidents=incidents))
        body.append(
            f"<p class=meta>last advice: "
            f"<b>{html.escape(str(last.get('scale_advice', '?')))}</b>"
            + ("; reasons: " + "; ".join(
                html.escape(str(r)) for r in last.get("reasons") or [])
               if last.get("reasons") else "") + "</p>")
        rows = [[k, _fmt(last.get(k))] for k in (
            "error_rate_fast", "error_rate_slow", "queue_slope",
            "inflight_slope", "saturation", "latency_p99_s",
            "store_hit_rate", "replicas_up", "replicas_total",
            "scrape_errors", "scrape_error_rate", "latency_anomaly",
            "store_hit_anomaly", "utilization", "idle_fraction",
            "padding_waste", "cost_per_request_s", "demand_rps",
            "capacity_rps", "headroom_rps",
            "utilization_forecast") if last.get(k) is not None]
        if rows:
            body.append("<h2>Latest signals</h2>"
                        + _table(rows, ["signal", "value"]))
        tenants = last.get("tenants")
        if isinstance(tenants, dict) and tenants:
            trows = [[t, _fmt(v.get("submitted_rate")),
                      _fmt(v.get("served_rate")), _fmt(v.get("shed_rate")),
                      _fmt(v.get("device_seconds"))]
                     for t, v in sorted(tenants.items())
                     if isinstance(v, dict)]
            body.append("<h2>Per-tenant demand</h2>"
                        "<p class=meta>submitted/served/shed rates over "
                        "the slow window; device-seconds measured from the "
                        "scraped cost plane when present, else estimated "
                        "from the dispatch p50.</p>"
                        + _table(trows, ["tenant", "submit/s", "served/s",
                                         "shed/s", "device_s"]))
    # cost panel (ISSUE 19): the chargeback rows loadgen lands as
    # cost_attribution extra events — utilization per engine, attributed
    # device-seconds per tenant lane; absent for pre-cost-plane ledgers
    if costs:
        eng_rows = [[str(e.get("label", "serve")),
                     _fmt(e.get("busy_fraction")),
                     _fmt(e.get("idle_fraction")),
                     _fmt(e.get("padding_waste")),
                     _fmt(e.get("occupancy")),
                     _fmt(e.get("cost_per_request_s"))]
                    for e in costs if e.get("scope") == "engine"]
        ten_rows = [[str(e.get("name", "?")), _fmt(e.get("requests")),
                     _fmt(e.get("device_seconds")), _fmt(e.get("flops")),
                     _fmt(e.get("saved_device_seconds"))]
                    for e in costs if e.get("scope") == "tenant"]
        body.append("<h2>Cost &amp; capacity</h2>"
                    "<p class=meta>fair-share attribution "
                    "(cost_attribution events — obs/cost.py); full "
                    "showback: tools/cost_report.py &lt;ledger&gt;.</p>")
        if eng_rows:
            body.append(_table(eng_rows, ["engine", "busy_frac",
                                          "idle_frac", "padding_waste",
                                          "occupancy", "cost/req (s)"]))
        if ten_rows:
            body.append(_table(ten_rows, ["tenant", "requests", "device_s",
                                          "flops", "saved_device_s"]))
    # correctness panel (ISSUE 20): the prober's known-answer verdicts —
    # per-target pass/fail counts plus every answer-audit divergence with
    # its hash pair; full report: tools/probe_report.py
    if probes or audits:
        divergent = {str(a.get("divergent")) for a in audits}
        tallies: Dict[str, List[int]] = {}
        for e in probes:
            t = tallies.setdefault(str(e.get("target", "?")), [0, 0])
            t[0] += 1
            t[1] += 0 if e.get("ok") else 1
        prows = [[tname, _fmt(n), _fmt(bad),
                  ("DIVERGENT — quarantine" if tname in divergent
                   else ("failing" if bad else "ok"))]
                 for tname, (n, bad) in sorted(tallies.items())]
        pmarks = [("bad" if r[3] != "ok" else "") for r in prows]
        body.append("<h2>Correctness probes</h2>"
                    "<p class=meta>known-answer canaries + cross-replica "
                    "answer audit (probe/probe_audit events); full "
                    "report: tools/probe_report.py &lt;ledger&gt;.</p>")
        if prows:
            body.append(_table(prows, ["target", "probes", "failed",
                                       "verdict"], pmarks))
        if audits:
            arows = [[str(a.get("divergent", "?")),
                      str(a.get("hash_b", ""))[:16],
                      str(a.get("replica_a", "?")),
                      str(a.get("hash_a", ""))[:16]]
                     for a in audits]
            body.append(_table(arows, ["divergent", "its hash",
                                       "reference", "ref hash"],
                               ["bad"] * len(arows)))
    if incidents:
        irows = [[_fmt(e.get("t", "")), str(e.get("trigger", "?")),
                  str(e.get("detail", ""))[:120],
                  str(e.get("bundle", "?")), _fmt(e.get("suppressed", 0))]
                 for e in incidents]
        body.append("<h2>Incidents</h2>"
                    "<p class=meta>capture bundles this run — render one "
                    "with tools/incident_report.py &lt;bundle&gt;.</p>"
                    + _table(irows, ["t (s)", "trigger", "detail",
                                     "bundle", "suppressed"],
                             ["bad"] * len(irows)))
    if snap is not None:
        body.append("<h2>Series</h2>")
        body.append(
            f"<p class=meta>tsdb snapshot: {_fmt(snap.get('series'))} "
            f"series / {_fmt(snap.get('samples'))} samples, "
            f"{_fmt(snap.get('gaps'))} gap(s), "
            f"{_fmt(snap.get('dropped'))} dropped, span "
            f"[{_fmt(snap.get('t_first'))}, {_fmt(snap.get('t_last'))}]s"
            "</p>")
        if series:
            body.append(_series_sparklines(series))
        elif snap.get("sidecar"):
            body.append(f"<p class=meta>(sidecar "
                        f"{html.escape(str(snap['sidecar']))} not found — "
                        "sparklines omitted)</p>")
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>{_CSS}</style>"
            "</head><body>" + "".join(b for b in body if b)
            + "</body></html>")


def _find_series(events: Sequence[Dict[str, Any]], ledger_path: str,
                 ) -> Optional[Dict[str, List[Tuple[float, float]]]]:
    snap = next((e for e in reversed(events)
                 if isinstance(e, dict) and e.get("event") == "fleet_series"
                 and e.get("sidecar")), None)
    if snap is None:
        return None
    sc = str(snap["sidecar"])
    for cand in (sc, os.path.join(os.path.dirname(os.path.abspath(
            ledger_path)), os.path.basename(sc))):
        if os.path.isfile(cand):
            try:
                return load_series_sidecar(cand)
            except Exception:  # noqa: BLE001 — a torn sidecar skips sparklines
                return None
    return None


def write_dash(ledger_path: str, out_path: Optional[str] = None,
               *, title: str = "Fleet dashboard") -> str:
    """Render the LAST run of a collector ledger into a self-contained
    HTML file next to it."""
    events = _last_run(read_ledger(ledger_path))
    series = _find_series(events, ledger_path)
    out_path = out_path or os.path.splitext(ledger_path)[0] + "_fleet.html"
    text = render_dash(events, series, title=title)
    with open(out_path, "w") as f:
        f.write(text)
    return out_path


def main(argv: List[str]) -> int:
    if any(a in ("-h", "--help") for a in argv[1:]):
        print(__doc__.strip())
        return 0
    args = list(argv[1:])
    out = None
    title = "Fleet dashboard"
    rest: List[str] = []
    i = 0
    while i < len(args):
        if args[i] == "--out" and i + 1 < len(args):
            out = args[i + 1]
            i += 2
        elif args[i] == "--title" and i + 1 < len(args):
            title = args[i + 1]
            i += 2
        else:
            rest.append(args[i])
            i += 1
    if len(rest) != 1:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    try:
        path = write_dash(rest[0], out, title=title)
    except OSError as e:
        print(f"fleet_dash: cannot read {rest[0]}: {e}", file=sys.stderr)
        return 2
    print(path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
