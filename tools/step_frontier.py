"""Execute the cached fast path's latency-vs-quality step frontier on CPU.

Usage:  python tools/step_frontier.py [--tiny] [--frames 2]
            [--base_steps 50] [--steps 50,20,8]
            [--variants w8+off,off+uniform:2,w8+uniform:2]

Runs ONE ``--base_steps`` captured DDIM inversion and then the cached
controlled edit at each requested step count via exact timestep-subset
schedules (``bench.run_step_frontier`` — the same function the healthy
bench runs on the accelerator), scoring every variant against the
full-step edit with the obs/quality metrics (PSNR / SSIM /
background-preservation / adjacent-frame consistency) and asserting the
source replay stays exact (``src_err == 0.0``) at every step count.

This is bench.py's backend-down fallback for the ISSUE-8 frontier
acceptance: quality-vs-steps is backend-independent math, so the 8- and
20-step variants can be proven to run e2e from a 50-step inversion EVERY
round — wall-clock is recorded but disclosed as CPU(-tiny), never a TPU
claim. One JSON line per step count, flushed as each finishes, so a
caller's timeout keeps whatever completed. ``--tiny`` swaps in the tiny
UNet (the test/backend-down configuration; SD scale would take hours of
CPU execute).

``--variants`` (ISSUE 15) adds per-call-cost rows to the same frontier:
a comma list of ``<quant_mode>+<reuse_schedule>`` pairs (each split on
its first ``+``; ``custom:`` schedules are comma-bearing and so not
expressible here — use ``off``/``uniform:K``), each running the
full-step cached edit with int8-quantized weights and/or a DeepCache
reuse schedule and scored against the full-precision full-step edit.
The replay-exactness invariant applies to these rows too: ``src_err``
must stay 0.0 under both knobs.

Student rows (ISSUE 16): a variant may instead be
``student:<N>+<quant_mode>+<reuse_schedule>`` (e.g.
``student:2+w8+uniform:2``) — the consistency-distilled few-step
student at ``N`` steps of the base schedule's exact timestep subset,
composed with the same quant/reuse knobs. The tool runs these with the
identity-initialized time-conditioning head (the untrained-student
baseline, value-exact with the teacher), so the rows prove the composed
program runs e2e and its ``src_err`` stays 0.0; quality claims for a
TRAINED student come from the distillation pipeline's ledger through
``tools/obs_diff.py``. Duplicate ``--variants`` entries are rejected
(exit 2) rather than silently recorded as duplicate frontier rows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import jax  # noqa: E402

# the env-var route loses to this image's sitecustomize (it hard-sets
# jax_platforms via jax.config) — only a later config update actually
# selects CPU (same dance as tools/cpu_cost_capture.py)
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from videop2p_tpu.cli.common import enable_compile_cache  # noqa: E402

enable_compile_cache()


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(prog="step_frontier.py",
                                     description=__doc__)
    parser.add_argument("--frames", type=int, default=2)
    parser.add_argument("--base_steps", type=int, default=50)
    parser.add_argument("--steps", type=str, default="50,20,8")
    parser.add_argument("--tiny", action="store_true",
                        help="tiny UNet config (the CPU-executable scale)")
    parser.add_argument("--no_time", action="store_true",
                        help="skip the timing dispatches (quality only)")
    parser.add_argument("--variants", type=str, default="",
                        help="comma list of quant_mode+reuse_schedule pairs "
                             "(e.g. w8+off,off+uniform:2,w8+uniform:2) "
                             "and/or student:N+quant_mode+reuse_schedule "
                             "rows (e.g. student:2+w8+uniform:2)")
    args = parser.parse_args(argv[1:])

    variants = []
    seen = set()
    for entry in args.variants.split(","):
        entry = entry.strip()
        if not entry:
            continue
        # the student prefix is checked BEFORE the first-"+" split: a
        # naive split would hand "student:2" to quant-mode validation
        # and produce a confusing downstream error
        if entry.startswith("student:"):
            parts = entry[len("student:"):].split("+", 2)
            if len(parts) != 3 or not parts[0].isdigit() or int(parts[0]) < 1:
                print(f"step_frontier: --variants entry {entry!r} is not "
                      "student:<N>+<quant_mode>+<reuse_schedule> (N >= 1)",
                      file=sys.stderr)
                return 2
            variant = (int(parts[0]), parts[1], parts[2])
        elif "+" in entry:
            qm, rs = entry.split("+", 1)
            variant = (qm, rs)
        else:
            print(f"step_frontier: --variants entry {entry!r} is not "
                  "<quant_mode>+<reuse_schedule> or "
                  "student:<N>+<quant_mode>+<reuse_schedule>",
                  file=sys.stderr)
            return 2
        if variant in seen:
            print(f"step_frontier: duplicate --variants entry {entry!r} — "
                  "each variant yields one frontier row; a repeat would be "
                  "silently recorded as a duplicate row", file=sys.stderr)
            return 2
        seen.add(variant)
        variants.append(variant)

    import bench

    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.pipelines import make_unet_fn

    if args.tiny:
        cfg = UNet3DConfig.tiny()
        lat, ctx_dim = cfg.sample_size, cfg.cross_attention_dim
        dtype = jnp.float32
    else:
        cfg = UNet3DConfig.sd15(frame_attention="chunked", group_norm="xla")
        lat, ctx_dim, dtype = 64, 768, jnp.bfloat16
    model = UNet3DConditionModel(config=cfg, dtype=dtype)
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()
    key = jax.random.key(0)
    x0 = jax.random.normal(key, (1, args.frames, lat, lat, 4), dtype)
    cond = jax.random.normal(jax.random.fold_in(key, 1),
                             (2, 77, ctx_dim), dtype)
    uncond = jnp.zeros((77, ctx_dim), dtype)
    params = jax.jit(model.init)(
        jax.random.fold_in(key, 2), x0[:, :2], jnp.asarray(10), cond[:1]
    )

    student_head = None
    if any(len(v) == 3 for v in variants):
        # the untrained-student baseline: identity-initialized time head
        # (zero-init output layer ⇒ value-exact with the teacher forward)
        from videop2p_tpu.train.distill import init_time_head

        student_head = init_time_head(jax.random.key(0), cfg)

    step_counts = [int(s) for s in args.steps.split(",") if s.strip()]
    records, _ = bench.run_step_frontier(
        fn, params, sched, cond, uncond, x0,
        base_steps=args.base_steps, step_counts=step_counts,
        timed=not args.no_time, variants=tuple(variants),
        student_head=student_head,
    )
    rc = 0
    for rec in records:
        rec = {"backend": "cpu", "tiny": bool(args.tiny), **rec}
        if rec["src_err"] != 0.0:
            rc = 1  # the replay-exactness invariant broke — say so loudly
        print(json.dumps(rec), flush=True)  # line per step: timeout-safe
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
