"""Closed-loop load generator for the edit-serving engine.

Drives N requests at a fixed concurrency against a running engine — over
HTTP (``--url``, a ``cli/serve.py`` process) or fully in-process
(``--inproc``, builds a tiny/random-init engine; the CI smoke mode) — and
writes an ``execute_timing``-compatible run ledger: per-phase client-side
latency reservoirs (``loadgen_request`` end-to-end, ``loadgen_submit``)
flushed through the same :class:`~videop2p_tpu.obs.timing.LatencyReservoir`
machinery every other run record uses. Two loadgen ledgers therefore diff
and GATE with ``tools/obs_diff.py`` (``TIMING_RULES``) like any bench run:

    python tools/serve_loadgen.py --url http://host:8000 --requests 64 \
        --concurrency 8 --image data/rabbit --ledger loadgen_a.jsonl
    python tools/obs_diff.py loadgen_a.jsonl loadgen_b.jsonl

Closed loop = each worker submits its next request only after the previous
one finished — the concurrency IS the offered load, so latency percentiles
are comparable across runs without open-loop arrival modeling.

Chaos mode (ISSUE 9): ``--faults <plan>`` (``--inproc`` only) drives the
engine under a deterministic injected fault plan (serve/faults.py DSL —
``fail@K``, ``hang@K:S``, ``unavail@A-B``, ``corrupt:PAT``), classifies
outcomes per terminal status (done / error / deadline_exceeded / shed),
copies the engine's ``fault``/``breaker`` events and its ``serve_health``
summary into the loadgen ledger (so ``tools/obs_diff.py`` gates the run's
reliability through ``FAULT_RULES`` exactly like its latency through
``TIMING_RULES``), and asserts the healthy-request success rate
(``--min_success_rate``; exit 1 below it):

    python tools/serve_loadgen.py --inproc --tiny --requests 8 \
        --faults 'fail@2,unavail@4-5' --min_success_rate 0.5
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


class _HttpTarget:
    def __init__(self, url: str, timeout_s: float):
        from videop2p_tpu.serve.client import EngineClient

        self.client = EngineClient(url)
        self.timeout_s = timeout_s

    def one(self, request: Dict[str, Any]) -> Dict[str, Any]:
        t0 = time.perf_counter()
        rid = self.client.submit(request)
        submit_s = time.perf_counter() - t0
        rec = self.client.wait(rid, timeout_s=self.timeout_s)
        rec["_submit_s"] = submit_s
        rec["_e2e_s"] = time.perf_counter() - t0
        return rec


class _InprocTarget:
    def __init__(self, engine, timeout_s: float):
        self.engine = engine
        self.timeout_s = timeout_s

    def one(self, request: Dict[str, Any]) -> Dict[str, Any]:
        from videop2p_tpu.serve.engine import EditRequest

        t0 = time.perf_counter()
        rid = self.engine.submit(EditRequest.from_dict(request))
        submit_s = time.perf_counter() - t0
        rec = self.engine.result(rid, wait_s=self.timeout_s)
        rec["_submit_s"] = submit_s
        rec["_e2e_s"] = time.perf_counter() - t0
        return rec


def _is_shed(exc: Exception) -> bool:
    """Was this submit load-shed (429) or fast-failed unavailable (503)?
    Sheds are the backpressure layer working as designed — counted apart
    from genuine errors."""
    try:
        from videop2p_tpu.serve.faults import EngineUnavailable, QueueFull

        if isinstance(exc, (QueueFull, EngineUnavailable)):
            return True
    except ImportError:
        pass
    msg = str(exc)
    return "HTTP 429" in msg or "HTTP 503" in msg


def run_loadgen(
    target,
    request: Dict[str, Any],
    *,
    requests: int,
    concurrency: int,
    ledger_path: Optional[str] = None,
    meta: Optional[Dict[str, Any]] = None,
    collect_extra=None,
) -> Dict[str, Any]:
    """Run the closed loop; returns the summary record (also printed as one
    JSON line by :func:`main`). When ``ledger_path`` is given, the
    reservoirs flush there as ``execute_timing`` events. ``collect_extra``
    (chaos mode) is called after the loop and may return extra ledger
    events (dicts with an ``"event"`` key — the engine's ``fault`` /
    ``breaker`` trail and its ``serve_health`` summary) to write into the
    same ledger, making the run's reliability obs_diff-gateable."""
    from videop2p_tpu.obs.timing import LatencyReservoir

    reservoirs = {
        "loadgen_request": LatencyReservoir(),
        "loadgen_submit": LatencyReservoir(),
    }
    lock = threading.Lock()
    counters = {"done": 0, "errors": 0, "deadline_exceeded": 0, "shed": 0,
                "store_hits": 0, "issued": 0}

    def worker():
        while True:
            with lock:
                if counters["issued"] >= requests:
                    return
                counters["issued"] += 1
            try:
                rec = target.one(dict(request))
            except Exception as e:  # noqa: BLE001 — a failed request is a counter, not a crash
                with lock:
                    counters["shed" if _is_shed(e) else "errors"] += 1
                print(f"[loadgen] request failed: {e}", file=sys.stderr)
                continue
            with lock:
                status = rec.get("status")
                if status == "done":
                    counters["done"] += 1
                    if rec.get("store_hit"):
                        counters["store_hits"] += 1
                elif status == "deadline_exceeded":
                    counters["deadline_exceeded"] += 1
                else:
                    counters["errors"] += 1
            reservoirs["loadgen_request"].add(rec["_e2e_s"], rec["_e2e_s"])
            reservoirs["loadgen_submit"].add(rec["_submit_s"], rec["_submit_s"])

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(int(concurrency), 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0

    summaries = {name: res.summary() for name, res in reservoirs.items()
                 if res.summary()}
    # sheds are correct backpressure, not failures — the success rate is
    # over the requests the engine actually accepted
    accepted = max(requests - counters["shed"], 1)
    record = {
        "requests": requests,
        "concurrency": concurrency,
        "done": counters["done"],
        "errors": counters["errors"],
        "deadline_exceeded": counters["deadline_exceeded"],
        "shed": counters["shed"],
        "store_hits": counters["store_hits"],
        "success_rate": round(counters["done"] / accepted, 4),
        "wall_s": round(wall_s, 4),
        "throughput_rps": round(counters["done"] / wall_s, 4) if wall_s else None,
        "latency": summaries.get("loadgen_request"),
    }
    extra_events = []
    if collect_extra is not None:
        try:
            extra_events = list(collect_extra(record) or [])
        except Exception as e:  # noqa: BLE001 — chaos bookkeeping must not fail the run
            print(f"[loadgen] collect_extra failed: {e}", file=sys.stderr)
    if ledger_path:
        from videop2p_tpu.obs import RunLedger

        led = RunLedger(
            ledger_path,
            meta={"cli": "serve_loadgen", **(meta or {}),
                  "requests": requests, "concurrency": concurrency},
        )
        for name, res in reservoirs.items():
            for d, b in res.samples():
                led.record_execute(name, d, b)
        for e in extra_events:
            ev = dict(e)
            led.event(ev.pop("event", "fault"), **ev)
        led.event("loadgen_summary", **{k: v for k, v in record.items()
                                        if k != "latency"})
        led.close()  # flushes execute_timing events
        record["ledger"] = ledger_path
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    target_group = ap.add_mutually_exclusive_group(required=True)
    target_group.add_argument("--url", type=str,
                              help="base URL of a running cli/serve.py engine")
    target_group.add_argument("--inproc", action="store_true",
                              help="build an in-process engine (tiny/"
                                   "random-init smoke mode)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--timeout_s", type=float, default=600.0)
    ap.add_argument("--image", type=str, default="data/rabbit")
    ap.add_argument("--prompt", type=str, default="a rabbit is jumping")
    ap.add_argument("--edit_prompt", type=str,
                    default="a origami rabbit is jumping")
    ap.add_argument("--distinct_seeds", action="store_true",
                    help="vary the request seed per issue index so every "
                         "request MISSES the inversion store (cold-path "
                         "load) instead of hitting after the first")
    ap.add_argument("--ledger", type=str, default="loadgen_ledger.jsonl")
    # in-process engine knobs (smoke mode)
    ap.add_argument("--tiny", action="store_true", default=None)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--video_len", type=int, default=2)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--checkpoint", type=str, default=None)
    ap.add_argument("--max_batch", type=int, default=4)
    # chaos mode (ISSUE 9): deterministic fault injection + resilience knobs
    ap.add_argument("--faults", type=str, default=None,
                    help="fault plan (serve/faults.py DSL: fail@K, "
                         "hang@K:S, unavail@A-B, corrupt:PAT) injected into "
                         "the --inproc engine; the engine's fault/breaker "
                         "events and serve_health summary land in the "
                         "loadgen ledger")
    ap.add_argument("--min_success_rate", type=float, default=None,
                    help="exit 1 when done/(requests-shed) falls below "
                         "this; default 0.5 in chaos mode, else the legacy "
                         "errors!=0 rule")
    ap.add_argument("--deadline_s", type=float, default=None,
                    help="default per-request deadline for the --inproc "
                         "engine")
    ap.add_argument("--dispatch_timeout_s", type=float, default=None)
    ap.add_argument("--max_retries", type=int, default=2)
    ap.add_argument("--breaker_threshold", type=int, default=3)
    ap.add_argument("--breaker_open_s", type=float, default=1.0)
    ap.add_argument("--max_queue", type=int, default=64)
    args = ap.parse_args(argv)
    if args.faults and args.url:
        ap.error("--faults injects at the engine seams — use --inproc "
                 "(a remote engine takes VIDEOP2P_SERVE_FAULTS / "
                 "cli/serve.py --faults instead)")

    request = {
        "image_path": args.image,
        "prompt": args.prompt,
        "prompts": [args.prompt, args.edit_prompt],
        "save_name": "loadgen",
    }
    engine = None
    collect_extra = None
    if args.url:
        target = _HttpTarget(args.url, args.timeout_s)
        meta = {"target": args.url}

        def collect_extra(record, client=target.client):
            # client-side reliability summary (the remote engine's own
            # ledger holds the authoritative one); breaker trips read from
            # the live /metrics when the engine still answers
            trips = None
            try:
                trips = client.metrics().get("breaker", {}).get("trips")
            except Exception:  # noqa: BLE001 — the engine may be gone
                pass
            health = {
                "event": "serve_health", "requests": record["requests"],
                "done": record["done"], "errors": record["errors"],
                "deadline_exceeded": record["deadline_exceeded"],
                "shed": record["shed"],
                "error_rate": round(
                    (record["errors"] + record["deadline_exceeded"])
                    / max(record["requests"] - record["shed"], 1), 4),
                "shed_rate": round(
                    record["shed"] / max(record["requests"], 1), 4),
            }
            if trips is not None:
                health["breaker_trips"] = trips
            return [health]
    else:
        from videop2p_tpu.cli.common import enable_compile_cache
        from videop2p_tpu.serve import EditEngine, FaultPlan, ProgramSpec

        enable_compile_cache()
        tiny = True if args.tiny is None else args.tiny
        faults = FaultPlan.parse(args.faults) if args.faults else None
        engine = EditEngine(
            ProgramSpec(checkpoint=args.checkpoint, tiny=tiny,
                        steps=args.steps, video_len=args.video_len,
                        width=args.width),
            out_dir="loadgen_out", max_batch=args.max_batch,
            max_queue=args.max_queue,
            default_deadline_s=args.deadline_s,
            dispatch_timeout_s=args.dispatch_timeout_s,
            max_retries=args.max_retries,
            breaker_threshold=args.breaker_threshold,
            breaker_open_s=args.breaker_open_s,
            faults=faults,
        )
        engine.warm((args.prompt, args.edit_prompt),
                    batch_sizes=(min(2, args.max_batch),))
        target = _InprocTarget(engine, args.timeout_s)
        meta = {"target": "inproc", "tiny": tiny, "steps": args.steps,
                "faults": args.faults}

        def collect_extra(record, engine=engine):
            # the engine's own fault/breaker trail + reliability summary —
            # written into the loadgen ledger so ONE file gates both the
            # latency (TIMING_RULES) and the reliability (FAULT_RULES)
            return [dict(e) for e in engine.fault_log] + [
                {"event": "serve_health", **engine.health_record()}
            ]

    if args.distinct_seeds:
        # closed-loop cold traffic: unique seed per request index
        issue_lock = threading.Lock()
        counter = {"n": 0}
        base_one = target.one

        def one_with_seed(req):
            with issue_lock:
                counter["n"] += 1
                req = dict(req, seed=counter["n"])
            return base_one(req)

        target.one = one_with_seed

    try:
        record = run_loadgen(
            target, request,
            requests=args.requests, concurrency=args.concurrency,
            ledger_path=args.ledger, meta=meta,
            collect_extra=collect_extra,
        )
    finally:
        if engine is not None:
            engine.close()
    print(json.dumps(record, default=str))
    min_rate = args.min_success_rate
    if min_rate is None and args.faults:
        min_rate = 0.5  # chaos default: doomed requests expected, most survive
    if min_rate is not None:
        ok = record["success_rate"] >= min_rate
        if not ok:
            print(f"[loadgen] success_rate {record['success_rate']} < "
                  f"required {min_rate}", file=sys.stderr)
        return 0 if ok else 1
    return 1 if record["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
