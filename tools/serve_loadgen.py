"""Closed-loop load generator for the edit-serving engine and fleet.

Drives N requests at a fixed concurrency against a running engine — over
HTTP (``--url``, a ``cli/serve.py`` process OR a ``cli/router.py`` fleet;
the API is identical), fully in-process (``--inproc``, builds a
tiny/random-init engine; the CI smoke mode), or against a self-built
in-process FLEET (``--router N``: N replicas sharing one disk inversion
store behind a real HTTP router) — and writes an ``execute_timing``-
compatible run ledger: per-phase client-side latency reservoirs
(``loadgen_request`` end-to-end, ``loadgen_submit``, plus one reservoir
per tenant) flushed through the same
:class:`~videop2p_tpu.obs.timing.LatencyReservoir` machinery every other
run record uses. Two loadgen ledgers therefore diff and GATE with
``tools/obs_diff.py`` (``TIMING_RULES`` + ``FAULT_RULES``) like any bench
run:

    python tools/serve_loadgen.py --url http://host:8000 --requests 64 \
        --concurrency 8 --image data/rabbit --ledger loadgen_a.jsonl
    python tools/obs_diff.py loadgen_a.jsonl loadgen_b.jsonl

Closed loop = each worker submits its next request only after the previous
one finished — the concurrency IS the offered load, so latency percentiles
are comparable across runs without open-loop arrival modeling. Workers are
one blocked thread each (8 KiB of interpreter state + a parked socket), so
thousands of closed-loop clients fit one driver process:
``--concurrency 2000`` is 2000 live clients against the fleet.

Per-tenant workload mix (ISSUE 11): ``--tenants A:5,B:1`` tags requests
with tenant names on a deterministic smooth-weighted-round-robin cycle (no
randomness — the same flags replay the same per-request tenants), and the
summary + ledger grow per-tenant p50/p99 latency and shed/success rates —
the client-side view of the engine's per-tenant QoS accounting. Pair with
``--scheduler fair`` to exercise the deficit-round-robin lanes.

Chaos modes (ISSUE 9 + 11): ``--faults <plan>`` (``--inproc``) injects a
deterministic fault plan into the single engine;
``--replica_faults IDX:PLAN`` (``--router N``) injects into ONE replica of
the fleet — the 2-replica acceptance run takes replica 0 through an
unavailable window and requires the ROUTER to shed traffic to the healthy
replica, gated by ``--min_success_rate`` (exit 1 below it) with the
engines' ``fault``/``breaker``/``serve_health`` events and the router's
``router_health`` summary copied into the loadgen ledger for
``tools/obs_diff.py``:

    python tools/serve_loadgen.py --router 2 --tiny --requests 16 \
        --replica_faults 0:unavail@1-999 --min_success_rate 0.6

Telemetry plane (ISSUE 17): ``--collector`` runs a
``videop2p_tpu.serve.collector.FleetCollector`` scrape loop alongside
the closed loop — every replica's + the router's ``/healthz`` and
``/metrics`` polled every ``--scrape_interval_s`` into a bounded
time-series store, with burn-rate/trend/demand signals evaluated on the
same cadence (``--window_scale`` shrinks the 300s/3600s SLO windows so
short smoke runs span them). The run's ``fleet_signals`` trail and the
final ``fleet_series`` snapshot (+ ``.npz`` sidecar in ``--out_dir``)
land in the SAME loadgen ledger: ``tools/obs_diff.py`` gates them via
``SIGNAL_RULES`` and ``tools/fleet_dash.py`` renders the dashboard:

    python tools/serve_loadgen.py --router 2 --tiny --requests 16 \
        --collector --window_scale 0.02 --ledger fleet.jsonl
    python tools/fleet_dash.py fleet.jsonl

Correctness plane (ISSUE 20): ``--probes`` runs a
``videop2p_tpu.serve.prober.FleetProber`` alongside the closed loop —
the known-answer probe suite (cached replay, determinism, golden
quality, store round-trip, contract probes) fired at every replica +
the router every ``--probe_interval_s`` under the reserved low-priority
``probe`` tenant, with canary content hashes audited fleet-wide. The
``probe``/``probe_audit`` trail lands in the SAME ledger (gated by
``PROBE_RULES``, rendered by ``tools/probe_report.py``); in ``--router``
mode the router consumes the prober's verdicts and routes around
quarantined wrong-answer replicas:

    python tools/serve_loadgen.py --router 2 --tiny --requests 16 \
        --probes --collector --window_scale 0.02 --ledger fleet.jsonl
    python tools/probe_report.py fleet.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


class _HttpTarget:
    def __init__(self, url: str, timeout_s: float):
        from videop2p_tpu.serve.client import EngineClient

        self.client = EngineClient(url)
        self.timeout_s = timeout_s

    def one(self, request: Dict[str, Any],
            traceparent: Optional[str] = None) -> Dict[str, Any]:
        t0 = time.perf_counter()
        rid = self.client.submit(request, traceparent=traceparent)
        submit_s = time.perf_counter() - t0
        rec = self.client.wait(rid, timeout_s=self.timeout_s)
        rec["_submit_s"] = submit_s
        rec["_e2e_s"] = time.perf_counter() - t0
        return rec


class _InprocTarget:
    def __init__(self, engine, timeout_s: float):
        self.engine = engine
        self.timeout_s = timeout_s

    def one(self, request: Dict[str, Any],
            traceparent: Optional[str] = None) -> Dict[str, Any]:
        from videop2p_tpu.serve.engine import EditRequest

        t0 = time.perf_counter()
        rid = self.engine.submit(EditRequest.from_dict(request),
                                 traceparent=traceparent)
        submit_s = time.perf_counter() - t0
        rec = self.engine.result(rid, wait_s=self.timeout_s)
        rec["_submit_s"] = submit_s
        rec["_e2e_s"] = time.perf_counter() - t0
        return rec


def _is_shed(exc: Exception) -> bool:
    """Was this submit load-shed (429) or fast-failed unavailable (503)?
    Sheds are the backpressure layer working as designed — counted apart
    from genuine errors."""
    try:
        from videop2p_tpu.serve.faults import EngineUnavailable, QueueFull

        if isinstance(exc, (QueueFull, EngineUnavailable)):
            return True
    except ImportError:
        pass
    msg = str(exc)
    return "HTTP 429" in msg or "HTTP 503" in msg


def tenant_cycle(weights: Dict[str, int], n: int) -> List[str]:
    """Deterministic smooth-weighted-round-robin tenant assignment for
    ``n`` requests: each step every tenant gains its weight in credit, the
    richest (ties by name) is picked and pays the total weight back. The
    mix converges to the weight ratio with maximal interleave — and the
    same weights always produce the same per-request tenants."""
    if not weights:
        return ["default"] * n
    names = sorted(weights)
    total = sum(max(int(weights[t]), 1) for t in names)
    credit = {t: 0 for t in names}
    out = []
    for _ in range(n):
        for t in names:
            credit[t] += max(int(weights[t]), 1)
        pick = max(names, key=lambda t: (credit[t], t))
        credit[pick] -= total
        out.append(pick)
    return out


def parse_tenant_weights(spec: Optional[str]) -> Dict[str, int]:
    """``"A:5,B:1"`` → ``{"A": 5, "B": 1}`` (the workload-mix side of the
    tenant syntax — weights only; engine-side QoS uses serve/sched.py's
    ``parse_tenants``)."""
    if not spec:
        return {}
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        if not name:
            raise ValueError(f"bad tenant weight {part!r} — expected name:weight")
        out[name] = int(w) if w else 1
    return out


def run_loadgen(
    target,
    request: Dict[str, Any],
    *,
    requests: int,
    concurrency: int,
    ledger_path: Optional[str] = None,
    meta: Optional[Dict[str, Any]] = None,
    collect_extra=None,
    tenants: Optional[Dict[str, int]] = None,
    mutate_request=None,
    tracing: bool = False,
    slo: bool = False,
) -> Dict[str, Any]:
    """Run the closed loop; returns the summary record (also printed as one
    JSON line by :func:`main`). When ``ledger_path`` is given, the
    reservoirs flush there as ``execute_timing`` events. ``collect_extra``
    (chaos/fleet mode) is called after the loop and may return extra
    ledger events (dicts with an ``"event"`` key — the engines' ``fault``
    / ``breaker`` trail, their ``serve_health`` summaries and the router's
    ``router_health``) to write into the same ledger, making the run's
    reliability obs_diff-gateable. ``tenants`` (name → weight) tags each
    request on the deterministic :func:`tenant_cycle` and adds per-tenant
    latency/shed accounting. ``mutate_request(req, issue_index)`` is the
    per-request hook (``--distinct_seeds`` rides it).

    ``tracing`` (ISSUE 14) mints a client-side root span per request and
    forwards its traceparent to the target — the engine/router/replica
    ledgers then share the loadgen's trace ids, and the `loadgen.request`
    spans land in THIS ledger so trace_view joins the full client→fleet
    tree. ``slo`` evaluates the default objectives over the run's own
    summaries into ``slo_report`` events (obs_diff's SLO_RULES gate
    them)."""
    from videop2p_tpu.obs.timing import LatencyReservoir

    reservoirs = {
        "loadgen_request": LatencyReservoir(),
        "loadgen_submit": LatencyReservoir(),
        # the engine-reported admit→dispatch queue wait, threaded back
        # per tenant so fair-scheduler starvation is VISIBLE client-side
        # (a starved lane shows a fat queue-wait p99 with a normal
        # dispatch latency)
        "loadgen_queue_wait": LatencyReservoir(),
    }
    assignment = tenant_cycle(tenants or {}, requests) if tenants else None
    tenant_names = sorted(tenants) if tenants else []
    for t in tenant_names:
        reservoirs[f"loadgen_request_{t}"] = LatencyReservoir()
        reservoirs[f"loadgen_queue_wait_{t}"] = LatencyReservoir()
    spans: List[Dict[str, Any]] = []  # buffered; the ledger opens at the end
    lock = threading.Lock()
    counters = {"done": 0, "errors": 0, "deadline_exceeded": 0, "shed": 0,
                "store_hits": 0, "issued": 0}
    tcounters = {t: {"requests": 0, "done": 0, "errors": 0,
                     "deadline_exceeded": 0, "shed": 0}
                 for t in tenant_names}

    def worker():
        while True:
            with lock:
                if counters["issued"] >= requests:
                    return
                idx = counters["issued"]
                counters["issued"] += 1
            req = dict(request)
            tenant = None
            if assignment is not None:
                tenant = assignment[idx]
                req["tenant"] = tenant
                with lock:
                    tcounters[tenant]["requests"] += 1
            if mutate_request is not None:
                req = mutate_request(req, idx)
            tid = span_id = tp = None
            wall0 = 0
            if tracing:
                from videop2p_tpu.obs.spans import (
                    format_traceparent,
                    make_span_id,
                    make_trace_id,
                )

                tid, span_id = make_trace_id(), make_span_id()
                tp = format_traceparent(tid, span_id)
                wall0 = time.time_ns()
            try:
                rec = target.one(req, tp)
            except Exception as e:  # noqa: BLE001 — a failed request is a counter, not a crash
                kind = "shed" if _is_shed(e) else "errors"
                with lock:
                    counters[kind] += 1
                    if tenant is not None:
                        tcounters[tenant][kind] += 1
                    if tracing:
                        spans.append({
                            "trace_id": tid, "span_id": span_id,
                            "parent_id": None, "name": "loadgen.request",
                            "wall_ns": wall0, "duration_s": 0.0,
                            "status": kind, "index": idx, "tenant": tenant,
                        })
                print(f"[loadgen] request failed: {e}", file=sys.stderr)
                continue
            with lock:
                status = rec.get("status")
                if status == "done":
                    counters["done"] += 1
                    if rec.get("store_hit"):
                        counters["store_hits"] += 1
                elif status == "deadline_exceeded":
                    counters["deadline_exceeded"] += 1
                else:
                    counters["errors"] += 1
                if tenant is not None:
                    key = {"done": "done",
                           "deadline_exceeded": "deadline_exceeded"}.get(
                               status, "errors")
                    tcounters[tenant][key] += 1
            reservoirs["loadgen_request"].add(rec["_e2e_s"], rec["_e2e_s"],
                                              tid)
            reservoirs["loadgen_submit"].add(rec["_submit_s"],
                                             rec["_submit_s"], tid)
            qw = rec.get("queue_wait_s")
            if isinstance(qw, (int, float)):
                reservoirs["loadgen_queue_wait"].add(float(qw), float(qw),
                                                     tid)
            if tenant is not None:
                reservoirs[f"loadgen_request_{tenant}"].add(
                    rec["_e2e_s"], rec["_e2e_s"], tid
                )
                if isinstance(qw, (int, float)):
                    reservoirs[f"loadgen_queue_wait_{tenant}"].add(
                        float(qw), float(qw), tid
                    )
            if tracing:
                with lock:
                    spans.append({
                        "trace_id": tid, "span_id": span_id,
                        "parent_id": None, "name": "loadgen.request",
                        "wall_ns": wall0,
                        "duration_s": round(rec["_e2e_s"], 6),
                        "status": rec.get("status") or "ok",
                        "index": idx, "tenant": tenant,
                    })

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(int(concurrency), 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0

    summaries = {name: res.summary() for name, res in reservoirs.items()
                 if res.summary()}
    # sheds are correct backpressure, not failures — the success rate is
    # over the requests the engine actually accepted
    accepted = max(requests - counters["shed"], 1)
    record = {
        "requests": requests,
        "concurrency": concurrency,
        "done": counters["done"],
        "errors": counters["errors"],
        "deadline_exceeded": counters["deadline_exceeded"],
        "shed": counters["shed"],
        "store_hits": counters["store_hits"],
        "success_rate": round(counters["done"] / accepted, 4),
        "wall_s": round(wall_s, 4),
        "throughput_rps": round(counters["done"] / wall_s, 4) if wall_s else None,
        "latency": summaries.get("loadgen_request"),
    }
    if tenant_names:
        per_tenant = {}
        for t in tenant_names:
            c = tcounters[t]
            lat = summaries.get(f"loadgen_request_{t}") or {}
            qw = summaries.get(f"loadgen_queue_wait_{t}") or {}
            attempted = max(c["requests"], 1)
            per_tenant[t] = {
                **c,
                "shed_rate": round(c["shed"] / attempted, 4),
                "success_rate": round(
                    c["done"] / max(c["requests"] - c["shed"], 1), 4),
                "p50_s": lat.get("blocked_p50_s"),
                "p99_s": lat.get("blocked_p99_s"),
                # the engine-side queue wait per lane: fair-scheduler
                # starvation shows up HERE even when dispatch is healthy
                "queue_wait_p50_s": qw.get("blocked_p50_s"),
                "queue_wait_p99_s": qw.get("blocked_p99_s"),
            }
        record["tenants"] = per_tenant
    extra_events = []
    if collect_extra is not None:
        try:
            extra_events = list(collect_extra(record) or [])
        except Exception as e:  # noqa: BLE001 — chaos bookkeeping must not fail the run
            print(f"[loadgen] collect_extra failed: {e}", file=sys.stderr)
    if ledger_path:
        from videop2p_tpu.obs import RunLedger

        led = RunLedger(
            ledger_path,
            meta={"cli": "serve_loadgen", **(meta or {}),
                  "requests": requests, "concurrency": concurrency,
                  "tracing": bool(tracing)},
        )
        for name, res in reservoirs.items():
            for d, b, t in res.samples():
                led.record_execute(name, d, b, t)
        for s in spans:
            led.event("span", **s)
        for e in extra_events:
            ev = dict(e)
            led.event(ev.pop("event", "fault"), **ev)
        if slo:
            from videop2p_tpu.obs.slo import emit_slo_reports

            # the run's own summaries shaped like an extracted record:
            # availability/deadline objectives over the loop counters,
            # the served-p99 objective over the e2e reservoir
            accepted_n = max(requests - counters["shed"], 1)
            pseudo = {
                "reliability": {"serve": {
                    "requests": float(requests),
                    "deadline_exceeded": float(
                        counters["deadline_exceeded"]),
                    "error_rate": round(
                        (counters["errors"] + counters["deadline_exceeded"])
                        / accepted_n, 6),
                }},
                "timing": {"serve_request_e2e":
                           summaries.get("loadgen_request") or {}},
            }
            emit_slo_reports(led, pseudo)
        led.event("loadgen_summary", **{k: v for k, v in record.items()
                                        if k not in ("latency", "tenants")})
        led.close()  # flushes execute_timing events
        record["ledger"] = ledger_path
    return record


def _parse_replica_faults(specs: List[str]) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for spec in specs or []:
        idx, sep, plan = str(spec).partition(":")
        if not sep or not plan:
            raise ValueError(
                f"bad --replica_faults {spec!r} — expected IDX:PLAN "
                "(e.g. 0:unavail@1-999)"
            )
        out[int(idx)] = plan
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    target_group = ap.add_mutually_exclusive_group(required=True)
    target_group.add_argument("--url", type=str,
                              help="base URL of a running cli/serve.py engine "
                                   "or cli/router.py fleet")
    target_group.add_argument("--inproc", action="store_true",
                              help="build an in-process engine (tiny/"
                                   "random-init smoke mode)")
    target_group.add_argument("--router", type=int, default=None,
                              metavar="N",
                              help="build an in-process FLEET: N engine "
                                   "replicas sharing one disk inversion "
                                   "store behind a real HTTP router, and "
                                   "drive the router URL")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop clients (one blocked thread each — "
                         "thousands fit one driver)")
    ap.add_argument("--timeout_s", type=float, default=600.0)
    ap.add_argument("--image", type=str, default="data/rabbit")
    ap.add_argument("--prompt", type=str, default="a rabbit is jumping")
    ap.add_argument("--edit_prompt", type=str,
                    default="a origami rabbit is jumping")
    ap.add_argument("--distinct_seeds", action="store_true",
                    help="vary the request seed per issue index so every "
                         "request MISSES the inversion store (cold-path "
                         "load) instead of hitting after the first")
    ap.add_argument("--tenants", type=str, default=None,
                    help="per-tenant workload mix, 'A:5,B:1' weight syntax: "
                         "requests carry tenant names on a deterministic "
                         "weighted cycle; the summary/ledger grow per-tenant "
                         "p50/p99 + shed rates. Also passed as the engine's "
                         "QoS config in --inproc/--router modes")
    ap.add_argument("--ledger", type=str, default="loadgen_ledger.jsonl")
    ap.add_argument("--tracing", action="store_true",
                    help="request-scoped tracing (ISSUE 14): mint a client "
                         "root span per request, forward traceparent to "
                         "the target, and record loadgen.request spans in "
                         "the ledger; --inproc/--router engines (and the "
                         "router itself) trace server-side with the SAME "
                         "trace ids — join with tools/trace_view.py")
    ap.add_argument("--slo", action="store_true",
                    help="evaluate the default SLOs over this run's "
                         "summaries into slo_report ledger events "
                         "(obs_diff SLO_RULES gate the budget burn)")
    ap.add_argument("--collector", action="store_true",
                    help="fleet telemetry plane (ISSUE 17): run a "
                         "FleetCollector scrape loop against the target "
                         "(every replica + the router in --router mode) "
                         "for the duration of the run; its fleet_signals "
                         "evaluations and the fleet_series tsdb snapshot "
                         "(+ .npz sidecar in --out_dir) land in THIS "
                         "ledger — gate with obs_diff SIGNAL_RULES, "
                         "render with tools/fleet_dash.py")
    ap.add_argument("--incidents", type=str, default=None, metavar="DIR",
                    help="incident plane (ISSUE 18): ONE shared "
                         "IncidentManager across the whole in-process "
                         "fleet — every engine/router ledger tees into "
                         "its flight ring, breaker-open/deadline/burn-"
                         "alert/crash triggers write debounced capture "
                         "bundles under DIR, and the incident events "
                         "land in THIS ledger (obs_diff INCIDENT_RULES "
                         "gate any increase) — render bundles with "
                         "tools/incident_report.py")
    ap.add_argument("--probes", action="store_true",
                    help="correctness plane (ISSUE 20): run a FleetProber "
                         "known-answer loop against the target (every "
                         "replica + the router in --router mode) for the "
                         "duration of the run — probe verdicts and "
                         "cross-replica answer-audit divergences land in "
                         "THIS ledger (gate with obs_diff PROBE_RULES, "
                         "render with tools/probe_report.py); in --router "
                         "mode the router quarantines divergent replicas")
    ap.add_argument("--probe_interval_s", type=float, default=5.0,
                    help="prober round cadence (each round runs the full "
                         "suite — several real canary edits per target)")
    ap.add_argument("--scrape_interval_s", type=float, default=0.5,
                    help="collector scrape/evaluate cadence")
    ap.add_argument("--window_scale", type=float, default=1.0,
                    help="scale the signal windows (fast 300s / slow "
                         "3600s x this) — short smoke runs want ~0.01 so "
                         "a 30s run spans the slow window")
    ap.add_argument("--saturation_threshold", type=float, default=5.0,
                    help="queue-wait-p99 / dispatch-p50 ratio past which "
                         "the signals advise grow — tiny CPU smoke "
                         "engines legitimately run 10-50x under a closed "
                         "loop, so raise this (e.g. 100) when smoking")
    # in-process engine knobs (smoke + fleet modes)
    ap.add_argument("--tiny", action="store_true", default=None)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--video_len", type=int, default=2)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--checkpoint", type=str, default=None)
    ap.add_argument("--max_batch", type=int, default=4)
    ap.add_argument("--scheduler", type=str, default="drain",
                    choices=["drain", "continuous", "fair"],
                    help="batching policy for the in-process engine(s) "
                         "(serve/sched.py)")
    ap.add_argument("--out_dir", type=str, default="loadgen_out")
    ap.add_argument("--inv_store", type=str, default=None,
                    help="fleet mode: the shared disk inversion-store root "
                         "(default <out_dir>/inv_store)")
    # chaos mode (ISSUEs 9 + 11): deterministic fault injection
    ap.add_argument("--faults", type=str, default=None,
                    help="fault plan (serve/faults.py DSL: fail@K, "
                         "hang@K:S, unavail@A-B, corrupt:PAT) injected into "
                         "the --inproc engine; the engine's fault/breaker "
                         "events and serve_health summary land in the "
                         "loadgen ledger")
    ap.add_argument("--replica_faults", action="append", default=[],
                    metavar="IDX:PLAN",
                    help="fleet chaos (--router): inject a fault plan into "
                         "replica IDX only (repeatable) — the router must "
                         "shed to the healthy replicas; gate with "
                         "--min_success_rate")
    ap.add_argument("--min_success_rate", type=float, default=None,
                    help="exit 1 when done/(requests-shed) falls below "
                         "this; default 0.5 in chaos mode, else the legacy "
                         "errors!=0 rule")
    ap.add_argument("--deadline_s", type=float, default=None,
                    help="default per-request deadline for the in-process "
                         "engine(s)")
    ap.add_argument("--dispatch_timeout_s", type=float, default=None)
    ap.add_argument("--max_retries", type=int, default=2)
    ap.add_argument("--breaker_threshold", type=int, default=3)
    ap.add_argument("--breaker_open_s", type=float, default=1.0)
    ap.add_argument("--max_queue", type=int, default=64)
    args = ap.parse_args(argv)
    if args.faults and not args.inproc:
        ap.error("--faults injects at the engine seams — use --inproc "
                 "(fleet chaos: --router N --replica_faults IDX:PLAN; a "
                 "remote engine takes VIDEOP2P_SERVE_FAULTS / "
                 "cli/serve.py --faults instead)")
    if args.replica_faults and not args.router:
        ap.error("--replica_faults needs --router N (per-replica fleet "
                 "chaos)")
    if args.collector and args.inproc:
        ap.error("--collector scrapes HTTP surfaces — use --router N or "
                 "--url (an --inproc engine has no /metrics endpoint)")
    if args.probes and args.inproc:
        ap.error("--probes exercises the real JSON API — use --router N "
                 "or --url (an --inproc engine has no HTTP surface to "
                 "probe)")

    request = {
        "image_path": args.image,
        "prompt": args.prompt,
        "prompts": [args.prompt, args.edit_prompt],
        "save_name": "loadgen",
    }
    tenant_weights = parse_tenant_weights(args.tenants)
    engine = None
    supervisor = None
    router_server = None
    collector = None
    collect_extra = None
    scrape_targets: List[Any] = []
    chaos = bool(args.faults or args.replica_faults)

    incident_mgr = None
    if args.incidents:
        # one manager for the whole run: fleet-wide debounce (a breaker
        # flapping on two replicas is ONE incident), crash hooks for the
        # driver process, and every in-process engine ledger teeing into
        # the same flight ring
        from videop2p_tpu.obs.incident import IncidentManager

        incident_mgr = IncidentManager(args.incidents, crash_hooks=True)
        print(f"[loadgen] incident plane armed: bundles under "
              f"{args.incidents}")

    def engine_kwargs():
        return dict(
            incidents=incident_mgr,
            max_batch=args.max_batch,
            max_queue=args.max_queue,
            default_deadline_s=args.deadline_s,
            dispatch_timeout_s=args.dispatch_timeout_s,
            max_retries=args.max_retries,
            breaker_threshold=args.breaker_threshold,
            breaker_open_s=args.breaker_open_s,
            scheduler=args.scheduler,
            tenants=args.tenants,
            tracing=args.tracing,
            slo=args.slo,
        )

    if args.url:
        target = _HttpTarget(args.url, args.timeout_s)
        meta = {"target": args.url}
        scrape_targets = [("engine", args.url)]

        def collect_extra(record, client=target.client):
            # client-side reliability summary (the remote engine's own
            # ledger holds the authoritative one); breaker trips and the
            # cost plane's capacity section read from the live /metrics
            # when the engine still answers
            trips = None
            capacity = None
            try:
                m = client.metrics()
                trips = m.get("breaker", {}).get("trips")
                capacity = m.get("capacity")
            except Exception:  # noqa: BLE001 — the engine may be gone
                pass
            health = {
                "event": "serve_health", "requests": record["requests"],
                "done": record["done"], "errors": record["errors"],
                "deadline_exceeded": record["deadline_exceeded"],
                "shed": record["shed"],
                "error_rate": round(
                    (record["errors"] + record["deadline_exceeded"])
                    / max(record["requests"] - record["shed"], 1), 4),
                "shed_rate": round(
                    record["shed"] / max(record["requests"], 1), 4),
            }
            if trips is not None:
                health["breaker_trips"] = trips
            events = [health]
            if isinstance(capacity, dict):
                # ISSUE 19: the remote engine's capacity accounting lands
                # as an engine-scope chargeback row so COST_RULES gate
                # remote runs too (tenant rows stay on the engine ledger)
                events.append({"event": "cost_attribution",
                               "label": "serve", "scope": "engine",
                               "name": "serve", **capacity})
            return events
    elif args.router:
        from videop2p_tpu.cli.common import enable_compile_cache
        from videop2p_tpu.serve import (
            ProgramSpec,
            ReplicaSupervisor,
            Router,
            RouterServer,
        )

        enable_compile_cache()
        tiny = True if args.tiny is None else args.tiny
        spec = ProgramSpec(checkpoint=args.checkpoint, tiny=tiny,
                           steps=args.steps, video_len=args.video_len,
                           width=args.width)
        supervisor = ReplicaSupervisor(
            spec, args.router, out_dir=args.out_dir,
            persist_dir=args.inv_store,
            warm_prompts=(args.prompt, args.edit_prompt),
            warm_kwargs=dict(batch_sizes=(min(2, args.max_batch),)),
            engine_kwargs=engine_kwargs(),
            faults=_parse_replica_faults(args.replica_faults),
        )
        print(f"[loadgen] starting {args.router}-replica fleet "
              f"(shared store: {supervisor.persist_dir})...")
        supervisor.start()
        router_ledger = None
        if args.tracing:
            os.makedirs(args.out_dir, exist_ok=True)
            router_ledger = os.path.join(args.out_dir,
                                         "router_ledger.jsonl")
        router = Router(supervisor.urls, probe_ttl_s=0.1,
                        ledger_path=router_ledger, tracing=args.tracing,
                        incidents=incident_mgr)
        router_server = RouterServer(router).start()
        target = _HttpTarget(router_server.url, args.timeout_s)
        scrape_targets = ([(r.name, r.url) for r in supervisor.replicas]
                          + [("router", router_server.url)])
        meta = {"target": f"router[{args.router}]", "tiny": tiny,
                "steps": args.steps, "scheduler": args.scheduler,
                "replica_faults": list(args.replica_faults)}

        def collect_extra(record, supervisor=supervisor, router=router):
            # the fleet's reliability trail: every replica's fault/breaker
            # events + serve_health (labelled), plus the router's summary —
            # one ledger gates latency AND fleet reliability
            events = []
            for r in supervisor.replicas:
                events += [dict(e) for e in r.engine.fault_log]
                events.append({"event": "serve_health", "label": r.name,
                               **r.engine.health_record()})
                # ISSUE 19: per-replica chargeback rows, labelled so the
                # cost section keeps replicas distinct ("r0:tenant:A")
                events += [{"event": "cost_attribution", "label": r.name,
                            **row} for row in r.engine.cost_records()]
            record["router"] = router.health_record()
            events.append({"event": "router_health", **record["router"]})
            return events
    else:
        from videop2p_tpu.cli.common import enable_compile_cache
        from videop2p_tpu.serve import EditEngine, FaultPlan, ProgramSpec

        enable_compile_cache()
        tiny = True if args.tiny is None else args.tiny
        faults = FaultPlan.parse(args.faults) if args.faults else None
        engine = EditEngine(
            ProgramSpec(checkpoint=args.checkpoint, tiny=tiny,
                        steps=args.steps, video_len=args.video_len,
                        width=args.width),
            out_dir=args.out_dir,
            faults=faults,
            **engine_kwargs(),
        )
        engine.warm((args.prompt, args.edit_prompt),
                    batch_sizes=(min(2, args.max_batch),))
        target = _InprocTarget(engine, args.timeout_s)
        meta = {"target": "inproc", "tiny": tiny, "steps": args.steps,
                "scheduler": args.scheduler, "faults": args.faults}

        def collect_extra(record, engine=engine):
            # the engine's own fault/breaker trail + reliability summary —
            # written into the loadgen ledger so ONE file gates both the
            # latency (TIMING_RULES) and the reliability (FAULT_RULES) —
            # plus the cost plane's chargeback rows (COST_RULES, ISSUE 19)
            return [dict(e) for e in engine.fault_log] + [
                {"event": "serve_health", **engine.health_record()}
            ] + [{"event": "cost_attribution", "label": "serve", **row}
                 for row in engine.cost_records()]

    if args.collector:
        from videop2p_tpu.serve.collector import FleetCollector

        collector = FleetCollector(
            scrape_targets,
            interval_s=args.scrape_interval_s,
            window_scale=args.window_scale,
            signal_kwargs=dict(
                saturation_threshold=args.saturation_threshold),
            incidents=incident_mgr,
        )
        collector.start()
        meta["collector"] = {"targets": [n for n, _ in scrape_targets],
                             "scrape_interval_s": args.scrape_interval_s,
                             "window_scale": args.window_scale,
                             "saturation_threshold":
                                 args.saturation_threshold}
        print(f"[loadgen] collector scraping {len(scrape_targets)} "
              f"target(s) every {args.scrape_interval_s}s "
              f"(window_scale {args.window_scale})")
        base_collect = collect_extra

        def collect_extra(record, base=base_collect, collector=collector):
            # stop the scrape loop, drain its buffered fleet_signals
            # evaluations + the fleet_series tsdb snapshot into THIS
            # ledger (one file gates latency, reliability AND signals),
            # and fold the signal roll-up into the summary record
            events = list(base(record) or []) if base is not None else []
            collector.stop(final_evaluate=True)
            events += [{"event": "fleet_signals", **r}
                       for r in collector.history]
            os.makedirs(args.out_dir, exist_ok=True)
            snap = collector.snapshot(
                label="fleet",
                sidecar_path=os.path.join(args.out_dir,
                                          "fleet_series.npz"))
            events.append({"event": "fleet_series", **snap})
            record["signals"] = {**collector.signals.summary(),
                                 **collector.stats()}
            return events

    prober = None
    if args.probes:
        from videop2p_tpu.serve.prober import FleetProber

        # share the collector's tsdb + signal engine when both planes are
        # on: probe_success/probe_latency series land next to the scraped
        # gauges and the fleet_signals evaluations carry the probe burn
        prober = FleetProber(
            scrape_targets, dict(request),
            interval_s=args.probe_interval_s,
            http_timeout_s=args.timeout_s,
            wait_s=args.timeout_s,
            tsdb=collector.tsdb if collector is not None else None,
            signals=collector.signals if collector is not None else None,
            incidents=incident_mgr,
        )
        if args.router:
            # close the loop: the router consumes the prober's verdicts
            # and routes around quarantined wrong-answer replicas
            router.set_probe_status_provider(prober.probe_status)
        prober.start()
        meta["probes"] = {"targets": [n for n, _ in scrape_targets],
                          "probe_interval_s": args.probe_interval_s}
        print(f"[loadgen] prober running the known-answer suite against "
              f"{len(scrape_targets)} target(s) every "
              f"{args.probe_interval_s}s")
        base_probe = collect_extra

        def collect_extra(record, base=base_probe, prober=prober):
            # stop the probing loop (one final round if none completed)
            # and drain its probe/probe_audit trail into THIS ledger —
            # the same file then gates correctness via PROBE_RULES
            events = list(base(record) or []) if base is not None else []
            prober.stop(final_round=True)
            events += [{"event": kind, **rec}
                       for kind, rec in prober.history]
            record["probes"] = prober.stats()
            return events

    if incident_mgr is not None:
        base_inc = collect_extra

        def collect_extra(record, base=base_inc, mgr=incident_mgr):
            # last wrapper: runs AFTER the collector drain, so a burn
            # alert fired by the final evaluate still lands here — the
            # incident events go into THIS ledger (INCIDENT_RULES teeth)
            # and the summary names every bundle
            events = list(base(record) or []) if base is not None else []
            events += mgr.records()
            record["incidents"] = mgr.summary()
            return events

    mutate_request = None
    if args.distinct_seeds:
        # closed-loop cold traffic: unique seed per request issue index
        def mutate_request(req, idx):
            return dict(req, seed=idx + 1)

    try:
        record = run_loadgen(
            target, request,
            requests=args.requests, concurrency=args.concurrency,
            ledger_path=args.ledger, meta=meta,
            collect_extra=collect_extra,
            tenants=tenant_weights or None,
            mutate_request=mutate_request,
            tracing=args.tracing,
            slo=args.slo,
        )
    finally:
        if prober is not None:
            prober.stop(final_round=False)  # no-op when drained
        if collector is not None:
            collector.stop(final_evaluate=False)  # no-op when drained
        if router_server is not None:
            router_server.close()
        if supervisor is not None:
            supervisor.stop()
        if engine is not None:
            engine.close()
        if incident_mgr is not None:
            incident_mgr.close()
    print(json.dumps(record, default=str))
    min_rate = args.min_success_rate
    if min_rate is None and chaos:
        min_rate = 0.5  # chaos default: doomed requests expected, most survive
    if min_rate is not None:
        ok = record["success_rate"] >= min_rate
        if not ok:
            print(f"[loadgen] success_rate {record['success_rate']} < "
                  f"required {min_rate}", file=sys.stderr)
        return 0 if ok else 1
    return 1 if record["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
