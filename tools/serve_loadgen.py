"""Closed-loop load generator for the edit-serving engine.

Drives N requests at a fixed concurrency against a running engine — over
HTTP (``--url``, a ``cli/serve.py`` process) or fully in-process
(``--inproc``, builds a tiny/random-init engine; the CI smoke mode) — and
writes an ``execute_timing``-compatible run ledger: per-phase client-side
latency reservoirs (``loadgen_request`` end-to-end, ``loadgen_submit``)
flushed through the same :class:`~videop2p_tpu.obs.timing.LatencyReservoir`
machinery every other run record uses. Two loadgen ledgers therefore diff
and GATE with ``tools/obs_diff.py`` (``TIMING_RULES``) like any bench run:

    python tools/serve_loadgen.py --url http://host:8000 --requests 64 \
        --concurrency 8 --image data/rabbit --ledger loadgen_a.jsonl
    python tools/obs_diff.py loadgen_a.jsonl loadgen_b.jsonl

Closed loop = each worker submits its next request only after the previous
one finished — the concurrency IS the offered load, so latency percentiles
are comparable across runs without open-loop arrival modeling.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


class _HttpTarget:
    def __init__(self, url: str, timeout_s: float):
        from videop2p_tpu.serve.client import EngineClient

        self.client = EngineClient(url)
        self.timeout_s = timeout_s

    def one(self, request: Dict[str, Any]) -> Dict[str, Any]:
        t0 = time.perf_counter()
        rid = self.client.submit(request)
        submit_s = time.perf_counter() - t0
        rec = self.client.wait(rid, timeout_s=self.timeout_s)
        rec["_submit_s"] = submit_s
        rec["_e2e_s"] = time.perf_counter() - t0
        return rec


class _InprocTarget:
    def __init__(self, engine, timeout_s: float):
        self.engine = engine
        self.timeout_s = timeout_s

    def one(self, request: Dict[str, Any]) -> Dict[str, Any]:
        from videop2p_tpu.serve.engine import EditRequest

        t0 = time.perf_counter()
        rid = self.engine.submit(EditRequest.from_dict(request))
        submit_s = time.perf_counter() - t0
        rec = self.engine.result(rid, wait_s=self.timeout_s)
        rec["_submit_s"] = submit_s
        rec["_e2e_s"] = time.perf_counter() - t0
        return rec


def run_loadgen(
    target,
    request: Dict[str, Any],
    *,
    requests: int,
    concurrency: int,
    ledger_path: Optional[str] = None,
    meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Run the closed loop; returns the summary record (also printed as one
    JSON line by :func:`main`). When ``ledger_path`` is given, the
    reservoirs flush there as ``execute_timing`` events."""
    from videop2p_tpu.obs.timing import LatencyReservoir

    reservoirs = {
        "loadgen_request": LatencyReservoir(),
        "loadgen_submit": LatencyReservoir(),
    }
    lock = threading.Lock()
    counters = {"done": 0, "errors": 0, "store_hits": 0, "issued": 0}

    def worker():
        while True:
            with lock:
                if counters["issued"] >= requests:
                    return
                counters["issued"] += 1
            try:
                rec = target.one(dict(request))
            except Exception as e:  # noqa: BLE001 — a failed request is a counter, not a crash
                with lock:
                    counters["errors"] += 1
                print(f"[loadgen] request failed: {e}", file=sys.stderr)
                continue
            with lock:
                if rec.get("status") == "done":
                    counters["done"] += 1
                    if rec.get("store_hit"):
                        counters["store_hits"] += 1
                else:
                    counters["errors"] += 1
            reservoirs["loadgen_request"].add(rec["_e2e_s"], rec["_e2e_s"])
            reservoirs["loadgen_submit"].add(rec["_submit_s"], rec["_submit_s"])

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(int(concurrency), 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0

    summaries = {name: res.summary() for name, res in reservoirs.items()
                 if res.summary()}
    record = {
        "requests": requests,
        "concurrency": concurrency,
        "done": counters["done"],
        "errors": counters["errors"],
        "store_hits": counters["store_hits"],
        "wall_s": round(wall_s, 4),
        "throughput_rps": round(counters["done"] / wall_s, 4) if wall_s else None,
        "latency": summaries.get("loadgen_request"),
    }
    if ledger_path:
        from videop2p_tpu.obs import RunLedger

        led = RunLedger(
            ledger_path,
            meta={"cli": "serve_loadgen", **(meta or {}),
                  "requests": requests, "concurrency": concurrency},
        )
        for name, res in reservoirs.items():
            for d, b in res.samples():
                led.record_execute(name, d, b)
        led.event("loadgen_summary", **{k: v for k, v in record.items()
                                        if k != "latency"})
        led.close()  # flushes execute_timing events
        record["ledger"] = ledger_path
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    target_group = ap.add_mutually_exclusive_group(required=True)
    target_group.add_argument("--url", type=str,
                              help="base URL of a running cli/serve.py engine")
    target_group.add_argument("--inproc", action="store_true",
                              help="build an in-process engine (tiny/"
                                   "random-init smoke mode)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--timeout_s", type=float, default=600.0)
    ap.add_argument("--image", type=str, default="data/rabbit")
    ap.add_argument("--prompt", type=str, default="a rabbit is jumping")
    ap.add_argument("--edit_prompt", type=str,
                    default="a origami rabbit is jumping")
    ap.add_argument("--distinct_seeds", action="store_true",
                    help="vary the request seed per issue index so every "
                         "request MISSES the inversion store (cold-path "
                         "load) instead of hitting after the first")
    ap.add_argument("--ledger", type=str, default="loadgen_ledger.jsonl")
    # in-process engine knobs (smoke mode)
    ap.add_argument("--tiny", action="store_true", default=None)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--video_len", type=int, default=2)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--checkpoint", type=str, default=None)
    ap.add_argument("--max_batch", type=int, default=4)
    args = ap.parse_args(argv)

    request = {
        "image_path": args.image,
        "prompt": args.prompt,
        "prompts": [args.prompt, args.edit_prompt],
        "save_name": "loadgen",
    }
    engine = None
    if args.url:
        target = _HttpTarget(args.url, args.timeout_s)
        meta = {"target": args.url}
    else:
        from videop2p_tpu.cli.common import enable_compile_cache
        from videop2p_tpu.serve import EditEngine, ProgramSpec

        enable_compile_cache()
        tiny = True if args.tiny is None else args.tiny
        engine = EditEngine(
            ProgramSpec(checkpoint=args.checkpoint, tiny=tiny,
                        steps=args.steps, video_len=args.video_len,
                        width=args.width),
            out_dir="loadgen_out", max_batch=args.max_batch,
        )
        engine.warm((args.prompt, args.edit_prompt),
                    batch_sizes=(min(2, args.max_batch),))
        target = _InprocTarget(engine, args.timeout_s)
        meta = {"target": "inproc", "tiny": tiny, "steps": args.steps}

    if args.distinct_seeds:
        # closed-loop cold traffic: unique seed per request index
        issue_lock = threading.Lock()
        counter = {"n": 0}
        base_one = target.one

        def one_with_seed(req):
            with issue_lock:
                counter["n"] += 1
                req = dict(req, seed=counter["n"])
            return base_one(req)

        target.one = one_with_seed

    try:
        record = run_loadgen(
            target, request,
            requests=args.requests, concurrency=args.concurrency,
            ledger_path=args.ledger, meta=meta,
        )
    finally:
        if engine is not None:
            engine.close()
    print(json.dumps(record, default=str))
    return 1 if record["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
