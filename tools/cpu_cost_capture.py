"""Capture XLA cost/memory analyses of the bench programs ON CPU.

Usage:  python tools/cpu_cost_capture.py [--frames 8] [--steps 50] [--tiny]
            [--programs invert_captured,edit_cached,e2e_cached]
            [--frame_counts 8,32,64] [--shards 8] [--ledger PATH]

Besides the UNet pipeline programs, the tool builds the DISTRIBUTED unit
programs (ISSUE 10): ``ring_unit_{serial,overlap,bidir}_f<F>`` — the
standalone ring-attention pass at ``F`` frames over ``--shards`` virtual
devices, whose unrolled rotation loop makes the static collective-permute
counts TRUE per-pass counts (serial 2n / overlap 2(n−1) / bidir 4(n−1) at
half payload) — and ``tp_unit_{gspmd,scatter}`` — the Megatron
row-parallel output projection, declarative all-reduce vs the explicit
``psum_scatter`` seam. Their records merge the comm accounting
(``obs/comm.py`` collective counts/bytes) into the cost analysis, so
per-frame-count comm+flop evidence lands in ``bench_details.json`` even
on ``backend_unavailable`` rounds (``bench.record_frame_scaling``).

The PER-CALL cost units (ISSUE 15): ``unet_unit_{fp,w8,w8a8}`` — one UNet
forward at the cached edit's 2-stream batch with full-precision, int8
weight-quantized, and weight+activation-quantized parameters (the w8 tree
comes from ``jax.eval_shape`` over the real ``quantize_unet_params``
converter, so the 1-byte weights ARE the analyzed program's inputs and the
argument-bytes delta is the weight-footprint claim) — and
``reuse_unit_<K>`` — one straight-line DeepCache block (a capture forward
+ K−1 shallow forwards, loop-free so the static flop count is the true
K-step count; a ``lax.cond``'s static analysis would count BOTH branches).
``bench.per_call_cost_records`` turns these into the quantization/reuse
evidence rows.

The STUDENT cost units (ISSUE 16): ``distill_unit_fp`` — one few-step
student forward (the UNet forward plus the consistency-distilled
time-conditioning head, ``train/distill.apply_time_head``), whose flop
delta over ``unet_unit_fp`` IS the head's overhead claim — and
``distill_unit_<N>`` — N loop-free student forwards (each step with its
own abstract latent/timestep, same CSE hazard as the reuse units), the
true N-step student program a ``student:N+...`` frontier row runs. Their
ratios against the teacher units land in ``bench_details.json`` every
round, ``backend_unavailable`` included.

Builds the bench's headline programs (the captured inversion, the cached
2-stream edit, and the fused e2e — the same pipeline calls
``bench.build_fast_edit_working_point`` jits) against ABSTRACT inputs
(``jax.eval_shape`` parameters — nothing is initialized or executed),
compiles them on the CPU backend, and prints one JSON line per program:
``{"program": ..., "flops": ..., "temp_bytes": ..., "peak_hbm_bytes": ...,
"hlo_fingerprint": ..., ...}`` (obs/introspect.py's record, plus the
working-point config).

This is bench.py's backend-down fallback (VERDICT r5 "What's missing" #1:
a dead TPU left the round with ``value: null`` and nothing else): XLA's
analyses are deterministic and backend-compile on CPU needs no healthy
accelerator, so FLOPs / bytes-accessed / temp-HBM per program can be
recorded EVERY round. Lines flush as each program completes, so a caller's
timeout keeps whatever finished. ``--tiny`` swaps in the tiny UNet config
(seconds, used by the tests); ``--ledger`` additionally appends the
records as ``program_analysis`` events to a run-ledger JSONL.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import jax  # noqa: E402

# the env-var route loses to this image's sitecustomize (it hard-sets
# jax_platforms via jax.config) — only a later config update actually
# selects CPU (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from videop2p_tpu.cli.common import enable_compile_cache  # noqa: E402

# binary boundary: persist the (CPU) compiles so a re-run after a caller
# timeout resumes warm instead of repaying minutes of XLA compile
enable_compile_cache()


def build_abstract_programs(frames: int, steps: int, tiny: bool,
                            reuse_ks=(), distill_ns=()):
    """(name → (jitted, abstract_args)) for the bench working point, with
    every array an eval_shape/ShapeDtypeStruct — no device execution.

    ``reuse_ks``: extra ``reuse_unit_<K>`` straight-line DeepCache programs
    to build (one capture forward + K−1 shallow forwards, loop-free — the
    only form whose STATIC cost counts are true per-K-step counts, since
    ``cost_analysis`` counts a ``lax.cond``'s BOTH branches and a scan body
    once).

    ``distill_ns``: extra ``distill_unit_<N>`` straight-line few-step
    student programs (N UNet-forward + time-head steps, loop-free with
    per-step abstract inputs for the same CSE reason)."""
    from videop2p_tpu.control import make_controller
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import (
        cached_fast_edit,
        ddim_inversion_captured,
        edit_sample,
        make_unet_fn,
    )
    from videop2p_tpu.pipelines.cached import capture_windows
    from videop2p_tpu.utils.tokenizers import WordTokenizer

    # the bench's model configuration, minus accelerator-only kernels: the
    # fused Pallas GroupNorm / frame-attention cannot lower for CPU, and
    # the XLA paths compute the same math (cost analysis differs only by
    # the kernel's internal schedule, which CPU could not predict anyway)
    if tiny:
        cfg = UNet3DConfig.tiny()
        lat = cfg.sample_size
        ctx_dim = cfg.cross_attention_dim
    else:
        cfg = UNet3DConfig.sd15(frame_attention="chunked", group_norm="xla")
        lat, ctx_dim = 64, 768
    model = UNet3DConditionModel(config=cfg, dtype=jnp.bfloat16)
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()

    x0 = jax.ShapeDtypeStruct((1, frames, lat, lat, 4), jnp.bfloat16)
    cond = jax.ShapeDtypeStruct((2, 77, ctx_dim), jnp.bfloat16)
    cond_src = jax.ShapeDtypeStruct((1, 77, ctx_dim), jnp.bfloat16)
    uncond = jax.ShapeDtypeStruct((77, ctx_dim), jnp.bfloat16)
    params = jax.eval_shape(
        model.init, jax.random.key(0),
        jax.ShapeDtypeStruct((1, 2, lat, lat, 4), jnp.bfloat16),
        jax.ShapeDtypeStruct((), jnp.int32), cond_src,
    )

    # the bench's controller working point (refine + reweight + LocalBlend)
    ctx = make_controller(
        ["a rabbit is jumping on the grass",
         "a origami rabbit is jumping on the grass"],
        WordTokenizer(),
        num_steps=steps,
        is_replace_controller=False,
        cross_replace_steps=0.2,
        self_replace_steps=0.5,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )
    cross_len, self_window = capture_windows(ctx, steps)

    invert_captured = jax.jit(
        lambda p, x, c: ddim_inversion_captured(
            fn, p, sched, x, c, num_inference_steps=steps,
            cross_len=cross_len, self_window=self_window, capture_blend=True,
        )
    )
    traj_sds, cached_sds = jax.eval_shape(
        invert_captured, params, x0, cond_src
    )
    edit_cached = jax.jit(
        lambda p, xt, c2, u, cch: edit_sample(
            fn, p, sched, xt, c2, u,
            num_inference_steps=steps, ctx=ctx, source_uses_cfg=False,
            cached_source=cch,
        )
    )
    e2e_cached = jax.jit(
        lambda p, x, c1, c2, u: cached_fast_edit(
            fn, p, sched, x, c1, c2, u, ctx,
            num_inference_steps=steps,
            cross_len=cross_len, self_window=self_window,
        )[1]
    )
    xt_sds = jax.ShapeDtypeStruct(x0.shape, x0.dtype)

    # straight-line null-text UNIT programs (bench.null_text_flop_records):
    # one UNet forward and one inner Adam iteration (loss forward + backward
    # + update). NO loops — XLA's static cost_analysis counts scan/while
    # bodies once, so only loop-free programs have static counts equal to
    # their true flops; the per-mode totals (optimize / amortized / hybrid)
    # follow analytically from these units and the disclosed loop structure.
    # The grad program uses the SAME per-block remat the real null-text
    # optimization runs with (its recompute flops are part of the real cost).
    import optax

    if tiny:
        cfg_r = type(cfg)(**{**cfg.__dict__, "gradient_checkpointing": True})
    else:
        cfg_r = UNet3DConfig.sd15(frame_attention="chunked", group_norm="xla",
                                  gradient_checkpointing=True)
    fn_r = make_unet_fn(UNet3DConditionModel(config=cfg_r, dtype=jnp.bfloat16))

    lat_f32 = jax.ShapeDtypeStruct((1, frames, lat, lat, 4), jnp.float32)
    t_sds = jax.ShapeDtypeStruct((), jnp.int32)
    u_sds = jax.ShapeDtypeStruct((1, 77, ctx_dim), jnp.float32)
    adam = optax.adam(1.0)

    def unit_fwd(p, x, t, text):
        eps, _ = fn_r(p, x, t, text, None)
        return eps.astype(jnp.float32)

    def unit_inner(p, u, lat_cur, t, eps_cond, latent_prev):
        opt_state = adam.init(u)

        def loss_fn(u_):
            eps_u, _ = fn_r(p, lat_cur, t, u_, None)
            eps = eps_u.astype(jnp.float32) + 7.5 * (
                eps_cond - eps_u.astype(jnp.float32)
            )
            prev_rec = sched.prev_step(eps, t, lat_cur, steps)
            return jnp.mean((prev_rec - latent_prev) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(u)
        updates, opt_state = adam.update(grads, opt_state, u)
        return optax.apply_updates(u, updates), loss

    # per-call cost UNIT programs (ISSUE 15, bench.per_call_cost_records):
    # ONE UNet forward at the cached edit's batch geometry (2 streams:
    # edit uncond + edit cond) in each quantization mode. The quantized
    # trees come from jax.eval_shape over the REAL load-time converter
    # (models/convert.quantize_unet_params), so the analyzed programs take
    # the actual 1-byte weight tensors as inputs — argument_bytes IS the
    # weight-footprint evidence.
    from videop2p_tpu.models.quant import fake_quant_act
    from videop2p_tpu.models.convert import quantize_unet_params

    xt_unit = jax.ShapeDtypeStruct((2, frames, lat, lat, 4), jnp.bfloat16)
    params_w8 = jax.eval_shape(
        lambda p: quantize_unet_params(p, mode="w8"), params
    )
    model_a8 = UNet3DConditionModel(config=cfg, dtype=jnp.bfloat16,
                                    act_quant_fn=fake_quant_act)
    fn_a8 = make_unet_fn(model_a8)

    def unet_unit(p, x, t, text):
        eps, _ = fn(p, x, t, text, None)
        return eps

    def unet_unit_a8(p, x, t, text):
        eps, _ = fn_a8(p, x, t, text, None)
        return eps

    t_unit = jax.ShapeDtypeStruct((), jnp.int32)
    programs = {
        "invert_captured": (invert_captured, (params, x0, cond_src)),
        "edit_cached": (edit_cached, (params, xt_sds, cond, uncond, cached_sds)),
        "e2e_cached": (e2e_cached, (params, x0, cond_src, cond, uncond)),
        "null_text_unit_fwd": (
            jax.jit(unit_fwd), (params, lat_f32, t_sds, u_sds)
        ),
        "null_text_unit_inner": (
            jax.jit(unit_inner),
            (params, u_sds, lat_f32, t_sds, lat_f32, lat_f32),
        ),
        "unet_unit_fp": (jax.jit(unet_unit), (params, xt_unit, t_unit, cond)),
        "unet_unit_w8": (
            jax.jit(unet_unit), (params_w8, xt_unit, t_unit, cond)
        ),
        "unet_unit_w8a8": (
            jax.jit(unet_unit_a8), (params_w8, xt_unit, t_unit, cond)
        ),
    }

    # straight-line DeepCache blocks: one full forward CAPTURING the deep
    # feature (the final up block's input) + K−1 SHALLOW forwards reusing
    # it — exactly what reuse_schedule="uniform:K" runs per K-step window
    # inside the fused edit scan, unrolled here so the static flop count
    # is the true K-step count
    # each step gets its OWN abstract latent and timestep (as the real
    # scan does): with a shared x the shallow forward is an exact
    # subcomputation of the capture forward and XLA CSE deletes it,
    # zeroing the count the unit exists to measure
    def make_reuse_unit(k):
        def reuse_unit(p, xs, ts, text):
            (eps, deep), _ = fn(p, xs[0], ts[0], text, None,
                                deep_mode="capture")
            acc = eps
            for i in range(1, k):
                eps_s, _ = fn(p, xs[i], ts[i], text, None,
                              deep_mode="shallow", deep_feature=deep)
                acc = acc + eps_s
            return acc
        return jax.jit(reuse_unit)

    for k in sorted(set(int(k) for k in reuse_ks)):
        if k < 1:
            raise ValueError(f"reuse_unit K must be >= 1, got {k}")
        xs_unit = jax.ShapeDtypeStruct((k,) + xt_unit.shape, jnp.bfloat16)
        ts_unit = jax.ShapeDtypeStruct((k,), jnp.int32)
        programs[f"reuse_unit_{k}"] = (
            make_reuse_unit(k), (params, xs_unit, ts_unit, cond)
        )

    # few-step STUDENT units (ISSUE 16, bench.per_call_cost_records): the
    # student is the same UNet plus the distilled time-conditioning head
    # on ε, so one student step = unet_unit_fp + apply_time_head — the
    # fp-vs-distill flop delta is the head-overhead claim, and the N-step
    # unit (loop-free, per-step abstract inputs like the reuse units:
    # shared inputs would let XLA CSE collapse identical forwards) is the
    # true program a student:N frontier row runs
    from videop2p_tpu.train.distill import apply_time_head, init_time_head

    head = jax.eval_shape(lambda k: init_time_head(k, cfg),
                          jax.random.key(0))

    def distill_unit_fp(p, h, x, t, text):
        eps, _ = fn(p, x, t, text, None)
        return apply_time_head(h, eps, t)

    programs["distill_unit_fp"] = (
        jax.jit(distill_unit_fp), (params, head, xt_unit, t_unit, cond)
    )

    def make_distill_unit(n):
        def distill_unit(p, h, xs, ts, text):
            acc = None
            for i in range(n):
                eps, _ = fn(p, xs[i], ts[i], text, None)
                eps = apply_time_head(h, eps, ts[i])
                acc = eps if acc is None else acc + eps
            return acc
        return jax.jit(distill_unit)

    for n in sorted(set(int(n) for n in distill_ns)):
        if n < 1:
            raise ValueError(f"distill_unit N must be >= 1, got {n}")
        xs_unit = jax.ShapeDtypeStruct((n,) + xt_unit.shape, jnp.bfloat16)
        ts_unit = jax.ShapeDtypeStruct((n,), jnp.int32)
        programs[f"distill_unit_{n}"] = (
            make_distill_unit(n), (params, head, xs_unit, ts_unit, cond)
        )
    return programs


def unit_program_records(wanted: List[str], shards: int):
    """Build + analyze the requested ring/tp unit programs (names
    ``ring_unit_<variant>_f<F>`` / ``tp_unit_<gspmd|scatter>``) on a
    ``shards``-wide virtual mesh. Returns ``{name: record}`` with the
    comm accounting merged in; unknown unit names raise ValueError."""
    from videop2p_tpu.parallel import make_mesh

    import __graft_entry__ as graft

    ring_mesh = tp_mesh = None
    ring_cache: dict = {}
    tp_cache: dict = {}
    out = {}
    for name in wanted:
        if name.startswith("ring_unit_"):
            rest = name[len("ring_unit_"):]
            variant, _, fpart = rest.rpartition("_f")
            if not variant or not fpart.isdigit():
                raise ValueError(f"bad ring unit name {name!r} "
                                 "(want ring_unit_<variant>_f<frames>)")
            frames = int(fpart)
            if frames % shards:
                raise ValueError(f"{name!r}: {shards} shards cannot divide "
                                 f"{frames} frames")
            if ring_mesh is None:
                ring_mesh = make_mesh((1, shards, 1),
                                      devices=jax.devices()[:shards])
            if frames not in ring_cache:
                ring_cache[frames] = graft._ring_unit_records(ring_mesh, frames)
            if variant not in ring_cache[frames]:
                raise ValueError(f"unknown ring variant in {name!r}")
            out[name] = dict(ring_cache[frames][variant], shards=shards)
        elif name.startswith("tp_unit_"):
            variant = name[len("tp_unit_"):]
            if tp_mesh is None:
                tp_mesh = make_mesh((1, 1, shards),
                                    devices=jax.devices()[:shards])
            if not tp_cache:
                tp_cache = graft._tp_unit_records(tp_mesh)
            if variant not in tp_cache:
                raise ValueError(f"unknown tp unit {name!r} "
                                 f"(have {sorted(tp_cache)})")
            out[name] = dict(tp_cache[variant], shards=shards)
    return out


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(prog="cpu_cost_capture.py",
                                     description=__doc__)
    parser.add_argument("--frames", type=int, default=8)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--tiny", action="store_true",
                        help="tiny UNet config (fast; used by tests)")
    parser.add_argument("--programs", type=str,
                        default="invert_captured,edit_cached,e2e_cached")
    parser.add_argument("--shards", type=int, default=8,
                        help="virtual device count for the ring/tp unit "
                             "programs")
    parser.add_argument("--ledger", type=str, default=None,
                        help="also append program_analysis events to this "
                             "run-ledger JSONL")
    args = parser.parse_args(argv[1:])

    from videop2p_tpu.obs.introspect import analyze_jitted

    wanted = [p.strip() for p in args.programs.split(",") if p.strip()]
    unit_wanted = [p for p in wanted
                   if p.startswith(("ring_unit_", "tp_unit_"))]
    if unit_wanted:
        # the unit programs shard over a virtual CPU mesh; the flag only
        # takes effect because no backend has initialized yet (this tool
        # always runs as a fresh subprocess)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.shards}"
            ).strip()

    pipeline_wanted = [p for p in wanted if p not in unit_wanted]
    reuse_ks = []
    distill_ns = []
    for p in pipeline_wanted:
        if p.startswith("reuse_unit_"):
            kpart = p[len("reuse_unit_"):]
            if not kpart.isdigit() or int(kpart) < 1:
                print(f"cpu_cost_capture: bad reuse unit name {p!r} "
                      "(want reuse_unit_<K>, K >= 1)", file=sys.stderr)
                return 2
            reuse_ks.append(int(kpart))
        elif p.startswith("distill_unit_") and p != "distill_unit_fp":
            npart = p[len("distill_unit_"):]
            if not npart.isdigit() or int(npart) < 1:
                print(f"cpu_cost_capture: bad distill unit name {p!r} "
                      "(want distill_unit_fp or distill_unit_<N>, N >= 1)",
                      file=sys.stderr)
                return 2
            distill_ns.append(int(npart))
    programs = build_abstract_programs(args.frames, args.steps, args.tiny,
                                       reuse_ks=reuse_ks,
                                       distill_ns=distill_ns)
    unknown = [p for p in pipeline_wanted if p not in programs]
    if unknown:
        print(f"cpu_cost_capture: unknown programs {unknown} "
              f"(have {sorted(programs)} + reuse_unit_<K> + "
              f"distill_unit_<N> + "
              f"ring_unit_<variant>_f<F> + tp_unit_<gspmd|scatter>)",
              file=sys.stderr)
        return 2
    try:
        unit_records = unit_program_records(unit_wanted, args.shards)
    except ValueError as e:
        print(f"cpu_cost_capture: {e}", file=sys.stderr)
        return 2

    ledger = None
    if args.ledger:
        from videop2p_tpu.obs.ledger import RunLedger

        ledger = RunLedger(args.ledger, meta={"tool": "cpu_cost_capture",
                                              "frames": args.frames,
                                              "steps": args.steps}).activate()
    rc = 0
    for name in wanted:
        if name in unit_records:
            rec = unit_records[name]
        else:
            jitted, abstract_args = programs[name]
            rec = analyze_jitted(jitted, *abstract_args)
        if rec is None:
            print(f"cpu_cost_capture: analysis failed for {name}",
                  file=sys.stderr)
            rc = 1
            continue
        rec = {"program": name, "backend": "cpu", "frames": args.frames,
               "steps": args.steps, **rec}
        print(json.dumps(rec), flush=True)  # line per program: timeout-safe
        if ledger is not None:
            ledger.program_analysis(name, {k: v for k, v in rec.items()
                                           if k != "program"})
    if ledger is not None:
        ledger.close()
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
