"""Op-family breakdown of the jitted fast-edit phases on the real chip.

Runs the 50-step inversion + controlled edit (the exact bench working point —
shared via ``bench.build_fast_edit_working_point``) under ``jax.profiler.trace``
and sums per-op device time from the raw ``*.xplane.pb`` (the tensorboard-
plugin converter is broken in this image; parse the proto directly with the
pure-Python protobuf implementation).

Usage:  PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python python tools/profile_xplane.py
"""

from __future__ import annotations

import collections
import glob
import os
import re
import sys
import tempfile

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def iter_device_events(trace_dir: str, line_name: str = "XLA Ops"):
    """Yield ``(op_name, duration_ps)`` for every ``line_name`` line event on
    a device plane of every xplane proto under ``trace_dir``."""
    for name, _, dur in iter_device_event_windows(trace_dir, line_name):
        yield name, dur


def iter_device_event_windows(trace_dir: str, line_name: str = "XLA Ops"):
    """Yield ``(op_name, start_ps, duration_ps)`` for every ``line_name``
    line event on a device plane, with starts on the trace's absolute
    timeline (line timestamp + event offset)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    ):
        xspace = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xspace.ParseFromString(f.read())
        for plane in xspace.planes:
            if "TPU" not in plane.name and "/device" not in plane.name.lower():
                continue
            ev_names = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                if line.name != line_name:
                    continue
                base_ps = line.timestamp_ns * 1000
                for ev in line.events:
                    yield (
                        ev_names.get(ev.metadata_id, "?"),
                        base_ps + ev.offset_ps,
                        ev.duration_ps,
                    )


def module_device_seconds(trace_dir: str) -> float:
    """Total device execution time (seconds) of every XLA program run during
    the trace, summed from the "XLA Modules" line (one event per executed
    program, carrying its true device duration).

    This is the replay-proof measurement source ``bench.measure_with_floor``
    falls back to: the axon tunnel can hand the host an unphysically fast
    wall-clock (async dispatch / server-side replay), but it cannot fabricate
    device execution records — if the programs really ran during the traced
    window, their module events carry the real duration; if they were
    replayed, the line is (near-)empty and the reading stays suspect.
    """
    return sum(
        ps for _, ps in iter_device_events(trace_dir, "XLA Modules")
    ) / 1e12


def module_device_span_seconds(trace_dir: str) -> float:
    """Envelope span (first program start → last program end, seconds) of the
    "XLA Modules" events. With async dispatch several programs can overlap on
    device, so the summed durations (:func:`module_device_seconds`) can
    EXCEED true wall-clock; the span cannot, making it the honest reading
    when the host-side wall-clock is untrusted. Returns 0.0 when the trace
    recorded no module events."""
    starts_ends = [
        (start, start + dur)
        for _, start, dur in iter_device_event_windows(trace_dir, "XLA Modules")
    ]
    if not starts_ends:
        return 0.0
    return (max(e for _, e in starts_ends) - min(s for s, _ in starts_ends)) / 1e12


def _op_family(name: str) -> str:
    """Bucket an XLA op name into a coarse family."""
    base = name.split(".")[0].split("%")[-1]
    for fam in (
        "convolution", "dot", "fusion", "copy", "transpose", "reshape",
        "reduce", "broadcast", "convert", "all-gather", "all-reduce",
        "dynamic-slice", "dynamic-update-slice", "scatter", "gather",
        "custom-call", "rng", "iota", "slice", "concatenate", "pad",
    ):
        if base.startswith(fam):
            return fam
    return re.sub(r"[-_.]?\d+$", "", base) or base


def collect(trace_dir: str) -> dict:
    fams = collections.Counter()
    total_ps = 0
    for name, ps in iter_device_events(trace_dir):
        fams[_op_family(name)] += ps
        total_ps += ps
    return {"families": fams, "total_ps": total_ps}


def main() -> None:
    # jax only here: iter_device_events stays import-light for the
    # proto-parsing CLIs that share it (xplane_top_ops.py)
    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_fast_edit_working_point

    # profile the CACHED pair (the headline path) unless VIDEOP2P_PROFILE_LIVE=1
    live = os.environ.get("VIDEOP2P_PROFILE_LIVE", "0") == "1"
    wp = build_fast_edit_working_point(cached=not live)
    # compile + warm on a different input (memoization defeat)
    if live:
        jax.block_until_ready(wp.edit(wp.params, wp.invert(wp.params, wp.x_warm)[-1]))
    else:
        wtr, wcc = wp.invert_captured(wp.params, wp.x_warm)
        jax.block_until_ready(wp.edit_cached(wp.params, wtr[-1], wcc))

    trace_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="videop2p_xplane_"
    )
    with jax.profiler.trace(trace_dir):
        if live:
            traj = wp.invert(wp.params, wp.x0)
            out = wp.edit(wp.params, traj[-1])
        else:
            traj, cc = wp.invert_captured(wp.params, wp.x0)
            out = wp.edit_cached(wp.params, traj[-1], cc)
        jax.block_until_ready(out)

    res = collect(trace_dir)
    total = res["total_ps"] / 1e12
    print(f"trace: {trace_dir}")
    print(f"device op time total: {total:.3f} s")
    for fam, ps in res["families"].most_common(20):
        print(f"  {fam:24s} {ps/1e12:8.3f} s  {ps/res['total_ps']*100:5.1f}%")


if __name__ == "__main__":
    main()
