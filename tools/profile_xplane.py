"""Op-family breakdown of the jitted fast-edit phases on the real chip.

Runs the 50-step inversion + controlled edit (the exact bench working point —
shared via ``bench.build_fast_edit_working_point``) under ``jax.profiler.trace``
and sums per-op device time from the raw ``*.xplane.pb``.

The proto walk now lives in :mod:`videop2p_tpu.obs.trace` — a **stdlib
wire-format reader**, so this tool no longer needs
``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` or an installed
tensorflow, and the same parser feeds the ledger's ``trace_analysis``
events. Set ``VIDEOP2P_XPLANE_TF=1`` to force the legacy
tensorflow-proto path (the only reason: validating the stdlib reader
against the reference decoder on a box that has tensorflow).

Usage:  python tools/profile_xplane.py [trace_dir]
"""

from __future__ import annotations

import collections
import glob
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from videop2p_tpu.obs.trace import op_family as _op_family  # noqa: E402


def iter_device_events(trace_dir: str, line_name: str = "XLA Ops"):
    """Yield ``(op_name, duration_ps)`` for every ``line_name`` line event on
    a device plane of every xplane proto under ``trace_dir``."""
    for name, _, dur in iter_device_event_windows(trace_dir, line_name):
        yield name, dur


def iter_device_event_windows(trace_dir: str, line_name: str = "XLA Ops"):
    """Yield ``(op_name, start_ps, duration_ps)`` for every ``line_name``
    line event on a device plane, with starts on the trace's absolute
    timeline (line timestamp + event offset).

    Decodes the protos with the stdlib reader (obs/trace.py); the
    tensorflow-proto fallback survives behind ``VIDEOP2P_XPLANE_TF=1``
    for cross-validation only.
    """
    if os.environ.get("VIDEOP2P_XPLANE_TF", "0") == "1":
        yield from _iter_device_event_windows_tf(trace_dir, line_name)
        return
    from videop2p_tpu.obs.trace import iter_line_events, load_xplanes

    yield from iter_line_events(load_xplanes(trace_dir), line_name)


def _iter_device_event_windows_tf(trace_dir: str, line_name: str):
    """Legacy decoder through the tensorflow protobuf package (requires
    tensorflow + the pure-Python protobuf implementation)."""
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    ):
        xspace = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xspace.ParseFromString(f.read())
        for plane in xspace.planes:
            if "TPU" not in plane.name and "/device" not in plane.name.lower():
                continue
            ev_names = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                if line.name != line_name:
                    continue
                base_ps = line.timestamp_ns * 1000
                for ev in line.events:
                    yield (
                        ev_names.get(ev.metadata_id, "?"),
                        base_ps + ev.offset_ps,
                        ev.duration_ps,
                    )


def module_device_seconds(trace_dir: str) -> float:
    """Total device execution time (seconds) of every XLA program run during
    the trace, summed from the "XLA Modules" line (one event per executed
    program, carrying its true device duration).

    This is the replay-proof measurement source ``bench.measure_with_floor``
    falls back to: the axon tunnel can hand the host an unphysically fast
    wall-clock (async dispatch / server-side replay), but it cannot fabricate
    device execution records — if the programs really ran during the traced
    window, their module events carry the real duration; if they were
    replayed, the line is (near-)empty and the reading stays suspect.
    """
    return sum(
        ps for _, ps in iter_device_events(trace_dir, "XLA Modules")
    ) / 1e12


def module_device_span_seconds(trace_dir: str) -> float:
    """Envelope span (first program start → last program end, seconds) of the
    "XLA Modules" events. With async dispatch several programs can overlap on
    device, so the summed durations (:func:`module_device_seconds`) can
    EXCEED true wall-clock; the span cannot, making it the honest reading
    when the host-side wall-clock is untrusted. Returns 0.0 when the trace
    recorded no module events."""
    starts_ends = [
        (start, start + dur)
        for _, start, dur in iter_device_event_windows(trace_dir, "XLA Modules")
    ]
    if not starts_ends:
        return 0.0
    return (max(e for _, e in starts_ends) - min(s for s, _ in starts_ends)) / 1e12


def collect(trace_dir: str) -> dict:
    fams = collections.Counter()
    total_ps = 0
    for name, ps in iter_device_events(trace_dir):
        fams[_op_family(name)] += ps
        total_ps += ps
    return {"families": fams, "total_ps": total_ps}


def main() -> None:
    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(__doc__.strip())
        return
    # jax only here: iter_device_events stays import-light for the
    # proto-parsing CLIs that share it (xplane_top_ops.py)
    import jax

    from bench import build_fast_edit_working_point

    # profile the CACHED pair (the headline path) unless VIDEOP2P_PROFILE_LIVE=1
    live = os.environ.get("VIDEOP2P_PROFILE_LIVE", "0") == "1"
    wp = build_fast_edit_working_point(cached=not live)
    # compile + warm on a different input (memoization defeat)
    if live:
        jax.block_until_ready(wp.edit(wp.params, wp.invert(wp.params, wp.x_warm)[-1]))
    else:
        wtr, wcc = wp.invert_captured(wp.params, wp.x_warm)
        jax.block_until_ready(wp.edit_cached(wp.params, wtr[-1], wcc))

    trace_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="videop2p_xplane_"
    )
    with jax.profiler.trace(trace_dir):
        if live:
            traj = wp.invert(wp.params, wp.x0)
            out = wp.edit(wp.params, traj[-1])
        else:
            traj, cc = wp.invert_captured(wp.params, wp.x0)
            out = wp.edit_cached(wp.params, traj[-1], cc)
        jax.block_until_ready(out)

    res = collect(trace_dir)
    total = res["total_ps"] / 1e12
    print(f"trace: {trace_dir}")
    print(f"device op time total: {total:.3f} s")
    for fam, ps in res["families"].most_common(20):
        print(f"  {fam:24s} {ps/1e12:8.3f} s  {ps/res['total_ps']*100:5.1f}%")
    # the full time-domain record (obs/trace.py): compute vs collective
    # union seconds, the overlap fraction, idle gaps
    from videop2p_tpu.obs.trace import analyze_trace_dir

    record, _ = analyze_trace_dir(trace_dir, name="profile_xplane")
    ov = record["overlap_fraction"]
    print(
        f"compute {record['compute_s']:.3f} s / collective "
        f"{record['collective_s']:.3f} s, overlap "
        + ("n/a (no collectives)" if ov is None else f"{ov:.2f}")
        + f", idle {record['idle_s']:.3f} s over a {record['span_s']:.3f} s span"
    )


if __name__ == "__main__":
    main()
