"""Op-family breakdown of the jitted fast-edit phases on the real chip.

Runs the 50-step inversion + controlled edit under ``jax.profiler.trace`` and
sums per-op device time from the raw ``*.xplane.pb`` (the tensorboard-plugin
converter is broken in this image; parse the proto directly with the pure-
Python protobuf implementation). Inputs are seeded from runtime entropy so the
axon tunnel's server-side (executable, args) memoization cannot fake a cached
run (see .claude/skills/verify/SKILL.md).

Usage:  PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python python tools/profile_xplane.py
"""

from __future__ import annotations

import collections
import glob
import os
import re
import sys
import tempfile
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

import jax
import jax.numpy as jnp


def _op_family(name: str) -> str:
    """Bucket an XLA op name into a coarse family."""
    base = name.split(".")[0].split("%")[-1]
    for fam in (
        "convolution", "dot", "fusion", "copy", "transpose", "reshape",
        "reduce", "broadcast", "convert", "all-gather", "all-reduce",
        "dynamic-slice", "dynamic-update-slice", "scatter", "gather",
        "custom-call", "rng", "iota", "slice", "concatenate", "pad",
    ):
        if base.startswith(fam):
            return fam
    return re.sub(r"[-_.]?\d+$", "", base) or base


def collect(trace_dir: str) -> dict:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    fams = collections.Counter()
    total_ps = 0
    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    ):
        xspace = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xspace.ParseFromString(f.read())
        for plane in xspace.planes:
            if "TPU" not in plane.name and "/device" not in plane.name.lower():
                continue
            ev_names = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    name = ev_names.get(ev.metadata_id, "?")
                    fams[_op_family(name)] += ev.duration_ps
                    total_ps += ev.duration_ps
    return {"families": fams, "total_ps": total_ps}


def main() -> None:
    from videop2p_tpu.control import make_controller
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import ddim_inversion, edit_sample, make_unet_fn
    from videop2p_tpu.utils.tokenizers import WordTokenizer

    cfg = UNet3DConfig.sd15()
    model = UNet3DConditionModel(config=cfg, dtype=jnp.bfloat16)
    F, STEPS = 8, 50
    base = jax.random.key(time.time_ns() % (2**31))
    k0, k1, k2, k7 = jax.random.split(base, 4)
    x0 = jax.random.normal(k0, (1, F, 64, 64, 4), jnp.bfloat16)
    cond = jax.random.normal(k1, (2, 77, 768), jnp.bfloat16)
    uncond = jnp.zeros((77, 768), jnp.bfloat16)
    params = jax.jit(model.init)(k2, x0, jnp.asarray(10), cond[:1])
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()
    ctx = make_controller(
        ["a rabbit is jumping on the grass",
         "a origami rabbit is jumping on the grass"],
        WordTokenizer(),
        num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.2,
        self_replace_steps=0.5,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )
    invert = jax.jit(
        lambda p, x: ddim_inversion(fn, p, sched, x, cond[:1],
                                    num_inference_steps=STEPS)
    )
    edit = jax.jit(
        lambda p, xt: edit_sample(
            fn, p, sched, xt, cond, uncond,
            num_inference_steps=STEPS, ctx=ctx, source_uses_cfg=False,
        )
    )
    # compile + warm on a different input (memoization defeat)
    x_warm = jax.random.normal(k7, x0.shape, x0.dtype)
    jax.block_until_ready(edit(params, invert(params, x_warm)[-1]))

    trace_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="videop2p_xplane_"
    )
    with jax.profiler.trace(trace_dir):
        traj = invert(params, x0)
        out = edit(params, traj[-1])
        jax.block_until_ready(out)

    res = collect(trace_dir)
    total = res["total_ps"] / 1e12
    print(f"trace: {trace_dir}")
    print(f"device op time total: {total:.3f} s")
    for fam, ps in res["families"].most_common(20):
        print(f"  {fam:24s} {ps/1e12:8.3f} s  {ps/res['total_ps']*100:5.1f}%")


if __name__ == "__main__":
    main()
