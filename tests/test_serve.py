"""Serving-subsystem tests (ISSUE 7): deterministic batching, the
byte-budgeted device-resident inversion store, the warm ProgramSet
(batched == singleton bit-exact), the engine request lifecycle (second
identical request compile-free with ``src_err == 0.0``), the stdlib HTTP
API, the loadgen's ``execute_timing``-compatible ledger, and the
RunLedger concurrent-writer guarantee the multi-threaded engine relies on.
"""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from videop2p_tpu.serve.batching import (
    bucket_size,
    compat_key,
    plan_batches,
    stack_items,
    unstack_outputs,
)
from videop2p_tpu.serve.store import (
    InversionStore,
    load_persisted_inversion,
    save_persisted_inversion,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_serve_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Item:
    def __init__(self, compat, tag):
        self.compat = compat
        self.tag = tag

    def __repr__(self):
        return f"Item({self.compat}, {self.tag})"


# ------------------------------------------------------------- batching --


def test_bucket_size_powers_of_two_capped():
    assert [bucket_size(n, 8) for n in (1, 2, 3, 4, 5, 7, 8, 9)] == \
        [1, 2, 4, 4, 8, 8, 8, 8]
    assert bucket_size(3, 2) == 2  # cap wins over the power-of-two round-up


def test_plan_batches_deterministic_grouping_and_padding():
    items = [_Item("a", 0), _Item("b", 1), _Item("a", 2), _Item("a", 3),
             _Item("b", 4), _Item("a", 5), _Item("a", 6)]
    plans = plan_batches(items, max_batch=4)
    # groups form in first-seen order; items keep submit order; the 5-item
    # "a" group splits 4+1; every chunk pads to its bucket
    assert [(p.key, [i.tag for i in p.items], p.padded_size, p.pad)
            for p in plans] == [
        ("a", [0, 2, 3, 5], 4, 0),
        ("a", [6], 1, 0),
        ("b", [1, 4], 2, 0),
    ]
    # identical input -> identical plan (pure function)
    again = plan_batches(items, max_batch=4)
    assert [(p.key, [i.tag for i in p.items]) for p in again] == \
        [(p.key, [i.tag for i in p.items]) for p in plans]
    # a 3-item group pads to 4 with one repeated entry
    three = plan_batches([_Item("a", i) for i in range(3)], max_batch=4)
    assert [(p.padded_size, p.pad) for p in three] == [(4, 1)]
    # pad=False keeps exact sizes
    nopad = plan_batches([_Item("a", i) for i in range(3)], max_batch=4,
                         pad=False)
    assert [(p.padded_size, p.pad) for p in nopad] == [(3, 0)]


def test_stack_unstack_roundtrip_with_padding():
    trees = [{"x": jnp.full((2, 3), i, jnp.float32), "y": jnp.asarray(i)}
             for i in range(3)]
    stacked = stack_items(trees, padded_size=4)
    assert stacked["x"].shape == (4, 2, 3)
    # the pad entry repeats the last tree
    assert np.array_equal(np.asarray(stacked["x"][3]), np.asarray(trees[-1]["x"]))
    outs = unstack_outputs(stacked, 3)
    for i, out in enumerate(outs):
        assert np.array_equal(np.asarray(out["x"]), np.asarray(trees[i]["x"]))


def test_compat_key_shape_dtype_and_statics():
    a = {"x": np.zeros((2, 3), np.float32)}
    b = {"x": np.ones((2, 3), np.float32)}   # values differ -> same key
    c = {"x": np.zeros((2, 4), np.float32)}  # shape differs
    d = {"x": np.zeros((2, 3), np.float16)}  # dtype differs
    assert compat_key(a) == compat_key(b)
    assert compat_key(a) != compat_key(c)
    assert compat_key(a) != compat_key(d)
    # extra statics (steps, spec fingerprint) discriminate too
    assert compat_key(a, extra=(50,)) != compat_key(a, extra=(8,))


# ---------------------------------------------------------------- store --


def _products(mb):
    return {"traj": np.zeros((mb << 20) // 4, np.float32)}


def test_store_lru_eviction_by_byte_budget():
    store = InversionStore(byte_budget=3 << 20)
    assert store.put("a", _products(1))
    assert store.put("b", _products(1))
    assert store.put("c", _products(1))
    assert len(store) == 3 and store.stats()["bytes_in_use"] == 3 << 20
    # touching "a" makes "b" the LRU victim of the next insert
    assert store.get("a") is not None
    assert store.put("d", _products(1))
    assert "b" not in store and {"a", "c", "d"} <= set(store.keys())
    assert store.stats()["evictions"] == 1
    # a repeat request is a hit; an evicted key is a miss
    assert store.get("d") is not None
    assert store.get("b") is None
    stats = store.stats()
    assert stats["hits"] == 2 and stats["misses"] == 1


def test_store_oversize_rejected_not_thrashed():
    store = InversionStore(byte_budget=1 << 20)
    assert store.put("small", _products(1))  # exactly the budget
    assert not store.put("big", _products(2))
    stats = store.stats()
    assert stats["rejected_oversize"] == 1
    # the resident entry survived the rejected insert
    assert store.get("small") is not None


def test_store_disk_layer_roundtrip(tmp_path):
    root = str(tmp_path / "inv_store")
    traj = np.arange(24, dtype=np.float32).reshape(3, 1, 2, 2, 2)
    assert load_persisted_inversion(root, "k1") is None
    save_persisted_inversion(root, "k1", traj, meta={"clip": "x"})
    got, null = load_persisted_inversion(root, "k1", want_null=True)
    assert np.array_equal(got, traj) and null is None
    # write-through from the resident store lands in the same layout
    store = InversionStore(byte_budget=1 << 20, persist_dir=root)
    store.put("k2", {"anchor": np.zeros(4, np.float32)}, trajectory=traj)
    got2, _ = load_persisted_inversion(root, "k2")
    assert np.array_equal(got2, traj)


def test_store_write_through_is_atomic_and_truncation_detected(tmp_path):
    """ISSUE 12 satellite: the disk write-through leaves no temp files
    behind (every entry file goes write-temp-then-os.replace, so a kill
    mid-write can never publish a torn entry), and an entry that somehow
    IS truncated on disk is detected by `load_disk` — counted as
    `disk_corrupt`, reported as a miss, never served."""
    root = str(tmp_path / "inv_store")
    traj = np.arange(4 * 1 * 2 * 2 * 2, dtype=np.float32).reshape(4, 1, 2, 2, 2)
    store = InversionStore(byte_budget=1 << 20, persist_dir=root)
    store.put("kt", {"anchor": np.zeros(4, np.float32)}, trajectory=traj,
              meta={"clip": "x"})
    entry_dir = os.path.join(root, "inv_cache", "kt")
    assert sorted(os.listdir(entry_dir)) == ["meta.json", "trajectory.npy"]
    assert not [f for f in os.listdir(entry_dir) if ".tmp" in f]
    # healthy read first
    assert np.array_equal(store.load_disk("kt"), traj)
    assert store.disk_corrupt == 0
    # truncate the published file to half — the kill-mid-write artifact a
    # pre-atomic layout could leave
    traj_path = os.path.join(entry_dir, "trajectory.npy")
    size = os.path.getsize(traj_path)
    with open(traj_path, "r+b") as f:
        f.truncate(size // 2)
    assert store.load_disk("kt") is None
    assert store.disk_corrupt == 1
    # an absent entry stays a plain miss, not a corruption
    assert store.load_disk("never-written") is None
    assert store.disk_corrupt == 1


# ------------------------------------------- ledger concurrent writers --


def test_run_ledger_concurrent_writers_no_torn_lines(tmp_path):
    """ISSUE 7 satellite: multiple in-flight requests share one ledger —
    concurrent emits (events, execute-timing samples, compile callbacks)
    must produce only whole, parseable JSONL lines."""
    from videop2p_tpu.obs import RunLedger, read_ledger

    path = str(tmp_path / "concurrent.jsonl")
    led = RunLedger(path, meta={"test": "concurrent"})
    n_threads, n_events = 8, 200
    barrier = threading.Barrier(n_threads)

    def writer(tid):
        barrier.wait()
        for i in range(n_events):
            led.event("spam", tid=tid, i=i, payload="x" * 64)
            led.record_execute(f"prog_{tid % 3}", 0.001, 0.002)
            led._on_compile(0.01, f"prog_{tid % 3}")

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    led.close()
    raw = open(path).read().splitlines()
    events = []
    for line in raw:
        events.append(json.loads(line))  # a torn line would raise here
    spam = [e for e in events if e["event"] == "spam"]
    assert len(spam) == n_threads * n_events
    # every (tid, i) pair landed exactly once
    assert len({(e["tid"], e["i"]) for e in spam}) == len(spam)
    compiles = [e for e in events if e["event"] == "compile"]
    assert len(compiles) == n_threads * n_events
    assert len(led.compile_seconds) == n_threads * n_events
    # reservoirs accumulated under their own lock
    timing = led.execute_timing_summary()
    assert sum(t["count"] for t in timing.values()) == n_threads * n_events


def test_run_ledger_event_after_close_is_silent(tmp_path):
    from videop2p_tpu.obs import RunLedger, read_ledger

    path = str(tmp_path / "closed.jsonl")
    led = RunLedger(path)
    led.close()
    led.event("late", x=1)  # must not raise or write
    assert all(e["event"] != "late" for e in read_ledger(path))


# ----------------------------------------------------- sweep satellite --


def test_sweep_routes_p2p_through_inv_store():
    from videop2p_tpu.cli.sweep import cell_commands

    kw = dict(decay_rate=0.1, eta=0.0, dependent_weight=0.0, window_size=8,
              ar_sample=False, ar_coeff=0.1, num_frames=8, fast=True,
              dependent_p2p=False, extra=["--tiny"])
    tune, p2p = cell_commands("t.yaml", "p.yaml", inv_store="shared/inv", **kw)
    assert "--inv_store" in p2p and p2p[p2p.index("--inv_store") + 1] == "shared/inv"
    assert "--inv_store" not in tune  # Stage-1 has no inversion reuse path
    tune2, p2p2 = cell_commands("t.yaml", "p.yaml", inv_store=None, **kw)
    assert "--inv_store" not in p2p2


# ------------------------------------------------- request validation --


def test_edit_request_validation_and_json_surface():
    from videop2p_tpu.serve import EditRequest

    with pytest.raises(ValueError, match="source 'prompt'"):
        EditRequest(image_path="x", prompts=["a", "b"]).validate()
    with pytest.raises(ValueError, match=">= 2"):
        EditRequest(image_path="x", prompt="a", prompts=["a"]).validate()
    with pytest.raises(ValueError, match="prompts\\[0\\]"):
        EditRequest(image_path="x", prompt="a", prompts=["b", "c"]).validate()
    with pytest.raises(ValueError, match="image_path"):
        EditRequest(prompt="a", prompts=["a", "b"]).validate()
    with pytest.raises(ValueError, match="unknown request field"):
        EditRequest.from_dict({"prompt": "a", "bogus": 1})
    req = EditRequest.from_dict(
        {"image_path": "x", "prompt": "a", "prompts": ["a", "b"]}
    )
    req.validate()
    assert "frames" not in req.to_dict()


# ------------------------------------------------ warm program set -------

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)


@pytest.fixture(scope="module")
def programs():
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    return ProgramSet(ProgramSpec(**_SPEC_KW))


def _prepare(ps, prompts, clip_phase, blend=None):
    """Resolve one synthetic request by hand: controller, deterministic
    clip, encode, capture-inversion. Returns the edit-program arg tree."""
    ctx = ps.controller(prompts, blend_word=blend)
    grid = np.arange(2 * 16 * 16 * 3, dtype=np.float64).reshape(2, 16, 16, 3)
    frames = (np.abs(np.sin(grid * clip_phase)) * 255).astype(np.uint8)
    key = jax.random.key(0)
    latents = ps.encode(ps.frames_to_video(frames), key)
    traj, cached = ps.invert_capture(
        latents, ps.encode_prompts(prompts[:1]), ctx, key
    )[:2]
    return (cached, ps.encode_prompts(prompts), ps.encode_prompts([""])[0],
            ctx, latents)


def test_programset_spec_fingerprint_content_addressed():
    from videop2p_tpu.serve import ProgramSpec

    a = ProgramSpec(**_SPEC_KW)
    assert a.fingerprint() == ProgramSpec(**_SPEC_KW).fingerprint()
    assert a.fingerprint() != ProgramSpec(**{**_SPEC_KW, "steps": 4}).fingerprint()
    # the tiny-width rule resolves before fingerprinting (512 -> 16)
    assert ProgramSpec(**{**_SPEC_KW, "width": 512}).fingerprint() == a.fingerprint()
    # the sharded-schedule knobs are content-addressed (ISSUE 10): a ring
    # or tp-collective schedule change builds DIFFERENT compiled programs,
    # so sharded specs must never collide with single-chip ones — nor with
    # each other across schedules
    variants = {
        ProgramSpec(**kw).fingerprint()
        for kw in (
            _SPEC_KW,
            {**_SPEC_KW, "mesh": "1,4,2"},
            {**_SPEC_KW, "mesh": "1,4,2", "ring_variant": "bidir"},
            {**_SPEC_KW, "mesh": "1,4,2", "tp_collectives": "psum_scatter"},
        )
    }
    assert len(variants) == 4


def test_batched_scan_dispatch_bit_exact_vs_singleton(programs):
    """The acceptance pin: two compatible requests (different prompts AND
    different clips) stacked into one scan-mode dispatch produce BIT-EXACT
    outputs vs their singleton dispatches, and the exact source replay
    (src_err == 0.0) survives batching."""
    a = _prepare(programs, ["a rabbit is jumping", "a origami rabbit is jumping"], 0.013)
    b = _prepare(programs, ["a cat is running", "a plush cat is running"], 0.071)
    assert compat_key(a) == compat_key(b)
    va, ea = programs.edit_decode(*a)
    vb, eb = programs.edit_decode(*b)
    stacked = stack_items([a, b], 2)
    vbat, ebat = programs.edit_decode_batch(stacked, 2, dispatch="scan")
    assert np.array_equal(np.asarray(va), np.asarray(vbat[0]))
    assert np.array_equal(np.asarray(vb), np.asarray(vbat[1]))
    assert [float(x) for x in (ea, eb, ebat[0], ebat[1])] == [0.0] * 4
    # padding repeats the last item without touching real outputs
    padded = stack_items([a], 2)
    vpad, _ = programs.edit_decode_batch(padded, 2, dispatch="scan")
    assert np.array_equal(np.asarray(va), np.asarray(vpad[0]))


def test_batched_vmap_dispatch_allclose(programs):
    a = _prepare(programs, ["a rabbit is jumping", "a origami rabbit is jumping"], 0.013)
    b = _prepare(programs, ["a cat is running", "a plush cat is running"], 0.071)
    va, _ = programs.edit_decode(*a)
    vb, _ = programs.edit_decode(*b)
    vbat, errs = programs.edit_decode_batch(
        stack_items([a, b], 2), 2, dispatch="vmap"
    )
    assert np.allclose(np.asarray(va), np.asarray(vbat[0]), atol=1e-5)
    assert np.allclose(np.asarray(vb), np.asarray(vbat[1]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(errs), 0.0, atol=1e-5)


def test_blend_structure_gets_its_own_compat_key(programs):
    plain = _prepare(programs, ["a rabbit is jumping", "a origami rabbit is jumping"], 0.013)
    blended = _prepare(
        programs, ["a rabbit is jumping", "a origami rabbit is jumping"],
        0.013, blend=["rabbit", "rabbit"],
    )
    assert compat_key(plain) != compat_key(blended)
    # and the blended structure still dispatches (its own program)
    v, err = programs.edit_decode(*blended)
    assert v.shape[0] == 2 and float(err) == 0.0


@pytest.mark.slow
def test_data_mesh_vmap_batch_allclose():
    """dp>1 serving mesh: the batched vmap dispatch shards the request
    axis over 'data' and matches unsharded singleton results."""
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps1 = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps2 = ProgramSet(ProgramSpec(**_SPEC_KW, mesh="2,1,1"))
    assert ps2.mesh is not None and ps2.data_axis_size == 2
    a = _prepare(ps1, ["a rabbit is jumping", "a origami rabbit is jumping"], 0.013)
    b = _prepare(ps1, ["a cat is running", "a plush cat is running"], 0.071)
    va, _ = ps1.edit_decode(*a)
    vb, _ = ps1.edit_decode(*b)
    vbat, _ = ps2.edit_decode_batch(stack_items([a, b], 2), 2, dispatch="vmap")
    assert np.allclose(np.asarray(va), np.asarray(vbat[0]), atol=1e-5)
    assert np.allclose(np.asarray(vb), np.asarray(vbat[1]), atol=1e-5)


# ----------------------------------------------------------- engine ------


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    from videop2p_tpu.serve import EditEngine, ProgramSpec

    root = tmp_path_factory.mktemp("serve")
    eng = EditEngine(
        ProgramSpec(**_SPEC_KW),
        out_dir=str(root / "out"),
        store_budget_bytes=64 << 20,
        persist_dir=str(root / "inv_store"),
        max_batch=4,
        max_wait_s=0.3,
        keep_videos=True,
    )
    eng.warm(("a rabbit is jumping", "a origami rabbit is jumping"),
             batch_sizes=(2,), step_buckets=(1,),
             reuse_schedules=("uniform:2",))
    yield eng
    eng.close()


def _rabbit_request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt="a rabbit is jumping",
              prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
              save_name="origami")
    kw.update(overrides)
    return EditRequest(**kw)


def test_engine_second_identical_request_is_compile_free(engine):
    """THE acceptance criterion: with the engine warm, a repeat identical
    edit completes with ZERO new compile events (warm ProgramSet +
    inversion-store hit), its source stream replays with src_err == 0.0,
    and its outputs are bit-identical to the first run's."""
    r1 = engine.submit(_rabbit_request())
    rec1 = engine.result(r1, wait_s=300.0)
    assert rec1["status"] == "done", rec1.get("error")
    assert rec1["store_hit"] is False
    assert rec1["src_err"] == 0.0
    assert os.path.isfile(rec1["edit_gif"])
    assert os.path.isfile(rec1["inversion_gif"])

    r2 = engine.submit(_rabbit_request())
    rec2 = engine.result(r2, wait_s=300.0)
    assert rec2["status"] == "done", rec2.get("error")
    assert rec2["store_hit"] is True
    assert rec2["compile_events"] == 0
    assert rec2["src_err"] == 0.0
    assert np.array_equal(engine.videos(r1), engine.videos(r2))
    # ISSUE 20 satellite (c): the record's stable answer identity agrees
    # with the tensors — the determinism probe keys on exactly this hash
    assert rec1["content_sha256"] == rec2["content_sha256"]
    assert len(rec1["content_sha256"]) == 64
    # the store's trajectory write-through landed in the disk layer
    hit_key = rec2["store_key"]
    traj, _ = load_persisted_inversion(engine.store.persist_dir, hit_key)
    # (steps+1, B=1, F, h, w, C) in inversion-walk order
    assert traj is not None and traj.ndim == 6
    assert traj.shape[0] == engine.spec.steps + 1


def test_engine_batches_concurrent_compatible_requests(engine):
    """Three compatible requests submitted together dispatch as one
    batched program (the 0.3 s admit window collects them before any
    resolve starts), bit-equal to the earlier singleton result for the
    repeated clip."""
    reqs = [
        _rabbit_request(),                      # store hit
        _rabbit_request(seed=7),                # distinct key -> fresh invert
        _rabbit_request(image_path="data/car",
                        prompt="a car is moving",
                        prompts=["a car is moving", "a toy car is moving"]),
    ]
    rids = [engine.submit(r) for r in reqs]
    recs = [engine.result(r, wait_s=300.0) for r in rids]
    for rec in recs:
        assert rec["status"] == "done", rec.get("error")
        assert rec["src_err"] == 0.0
    assert all(rec["batch_size"] == 3 for rec in recs)
    assert all(rec["padded_size"] == 4 for rec in recs)


def test_engine_per_request_steps_runs_few_step_fast_path(engine):
    """ISSUE 8: a warmed few-step request runs the timestep-subset fast
    path from the SAME inversion products — store hit, source replay still
    exact (src_err == 0.0), output genuinely different from the full-step
    edit."""
    r_base = engine.submit(_rabbit_request())
    rec_base = engine.result(r_base, wait_s=300.0)
    assert rec_base["status"] == "done", rec_base.get("error")
    rid = engine.submit(_rabbit_request(steps=1))
    rec = engine.result(rid, wait_s=300.0)
    assert rec["status"] == "done", rec.get("error")
    assert rec["steps"] == 1
    assert rec["store_hit"] is True
    assert rec["src_err"] == 0.0
    assert not np.array_equal(engine.videos(rid), engine.videos(r_base))


def test_engine_rejects_unwarmed_steps_with_warm_list(engine):
    """ISSUE 8 satellite: per-request `steps` outside the warmed buckets
    is rejected AT SUBMIT with the warm list — unknown step geometry must
    not silently compile cold mid-serve (the HTTP layer maps this
    ValueError to a 400)."""
    from videop2p_tpu.serve import EditRequest

    with pytest.raises(ValueError, match=r"warmed: \[1, 2\]"):
        engine.submit(_rabbit_request(steps=3))
    # the request-shape validation catches non-positive steps before the
    # bucket check
    with pytest.raises(ValueError, match="positive int"):
        EditRequest(image_path="x", prompt="a", prompts=["a", "b"],
                    steps=0).validate()
    # healthz/warm summary advertises the admitted buckets
    assert engine.programs.warmed["steps"] == [1, 2]


def test_engine_reuse_and_quant_admission(engine):
    """ISSUE 15 satellites: a warmed reuse schedule serves (store hit,
    source replay still exact, output genuinely different from the full
    scan's), an un-warmed one is rejected AT SUBMIT with the warm list
    (same no-cold-compile-mid-serve contract as per-request steps), and
    ``quant_mode`` is an assertion about the SET — a mismatch is rejected
    naming the served mode (weights are quantized at set build, never per
    request)."""
    r_base = engine.submit(_rabbit_request())
    rec_base = engine.result(r_base, wait_s=300.0)
    assert rec_base["status"] == "done", rec_base.get("error")

    rid = engine.submit(_rabbit_request(reuse_schedule="uniform:2"))
    rec = engine.result(rid, wait_s=300.0)
    assert rec["status"] == "done", rec.get("error")
    assert rec["store_hit"] is True
    assert rec["src_err"] == 0.0  # stream 0 is REPLAYED, reuse or not
    assert not np.array_equal(engine.videos(rid), engine.videos(r_base))

    with pytest.raises(ValueError, match=r"not a warmed schedule"):
        engine.submit(_rabbit_request(reuse_schedule="uniform:3"))
    # malformed schedules fail validation before the warm-list check
    with pytest.raises(ValueError, match="uniform:K"):
        engine.submit(_rabbit_request(reuse_schedule="uniform:x"))
    with pytest.raises(ValueError, match=r"quant_mode='off'"):
        engine.submit(_rabbit_request(quant_mode="w8"))
    # matching the served mode is a no-op assertion, not a rejection
    rid2 = engine.submit(_rabbit_request(quant_mode="off"))
    assert engine.result(rid2, wait_s=300.0)["status"] == "done"
    # healthz/warm summary advertises the admitted schedules and mode
    assert sorted(engine.warm_reuse) == ["off", "uniform:2"]
    assert engine.programs.warmed["quant"] == "off"


def test_engine_student_admission_contract(engine):
    """ISSUE 16: student requests are admitted against the warmed STUDENT
    buckets, not the teacher's. A set with no student checkpoint rejects
    every student request at submit with remediation (the HTTP layer maps
    this to a 400), and the flag itself is shape-validated."""
    assert engine.warm_student == set()
    with pytest.raises(ValueError, match="no student checkpoint"):
        engine.submit(_rabbit_request(student=True))
    with pytest.raises(ValueError, match="must be a bool"):
        engine.submit(_rabbit_request(student="yes"))


@pytest.mark.slow
def test_engine_student_warm_serve_identity(programs, tmp_path):
    """ISSUE 16 end to end at the serving layer: an (identity-init) tiny
    student checkpoint loads into the set, changes the spec fingerprint,
    warms its own step buckets, serves a student request with the source
    replay exact AND bit-identical to the teacher at the same step subset
    (the 0-distill-steps teacher-identity pin), and an un-warmed student
    bucket is rejected at submit with the warmed student list."""
    from videop2p_tpu.serve import EditEngine, ProgramSpec
    from videop2p_tpu.train.distill import (
        DistillConfig,
        DistillState,
        init_time_head,
        make_distill_optimizer,
        save_student,
    )

    inner = programs.bundle.unet_params["params"]
    dcfg = DistillConfig(max_train_steps=1)
    head = init_time_head(jax.random.key(0), programs.bundle.unet.config)
    state = DistillState.create(inner, head, make_distill_optimizer(dcfg),
                                dcfg.trainable_modules)
    ckpt = save_student(str(tmp_path / "student"), jax.device_get(state), 0)

    spec = ProgramSpec(**_SPEC_KW, student_ckpt=ckpt)
    # the checkpoint is part of the program identity: warm caches and the
    # inversion store must never collide across student/teacher sets
    assert spec.fingerprint() != ProgramSpec(**_SPEC_KW).fingerprint()

    eng = EditEngine(spec, out_dir=str(tmp_path / "out"),
                     persist_dir=str(tmp_path / "inv_store"),
                     keep_videos=True)
    try:
        eng.warm(("a rabbit is jumping", "a origami rabbit is jumping"),
                 step_buckets=(1,), student_steps=(1,))
        assert eng.warm_student == {1}
        assert eng.programs.warmed["student"] == [1]

        r_teacher = eng.submit(_rabbit_request(steps=1))
        rec_t = eng.result(r_teacher, wait_s=300.0)
        assert rec_t["status"] == "done", rec_t.get("error")
        r_student = eng.submit(_rabbit_request(steps=1, student=True))
        rec_s = eng.result(r_student, wait_s=300.0)
        assert rec_s["status"] == "done", rec_s.get("error")
        assert rec_s["src_err"] == 0.0
        assert rec_s["store_hit"] is True  # same inversion, student or not
        np.testing.assert_array_equal(eng.videos(r_student),
                                      eng.videos(r_teacher))

        with pytest.raises(ValueError, match=r"warmed student: \[1\]"):
            eng.submit(_rabbit_request(steps=2, student=True))
    finally:
        eng.close()


def test_engine_metrics_report_reservoir_latency(engine):
    m = engine.metrics()
    lat = m["request_latency"]
    assert lat is not None and lat["count"] >= 2
    assert lat["blocked_p50_s"] > 0.0 and lat["blocked_p99_s"] > 0.0
    assert "serve_edit" in m["programs"] and "serve_resolve" in m["programs"]
    assert m["store"]["hits"] >= 1 and m["store"]["entries"] >= 1
    assert m["compile"]["events"] > 0  # the warm-up compiles were recorded
    assert m["requests"].get("done", 0) >= 2


def test_engine_bad_request_fails_cleanly(engine):
    rid = engine.submit(_rabbit_request(image_path="data/does_not_exist"))
    rec = engine.result(rid, wait_s=120.0)
    assert rec["status"] == "error"
    assert "resolve failed" in rec["error"]
    # the engine worker survived — a good request still completes
    rec2 = engine.result(engine.submit(_rabbit_request()), wait_s=300.0)
    assert rec2["status"] == "done"


def test_engine_cost_vector_amortization_and_conservation(engine):
    """ISSUE 19 acceptance pins. (a) Every terminal record carries a
    ``cost`` vector with exactly REQUEST_COST_FIELDS plus the dispatch's
    ``batch_occupancy``; (b) amortization: a cold request's attributed
    device-seconds INCLUDE its fresh inversion, so the identical repeat
    (store hit) is strictly cheaper AND records the avoided spend as
    ``saved_device_seconds > 0``; (c) conservation: attributed + padding
    device-seconds equal worker busy seconds (residual ~0), idle is
    explicit, and the per-tenant ledger sums back to the attributed
    total — nothing is silently dropped."""
    from videop2p_tpu.obs.cost import (
        CAPACITY_FIELDS,
        COST_ATTRIBUTION_FIELDS,
        REQUEST_COST_FIELDS,
    )

    tiger = dict(image_path="data/tiger", prompt="a tiger is resting",
                 prompts=["a tiger is resting", "a origami tiger is resting"],
                 save_name="tiger", tenant="chargeback")
    cold = engine.result(engine.submit(_rabbit_request(**tiger)),
                         wait_s=300.0)
    hit = engine.result(engine.submit(_rabbit_request(**tiger)),
                        wait_s=300.0)
    assert cold["status"] == "done" and cold["store_hit"] is False
    assert hit["status"] == "done" and hit["store_hit"] is True
    for rec in (cold, hit):
        assert set(rec["cost"]) == set(REQUEST_COST_FIELDS)
        occ = rec["batch_occupancy"]
        assert 1 <= occ["real"] <= occ["padded"]
        assert rec["cost"]["device_seconds"] > 0.0
    # the cold request paid for its inversion; the hit avoided it
    assert cold["cost"]["saved_device_seconds"] == 0.0
    assert hit["cost"]["saved_device_seconds"] > 0.0
    assert hit["cost"]["device_seconds"] < cold["cost"]["device_seconds"]
    # /metrics capacity: the conservation invariant, with idle explicit
    cap = engine.metrics()["capacity"]
    assert set(cap) == set(CAPACITY_FIELDS)
    assert cap["busy_seconds"] == pytest.approx(
        cap["attributed_seconds"] + cap["padding_seconds"], abs=1e-5)
    assert abs(cap["conservation_residual_s"]) < 1e-5
    assert cap["idle_seconds"] >= 0.0 and cap["dispatches"] > 0
    assert 0.0 < cap["occupancy"] <= 1.0
    # the chargeback rows: engine scope carries the capacity record,
    # tenant rows sum back to the attributed total (every dispatched
    # request accounted — rounding is the only slack)
    rows = engine.cost_records()
    by_scope = {}
    for r in rows:
        by_scope.setdefault(r["scope"], []).append(r)
    assert set(by_scope["engine"][0]) >= set(CAPACITY_FIELDS) | {
        "scope", "name"}
    tenant_rows = by_scope["tenant"]
    for r in tenant_rows:
        assert set(r) == set(COST_ATTRIBUTION_FIELDS)
    assert "chargeback" in {r["name"] for r in tenant_rows}
    assert sum(r["device_seconds"] for r in tenant_rows) == pytest.approx(
        cap["attributed_seconds"], abs=0.01)
    # per-program rows carry the static-model join (serve_invert priced
    # the cold inversion as a singleton dispatch)
    assert "serve_invert" in {r["name"] for r in by_scope["program"]}
    # the health surface rides the same books
    health = engine.health_record()
    assert health["busy_fraction"] == pytest.approx(cap["busy_fraction"],
                                                    abs=0.05)
    assert "padding_waste" in health


def test_http_roundtrip_and_metrics(engine):
    from videop2p_tpu.serve.client import EngineClient, engine_available
    from videop2p_tpu.serve.http import make_server

    server = make_server(engine).start()
    try:
        client = EngineClient(server.url)
        assert engine_available(server.url)
        health = client.healthz()
        assert health["ok"] and health["warm"]["src_err"] == 0.0
        rid = client.submit(_rabbit_request().to_dict())
        rec = client.wait(rid, timeout_s=300.0)
        assert rec["status"] == "done" and rec["store_hit"] is True
        assert rec["compile_events"] == 0 and rec["src_err"] == 0.0
        # server-side wait endpoint returns the same terminal record
        rec_srv = client.result(rid, wait_s=5.0)
        assert rec_srv["status"] == "done" and rec_srv["id"] == rec["id"]
        metrics = client.metrics()
        assert metrics["request_latency"]["blocked_p99_s"] > 0.0
        # error surfaces: unknown id -> 404, malformed request -> 400,
        # unwarmed per-request steps -> 400 carrying the warm list
        with pytest.raises(RuntimeError, match="404"):
            client.poll("feedfacefeed")
        with pytest.raises(RuntimeError, match="400"):
            client.submit({"prompt": "a", "bogus": True})
        with pytest.raises(RuntimeError, match="400"):
            client.submit({**_rabbit_request().to_dict(), "steps": 37})
        # un-warmed reuse schedule / mismatched quant mode -> 400 too
        # (ISSUE 15: the admission contract is HTTP-pinned)
        with pytest.raises(RuntimeError, match="400"):
            client.submit({**_rabbit_request().to_dict(),
                           "reuse_schedule": "uniform:5"})
        with pytest.raises(RuntimeError, match="400"):
            client.submit({**_rabbit_request().to_dict(),
                           "quant_mode": "w8"})
        # student request without a student checkpoint / warmed student
        # bucket -> 400 too (ISSUE 16: the admission contract is
        # HTTP-pinned)
        with pytest.raises(RuntimeError, match="400"):
            client.submit({**_rabbit_request().to_dict(), "student": True})
    finally:
        server.close()
    assert not engine_available(server.url)
    assert not engine_available(None)


def test_loadgen_writes_obs_diff_compatible_ledger(engine, tmp_path):
    loadgen = _load_tool("serve_loadgen")
    target = loadgen._InprocTarget(engine, timeout_s=300.0)
    ledger_path = str(tmp_path / "loadgen.jsonl")
    record = loadgen.run_loadgen(
        target,
        _rabbit_request().to_dict(),
        requests=3, concurrency=2, ledger_path=ledger_path,
        meta={"target": "test"},
    )
    assert record["done"] == 3 and record["errors"] == 0
    assert record["store_hits"] >= 2  # same clip: everything after #1 hits
    assert record["latency"]["count"] == 3
    assert record["latency"]["blocked_p50_s"] > 0.0

    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs

    runs = split_runs(read_ledger(ledger_path))
    assert len(runs) == 1
    timing = extract_run(runs[0]).get("timing", {})
    assert "loadgen_request" in timing
    assert timing["loadgen_request"]["count"] == 3
    # the ledger gates with obs_diff like any other run record
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", ledger_path, ledger_path]) == 0
