"""Scheduler unit tests: golden values vs. closed-form DDIM math
(reference semantics: /root/reference/dependent_ddim.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.core import DDIMScheduler, DDPMScheduler


def test_beta_schedule_scaled_linear_matches_sd():
    s = DDIMScheduler.create_sd()
    # SD-1.x: betas linear in sqrt space between 0.00085 and 0.012
    betas = np.linspace(0.00085**0.5, 0.012**0.5, 1000) ** 2
    ac = np.cumprod(1 - betas)
    np.testing.assert_allclose(np.asarray(s.alphas_cumprod), ac, rtol=1e-5)
    # set_alpha_to_one=False -> final alpha is alphas_cumprod[0]
    np.testing.assert_allclose(float(s.final_alpha_cumprod), ac[0], rtol=1e-6)


def test_timesteps_grid():
    s = DDIMScheduler.create_sd()
    ts = s.timesteps(50)
    assert ts.shape == (50,)
    assert ts[0] == 980 and ts[-1] == 0
    assert np.all(np.diff(ts) == -20)


def test_step_eta0_closed_form():
    s = DDIMScheduler.create_sd()
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (1, 4, 8, 8, 4))
    eps = jax.random.normal(jax.random.PRNGKey(1), x.shape)
    t = jnp.asarray(980)
    prev, x0 = s.step(eps, t, x, 50)

    a_t = np.asarray(s.alphas_cumprod)[980]
    a_prev = np.asarray(s.alphas_cumprod)[960]
    x0_ref = (np.asarray(x) - np.sqrt(1 - a_t) * np.asarray(eps)) / np.sqrt(a_t)
    prev_ref = np.sqrt(a_prev) * x0_ref + np.sqrt(1 - a_prev) * np.asarray(eps)
    np.testing.assert_allclose(np.asarray(x0), x0_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(prev), prev_ref, rtol=1e-4, atol=1e-5)


def test_step_final_timestep_uses_final_alpha():
    s = DDIMScheduler.create_sd()
    x = jnp.ones((1, 2, 4, 4, 4))
    eps = jnp.zeros_like(x)
    prev, x0 = s.step(eps, jnp.asarray(0), x, 50)
    a_t = np.asarray(s.alphas_cumprod)[0]
    x0_ref = np.asarray(x) / np.sqrt(a_t)
    # prev alpha == final_alpha_cumprod == alphas_cumprod[0]
    np.testing.assert_allclose(np.asarray(prev), np.sqrt(a_t) * x0_ref, rtol=1e-5)


def test_next_prev_roundtrip():
    """Forward (inversion) then reverse step with the same model output is an
    exact inverse — the property null-text optimization relies on."""
    s = DDIMScheduler.create_sd()
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 4, 8, 8, 4))
    eps = jax.random.normal(jax.random.PRNGKey(3), x.shape)
    t = jnp.asarray(500)
    up = s.next_step(eps, t, x, 50)
    down = s.prev_step(eps, t, up, 50)
    np.testing.assert_allclose(np.asarray(down), np.asarray(x), rtol=1e-3, atol=1e-4)


def test_eta_variance_injection():
    s = DDIMScheduler.create_sd()
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 4, 4, 4, 4))
    eps = jax.random.normal(jax.random.PRNGKey(5), x.shape)
    noise = jax.random.normal(jax.random.PRNGKey(6), x.shape)
    t = jnp.asarray(500)
    prev0, _ = s.step(eps, t, x, 50, eta=0.0)
    prev1, _ = s.step(eps, t, x, 50, eta=0.1, variance_noise=noise)
    var = float(s.variance(t, t - 20))
    delta = np.asarray(prev1) - np.asarray(prev0)
    # x_{t-1} shifts by η·σ_t·noise plus the direction-term correction
    a_prev = np.asarray(s.alphas_cumprod)[480]
    std = 0.1 * np.sqrt(var)
    dir_corr = (np.sqrt(1 - a_prev - std**2) - np.sqrt(1 - a_prev)) * np.asarray(eps)
    np.testing.assert_allclose(delta, std * np.asarray(noise) + dir_corr, rtol=1e-3, atol=1e-5)

    with pytest.raises(ValueError):
        s.step(eps, t, x, 50, eta=0.1)


def test_step_jittable_with_traced_timestep():
    s = DDIMScheduler.create_sd()

    @jax.jit
    def f(sched, eps, t, x):
        return sched.step(eps, t, x, 50)[0]

    x = jnp.ones((1, 2, 4, 4, 4))
    out = f(s, jnp.zeros_like(x), jnp.asarray(20), x)
    assert out.shape == x.shape


def test_add_noise_and_velocity():
    s = DDPMScheduler.create_sd(prediction_type="v_prediction")
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 4, 8, 8, 4))
    n = jax.random.normal(jax.random.PRNGKey(8), x.shape)
    t = jnp.asarray([100, 700])
    noisy = s.add_noise(x, n, t)
    v = s.get_velocity(x, n, t)
    a = np.sqrt(np.asarray(s.alphas_cumprod)[np.asarray(t)])[:, None, None, None, None]
    b = np.sqrt(1 - np.asarray(s.alphas_cumprod)[np.asarray(t)])[:, None, None, None, None]
    np.testing.assert_allclose(np.asarray(noisy), a * np.asarray(x) + b * np.asarray(n), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v), a * np.asarray(n) - b * np.asarray(x), rtol=1e-4, atol=1e-5)
    assert s.training_target(x, n, t) is v or np.allclose(np.asarray(s.training_target(x, n, t)), np.asarray(v))


def test_subset_schedule_is_exact_subset_of_base_grid():
    """ISSUE 8: the few-step serving schedules visit EXACT base-grid
    timesteps (so a base-steps inversion trajectory has a latent at every
    visited point), start at x_T, and end on the base walk's own terminal
    target (the same final ᾱ)."""
    s = DDIMScheduler.create_sd()
    base = s.timesteps(50)
    for steps in (8, 20, 50):
        pos, ts, prev = s.subset_schedule(50, steps)
        assert pos.shape == ts.shape == prev.shape == (steps,)
        assert pos[0] == 0  # starts at the base walk's x_T
        assert (np.diff(pos) > 0).all()
        np.testing.assert_array_equal(ts, base[pos])  # exact subset
        assert set(ts.tolist()) <= set(base.tolist())
        # each step lands on the next visited timestep; the last on the
        # base walk's terminal target (< 0 → final_alpha_cumprod)
        np.testing.assert_array_equal(prev[:-1], ts[1:])
        assert prev[-1] == base[-1] - 1000 // 50
    # steps == base reproduces the uniform rule exactly — subset walks at
    # full count are the plain walk
    pos, ts, prev = s.subset_schedule(50, 50)
    np.testing.assert_array_equal(ts, base)
    np.testing.assert_array_equal(prev, ts - 20)


def test_subset_schedule_validation():
    s = DDIMScheduler.create_sd()
    with pytest.raises(ValueError, match="steps"):
        s.subset_positions(50, 0)
    with pytest.raises(ValueError, match="steps"):
        s.subset_positions(50, 51)


def test_step_with_explicit_prev_timestep_matches_uniform_rule():
    """Passing the uniform prev timestep explicitly must reproduce the
    default path bit-for-bit — the subset seam changes nothing at full
    step count — and a non-uniform prev uses that ᾱ exactly."""
    s = DDIMScheduler.create_sd()
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 2, 4, 4, 4))
    eps = jax.random.normal(jax.random.PRNGKey(10), x.shape)
    t = jnp.asarray(500)
    d_prev, d_x0 = s.step(eps, t, x, 50)
    e_prev, e_x0 = s.step(eps, t, x, 50, prev_timestep=jnp.asarray(480))
    np.testing.assert_array_equal(np.asarray(d_prev), np.asarray(e_prev))
    np.testing.assert_array_equal(np.asarray(d_x0), np.asarray(e_x0))
    np.testing.assert_array_equal(
        np.asarray(s.prev_step(eps, t, x, 50)),
        np.asarray(s.prev_step(eps, t, x, 50, prev_timestep=jnp.asarray(480))),
    )
    # a larger jump (500 → 200) lands on ᾱ(200): closed form check
    big, _ = s.step(eps, t, x, 50, prev_timestep=jnp.asarray(200))
    a_t = np.asarray(s.alphas_cumprod)[500]
    a_prev = np.asarray(s.alphas_cumprod)[200]
    x0_ref = (np.asarray(x) - np.sqrt(1 - a_t) * np.asarray(eps)) / np.sqrt(a_t)
    ref = np.sqrt(a_prev) * x0_ref + np.sqrt(1 - a_prev) * np.asarray(eps)
    np.testing.assert_allclose(np.asarray(big), ref, rtol=1e-4, atol=1e-5)


def test_from_config_maps_diffusers_keys():
    """Stage-2 builds its scheduler from the checkpoint's
    scheduler_config.json (run_videop2p.py:101-114) — known keys map,
    unknown keys are ignored."""
    from videop2p_tpu.core import DDIMScheduler

    cfg = {
        "_class_name": "DDIMScheduler",
        "_diffusers_version": "0.11.1",
        "beta_start": 0.00085,
        "beta_end": 0.012,
        "beta_schedule": "scaled_linear",
        "clip_sample": False,
        "set_alpha_to_one": False,
        "steps_offset": 1,
        "skip_prk_steps": True,  # PNDM leftover diffusers writes — ignored
    }
    s = DDIMScheduler.from_config(cfg)
    assert s.steps_offset == 1
    assert s.beta_schedule == "scaled_linear"
    assert not s.clip_sample
    ref = DDIMScheduler.create_sd(steps_offset=1)
    np.testing.assert_allclose(
        np.asarray(s.alphas_cumprod), np.asarray(ref.alphas_cumprod)
    )
    # the offset shifts the inference grid (dependent_ddim.py:205-210)
    assert s.timesteps(50)[0] != DDIMScheduler.create_sd().timesteps(50)[0]
