"""Semantic observability (ISSUE 4): in-program attention capture,
edit-quality metrics, and the self-contained HTML run report.

CPU gates for the tentpole's contracts:

  * PSNR/SSIM pinned against closed forms (identical → inf / 1.0, a
    known constant-offset delta → the exact dB figure);
  * capture-off bit-exactness: ``edit_sample`` and ``cached_fast_edit``
    with ``attn_maps=False`` produce byte-identical outputs to the
    capture-on primary outputs — the cached replay's ``src_err == 0.0``
    included — and the capture record has the documented fixed shapes;
  * ``blend_mask`` is exactly the mask ``local_blend`` applies;
  * the quality ``RegressionRule``s (direction="decrease") flag PSNR
    drops, pass inf→inf, and flag inf→finite;
  * the report renders per-word heatmap grids, mask overlays, the
    quality table and verdicts from a ledger + sidecar, embedded as data
    URIs — numpy+stdlib only.

Fake attention-sowing denoisers keep everything eager-CPU-fast; the
full-pipeline CLI e2e (tiny models, --attn_maps --quality --report) is
the slow-marked acceptance test at the bottom.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.obs.attention import (
    attn_step_record,
    cross_attention_heat,
    load_obs_sidecar,
    save_obs_sidecar,
    site_entropies,
    summarize_attn_record,
)
from videop2p_tpu.obs.quality import (
    QUALITY_SUMMARY_FIELDS,
    adjacent_frame_psnr,
    edit_quality_record,
    masked_psnr,
    psnr,
    ssim,
)
from videop2p_tpu.pipelines import cached_fast_edit, ddim_inversion, edit_sample

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS = 4
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C) — 8×8 latent, 2 frames
TEXT_LEN = 77


@pytest.fixture(scope="module")
def sched():
    return DDIMScheduler.create_sd()


def attn_unet():
    """Fake denoiser that sows head-mean maps the way the real UNet does:
    one cross site (B·F, h·w, 77) and one temporal site (B·N, F, F), both
    mildly input-dependent so the capture is not a constant."""

    def fn(params, sample, t, text, control=None):
        b, f, h, w, _ = sample.shape
        L = text.shape[-2]
        wiggle = 1e-3 * jnp.mean(jnp.abs(sample))
        probs = jnp.full((b * f, h * w, L), 1.0 / L) + wiggle
        tprobs = jnp.full((b * h * w, f, f), 1.0 / f)
        store = {
            "attn_store": {
                "blocks_0": {"attn2": {"maps": (probs,)},
                             "attn_temp": {"maps": (tprobs,)}}
            },
            "attn_base": {},
        }
        bias = jnp.mean(text, axis=(1, 2))
        return 0.1 * sample + bias[:, None, None, None, None], store

    return fn


@pytest.fixture(scope="module")
def problem(sched):
    fn = attn_unet()
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, TEXT_LEN, 8))
    cond2 = jnp.concatenate([cond, 0.5 * jnp.ones((1, TEXT_LEN, 8))], axis=0)
    uncond = jnp.zeros((TEXT_LEN, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond, num_inference_steps=STEPS)
    return fn, x0, cond, cond2, uncond, traj


# ------------------------------------------------------- quality metrics --


def test_psnr_closed_forms():
    a = np.random.RandomState(0).rand(3, 16, 16, 3).astype(np.float32)
    assert float(psnr(a, a)) == float("inf")
    # constant offset c: MSE = c², PSNR = −20·log10(c) exactly
    assert float(psnr(a, a + 0.1)) == pytest.approx(20.0, abs=1e-3)
    assert float(psnr(a, a + 0.01)) == pytest.approx(40.0, abs=1e-2)
    # data_range scales the peak
    assert float(psnr(a * 255, a * 255 + 25.5, data_range=255.0)) == (
        pytest.approx(20.0, abs=1e-3)
    )


def test_ssim_closed_forms_and_monotonicity():
    a = np.random.RandomState(1).rand(2, 20, 20, 3).astype(np.float32)
    assert float(ssim(a, a)) == pytest.approx(1.0, abs=1e-6)
    rng = np.random.RandomState(2)
    small = a + 0.01 * rng.randn(*a.shape).astype(np.float32)
    large = a + 0.10 * rng.randn(*a.shape).astype(np.float32)
    s_small, s_large = float(ssim(a, small)), float(ssim(a, large))
    assert 0.9 < s_small < 1.0
    assert s_large < s_small  # more noise, less similar


def test_masked_psnr_scores_only_the_weighted_region():
    a = np.random.RandomState(3).rand(2, 8, 8, 3).astype(np.float32)
    mask = np.zeros((2, 8, 8), np.float32)
    mask[:, :4] = 1.0  # "edit region" = top half
    edited = a.copy()
    edited[:, :4] += 0.5  # change ONLY inside the mask
    # background (1 − mask) is untouched → inf
    assert float(masked_psnr(edited, a, (1.0 - mask)[..., None])) == float("inf")
    # the edit region itself scores the 0.5 offset: −20·log10(0.5) ≈ 6.02 dB
    assert float(masked_psnr(edited, a, mask[..., None])) == pytest.approx(
        6.0206, abs=1e-3
    )
    # an all-zero weight has nothing to measure → NaN, not a fake number
    assert np.isnan(float(masked_psnr(edited, a, np.zeros_like(mask)[..., None])))


def test_adjacent_frame_psnr():
    static = np.ones((4, 8, 8, 3), np.float32) * 0.5
    assert np.all(np.isinf(np.asarray(adjacent_frame_psnr(static))))
    flicker = static.copy()
    flicker[2] += 0.1
    curve = np.asarray(adjacent_frame_psnr(flicker))
    assert curve.shape == (3,)
    # both transitions around the flicker frame read the 0.1 offset (20 dB)
    assert curve[1] == pytest.approx(20.0, abs=1e-3)
    assert curve[2] == pytest.approx(20.0, abs=1e-3)
    assert np.isinf(curve[0])


def test_edit_quality_record_schema_and_mask_keys():
    a = np.random.RandomState(4).rand(3, 16, 16, 3).astype(np.float32)
    edited = a.copy()
    mask = np.zeros((3, 16, 16), np.float32)
    mask[:, :8] = 1.0
    edited[:, :8] = 1.0 - edited[:, :8]
    summary, curves = edit_quality_record(a, a, edited, mask=mask)
    for k in QUALITY_SUMMARY_FIELDS:
        assert k in summary, k
    assert summary["recon_psnr"] == float("inf")
    assert summary["recon_ssim"] == 1.0
    assert summary["background_psnr"] == float("inf")  # untouched outside mask
    assert summary["mask_coverage"] == pytest.approx(0.5, abs=1e-6)
    assert curves["recon_psnr_frames"].shape == (3,)
    assert curves["background_psnr_frames"].shape == (3,)
    # no mask → the background keys are absent, the core schema stays
    summary2, curves2 = edit_quality_record(a, a, edited)
    assert "background_psnr" not in summary2
    assert set(QUALITY_SUMMARY_FIELDS) <= set(summary2)
    assert "background_psnr_frames" not in curves2


# ---------------------------------------------------- attention capture --


def _fake_store(b_total, q, L, f=2, n=None):
    probs = jnp.full((b_total, q, L), 1.0 / L)
    store = {"attn_store": {"blocks_0": {"attn2": {"maps": (probs,)}}},
             "attn_base": {}}
    if n is not None:
        store["attn_store"]["blocks_0"]["attn_temp"] = {
            "maps": (jnp.full((n, f, f), 1.0 / f),)
        }
    return store


def test_cross_attention_heat_shapes_and_uniformity():
    # 2 uncond + 2 cond streams × 2 frames, 8×8 queries
    store = _fake_store((2 + 2) * 2, 64, TEXT_LEN)
    heat = cross_attention_heat(
        store, num_uncond=2, num_cond=2, video_length=2,
        text_len=TEXT_LEN, latent_hw=(8, 8),
    )
    assert heat.shape == (2, 16, 16, TEXT_LEN)
    # a uniform attention distribution stays uniform through the pooling
    np.testing.assert_allclose(np.asarray(heat), 1.0 / TEXT_LEN, rtol=1e-5)
    # no qualifying site → zeros at the same fixed shape, not an error
    zero = cross_attention_heat(
        {"attn_store": {}, "attn_base": {}}, num_uncond=2, num_cond=2,
        video_length=2, text_len=TEXT_LEN, latent_hw=(8, 8),
    )
    assert zero.shape == (2, 16, 16, TEXT_LEN)
    assert float(jnp.abs(zero).max()) == 0.0


def test_site_entropies_uniform_is_log_k():
    store = _fake_store(8, 64, TEXT_LEN, f=2, n=16)
    ents = site_entropies(store)
    assert set(ents) == {"blocks_0/attn2", "blocks_0/attn_temp"}
    assert float(ents["blocks_0/attn2"]) == pytest.approx(np.log(TEXT_LEN), rel=1e-3)
    assert float(ents["blocks_0/attn_temp"]) == pytest.approx(np.log(2), rel=1e-3)


def test_summarize_attn_record_and_sidecar_roundtrip(tmp_path):
    rec = {
        "cross_heat": np.random.rand(5, 2, 16, 16, TEXT_LEN).astype(np.float32),
        "entropy": {"a/attn2": np.linspace(4.0, 4.2, 5)},
        "mask_cov": np.full((5, 2, 2), 0.25, np.float32),
        "blend_active": np.array([0, 0, 1, 1, 1]),
    }
    s = summarize_attn_record(rec)
    assert s["steps"] == 5
    assert s["heat_shape"] == [5, 2, 16, 16, TEXT_LEN]
    assert s["sites"] == ["a/attn2"]
    assert s["entropy_mean"]["a/attn2"] == pytest.approx(4.1, abs=1e-3)
    assert s["mask_cov_final"] == [0.25, 0.25]
    assert s["blend_active_steps"] == 3
    path = save_obs_sidecar(str(tmp_path / "sc.npz"),
                            {"attn_edit/cross_heat": rec["cross_heat"]})
    back = load_obs_sidecar(path)
    np.testing.assert_array_equal(back["attn_edit/cross_heat"], rec["cross_heat"])


def test_blend_mask_is_exactly_what_local_blend_applies():
    from videop2p_tpu.control.local_blend import (
        LocalBlendConfig, blend_mask, local_blend,
    )

    P, F, S, r, L = 2, 2, 1, 8, TEXT_LEN
    alpha = np.zeros((P, 1, L), np.float32)
    alpha[:, :, 2] = 1.0
    cfg = LocalBlendConfig(alpha_layers=jnp.asarray(alpha), start_blend=1)
    maps = jax.random.uniform(jax.random.key(5), (P, F, S, r, r, L))
    x = jax.random.normal(jax.random.key(6), (P, F, 8, 8, 4))
    mask = blend_mask(maps, cfg, (8, 8))
    assert mask.shape == (P, F, 8, 8) and mask.dtype == jnp.bool_
    maskf = mask.astype(x.dtype)[..., None]
    expect = x[:1] + maskf * (x - x[:1])
    got = local_blend(x, maps, cfg, jnp.asarray(5))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expect))


# -------------------------------------- capture-off bit-exactness pins --


def test_edit_sample_attn_off_is_bit_exact(problem, sched):
    fn, _, _, cond2, uncond, traj = problem
    out_off = jax.jit(
        lambda xt: edit_sample(fn, None, sched, xt, cond2, uncond,
                               num_inference_steps=STEPS)
    )(traj[-1])
    out_on, attn = jax.jit(
        lambda xt: edit_sample(fn, None, sched, xt, cond2, uncond,
                               num_inference_steps=STEPS, attn_maps=True)
    )(traj[-1])
    assert np.array_equal(np.asarray(out_off), np.asarray(out_on))
    assert attn["cross_heat"].shape == (STEPS, 2, 16, 16, TEXT_LEN)
    assert set(attn["entropy"]) == {"blocks_0/attn2", "blocks_0/attn_temp"}
    for v in attn["entropy"].values():
        assert v.shape == (STEPS,)
        assert np.isfinite(np.asarray(v)).all()
    # telemetry + attn compose in documented order
    out_both, tel, attn2 = jax.jit(
        lambda xt: edit_sample(fn, None, sched, xt, cond2, uncond,
                               num_inference_steps=STEPS, telemetry=True,
                               attn_maps=True)
    )(traj[-1])
    assert np.array_equal(np.asarray(out_off), np.asarray(out_both))
    assert tel["abs_max"].shape == (STEPS,)
    np.testing.assert_array_equal(np.asarray(attn2["cross_heat"]),
                                  np.asarray(attn["cross_heat"]))


def test_cached_fast_edit_attn_off_bit_exact_and_replay_exact(problem, sched):
    fn, x0, cond, cond2, uncond, _ = problem
    kw = dict(num_inference_steps=STEPS, cross_len=0, self_window=(0, 0))
    traj_off, edited_off = jax.jit(
        lambda x: cached_fast_edit(fn, None, sched, x, cond, cond2,
                                   uncond, None, **kw)
    )(x0)
    traj_on, edited_on, attn = jax.jit(
        lambda x: cached_fast_edit(fn, None, sched, x, cond, cond2,
                                   uncond, None, attn_maps=True, **kw)
    )(x0)
    assert np.array_equal(np.asarray(edited_off), np.asarray(edited_on))
    assert np.array_equal(np.asarray(traj_off), np.asarray(traj_on))
    # the capture-on cached replay keeps the src_err == 0.0 guarantee
    assert float(jnp.max(jnp.abs(edited_on[0] - x0[0]))) == 0.0
    assert set(attn) == {"inversion", "edit"}
    # edit batch holds only the E=1 edit stream; inversion the source
    assert attn["edit"]["cross_heat"].shape == (STEPS, 1, 16, 16, TEXT_LEN)
    assert attn["inversion"]["cross_heat"].shape == (STEPS, 1, 16, 16, TEXT_LEN)


def test_ddim_inversion_attn_off_is_bit_exact(problem, sched):
    fn, x0, cond, _, _, traj = problem
    traj_on, attn = ddim_inversion(
        fn, None, sched, x0, cond, num_inference_steps=STEPS, attn_maps=True
    )
    assert np.array_equal(np.asarray(traj), np.asarray(traj_on))
    assert attn["cross_heat"].shape == (STEPS, 1, 16, 16, TEXT_LEN)


# ------------------------------------------------ quality regressions --


def _qrec(**quality):
    return {"run_id": "x", "programs": {}, "compiles": {}, "phases": {},
            "dispatch": {}, "quality": quality}


def test_quality_rules_flag_psnr_drop_and_pass_improvements():
    from videop2p_tpu.obs import QUALITY_RULES, evaluate_rules

    base = _qrec(recon_psnr=32.0, background_psnr=40.0, recon_ssim=0.98)
    # a 4 dB reconstruction drop (> 5% and > 0.5 abs) regresses
    res = evaluate_rules(base, _qrec(recon_psnr=28.0, background_psnr=40.0,
                                     recon_ssim=0.98), QUALITY_RULES)
    assert not res["pass"]
    assert {v["metric"] for v in res["regressions"]} == {"recon_psnr"}
    # an improvement (or tiny noise) passes
    assert evaluate_rules(base, _qrec(recon_psnr=33.0, background_psnr=40.1,
                                      recon_ssim=0.981), QUALITY_RULES)["pass"]
    # noise-floor: a 0.2 dB wobble is under min_abs even at small bases
    assert evaluate_rules(_qrec(recon_psnr=3.0), _qrec(recon_psnr=2.8),
                          QUALITY_RULES)["pass"]


def test_quality_rules_inf_semantics():
    from videop2p_tpu.obs import QUALITY_RULES, evaluate_rules

    inf = float("inf")
    # bit-exact both runs: clean pass
    assert evaluate_rules(_qrec(recon_psnr=inf), _qrec(recon_psnr=inf),
                          QUALITY_RULES)["pass"]
    # losing the exactness pedestal always regresses
    res = evaluate_rules(_qrec(recon_psnr=inf), _qrec(recon_psnr=45.0),
                         QUALITY_RULES)
    assert not res["pass"]
    # gaining it is an improvement
    assert evaluate_rules(_qrec(recon_psnr=45.0), _qrec(recon_psnr=inf),
                          QUALITY_RULES)["pass"]


def test_extract_run_collects_quality_events():
    from videop2p_tpu.obs import extract_run

    rec = extract_run([
        {"event": "run_start", "run_id": "q"},
        {"event": "quality", "program": "edit_quality", "sidecar": "x.npz",
         "recon_psnr": 30.5, "recon_ssim": 0.97, "note": "text-ignored"},
    ])
    assert rec["quality"] == {"recon_psnr": 30.5, "recon_ssim": 0.97}


# ------------------------------------------------------------- report --


def _report_fixture(tmp_path):
    events = [
        {"event": "run_start", "run_id": "rep", "prompt": "a rabbit is jumping",
         "wall_time": "2026-08-04T00:00:00Z"},
        {"event": "attn_maps", "scope": "edit", "sidecar": "sc.npz",
         "streams": [0, 1], "steps": 4,
         "heat_shape": [4, 2, 16, 16, TEXT_LEN], "sites": ["b/attn2"],
         "entropy_mean": {"b/attn2": 4.3},
         "words": [{"prompt": 1, "word": "origami", "tokens": [2]},
                   {"prompt": 0, "word": "rabbit", "tokens": [2, 3]}]},
        {"event": "quality", "program": "edit_quality", "recon_psnr": 31.2,
         "recon_ssim": 0.97, "edit_adjacent_psnr": 28.0,
         "source_adjacent_psnr": 29.0, "background_psnr": 38.5},
        {"event": "telemetry", "program": "null_text_fused",
         "loss_curve": [1.0, 0.5, 0.2], "loss_final": 0.2,
         "inner_steps_total": 12},
        {"event": "regression_verdicts", "baseline_run_id": "r0", "pass": False,
         "verdicts": [{"rule": "quality:recon_psnr-5%", "program": "edit_quality",
                       "base": 35.0, "new": 31.2, "delta_pct": 10.9,
                       "regressed": True}],
         "regressions": [{"rule": "quality:recon_psnr-5%"}]},
        {"event": "phase", "name": "cached_invert_edit", "seconds": 9.5},
        {"event": "trace", "name": "edit", "trace_dir": "/tmp/tr/edit"},
    ]
    sidecar = {
        "attn_edit/cross_heat":
            np.random.RandomState(0).rand(4, 2, 16, 16, TEXT_LEN)
            .astype(np.float32),
        "attn_edit/mask_heat":
            np.random.RandomState(1).rand(4, 2, 3, 16, 16).astype(np.float32),
        "attn_edit/mask_cov":
            np.random.RandomState(2).rand(4, 2, 3).astype(np.float32),
        "frames/edit":
            (np.random.RandomState(3).rand(3, 24, 24, 3) * 255)
            .astype(np.uint8),
    }
    ledger = str(tmp_path / "ledger.jsonl")
    with open(ledger, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    np.savez_compressed(str(tmp_path / "sc.npz"), **sidecar)
    return events, sidecar, ledger


def test_render_report_sections(tmp_path):
    from videop2p_tpu.obs.report import render_report

    events, sidecar, _ = _report_fixture(tmp_path)
    html = render_report(events, sidecar)
    # per-word heatmaps: both words, embedded PNGs, step labels
    assert "origami" in html and "rabbit" in html
    assert html.count("data:image/png;base64,") >= 4
    # quality table, null-text sparkline, verdicts, phases, trace link
    assert "recon_psnr" in html and "Edit quality" in html
    assert "Null-text" in html and "<svg" in html
    assert "REGRESSIONS" in html and "quality:recon_psnr-5%" in html
    assert "cached_invert_edit" in html
    assert "/tmp/tr/edit" in html
    # mask overlay section present (mask_heat + frames/edit in sidecar)
    assert "LocalBlend mask" in html


def test_edit_report_tool_cli(tmp_path, capsys):
    spec = importlib.util.spec_from_file_location(
        "edit_report_under_test", os.path.join(_REPO, "tools", "edit_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _, _, ledger = _report_fixture(tmp_path)
    out = str(tmp_path / "rep.html")
    assert mod.main(["edit_report.py", ledger, "-o", out,
                     "--sidecar", str(tmp_path / "sc.npz")]) == 0
    assert os.path.isfile(out)
    html = open(out).read()
    assert "origami" in html and "data:image/png;base64," in html
    # sidecar auto-discovery: the event's basename resolves next to the ledger
    out2 = str(tmp_path / "rep2.html")
    assert mod.main(["edit_report.py", ledger, "-o", out2]) == 0
    assert "data:image/png;base64," in open(out2).read()
    # usage errors: no args / missing ledger → 2, no traceback
    assert mod.main(["edit_report.py"]) == 2
    assert mod.main(["edit_report.py", str(tmp_path / "nope.jsonl")]) == 2


def test_report_tolerates_empty_ledger_and_missing_sidecar(tmp_path):
    from videop2p_tpu.obs.report import render_report, write_report

    assert "html" in render_report([], {})
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"event": "run_start", "run_id": "e"}) + "\n")
    out = write_report(str(empty))
    assert os.path.isfile(out)


# ------------------------------------------------------ CLI e2e (slow) --


@pytest.mark.slow
def test_cli_fast_edit_report_acceptance(tmp_path):
    """The ISSUE-4 acceptance run: a tiny-config cached fast edit with
    --attn_maps --quality --report writes the sidecar + HTML report; the
    report embeds ≥1 per-word heatmap and the quality table, and the
    quality RegressionRules evaluate the run's ledger into verdicts."""
    from videop2p_tpu.cli.run_videop2p import main as p2p
    from videop2p_tpu.obs import (
        QUALITY_RULES,
        evaluate_rules,
        extract_run,
        read_ledger,
        split_runs,
    )

    ledger_path = str(tmp_path / "ledger.jsonl")
    inv_gif, edit_gif = p2p(
        pretrained_model_path=str(tmp_path / "no_ckpt"),
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
        save_name="origami", is_word_swap=False,
        blend_word=["rabbit", "rabbit"],
        video_len=2, fast=True, tiny=True,
        attn_maps=True, quality=True, report=True,
        ledger=ledger_path, reuse_inversion=False,
    )
    assert os.path.isfile(inv_gif) and os.path.isfile(edit_gif)
    folder = os.path.dirname(edit_gif)
    report = os.path.join(folder, "report_origami_fast.html")
    assert os.path.isfile(report), sorted(os.listdir(folder))
    html = open(report).read()
    # ≥1 per-word heatmap embedded + the quality table
    assert html.count("data:image/png;base64,") >= 1
    assert "origami" in html and "rabbit" in html
    assert "Edit quality" in html and "recon_psnr" in html

    events = read_ledger(ledger_path)
    attn_evs = [e for e in events if e["event"] == "attn_maps"]
    scopes = {e["scope"] for e in attn_evs}
    assert scopes == {"inversion", "edit"}
    for e in attn_evs:
        assert os.path.isfile(e["sidecar"])
        assert e["steps"] == 50
        assert e["words"] and e["sites"]
    qual = [e for e in events if e["event"] == "quality"]
    assert qual and all(k in qual[0] for k in ("recon_psnr", "recon_ssim",
                                               "background_psnr"))
    # the sidecar holds the heat stacks, mask series and quality curves
    sc = np.load(qual[0]["sidecar"])
    assert "attn_edit/cross_heat" in sc.files
    assert "attn_edit/mask_heat" in sc.files
    assert "quality/recon_psnr_frames" in sc.files
    assert sc["attn_edit/cross_heat"].shape[0] == 50

    # quality RegressionRules evaluate this run's record into verdicts
    rec = extract_run(split_runs(events)[-1])
    res = evaluate_rules(rec, rec, QUALITY_RULES)
    assert res["pass"]
    assert {v["metric"] for v in res["verdicts"]} >= {"recon_psnr",
                                                      "background_psnr"}


@pytest.mark.slow
def test_cli_repeat_run_emits_regression_verdicts(tmp_path):
    """A second quality-enabled run appending to the same ledger gets the
    cross-run verdict event (the PR-3 engine closing over quality)."""
    from videop2p_tpu.cli.run_videop2p import main as p2p
    from videop2p_tpu.obs import read_ledger

    ledger_path = str(tmp_path / "ledger.jsonl")
    kw = dict(
        pretrained_model_path=str(tmp_path / "no_ckpt"),
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
        save_name="origami", is_word_swap=False,
        video_len=2, fast=True, tiny=True,
        quality=True, ledger=ledger_path, reuse_inversion=False,
    )
    p2p(**kw)
    p2p(**kw)
    events = read_ledger(ledger_path)
    verdicts = [e for e in events if e["event"] == "regression_verdicts"]
    assert verdicts, "second run emitted no cross-run verdicts"
    v = verdicts[-1]
    assert "verdicts" in v and isinstance(v["verdicts"], list)
    # identical tiny runs: the quality verdicts exist and pass
    qv = [x for x in v["verdicts"] if x.get("kind") == "quality"]
    assert qv and all(not x["regressed"] for x in qv)
