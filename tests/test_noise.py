"""Dependent-noise sampler: distributional tests against closed-form
covariances (reference semantics: /root/reference/dependent_noise.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.core import DependentNoiseSampler
from videop2p_tpu.core.noise import ar_window_cov, toeplitz_cov


def test_toeplitz_cov():
    cov = toeplitz_cov(4, 0.5)
    expected = np.array(
        [
            [1.0, 0.5, 0.25, 0.125],
            [0.5, 1.0, 0.5, 0.25],
            [0.25, 0.5, 1.0, 0.5],
            [0.125, 0.25, 0.5, 1.0],
        ],
        dtype=np.float32,
    )
    np.testing.assert_allclose(cov, expected)


def test_ar_window_cov_kron():
    ws, dr, ac, nw = 3, 0.3, 0.25, 2
    cov = ar_window_cov(ws, dr, ac, nw)
    inner = toeplitz_cov(ws, dr)
    # cross-window block scales by sqrt(ac)^|i-j|
    np.testing.assert_allclose(cov[:ws, :ws], inner, rtol=1e-6)
    np.testing.assert_allclose(cov[:ws, ws:], np.sqrt(ac) * inner, rtol=1e-6)


def _empirical_cov(samples: np.ndarray) -> np.ndarray:
    # samples: (N, f)
    return (samples.T @ samples) / samples.shape[0]


def test_single_window_covariance():
    s = DependentNoiseSampler.create(num_frames=8, decay_rate=0.4, window_size=8)
    draws = s.sample(jax.random.PRNGKey(0), (4096, 8, 2), frame_axis=1)
    flat = np.asarray(draws).transpose(0, 2, 1).reshape(-1, 8)
    emp = _empirical_cov(flat)
    np.testing.assert_allclose(emp, s.joint_cov(), atol=0.08)


def test_independent_windows():
    s = DependentNoiseSampler.create(num_frames=8, decay_rate=0.5, window_size=4, ar_sample=False)
    draws = s.sample(jax.random.PRNGKey(1), (8192, 8), frame_axis=1)
    emp = _empirical_cov(np.asarray(draws))
    ref = s.joint_cov()
    # off-diagonal window block must be ~0
    np.testing.assert_allclose(emp[:4, 4:], np.zeros((4, 4)), atol=0.08)
    np.testing.assert_allclose(emp[:4, :4], ref[:4, :4], atol=0.08)


def test_ar_chained_windows_covariance():
    """AR chaining realizes kron(toeplitz(sqrt(ac)^|i-j|), Σ)
    (dependent_noise.py:59-71 vs :17-20)."""
    s = DependentNoiseSampler.create(
        num_frames=12, decay_rate=0.3, window_size=4, ar_sample=True, ar_coeff=0.36
    )
    draws = s.sample(jax.random.PRNGKey(2), (16384, 12), frame_axis=1)
    emp = _empirical_cov(np.asarray(draws))
    np.testing.assert_allclose(emp, s.joint_cov(), atol=0.1)


def test_sample_like_layout_and_dtype():
    s = DependentNoiseSampler.create(num_frames=8, window_size=8)
    x = jnp.zeros((2, 8, 16, 16, 4), dtype=jnp.bfloat16)
    n = s.sample_like(jax.random.PRNGKey(3), x)
    assert n.shape == x.shape and n.dtype == x.dtype


def test_frame_axis_mismatch_raises():
    s = DependentNoiseSampler.create(num_frames=8, window_size=8)
    with pytest.raises(ValueError):
        s.sample(jax.random.PRNGKey(0), (2, 6, 4), frame_axis=1)
    with pytest.raises(ValueError):
        DependentNoiseSampler.create(num_frames=10, window_size=4)


def test_sampler_jittable():
    s = DependentNoiseSampler.create(num_frames=8, window_size=4, ar_sample=True)

    @jax.jit
    def draw(sampler, key):
        return sampler.sample(key, (2, 8, 4, 4, 4), frame_axis=1)

    out = draw(s, jax.random.PRNGKey(9))
    assert out.shape == (2, 8, 4, 4, 4)
