"""End-to-end CLI tests: the full two-stage flow on tiny random-init models.

Covers the filesystem contract between stages (suffix mangling + resolution,
diffusers-layout checkpoint, scheduler config), metrics logging, inversion,
controller construction from config-shaped inputs, LocalBlend (and the
no-blend path), and GIF artifacts — the same flow as
``run_tuning.py`` → ``run_videop2p.py`` in the reference.
"""

import json
import os

import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tuned_dir(tmp_path_factory):
    from videop2p_tpu.cli.run_tuning import main as tune

    root = tmp_path_factory.mktemp("e2e")
    out = tune(
        pretrained_model_path=str(root / "no_ckpt"),
        output_dir=str(root / "exp"),
        train_data={
            "video_path": "data/rabbit", "prompt": "a rabbit is jumping",
            "n_sample_frames": 2, "width": 16, "height": 16,
        },
        validation_data={
            "prompts": ["a origami rabbit"], "num_inference_steps": 2,
            "num_inv_steps": 2, "guidance_scale": 7.5, "use_inv_latent": True,
        },
        max_train_steps=3, validation_steps=3, checkpointing_steps=3,
        tiny=True, mixed_precision="no", log_every=1,
    )
    return out


def test_stage1_artifacts(tuned_dir):
    # diffusers-layout pipeline dir + metrics + validation latents/samples
    assert os.path.isfile(os.path.join(tuned_dir, "model_index.json"))
    assert os.path.isdir(os.path.join(tuned_dir, "unet"))
    sched_cfg = json.load(
        open(os.path.join(tuned_dir, "scheduler", "scheduler_config.json"))
    )
    assert sched_cfg["steps_offset"] == 1
    metrics = [json.loads(l) for l in open(os.path.join(tuned_dir, "metrics.jsonl"))]
    assert [m["step"] for m in metrics] == [1, 2, 3]
    assert os.path.isdir(os.path.join(tuned_dir, "inv_latents"))


def test_distillation_after_tuning(tuned_dir):
    """ISSUE 16: the post-tuning distillation stage trains the few-step
    student against the tuned teacher on the same clip and writes the
    servable checkpoint under ``<pipeline>/student/`` — the path
    ``cli.serve --student_ckpt`` takes — and it loads back against the
    tuned pipeline's own parameter tree."""
    import jax.numpy as jnp

    from videop2p_tpu.cli.common import build_models
    from videop2p_tpu.cli.run_tuning import run_distillation
    from videop2p_tpu.train import load_student

    ckpt = run_distillation(
        tuned_dir,
        {"video_path": "data/rabbit", "prompt": "a rabbit is jumping",
         "n_sample_frames": 2, "width": 16, "height": 16},
        distill_steps=2, distill_grid=2, tiny=True, seed=0,
    )
    assert os.path.isdir(ckpt)
    assert os.path.basename(ckpt) == "checkpoint-2"
    assert os.path.dirname(ckpt) == os.path.join(tuned_dir, "student")
    bundle = build_models(tuned_dir, dtype=jnp.float32,
                          frame_attention="chunked", tiny=True)
    merged, head = load_student(ckpt, bundle.unet_params["params"],
                                bundle.unet.config)
    assert head["dense2"]["kernel"].ndim == 2
    assert jnp.isfinite(head["dense2"]["kernel"].astype(jnp.float32)).all()


def test_stage2_fast_edit_with_blend(tuned_dir):
    from videop2p_tpu.cli.run_videop2p import main as p2p

    # pass the UNSUFFIXED experiment root: the resolver must find the
    # suffixed pipeline dir Stage-1 wrote
    base = tuned_dir.rsplit("_dependent", 1)[0]
    inv_gif, edit_gif = p2p(
        pretrained_model_path=base,
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
        save_name="origami", is_word_swap=False,
        blend_word=["rabbit", "rabbit"],
        eq_params={"words": ["origami"], "values": [2.0]},
        video_len=2, fast=True, tiny=True,
    )
    assert os.path.isfile(inv_gif) and os.path.isfile(edit_gif)
    assert tuned_dir in edit_gif  # results land inside the suffixed dir


@pytest.fixture(scope="module")
def source_pipeline_dir(tmp_path_factory):
    """A tiny diffusers-layout SD checkpoint WITH vae and text_encoder
    weights — the real Stage-1 input shape (run_tuning.py:126-131 loads all
    components from ``pretrained_model_path``), as opposed to the weightless
    smoke path the other fixtures drive."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch
    from safetensors.numpy import save_file
    from transformers import CLIPTextConfig as HFConfig, CLIPTextModel

    from tests.torch_ref import TorchVAE
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig, VAEConfig
    from videop2p_tpu.models.pipeline_io import save_pipeline

    root = str(tmp_path_factory.mktemp("source_ckpt") / "sd-tiny")
    ucfg = UNet3DConfig.tiny()
    unet = UNet3DConditionModel(config=ucfg)
    uparams = unet.init(
        jax.random.key(0),
        jnp.zeros((1, 2, 8, 8, 4)),
        jnp.asarray(0),
        jnp.zeros((1, 77, ucfg.cross_attention_dim)),
    )
    save_pipeline(
        root, ucfg, uparams,
        scheduler_config={
            "num_train_timesteps": 1000, "beta_start": 0.00085,
            "beta_end": 0.012, "beta_schedule": "scaled_linear",
            "clip_sample": False, "set_alpha_to_one": False, "steps_offset": 1,
        },
    )

    vcfg = VAEConfig.tiny()
    torch.manual_seed(0)
    tvae = TorchVAE(vcfg).eval()
    os.makedirs(os.path.join(root, "vae"))
    save_file(
        {k: v.detach().numpy() for k, v in tvae.state_dict().items()},
        os.path.join(root, "vae", "diffusion_pytorch_model.safetensors"),
    )
    with open(os.path.join(root, "vae", "config.json"), "w") as f:
        json.dump({
            "in_channels": vcfg.in_channels, "out_channels": vcfg.out_channels,
            "latent_channels": vcfg.latent_channels,
            "block_out_channels": list(vcfg.block_out_channels),
            "layers_per_block": vcfg.layers_per_block,
            "norm_num_groups": vcfg.norm_num_groups,
            "scaling_factor": vcfg.scaling_factor,
        }, f)

    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=ucfg.cross_attention_dim,
        intermediate_size=32, num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=77, hidden_act="quick_gelu",
    )
    te = CLIPTextModel(hf_cfg).eval()
    os.makedirs(os.path.join(root, "text_encoder"))
    save_file(
        {k: v.detach().numpy() for k, v in te.state_dict().items()},
        os.path.join(root, "text_encoder", "model.safetensors"),
    )
    with open(os.path.join(root, "text_encoder", "config.json"), "w") as f:
        json.dump({
            "vocab_size": 128, "hidden_size": ucfg.cross_attention_dim,
            "intermediate_size": 32, "num_hidden_layers": 2,
            "num_attention_heads": 2, "max_position_embeddings": 77,
        }, f)
    return root


def test_two_stage_real_weights_no_backfill(source_pipeline_dir, tmp_path):
    """The NON-degraded export contract (VERDICT r2 item 7): Stage 1 starts
    from a checkpoint with real vae/text_encoder weights, copies them through
    to its export (run_tuning.py:387-393 semantics), and Stage 2 loads that
    export WITHOUT the RANDOM-INIT backfill warning."""
    import warnings

    from videop2p_tpu.cli.run_tuning import main as tune
    from videop2p_tpu.cli.run_videop2p import main as p2p

    with warnings.catch_warnings():
        # only the random-init backfill warnings fail the test — a blanket
        # simplefilter("error", UserWarning) would also escalate unrelated
        # torch/transformers deprecation warnings raised under the run
        warnings.filterwarnings("error", message=".*RANDOM-INIT.*")
        out = tune(
            pretrained_model_path=source_pipeline_dir,
            output_dir=str(tmp_path / "exp"),
            train_data={
                "video_path": "data/rabbit", "prompt": "a rabbit is jumping",
                "n_sample_frames": 2, "width": 16, "height": 16,
            },
            validation_data={
                "prompts": ["a origami rabbit"], "num_inference_steps": 2,
                "num_inv_steps": 2, "guidance_scale": 7.5, "use_inv_latent": True,
            },
            max_train_steps=2, validation_steps=2, checkpointing_steps=2,
            mixed_precision="no", log_every=1,
        )
        # the export carries the frozen components, not just the UNet
        for sub in ("vae", "text_encoder", "unet", "scheduler"):
            assert os.path.isdir(os.path.join(out, sub)), sub

        inv_gif, edit_gif = p2p(
            pretrained_model_path=out,
            image_path="data/rabbit",
            prompt="a rabbit is jumping",
            prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
            save_name="origami", is_word_swap=False,
            video_len=2, width=16, fast=True,
        )
    assert os.path.isfile(inv_gif) and os.path.isfile(edit_gif)


def test_stage2_reuses_persisted_inversion(tuned_dir, capsys):
    """VERDICT r3 item 7: a second edit of the same clip must skip DDIM
    inversion (and null-text in full mode) by loading the persisted products;
    iterating on the edit prompt is then cheap."""
    from videop2p_tpu.cli.run_videop2p import main as p2p

    base = tuned_dir.rsplit("_dependent", 1)[0]
    # a source prompt no other test uses — the cache key covers the source
    # prompt, so this test controls its own entry even though the fixture dir
    # (and its inv_cache) is shared module-wide
    src = "a rabbit is jumping quickly"
    kw = dict(
        pretrained_model_path=base,
        image_path="data/rabbit",
        prompt=src,
        prompts=[src, "a origami rabbit is jumping quickly"],
        is_word_swap=False, video_len=2, fast=False, tiny=True,
        num_inner_steps=2,
    )
    p2p(save_name="reuse_a", **kw)
    first = capsys.readouterr().out
    assert "reusing persisted inversion" not in first
    cache_root = os.path.join(tuned_dir, "results_dpFalse", "inv_cache")
    assert os.path.isdir(cache_root)
    from videop2p_tpu.utils.inv_cache import load_inversion

    keys = [
        k for k in os.listdir(cache_root)
        if load_inversion(
            os.path.join(tuned_dir, "results_dpFalse"), k,
            want_null=True, null_tag="_i2",
        ) is not None
    ]
    entries = [
        os.path.join(cache_root, k) for k in keys
        if os.path.isfile(os.path.join(cache_root, k, "null_embeddings_i2.npy"))
    ]
    assert entries, f"no entry with null embeddings under {cache_root}"

    # second run, different EDIT prompt (source stays src) — same
    # clip+source ⇒ full reuse
    kw["prompts"] = [src, "a plush rabbit is jumping quickly"]
    _, gif = p2p(save_name="reuse_b", **kw)
    second = capsys.readouterr().out
    assert "skipping DDIM inversion and null-text optimization" in second
    assert os.path.isfile(gif)

    # opting out must bypass the cache
    p2p(save_name="reuse_c", reuse_inversion=False, **kw)
    assert "reusing persisted inversion" not in capsys.readouterr().out


def test_stage2_no_blend_path(tuned_dir):
    """bird-forest style edit: refine controller, custom replace ratios, NO
    LocalBlend (configs/bird-forest-p2p.yaml has no blend_word)."""
    from videop2p_tpu.cli.run_videop2p import main as p2p

    inv_gif, edit_gif = p2p(
        pretrained_model_path=tuned_dir,  # already-suffixed dir also works
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a crochet rabbit is jumping"],
        save_name="crochet", is_word_swap=False,
        cross_replace_steps=0.8, self_replace_steps=0.7,
        video_len=2, fast=True, tiny=True,
    )
    assert os.path.isfile(edit_gif)
