"""End-to-end CLI tests: the full two-stage flow on tiny random-init models.

Covers the filesystem contract between stages (suffix mangling + resolution,
diffusers-layout checkpoint, scheduler config), metrics logging, inversion,
controller construction from config-shaped inputs, LocalBlend (and the
no-blend path), and GIF artifacts — the same flow as
``run_tuning.py`` → ``run_videop2p.py`` in the reference.
"""

import json
import os

import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tuned_dir(tmp_path_factory):
    from videop2p_tpu.cli.run_tuning import main as tune

    root = tmp_path_factory.mktemp("e2e")
    out = tune(
        pretrained_model_path=str(root / "no_ckpt"),
        output_dir=str(root / "exp"),
        train_data={
            "video_path": "data/rabbit", "prompt": "a rabbit is jumping",
            "n_sample_frames": 2, "width": 16, "height": 16,
        },
        validation_data={
            "prompts": ["a origami rabbit"], "num_inference_steps": 2,
            "num_inv_steps": 2, "guidance_scale": 7.5, "use_inv_latent": True,
        },
        max_train_steps=3, validation_steps=3, checkpointing_steps=3,
        tiny=True, mixed_precision="no", log_every=1,
    )
    return out


def test_stage1_artifacts(tuned_dir):
    # diffusers-layout pipeline dir + metrics + validation latents/samples
    assert os.path.isfile(os.path.join(tuned_dir, "model_index.json"))
    assert os.path.isdir(os.path.join(tuned_dir, "unet"))
    sched_cfg = json.load(
        open(os.path.join(tuned_dir, "scheduler", "scheduler_config.json"))
    )
    assert sched_cfg["steps_offset"] == 1
    metrics = [json.loads(l) for l in open(os.path.join(tuned_dir, "metrics.jsonl"))]
    assert [m["step"] for m in metrics] == [1, 2, 3]
    assert os.path.isdir(os.path.join(tuned_dir, "inv_latents"))


def test_stage2_fast_edit_with_blend(tuned_dir):
    from videop2p_tpu.cli.run_videop2p import main as p2p

    # pass the UNSUFFIXED experiment root: the resolver must find the
    # suffixed pipeline dir Stage-1 wrote
    base = tuned_dir.rsplit("_dependent", 1)[0]
    inv_gif, edit_gif = p2p(
        pretrained_model_path=base,
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
        save_name="origami", is_word_swap=False,
        blend_word=["rabbit", "rabbit"],
        eq_params={"words": ["origami"], "values": [2.0]},
        video_len=2, fast=True, tiny=True,
    )
    assert os.path.isfile(inv_gif) and os.path.isfile(edit_gif)
    assert tuned_dir in edit_gif  # results land inside the suffixed dir


def test_stage2_no_blend_path(tuned_dir):
    """bird-forest style edit: refine controller, custom replace ratios, NO
    LocalBlend (configs/bird-forest-p2p.yaml has no blend_word)."""
    from videop2p_tpu.cli.run_videop2p import main as p2p

    inv_gif, edit_gif = p2p(
        pretrained_model_path=tuned_dir,  # already-suffixed dir also works
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a crochet rabbit is jumping"],
        save_name="crochet", is_word_swap=False,
        cross_replace_steps=0.8, self_replace_steps=0.7,
        video_len=2, fast=True, tiny=True,
    )
    assert os.path.isfile(edit_gif)
