"""Fleet telemetry plane tests (ISSUE 17).

Layers under test, bottom up:

  * ``obs/tsdb.py`` — the bounded ring-buffer store: caller-injected
    monotonic clocks, explicit NaN gaps, aligned-window queries,
    counter-reset-aware rates, snapshot/sidecar round-trip;
  * ``obs/prom.py`` — ``parse_prometheus`` round-trips our own
    exposition (and the live engine's/router's) back to the exact
    ``/metrics`` JSON scalars;
  * ``obs/signals.py`` — the multi-window burn-rate semantics (fast-only
    must NOT page), Theil–Sen trends, saturation, EWMA anomalies,
    per-tenant demand metering, grow/hold/shrink advice;
  * ``serve/collector.py`` — the scrape loop against live HTTP targets
    in both formats, and dead-target gap recording;
  * the verdict/rendering plumbing — SIGNAL_RULES obs_diff teeth,
    rotation x history cross-segment extraction, tools/fleet_dash.py;
  * THE acceptance: a 2-replica fleet under loadgen with the collector
    riding along — healthy run holds with zero burn alerts, a chaos run
    burns both windows, flips the advice to grow and regresses against
    the healthy baseline through obs_diff.
"""

import importlib.util
import json
import math
import os
import threading
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_fleet_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- tsdb -----


def test_tsdb_monotonic_clock_gaps_and_window_queries():
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    ts = TimeSeriesStore(capacity=8)
    lab = {"replica": "replica0"}
    assert ts.add("queue_depth", 1.0, 2.0, lab)
    assert ts.add("queue_depth", 2.0, 4.0, lab)
    # non-monotonic timestamps are DROPPED and counted, never reordered
    assert not ts.add("queue_depth", 2.0, 9.0, lab)   # equal t
    assert not ts.add("queue_depth", 1.5, 9.0, lab)   # backwards t
    assert not ts.add("queue_depth", 3.0, "nope", lab)  # unfloatable
    assert ts.dropped == 3
    assert ts.series("queue_depth", lab) == [(1.0, 2.0), (2.0, 4.0)]
    # an explicit gap keeps the time axis honest but is skipped by queries
    assert ts.gap("queue_depth", 3.0, lab)
    assert ts.add("queue_depth", 4.0, 6.0, lab)
    assert ts.gaps == 1
    assert ts.window("queue_depth", 4.0, 3.5, lab) == [
        (1.0, 2.0), (2.0, 4.0), (4.0, 6.0)]
    # window alignment is (now - w, now]: t=1.0 falls OUT at window 3.0
    assert ts.window("queue_depth", 4.0, 3.0, lab) == [(2.0, 4.0), (4.0, 6.0)]
    assert ts.mean("queue_depth", 4.0, 3.0, lab) == pytest.approx(5.0)
    assert ts.vmax("queue_depth", 4.0, 3.0, lab) == 6.0
    # latest skips trailing gaps; empty windows are None, never 0
    ts.gap("queue_depth", 5.0, lab)
    assert ts.latest("queue_depth", lab) == (4.0, 6.0)
    assert ts.mean("queue_depth", 100.0, 1.0, lab) is None
    # label identity: same name, different labels = a different series
    ts.add("queue_depth", 1.0, 7.0, {"replica": "replica1"})
    assert len(ts) == 2
    assert ts.labelsets("queue_depth") == [
        {"replica": "replica0"}, {"replica": "replica1"}]
    # the ring is bounded: capacity 8 evicts the oldest, samples stay flat
    for i in range(20):
        ts.add("queue_depth", 10.0 + i, 1.0, lab)
    assert len(ts.series("queue_depth", lab)) == 8


def test_tsdb_counter_reset_rate_and_nearest_rank_quantile():
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    ts = TimeSeriesStore()
    # a counter that restarts mid-window: 10 -> 14 (+4), reset to 3 (+3
    # post-reset, the Prometheus treatment), 3 -> 8 (+5) = 12 total
    for t, v in [(1.0, 10.0), (2.0, 14.0), (3.0, 3.0), (4.0, 8.0)]:
        ts.add("requests_total", t, v)
    assert ts.increase("requests_total", 4.0, 10.0) == pytest.approx(12.0)
    assert ts.rate("requests_total", 4.0, 10.0) == pytest.approx(12.0 / 3.0)
    # < 2 samples in window -> None (no fake zero-rates)
    assert ts.increase("requests_total", 4.0, 0.5) is None
    # nearest-rank quantiles over the window
    ts2 = TimeSeriesStore()
    for i, v in enumerate([5.0, 1.0, 3.0, 2.0, 4.0]):
        ts2.add("lat", float(i), v)
    assert ts2.quantile("lat", 10.0, 20.0, 50) == 3.0
    assert ts2.quantile("lat", 10.0, 20.0, 100) == 5.0
    assert ts2.quantile("lat", 10.0, 20.0, 0) == 1.0


def test_tsdb_snapshot_sidecar_roundtrip_and_restore(tmp_path):
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.tsdb import (
        FLEET_SERIES_FIELDS,
        TimeSeriesStore,
        load_series_sidecar,
        restore_store,
    )

    ts = TimeSeriesStore(capacity=512)
    for i in range(10):
        ts.add("up", float(i), 1.0, {"replica": "replica0"})
        ts.add("queue_depth", float(i), float(i % 3), {"replica": "replica0"})
    ts.gap("queue_depth", 10.0, {"replica": "replica0"})
    path = str(tmp_path / "ledger.jsonl")
    sidecar = str(tmp_path / "series.npz")
    with RunLedger(path) as led:
        rec = ts.snapshot(led, label="fleet", sidecar_path=sidecar)
    assert set(rec) == set(FLEET_SERIES_FIELDS)
    assert rec["series"] == 2 and rec["gaps"] == 1
    assert rec["t_first"] == 0.0 and rec["t_last"] == 10.0
    events = [e for e in read_ledger(path) if e["event"] == "fleet_series"]
    assert len(events) == 1 and events[0]["sidecar"] == rec["sidecar"]
    # sidecar round-trip preserves every sample INCLUDING the NaN gap
    series = load_series_sidecar(rec["sidecar"])
    key = 'queue_depth{replica="replica0"}'
    assert key in series and len(series[key]) == 11
    assert math.isnan(series[key][-1][1])
    # and restore_store rebuilds a queryable store offline
    ts2 = restore_store(rec["sidecar"])
    assert ts2.latest("queue_depth", {"replica": "replica0"}) == (9.0, 0.0)
    assert ts2.samples == ts.samples
    # downsampling keeps the NEWEST sample exactly
    big = TimeSeriesStore(capacity=600)
    for i in range(600):
        big.add("x", float(i), float(i))
    arrays, keys = big.snapshot_arrays(max_points=100)
    assert keys == ["x"] and len(arrays["s0_v"]) <= 100
    assert arrays["s0_t"][-1] == 599.0 and arrays["s0_v"][-1] == 599.0


def test_theil_sen_slope_robust_to_outliers():
    from videop2p_tpu.obs.signals import theil_sen_slope

    pts = [(float(i), 2.0 * i + 1.0) for i in range(20)]
    assert theil_sen_slope(pts) == pytest.approx(2.0)
    # one wild outlier scrape cannot fake (or hide) the trend
    spiked = list(pts)
    spiked[10] = (10.0, 1e6)
    assert theil_sen_slope(spiked) == pytest.approx(2.0, abs=0.2)
    assert theil_sen_slope([]) == 0.0
    assert theil_sen_slope([(1.0, 5.0)]) == 0.0
    assert theil_sen_slope([(1.0, 5.0), (1.0, 9.0)]) == 0.0  # dt <= 0 only


# ------------------------------------------------- prometheus parse -----


def test_parse_prometheus_roundtrip_escapes_and_nonfinite():
    from videop2p_tpu.obs.prom import (
        parse_prometheus,
        render_prometheus,
        samples_by_name,
    )

    metrics = {
        "queue_depth": 3,
        "store": {"hit_rate": 0.75},
        "requests": {"done": 9, "error": 1},
        "tenants": {"team a": {"submitted": 4}},   # space in label value
        "nan_gauge": float("nan"),
        "inf_gauge": float("inf"),
    }
    text = render_prometheus(metrics)
    parsed = parse_prometheus(text)
    by = samples_by_name(parsed)
    assert by["videop2p_queue_depth"][0]["value"] == 3.0
    assert by["videop2p_store_hit_rate"][0]["value"] == 0.75
    done = [s for s in by["videop2p_requests_total"]
            if s["labels"] == {"status": "done"}]
    assert done[0]["value"] == 9.0
    assert by["videop2p_tenant_submitted"][0]["labels"] == {
        "tenant": "team a"}
    assert math.isnan(by["videop2p_nan_gauge"][0]["value"])
    assert by["videop2p_inf_gauge"][0]["value"] == float("inf")
    # HELP/TYPE comments are collected per metric (format conformance)
    assert parsed["types"]["videop2p_queue_depth"] == "gauge"
    assert "gauge" in parsed["help"]["videop2p_queue_depth"]
    # label ESCAPES round-trip: backslash, quote, newline
    tricky = 'm{k="a\\\\b\\"c\\nd"} 1\n'
    s = parse_prometheus(tricky)["samples"][0]
    assert s["labels"]["k"] == 'a\\b"c\nd'
    # malformed lines raise — a half-parsed scrape must not drop gauges
    with pytest.raises(ValueError):
        parse_prometheus("not a metric line at all{\n")


# ---------------------------------------------------------- signals -----


def _seed_requests(ts, now, *, errors_recent=0, errors_old=0, done=20):
    """A replica whose done-counter rises 1/s for `now` seconds; error
    counter rises `errors_old` early and `errors_recent` in the last 2s."""
    from videop2p_tpu.obs.signals import S_REQUESTS, S_UP

    lab = {"replica": "replica0"}
    err = 0.0
    for i in range(int(now) + 1):
        t = float(i)
        ts.add(S_UP, t, 1.0, lab)
        ts.add(S_REQUESTS, t, min(float(i), float(done)),
               {**lab, "status": "done"})
        if i < 3:
            err += errors_old / 3.0
        if i > now - 2:
            err += errors_recent / 2.0
        ts.add(S_REQUESTS, t, err, {**lab, "status": "error"})


def test_burn_alert_requires_both_windows():
    """THE multi-window semantic: a fast-window error spike alone (noisy)
    must NOT page; sustained errors burn both windows and do."""
    from videop2p_tpu.obs.signals import SignalEngine
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    # window_scale 0.01 -> fast 3 s, slow 36 s
    ts = TimeSeriesStore()
    eng = SignalEngine(ts, window_scale=0.01)
    # 30 s of history: clean everywhere except 2 errors in the last 2 s —
    # fast window burns hard, slow window only mildly (2/30 ≈ 6.7% > 1%
    # would still burn... use a tighter spike: 0.2 errors => slow 0.7%)
    _seed_requests(ts, 30, errors_recent=0.2)
    rec = eng.evaluate(30.0)
    assert rec["burn_fast"] > 1.0          # the spike floods the fast window
    assert rec["burn_slow"] < 1.0          # but the hour-equivalent shrugs
    assert rec["burn_alert"] is False      # -> nobody is paged
    assert rec["burn_alerts"] == 0
    assert rec["scale_advice"] == "hold"
    # sustained failure: errors throughout -> both windows burn -> alert
    ts2 = TimeSeriesStore()
    eng2 = SignalEngine(ts2, window_scale=0.01)
    _seed_requests(ts2, 30, errors_recent=2, errors_old=6)
    rec2 = eng2.evaluate(30.0)
    assert rec2["burn_fast"] > 1.0 and rec2["burn_slow"] > 1.0
    assert rec2["burn_alert"] is True and rec2["burn_alerts"] == 1
    assert rec2["scale_advice"] == "grow"
    assert any("slo-burn" in r for r in rec2["reasons"])
    # cumulative across evaluations (the run roll-up obs_diff gates)
    rec3 = eng2.evaluate(30.5)
    assert rec3["burn_alerts"] == 2
    assert eng2.summary()["burn_alerts"] == 2
    assert eng2.summary()["advice"]["grow"] == 2


def test_advice_shrink_only_when_fully_idle_and_down_replica_grows():
    from videop2p_tpu.obs.signals import (
        S_IN_FLIGHT,
        S_QUEUE_DEPTH,
        S_UP,
        SignalEngine,
    )
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    ts = TimeSeriesStore()
    eng = SignalEngine(ts, window_scale=0.01)
    for i in range(10):
        t = float(i)
        for r in ("replica0", "replica1"):
            lab = {"replica": r}
            ts.add(S_UP, t, 1.0, lab)
            ts.add(S_QUEUE_DEPTH, t, 0.0, lab)
            ts.add(S_IN_FLIGHT, t, 0.0, lab)
    rec = eng.evaluate(9.0)
    assert rec["replicas_up"] == 2 and rec["replicas_total"] == 2
    assert rec["scale_advice"] == "shrink"      # idle across the slow window
    assert any("idle" in r for r in rec["reasons"])
    # ONE in-flight sample anywhere in the window blocks the shrink
    ts.add(S_IN_FLIGHT, 9.5, 1.0, {"replica": "replica1"})
    assert eng.evaluate(9.6)["scale_advice"] == "hold"
    # a replica going dark (trailing gaps) counts DOWN and advises grow
    ts.gap(S_UP, 10.0, {"replica": "replica0"})
    ts.add(S_UP, 10.0, 1.0, {"replica": "replica1"})
    rec = eng.evaluate(10.1)
    assert rec["replicas_up"] == 1 and rec["replicas_total"] == 2
    assert rec["scale_advice"] == "grow"
    assert any("replicas down 1/2" in r for r in rec["reasons"])


def test_saturation_tenant_demand_and_ewma_anomaly():
    from videop2p_tpu.obs.signals import (
        S_DISPATCH_P50,
        S_LATENCY_P99,
        S_QUEUE_WAIT_P99,
        S_TENANT,
        S_UP,
        SignalEngine,
    )
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    ts = TimeSeriesStore()
    eng = SignalEngine(ts, window_scale=0.01)
    lab = {"replica": "replica0"}
    for i in range(12):
        t = float(i)
        ts.add(S_UP, t, 1.0, lab)
        ts.add(S_DISPATCH_P50, t, 0.1, lab)
        # queue-wait p99 6x the dispatch p50 -> saturation 6 > threshold 5
        ts.add(S_QUEUE_WAIT_P99, t, 0.6, lab)
        ts.add(S_LATENCY_P99, t, 0.5, lab)
        # tenant A: submitted/done climb 2/s, 1/s; 3 sheds total
        ts.add(S_TENANT, t, 2.0 * i, {**lab, "tenant": "A",
                                      "field": "submitted"})
        ts.add(S_TENANT, t, 1.0 * i, {**lab, "tenant": "A", "field": "done"})
        ts.add(S_TENANT, t, min(float(i), 3.0), {**lab, "tenant": "A",
                                                 "field": "shed"})
    rec = eng.evaluate(11.0)
    assert rec["saturation"] == pytest.approx(6.0)
    assert rec["scale_advice"] == "grow"
    assert any("saturation" in r for r in rec["reasons"])
    lane = rec["tenants"]["A"]
    assert lane["submitted_rate"] == pytest.approx(2.0)
    assert lane["served_rate"] == pytest.approx(1.0)
    assert lane["shed_rate"] > 0.0
    # device-seconds = served increase x dispatch p50 = 11 * 0.1
    assert lane["device_seconds"] == pytest.approx(1.1)
    # EWMA anomaly: a stable latency baseline, then a 10x step -> flagged
    # exactly at the step (flag-then-update, >= 3 warmup observations)
    flags = []
    for i in range(8):
        ts.add(S_LATENCY_P99, 12.0 + i, 0.5 if i < 6 else 5.0, lab)
        flags.append(eng.evaluate(12.0 + i)["latency_anomaly"])
    assert flags[:6] == [False] * 6
    assert flags[6] is True


# -------------------------------------------------------- collector -----


class _FakeEngineMetrics:
    """A stdlib HTTP stand-in for an engine's /healthz + /metrics (both
    formats) — lets the collector tests drive scrapes deterministically
    and then KILL the target to pin gap recording."""

    def __init__(self):
        import http.server

        self.metrics = {
            "queue_depth": 2,
            "in_flight": 1,
            "request_latency": {"blocked_p50_s": 0.2, "blocked_p99_s": 0.9},
            "programs": {"serve_queue_wait": {"blocked_p99_s": 0.3},
                         "serve_dispatch": {"blocked_p50_s": 0.15}},
            "store": {"hit_rate": 0.5},
            "requests": {"done": 7, "error": 1},
            "tenants": {"A": {"submitted": 5, "done": 4, "shed": 1}},
        }
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    body = json.dumps({"ok": True}).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    if "format=prometheus" in self.path:
                        from videop2p_tpu.obs.prom import render_prometheus

                        body = render_prometheus(outer.metrics).encode()
                        ctype = "text/plain"
                    else:
                        body = json.dumps(outer.metrics).encode()
                        ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=5.0)


def test_collector_json_prometheus_equivalence_and_dead_target_gaps():
    """Both scrape formats land IDENTICAL scalars in the tsdb, and a
    target dying mid-run records up=0 plus explicit gaps in every series
    it previously produced — never interpolated values."""
    from videop2p_tpu.obs.signals import S_QUEUE_DEPTH, S_SCRAPE_ERRORS, S_UP
    from videop2p_tpu.serve.collector import FleetCollector

    fake = _FakeEngineMetrics()
    try:
        stores = {}
        for fmt in ("json", "prometheus"):
            col = FleetCollector([("replica0", fake.url)], fmt=fmt,
                                 probe_timeout_s=5.0)
            assert col.scrape_once(now=1.0) == 1
            stores[fmt] = col.tsdb
        jkeys = stores["json"].keys()
        assert jkeys == stores["prometheus"].keys()
        assert len(jkeys) >= 12  # gauges + statuses + tenant fields + meta
        for key in jkeys:
            name, items = key
            jv = stores["json"].latest(name, dict(items))
            pv = stores["prometheus"].latest(name, dict(items))
            assert jv[1] == pv[1], (key, jv, pv)
        # now the outage: scrape ok at t=1..2, target dies, scrape at t=3
        col = FleetCollector([("replica0", fake.url)], probe_timeout_s=5.0)
        assert col.scrape_once(now=1.0) == 1
        assert col.scrape_once(now=2.0) == 1
        seen_before = dict(col.tsdb._series)
        fake.close()
        assert col.scrape_once(now=3.0) == 0
        lab = {"replica": "replica0"}
        assert col.tsdb.series(S_UP, lab)[-1] == (3.0, 0.0)
        # every previously-produced series got an explicit NaN gap
        q = col.tsdb.series(S_QUEUE_DEPTH, lab)
        assert q[-1][0] == 3.0 and math.isnan(q[-1][1])
        gapped = sum(1 for key, ring in col.tsdb._series.items()
                     if key in seen_before and math.isnan(ring[-1][1]))
        assert gapped == len(col.targets[0].seen) >= 10
        # scrape-health counters are first-class series the signals read
        assert col.tsdb.latest(S_SCRAPE_ERRORS, lab)[1] == 1.0
        assert col.scrape_errors == 1 and col.stats()["gaps"] >= 10
        # the signal pass sees the fleet degraded: replica down -> grow
        rec = col.evaluate(now=3.1)
        assert rec["replicas_up"] == 0 and rec["replicas_total"] == 1
        assert rec["scale_advice"] == "grow"
        assert rec["scrape_error_rate"] > 0.0
        assert list(col.history)[-1] is rec
    finally:
        fake.close()


def test_collector_background_thread_scrapes_on_interval():
    from videop2p_tpu.serve.collector import FleetCollector

    fake = _FakeEngineMetrics()
    try:
        col = FleetCollector([("replica0", fake.url)], interval_s=0.02,
                             window_scale=0.001, probe_timeout_s=5.0)
        col.start()
        deadline = time.perf_counter() + 10.0
        while col.scrapes < 3 and time.perf_counter() < deadline:
            time.sleep(0.02)
        col.stop(final_evaluate=True)
        assert col.scrapes >= 3 and col.scrape_errors == 0
        assert col.signals.evaluations >= 1 and len(col.history) >= 1
        # wall-clock scrapes: strictly monotonic timestamps per series
        up = col.tsdb.series("up", {"replica": "replica0"})
        assert all(a[0] < b[0] for a, b in zip(up, up[1:]))
    finally:
        fake.close()


def test_collector_rejects_unknown_format():
    from videop2p_tpu.serve.collector import FleetCollector

    with pytest.raises(ValueError, match="json.*prometheus"):
        FleetCollector([("a", "http://127.0.0.1:1")], fmt="xml")


# ------------------------------------- verdicts, rotation, dashboard ----


def _signals_ledger(path, label="fleet", *, alerts=0, saturation=0.5,
                    advice="hold"):
    """A minimal collector-shaped ledger: N fleet_signals evaluations
    whose LAST event carries the run roll-up obs_diff extracts."""
    from videop2p_tpu.obs import RunLedger
    from videop2p_tpu.obs.signals import FLEET_SIGNALS_FIELDS

    base = {k: 0.0 for k in FLEET_SIGNALS_FIELDS}
    base.update(label=label, window_scale=0.01, fast_window_s=3.0,
                slow_window_s=36.0, burn_alert=False, latency_anomaly=False,
                store_hit_anomaly=False, replicas_up=2, replicas_total=2,
                tenants={}, scale_advice="hold", reasons=[])
    with RunLedger(path) as led:
        # only the last event is the roll-up; earlier ones are superseded
        for i in range(3):
            rec = dict(base, t=float(i), burn_alerts=min(i, alerts),
                       saturation=saturation,
                       scale_advice=advice if i == 2 else "hold",
                       burn_alert=bool(alerts) and i == 2)
            led.event("fleet_signals", **rec)
    return path


def test_obs_diff_signal_rules_teeth(tmp_path, capsys):
    """SIGNAL_RULES gate: self-compare exits 0, a burn-alert appearing
    (0 -> 1) or saturation doubling regresses with exit 1 and a
    machine-readable verdict naming the signal."""
    healthy = _signals_ledger(str(tmp_path / "healthy.jsonl"))
    burned = _signals_ledger(str(tmp_path / "burned.jsonl"), alerts=1,
                             advice="grow")
    saturated = _signals_ledger(str(tmp_path / "sat.jsonl"), saturation=2.0)
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", healthy, healthy]) == 0
    capsys.readouterr()
    assert obs_diff.main(["obs_diff.py", healthy, burned]) == 1
    out = capsys.readouterr().out
    assert "burn_alerts" in out
    assert obs_diff.main(["obs_diff.py", healthy, saturated]) == 1
    assert "saturation" in capsys.readouterr().out
    # teeth point the right way: burning -> healthy is an improvement
    assert obs_diff.main(["obs_diff.py", burned, healthy]) == 0


def test_rotation_history_cross_segment_signals_extraction(tmp_path):
    """ISSUE 17 satellite: a rotated collector ledger (PR-14 segments)
    still extracts one coherent run — events stranded in .N.jsonl
    segments (the early serve_health, the first evaluations) replay
    through the chain, and the LAST fleet_signals event wins."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import RunHistory, extract_run, split_runs
    from videop2p_tpu.obs.signals import FLEET_SIGNALS_FIELDS

    path = str(tmp_path / "collector.jsonl")
    base = {k: 0.0 for k in FLEET_SIGNALS_FIELDS}
    base.update(label="fleet", burn_alert=False, latency_anomaly=False,
                store_hit_anomaly=False, tenants={}, scale_advice="hold",
                reasons=[], replicas_up=2, replicas_total=2)
    with RunLedger(path, max_bytes=2000) as led:
        led.event("serve_health", requests=8, done=8, errors=0,
                  error_rate=0.0)   # early event -> oldest segment
        for i in range(30):
            led.event("fleet_signals", **dict(
                base, t=float(i), burn_alerts=float(i),
                tenants={"A": {"submitted_rate": float(i), "served_rate": 0.0,
                               "shed_rate": 0.0, "device_seconds": 0.0}}))
    rotated = sorted(tmp_path.glob("collector.*.jsonl"))
    assert rotated, "no rotation happened — lower max_bytes"
    # the chain replays as ONE run...
    hist = RunHistory.scan(str(tmp_path))
    assert len(hist.runs) == 1
    events = read_ledger(path)
    assert sum(e["event"] == "fleet_signals" for e in events) == 30
    rec = extract_run(split_runs(events)[-1])
    # ...with the LAST evaluation (written to the LIVE file) as the
    # roll-up AND the rotated-out early serve_health still extracted
    assert rec["signals"]["fleet"]["burn_alerts"] == 29.0
    assert rec["signals"]["fleet:tenant:A"]["submitted_rate"] == 29.0
    assert rec["reliability"]["serve"]["requests"] == 8.0


def test_fleet_dash_renders_self_contained_html(tmp_path):
    from videop2p_tpu.obs import RunLedger
    from videop2p_tpu.obs.signals import (
        S_IN_FLIGHT,
        S_QUEUE_DEPTH,
        S_REQUESTS,
        S_UP,
        SignalEngine,
    )
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    fleet_dash = _load_tool("fleet_dash")
    path = str(tmp_path / "collector.jsonl")
    ts = TimeSeriesStore()
    eng = SignalEngine(ts, window_scale=0.01)
    with RunLedger(path) as led:
        for i in range(20):
            t = float(i)
            for r in ("replica0", "replica1"):
                lab = {"replica": r}
                if r == "replica1" and 8 <= i < 14:
                    ts.gap(S_UP, t, lab)       # an outage window
                else:
                    ts.add(S_UP, t, 1.0, lab)
                ts.add(S_QUEUE_DEPTH, t, float(i % 4), lab)
                ts.add(S_IN_FLIGHT, t, 1.0, lab)
                ts.add(S_REQUESTS, t, float(i), {**lab, "status": "done"})
                ts.add(S_REQUESTS, t, float(i) * 0.5,
                       {**lab, "status": "error"})
            if i % 4 == 3:
                eng.evaluate(t, ledger=led)
        ts.snapshot(led, label="fleet",
                    sidecar_path=str(tmp_path / "series.npz"))
    out = fleet_dash.write_dash(path)
    assert out.endswith("_fleet.html") and os.path.isfile(out)
    html_text = open(out).read()
    assert html_text.startswith("<!doctype html>")
    for marker in ("Burn gauges", "Scale advice", "Series", "<svg", "gaps"):
        assert marker in html_text, marker
    # the sidecar sparklines made it in (one row per stored series)
    assert html_text.count('queue_depth{replica=') == 2
    # the CLI wrapper and --out/--title flags
    custom = str(tmp_path / "custom.html")
    assert fleet_dash.main(["fleet_dash", path, "--out", custom,
                            "--title", "My fleet"]) == 0
    assert "<h1>My fleet</h1>" in open(custom).read()
    assert fleet_dash.main(["fleet_dash"]) == 2          # usage error
    assert fleet_dash.main(["fleet_dash", str(tmp_path / "nope.jsonl")]) == 2
    # a signals-only ledger (no snapshot) and an empty ledger both render
    from videop2p_tpu.obs import RunLedger as _RL

    bare = str(tmp_path / "bare.jsonl")
    with _RL(bare) as led:
        eng.evaluate(99.0, ledger=led)
    assert "Burn gauges" in fleet_dash.render_dash(
        __import__("videop2p_tpu.obs.ledger", fromlist=["read_ledger"]
                   ).read_ledger(bare))
    empty = str(tmp_path / "empty.jsonl")
    with _RL(empty):
        pass
    assert "no fleet_signals" in fleet_dash.render_dash(
        __import__("videop2p_tpu.obs.ledger", fromlist=["read_ledger"]
                   ).read_ledger(empty))


def test_loadgen_collector_flag_validation():
    loadgen = _load_tool("serve_loadgen")
    with pytest.raises(SystemExit):
        loadgen.main(["--inproc", "--collector"])


# ------------------------------------------- live fleet (tiny, CPU) -----

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)
_PROMPTS = ("a rabbit is jumping", "a origami rabbit is jumping")


@pytest.fixture(scope="module")
def programs():
    """One warm tiny ProgramSet shared by every fleet in this module."""
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps.warm(_PROMPTS, batch_sizes=(2,))
    return ps


def _request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt=_PROMPTS[0],
              prompts=list(_PROMPTS), save_name="fleet")
    kw.update(overrides)
    return EditRequest(**kw)


def _fleet_loadgen_run(programs, root, *, faults=None, seed=71):
    """A 2-replica fleet + router + riding FleetCollector, driven by the
    loadgen closed loop — the exact composition tools/serve_loadgen.py
    --router N --collector wires up."""
    from videop2p_tpu.serve import ReplicaSupervisor, Router, RouterServer
    from videop2p_tpu.serve.collector import FleetCollector

    loadgen = _load_tool("serve_loadgen")
    sup = ReplicaSupervisor(
        programs.spec, 2, out_dir=root, programs=programs,
        warm_prompts=_PROMPTS,
        engine_kwargs=dict(max_retries=0, breaker_threshold=1,
                           breaker_open_s=60.0),
        faults=faults or {},
    )
    sup.start()
    router = Router(sup.urls, probe_ttl_s=0.05, suspend_s=5.0)
    server = RouterServer(router).start()
    # tiny CPU engines legitimately run queue-wait p99 tens of times the
    # dispatch p50 under a closed loop, and a 10 s burst makes any queue
    # trend pure noise — with the default thresholds every run would pin
    # "grow" and mask the burn/advice teeth this acceptance is about, so
    # raise both policy knobs out of the way
    collector = FleetCollector(
        [(r.name, r.url) for r in sup.replicas] + [("router", server.url)],
        interval_s=0.05, window_scale=0.02,   # fast 6 s / slow 72 s
        signal_kwargs=dict(saturation_threshold=100.0,
                           queue_slope_threshold=10.0),
    )
    collector.start()
    ledger_path = os.path.join(root, "loadgen.jsonl")
    try:
        def collect_extra(record):
            events = []
            for r in sup.replicas:
                events += [dict(e) for e in r.engine.fault_log]
                events.append({"event": "serve_health", "label": r.name,
                               **r.engine.health_record()})
                events += [{"event": "cost_attribution", "label": r.name,
                            **row} for row in r.engine.cost_records()]
            events.append({"event": "router_health",
                           **router.health_record()})
            collector.stop(final_evaluate=True)
            events += [{"event": "fleet_signals", **rec}
                       for rec in collector.history]
            snap = collector.snapshot(
                label="fleet",
                sidecar_path=os.path.join(root, "fleet_series.npz"))
            events.append({"event": "fleet_series", **snap})
            record["signals"] = {**collector.signals.summary(),
                                 **collector.stats()}
            return events

        record = loadgen.run_loadgen(
            loadgen._HttpTarget(server.url, timeout_s=300.0),
            _request(seed=seed).to_dict(),
            requests=8, concurrency=2, ledger_path=ledger_path,
            meta={"target": "fleet-collector"}, collect_extra=collect_extra,
        )
    finally:
        collector.stop(final_evaluate=False)
        server.close()
        sup.stop()
    return record, ledger_path


def test_live_exposition_roundtrip_and_probe_age(programs, tmp_path):
    """ISSUE 17 satellites on LIVE surfaces: the engine's and router's
    prometheus expositions parse back to the exact /metrics JSON scalars
    (# HELP/# TYPE conformance included), and the router's per-replica
    metrics carry the probe_age_s staleness stamp."""
    from videop2p_tpu.obs.prom import parse_prometheus, samples_by_name
    from videop2p_tpu.serve import ReplicaSupervisor, Router, RouterServer
    from videop2p_tpu.serve.client import EngineClient

    sup = ReplicaSupervisor(programs.spec, 1, out_dir=str(tmp_path),
                            programs=programs, warm_prompts=_PROMPTS)
    sup.start()
    router = Router(sup.urls, probe_ttl_s=0.05)
    server = RouterServer(router).start()
    try:
        eng = sup.replicas[0].engine
        rec = eng.result(eng.submit(_request(seed=70)), wait_s=300.0)
        assert rec["status"] == "done", rec.get("error")
        client = EngineClient(sup.replicas[0].url)
        metrics = client.metrics()
        parsed = parse_prometheus(client.metrics_prometheus())
        by = samples_by_name(parsed)
        assert by["videop2p_queue_depth"][0]["value"] == float(
            metrics["queue_depth"])
        assert by["videop2p_store_hit_rate"][0]["value"] == float(
            metrics["store"]["hit_rate"])
        done = [s for s in by["videop2p_requests_total"]
                if s["labels"] == {"status": "done"}]
        assert done[0]["value"] == float(metrics["requests"]["done"])
        # every rendered metric is HELP/TYPE-annotated
        for name in by:
            assert parsed["types"][name] == "gauge"
            assert name in parsed["help"]
        # the router: same round-trip + the probe staleness stamp
        rclient = EngineClient(server.url)
        rclient.healthz()   # force a probe so the cache has an age
        rmetrics = rclient.metrics()
        view = rmetrics["replicas"]["replica0"]
        assert "probe_age_s" in view
        assert view["probe_age_s"] is not None and view["probe_age_s"] >= 0.0
        rby = samples_by_name(parse_prometheus(rclient.metrics_prometheus()))
        assert rby["videop2p_replica_probe_age_s"][0]["labels"] == {
            "replica": "replica0"}
    finally:
        server.close()
        sup.stop()


def test_fleet_collector_acceptance_healthy_vs_chaos(programs, tmp_path):
    """THE ISSUE 17 acceptance: a healthy 2-replica loadgen run records
    ZERO burn alerts and holds; the same run with replica 0 in an
    unavailable fault window fires fast+slow burn, flips the advice to
    grow while degraded, and REGRESSES against the healthy baseline
    through obs_diff's SIGNAL_RULES; both ledgers render to HTML
    dashboards."""
    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs

    healthy_root = str(tmp_path / "healthy")
    chaos_root = str(tmp_path / "chaos")
    os.makedirs(healthy_root)
    os.makedirs(chaos_root)
    h_record, h_ledger = _fleet_loadgen_run(programs, healthy_root, seed=71)
    c_record, c_ledger = _fleet_loadgen_run(
        programs, chaos_root, faults={0: "unavail@1-999"}, seed=72)

    # healthy: everything served, no burn, the final advice is hold
    assert h_record["done"] == 8 and h_record["errors"] == 0
    assert h_record["signals"]["evaluations"] >= 2
    assert h_record["signals"]["burn_alerts"] == 0
    h_events = [e for e in read_ledger(h_ledger)
                if e["event"] == "fleet_signals"]
    assert h_events and h_events[-1]["scale_advice"] == "hold"
    assert all(not e["burn_alert"] for e in h_events)
    # the scrape loop genuinely watched all three surfaces
    assert h_record["signals"]["targets"] == 3
    assert h_record["signals"]["scrape_errors"] == 0
    assert h_events[-1]["replicas_up"] == 2
    # ISSUE 19: the scraped cost plane PRICED the advice — the roll-up
    # carries measured utilization and at least one evaluation cites an
    # economic reason (hold/shrink annotations or a priced grow)
    assert h_events[-1]["utilization"] is not None
    assert any(("economics" in r) or ("shrink-is-cheap" in r)
               for e in h_events for r in e["reasons"])
    # the replicas' chargeback rows rode collect_extra into the ledger
    h_costs = [e for e in read_ledger(h_ledger)
               if e.get("event") == "cost_attribution"]
    assert {e["scope"] for e in h_costs} >= {"engine", "tenant"}

    # chaos: replica 0's doomed dispatches burned BOTH windows at least
    # once and the advice flipped to grow while degraded
    assert c_record["errors"] >= 1
    assert c_record["signals"]["burn_alerts"] >= 1
    c_events = [e for e in read_ledger(c_ledger)
                if e["event"] == "fleet_signals"]
    burned = [e for e in c_events if e["burn_alert"]]
    assert burned, "no evaluation saw both windows burn"
    assert burned[0]["burn_fast"] > 1.0 and burned[0]["burn_slow"] > 1.0
    assert burned[0]["scale_advice"] == "grow"
    assert any("slo-burn" in r for e in burned for r in e["reasons"])
    # the run roll-up (LAST event) carries the cumulative alert count
    assert c_events[-1]["burn_alerts"] == c_record["signals"]["burn_alerts"]

    # gates: self-compare clean, chaos-vs-healthy regresses on SIGNAL_RULES
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", h_ledger, h_ledger]) == 0
    assert obs_diff.main(["obs_diff.py", h_ledger, c_ledger]) == 1
    sig = extract_run(split_runs(read_ledger(c_ledger))[-1])["signals"]
    assert sig["fleet"]["burn_alerts"] >= 1.0
    assert extract_run(split_runs(read_ledger(h_ledger))[-1])[
        "signals"]["fleet"]["burn_alerts"] == 0.0

    # both runs render through the dashboard to self-contained HTML
    fleet_dash = _load_tool("fleet_dash")
    for ledger in (h_ledger, c_ledger):
        out = fleet_dash.write_dash(ledger)
        text = open(out).read()
        assert text.startswith("<!doctype html>")
        assert "Burn gauges" in text and "Series" in text
