"""UNet3D model tests: shapes, inflation identity, control threading, stores.

Mirrors the test strategy recommended in SURVEY §4 (the reference ships no
tests): shape/equivariance tests for the UNet, the inflation-identity property
(with zero temporal attention the 3-D UNet is a per-frame 2-D UNet), and
controller behavior on live forwards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.control import make_controller
from videop2p_tpu.models import AttnControl, UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.utils.tokenizers import WordTokenizer


_apply_cache = {}


def apply(model, params, sample, t, text):
    """Jitted apply, cached per model so repeated same-shape calls hit the
    compile cache (eager linen apply dispatches hundreds of tiny kernels)."""
    key = id(model)
    if key not in _apply_cache:
        _apply_cache[key] = jax.jit(model.apply)
    return _apply_cache[key](params, sample, t, text)


@pytest.fixture(scope="module")
def tiny_unet():
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    B, F = 2, 4
    sample = jax.random.normal(jax.random.key(0), (B, F, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (B, 7, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(10), text)
    return model, params, sample, text


def test_forward_shape(tiny_unet):
    model, params, sample, text = tiny_unet
    out = apply(model, params, sample, jnp.asarray(10), text)
    assert out.shape == sample.shape
    assert jnp.isfinite(out).all()


def test_per_sample_timesteps(tiny_unet):
    model, params, sample, text = tiny_unet
    out = apply(model, params, sample, jnp.asarray([10, 20]), text)
    assert out.shape == sample.shape


def test_temporal_attention_zero_init(tiny_unet):
    """The temporal attention output projection must start at zero so
    inflation is the identity (reference attention.py:196-202)."""
    _, params, _, _ = tiny_unet
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    zero_kernels = [
        jax.tree_util.keystr(path)
        for path, leaf in flat
        if "attn_temp" in jax.tree_util.keystr(path)
        and "to_out" in jax.tree_util.keystr(path)
        and "kernel" in jax.tree_util.keystr(path)
        and not np.any(np.asarray(leaf))
    ]
    assert len(zero_kernels) > 0


def test_inflation_identity(tiny_unet):
    """With identical frames, frame-0-KV spatial attention equals per-frame
    self-attention and zero-init temporal attention contributes nothing — so
    every output frame must equal the single-frame (2-D) result
    (SURVEY §4: 'with zeroed temporal attn, 3-D UNet ≡ per-frame 2-D UNet')."""
    model, params, sample, text = tiny_unet
    one = sample[:, :1]
    rep = jnp.broadcast_to(one, sample.shape)
    out_rep = apply(model, params, rep, jnp.asarray(3), text)
    out_one = apply(model, params, one, jnp.asarray(3), text)
    np.testing.assert_allclose(np.asarray(out_rep[:, 2]), np.asarray(out_one[:, 0]), atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(out_rep[:, 0]), np.asarray(out_rep[:, -1]), atol=1e-4
    )


def test_attention_store_collection(tiny_unet):
    model, params, sample, text = tiny_unet
    out, store = jax.jit(
        lambda p, s, t, e: model.apply(p, s, t, e, mutable=["attn_store"])
    )(params, sample, jnp.asarray(10), text)
    leaves = jax.tree_util.tree_leaves(store)
    assert len(leaves) > 0
    # cross maps: (B·F, Q, L); temporal maps: (B·N, F, F)
    shapes = {leaf.shape for leaf in leaves}
    assert any(s[-1] == text.shape[1] for s in shapes), shapes
    assert any(s[-1] == sample.shape[1] for s in shapes), shapes


def test_control_threading(tiny_unet):
    """A live ControlContext changes the conditional streams' output but not
    the source stream (the conditional-half-only rule, run_videop2p.py:217-218
    — with the base stream's own maps left untouched)."""
    model, params, sample, text = tiny_unet
    tok = WordTokenizer()
    # batch layout: [uncond_src, uncond_edit, cond_src, cond_edit]
    ctx = make_controller(
        ["a cat runs", "a dog runs"],
        tok,
        num_steps=10,
        is_replace_controller=True,
        cross_replace_steps=1.0,
        self_replace_steps=1.0,
    )
    B = 4  # 2 (cfg) * 2 prompts
    F = sample.shape[1]
    smp = jnp.concatenate([sample, sample], axis=0)
    txt = jax.random.normal(jax.random.key(5), (B, 77, 16))
    params2 = jax.jit(model.init)(jax.random.key(2), smp, jnp.asarray(10), txt)
    control = AttnControl(ctx=ctx, step_index=jnp.asarray(0))
    jfwd = jax.jit(lambda p, s, t, e, c: model.apply(p, s, t, e, c))
    out_ctrl = jfwd(params2, smp, jnp.asarray(10), txt, control)
    out_free = jax.jit(lambda p, s, t, e: model.apply(p, s, t, e))(params2, smp, jnp.asarray(10), txt)
    assert out_ctrl.shape == out_free.shape
    # source-conditional stream (index 2) sees unedited attention
    np.testing.assert_allclose(
        np.asarray(out_ctrl[2]), np.asarray(out_free[2]), atol=1e-4
    )
    # edited-conditional stream (index 3) must differ (its attention was
    # replaced by the source stream's)
    assert not np.allclose(np.asarray(out_ctrl[3]), np.asarray(out_free[3]), atol=1e-4)


def test_gradient_checkpointing_matches(tiny_unet):
    model, params, sample, text = tiny_unet
    model_ckpt = UNet3DConditionModel(
        config=UNet3DConfig.tiny(gradient_checkpointing=True)
    )
    out = apply(model, params, sample, jnp.asarray(10), text)
    out_ckpt = jax.jit(model_ckpt.apply)(params, sample, jnp.asarray(10), text)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ckpt), atol=1e-5)


def test_transformer3d_per_frame_norm():
    """The transformer's input GroupNorm must normalize each frame separately
    (the reference folds frames into batch before the norm, attention.py:94-101).
    With per-frame stats, frame 0's output is independent of frame 1's content
    (frame-0 KV + zero-init temporal attention); cross-frame pooling would leak
    frame 1 into frame 0."""
    from videop2p_tpu.models import Transformer3DModel

    model = Transformer3DModel(heads=2, dim_head=4, norm_groups=2)
    x2 = jax.random.normal(jax.random.key(0), (1, 2, 4, 4, 8))
    ctx = jax.random.normal(jax.random.key(1), (1, 5, 8))
    params = jax.jit(model.init)(jax.random.key(2), x2, ctx)
    fwd = jax.jit(lambda p, x, c: model.apply(p, x, c))
    out2 = fwd(params, x2, ctx)
    out1 = fwd(params, x2[:, :1], ctx)
    np.testing.assert_allclose(np.asarray(out2[:, 0]), np.asarray(out1[:, 0]), atol=1e-5)


def test_unknown_block_type_raises(tiny_unet):
    model, params, sample, text = tiny_unet
    bad = UNet3DConditionModel(
        config=UNet3DConfig.tiny(down_block_types=("CrossAttnDownBlock3d", "DownBlock3D"))
    )
    with pytest.raises(ValueError, match="unknown down block type"):
        bad.init(jax.random.key(0), sample, jnp.asarray(1), text)


def test_sdxl_preset_shape_and_depths():
    """SDXL-shaped stress config (BASELINE config 4): 3 levels, per-block
    transformer depths (1, 2, 10), 2048-dim context, 64-wide heads."""
    cfg = UNet3DConfig.sdxl()
    assert cfg.block_out_channels == (320, 640, 1280)
    assert cfg.transformer_depth == (1, 2, 10)
    assert cfg.attention_head_dim == (5, 10, 20)
    assert cfg.cross_attention_dim == 2048
    assert cfg.down_block_types[0] == "DownBlock3D"  # no attention at level 0
    assert cfg.up_block_types[-1] == "UpBlock3D"


def test_sdxl_shaped_forward_and_torch_parity():
    """Width-scaled SDXL topology (same per-block depth/head structure) must
    run, and the converter must map the per-block transformer depths — the
    deep upper blocks have transformer_blocks.0..N keys per site."""
    import torch

    from tests.torch_ref import TorchUNet3D
    from videop2p_tpu.models.convert import unet3d_params_from_torch

    cfg = UNet3DConfig.sdxl(
        sample_size=8,
        block_out_channels=(8, 16, 32),
        attention_head_dim=(1, 2, 4),
        transformer_depth=(1, 2, 3),
        cross_attention_dim=16,
        norm_num_groups=4,
        layers_per_block=1,
    )
    torch.manual_seed(3)
    tmodel = TorchUNet3D(cfg).eval()
    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}
    # deep block: level-2 down attention carries 3 transformer blocks
    assert any("down_blocks.2.attentions.0.transformer_blocks.2." in k for k in sd)

    model = UNet3DConditionModel(config=cfg)
    B, F, S = 1, 2, 8
    x = np.random.RandomState(0).randn(B, F, S, S, cfg.in_channels).astype(np.float32)
    ctx = np.random.RandomState(1).randn(B, 7, cfg.cross_attention_dim).astype(np.float32)
    t = np.array([11], dtype=np.int32)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx))
    )["params"]
    params, report = unet3d_params_from_torch(sd, abstract)
    assert report["kept_init"] == [] and report["unused"] == []
    # jitted: the eager op-by-op apply of this wider config costs ~35 s of
    # dispatch overhead on the test host, and only jitted programs hit the
    # persistent compilation cache
    out_flax = jax.jit(model.apply)(
        {"params": params}, jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx)
    )
    with torch.no_grad():
        out_torch = tmodel(
            torch.tensor(np.transpose(x, (0, 4, 1, 2, 3))),
            torch.tensor(t), torch.tensor(ctx),
        )
    np.testing.assert_allclose(
        np.asarray(out_flax),
        np.transpose(out_torch.numpy(), (0, 2, 3, 4, 1)),
        atol=5e-5,
    )
