"""LocalBlend tests on synthetic attention maps
(reference semantics: /root/reference/run_videop2p.py:129-181)."""

import jax.numpy as jnp
import numpy as np

from videop2p_tpu.control import make_local_blend, local_blend
from videop2p_tpu.control.local_blend import _get_mask, _max_pool_3x3
from videop2p_tpu.utils.tokenizers import WordTokenizer
from videop2p_tpu.control.schedules import get_word_inds

P, F, S, R = 2, 2, 5, 16
HW = 32


def _cfg(start_blend=0.2, num_steps=50):
    tok = WordTokenizer()
    prompts = ["a rabbit is jumping", "a origami rabbit is jumping"]
    cfg = make_local_blend(
        prompts, (("rabbit",), ("origami", "rabbit")), tok, num_steps, start_blend=start_blend
    )
    word_inds = {
        "src_rabbit": get_word_inds(prompts[0], "rabbit", tok),
        "tgt_origami": get_word_inds(prompts[1], "origami", tok),
    }
    return cfg, word_inds


def _maps_with_hotspot(word_inds):
    """Cross-attn maps where the blend words attend to the top-left corner."""
    maps = np.full((P, F, S, R, R, 77), 1e-4, dtype=np.float32)
    maps[0, :, :, :4, :4, word_inds["src_rabbit"][0]] = 1.0
    maps[1, :, :, :4, :4, word_inds["tgt_origami"][0]] = 1.0
    return jnp.asarray(maps)


def test_alpha_layers_mark_blend_words():
    cfg, wi = _cfg()
    assert cfg.alpha_layers.shape == (P, 1, 77)
    assert cfg.alpha_layers[0, 0, wi["src_rabbit"][0]] == 1.0
    assert cfg.alpha_layers[1, 0, wi["tgt_origami"][0]] == 1.0
    assert cfg.alpha_layers.sum() == 3.0  # rabbit + (origami, rabbit)
    assert cfg.start_blend == 10


def test_mask_localizes_to_hotspot():
    cfg, wi = _cfg()
    maps = _maps_with_hotspot(wi)
    mask = _get_mask(maps, cfg.alpha_layers[:, 0, :], True, (HW, HW), cfg.th)
    mask = np.asarray(mask)
    assert mask.shape == (P, F, HW, HW)
    # hotspot (top-left quarter) is masked, bottom-right is not
    assert mask[:, :, :6, :6].all()
    assert not mask[:, :, 16:, 16:].any()


def test_blend_outside_mask_pulls_to_source():
    cfg, wi = _cfg()
    maps = _maps_with_hotspot(wi)
    x = jnp.asarray(np.random.RandomState(0).randn(P, F, HW, HW, 4).astype(np.float32))
    out = local_blend(x, maps, cfg, jnp.asarray(20))
    out = np.asarray(out)
    # source stream always unchanged
    np.testing.assert_allclose(out[0], np.asarray(x)[0], rtol=1e-6)
    # outside the mask the edit stream equals the source stream
    np.testing.assert_allclose(out[1, :, 20:, 20:], np.asarray(x)[0, :, 20:, 20:], rtol=1e-6)
    # inside the mask the edit stream is kept (x0 + (x1-x0) ≈ x1 up to fp assoc.)
    np.testing.assert_allclose(out[1, :, :4, :4], np.asarray(x)[1, :, :4, :4], rtol=1e-5, atol=1e-6)


def test_blend_inactive_before_start():
    cfg, wi = _cfg()
    maps = _maps_with_hotspot(wi)
    x = jnp.asarray(np.random.RandomState(1).randn(P, F, HW, HW, 4).astype(np.float32))
    out = local_blend(x, maps, cfg, jnp.asarray(5))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


def test_max_pool_window():
    x = jnp.zeros((1, 1, 5, 5)).at[0, 0, 2, 2].set(1.0)
    pooled = np.asarray(_max_pool_3x3(x))
    assert pooled[0, 0, 1:4, 1:4].min() == 1.0
    assert pooled[0, 0, 0, 0] == 0.0


def test_blend_maps_fallback_to_nearest_site():
    """When no cross site sits at the (latent/4)² default, the nearest square
    site is used (tiny UNets at small latents; cli smoke path)."""
    import jax.numpy as jnp

    from videop2p_tpu.pipelines.stores import blend_maps_from_store

    P, F, L = 2, 2, 77
    # store with sites at 8²=64 and 4²=16 queries only (tiny UNet at 8×8)
    store = {
        "down": {"attn2": {"maps": jnp.ones((2 * P * F, 64, L))}},
        "up": {"attn2": {"maps": jnp.ones((2 * P * F, 16, L))}},
    }
    out = blend_maps_from_store(
        store, latent_hw=(8, 8), video_length=F, num_prompts=P, text_len=L,
    )
    # default rule wants 2×2=4 queries; nearest available square is 16 → 4×4
    assert out.shape == (P, F, 1, 4, 4, L)

    # explicit blend_res still errors when absent
    import pytest

    with pytest.raises(ValueError, match="no cross-attention maps"):
        blend_maps_from_store(
            store, latent_hw=(8, 8), video_length=F, num_prompts=P, text_len=L,
            blend_res=(3, 3),
        )
