"""Fused one-pass GroupNorm: numerics vs flax nn.GroupNorm (the module the
UNet used through round 4) and the torch-semantics reference math.

The kernel runs in interpret mode on CPU (tests/conftest.py pins cpu);
the real Mosaic compile is exercised on-chip by bench.py's A/B.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from videop2p_tpu.ops.groupnorm import (
    fits_fused_group_norm,
    fused_group_norm,
    group_norm_reference,
)


def _flax_gn(x2, scale, bias, groups, eps):
    """nn.GroupNorm on (N, rows, C) with bound params."""
    mod = nn.GroupNorm(num_groups=groups, epsilon=eps, dtype=x2.dtype)
    return mod.apply({"params": {"scale": scale, "bias": bias}}, x2)


@pytest.mark.parametrize(
    "n,rows,c,groups",
    [
        (2, 256, 320, 32),   # 16²-site per-frame shape class
        (1, 512, 640, 32),
        (3, 256, 1280, 32),
        (2, 256, 96, 32),    # tiny-config widths (3 ch/group)
    ],
)
def test_fused_matches_flax_groupnorm(n, rows, c, groups):
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    x = jax.random.normal(k1, (n, rows, c), jnp.float32) * 2.0 + 0.5
    scale = jax.random.normal(k2, (c,)) * 0.2 + 1.0
    bias = jax.random.normal(k3, (c,)) * 0.1
    want = _flax_gn(x, scale, bias, groups, 1e-5)
    got = fused_group_norm(
        x, scale, bias, num_groups=groups, eps=1e-5, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_fused_bf16_matches_reference_math():
    k1, k2, k3 = jax.random.split(jax.random.key(1), 3)
    x = (jax.random.normal(k1, (2, 256, 320)) * 3).astype(jnp.bfloat16)
    scale = jax.random.normal(k2, (320,)).astype(jnp.float32)
    bias = jax.random.normal(k3, (320,)).astype(jnp.float32)
    want = group_norm_reference(x, scale, bias, num_groups=32)
    got = fused_group_norm(x, scale, bias, num_groups=32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=0.05
    )


def test_fused_silu_fusion():
    k = jax.random.key(2)
    x = jax.random.normal(k, (1, 256, 128), jnp.float32)
    scale = jnp.ones((128,))
    bias = jnp.zeros((128,))
    plain = fused_group_norm(x, scale, bias, num_groups=32, interpret=True)
    want = plain * jax.nn.sigmoid(plain)
    got = fused_group_norm(
        x, scale, bias, num_groups=32, act="silu", interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-6)


def test_reference_math_matches_flax():
    """The XLA fallback itself must be flax/torch GroupNorm (it replaces
    nn.GroupNorm at the un-fusable big-slab sites)."""
    k1, k2, k3 = jax.random.split(jax.random.key(3), 3)
    x = jax.random.normal(k1, (2, 512, 640), jnp.float32)
    scale = jax.random.normal(k2, (640,)) + 1.0
    bias = jax.random.normal(k3, (640,))
    want = _flax_gn(x, scale, bias, 32, 1e-6)
    got = group_norm_reference(x, scale, bias, num_groups=32, eps=1e-6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_gate_logic():
    assert fits_fused_group_norm(4096, 320)          # 64² per-frame: 2.6 MB
    assert fits_fused_group_norm(1024, 640)          # 32² per-frame: 1.3 MB
    assert fits_fused_group_norm(512, 1280)          # 8² frame-pooled
    assert not fits_fused_group_norm(8 * 4096, 320)  # 64² frame-pooled: 21 MB
    assert not fits_fused_group_norm(8 * 1024, 640)  # 32² frame-pooled: 10 MB
    assert not fits_fused_group_norm(100, 320)       # row-tile misalignment


def test_unfittable_shape_raises():
    x = jnp.zeros((1, 100, 320))
    with pytest.raises(ValueError, match="rows"):
        fused_group_norm(x, jnp.ones(320), jnp.zeros(320), num_groups=32,
                         interpret=True)


@pytest.mark.slow  # ~17 s: two full UNet compiles (interpret-GN vs XLA-GN)
def test_unet_forward_same_with_fused_gn():
    """The whole UNet must produce the same output through the fused-GN
    path (kernel in interpret mode) as through the XLA two-pass path —
    same parameter tree, same math, different schedule."""
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig

    cfg_x = UNet3DConfig.tiny(sample_size=16, group_norm="xla")
    cfg_f = UNet3DConfig.tiny(sample_size=16, group_norm="interpret")
    m_x = UNet3DConditionModel(config=cfg_x)
    m_f = UNet3DConditionModel(config=cfg_f)
    k = jax.random.key(7)
    x = jax.random.normal(k, (1, 2, 16, 16, 4))
    txt = jax.random.normal(jax.random.fold_in(k, 1), (1, 7, cfg_x.cross_attention_dim))
    params = m_x.init(jax.random.fold_in(k, 2), x, jnp.asarray(3), txt)
    out_x = m_x.apply(params, x, jnp.asarray(3), txt)
    out_f = m_f.apply(params, x, jnp.asarray(3), txt)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_x), atol=3e-5
    )
    # the tiny-16 shapes actually exercise the kernel (rows 256/512 pass the
    # row-tile gate) — guard against a silently-all-fallback test
    assert fits_fused_group_norm(256, 8) and fits_fused_group_norm(512, 8)


def test_gn_gradients_flow_through_fused_path():
    """Training differentiates through the UNet; the kernel's custom VJP
    recomputes via the reference math and must match its gradients."""
    k = jax.random.key(9)
    x = jax.random.normal(k, (1, 256, 64), jnp.float32)
    scale = jnp.ones((64,))
    bias = jnp.zeros((64,))

    def loss_fused(x, s, b):
        return jnp.sum(fused_group_norm(
            x, s, b, num_groups=32, act="silu", interpret=True) ** 2)

    def loss_ref(x, s, b):
        return jnp.sum(group_norm_reference(
            x, s, b, num_groups=32, act="silu") ** 2)

    g_f = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, bias)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_ in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_tpu_groupnorm_rejects_unknown_impl():
    """A typo'd impl (e.g. 'pallas') must raise, not silently select the
    XLA fallback and change the performance path (ADVICE r5 item 3)."""
    from videop2p_tpu.models.layers import TpuGroupNorm

    x = jnp.ones((1, 8, 32))
    good = TpuGroupNorm(num_groups=4, impl="xla")
    params = good.init(jax.random.key(0), x)
    for impl in ("auto", "xla", "interpret"):
        TpuGroupNorm(num_groups=4, impl=impl).apply(params, x)
    with pytest.raises(ValueError, match="impl"):
        TpuGroupNorm(num_groups=4, impl="pallas").apply(params, x)
