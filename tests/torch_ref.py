"""Hand-built torch mirror of the reference video UNet for parity tests.

diffusers is not installed in this image, so these modules re-implement the
reference's blocks (/root/reference/tuneavideo/models/{unet,unet_blocks,
attention,resnet}.py) directly in torch with diffusers-compatible parameter
names — ``state_dict()`` of :class:`TorchUNet3D` is a valid input to
``videop2p_tpu.models.convert.unet3d_params_from_torch``. Layout is the
reference's channels-first ``(B, C, F, H, W)``.

Only what the tiny test config exercises is implemented; semantics follow the
reference line-by-line (frame-0 KV frame attention attention.py:296-302,
temporal rearrange :262-268, GEGLU FF, time-emb broadcast resnet.py:181-184,
skip-concat up path unet_blocks.py:486-488).
"""

from __future__ import annotations

import math

import torch
import torch.nn.functional as F
from torch import nn


class InflatedConv3d(nn.Conv2d):
    """2-D conv applied per frame (resnet.py:11-19)."""

    def forward(self, x):  # (B, C, F, H, W)
        b, c, f, h, w = x.shape
        x = x.permute(0, 2, 1, 3, 4).reshape(b * f, c, h, w)
        x = super().forward(x)
        return x.reshape(b, f, *x.shape[1:]).permute(0, 2, 1, 3, 4)


def timestep_embedding(timesteps, dim, *, flip_sin_to_cos=True, shift=0.0):
    """diffusers ``Timesteps`` (unet.py:120-124 config)."""
    half = dim // 2
    exponent = -math.log(10000.0) * torch.arange(half, dtype=torch.float32)
    exponent = exponent / (half - shift)
    emb = timesteps.float()[:, None] * torch.exp(exponent)[None, :]
    sin, cos = torch.sin(emb), torch.cos(emb)
    return torch.cat([cos, sin] if flip_sin_to_cos else [sin, cos], dim=-1)


class TimestepEmbedding(nn.Module):
    def __init__(self, in_dim, dim):
        super().__init__()
        self.linear_1 = nn.Linear(in_dim, dim)
        self.linear_2 = nn.Linear(dim, dim)

    def forward(self, x):
        return self.linear_2(F.silu(self.linear_1(x)))


class ResnetBlock3D(nn.Module):
    """resnet.py:111-205 (``time_embedding_norm="default"``, swish)."""

    def __init__(self, in_ch, out_ch, temb_ch, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_ch, eps=1e-5)
        self.conv1 = InflatedConv3d(in_ch, out_ch, 3, padding=1)
        self.time_emb_proj = nn.Linear(temb_ch, out_ch)
        self.norm2 = nn.GroupNorm(groups, out_ch, eps=1e-5)
        self.conv2 = InflatedConv3d(out_ch, out_ch, 3, padding=1)
        self.conv_shortcut = (
            InflatedConv3d(in_ch, out_ch, 1) if in_ch != out_ch else None
        )

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if self.conv_shortcut is not None:
            x = self.conv_shortcut(x)
        return x + h


class _Attention(nn.Module):
    """The reference's ``CrossAttention`` shape (diffusers 0.11): to_q/k/v
    bias-free, out proj in a ModuleList (→ ``to_out.0``)."""

    def __init__(self, dim, ctx_dim, heads):
        super().__init__()
        self.heads = heads
        self.to_q = nn.Linear(dim, dim, bias=False)
        self.to_k = nn.Linear(ctx_dim, dim, bias=False)
        self.to_v = nn.Linear(ctx_dim, dim, bias=False)
        self.to_out = nn.ModuleList([nn.Linear(dim, dim)])

    def attend(self, q, k, v):
        b, n, c = q.shape
        h = self.heads
        d = c // h
        q = q.reshape(b, n, h, d).transpose(1, 2)
        k = k.reshape(b, k.shape[1], h, d).transpose(1, 2)
        v = v.reshape(b, v.shape[1], h, d).transpose(1, 2)
        sim = torch.einsum("bhqd,bhkd->bhqk", q, k) * d**-0.5
        probs = sim.float().softmax(dim=-1).to(q.dtype)
        out = torch.einsum("bhqk,bhkd->bhqd", probs, v)
        return out.transpose(1, 2).reshape(b, n, c)

    def forward(self, x, context=None):
        ctx = x if context is None else context
        return self.to_out[0](self.attend(self.to_q(x), self.to_k(ctx), self.to_v(ctx)))


class FrameAttention(_Attention):
    """Spatial self-attention with frame-0 keys/values (attention.py:239-328).
    Input (B·F, N, C) with ``video_length`` frames folded batch-major."""

    def forward(self, x, video_length):
        bf, n, c = x.shape
        b = bf // video_length
        kv = x.reshape(b, video_length, n, c)[:, [0] * video_length].reshape(bf, n, c)
        return self.to_out[0](self.attend(self.to_q(x), self.to_k(kv), self.to_v(kv)))


class GEGLUFeedForward(nn.Module):
    """diffusers ``FeedForward`` with GEGLU (→ ``ff.net.0.proj`` / ``ff.net.2``)."""

    def __init__(self, dim, mult=4):
        super().__init__()
        proj = nn.Linear(dim, dim * mult * 2)
        self.net = nn.ModuleList([nn.ModuleDict({"proj": proj}), nn.Identity(),
                                  nn.Linear(dim * mult, dim)])

    def forward(self, x):
        h, gate = self.net[0]["proj"](x).chunk(2, dim=-1)
        return self.net[2](h * F.gelu(gate))


class BasicTransformerBlock(nn.Module):
    """attention.py:140-268: frame-attn → cross-attn → FF → temporal attn."""

    def __init__(self, dim, ctx_dim, heads):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = FrameAttention(dim, dim, heads)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = _Attention(dim, ctx_dim, heads)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = GEGLUFeedForward(dim)
        self.norm_temp = nn.LayerNorm(dim)
        self.attn_temp = _Attention(dim, dim, heads)

    def forward(self, x, context, video_length):  # x: (B·F, N, C)
        x = x + self.attn1(self.norm1(x), video_length)
        x = x + self.attn2(self.norm2(x), context)
        x = x + self.ff(self.norm3(x))
        # temporal: (B·F, N, C) → (B·N, F, C)  (attention.py:262-268)
        bf, n, c = x.shape
        b = bf // video_length
        h = x.reshape(b, video_length, n, c).permute(0, 2, 1, 3).reshape(b * n, video_length, c)
        h = self.attn_temp(self.norm_temp(h))
        h = h.reshape(b, n, video_length, c).permute(0, 2, 1, 3).reshape(bf, n, c)
        return x + h


class Transformer3DModel(nn.Module):
    """attention.py:32-137: GN → 1×1-conv proj_in → blocks → proj_out + res."""

    def __init__(self, channels, ctx_dim, heads, depth, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels, eps=1e-6)
        self.proj_in = nn.Conv2d(channels, channels, 1)
        self.transformer_blocks = nn.ModuleList(
            [BasicTransformerBlock(channels, ctx_dim, heads) for _ in range(depth)]
        )
        self.proj_out = nn.Conv2d(channels, channels, 1)

    def forward(self, x, context):  # (B, C, F, H, W), context (B, L, D)
        b, c, f, hh, ww = x.shape
        residual = x
        h = x.permute(0, 2, 1, 3, 4).reshape(b * f, c, hh, ww)  # fold frames
        h = self.proj_in(self.norm(h))
        h = h.permute(0, 2, 3, 1).reshape(b * f, hh * ww, c)
        ctx = context.repeat_interleave(f, dim=0)  # text per frame (:94-95)
        for blk in self.transformer_blocks:
            h = blk(h, ctx, f)
        h = h.reshape(b * f, hh, ww, c).permute(0, 3, 1, 2)
        h = self.proj_out(h)
        h = h.reshape(b, f, c, hh, ww).permute(0, 2, 1, 3, 4)
        return h + residual


class Downsample3D(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = InflatedConv3d(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample3D(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = InflatedConv3d(ch, ch, 3, padding=1)

    def forward(self, x):  # nearest ×2 spatial (resnet.py:22-74)
        b, c, f, h, w = x.shape
        x = x.reshape(b, c * f, h, w)
        x = F.interpolate(x, scale_factor=2.0, mode="nearest")
        x = x.reshape(b, c, f, h * 2, w * 2)
        return self.conv(x)


class CrossAttnDownBlock3D(nn.Module):
    def __init__(self, in_ch, out_ch, temb_ch, ctx_dim, heads, depth, groups,
                 num_layers, add_downsample):
        super().__init__()
        self.resnets = nn.ModuleList(
            [ResnetBlock3D(in_ch if i == 0 else out_ch, out_ch, temb_ch, groups)
             for i in range(num_layers)]
        )
        self.attentions = nn.ModuleList(
            [Transformer3DModel(out_ch, ctx_dim, heads, depth, groups)
             for _ in range(num_layers)]
        )
        self.downsamplers = (
            nn.ModuleList([Downsample3D(out_ch)]) if add_downsample else None
        )

    def forward(self, x, temb, ctx):
        outs = []
        for res, attn in zip(self.resnets, self.attentions):
            x = attn(res(x, temb), ctx)
            outs.append(x)
        if self.downsamplers is not None:
            x = self.downsamplers[0](x)
            outs.append(x)
        return x, outs


class DownBlock3D(nn.Module):
    def __init__(self, in_ch, out_ch, temb_ch, groups, num_layers, add_downsample):
        super().__init__()
        self.resnets = nn.ModuleList(
            [ResnetBlock3D(in_ch if i == 0 else out_ch, out_ch, temb_ch, groups)
             for i in range(num_layers)]
        )
        self.downsamplers = (
            nn.ModuleList([Downsample3D(out_ch)]) if add_downsample else None
        )

    def forward(self, x, temb):
        outs = []
        for res in self.resnets:
            x = res(x, temb)
            outs.append(x)
        if self.downsamplers is not None:
            x = self.downsamplers[0](x)
            outs.append(x)
        return x, outs


class UNetMidBlock3DCrossAttn(nn.Module):
    def __init__(self, ch, temb_ch, ctx_dim, heads, depth, groups, num_layers=1):
        super().__init__()
        self.resnets = nn.ModuleList(
            [ResnetBlock3D(ch, ch, temb_ch, groups) for _ in range(num_layers + 1)]
        )
        self.attentions = nn.ModuleList(
            [Transformer3DModel(ch, ctx_dim, heads, depth, groups)
             for _ in range(num_layers)]
        )

    def forward(self, x, temb, ctx):
        x = self.resnets[0](x, temb)
        for attn, res in zip(self.attentions, self.resnets[1:]):
            x = res(attn(x, ctx), temb)
        return x


class CrossAttnUpBlock3D(nn.Module):
    def __init__(self, in_ch, out_ch, prev_ch, temb_ch, ctx_dim, heads, depth,
                 groups, num_layers, add_upsample, skip_chs):
        super().__init__()
        self.resnets = nn.ModuleList(
            [ResnetBlock3D(
                (prev_ch if i == 0 else out_ch) + skip_chs[i], out_ch, temb_ch, groups)
             for i in range(num_layers)]
        )
        self.attentions = nn.ModuleList(
            [Transformer3DModel(out_ch, ctx_dim, heads, depth, groups)
             for _ in range(num_layers)]
        )
        self.upsamplers = nn.ModuleList([Upsample3D(out_ch)]) if add_upsample else None

    def forward(self, x, res_samples, temb, ctx):
        res_samples = list(res_samples)
        for res, attn in zip(self.resnets, self.attentions):
            x = torch.cat([x, res_samples.pop()], dim=1)
            x = attn(res(x, temb), ctx)
        if self.upsamplers is not None:
            x = self.upsamplers[0](x)
        return x


class UpBlock3D(nn.Module):
    def __init__(self, in_ch, out_ch, prev_ch, temb_ch, groups, num_layers,
                 add_upsample, skip_chs):
        super().__init__()
        self.resnets = nn.ModuleList(
            [ResnetBlock3D(
                (prev_ch if i == 0 else out_ch) + skip_chs[i], out_ch, temb_ch, groups)
             for i in range(num_layers)]
        )
        self.upsamplers = nn.ModuleList([Upsample3D(out_ch)]) if add_upsample else None

    def forward(self, x, res_samples, temb):
        res_samples = list(res_samples)
        for res in self.resnets:
            x = torch.cat([x, res_samples.pop()], dim=1)
            x = res(x, temb)
        if self.upsamplers is not None:
            x = self.upsamplers[0](x)
        return x


class TorchUNet3D(nn.Module):
    """The reference ``UNet3DConditionModel`` (unet.py:38-415) at an arbitrary
    config dict matching :class:`videop2p_tpu.models.UNet3DConfig` fields."""

    def __init__(self, cfg):
        super().__init__()
        chans = cfg.block_out_channels
        n = len(chans)
        temb_ch = chans[0] * 4
        groups = cfg.norm_num_groups
        depths = cfg.transformer_depth if isinstance(cfg.transformer_depth, tuple) \
            else (cfg.transformer_depth,) * n
        heads = cfg.attention_head_dim if isinstance(cfg.attention_head_dim, tuple) \
            else (cfg.attention_head_dim,) * n
        L = cfg.layers_per_block
        self.cfg = cfg
        self.conv_in = InflatedConv3d(cfg.in_channels, chans[0], 3, padding=1)
        self.time_embedding = TimestepEmbedding(chans[0], temb_ch)

        self.down_blocks = nn.ModuleList()
        skip_stack = [chans[0]]
        in_ch = chans[0]
        for i, bt in enumerate(cfg.down_block_types):
            out_ch = chans[i]
            final = i == n - 1
            if bt == "CrossAttnDownBlock3D":
                blk = CrossAttnDownBlock3D(
                    in_ch, out_ch, temb_ch, cfg.cross_attention_dim, heads[i],
                    depths[i], groups, L, not final)
            else:
                blk = DownBlock3D(in_ch, out_ch, temb_ch, groups, L, not final)
            self.down_blocks.append(blk)
            skip_stack.extend([out_ch] * L + ([out_ch] if not final else []))
            in_ch = out_ch

        self.mid_block = UNetMidBlock3DCrossAttn(
            chans[-1], temb_ch, cfg.cross_attention_dim, heads[-1], depths[-1], groups)

        self.up_blocks = nn.ModuleList()
        rev = tuple(reversed(chans))
        rev_heads = tuple(reversed(heads))
        rev_depths = tuple(reversed(depths))
        prev_ch = chans[-1]
        for i, bt in enumerate(cfg.up_block_types):
            out_ch = rev[i]
            final = i == n - 1
            num_layers = L + 1
            skips = [skip_stack.pop() for _ in range(num_layers)]
            if bt == "CrossAttnUpBlock3D":
                blk = CrossAttnUpBlock3D(
                    None, out_ch, prev_ch, temb_ch, cfg.cross_attention_dim,
                    rev_heads[i], rev_depths[i], groups, num_layers, not final, skips)
            else:
                blk = UpBlock3D(None, out_ch, prev_ch, temb_ch, groups,
                                num_layers, not final, skips)
            self.up_blocks.append(blk)
            prev_ch = out_ch

        self.conv_norm_out = nn.GroupNorm(groups, chans[0], eps=1e-5)
        self.conv_out = InflatedConv3d(chans[0], cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, context):  # (B,C,F,H,W), (B,), (B,L,D)
        temb = self.time_embedding(
            timestep_embedding(timesteps, self.cfg.block_out_channels[0])
        )
        x = self.conv_in(sample)
        res_stack = [x]
        for blk in self.down_blocks:
            if isinstance(blk, CrossAttnDownBlock3D):
                x, outs = blk(x, temb, context)
            else:
                x, outs = blk(x, temb)
            res_stack.extend(outs)
        x = self.mid_block(x, temb, context)
        for blk in self.up_blocks:
            num_layers = len(blk.resnets)
            res = res_stack[-num_layers:]
            del res_stack[-num_layers:]
            if isinstance(blk, CrossAttnUpBlock3D):
                x = blk(x, res, temb, context)
            else:
                x = blk(x, res, temb)
        return self.conv_out(F.silu(self.conv_norm_out(x)))


# --------------------------------------------------------------------- #
# VAE (diffusers AutoencoderKL layout, /root/reference uses it frozen)
# --------------------------------------------------------------------- #


class VAEResnet(nn.Module):
    def __init__(self, in_ch, out_ch, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_ch, eps=1e-6)
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, padding=1)
        self.norm2 = nn.GroupNorm(groups, out_ch, eps=1e-6)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, padding=1)
        self.conv_shortcut = nn.Conv2d(in_ch, out_ch, 1) if in_ch != out_ch else None

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        if self.conv_shortcut is not None:
            x = self.conv_shortcut(x)
        return x + h


class VAEAttention(nn.Module):
    """Single-head mid-block attention (diffusers ≥0.15 to_q/k/v naming)."""

    def __init__(self, ch, groups):
        super().__init__()
        self.group_norm = nn.GroupNorm(groups, ch, eps=1e-6)
        self.to_q = nn.Linear(ch, ch)
        self.to_k = nn.Linear(ch, ch)
        self.to_v = nn.Linear(ch, ch)
        self.to_out = nn.ModuleList([nn.Linear(ch, ch)])

    def forward(self, x):
        b, c, h, w = x.shape
        res = x
        t = self.group_norm(x).reshape(b, c, h * w).transpose(1, 2)
        q, k, v = self.to_q(t), self.to_k(t), self.to_v(t)
        sim = torch.einsum("bqc,bkc->bqk", q, k) * c**-0.5
        probs = sim.float().softmax(dim=-1).to(q.dtype)
        out = self.to_out[0](torch.einsum("bqk,bkc->bqc", probs, v))
        return res + out.transpose(1, 2).reshape(b, c, h, w)


class _VAEDown(nn.Module):
    def __init__(self, in_ch, out_ch, groups, layers, add_down):
        super().__init__()
        self.resnets = nn.ModuleList(
            [VAEResnet(in_ch if j == 0 else out_ch, out_ch, groups) for j in range(layers)]
        )
        self.downsamplers = (
            nn.ModuleList([nn.ModuleDict({"conv": nn.Conv2d(out_ch, out_ch, 3, stride=2)})])
            if add_down else None
        )

    def forward(self, x):
        for r in self.resnets:
            x = r(x)
        if self.downsamplers is not None:
            x = F.pad(x, (0, 1, 0, 1))  # diffusers Downsample2D pad=0 path
            x = self.downsamplers[0]["conv"](x)
        return x


class _VAEUp(nn.Module):
    def __init__(self, in_ch, out_ch, groups, layers, add_up):
        super().__init__()
        self.resnets = nn.ModuleList(
            [VAEResnet(in_ch if j == 0 else out_ch, out_ch, groups) for j in range(layers)]
        )
        self.upsamplers = (
            nn.ModuleList([nn.ModuleDict({"conv": nn.Conv2d(out_ch, out_ch, 3, padding=1)})])
            if add_up else None
        )

    def forward(self, x):
        for r in self.resnets:
            x = r(x)
        if self.upsamplers is not None:
            x = F.interpolate(x, scale_factor=2.0, mode="nearest")
            x = self.upsamplers[0]["conv"](x)
        return x


class _VAEMid(nn.Module):
    def __init__(self, ch, groups):
        super().__init__()
        self.resnets = nn.ModuleList([VAEResnet(ch, ch, groups), VAEResnet(ch, ch, groups)])
        self.attentions = nn.ModuleList([VAEAttention(ch, groups)])

    def forward(self, x):
        return self.resnets[1](self.attentions[0](self.resnets[0](x)))


class TorchVAE(nn.Module):
    """diffusers ``AutoencoderKL`` at a videop2p_tpu ``VAEConfig``."""

    def __init__(self, cfg):
        super().__init__()
        chans = cfg.block_out_channels
        g = cfg.norm_num_groups
        L = cfg.layers_per_block

        enc = nn.Module()
        enc.conv_in = nn.Conv2d(cfg.in_channels, chans[0], 3, padding=1)
        enc.down_blocks = nn.ModuleList()
        in_ch = chans[0]
        for i, ch in enumerate(chans):
            enc.down_blocks.append(_VAEDown(in_ch, ch, g, L, i < len(chans) - 1))
            in_ch = ch
        enc.mid_block = _VAEMid(chans[-1], g)
        enc.conv_norm_out = nn.GroupNorm(g, chans[-1], eps=1e-6)
        enc.conv_out = nn.Conv2d(chans[-1], 2 * cfg.latent_channels, 3, padding=1)
        self.encoder = enc

        dec = nn.Module()
        rev = tuple(reversed(chans))
        dec.conv_in = nn.Conv2d(cfg.latent_channels, rev[0], 3, padding=1)
        dec.mid_block = _VAEMid(rev[0], g)
        dec.up_blocks = nn.ModuleList()
        in_ch = rev[0]
        for i, ch in enumerate(rev):
            dec.up_blocks.append(_VAEUp(in_ch, ch, g, L + 1, i < len(rev) - 1))
            in_ch = ch
        dec.conv_norm_out = nn.GroupNorm(g, rev[-1], eps=1e-6)
        dec.conv_out = nn.Conv2d(rev[-1], cfg.out_channels, 3, padding=1)
        self.decoder = dec

        self.quant_conv = nn.Conv2d(2 * cfg.latent_channels, 2 * cfg.latent_channels, 1)
        self.post_quant_conv = nn.Conv2d(cfg.latent_channels, cfg.latent_channels, 1)

    def encode_moments(self, x):
        h = self.encoder.conv_in(x)
        for blk in self.encoder.down_blocks:
            h = blk(h)
        h = self.encoder.mid_block(h)
        h = self.encoder.conv_out(F.silu(self.encoder.conv_norm_out(h)))
        return self.quant_conv(h)

    def decode(self, z):
        h = self.decoder.conv_in(self.post_quant_conv(z))
        h = self.decoder.mid_block(h)
        for blk in self.decoder.up_blocks:
            h = blk(h)
        return self.decoder.conv_out(F.silu(self.decoder.conv_norm_out(h)))
