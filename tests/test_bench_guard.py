"""Driver-capture hardening: the bench and the multichip dryrun must produce
machine-readable artifacts even when the real TPU backend is down.

Round 4 lost BOTH driver artifacts to a transiently-unavailable chip:
``BENCH_r04.json`` rc=1 (backend init raised at the first device op, no JSON
line emitted) and ``MULTICHIP_r04.json`` rc=124 (``dryrun_multichip`` probed
``jax.devices()`` in the driver's process and hung with it). These tests pin
the round-5 guards: bounded backend retry with an error record in bench.py,
and a backend-blind re-exec decision in ``__graft_entry__``.
"""

import importlib.util
import json
import os
import subprocess
import sys
import types

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(name, filename):
    spec = importlib.util.spec_from_file_location(name, os.path.join(_REPO, filename))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_module("bench_under_test", "bench.py")


@pytest.fixture(scope="module")
def graft():
    return _load_module("graft_under_test", "__graft_entry__.py")


# ---------------------------------------------------------------- bench.py --


def test_wait_for_backend_retries_then_succeeds(bench):
    calls = {"probe": 0, "slept": []}

    def probe():
        calls["probe"] += 1
        return calls["probe"] >= 3  # down for two probes, then healthy

    ok = bench.wait_for_backend(
        attempts=5, _probe=probe, _sleep=calls["slept"].append
    )
    assert ok
    assert calls["probe"] == 3
    # backed off once per failed probe, with the documented escalation
    assert calls["slept"] == [10.0, 20.0]


def test_wait_for_backend_gives_up_after_bounded_attempts(bench):
    calls = {"probe": 0, "slept": []}

    def probe():
        calls["probe"] += 1
        return False

    ok = bench.wait_for_backend(
        attempts=5, _probe=probe, _sleep=calls["slept"].append
    )
    assert not ok
    assert calls["probe"] == 5
    # no sleep after the final failure — the driver's clock is precious
    assert len(calls["slept"]) == 4
    # total backoff stays within the ~3-minute budget VERDICT r4 item 1 set
    assert sum(calls["slept"]) <= 200.0


def test_unavailable_backend_still_emits_one_parseable_line(bench, capsys):
    bench.emit_backend_unavailable()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["error"] == "backend_unavailable"
    assert rec["metric"] == "fast_edit_e2e_wall"
    assert rec["value"] is None


def test_main_short_circuits_when_backend_unavailable(bench, capsys, monkeypatch):
    # main() must emit the error record and return WITHOUT touching jax —
    # a failed init can be cached for the life of the process. The only
    # extra work allowed after the error line is the CPU cost-analysis
    # capture (subprocess-isolated, ISSUE 3 satellite) — verified invoked.
    monkeypatch.setattr(bench, "wait_for_backend", lambda **kw: False)
    monkeypatch.setattr(
        bench, "build_fast_edit_working_point",
        lambda **kw: pytest.fail("touched the device after a failed probe"),
    )
    called = []
    monkeypatch.setattr(bench, "record_cpu_only_evidence",
                        lambda: called.append(True))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["error"] == "backend_unavailable"
    assert called == [True]


def test_cpu_only_evidence_records_analyses_and_verdicts(
    bench, tmp_path, monkeypatch
):
    """Backend-down evidence path: the subprocess capture's analyses land
    in bench_details.json with regression verdicts vs the previous
    record — no round is evidence-free (VERDICT r5 'What's missing' #1)."""
    details = tmp_path / "bench_details.json"
    # a previous record to regress against: e2e temp bytes grew 50%
    details.write_text(json.dumps({
        "breakdown": {"program_analysis": {
            "e2e_cached": {"flops": 1000, "temp_bytes": 100 * 2**20,
                           "hlo_fingerprint": "aa"},
        }},
    }))
    analyses = {
        "e2e_cached": {"flops": 1000, "temp_bytes": 150 * 2**20,
                       "hlo_fingerprint": "bb"},
        "invert_captured": {"flops": 500, "temp_bytes": 10,
                            "hlo_fingerprint": "cc"},
    }
    frontier = [{"steps": 50, "src_err": 0.0}, {"steps": 8, "src_err": 0.0}]
    monkeypatch.setattr(bench, "collect_cpu_analysis",
                        lambda *a, **kw: analyses)
    monkeypatch.setattr(bench, "collect_step_frontier",
                        lambda **kw: frontier)
    bench.record_cpu_only_evidence(repo_dir=str(tmp_path))
    doc = json.loads(details.read_text())
    bd = doc["breakdown"]
    assert bd["program_analysis"] == analyses
    assert bd["program_analysis_backend"] == "cpu"
    # the ISSUE-8 backend-down evidence rides along: the tiny CPU frontier
    # (disclosed backend) — the unit-flop record skips here because the
    # stubbed capture has no null_text_unit_* programs
    assert bd["latency_quality_frontier"] == frontier
    assert bd["latency_quality_frontier_backend"] == "cpu-tiny"
    assert "null_text_flops_reduction_amortized" not in bd
    # the per-call cost record skips quietly too: the stubbed capture has
    # no unet_unit_*/reuse_unit_* programs (ISSUE 15)
    assert "per_call_cost" not in bd
    v = bd["analysis_verdicts"]
    assert v["baseline"] == "bench_details.json"
    assert v["compared_programs"] == ["e2e_cached"]
    assert not v["pass"]
    regs = {r["metric"] for r in v["regressions"]}
    assert "temp_bytes" in regs
    assert all(r["fingerprint_changed"] for r in v["regressions"]
               if "fingerprint_changed" in r)


def test_cpu_only_evidence_skippable_and_failure_tolerant(
    bench, tmp_path, monkeypatch
):
    # kill-switch: no capture attempted
    monkeypatch.setenv("VIDEOP2P_BENCH_CPU_ANALYSIS", "0")
    monkeypatch.setattr(
        bench, "collect_cpu_analysis",
        lambda *a, **kw: pytest.fail("capture ran despite the kill-switch"),
    )
    bench.record_cpu_only_evidence(repo_dir=str(tmp_path))
    assert not (tmp_path / "bench_details.json").exists()
    # empty capture (timeout before any program finished): readable error
    monkeypatch.setenv("VIDEOP2P_BENCH_CPU_ANALYSIS", "1")
    monkeypatch.setattr(bench, "collect_cpu_analysis", lambda *a, **kw: {})
    monkeypatch.setattr(bench, "collect_step_frontier", lambda **kw: [])
    bench.record_cpu_only_evidence(repo_dir=str(tmp_path))
    doc = json.loads((tmp_path / "bench_details.json").read_text())
    assert "cpu_analysis_error" in doc["breakdown"]
    # an empty frontier records nothing rather than a fake empty table
    assert "latency_quality_frontier" not in doc["breakdown"]


def test_collect_cpu_analysis_parses_partial_output(bench, monkeypatch):
    """A timeout mid-capture keeps the programs whose JSON lines flushed."""
    payload = (
        json.dumps({"program": "invert_captured", "flops": 7}) + "\n"
        + '{"program": "e2e_cached", "flo'  # torn final line
    )

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout"),
                                        output=payload.encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench.collect_cpu_analysis(8, 50, timeout_s=1.0)
    assert out == {"invert_captured": {"flops": 7}}


def test_collect_step_frontier_parses_partial_output(bench, monkeypatch):
    """A timeout mid-frontier keeps the step counts whose lines flushed
    (same contract as collect_cpu_analysis)."""
    payload = (
        json.dumps({"steps": 50, "src_err": 0.0, "edit_s": 1.0}) + "\n"
        + json.dumps({"steps": 20, "src_err": 0.0, "edit_s": 0.5}) + "\n"
        + '{"steps": 8, "src_'  # torn final line
    )

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout"),
                                        output=payload.encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench.collect_step_frontier(timeout_s=1.0)
    assert [r["steps"] for r in out] == [50, 20]


def test_collect_step_frontier_serializes_student_variants(bench, monkeypatch):
    """ISSUE 16: 3-tuple (student_steps, quant, reuse) variants serialize
    to the tool's student:N+qm+rs grammar; 2-tuples stay qm+rs."""
    seen = {}

    def fake_run(cmd, **kw):
        seen["cmd"] = cmd
        return types.SimpleNamespace(stdout="", stderr="", returncode=0)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    bench.collect_step_frontier(
        timeout_s=1.0,
        variants=(("w8", "uniform:2"), (2, "off", "off"),
                  (2, "w8", "uniform:2")),
    )
    i = seen["cmd"].index("--variants")
    assert seen["cmd"][i + 1] == (
        "w8+uniform:2,student:2+off+off,student:2+w8+uniform:2"
    )


def test_collect_served_latency_parses_record_and_tolerates_failure(
        bench, monkeypatch):
    """ISSUE 14 satellite: the served-latency capture parses the loadgen's
    final JSON record into the queueing-inclusive e2e percentiles (noise
    lines skipped), and every failure mode — timeout, bad exit, no record
    — degrades to None, never an exception."""
    record = {"requests": 6, "concurrency": 3, "done": 6, "store_hits": 5,
              "shed": 0, "throughput_rps": 1.5,
              "latency": {"blocked_p50_s": 0.1, "blocked_p99_s": 0.4,
                          "blocked_max_s": 0.4}}
    payload = "[loadgen] warming...\n" + json.dumps(record) + "\n"

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(stdout=payload, stderr="",
                                     returncode=0)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench.collect_served_latency(timeout_s=1.0)
    assert out["backend"] == "cpu-tiny" and out["done"] == 6
    assert out["e2e_p50_s"] == 0.1 and out["e2e_p99_s"] == 0.4
    assert "segments" not in out  # fake run wrote no span ledgers

    def fake_timeout(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", fake_timeout)
    assert bench.collect_served_latency(timeout_s=1.0) is None

    def fake_fail(cmd, **kw):
        return types.SimpleNamespace(stdout="no json here\n",
                                     stderr="boom", returncode=1)

    monkeypatch.setattr(bench.subprocess, "run", fake_fail)
    assert bench.collect_served_latency(timeout_s=1.0) is None


@pytest.mark.slow
def test_step_frontier_tool_end_to_end_tiny(bench):
    """The ISSUE 8 frontier acceptance, through the real subprocess at tiny
    scale: the 20- and 8-step cached fast-path variants run e2e from ONE
    50-step inversion (exact timestep subsets), the source replay stays
    exact at every step count, and each record carries the quality metrics
    (PSNR/SSIM vs the full-step edit) next to its wall-clock."""
    records = bench.collect_step_frontier(
        timeout_s=560.0, tiny=True, frames=2,
        base_steps=50, step_counts=(50, 20, 8),
        variants=(("w8", "uniform:2"), (2, "w8", "uniform:2")),
    )
    assert [r["steps"] for r in records] == [50, 20, 8, 50, 2]
    for r in records:
        assert r["base_steps"] == 50
        assert r["src_err"] == 0.0, r          # replay exact at any count
        assert r["backend"] == "cpu" and r["tiny"] is True
        assert r["edit_s"] is not None and r["edit_s"] > 0
    for r in records[1:]:  # subset+variant rows score against the full edit
        assert isinstance(r["vs_full_psnr_db"], float)
        assert isinstance(r["vs_full_ssim"], float)
        assert r["speedup_vs_full"] is not None
    # the ISSUE 15 variant row: quantized + reuse at full steps, replay
    # still exact (asserted above), knobs recorded on every row — and the
    # ISSUE 16 composed student row (student:2+w8+uniform:2) rides the
    # same frontier with the student flag recorded on every row
    assert [(r["quant_mode"], r["reuse_schedule"], r["student"])
            for r in records] == [
        ("off", "off", False), ("off", "off", False), ("off", "off", False),
        ("w8", "uniform:2", False), ("w8", "uniform:2", True),
    ]


@pytest.mark.slow
def test_null_text_unit_capture_yields_3x_flop_reduction(bench, tmp_path):
    """The ISSUE 8 flop acceptance, through the real subprocess at tiny
    scale: the straight-line unit analyses (one UNet forward, one inner
    Adam iteration) feed null_text_flop_records, and at the official
    defaults the amortized and hybrid inner-loop totals are ≥3× below the
    optimize baseline."""
    out = bench.collect_cpu_analysis(
        2, 2, tiny=True, timeout_s=560.0,
        programs=("null_text_unit_fwd", "null_text_unit_inner"),
    )
    assert set(out) == {"null_text_unit_fwd", "null_text_unit_inner"}
    fwd = out["null_text_unit_fwd"]["flops"]
    inner = out["null_text_unit_inner"]["flops"]
    assert inner >= fwd > 0  # a grad step costs at least a forward
    rec = bench.null_text_flop_records(fwd, inner)
    assert rec["null_text_flops_reduction_amortized"] >= 3.0
    assert rec["null_text_flops_reduction_hybrid"] >= 3.0


def test_load_analysis_baseline_precedence(bench, tmp_path):
    # nothing on disk: no baseline
    assert bench.load_analysis_baseline(str(tmp_path)) == (None, None)
    # bench_details.json record is the fallback baseline
    (tmp_path / "bench_details.json").write_text(json.dumps(
        {"breakdown": {"program_analysis": {"p": {"flops": 1}}}}
    ))
    section, source = bench.load_analysis_baseline(str(tmp_path))
    assert source == "bench_details.json" and section == {"p": {"flops": 1}}
    # an explicit BASELINE.json budget wins over it
    (tmp_path / "BASELINE.json").write_text(json.dumps(
        {"program_analysis": {"p": {"flops": 2}}}
    ))
    section, source = bench.load_analysis_baseline(str(tmp_path))
    assert source == "BASELINE.json" and section == {"p": {"flops": 2}}


def test_bench_analysis_verdicts_schema(bench):
    base = {"p": {"flops": 100, "temp_bytes": 100, "hlo_fingerprint": "x"}}
    same = bench.bench_analysis_verdicts(base, base, "BASELINE.json")
    assert same["pass"] and same["regressions"] == []
    assert same["compared_programs"] == ["p"]
    # first capture: no baseline → vacuous pass, still machine-readable
    first = bench.bench_analysis_verdicts(base, None, None)
    assert first["pass"] and first["baseline"] is None


def test_sub_floor_trace_span_is_recorded_suspect_not_floor_clamped(
    bench, monkeypatch
):
    """Advisor r4 (medium): when the trace's envelope span is itself below
    the FLOP floor, the reading must be the measured span flagged suspect —
    not the theoretical floor presented as a trusted measurement."""
    fake_px = types.SimpleNamespace(
        module_device_seconds=lambda tdir: 10.0,  # sum clears the floor...
        module_device_span_seconds=lambda tdir: 2.0,  # ...only via overlap
    )
    monkeypatch.setattr(bench, "_tools_import", lambda name: fake_px)
    monkeypatch.setattr(
        bench.jax.profiler, "start_trace",
        lambda *a, **kw: None, raising=False,
    )
    monkeypatch.setattr(
        bench.jax.profiler, "stop_trace", lambda: None, raising=False
    )

    r = bench.measure_with_floor(
        lambda x: bench.jnp.float32(x), [1.0], floor_s=5.0, what="test-phase"
    )
    assert r.source == "device_trace"
    assert r.seconds == pytest.approx(2.0)
    assert r.suspect


def test_above_floor_trace_span_is_trusted(bench, monkeypatch):
    fake_px = types.SimpleNamespace(
        module_device_seconds=lambda tdir: 10.0,
        module_device_span_seconds=lambda tdir: 6.0,
    )
    monkeypatch.setattr(bench, "_tools_import", lambda name: fake_px)
    monkeypatch.setattr(
        bench.jax.profiler, "start_trace",
        lambda *a, **kw: None, raising=False,
    )
    monkeypatch.setattr(
        bench.jax.profiler, "stop_trace", lambda: None, raising=False
    )

    r = bench.measure_with_floor(
        lambda x: bench.jnp.float32(x), [1.0], floor_s=5.0, what="test-phase"
    )
    assert r.source == "device_trace"
    assert r.seconds == pytest.approx(6.0)
    assert not r.suspect


def test_samples_mode_reports_median_and_spread(bench):
    """samples=3: the reading of record is the MEDIAN of three valid runs,
    with every valid reading recorded (discard-first/report-spread
    discipline on the headline phase)."""
    r = bench.measure_with_floor(
        lambda x: bench.jnp.float32(x), [1.0, 2.0, 3.0],
        floor_s=0.0, what="t", samples=3,
    )
    assert len(r.samples) == 3
    assert not r.suspect
    assert round(r.seconds, 3) == sorted(r.samples)[1]


def test_samples_mode_single_valid_still_returns(bench):
    """Fewer valid readings than requested samples: return what exists
    (bounded by the supplied fresh inputs) rather than failing."""
    r = bench.measure_with_floor(
        lambda x: bench.jnp.float32(x), [1.0],
        floor_s=0.0, what="t", samples=3,
    )
    assert len(r.samples) == 1
    assert round(r.seconds, 3) == r.samples[0]


def test_details_recorder_merges_and_flags_stale(bench, tmp_path):
    """bench_details.json survives partial runs: keys from a previous run
    are inherited but flagged stale until re-measured; re-recording
    freshens them; suspect propagation follows the Reading."""
    path = str(tmp_path / "details.json")
    rec1 = bench.DetailsRecorder(path, {"device": "t"}, [])
    r_ok = bench.Reading(None, 1.0, False, "wall", None)
    r_bad = bench.Reading(None, 2.0, True, "wall", None)
    rec1.record("a_s", 1.0, reading=r_ok)
    rec1.record("b_s", 2.0, reading=r_bad)
    saved = json.load(open(path))["breakdown"]
    assert saved["a_s"] == 1.0
    assert saved["suspect_measurements"] == ["b_s"]
    assert "stale_from_previous_run" not in saved

    # a later (partial) run inherits both, flags them stale, then
    # re-measures one — which must clear BOTH its stale and suspect marks
    rec2 = bench.DetailsRecorder(path, {"device": "t"}, [])
    assert set(rec2.stale) >= {"a_s", "b_s"}
    rec2.record("b_s", 2.5, reading=r_ok)
    saved = json.load(open(path))["breakdown"]
    assert saved["b_s"] == 2.5
    assert "b_s" not in saved.get("suspect_measurements", [])
    assert "b_s" not in saved.get("stale_from_previous_run", [])
    assert "a_s" in saved["stale_from_previous_run"]

    # derived values inherit suspicion from their constituents
    rec2.record("c_s", 3.0, derived=(r_bad,))
    saved = json.load(open(path))["breakdown"]
    assert "c_s" in saved["suspect_measurements"]

    # drop removes inherited keys entirely (e.g. a renamed metric)
    rec2.drop("a_s")
    saved = json.load(open(path))["breakdown"]
    assert "a_s" not in saved
    assert "a_s" not in saved.get("stale_from_previous_run", [])


# ------------------------------------------------- ledger/compile fields --


def test_ledger_bench_fields_schema(bench):
    """The bench breakdown's ledger/compile provenance fields (ISSUE 2):
    schema-stable and machine-readable, with the compile-vs-execute split
    explicit. Values may be null when unmeasured, keys never vanish."""
    rec = bench.ledger_bench_fields(
        "/tmp/bench_ledger.jsonl", [1.5, 2.25, 0.25], execute_s=8.0
    )
    assert rec == {
        "ledger_path": "/tmp/bench_ledger.jsonl",
        "compile_events": 3,
        "compile_total_s": 4.0,
        "execute_headline_s": 8.0,
        "compile_vs_execute": 0.5,
    }
    # unmeasured execute: keys stay, split is null (not a division crash)
    empty = bench.ledger_bench_fields("p", [], execute_s=None)
    assert empty["compile_events"] == 0
    assert empty["compile_total_s"] == 0.0
    assert empty["execute_headline_s"] is None
    assert empty["compile_vs_execute"] is None
    assert set(empty) == set(rec)


def _import_roots(path):
    """Every imported top-level module name in a file, comprehensions and
    function bodies included (AST walk — lazy imports don't hide)."""
    import ast

    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    roots = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            roots.update(a.name.split(".")[0] for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            roots.add(node.module.split(".")[0])
    return roots


def test_report_and_obs_import_only_stdlib_numpy_jax():
    """CI satellite (ISSUEs 4 + 7): tools/edit_report.py,
    videop2p_tpu/obs/ AND videop2p_tpu/serve/ must import only stdlib +
    numpy + jax (+ the package itself) — no matplotlib/PIL/imageio-only
    paths — so the report renders, the obs stack decodes, and the serving
    engine runs on any box, plotting stack or not."""
    import sys

    allowed = set(sys.stdlib_module_names) | {"numpy", "jax", "videop2p_tpu"}
    banned = {"matplotlib", "PIL", "imageio", "cv2", "torch", "torchvision",
              "pandas", "seaborn", "plotly", "scipy", "skimage",
              "tensorflow", "flax", "optax", "transformers"}
    files = [os.path.join(_REPO, "tools", "edit_report.py"),
             # ISSUE 17 pin: the fleet dashboard renders on any box the
             # collector runs on — stdlib+numpy SVG, no plotting stack
             os.path.join(_REPO, "tools", "fleet_dash.py"),
             # ISSUE 18 pin: the post-mortem renderer must open a bundle
             # anywhere — it ships in bug reports, not deployments
             os.path.join(_REPO, "tools", "incident_report.py"),
             # ISSUE 19 pin: the showback report ships in chargeback
             # emails — stdlib+numpy SVG bars, no plotting stack
             os.path.join(_REPO, "tools", "cost_report.py"),
             # ISSUE 20 pin: the correctness report ships in bug reports
             # too — stdlib+numpy SVG timelines, no plotting stack
             os.path.join(_REPO, "tools", "probe_report.py")]
    obs_dir = os.path.join(_REPO, "videop2p_tpu", "obs")
    obs_files = sorted(f for f in os.listdir(obs_dir) if f.endswith(".py"))
    # ISSUE 6 pins: the time-domain modules are IN the guarded set — the
    # stdlib xplane reader must never grow a tensorflow path, and the
    # latency reservoirs must stay stdlib
    # ISSUE 14 pins: the tracing/SLO/exposition tier joins — span
    # emission, budget math and the Prometheus renderer must run on any
    # box the engine does (no opentelemetry/prometheus_client deps)
    # ISSUE 17 pins: the telemetry plane joins — the time-series store
    # and the signal engine must never grow a prometheus_client/pandas
    # path; the fleet ships its own tsdb
    # ISSUE 18 pins: the incident plane joins — the flight recorder is
    # on the ledger hot path and the capture manager runs in every
    # serving process, so both stay stdlib(+numpy via the sidecar)
    # ISSUE 19 pins: the cost plane joins — the attribution model runs
    # inside every engine, so it stays stdlib+numpy
    # ISSUE 20 pins: the correctness plane joins — the known-answer
    # probe suite and the answer audit run inside every prober/engine
    # process, so they stay stdlib
    assert {"timing.py", "trace.py",
            "spans.py", "slo.py", "prom.py",
            "tsdb.py", "signals.py",
            "flight.py", "incident.py",
            "cost.py", "probe.py"} <= set(obs_files)
    files += [os.path.join(obs_dir, f) for f in obs_files]
    # ISSUE 7 pins: the serving subsystem is IN the guarded set — the
    # HTTP layer stays stdlib http.server/urllib (no flask/requests), and
    # the engine reaches models only through the package
    serve_dir = os.path.join(_REPO, "videop2p_tpu", "serve")
    serve_files = sorted(f for f in os.listdir(serve_dir) if f.endswith(".py"))
    # ISSUE 9 pin: the resilience layer (fault injection, breaker, retry)
    # joins the guarded set — chaos machinery must run anywhere the engine
    # does, so it stays stdlib
    # ISSUE 11 pin: the fleet tier (pluggable schedulers, the replica
    # supervisor and the router) joins too — the router must deploy on any
    # box with nothing beyond the stdlib HTTP stack
    # ISSUE 17 pin: the scrape loop joins — the collector must deploy on
    # any box the router does (stdlib urllib probes, no requests)
    # ISSUE 20 pin: the probing loop joins — the prober deploys next to
    # the router (stdlib urllib canaries, no requests)
    assert {"engine.py", "store.py", "batching.py", "programs.py",
            "http.py", "client.py", "faults.py", "sched.py", "replica.py",
            "router.py", "collector.py", "prober.py"} <= set(serve_files)
    files += [os.path.join(serve_dir, f) for f in serve_files]
    # ISSUE 12 pin: the streaming tier (window plan, resumable manifest,
    # job driver) joins the guarded set — resume/chaos machinery must run
    # anywhere the engine does, so it stays stdlib+numpy+jax
    stream_dir = os.path.join(_REPO, "videop2p_tpu", "stream")
    stream_files = sorted(f for f in os.listdir(stream_dir)
                          if f.endswith(".py"))
    assert {"windows.py", "manifest.py", "driver.py"} <= set(stream_files)
    files += [os.path.join(stream_dir, f) for f in stream_files]
    offenders = []
    for path in files:
        roots = _import_roots(path)
        for r in sorted(roots):
            if r in banned or r not in allowed:
                offenders.append(f"{path}: imports {r!r}")
    assert not offenders, (
        "stdlib+numpy+jax-only import contract violated:\n"
        + "\n".join(offenders)
    )


def test_quality_and_attn_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 4): the new `quality` and `attn_maps` ledger
    events carry their documented field sets — the report, the regression
    rules and ledger_summary all key on these names."""
    import numpy as np

    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.attention import (
        ATTN_SUMMARY_FIELDS,
        summarize_attn_record,
    )
    from videop2p_tpu.obs.quality import (
        QUALITY_SUMMARY_FIELDS,
        edit_quality_record,
    )

    frames = np.random.RandomState(0).rand(2, 8, 8, 3).astype(np.float32)
    summary, curves = edit_quality_record(frames, frames, frames,
                                          mask=np.ones((2, 8, 8)))
    attn_summary = summarize_attn_record({
        "cross_heat": np.zeros((3, 1, 16, 16, 77), np.float32),
        "entropy": {"b/attn2": np.zeros(3)},
        "mask_cov": np.zeros((3, 2, 2)),
        "blend_active": np.zeros(3, np.int64),
    })
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.event("quality", program="edit_quality", sidecar="sc.npz",
                  **summary)
        led.event("attn_maps", scope="edit", program="attn_edit",
                  sidecar="sc.npz", streams=[1], words=[], **attn_summary)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    q = by_kind["quality"]
    assert set(QUALITY_SUMMARY_FIELDS) <= set(q)
    assert {"program", "sidecar", "background_psnr", "mask_coverage"} <= set(q)
    a = by_kind["attn_maps"]
    assert set(ATTN_SUMMARY_FIELDS) <= set(a)
    assert {"scope", "program", "sidecar", "streams", "words",
            "mask_cov_final", "blend_active_steps"} <= set(a)
    assert a["steps"] == 3 and a["sites"] == ["b/attn2"]
    # per-frame curves exist for the sidecar side of the contract
    assert {"recon_psnr_frames", "background_psnr_frames"} <= set(curves)


def test_comm_and_device_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 5): the ``comm_analysis`` / ``device_telemetry`` /
    per-device ``memory`` / ``divergence`` ledger events carry their
    documented field sets — obs/history.py rules, both tools and the HTML
    report key on these names."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.comm import (
        COMM_ANALYSIS_FIELDS,
        DEVICE_TELEMETRY_FIELDS,
        comm_analysis_record,
        summarize_device_stats,
    )
    from videop2p_tpu.parallel import make_mesh

    # a minimal partitioned program: the sharded sum's partial results
    # meet in an all-reduce, so the record has real collectives in it
    mesh = make_mesh((1, 8, 1))
    sds = jax.ShapeDtypeStruct(
        (16, 16), jnp.float32, sharding=NamedSharding(mesh, P("frames"))
    )
    comm_rec = comm_analysis_record(jax.jit(lambda x: x.sum()).lower(sds).compile())
    assert comm_rec is not None
    assert set(COMM_ANALYSIS_FIELDS) <= set(comm_rec)
    assert comm_rec["num_partitions"] == 8
    assert comm_rec["collective_count"] >= 1

    dev_rec = summarize_device_stats({
        "device_abs_max": np.ones((3, 8)),
        "device_mean": np.zeros((3, 8)),
        "device_nan_count": np.zeros((3, 8)),
        "device_inf_count": np.zeros((3, 8)),
        "divergence": np.zeros(3),
    }, device_ids=list(range(8)))
    assert set(DEVICE_TELEMETRY_FIELDS) <= set(dev_rec)

    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.comm_analysis("p", comm_rec)
        led.device_telemetry("p", dev_rec)
        led.divergence("train_params", 0.0, axes=["data"])
        led.memory_snapshot(note="pin")
    by_kind = {e["event"]: e for e in read_ledger(path)}
    c = by_kind["comm_analysis"]
    assert set(COMM_ANALYSIS_FIELDS) <= set(c) and c["program"] == "p"
    assert set(DEVICE_TELEMETRY_FIELDS) <= set(by_kind["device_telemetry"])
    v = by_kind["divergence"]
    assert v["label"] == "train_params" and v["value"] == 0.0
    # memory snapshots list EVERY local device (8 on the virtual CPU mesh)
    # with a stable per-entry schema even where memory_stats is missing
    m = by_kind["memory"]
    assert len(m["devices"]) == len(jax.local_devices())
    for entry in m["devices"]:
        assert {"device", "coords", "process_index", "bytes_in_use",
                "peak_bytes_in_use", "bytes_limit", "live_bytes"} <= set(entry)


def test_execute_timing_and_trace_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 6): the ``execute_timing`` and ``trace_analysis``
    ledger events carry their documented field sets — TIMING_RULES, both
    tools and the HTML report's "Where time goes" section key on these
    names — and the reservoir summary matches the pin EXACTLY (drift in
    either direction fails)."""
    from videop2p_tpu.obs import (
        EXECUTE_TIMING_FIELDS,
        TRACE_ANALYSIS_FIELDS,
        LatencyReservoir,
        RunLedger,
        read_ledger,
    )
    from videop2p_tpu.obs.trace import analyze_events

    res = LatencyReservoir()
    for i in range(10):
        res.add(0.01 + i * 1e-4, 0.02 + i * 1e-4)
    assert set(res.summary()) == set(EXECUTE_TIMING_FIELDS)

    record, arrays = analyze_events(
        [("fusion.1", 0, 1_000_000), ("all-reduce.2", 500_000, 1_000_000)],
        [("jit_m", 0, 2_000_000)],
        name="w", trace_dir="/tmp/x",
    )
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.record_execute("edit", 0.01, 0.02)
        led.flush_execute_timing()
        led.event("trace_analysis", sidecar="s.npz", **record)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    et = by_kind["execute_timing"]
    assert set(EXECUTE_TIMING_FIELDS) <= set(et)
    assert et["program"] == "edit" and et["count"] == 1
    ta = by_kind["trace_analysis"]
    assert set(TRACE_ANALYSIS_FIELDS) <= set(ta)
    assert ta["sidecar"] == "s.npz" and ta["name"] == "w"
    assert 0.0 <= ta["overlap_fraction"] <= 1.0
    # the close() flush is idempotent over an already-flushed reservoir:
    # exactly one more event (same count), not a duplicate explosion
    events = read_ledger(path)
    assert [e["count"] for e in events
            if e["event"] == "execute_timing"] == [1, 1]


def test_fault_and_serve_health_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 9): the ``fault`` / ``breaker`` / ``serve_health``
    ledger events carry their documented field sets, FAULT_RULES ride in
    DEFAULT_RULES, and obs/history.py's reliability section extracts them
    — tools/obs_diff.py's reliability table and exit-1 teeth key on these
    names."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        FAULT_RULES,
        extract_run,
        split_runs,
    )
    from videop2p_tpu.serve.faults import (
        BREAKER_EVENT_FIELDS,
        FAULT_EVENT_FIELDS,
        SERVE_HEALTH_FIELDS,
    )

    assert all(r in DEFAULT_RULES for r in FAULT_RULES)
    assert {r.metric for r in FAULT_RULES} == {
        "error_rate", "shed_rate", "breaker_trips", "deadline_exceeded"}
    assert all(r.kind == "reliability" for r in FAULT_RULES)

    health = {k: 0 for k in SERVE_HEALTH_FIELDS}
    health.update(requests=3, done=2, errors=1, error_rate=round(1 / 3, 4))
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.fault("backend_unavailable", detail="attempt=4")
        led.breaker("closed", "open", consecutive_failures=2, trips=1)
        led.event("serve_health", **health)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    assert set(FAULT_EVENT_FIELDS) <= set(by_kind["fault"])
    assert by_kind["fault"]["kind"] == "backend_unavailable"
    assert set(BREAKER_EVENT_FIELDS) <= set(by_kind["breaker"])
    assert set(SERVE_HEALTH_FIELDS) <= set(by_kind["serve_health"])
    rec = extract_run(split_runs(read_ledger(path))[-1])
    rel = rec["reliability"]["serve"]
    assert set(SERVE_HEALTH_FIELDS) <= set(rel)
    assert rel["error_rate"] == round(1 / 3, 4)
    # pre-PR-9 ledgers extract an empty (but present) reliability section
    assert extract_run([{"event": "run_start"}])["reliability"] == {}


def test_span_and_slo_report_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 14): the ``span`` and ``slo_report`` ledger
    events carry their documented field sets, SLO_RULES + SEGMENT_RULES
    ride in DEFAULT_RULES (kinds "slo" / "segment"), and obs/history.py
    extracts both new sections — tools/obs_diff.py's SLO/segment tables
    and exit-1 teeth key on these names."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        SEGMENT_RULES,
        SLO_RULES,
        extract_run,
        split_runs,
    )
    from videop2p_tpu.obs.slo import (
        DEFAULT_SLOS,
        SLO_REPORT_FIELDS,
        emit_slo_reports,
    )
    from videop2p_tpu.obs.spans import (
        SPAN_EVENT_FIELDS,
        SPAN_SEGMENTS,
        Tracer,
        make_span_id,
        make_trace_id,
    )

    assert all(r in DEFAULT_RULES for r in SLO_RULES + SEGMENT_RULES)
    assert {r.metric for r in SLO_RULES} == {"budget_burn", "compliant"}
    assert all(r.kind == "slo" for r in SLO_RULES)
    assert {r.metric for r in SEGMENT_RULES} == {"p50_s", "p99_s"}
    assert all(r.kind == "segment" for r in SEGMENT_RULES)
    # the default objectives cover the serving AND streaming tiers
    assert {s.name for s in DEFAULT_SLOS} == {
        "availability", "deadline_miss_rate", "served_p99_latency",
        "seam_min_psnr"}

    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        tracer = Tracer(led, enabled=True)
        tid = make_trace_id()
        tracer.emit("serve.dispatch", trace_id=tid, span_id=make_span_id(),
                    duration_s=0.25, batch_size=2)
        emit_slo_reports(led, {
            "reliability": {"serve": {"error_rate": 0.005, "requests": 10,
                                      "deadline_exceeded": 0}},
        })
    by_kind = {}
    for e in read_ledger(path):
        by_kind.setdefault(e["event"], e)
    assert set(SPAN_EVENT_FIELDS) <= set(by_kind["span"])
    assert by_kind["span"]["name"] in SPAN_SEGMENTS
    assert set(SLO_REPORT_FIELDS) <= set(by_kind["slo_report"])
    rec = extract_run(split_runs(read_ledger(path))[-1])
    assert rec["segments"]["dispatch"]["count"] == 1.0
    assert rec["segments"]["dispatch"]["p99_s"] == 0.25
    assert rec["slo"]["availability"]["budget_burn"] == pytest.approx(0.5)
    assert rec["slo"]["availability"]["compliant"] == 1.0
    # pre-PR-14 ledgers extract empty (but present) sections
    old = extract_run([{"event": "run_start"}])
    assert old["segments"] == {} and old["slo"] == {}


def test_fleet_signals_and_series_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 17): the ``fleet_signals`` and ``fleet_series``
    ledger events carry their documented field sets, SIGNAL_RULES ride in
    DEFAULT_RULES (kind "signal"), and obs/history.py extracts the new
    `signals` section — tools/obs_diff.py's fleet table and exit-1 teeth
    key on these names."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        SIGNAL_RULES,
        extract_run,
        split_runs,
    )
    from videop2p_tpu.obs.signals import (
        FLEET_SIGNALS_FIELDS,
        FLEET_TENANT_FIELDS,
        S_IN_FLIGHT,
        S_QUEUE_DEPTH,
        S_REQUESTS,
        S_TENANT,
        S_UP,
        SignalEngine,
    )
    from videop2p_tpu.obs.tsdb import FLEET_SERIES_FIELDS, TimeSeriesStore

    assert all(r in DEFAULT_RULES for r in SIGNAL_RULES)
    assert all(r.kind == "signal" for r in SIGNAL_RULES)
    assert {r.metric for r in SIGNAL_RULES} == {
        "burn_alerts", "scrape_error_rate", "saturation"}

    # a minimal degraded fleet: one replica, 50% of finished requests
    # erroring — both burn windows blow the 1% objective, alert fires
    ts = TimeSeriesStore(capacity=64)
    eng = SignalEngine(ts, window_scale=0.01)  # fast 3 s / slow 36 s
    lab = {"replica": "replica0"}
    for i in range(6):
        t = float(i)
        ts.add(S_UP, t, 1.0, lab)
        ts.add(S_QUEUE_DEPTH, t, 1.0, lab)
        ts.add(S_IN_FLIGHT, t, 1.0, lab)
        ts.add(S_REQUESTS, t, float(i), {**lab, "status": "done"})
        ts.add(S_REQUESTS, t, float(i), {**lab, "status": "error"})
        ts.add(S_TENANT, t, float(i),
               {**lab, "tenant": "A", "field": "submitted"})
        ts.add(S_TENANT, t, float(i), {**lab, "tenant": "A", "field": "done"})
    # ISSUE 18 satellite: reservoir trace-id exemplars thread into the
    # evaluation record and the burn-alert reason NAMES a trace
    eng.set_exemplars({"edit": {"p99_trace_id": "tid-p99",
                                "max_trace_id": "tid-max"}})
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        rec = eng.evaluate(5.5, ledger=led)
        ts.snapshot(led, label="fleet",
                    sidecar_path=str(tmp_path / "series.npz"))
    assert set(rec) == set(FLEET_SIGNALS_FIELDS)
    assert rec["burn_alert"] is True and rec["scale_advice"] == "grow"
    assert set(rec["tenants"]["A"]) == set(FLEET_TENANT_FIELDS)
    assert rec["exemplars"]["edit"]["p99_trace_id"] == "tid-p99"
    assert any("tid-p99" in r for r in rec["reasons"])
    by_kind = {e["event"]: e for e in read_ledger(path)}
    assert set(FLEET_SIGNALS_FIELDS) <= set(by_kind["fleet_signals"])
    assert set(FLEET_SERIES_FIELDS) <= set(by_kind["fleet_series"])
    run = extract_run(split_runs(read_ledger(path))[-1])
    sig = run["signals"]
    assert sig["fleet"]["burn_alerts"] == 1.0
    assert sig["fleet"]["advice_grow"] == 1.0
    assert sig["fleet:tenant:A"]["submitted_rate"] > 0.0
    assert sig["fleet:series"]["samples"] > 0.0
    # pre-PR-17 ledgers extract an empty (but present) signals section
    assert extract_run([{"event": "run_start"}])["signals"] == {}


def test_incident_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 18): the ``incident`` ledger event carries
    INCIDENT_FIELDS, INCIDENT_RULES ride in DEFAULT_RULES (kind
    "incident", any-increase), and obs/history.py extracts the
    ``incidents`` section with the overall label SEEDED at zero — a
    healthy baseline must hold the label so a chaos run's first bundle
    regresses against it with obs_diff exit-1 teeth."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        INCIDENT_RULES,
        evaluate_rules,
        extract_run,
        split_runs,
    )
    from videop2p_tpu.obs.incident import (
        INCIDENT_FIELDS,
        INCIDENT_TRIGGERS,
        IncidentManager,
    )

    assert all(r in DEFAULT_RULES for r in INCIDENT_RULES)
    assert all(r.kind == "incident" for r in INCIDENT_RULES)
    assert {r.metric for r in INCIDENT_RULES} == {"count", "suppressed"}
    assert all(r.threshold_pct == 0.0 for r in INCIDENT_RULES)
    assert set(INCIDENT_TRIGGERS) == {
        "burn_alert", "breaker_open", "deadline_exceeded",
        "window_poisoned", "crash", "sigusr1", "probe_failed"}

    path = str(tmp_path / "ledger.jsonl")
    mgr = IncidentManager(str(tmp_path / "inc"), cooldown_s=3600.0,
                          crash_hooks=False)
    with RunLedger(path) as led:
        mgr.attach_ledger(led)
        led.event("fault", kind="dispatch_error", error="boom")
        bundle = mgr.trigger("breaker_open", detail="closed->open")
        assert mgr.trigger("breaker_open", detail="flap") is None  # debounced
    assert bundle is not None and os.path.isdir(bundle)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    assert set(INCIDENT_FIELDS) <= set(by_kind["incident"])
    assert by_kind["incident"]["trigger"] == "breaker_open"

    run = extract_run(split_runs(read_ledger(path))[-1])
    assert run["incidents"]["incident"]["count"] == 1.0
    assert run["incidents"]["incident:breaker_open"]["count"] == 1.0
    # a run with NO incident events still extracts the seeded zero label
    healthy = extract_run([{"event": "run_start"}])
    assert healthy["incidents"] == {
        "incident": {"count": 0.0, "suppressed": 0.0, "events": 0.0}}
    # verdict teeth: healthy vs incident regresses; self-compare passes
    assert not evaluate_rules(healthy, run)["pass"]
    assert evaluate_rules(run, run)["pass"]
    assert evaluate_rules(healthy, healthy)["pass"]
    mgr.close()


def test_router_and_tenant_ledger_event_schema(tmp_path):
    """Schema pin (ISSUE 11): the ``router_health`` event and the
    per-tenant ``serve_health`` sub-records carry their documented field
    sets, and obs/history.py flattens both into the reliability section —
    the fleet's obs_diff gates key on these names."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs
    from videop2p_tpu.serve.faults import (
        SERVE_HEALTH_FIELDS,
        SERVE_TENANT_FIELDS,
    )
    from videop2p_tpu.serve.router import ROUTER_HEALTH_FIELDS

    health = {k: 0 for k in SERVE_HEALTH_FIELDS}
    health.update(requests=4, done=3, errors=1, error_rate=0.25)
    tenants = {
        "A": {k: 0 for k in SERVE_TENANT_FIELDS},
        "B": {**{k: 0 for k in SERVE_TENANT_FIELDS},
              "shed": 2, "shed_rate": 0.5},
    }
    router = {k: 0 for k in ROUTER_HEALTH_FIELDS}
    router.update(replicas=2, healthy=1, routed_around=3,
                  per_replica={"replica0": 1, "replica1": 3})
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.event("serve_health", tenants=tenants, **health)
        led.event("router_health", **router)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    assert set(SERVE_TENANT_FIELDS) <= set(by_kind["serve_health"]["tenants"]["A"])
    assert set(ROUTER_HEALTH_FIELDS) <= set(by_kind["router_health"])
    rec = extract_run(split_runs(read_ledger(path))[-1])
    rel = rec["reliability"]
    # the fleet summary and every tenant lane get their own labels, so
    # FAULT_RULES (error_rate/shed_rate/...) gate each one independently
    assert {"serve", "serve:tenant:A", "serve:tenant:B", "router"} <= set(rel)
    assert set(SERVE_TENANT_FIELDS) <= set(rel["serve:tenant:B"])
    assert rel["serve:tenant:B"]["shed_rate"] == 0.5
    assert set(ROUTER_HEALTH_FIELDS) <= set(rel["router"])
    assert rel["router"]["routed_around"] == 3.0
    # engine-side constants agree with the ledger surface: the engine's
    # per-tenant records carry exactly the pinned keys
    from videop2p_tpu.serve.engine import EditEngine

    # ISSUE 19: the chargeback fields ride the same records — counters
    # plus rates plus the measured cost-plane columns cover the pin
    assert set(EditEngine._TENANT_COUNTER_KEYS) | {
        "error_rate", "shed_rate", "device_seconds",
        "saved_device_seconds"} == set(SERVE_TENANT_FIELDS)


def test_cost_plane_schema_pins_and_extraction(tmp_path):
    """Schema pin (ISSUE 19): the cost plane's field tuples are pinned
    byte-for-byte — terminal request ``cost`` vectors, the
    ``cost_attribution`` chargeback rows, the engine capacity roll-up —
    COST_RULES ride in DEFAULT_RULES (kind "cost", teeth for
    cost_per_request/utilization/padding-waste regressions), and
    obs/history.py flattens attribution rows into the ``cost`` section
    under the serve / serve:tenant:X / serve:program:Y label scheme."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.cost import (
        CAPACITY_FIELDS,
        COST_ATTRIBUTION_FIELDS,
        REQUEST_COST_FIELDS,
    )
    from videop2p_tpu.obs.history import (
        COST_RULES,
        DEFAULT_RULES,
        extract_run,
        split_runs,
    )

    assert REQUEST_COST_FIELDS == (
        "program", "device_seconds", "flops", "hbm_byte_seconds",
        "queue_seconds", "padding_share", "saved_device_seconds",
        "saved_flops")
    assert COST_ATTRIBUTION_FIELDS == (
        "scope", "name", "requests", "store_hits", "device_seconds",
        "flops", "hbm_byte_seconds", "queue_seconds",
        "saved_device_seconds", "saved_flops", "cost_per_request_s")
    assert CAPACITY_FIELDS == (
        "uptime_s", "busy_seconds", "attributed_seconds",
        "padding_seconds", "idle_seconds", "busy_fraction",
        "idle_fraction", "padding_waste", "occupancy", "dispatches",
        "real_slots", "padded_slots", "requests_costed",
        "cost_per_request_s", "conservation_residual_s")
    # the rules gate by default, all kind "cost", utilization pointing
    # the economic way (busy_fraction regresses by DECREASING)
    assert set(COST_RULES) <= set(DEFAULT_RULES)
    assert all(r.kind == "cost" for r in COST_RULES)
    by_metric = {r.metric: r for r in COST_RULES}
    assert set(by_metric) == {"cost_per_request_s", "busy_fraction",
                              "padding_waste", "idle_fraction"}
    assert by_metric["busy_fraction"].direction == "decrease"
    # extraction: engine/tenant/program rows land under the documented
    # label scheme; a pre-cost-plane ledger extracts an empty section
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.event("cost_attribution", label="serve", scope="engine",
                  name="serve", busy_fraction=0.5, cost_per_request_s=0.2)
        led.event("cost_attribution", label="serve", scope="tenant",
                  name="A", requests=3, device_seconds=0.6)
        led.event("cost_attribution", label="serve", scope="program",
                  name="serve_edit", requests=3, flops=9.0)
    rec = extract_run(split_runs(read_ledger(path))[-1])
    assert set(rec["cost"]) == {"serve", "serve:tenant:A",
                                "serve:program:serve_edit"}
    assert rec["cost"]["serve"]["busy_fraction"] == 0.5
    assert rec["cost"]["serve:tenant:A"]["device_seconds"] == 0.6
    empty = str(tmp_path / "old.jsonl")
    with RunLedger(empty) as led:
        led.event("serve_health", requests=1)
    assert extract_run(split_runs(read_ledger(empty))[-1])["cost"] == {}


def test_stream_health_ledger_event_schema_and_seam_rules(tmp_path):
    """Schema pin (ISSUE 12): the ``stream_health`` summary carries its
    documented field set, SEAM_RULES ride in DEFAULT_RULES (kind
    "stream"), obs/history.py extracts the event into the `stream`
    section — and the gate semantics hold: identical runs self-compare
    clean, a seam-PSNR drop / a new passthrough / a nonzero src_err_max
    regress with obs_diff exit-1 teeth."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        SEAM_RULES,
        evaluate_rules,
        extract_run,
        split_runs,
    )
    from videop2p_tpu.stream.driver import (
        STREAM_HEALTH_FIELDS,
        STREAM_SEAM_FIELDS,
        STREAM_WINDOW_FIELDS,
    )

    assert all(r in DEFAULT_RULES for r in SEAM_RULES)
    assert all(r.kind == "stream" for r in SEAM_RULES)
    assert {r.metric for r in SEAM_RULES} == {
        "seam_min_psnr", "seam_mean_psnr", "windows_failed",
        "windows_passthrough", "manifest_corrupt", "src_err_max"}

    health = {k: 0 for k in STREAM_HEALTH_FIELDS}
    health.update(windows_total=4, windows_done=4, seams=3,
                  seam_min_psnr=24.0, seam_mean_psnr=30.0,
                  source_seam_min_psnr=26.0, src_err_max=0.0)
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.event("stream_window", index=0, key="k", status="done",
                  attempts=1, store_source="fresh", src_err=0.0,
                  window_s=0.5)
        led.event("stream_seam", left=0, right=1, start=3, stop=4,
                  seam_psnr=24.0, source_psnr=26.0)
        led.event("stream_health", **health)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    assert set(STREAM_WINDOW_FIELDS) <= set(by_kind["stream_window"])
    assert set(STREAM_SEAM_FIELDS) <= set(by_kind["stream_seam"])
    assert set(STREAM_HEALTH_FIELDS) <= set(by_kind["stream_health"])
    rec = extract_run(split_runs(read_ledger(path))[-1])
    assert set(STREAM_HEALTH_FIELDS) <= set(rec["stream"]["stream"])
    # pre-PR-12 ledgers extract an empty (but present) stream section
    assert extract_run([{"event": "run_start"}])["stream"] == {}

    # gate semantics: self-compare clean; seam drop / new passthrough /
    # nonzero src_err_max regress
    assert evaluate_rules(rec, rec, SEAM_RULES)["pass"]
    worse = {**rec, "stream": {"stream": {
        **rec["stream"]["stream"],
        "seam_min_psnr": 12.0, "windows_passthrough": 1.0,
    }}}
    result = evaluate_rules(rec, worse, SEAM_RULES)
    assert not result["pass"]
    assert {v["metric"] for v in result["regressions"]} == {
        "seam_min_psnr", "windows_passthrough"}
    # src_err_max is an exactness invariant: nonzero fails SELF-compare
    diverged = {**rec, "stream": {"stream": {
        **rec["stream"]["stream"], "src_err_max": 1e-6,
    }}}
    assert not evaluate_rules(diverged, diverged, SEAM_RULES)["pass"]
    # inf→inf (a single-window job with no seams) passes clean
    no_seams = {**rec, "stream": {"stream": {
        **rec["stream"]["stream"],
        "seam_min_psnr": float("inf"), "seam_mean_psnr": float("inf"),
    }}}
    assert evaluate_rules(no_seams, no_seams, SEAM_RULES)["pass"]


def test_streaming_window_record_schema(bench):
    """Schema pin (ISSUE 12): `streaming_window_records` turns one
    per-window analysis into the 128f/480f streaming evidence rows —
    exact window counts from the REAL planner, linear flop/store
    scaling, every record carrying exactly STREAMING_WINDOW_FIELDS."""
    records = bench.streaming_window_records(
        {"e2e_cached": {"flops": 2.0e13, "temp_bytes": 1}}
    )
    assert [r["total_frames"] for r in records] == [128, 480]
    by_total = {r["total_frames"]: r for r in records}
    # the planner's counts: stride 6 with the final window end-anchored
    assert by_total[128]["windows"] == 21
    assert by_total[480]["windows"] == 80
    for r in records:
        assert set(r) == set(bench.STREAMING_WINDOW_FIELDS), r
        assert r["window"] == bench.BENCH_FRAMES
        assert r["flops_per_window"] == 2.0e13
        assert r["flops_total"] == 2.0e13 * r["windows"]
        assert r["store_bytes_total"] == \
            r["store_bytes_per_window"] * r["windows"]
        assert r["frames_processed"] == r["windows"] * r["window"]
        assert r["overlap_overhead"] == pytest.approx(
            r["frames_processed"] / r["total_frames"] - 1.0, abs=1e-3)
        # one fp32 trajectory of steps+1 latent stacks per window
        assert r["store_bytes_per_window"] == \
            (bench.BENCH_STEPS + 1) * r["window"] * 64 * 64 * 4 * 4
    # an incomplete capture still records the static plan geometry
    no_flops = bench.streaming_window_records({})
    assert all(r["flops_per_window"] is None and r["flops_total"] is None
               for r in no_flops)
    assert [r["windows"] for r in no_flops] == [21, 80]


def test_no_wall_clock_in_timed_regions():
    """Satellite guard (ISSUE 2): every timed region in the package uses
    the monotonic clock — ``time.time()`` steps under NTP adjustment and
    corrupted phase records. Grep-based so a reintroduction anywhere in
    videop2p_tpu/ fails loudly with the offending lines."""
    offenders = []
    pkg = os.path.join(_REPO, "videop2p_tpu")
    for root, _, files in os.walk(pkg):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    if "time.time()" in line:
                        offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "time.time() reintroduced in a timed region — use "
        "time.perf_counter():\n" + "\n".join(offenders)
    )


# ---------------------------------------------------- __graft_entry__.py --


def test_dryrun_decision_never_probes_the_real_backend(graft, monkeypatch):
    """With JAX_PLATFORMS pointing anywhere but cpu, dryrun_multichip must
    re-exec a CPU subprocess without ever calling jax.devices() in the
    parent — that exact probe hung the r4 driver with an unhealthy TPU."""
    monkeypatch.setenv("JAX_PLATFORMS", "axon")

    def poisoned_devices(*a, **kw):
        pytest.fail("dryrun_multichip touched the parent's backend")

    monkeypatch.setattr(graft.jax, "devices", poisoned_devices)

    seen = {}

    def fake_run(cmd, **kw):
        seen["cmd"], seen["env"] = cmd, kw.get("env", {})
        seen["timeout"] = kw.get("timeout")
        return types.SimpleNamespace(returncode=0, stdout="ok\n", stderr="")

    monkeypatch.setattr(graft.subprocess, "run", fake_run)
    graft.dryrun_multichip(8)

    assert seen["env"]["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in seen["env"]["XLA_FLAGS"]
    assert seen["timeout"] is not None  # a wedged child cannot hang the driver
    assert "dryrun" in seen["cmd"]


def test_dryrun_subprocess_failure_is_a_readable_error(graft, monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(
        graft.jax, "devices",
        lambda *a, **kw: pytest.fail("touched the parent's backend"),
    )
    monkeypatch.setattr(
        graft.subprocess, "run",
        lambda cmd, **kw: types.SimpleNamespace(
            returncode=3, stdout="", stderr="boom"
        ),
    )
    with pytest.raises(RuntimeError, match="rc=3"):
        graft.dryrun_multichip(8)


def test_dryrun_subprocess_timeout_is_a_readable_error(graft, monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(
        graft.jax, "devices",
        lambda *a, **kw: pytest.fail("touched the parent's backend"),
    )

    def raise_timeout(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0), stderr=b"slow")

    monkeypatch.setattr(graft.subprocess, "run", raise_timeout)
    with pytest.raises(RuntimeError, match="exceeded"):
        graft.dryrun_multichip(8, timeout_s=1.0)


def test_dryrun_reexecs_when_config_overrides_cpu_env(graft, monkeypatch):
    """This image's sitecustomize hard-sets jax_platforms='axon,cpu' via
    jax.config, which beats the JAX_PLATFORMS env var — so env=cpu alone is
    NOT proof that jax.devices() can't init the real backend. The decision
    must consult the effective config value."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(
        type(graft.jax.config), "jax_platforms",
        property(lambda self: "axon,cpu"), raising=False,
    )
    monkeypatch.setattr(
        graft.jax, "devices",
        lambda *a, **kw: pytest.fail("touched the parent's backend"),
    )
    seen = {}
    monkeypatch.setattr(
        graft.subprocess, "run",
        lambda cmd, **kw: seen.update(env=kw.get("env", {})) or
        types.SimpleNamespace(returncode=0, stdout="", stderr=""),
    )
    graft.dryrun_multichip(8)
    assert seen["env"]["JAX_PLATFORMS"] == "cpu"


def test_dryrun_runs_inline_when_already_on_a_big_cpu_mesh(graft, monkeypatch):
    """When the process is already pinned to cpu with enough devices (the
    test-suite configuration), no subprocess indirection should happen."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(graft.jax, "devices", lambda *a, **kw: list(range(8)))
    monkeypatch.setattr(
        graft.subprocess, "run",
        lambda *a, **kw: pytest.fail("re-exec'd despite a sufficient cpu mesh"),
    )
    ran = {}
    monkeypatch.setattr(graft, "_dryrun_impl", lambda n: ran.setdefault("n", n))
    graft.dryrun_multichip(8)
    assert ran["n"] == 8


@pytest.mark.slow
def test_dryrun_writes_obs_ledger_acceptance(graft, tmp_path, monkeypatch):
    """The ISSUE 5 acceptance criterion, end to end on the in-process
    8-device CPU mesh: the dryrun writes dryrun_ledger.jsonl with ≥1
    comm_analysis event carrying nonzero collective bytes, a per-device
    memory snapshot, and passing divergence verdicts; obs_diff self-compare
    exits 0 and an injected +20% collective-bytes delta exits 1 with a
    machine-readable comm verdict."""
    ledger = str(tmp_path / "dryrun_ledger.jsonl")
    monkeypatch.setenv("VIDEOP2P_DRYRUN_LEDGER", ledger)
    graft._dryrun_impl(8)

    events = [json.loads(l) for l in open(ledger) if l.strip()]
    comm = [e for e in events if e["event"] == "comm_analysis"]
    assert any(e["collective_bytes"] > 0 for e in comm)
    assert any(e["event"] == "memory" and e.get("devices") for e in events)
    divs = [e for e in events if e["event"] == "divergence"]
    assert divs and all(e["value"] == 0.0 for e in divs)
    dev = [e for e in events if e["event"] == "device_telemetry"]
    assert dev and all(e["divergence_max"] == 0.0 for e in dev)

    obs_diff = _load_module("obs_diff_under_graft_test", "tools/obs_diff.py")
    assert obs_diff.main(["obs_diff.py", ledger, ledger]) == 0
    # inject +20% collective bytes into a copy → nonzero exit + verdict
    perturbed = str(tmp_path / "perturbed.jsonl")
    with open(perturbed, "w") as f:
        for e in events:
            if e["event"] == "comm_analysis":
                e = dict(e, collective_bytes=int(e["collective_bytes"] * 1.2))
            f.write(json.dumps(e) + "\n")
    assert obs_diff.main(["obs_diff.py", ledger, perturbed]) == 1


@pytest.mark.slow
def test_cpu_cost_capture_tool_end_to_end_tiny(bench, tmp_path):
    """The real subprocess path at tiny scale: the tool builds the bench
    programs abstractly, compiles them on CPU, and emits one JSON record
    per program plus program_analysis ledger events."""
    ledger = str(tmp_path / "capture_ledger.jsonl")
    out = bench.collect_cpu_analysis(2, 2, tiny=True, timeout_s=560.0,
                                     ledger_path=ledger)
    assert set(out) == {"invert_captured", "edit_cached", "e2e_cached"}
    for name, rec in out.items():
        assert rec["flops"] > 0, name
        assert rec["peak_hbm_bytes"] > 0, name
        assert len(rec["hlo_fingerprint"]) == 16, name
        assert rec["backend"] == "cpu" and rec["steps"] == 2
    events = [json.loads(l) for l in open(ledger) if l.strip()]
    pa = {e["program"] for e in events if e["event"] == "program_analysis"}
    assert pa == set(out)


def test_frame_scaling_record_schema(bench):
    """ISSUE 10: the per-frame-count scale-out records are schema-pinned —
    every ring record carries exactly FRAME_SCALING_FIELDS with the
    vs-serial ratios, the tp pairing exactly TP_PAIRING_FIELDS, and
    degenerate inputs yield empty/None instead of raising."""
    analyses = {
        "ring_unit_serial_f64": {"collective_permute_count": 16,
                                 "collective_permute_bytes": 8192,
                                 "flops": 100, "shards": 8},
        "ring_unit_overlap_f64": {"collective_permute_count": 14,
                                  "collective_permute_bytes": 7168,
                                  "flops": 90, "shards": 8},
        "ring_unit_bidir_f64": {"collective_permute_count": 28,
                                "collective_permute_bytes": 7168,
                                "flops": 95, "shards": 8},
        "ring_unit_overlap_f8": {"collective_permute_count": 14,
                                 "collective_permute_bytes": 896,
                                 "flops": 9, "shards": 8},
        "tp_unit_gspmd": {"all_reduce_bytes": 32768, "flops": 7, "shards": 8},
        "tp_unit_scatter": {"reduce_scatter_bytes": 4096, "flops": 7,
                            "shards": 8},
        "not_a_ring_unit": {"flops": 1},
    }
    records = bench.frame_scaling_records(analyses)
    assert [r["frames"] for r in records] == [8, 64, 64, 64]
    for r in records:
        assert set(r) == set(bench.FRAME_SCALING_FIELDS), r
    by = {(r["frames"], r["variant"]): r for r in records}
    assert by[(64, "overlap")]["permute_count_vs_serial"] == round(14 / 16, 3)
    assert by[(64, "overlap")]["permute_bytes_vs_serial"] == 0.875
    assert by[(64, "bidir")]["bytes_per_permute"] == 7168 // 28
    # the 8-frame group has no serial record → ratios None, shape stable
    assert by[(8, "overlap")]["permute_count_vs_serial"] is None

    tp = bench.tp_pairing_record(analyses)
    assert set(tp) == set(bench.TP_PAIRING_FIELDS)
    assert tp["bytes_reduction"] == 8.0
    assert bench.frame_scaling_records({}) == []
    assert bench.tp_pairing_record({}) is None
    assert bench.tp_pairing_record({"tp_unit_gspmd": {"all_reduce_bytes": 1,
                                                      "shards": 8}}) is None


def test_per_call_cost_record_schema(bench):
    """ISSUE 15: the per-UNet-call cost records are schema-pinned — every
    row carries exactly PER_CALL_COST_FIELDS, quant rows normalize against
    ONE fp call, reuse rows against K fp calls for flops/bytes but ONE for
    argument bytes (weights are passed once however many steps read them),
    a missing fp unit yields None ratios, and no unit analyses yield []."""
    analyses = {
        "unet_unit_fp": {"flops": 1000, "bytes_accessed": 2000,
                         "argument_bytes": 400, "peak_hbm_bytes": 50},
        "unet_unit_w8": {"flops": 1010, "bytes_accessed": 1900,
                         "argument_bytes": 100, "peak_hbm_bytes": 40},
        "unet_unit_w8a8": {"flops": 1100, "bytes_accessed": 2100,
                           "argument_bytes": 100, "peak_hbm_bytes": 40},
        "reuse_unit_2": {"flops": 1600, "bytes_accessed": 3400,
                         "argument_bytes": 410, "peak_hbm_bytes": 60},
        "reuse_unit_5": {"flops": 3750, "bytes_accessed": 8000,
                         "argument_bytes": 430, "peak_hbm_bytes": 65},
        "reuse_unit_x": {"flops": 1},   # malformed suffix: ignored
        "distill_unit_fp": {"flops": 1004, "bytes_accessed": 2010,
                            "argument_bytes": 404, "peak_hbm_bytes": 51},
        "distill_unit_2": {"flops": 2008, "bytes_accessed": 4020,
                           "argument_bytes": 414, "peak_hbm_bytes": 62},
        "distill_unit_x": {"flops": 1},  # malformed suffix: ignored
        "e2e_cached": {"flops": 9},     # not a per-call unit: ignored
    }
    records = bench.per_call_cost_records(analyses)
    assert [r["program"] for r in records] == [
        "unet_unit_fp", "unet_unit_w8", "unet_unit_w8a8",
        "reuse_unit_2", "reuse_unit_5",
        "distill_unit_fp", "distill_unit_2",
    ]
    for r in records:
        assert set(r) == set(bench.PER_CALL_COST_FIELDS), r
    by = {r["program"]: r for r in records}
    assert by["unet_unit_fp"]["flops_vs_full"] == 1.0
    assert by["unet_unit_fp"]["calls"] == 1
    assert by["unet_unit_w8"]["quant_mode"] == "w8"
    assert by["unet_unit_w8"]["argument_bytes_vs_full"] == 0.25
    assert by["unet_unit_w8a8"]["quant_mode"] == "w8a8"
    assert by["reuse_unit_2"]["reuse_schedule"] == "uniform:2"
    assert by["reuse_unit_2"]["calls"] == 2
    assert by["reuse_unit_2"]["flops_vs_full"] == 0.8    # 1600 / (2*1000)
    assert by["reuse_unit_5"]["flops_vs_full"] == 0.75   # 3750 / (5*1000)
    assert by["reuse_unit_5"]["bytes_vs_full"] == 0.8    # 8000 / (5*2000)
    assert by["reuse_unit_5"]["argument_bytes_vs_full"] == round(430 / 400, 3)
    # ISSUE 16: the student units — distill_unit_fp's flops_vs_full IS the
    # time-head overhead over one teacher call; distill_unit_<N> normalizes
    # against N teacher calls (per-step student-vs-teacher ratio)
    assert by["distill_unit_fp"]["calls"] == 1
    assert by["distill_unit_fp"]["flops_vs_full"] == 1.004  # 1004 / 1000
    assert by["distill_unit_2"]["calls"] == 2
    assert by["distill_unit_2"]["flops_vs_full"] == 1.004   # 2008 / (2*1000)
    # fp unit missing → ratios None but rows still land, shape stable
    partial = bench.per_call_cost_records(
        {k: v for k, v in analyses.items() if k != "unet_unit_fp"}
    )
    assert all(r["flops_vs_full"] is None for r in partial)
    assert all(set(r) == set(bench.PER_CALL_COST_FIELDS) for r in partial)
    assert bench.per_call_cost_records({}) == []
    assert bench.per_call_cost_records(None) == []


def test_bench_cost_records_schema(bench):
    """ISSUE 19: bench's cost rows are schema-pinned — every analyzed
    program lands with exactly BENCH_COST_FIELDS, measured seconds price
    an achieved flops/s, static-only rows (backend down: no timings)
    carry None for both measured columns, and malformed/empty analyses
    yield []."""
    assert bench.BENCH_COST_FIELDS == (
        "program", "flops", "argument_bytes", "peak_hbm_bytes",
        "measured_s", "achieved_flops_per_s")
    analyses = {
        "invert_captured": {"flops": 1000.0, "argument_bytes": 64,
                            "temp_bytes": 8, "peak_hbm_bytes": 128,
                            "bytes_accessed": 256},
        "edit_cached": {"flops": 500.0, "argument_bytes": 32,
                        "peak_hbm_bytes": 100, "bytes_accessed": 90},
        "bogus": "not-a-dict",   # ignored, never raises
    }
    rows = bench.bench_cost_records(analyses,
                                    {"invert_captured": 2.0,
                                     "edit_cached": 0})   # 0 s: unusable
    assert [r["program"] for r in rows] == ["edit_cached",
                                            "invert_captured"]
    for r in rows:
        assert set(r) == set(bench.BENCH_COST_FIELDS), r
    by = {r["program"]: r for r in rows}
    assert by["invert_captured"]["measured_s"] == 2.0
    assert by["invert_captured"]["achieved_flops_per_s"] == 500.0
    assert by["edit_cached"]["measured_s"] is None
    assert by["edit_cached"]["achieved_flops_per_s"] is None
    # static-only path (record_cpu_only_evidence: backend down)
    static = bench.bench_cost_records(analyses)
    assert all(r["measured_s"] is None for r in static)
    assert bench.bench_cost_records({}) == []
    assert bench.bench_cost_records(None) == []


@pytest.mark.slow
def test_dryrun_longvideo_obs_acceptance(graft, tmp_path):
    """The ISSUE 10 acceptance criterion end to end on the in-process
    8-device CPU mesh: the 64-frame dryrun section completes its float8
    sharded cached edit with src_err == 0.0, lands per-frame-count
    frame_scaling events and the ring/tp comm evidence in the ledger, and
    the ring before/after pair gates through tools/obs_diff.py — exit 0 in
    the engineered direction (collective count/bytes DROP), exit 0 on
    self-compare, exit 1 on an injected collective-bytes bump."""
    from videop2p_tpu.obs.ledger import RunLedger

    ledger_path = str(tmp_path / "longvideo_ledger.jsonl")
    led = RunLedger(ledger_path, mesh="1,8,1",
                    meta={"cli": "longvideo_acceptance"}).activate()
    try:
        res = graft._dryrun_longvideo_impl(8, led)
    finally:
        led.close()
    assert res["src_err_64f"] == 0.0
    assert res["ring"]["overlap"]["collective_permute_count"] == 14
    assert res["ring"]["serial"]["collective_permute_count"] == 16

    events = [json.loads(l) for l in open(ledger_path) if l.strip()]
    fs = [e for e in events if e["event"] == "frame_scaling"]
    assert {e["frames"] for e in fs} >= {8, 32, 64}
    edit = [e for e in fs if e["variant"] == "edit"]
    assert edit and edit[0]["src_err"] == 0.0
    assert edit[0]["temporal_maps_dtype"] == "float8_e4m3fn"
    comm = [e for e in events if e["event"] == "comm_analysis"]
    assert any(e["program"] == "sharded_edit_64f" for e in comm)
    assert any(e["program"] == "tp_out_scatter" for e in comm)

    obs_diff = _load_module("obs_diff_under_longvideo_test", "tools/obs_diff.py")
    assert obs_diff.main(
        ["obs_diff.py", res["ring_before"], res["ring_after"]]
    ) == 0
    assert obs_diff.main(["obs_diff.py", ledger_path, ledger_path]) == 0
    perturbed = str(tmp_path / "perturbed.jsonl")
    with open(perturbed, "w") as f:
        for e in events:
            if e["event"] == "comm_analysis":
                e = dict(e, collective_bytes=int(e["collective_bytes"] * 1.2))
            f.write(json.dumps(e) + "\n")
    assert obs_diff.main(["obs_diff.py", ledger_path, perturbed]) == 1


@pytest.mark.slow
def test_cpu_cost_capture_ring_tp_units(bench):
    """The real subprocess path for the distributed unit programs: one
    JSON record per ring variant × frame count (true unrolled counts,
    frames overriding the global flag) plus the tp pairing units."""
    out = bench.collect_cpu_analysis(
        2, 2, tiny=True, timeout_s=560.0,
        programs=("ring_unit_serial_f64", "ring_unit_overlap_f64",
                  "ring_unit_bidir_f64", "tp_unit_gspmd", "tp_unit_scatter"),
    )
    assert set(out) == {"ring_unit_serial_f64", "ring_unit_overlap_f64",
                        "ring_unit_bidir_f64", "tp_unit_gspmd",
                        "tp_unit_scatter"}
    assert out["ring_unit_serial_f64"]["collective_permute_count"] == 16
    assert out["ring_unit_overlap_f64"]["collective_permute_count"] == 14
    assert out["ring_unit_bidir_f64"]["collective_permute_count"] == 28
    assert all(out[p]["frames"] == 64 for p in out if p.startswith("ring"))
    assert (out["tp_unit_scatter"]["reduce_scatter_bytes"]
            == out["tp_unit_gspmd"]["all_reduce_bytes"] // 8)
    records = bench.frame_scaling_records(out)
    assert {r["variant"] for r in records} == {"serial", "overlap", "bidir"}
    assert bench.tp_pairing_record(out)["bytes_reduction"] == 8.0
