"""Correctness plane tests (ISSUE 20).

Layers under test, bottom up:

  * ``obs/probe.py`` — the known-answer suite, probe by probe, against
    scriptable JSON-API fakes: cached-replay exact-zero, determinism
    hash flips, golden-quality bands, store round-trips, the 400
    admission contract, traceparent echo, dead-target containment;
  * ``AnswerAudit`` — majority vote, earliest-observed tie-break,
    reference seeding (the across-restarts anchor), PROBE_AUDIT_FIELDS;
  * ``serve/prober.py`` — run_once over a faked fleet: quarantine of
    the divergent replica, lift on re-agreement, divergence-incident
    dedup, the router's quarantine exemption, tsdb/ledger/signals
    emission;
  * PROBE_RULES obs_diff teeth and the seeded probes section;
  * THE acceptance (slow): a 2-replica fleet where replica 0 serves
    silently WRONG bytes with HTTP 200 — every self-check passes, the
    cross-replica answer audit flags it, the router quarantines it, the
    fleet keeps serving bit-correct answers, and the run regresses
    against the healthy baseline through obs_diff.
"""

import importlib.util
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_probe_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- scriptable fakes -----

_CANARY = dict(image_path="data/rabbit", prompt="a rabbit is jumping",
               prompts=["a rabbit is jumping", "a origami rabbit is jumping"])

_SHA_A = "aa" * 32
_SHA_B = "bb" * 32
_SHA_C = "cc" * 32


class _FakeClient:
    """A JSON-API shaped engine fake with a scriptable answer.

    ``sha`` is the content hash every wait() returns (mutate it to model
    a replica whose answer changes); ``flip_hash`` returns a fresh hash
    per wait (a non-deterministic replica); ``echo_trace`` is True
    (echo), False (echo garbage) or None (tracing off — no trace_id).
    """

    def __init__(self, *, fingerprint="fp-tiny", sha=_SHA_A, src_err=0.0,
                 status="done", psnr=30.0, ssim=0.9, store_hit=True,
                 store_source="memory", echo_trace=True, reject_bad=True,
                 flip_hash=False, dead=False, metrics=None):
        self.fingerprint = fingerprint
        self.sha = sha
        self.src_err = src_err
        self.status = status
        self.psnr = psnr
        self.ssim = ssim
        self.store_hit = store_hit
        self.store_source = store_source
        self.echo_trace = echo_trace
        self.reject_bad = reject_bad
        self.flip_hash = flip_hash
        self.dead = dead
        self._metrics = metrics
        self.submitted = []
        self._pending = {}
        self._n = 0

    def submit(self, request, *, traceparent=None):
        if self.dead:
            raise ConnectionError("connection refused")
        if self.reject_bad and int(request.get("steps") or 0) > 9000:
            raise RuntimeError(
                f"/v1/edits failed with HTTP 400: steps="
                f"{request['steps']} not warmed")
        self.submitted.append(dict(request))
        rid = f"rid{len(self.submitted)}"
        self._pending[rid] = (dict(request), traceparent)
        return rid

    def wait(self, rid, *, timeout_s=600.0):
        request, traceparent = self._pending[rid]
        self._n += 1
        sha = f"{self._n:064x}" if self.flip_hash else self.sha
        rec = {"status": self.status, "src_err": self.src_err,
               "content_sha256": sha, "store_hit": self.store_hit,
               "store_source": self.store_source}
        from videop2p_tpu.obs.probe import PROBE_TENANT
        if request.get("tenant") == PROBE_TENANT:
            rec["edit_psnr"] = self.psnr
            rec["edit_ssim"] = self.ssim
        if traceparent is not None and self.echo_trace is not None:
            rec["trace_id"] = (traceparent.split("-")[1]
                               if self.echo_trace else "f00d" * 8)
        return rec

    def healthz(self):
        return {"status": "ok"}

    def metrics(self):
        if self._metrics is not None:
            return dict(self._metrics)
        return {"spec_fingerprint": self.fingerprint}


# ------------------------------------------------------ probe suite -----


def test_suite_schema_order_and_canary_pinning():
    """Every probe record carries exactly PROBE_EVENT_FIELDS, the suite
    runs the single-target probes in PROBE_KINDS order, and the canary is
    FORCED onto the reserved probe tenant with a pinned seed — so every
    submission is the same known-answer request."""
    from videop2p_tpu.obs.probe import (
        PROBE_EVENT_FIELDS,
        PROBE_KINDS,
        PROBE_TENANT,
        ProbeSuite,
    )

    suite = ProbeSuite(dict(_CANARY))
    assert suite.canary["tenant"] == PROBE_TENANT
    assert suite.canary["seed"] == 8888
    assert suite.canary["save_name"] == "probe_canary"
    # a caller-pinned seed/save_name survives; the tenant never does
    pinned = ProbeSuite(dict(_CANARY, seed=7, save_name="x", tenant="evil"))
    assert pinned.canary["seed"] == 7
    assert pinned.canary["save_name"] == "x"
    assert pinned.canary["tenant"] == PROBE_TENANT

    fake = _FakeClient()
    records = suite.run(fake, "replica0")
    assert [r["probe"] for r in records] == [
        k for k in PROBE_KINDS if k != "store_roundtrip"]
    for rec in records:
        assert set(rec) == set(PROBE_EVENT_FIELDS)
        assert rec["target"] == "replica0"
        assert rec["ok"], rec
    # every canary submission rode the probe lane with the pinned seed
    assert all(r["tenant"] == PROBE_TENANT for r in fake.submitted)
    assert all(r["seed"] == 8888 for r in fake.submitted)


def test_cached_replay_demands_exact_zero():
    """The paper's own invariant: src_err must be EXACTLY 0.0 — a
    near-zero replay error is already a broken cached-replay path."""
    from videop2p_tpu.obs.probe import ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    assert suite.probe_cached_replay(_FakeClient(), "r")["ok"]
    rec = suite.probe_cached_replay(_FakeClient(src_err=1e-9), "r")
    assert not rec["ok"] and "src_err" in rec["detail"]
    assert not suite.probe_cached_replay(
        _FakeClient(status="error"), "r")["ok"]


def test_determinism_catches_hash_flip():
    from videop2p_tpu.obs.probe import ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    rec = suite.probe_determinism(_FakeClient(sha=_SHA_A), "r")
    assert rec["ok"] and rec["content_sha256"] == _SHA_A
    assert rec["detail"] == "bit-identical"
    flip = suite.probe_determinism(_FakeClient(flip_hash=True), "r")
    assert not flip["ok"] and "hashes=" in flip["detail"]
    # a missing hash can never pass (nothing to prove identical)
    assert not suite.probe_determinism(_FakeClient(sha=""), "r")["ok"]


def test_golden_quality_band():
    from videop2p_tpu.obs.probe import ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    assert suite.probe_golden_quality(_FakeClient(), "r")["ok"]
    assert not suite.probe_golden_quality(_FakeClient(psnr=2.0), "r")["ok"]
    assert not suite.probe_golden_quality(_FakeClient(ssim=1.5), "r")["ok"]
    # a replica that never computed the metrics (probe lane broken) fails
    assert not suite.probe_golden_quality(
        _FakeClient(psnr=None, ssim=None), "r")["ok"]
    tight = ProbeSuite(dict(_CANARY), psnr_band=(35.0, 40.0))
    assert not tight.probe_golden_quality(_FakeClient(psnr=30.0), "r")["ok"]


def test_store_roundtrip_cross_replica_invariant():
    from videop2p_tpu.obs.probe import ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    src = _FakeClient(sha=_SHA_A)
    hit = _FakeClient(sha=_SHA_A, store_hit=True, store_source="disk")
    assert suite.probe_store_roundtrip(src, hit, "r0->r1")["ok"]
    miss = _FakeClient(sha=_SHA_A, store_hit=False, store_source=None)
    assert not suite.probe_store_roundtrip(src, miss, "r0->r1")["ok"]
    # a hit that hands back DIFFERENT bytes is the worst case of all
    wrong = _FakeClient(sha=_SHA_B, store_hit=True, store_source="disk")
    rec = suite.probe_store_roundtrip(src, wrong, "r0->r1")
    assert not rec["ok"] and "match=False" in rec["detail"]


def test_contract_unwarmed_steps_must_reject():
    from videop2p_tpu.obs.probe import ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    rec = suite.probe_contract_unwarmed_steps(_FakeClient(), "r")
    assert rec["ok"] and "HTTP 400" in rec["detail"]
    admitted = suite.probe_contract_unwarmed_steps(
        _FakeClient(reject_bad=False), "r")
    assert not admitted["ok"] and "ADMITTED" in admitted["detail"]


def test_contract_traceparent_echo():
    from videop2p_tpu.obs.probe import ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    rec = suite.probe_contract_traceparent(_FakeClient(), "r")
    assert rec["ok"] and "echoed=" in rec["detail"]
    assert not suite.probe_contract_traceparent(
        _FakeClient(echo_trace=False), "r")["ok"]
    # absence of tracing is a configuration, not a bug
    off = suite.probe_contract_traceparent(_FakeClient(echo_trace=None), "r")
    assert off["ok"] and "tracing off" in off["detail"]


def test_dead_target_is_failed_probes_not_an_exception():
    """Probing must never take the prober down with the replica: a dead
    target yields one failed record per probe, exception name inside."""
    from videop2p_tpu.obs.probe import PROBE_EVENT_FIELDS, ProbeSuite

    suite = ProbeSuite(dict(_CANARY))
    records = suite.run(_FakeClient(dead=True), "replica0")
    assert len(records) == 5
    for rec in records:
        assert set(rec) == set(PROBE_EVENT_FIELDS)
        assert not rec["ok"]
        assert "ConnectionError" in rec["detail"]
        assert rec["content_sha256"] == ""


# ----------------------------------------------------- answer audit -----


def test_answer_audit_majority_and_earliest_tiebreak():
    from videop2p_tpu.obs.probe import PROBE_AUDIT_FIELDS, AnswerAudit

    audit = AnswerAudit()
    audit.observe("fp", "replica0", _SHA_A)
    audit.observe("fp", "replica1", _SHA_A)
    audit.observe("fp", "replica2", _SHA_B)
    divs = audit.divergences()
    assert len(divs) == 1
    d = divs[0]
    assert set(d) == set(PROBE_AUDIT_FIELDS)
    assert d["divergent"] == "replica2" == d["replica_b"]
    assert d["replica_a"] == "replica0"
    assert d["hash_a"] == _SHA_A and d["hash_b"] == _SHA_B
    assert d["targets"] == 3 and d["hashes"] == 2
    assert audit.divergent_targets() == ["replica2"]
    assert audit.summary() == {
        "fingerprints": 1, "targets": 3, "divergences": 1,
        "divergent": ["replica2"], "ok": False}

    # a 1-vs-1 tie breaks toward the EARLIEST observed hash: a standing
    # fleet's answer beats a later divergent restart
    tie = AnswerAudit()
    tie.observe("fp", "replica0", _SHA_A)
    tie.observe("fp", "replica1", _SHA_B)
    assert tie.divergent_targets() == ["replica1"]

    # failed probes have no answer to audit
    empty = AnswerAudit()
    empty.observe("", "replica0", _SHA_A)
    empty.observe("fp", "replica0", "")
    assert empty.observed == {} and empty.summary()["ok"]


def test_answer_audit_reference_seed_beats_majority():
    """A seeded known answer (the across-restarts anchor) outvotes any
    live majority: if the WHOLE fleet restarts wrong, every replica is
    divergent — agreement among wrong answers proves nothing."""
    from videop2p_tpu.obs.probe import AnswerAudit

    audit = AnswerAudit({"fp": _SHA_C})
    audit.observe("fp", "replica0", _SHA_A)
    audit.observe("fp", "replica1", _SHA_A)
    divs = audit.divergences()
    assert audit.divergent_targets() == ["replica0", "replica1"]
    assert all(d["replica_a"] == "reference" and d["hash_a"] == _SHA_C
               for d in divs)
    # one live replica matching the seed becomes the named holder
    audit.observe("fp", "replica2", _SHA_C)
    assert all(d["replica_a"] == "replica2" for d in audit.divergences())


# ----------------------------------------------------- fleet prober -----


def _faked_prober(fakes, canary=None, **kw):
    """A FleetProber over unreachable URLs with its clients swapped for
    scriptable fakes — run_once never opens a socket."""
    from videop2p_tpu.serve.prober import FleetProber

    prober = FleetProber(
        [(name, "http://invalid.invalid:1") for name in fakes],
        dict(canary or _CANARY), interval_s=3600.0, **kw)
    for tgt in prober.targets:
        tgt.client = fakes[tgt.name]
    return prober


def test_prober_quarantines_divergent_replica_and_lifts(tmp_path):
    """run_once over a faked fleet: the wrong-but-healthy replica passes
    every self-check yet is flagged by the audit and quarantined; the
    SAME persistent divergence is one audit event, not one per round;
    re-agreement lifts the quarantine on the next round."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.obs.probe import PROBE_AUDIT_FIELDS, PROBE_EVENT_FIELDS
    from videop2p_tpu.obs.signals import S_PROBE_SUCCESS

    fakes = {"replica0": _FakeClient(sha=_SHA_A),
             "replica1": _FakeClient(sha=_SHA_B),   # wrong-but-healthy
             "router": _FakeClient(sha=_SHA_A, metrics={
                 "replicas": {"replica0": {"spec_fingerprint": "fp-tiny"},
                              "replica1": {"spec_fingerprint": "fp-tiny"}}})}
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        prober = _faked_prober(fakes, ledger=led)
        summary = prober.run_once(now=1.0)
        assert summary["divergences"] == 1
        assert summary["divergent"] == ["replica1"]
        status = prober.probe_status()
        assert status["replica1"] == "quarantine"
        assert status["router"] == "pass"
        # the ring store round-trip against a wrong peer legitimately
        # fails hash-match, so the healthy neighbour reads "fail" (which
        # does NOT route around it — only "quarantine" does)
        assert status["replica0"] == "fail"
        stats = prober.stats()
        assert stats["quarantined"] == ["replica1"]
        assert stats["divergences"] == 1
        assert stats["rounds"] == 1
        # 5 suite probes x 3 targets + 2 ring round-trips
        assert stats["probes"] == 17

        # a PERSISTENT divergence dedups to one audit event per hash
        prober.run_once(now=2.0)
        assert prober.divergences == 1
        # re-agreement lifts the quarantine the very next round
        fakes["replica1"].sha = _SHA_A
        prober.run_once(now=3.0)
        assert prober.probe_status() == {
            "replica0": "pass", "replica1": "pass", "router": "pass"}
        assert prober.stats()["quarantined"] == []
        assert prober.audit.summary()["ok"]

        # the tsdb carries per-(target, probe) success series
        assert prober.tsdb.series(S_PROBE_SUCCESS, {
            "target": "replica1", "probe": "determinism"})

    by_kind = {}
    for e in read_ledger(path):
        by_kind.setdefault(e["event"], []).append(e)
    assert set(PROBE_EVENT_FIELDS) <= set(by_kind["probe"][0])
    assert len(by_kind["probe_audit"]) == 1
    audit_e = by_kind["probe_audit"][0]
    assert set(PROBE_AUDIT_FIELDS) <= set(audit_e)
    assert audit_e["divergent"] == "replica1"
    assert audit_e["hash_a"] == _SHA_A and audit_e["hash_b"] == _SHA_B


def test_prober_router_exempt_and_push_channels():
    """A divergent ROUTER is audited and reported but never quarantined
    (there is no routing around the router); verdicts and divergences
    ride the signals push channel; failures and divergences fire the
    probe_failed incident trigger."""

    class _Recorder:
        def __init__(self):
            self.pushes, self.triggers, self.registered = [], [], []

        def set_probe_status(self, status, divergences=()):
            self.pushes.append((dict(status), list(divergences)))

        def register_target(self, name, probe):
            self.registered.append(name)

        def trigger(self, kind, detail="", **context):
            self.triggers.append((kind, detail, context))

    rec = _Recorder()
    fakes = {"replica0": _FakeClient(sha=_SHA_A),
             "replica1": _FakeClient(sha=_SHA_A),
             "router": _FakeClient(sha=_SHA_B, metrics={
                 "replicas": {"replica0": {"spec_fingerprint": "fp-tiny"},
                              "replica1": {"spec_fingerprint": "fp-tiny"}}})}
    prober = _faked_prober(fakes, signals=rec, incidents=rec)
    assert rec.registered == ["probe:replica0", "probe:replica1",
                              "probe:router"]
    summary = prober.run_once(now=1.0)
    assert summary["divergent"] == ["router"]
    assert prober.stats()["quarantined"] == []
    assert prober.probe_status()["router"] == "pass"
    status, divs = rec.pushes[-1]
    assert status == prober.probe_status()
    assert divs and divs[0]["divergent"] == "router"
    audits = [t for t in rec.triggers if "answer audit" in t[1]]
    assert len(audits) == 1
    kind, detail, ctx = audits[0]
    assert kind == "probe_failed"
    assert _SHA_B[:12] in detail and _SHA_A[:12] in detail
    assert ctx["replica_b"] == "router"

    # a target whose probes FAIL (without divergence) also pages
    fakes["replica1"].src_err = 0.5
    prober.run_once(now=2.0)
    assert prober.probe_status()["replica1"] == "fail"
    failed = [t for t in rec.triggers
              if t[2].get("target") == "replica1"]
    assert failed and "cached_replay" in failed[-1][2]["failed"]


def test_probe_rules_ride_default_rules_with_teeth():
    """Verdict pin: PROBE_RULES ride DEFAULT_RULES (kind "probe"),
    obs/history.py extracts the probes section with the overall label
    SEEDED perfect — so a probes-off healthy baseline still holds the
    label a chaos run's first divergence regresses against."""
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        PROBE_RULES,
        evaluate_rules,
        extract_run,
    )

    assert all(r in DEFAULT_RULES for r in PROBE_RULES)
    assert all(r.kind == "probe" for r in PROBE_RULES)
    assert {r.metric for r in PROBE_RULES} == {
        "success_rate", "divergences", "latency_p99_s"}

    healthy = extract_run([{"event": "run_start"}])
    assert healthy["probes"] == {"probe": {
        "success_rate": 1.0, "failures": 0.0, "divergences": 0.0}}

    probed = extract_run([
        {"event": "run_start"},
        {"event": "probe", "probe": "determinism", "target": "replica0",
         "ok": True, "latency_s": 0.2, "content_sha256": _SHA_A,
         "detail": "bit-identical"},
        {"event": "probe", "probe": "determinism", "target": "replica1",
         "ok": False, "latency_s": 0.2, "content_sha256": "",
         "detail": "hash flip"},
        {"event": "probe_audit", "fingerprint": "fp", "targets": 2,
         "hashes": 2, "divergent": "replica1", "replica_a": "replica0",
         "hash_a": _SHA_A, "replica_b": "replica1", "hash_b": _SHA_B},
    ])
    overall = probed["probes"]["probe"]
    assert overall["count"] == 2.0
    assert overall["success_rate"] == 0.5
    assert overall["failures"] == 1.0
    assert overall["divergences"] == 1.0
    assert probed["probes"]["probe:replica1"]["divergences"] == 1.0
    assert probed["probes"]["probe:replica0"]["success_rate"] == 1.0

    # teeth: healthy-vs-probed regresses; self-compare passes both ways
    verdict = evaluate_rules(healthy, probed)
    assert not verdict["pass"]
    flagged = {f["metric"] for f in verdict["regressions"]}
    assert {"success_rate", "divergences"} <= flagged
    assert evaluate_rules(probed, probed)["pass"]
    assert evaluate_rules(healthy, healthy)["pass"]


def test_loadgen_probe_flag_validation():
    """--probes exercises the real JSON API — an --inproc engine has no
    HTTP surface to probe, so the pairing is refused at arg-parse."""
    loadgen = _load_tool("serve_loadgen")
    with pytest.raises(SystemExit):
        loadgen.main(["--inproc", "--probes"])


# --------------------------------------- live acceptance (slow, CPU) -----

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)
_PROMPTS = ("a rabbit is jumping", "a origami rabbit is jumping")


@pytest.fixture(scope="module")
def programs():
    """One warm tiny ProgramSet shared by every fleet in this module."""
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps.warm(_PROMPTS, batch_sizes=(2,))
    return ps


def _request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt=_PROMPTS[0],
              prompts=list(_PROMPTS), save_name="fleet")
    kw.update(overrides)
    return EditRequest(**kw)


def _probed_fleet_run(programs, root, *, faults=None, reference=None,
                      seed=81):
    """A 2-replica fleet + router with the FleetProber's verdicts wired
    into the router — the composition tools/serve_loadgen.py --router 2
    --probes stands up. One deterministic probe round runs BEFORE the
    loadgen traffic so quarantine is in force while requests flow."""
    from videop2p_tpu.serve import ReplicaSupervisor, Router, RouterServer
    from videop2p_tpu.serve.prober import FleetProber

    loadgen = _load_tool("serve_loadgen")
    sup = ReplicaSupervisor(
        programs.spec, 2, out_dir=root, programs=programs,
        warm_prompts=_PROMPTS,
        engine_kwargs=dict(max_retries=0, breaker_threshold=1,
                           breaker_open_s=60.0),
        faults=faults or {},
    )
    sup.start()
    router = Router(sup.urls, probe_ttl_s=0.05, suspend_s=5.0)
    server = RouterServer(router).start()
    targets = ([(r.name, r.url) for r in sup.replicas]
               + [("router", server.url)])
    prober = FleetProber(targets, _request(seed=seed).to_dict(),
                         interval_s=3600.0, http_timeout_s=300.0,
                         wait_s=300.0, reference=reference)
    router.set_probe_status_provider(prober.probe_status)
    ledger_path = os.path.join(root, "loadgen.jsonl")
    try:
        prober.run_once()

        def collect_extra(record):
            events = [{"event": kind, **rec}
                      for kind, rec in prober.history]
            events.append({"event": "router_health",
                           **router.health_record()})
            record["probes"] = prober.stats()
            return events

        record = loadgen.run_loadgen(
            loadgen._HttpTarget(server.url, timeout_s=300.0),
            _request(seed=seed).to_dict(),
            requests=4, concurrency=2, ledger_path=ledger_path,
            meta={"target": "fleet-prober"}, collect_extra=collect_extra,
        )
    finally:
        server.close()
        sup.stop()
    return record, ledger_path, prober, router


@pytest.mark.slow
def test_probe_acceptance_wrong_replica_quarantined(programs, tmp_path):
    """THE ISSUE 20 acceptance: replica 0 serves silently WRONG bytes
    with HTTP 200 — src_err, PSNR/SSIM and its own determinism all pass,
    so Layers 1-8 see a healthy replica. The answer audit (seeded with
    the healthy run's known answer) flags it, the router quarantines it
    and keeps serving bit-correct answers from replica 1, the router's
    /healthz///metrics expose the verdict, and the run regresses against
    the healthy baseline through obs_diff's PROBE_RULES."""
    from videop2p_tpu.obs import read_ledger

    healthy_root = str(tmp_path / "healthy")
    wrong_root = str(tmp_path / "wrong")
    os.makedirs(healthy_root)
    os.makedirs(wrong_root)

    # healthy pass: every probe green, zero divergences, all-pass verdicts
    h_record, h_ledger, h_prober, _ = _probed_fleet_run(
        programs, healthy_root, seed=81)
    assert h_record["done"] == 4 and h_record["errors"] == 0
    assert h_record["probes"]["probe_failures"] == 0
    assert h_record["probes"]["divergences"] == 0
    assert h_record["probes"]["quarantined"] == []
    assert h_record["probes"]["audit"]["ok"]
    assert set(h_record["probes"]["status"].values()) == {"pass"}
    # the healthy fleet agreed on ONE known answer — seed the next audit
    # with it: the across-restarts anchor
    (fp, seen), = h_prober.audit.observed.items()
    assert len(set(seen.values())) == 1
    reference = {fp: next(iter(seen.values()))}

    # wrong pass: replica 0 perturbs every answer, HTTP 200 throughout
    c_record, c_ledger, c_prober, c_router = _probed_fleet_run(
        programs, wrong_root, faults={0: "wrong:*"}, seed=81,
        reference=reference)
    # the audit named the wrong replica; the router quarantined it
    assert "replica0" in c_prober.audit.divergent_targets()
    assert c_record["probes"]["quarantined"] == ["replica0"]
    assert c_record["probes"]["status"]["replica0"] == "quarantine"
    assert c_record["probes"]["divergences"] >= 1
    # ... and the fleet KEPT SERVING: every request done, and replica 1
    # still returns the bit-exact healthy answer (it matches the seeded
    # reference, so real traffic routed around the quarantine is correct)
    assert c_record["done"] == 4 and c_record["errors"] == 0
    assert c_prober.audit.observed[fp]["replica1"] == reference[fp]
    assert "replica1" not in c_prober.audit.divergent_targets()
    assert c_router.health_record()["quarantined"] >= 1
    # the wrong replica is deterministic about its wrong answer — every
    # self-check passed; ONLY the cross-replica audit caught it
    r0 = [e for e in read_ledger(c_ledger)
          if e.get("event") == "probe" and e.get("target") == "replica0"]
    assert r0 and all(e["ok"] for e in r0)

    # satellite (b): the router's own surfaces expose the verdict
    router_health = [e for e in read_ledger(c_ledger)
                     if e.get("event") == "router_health"]
    assert router_health[-1]["quarantined"] >= 1
    audits = [e for e in read_ledger(c_ledger)
              if e.get("event") == "probe_audit"]
    assert audits and audits[0]["divergent"] == "replica0"
    assert audits[0]["hash_a"] == reference[fp]
    assert audits[0]["hash_b"] != reference[fp]

    # gates: self-compare clean, wrong-vs-healthy regresses on PROBE_RULES
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", h_ledger, h_ledger]) == 0
    assert obs_diff.main(["obs_diff.py", h_ledger, c_ledger]) == 1

    # both ledgers render: the dashboard's correctness panel and the
    # standalone probe report mark the divergence
    fleet_dash = _load_tool("fleet_dash")
    probe_report = _load_tool("probe_report")
    for ledger in (h_ledger, c_ledger):
        text = open(fleet_dash.write_dash(ledger)).read()
        assert "Correctness" in text
        rtext = open(probe_report.write_probe_report(ledger)).read()
        assert rtext.startswith("<!doctype html>")
    wrong_dash = open(fleet_dash.write_dash(c_ledger)).read()
    assert "replica0" in wrong_dash
