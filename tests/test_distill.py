"""Consistency-distilled few-step student tests (ISSUE 16).

The student is the SAME UNet (the tuner's trainable subset, consistency-
distilled) plus an external time-conditioning head on ε
(``train/distill.py``), so the contracts pinned here are:

  * the identity pin — the zero-initialized head is an exact no-op on ε,
    so at 0 distillation steps the student path is BIT-EXACT with the
    teacher at the same step subset (the boundary every distilled
    checkpoint starts from);
  * the replay pin — stream 0 of the cached edit is concatenated from
    the captured trajectory and never runs the UNet, so no student can
    perturb the source replay (``src_err == 0.0`` is structural);
  * the trainer — ``distill_step``/``distill_steps`` follow the tuner's
    machinery contract (partitioned trainable subset, frozen majority as
    a closure constant, fold_in-per-absolute-step keys) with the
    consistency objective, and ``save_student``/``load_student``
    round-trip the (trainable, head) checkpoint exactly;
  * the quality gate — few-step student quality metrics ride the same
    ``quality`` ledger event QUALITY_RULES diff as quant/reuse
    (tools/obs_diff.py): the identity student gates clean (exit 0), a
    corrupted head regresses (exit 1).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.train.distill import (
    DistillConfig,
    DistillState,
    apply_time_head,
    distill_step,
    distill_steps,
    init_time_head,
    load_student,
    make_distill_optimizer,
    save_student,
)

STEPS = 5
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C)


# ------------------------------------------------- the time head --


def _tiny_cfg():
    from videop2p_tpu.models import UNet3DConfig

    return UNet3DConfig.tiny()


def test_identity_head_is_value_exact():
    """The zero-initialized output layer makes apply_time_head the exact
    identity on ε — scalar and batched timesteps alike — which is what
    makes the untrained student value-exact with the teacher."""
    head = init_time_head(jax.random.key(0), _tiny_cfg())
    eps = jax.random.normal(jax.random.key(1), (2,) + SHAPE[1:],
                            jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(apply_time_head(head, eps, jnp.asarray(10))),
        np.asarray(eps),
    )
    np.testing.assert_array_equal(
        np.asarray(apply_time_head(head, eps, jnp.asarray([10, 700]))),
        np.asarray(eps),
    )
    # a non-zero output layer really modulates (the head has teeth)
    perturbed = jax.tree.map(lambda x: x, head)
    perturbed["dense2"]["bias"] = head["dense2"]["bias"] + 0.5
    assert not np.array_equal(
        np.asarray(apply_time_head(perturbed, eps, jnp.asarray(10))),
        np.asarray(eps),
    )


def test_save_load_student_roundtrip():
    """The checkpoint stores exactly (trainable subset, head) and
    load_student merges the restored subset back over the caller's frozen
    majority — values exact both ways."""
    cfg = _tiny_cfg()
    params = {
        "blk": {
            "attn1": {"to_q": {"kernel": jnp.full((4, 4), 2.0)}},
            "proj": {"kernel": jnp.zeros((4, 4))},
        }
    }
    head = init_time_head(jax.random.key(0), cfg)
    dcfg = DistillConfig(max_train_steps=1)
    tx = make_distill_optimizer(dcfg)
    state = DistillState.create(params, head, tx, dcfg.trainable_modules)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = save_student(d, state, 3)
        assert os.path.basename(path) == "checkpoint-3"
        merged, head2 = load_student(path, params, cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        merged, params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        head2, head,
    )


# ------------------------------------------- tiny-model end-to-end --


@pytest.fixture(scope="module")
def sched():
    from videop2p_tpu.core import DDIMScheduler

    return DDIMScheduler.create_sd()


@pytest.fixture(scope="module")
def tiny():
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), SHAPE)
    text = jax.random.normal(jax.random.key(1),
                             (1, 77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample,
                                 jnp.asarray(10), text)
    return make_unet_fn(model), params, cfg


@pytest.fixture(scope="module")
def cached_edit(sched, tiny):
    """One captured inversion shared by the student tests, plus a runner
    that takes the step count and the student head."""
    from videop2p_tpu.pipelines import ddim_inversion_captured, edit_sample

    fn, params, cfg = tiny
    x0 = 0.5 * jax.random.normal(jax.random.key(3), SHAPE)
    cond = jax.random.normal(jax.random.key(4),
                             (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    traj, cached = jax.jit(
        lambda p, x: ddim_inversion_captured(
            fn, p, sched, x, cond[:1], num_inference_steps=STEPS,
            cross_len=0, self_window=(0, 0),
        )
    )(params, x0)

    def run(p, *, steps=STEPS, student_head=None, reuse=None):
        positions = (None if steps == STEPS else tuple(
            int(i) for i in sched.subset_positions(STEPS, steps)))
        return jax.jit(
            lambda pp, xt, c: edit_sample(
                fn, pp, sched, xt, cond, uncond,
                num_inference_steps=steps, step_positions=positions,
                source_uses_cfg=False, cached_source=c,
                reuse_schedule=reuse, student_head=student_head,
            )
        )(p, traj[-1], cached)

    return run, params, x0, cond


@pytest.mark.slow
def test_identity_student_is_teacher_exact_and_replays_source(cached_edit,
                                                              tiny):
    """The 0-distill-steps boundary: the identity-initialized student's
    2-step cached edit is BIT-EXACT with the teacher's 2-step edit, the
    source replay is exact under the student (and stays exact when the
    student composes with w8 quant + reuse — the full frontier row)."""
    from videop2p_tpu.models.convert import quantize_unet_params

    run, params, x0, _ = cached_edit
    _, _, cfg = tiny
    head = init_time_head(jax.random.key(0), cfg)
    teacher2 = run(params, steps=2)
    student2 = run(params, steps=2, student_head=head)
    np.testing.assert_array_equal(np.asarray(student2), np.asarray(teacher2))
    np.testing.assert_array_equal(np.asarray(student2[0]), np.asarray(x0[0]))
    # the composed row: student × w8 × uniform:2 — replay still exact
    out = run(quantize_unet_params(params, mode="w8"), steps=2,
              student_head=head, reuse="uniform:2")
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    assert np.isfinite(np.asarray(out)).all()
    # a trained (non-identity) head really changes the edit stream but
    # CANNOT touch the replayed source stream
    trained = jax.tree.map(lambda x: x, head)
    trained["dense2"]["bias"] = head["dense2"]["bias"] + 0.1
    out_t = run(params, steps=2, student_head=trained)
    np.testing.assert_array_equal(np.asarray(out_t[0]), np.asarray(x0[0]))
    assert not np.array_equal(np.asarray(out_t[1]), np.asarray(student2[1]))


@pytest.mark.slow
def test_distillation_trains_and_checkpoint_roundtrips(cached_edit, sched,
                                                       tiny, tmp_path):
    """A real (tiny) distillation run: finite losses through the scan, a
    student checkpoint on disk, load_student round-trips it exactly, and
    the loaded student's 2-step cached edit runs with the source replay
    still exact."""
    run, params, x0, cond = cached_edit
    fn, _, cfg = tiny
    dcfg = DistillConfig(max_train_steps=2, distill_grid=STEPS,
                         learning_rate=1e-3)
    tx = make_distill_optimizer(dcfg)
    head = init_time_head(jax.random.key(5), cfg)
    state = DistillState.create(params["params"], head, tx,
                                dcfg.trainable_modules)
    latents = x0.astype(jnp.float32)
    state, loss = distill_step(fn, tx, state, sched, latents, cond[:1],
                               jax.random.key(6), cfg=dcfg)
    assert np.isfinite(float(loss))
    state, losses = distill_steps(fn, tx, state, sched, latents, cond[:1],
                                  jax.random.key(6), num_steps=2, cfg=dcfg)
    assert int(state.step) == 3
    assert np.isfinite(np.asarray(losses)).all()
    ckpt = save_student(str(tmp_path / "student"), jax.device_get(state), 3)
    merged, head2 = load_student(ckpt, params["params"], cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        head2, jax.device_get(state.head),
    )
    out = run({"params": merged}, steps=2, student_head=head2)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_obs_diff_gates_student_quality(cached_edit, tiny, tmp_path, capsys):
    """The ISSUE 16 acceptance gate with real metrics: the student's
    2-step edit is scored against the teacher's full-step output and the
    numbers ride a ``quality`` ledger event through QUALITY_RULES — the
    identity student (bit-exact with the teacher's 2-step edit) gates
    clean against the teacher baseline (exit 0); a corrupted head's
    collapsed PSNR regresses (exit 1)."""
    import importlib.util

    from videop2p_tpu.obs import RunLedger
    from videop2p_tpu.obs.quality import psnr, ssim

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_diff_under_distill_test",
        os.path.join(repo, "tools", "obs_diff.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    run, params, x0, _ = cached_edit
    _, _, cfg = tiny
    head = init_time_head(jax.random.key(0), cfg)
    reference = np.asarray(run(params)[1])          # teacher, full steps
    span = float(np.max(reference) - np.min(reference))

    def score(edit):
        edit = np.asarray(edit)
        return (
            float(psnr(jnp.asarray(edit), jnp.asarray(reference),
                       data_range=span)),
            float(ssim(jnp.asarray(edit), jnp.asarray(reference),
                       data_range=span)),
        )

    def write(path, run_id, edit):
        db, s = score(edit)
        led = RunLedger(str(path), run_id=run_id, device_info=False)
        led.event("quality", recon_psnr=db, background_psnr=30.0,
                  recon_ssim=s, student=True, steps=2)
        led.close()

    base = tmp_path / "teacher.jsonl"
    good = tmp_path / "student.jsonl"
    bad = tmp_path / "student_bad.jsonl"
    write(base, "teacher_2step", run(params, steps=2)[1])
    write(good, "student_2step", run(params, steps=2, student_head=head)[1])
    broken = jax.tree.map(lambda x: x, head)
    broken["dense2"]["bias"] = head["dense2"]["bias"] + 10.0
    write(bad, "student_corrupt",
          run(params, steps=2, student_head=broken)[1])
    assert mod.main(["obs_diff.py", str(base), str(good)]) == 0
    assert mod.main(["obs_diff.py", str(base), str(bad)]) == 1
    assert "recon_psnr" in capsys.readouterr().out
