"""Observability subsystem (videop2p_tpu/obs): in-program telemetry + the
unified run ledger (ISSUE 2).

CPU gates for the tentpole's contracts:

  * telemetry buffers are fixed-shape and shape-stable under jit — NaNs in
    the data change values, never shapes;
  * telemetry OFF leaves the fused programs' outputs bit-exact (null-text
    fused, the controlled edit, the cached replay — whose source stream
    must stay exactly the inversion input);
  * the ledger JSONL schema round-trips, compile events are captured on
    CPU with program attribution, phase_timer emits into the active
    ledger, and tools/ledger_summary.py renders a real event stream;
  * the telemetry-on overhead of the fused null-text program is measured
    on a compute-dominated smoke workload and recorded in a ledger.

Fake denoisers keep everything eager-CPU-fast (the SURVEY §4 strategy).
"""

import importlib.util
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.obs import (
    RunLedger,
    current_ledger,
    decode_null_text_stats,
    decode_step_stats,
    instrumented_jit,
    latent_stats,
    read_ledger,
    sparkline,
    summarize_step_stats,
    telemetry_overhead_record,
)
from videop2p_tpu.obs.timing import measure_overhead_p50
from videop2p_tpu.pipelines import (
    ddim_inversion,
    edit_sample,
    null_text_optimization,
    null_text_optimization_fused,
)

STEPS = 6
SHAPE = (1, 2, 8, 8, 4)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sched():
    return DDIMScheduler.create_sd()


def text_unet():
    def fn(params, sample, t, text, control=None):
        bias = jnp.mean(text, axis=(1, 2))
        return 0.1 * sample + bias[:, None, None, None, None], {}

    return fn


@pytest.fixture(scope="module")
def problem(sched):
    fn = text_unet()
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond, num_inference_steps=STEPS)
    return fn, x0, cond, uncond, traj


# ------------------------------------------------------------- telemetry --


def test_latent_stats_shape_stable_under_jit():
    """The probe returns SCALARS whatever the data holds — a scan stacking
    it yields (num_steps,) vectors, and NaN inputs change values only."""

    def scan_stats(x):
        def body(c, _):
            return c * 2.0, latent_stats(c)

        _, ys = jax.lax.scan(body, x, None, length=5)
        return ys

    clean = jax.jit(scan_stats)(jnp.ones((2, 3, 4)))
    dirty = jax.jit(scan_stats)(
        jnp.array([[1.0, jnp.nan], [jnp.inf, -2.0]])
    )
    for ys in (clean, dirty):
        assert set(ys) == {"abs_max", "mean", "nan_count", "inf_count"}
        for k, v in ys.items():
            assert v.shape == (5,), k
    assert int(dirty["nan_count"][0]) == 1
    assert int(dirty["inf_count"][0]) == 1
    # finite-masked stats: the NaN/inf never poison the curve
    assert float(dirty["abs_max"][0]) == 2.0
    assert np.isfinite(np.asarray(dirty["mean"])).all()
    assert int(clean["nan_count"].sum()) == 0


def test_null_text_fused_telemetry_off_is_bit_exact(problem, sched):
    fn, _, cond, uncond, traj = problem
    kw = dict(num_inference_steps=STEPS, num_inner_steps=3, return_stats=True)
    seq_off, stats_off = null_text_optimization_fused(
        fn, None, sched, traj, cond, uncond, **kw
    )
    seq_on, stats_on = null_text_optimization_fused(
        fn, None, sched, traj, cond, uncond, telemetry=True, **kw
    )
    assert np.array_equal(np.asarray(seq_off), np.asarray(seq_on))
    assert np.array_equal(np.asarray(stats_off["final_loss"]),
                          np.asarray(stats_on["final_loss"]))
    tel = stats_on["latent_stats"]
    assert {k: np.asarray(v).shape for k, v in tel.items()} == {
        "abs_max": (STEPS,), "mean": (STEPS,),
        "nan_count": (STEPS,), "inf_count": (STEPS,),
    }
    assert int(np.asarray(tel["nan_count"]).sum()) == 0
    # the decoded record is ledger-ready: loss curve + inner steps + latent
    rec = decode_null_text_stats(stats_on)
    assert len(rec["loss_curve"]) == STEPS
    assert rec["inner_steps_total"] == sum(rec["inner_steps"])
    assert rec["latent"]["nan_total"] == 0


def test_null_text_telemetry_requires_stats(problem, sched):
    fn, _, cond, uncond, traj = problem
    with pytest.raises(ValueError, match="return_stats"):
        null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, telemetry=True,
        )


def test_null_text_chunked_telemetry_matches_fused(problem, sched):
    """The host-chunked watchdog fallback stacks the same telemetry as the
    fused program (chunk boundaries concatenate, values identical)."""
    fn, _, cond, uncond, traj = problem
    kw = dict(num_inference_steps=STEPS, num_inner_steps=2)
    _, stats = null_text_optimization_fused(
        fn, None, sched, traj, cond, uncond,
        return_stats=True, telemetry=True, **kw,
    )
    seq_c, tel_c = null_text_optimization(
        fn, None, sched, traj, cond, uncond,
        outer_chunk=2, telemetry=True, **kw,
    )
    assert seq_c.shape[0] == STEPS
    for k, v in stats["latent_stats"].items():
        np.testing.assert_allclose(
            np.asarray(tel_c[k]), np.asarray(v), rtol=0, atol=0, err_msg=k
        )


def test_edit_sample_telemetry_off_is_bit_exact(problem, sched):
    fn, _, cond, uncond, traj = problem
    cond2 = jnp.concatenate([cond, 0.5 * jnp.ones((1, 77, 8))], axis=0)

    out_off = jax.jit(
        lambda xt: edit_sample(fn, None, sched, xt, cond2, uncond[0],
                               num_inference_steps=STEPS)
    )(traj[-1])
    out_on, tel = jax.jit(
        lambda xt: edit_sample(fn, None, sched, xt, cond2, uncond[0],
                               num_inference_steps=STEPS, telemetry=True)
    )(traj[-1])
    assert np.array_equal(np.asarray(out_off), np.asarray(out_on))
    assert set(tel) == {"abs_max", "mean", "nan_count", "inf_count",
                        "cross_gate_mean", "self_edit_active"}
    for v in tel.values():
        assert np.asarray(v).shape == (STEPS,)
    # no controller: the edit-gate channels are identically zero
    assert float(np.asarray(tel["cross_gate_mean"]).sum()) == 0.0
    assert int(np.asarray(tel["self_edit_active"]).sum()) == 0
    summary = summarize_step_stats(tel)
    assert summary["steps"] == STEPS and summary["nan_total"] == 0
    assert len(decode_step_stats(tel)) == STEPS


def test_cached_edit_telemetry_keeps_exact_replay(problem, sched):
    """Telemetry through the cached-source path: outputs bit-exact vs
    telemetry-off, and stream 0 stays the EXACT inversion input — the
    src_err == 0.0 guarantee the multichip dryrun reports."""
    from videop2p_tpu.pipelines import cached_fast_edit

    fn, x0, cond, uncond, _ = problem
    cond2 = jnp.concatenate([cond, 0.5 * jnp.ones((1, 77, 8))], axis=0)
    kw = dict(num_inference_steps=STEPS, cross_len=0, self_window=(0, 0))
    traj_off, edited_off = jax.jit(
        lambda x: cached_fast_edit(fn, None, sched, x, cond, cond2,
                                   uncond[0], None, **kw)
    )(x0)
    traj_on, edited_on, tel = jax.jit(
        lambda x: cached_fast_edit(fn, None, sched, x, cond, cond2,
                                   uncond[0], None, telemetry=True, **kw)
    )(x0)
    assert np.array_equal(np.asarray(edited_off), np.asarray(edited_on))
    assert np.array_equal(np.asarray(traj_off), np.asarray(traj_on))
    src_err = float(jnp.max(jnp.abs(edited_on[0] - x0[0])))
    assert src_err == 0.0
    assert np.asarray(tel["abs_max"]).shape == (STEPS,)
    assert int(np.asarray(tel["nan_count"]).sum()) == 0


@pytest.mark.slow
def test_train_steps_telemetry_grad_norms():
    """Training telemetry: same losses bit-exact, plus finite per-step
    pre-clip global gradient norms stacked by the same scan.

    slow: the only remaining >10 s test in the r6 wall-clock audit (11.4 s
    — it compiles the train scan twice, telemetry off and on); tier-1
    keeps the telemetry bit-exactness pins via the other train test
    (test_train.py) and the fused-pipeline off-paths above."""
    from videop2p_tpu.core import DDPMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn
    from videop2p_tpu.train import (
        TrainState, TuneConfig, make_optimizer, train_steps,
    )

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    latents = 0.3 * jax.random.normal(jax.random.key(0), (1, 2, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    variables = jax.jit(model.init)(jax.random.key(2), latents, jnp.asarray(0), text)
    fn = make_unet_fn(model)
    tune_cfg = TuneConfig(max_train_steps=3)
    tx = make_optimizer(tune_cfg)
    noise_sched = DDPMScheduler.create_sd()
    key = jax.random.key(3)

    state0 = TrainState.create(dict(variables)["params"], tx)
    _, losses = train_steps(fn, tx, state0, noise_sched, latents, text, key,
                            num_steps=3)
    state1 = TrainState.create(dict(variables)["params"], tx)
    _, losses_t, gnorms = train_steps(fn, tx, state1, noise_sched, latents,
                                      text, key, num_steps=3, telemetry=True)
    np.testing.assert_array_equal(np.asarray(losses), np.asarray(losses_t))
    g = np.asarray(gnorms)
    assert g.shape == (3,) and np.isfinite(g).all() and (g > 0).all()


# ---------------------------------------------------------------- ledger --


def test_ledger_schema_round_trips(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path, run_id="t1", meta={"cli": "test"}) as led:
        assert current_ledger() is led
        led.phase("p", 1.25, count=3, unit="it")
        led.telemetry("prog", {"loss_curve": [1.0, 0.5], "loss_final": 0.5})
        led.memory_snapshot(note="now")
        led.event("custom", answer=42)
    assert current_ledger() is None
    events = read_ledger(path)
    by_kind = {e["event"]: e for e in events}
    start = by_kind["run_start"]
    assert start["run_id"] == "t1" and start["cli"] == "test"
    assert start["jax_version"] == jax.__version__
    assert "backend" in start
    assert by_kind["phase"]["name"] == "p"
    assert by_kind["phase"]["seconds"] == 1.25
    assert by_kind["telemetry"]["program"] == "prog"
    assert by_kind["memory"]["supported"] in (True, False)
    assert by_kind["custom"]["answer"] == 42
    assert events[-1]["event"] == "run_end"
    # every event is one JSON object per line with a monotonic t
    raw = [json.loads(l) for l in open(path) if l.strip()]
    assert [e["event"] for e in raw] == [e["event"] for e in events]
    ts = [e["t"] for e in events]
    assert ts == sorted(ts)


def test_compile_events_captured_on_cpu(tmp_path):
    """The jax.monitoring listener lands backend-compile durations in the
    active ledger, attributed to the instrumented program; a cache hit
    records a program_call with cache_miss=False and no new compile."""
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        f = instrumented_jit(lambda x: x * 3 + 1, program="triple")
        f(jnp.ones((4, 4)))
        n_compiles_after_first = len(led.compile_seconds)
        f(jnp.ones((4, 4)))
    events = read_ledger(path)
    compiles = [e for e in events if e["event"] == "compile"
                and e.get("program") == "triple"]
    assert len(compiles) >= 1
    assert all(e["seconds"] > 0 for e in compiles)
    calls = [e for e in events if e["event"] == "program_call"]
    assert [c["cache_miss"] for c in calls] == [True, False]
    # the second (hit) call triggered no further compile
    assert len(led.compile_seconds) == n_compiles_after_first


def test_phase_timer_emits_into_active_ledger(tmp_path, capsys):
    from videop2p_tpu.utils.profiling import phase_records, phase_timer, reset

    reset()
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path):
        with phase_timer("ledgered_phase", count=2, unit="u"):
            pass
    with phase_timer("unledgered_phase", verbose=False):
        pass
    events = [e for e in read_ledger(path) if e["event"] == "phase"]
    assert [e["name"] for e in events] == ["ledgered_phase"]
    assert events[0]["count"] == 2 and events[0]["unit"] == "u"
    # the process-local records caught both, and reset clears them
    recs = phase_records()
    assert set(recs) == {"ledgered_phase", "unledgered_phase"}
    reset()
    assert phase_records() == {}


def test_trace_emits_ledger_event(tmp_path, monkeypatch):
    """Satellite (ISSUE 4): utils.profiling.trace captures a device trace
    when VIDEOP2P_TRACE_DIR is set but the ledger never learned the path —
    now a ``trace`` event (name + directory) links it to the run."""
    import contextlib

    from videop2p_tpu.utils.profiling import trace

    traced = []
    monkeypatch.setattr(
        jax.profiler, "trace",
        lambda d: (traced.append(d), contextlib.nullcontext())[1],
    )
    monkeypatch.setenv("VIDEOP2P_TRACE_DIR", str(tmp_path / "traces"))
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path):
        with trace("edit_phase"):
            pass
    events = read_ledger(path)
    trace_evs = [e for e in events if e["event"] == "trace"]
    assert len(trace_evs) == 1
    assert trace_evs[0]["name"] == "edit_phase"
    assert trace_evs[0]["trace_dir"] == str(tmp_path / "traces" / "edit_phase")
    assert traced == [str(tmp_path / "traces" / "edit_phase")]
    # the phase event still lands alongside it
    assert any(e["event"] == "phase" and e["name"] == "edit_phase"
               for e in events)
    # no ledger active: the same region is trace+phase only, no crash
    with trace("unledgered"):
        pass
    from videop2p_tpu.utils.profiling import phase_records, phase_timer, reset

    reset()

    def work(i):
        for _ in range(50):
            with phase_timer(f"thread_{i}", verbose=False):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = phase_records()
    assert set(recs) == {f"thread_{i}" for i in range(4)}
    reset()


def test_metrics_logger_flushes_and_survives_abrupt_close(tmp_path):
    """Satellite: scalars must survive an abrupt close — the JSONL line
    buffer holds every step immediately, and the TensorBoard writer gets a
    flush every ``flush_every`` logs plus flush-before-close."""
    from videop2p_tpu.utils.metrics import MetricsLogger

    class StubTB:
        def __init__(self):
            self.scalars, self.flushes, self.closed = [], 0, False

        def add_scalar(self, k, v, step):
            self.scalars.append((k, v, step))

        def flush(self):
            self.flushes += 1

        def close(self):
            self.closed = True

    logger = MetricsLogger(str(tmp_path), use_tensorboard=False, flush_every=2)
    logger._tb = StubTB()
    for step in range(1, 6):
        logger.log(step, {"train_loss": 1.0 / step})
    # JSONL survives WITHOUT close: line-buffered append
    lines = [json.loads(l) for l in open(logger.path)]
    assert [l["step"] for l in lines] == [1, 2, 3, 4, 5]
    assert all("wall_s" in l for l in lines)
    assert logger._tb.flushes == 2  # every 2 logs
    logger.close()
    assert logger._tb.flushes == 3  # flush-on-close precedes close
    assert logger._tb.closed


def test_metrics_logger_is_a_ledger_view(tmp_path):
    from videop2p_tpu.utils.metrics import MetricsLogger

    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path):
        with MetricsLogger(str(tmp_path / "run"), use_tensorboard=False) as m:
            m.log(1, {"train_loss": 0.5, "lr": 1e-4})
    metric = [e for e in read_ledger(path) if e["event"] == "metric"]
    assert len(metric) == 1
    assert metric[0]["step"] == 1 and metric[0]["train_loss"] == 0.5


def test_instrumented_jit_passthrough_without_ledger():
    f = instrumented_jit(lambda x: x + 1, program="noop")
    assert current_ledger() is None
    assert float(f(jnp.asarray(1.0))) == 2.0


# -------------------------------------------------------- ledger summary --


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test",
        os.path.join(_REPO, "tools", f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_summary_tool():
    return _load_tool("ledger_summary")


def test_ledger_summary_renders_real_stream(tmp_path, problem, sched):
    """End-to-end: a ledger produced by real instrumented programs renders
    without error and shows phases, programs, and the loss sparkline."""
    from videop2p_tpu.utils.profiling import phase_timer

    fn, _, cond, uncond, traj = problem
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path, run_id="render") as led:
        with phase_timer("null_text", verbose=False):
            _, stats = null_text_optimization_fused(
                fn, None, sched, traj, cond, uncond,
                num_inference_steps=STEPS, num_inner_steps=2,
                return_stats=True, telemetry=True,
            )
        led.telemetry("null_text_fused", decode_null_text_stats(stats))
        led.memory_snapshot()
    mod = _load_summary_tool()
    text = mod.render(read_ledger(path))
    assert "run render" in text
    assert "null_text" in text
    assert "loss" in text and "inner steps" in text
    # sparkline characters (or the flat-series bar) present
    assert any(c in text for c in "▁▂▃▄▅▆▇█")


def test_sparkline_handles_degenerate_series():
    assert sparkline([]) == ""
    assert sparkline([1.0, 1.0, 1.0]) == "▄▄▄"
    assert "!" in sparkline([1.0, float("nan"), 2.0])
    assert len(sparkline(list(range(500)), width=50)) == 50
    # inf values render as '!' too; an all-non-finite series is all '!'
    assert sparkline([1.0, float("inf"), 2.0])[1] == "!"
    assert sparkline([float("nan"), float("inf")]) == "!!"


def test_decode_helpers_degenerate_inputs():
    """Satellite (ISSUE 4): the decode helpers must survive empty stats
    trees, zero-length curves, and NaN/inf VALUES (not just counts) — a
    killed run's partial telemetry still has to land in the ledger."""
    assert decode_step_stats({}) == []
    assert summarize_step_stats({}) == {"steps": 0}
    empty = {"abs_max": np.zeros((0,)), "mean": np.zeros((0,))}
    assert decode_step_stats(empty) == []
    assert summarize_step_stats(empty) == {"steps": 0}

    weird = {
        "abs_max": np.array([1.0, np.nan, np.inf]),
        "mean": np.array([0.0, np.nan, 5.0]),
        "nan_count": np.array([0, 1, 0]),
        "inf_count": np.array([0, 0, 1]),
    }
    recs = decode_step_stats(weird)
    assert [r["step"] for r in recs] == [0, 1, 2]
    assert np.isnan(recs[1]["abs_max"]) and recs[2]["abs_max"] == np.inf
    s = summarize_step_stats(weird)
    assert s["steps"] == 3
    assert s["nan_total"] == 1 and s["first_nan_step"] == 1
    assert s["inf_total"] == 1 and s["first_inf_step"] == 2
    assert s["mean_final"] == 5.0


def test_ledger_summary_tolerates_empty_and_truncated(tmp_path, capsys):
    """Satellite: the renderer must survive empty ledgers and torn/partial
    JSONL lines (a killed run's tail) instead of crashing."""
    mod = _load_summary_tool()
    # empty file
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert mod.main(["ledger_summary.py", str(empty)]) == 0
    assert "empty ledger" in capsys.readouterr().out
    # torn + partial lines: valid prefix renders, junk is skipped, events
    # missing payload fields degrade to placeholders
    torn = tmp_path / "torn.jsonl"
    torn.write_text("\n".join([
        json.dumps({"event": "run_start", "run_id": "torn", "t": 0}),
        json.dumps({"event": "phase"}),                      # no name/seconds
        json.dumps({"event": "compile", "seconds": None}),   # null seconds
        json.dumps({"event": "program_call", "program": "p",
                    "dispatch_s": "garbage"}),
        json.dumps({"event": "telemetry", "program": "p",
                    "loss_curve": [1.0, None]}),             # junk curve
        json.dumps({"event": "memory", "supported": True, "devices": None}),
        json.dumps({"event": "program_analysis", "program": "q"}),
        '{"event": "phase", "name": "tail", "secon',         # torn line
    ]) + "\n")
    assert mod.main(["ledger_summary.py", str(torn)]) == 0
    out = capsys.readouterr().out
    assert "run torn" in out
    # missing file: usage-style error code, no traceback
    assert mod.main(["ledger_summary.py", str(tmp_path / "nope.jsonl")]) == 2
    # wrong argc prints usage
    assert mod.main(["ledger_summary.py"]) == 2


def test_ledger_summary_renders_program_analysis_and_hbm_check(tmp_path):
    """The new program_analysis table + the predicted-vs-measured peak-HBM
    line (the run_videop2p HBM-gate sanity check)."""
    mod = _load_summary_tool()
    events = [
        {"event": "run_start", "run_id": "pa", "t": 0},
        {"event": "program_analysis", "program": "cached_invert_edit",
         "flops": 6.5e12, "bytes_accessed": 3 * 2**30,
         "temp_bytes": 2 * 2**30, "peak_hbm_bytes": 4 * 2**30,
         "hlo_instructions": 1234, "hlo_fingerprint": "deadbeefcafef00d"},
        {"event": "memory", "supported": True,
         "devices": [{"device": 0, "peak_bytes_in_use": 5 * 2**30}]},
    ]
    text = mod.render(events)
    assert "program analysis" in text
    assert "cached_invert_edit" in text and "deadbeefcafef00d" in text
    assert "predicted peak-HBM" in text
    assert "1.25× predicted" in text


# ------------------------------------------------- overhead (CPU smoke) --


def test_telemetry_overhead_recorded_and_small(tmp_path, sched):
    """The acceptance smoke: telemetry-on overhead of the fused null-text
    program on a COMPUTE-DOMINATED workload (a matmul-heavy denoiser over a
    small latent — the real UNet's FLOPs-per-latent-byte ratio is even more
    extreme), recorded in a ledger. The stats are four scalar reductions
    per outer step; once forwards dominate, their cost vanishes.

    The denoiser is sized so the fused program runs ~20 ms: the r6 audit
    caught the original ~1.3 ms version flaking in full-suite runs, where
    0.1 ms of host jitter reads as a fake double-digit 'overhead'.

    ISSUE 6 de-flake: the comparison rides obs/timing.py percentile
    reservoirs (measure_overhead_p50 — interleaved off/on sampling,
    nearest-rank p50s) instead of one median-of-5 wall-clock delta.

    ISSUE 11 de-flake: even the p50-of-9 (retry p50-of-13) flaked once
    in-suite in BOTH the r4 and r5 rounds — host scheduling jitter on a
    loaded CI box is not a property of this repo's code, so the overhead
    percentage is now RECORDED (ledger `telemetry` event, where cross-run
    obs_diff/TIMING_RULES gates drift against a baseline measured on the
    SAME box) rather than asserted against a fixed in-suite threshold.
    The hard assertions keep what host load cannot fake: the measurement
    ran, both timings are real, and the record schema holds."""
    W = 0.02 * jax.random.normal(jax.random.key(9), (1024, 1024))

    def heavy_fn(params, sample, t, text, control=None):
        h = sample.reshape(1, -1)
        h = jnp.pad(h, ((0, 0), (0, 1024 - h.shape[1])))
        for _ in range(24):
            h = jnp.tanh(h @ W)
        bias = jnp.mean(text, axis=(1, 2)) + jnp.mean(h)
        return 0.1 * sample + bias[:, None, None, None, None], {}

    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(heavy_fn, None, sched, x0, cond,
                          num_inference_steps=STEPS)
    kw = dict(num_inference_steps=STEPS, num_inner_steps=4,
              early_stop=False, return_stats=True)

    def run_off():
        jax.block_until_ready(null_text_optimization_fused(
            heavy_fn, None, sched, traj, cond, uncond, **kw)[0])

    def run_on():
        jax.block_until_ready(null_text_optimization_fused(
            heavy_fn, None, sched, traj, cond, uncond, telemetry=True, **kw)[0])

    rec = measure_overhead_p50(run_off, run_on, repeats=9)
    if rec["telemetry_overhead_pct"] > 5.0:  # one retry absorbs a CI blip
        rec = measure_overhead_p50(run_off, run_on, repeats=13)
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.telemetry("null_text_fused_overhead", rec)
    saved = [e for e in read_ledger(path) if e["event"] == "telemetry"][0]
    assert saved["telemetry_overhead_pct"] == rec["telemetry_overhead_pct"]
    assert set(rec) == {"telemetry_off_s", "telemetry_on_s",
                        "telemetry_overhead_pct"}
    # both arms genuinely ran a ~20 ms program (a broken measurement
    # reads ~0); the PERCENTAGE is recorded, not asserted — see docstring
    assert rec["telemetry_off_s"] > 1e-4 and rec["telemetry_on_s"] > 1e-4
    if rec["telemetry_overhead_pct"] > 5.0:
        import warnings

        warnings.warn(
            f"telemetry overhead p50 measured {rec['telemetry_overhead_pct']}"
            "% (> the 5% design budget) — recorded in the ledger, not "
            "asserted; investigate only if it reproduces on an idle host",
            stacklevel=1,
        )


def test_telemetry_overhead_record_schema():
    rec = telemetry_overhead_record(2.0, 2.05)
    assert rec == {"telemetry_off_s": 2.0, "telemetry_on_s": 2.05,
                   "telemetry_overhead_pct": 2.5}


# ------------------------------------- program introspection (ISSUE 3) --


def _tanh_matmul():
    # module-level name keeps the HLO module name (and so the fingerprint)
    # identical across fresh jit wrappers
    def cost_probe(x):
        return jnp.tanh(x @ x) + 1

    return cost_probe


def test_analyze_jitted_schema_and_determinism():
    """The acceptance pin: the analysis record is shape-stable and
    DETERMINISTIC across two independent compiles of the same program on
    CPU — fingerprints, flops, histograms, everything."""
    from videop2p_tpu.obs import analyze_jitted

    sds = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    rec1 = analyze_jitted(jax.jit(_tanh_matmul()), sds)
    jax.clear_caches()
    rec2 = analyze_jitted(jax.jit(_tanh_matmul()), sds)
    assert rec1 == rec2
    for key in ("flops", "transcendentals", "bytes_accessed",
                "argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "generated_code_bytes", "peak_hbm_bytes",
                "hlo_fingerprint", "hlo_instructions", "hlo_histogram"):
        assert key in rec1, key
    assert rec1["flops"] > 0
    assert rec1["peak_hbm_bytes"] == (
        rec1["argument_bytes"] + rec1["output_bytes"] + rec1["temp_bytes"]
        + rec1["generated_code_bytes"] - rec1["alias_bytes"]
    )
    assert sum(rec1["hlo_histogram"].values()) == rec1["hlo_instructions"]
    assert "dot" in rec1["hlo_histogram"]
    # a different program fingerprints differently
    rec3 = analyze_jitted(jax.jit(lambda x: x + 1), sds)
    assert rec3["hlo_fingerprint"] != rec1["hlo_fingerprint"]
    # analysis is best-effort: garbage in → None, not an exception
    assert analyze_jitted(jax.jit(lambda x: x.bad_attr), sds) is None


def test_instrumented_jit_emits_analysis_on_miss_only(tmp_path):
    """One program_analysis event per compile (cache miss), none on hits,
    attributed to the program label, with the numeric metrics identical
    across two runs of the same program."""
    recs = []
    for i in range(2):
        path = str(tmp_path / f"ledger{i}.jsonl")
        f = instrumented_jit(_tanh_matmul(), program="cost_probe")
        with RunLedger(path):
            f(jnp.ones((16, 16)))
            f(jnp.ones((16, 16)))  # hit: no second analysis
        events = read_ledger(path)
        pa = [e for e in events if e["event"] == "program_analysis"]
        assert len(pa) == 1
        assert pa[0]["program"] == "cost_probe"
        recs.append({k: v for k, v in pa[0].items() if k != "t"})
        jax.clear_caches()
    assert recs[0] == recs[1]


def test_program_analysis_kill_switch_and_ledger_off(tmp_path, monkeypatch):
    f = instrumented_jit(lambda x: x * 2, program="doubler")
    # no active ledger: plain passthrough, nothing recorded anywhere
    assert float(f(jnp.asarray(2.0))) == 4.0
    # active ledger + kill-switch: program_call still recorded, analysis not
    monkeypatch.setenv("VIDEOP2P_OBS_NO_ANALYSIS", "1")
    path = str(tmp_path / "ledger.jsonl")
    g = instrumented_jit(lambda x: x * 3, program="tripler")
    with RunLedger(path):
        g(jnp.asarray(2.0))
    kinds = [e["event"] for e in read_ledger(path)]
    assert "program_call" in kinds
    assert "program_analysis" not in kinds


def test_program_analysis_skip_is_an_event_not_silence(tmp_path, monkeypatch):
    """ISSUE 5 satellite: when the automatic analysis is disabled or cannot
    run, the ledger records a program_analysis_skipped event with the
    reason — a missing record is a statement, never a silent drop."""
    path = str(tmp_path / "ledger.jsonl")
    f = instrumented_jit(lambda x: x + 1, program="adder", analyze=False)
    with RunLedger(path):
        f(jnp.asarray(1.0))

    def skips(p):
        return [(e["program"], e["reason"]) for e in read_ledger(p)
                if e["event"] == "program_analysis_skipped"]

    assert skips(path) == [("adder", "analyze_false")]
    # the process-wide kill-switch states its reason too
    monkeypatch.setenv("VIDEOP2P_OBS_NO_ANALYSIS", "1")
    path2 = str(tmp_path / "ledger2.jsonl")
    g = instrumented_jit(lambda x: x + 2, program="adder2")
    with RunLedger(path2):
        g(jnp.asarray(1.0))
    assert skips(path2) == [("adder2", "disabled")]
    monkeypatch.delenv("VIDEOP2P_OBS_NO_ANALYSIS")
    # a failing lower/compile behind an otherwise-working call: the call
    # succeeds, the skip event lands with the failure reason
    from videop2p_tpu.obs import introspect as introspect_mod

    path3 = str(tmp_path / "ledger3.jsonl")
    h = instrumented_jit(lambda x: x * 2, program="flaky")
    with monkeypatch.context() as m:
        m.setattr(introspect_mod, "compile_abstract", lambda *a, **kw: None)
        with RunLedger(path3):
            out = h(jnp.asarray(3.0))
    assert float(out) == 6.0
    assert skips(path3) == [("flaky", "lower_or_compile_failed")]
    # skipped events never fire on a healthy analyzed program
    path4 = str(tmp_path / "ledger4.jsonl")
    k = instrumented_jit(lambda x: x * 3, program="ok")
    with RunLedger(path4):
        k(jnp.asarray(1.0))
    assert skips(path4) == []
    assert any(e["event"] == "program_analysis" for e in read_ledger(path4))


def test_null_text_programs_emit_analysis(problem, sched, tmp_path):
    """The pipelines' internal jits (fused + chunked null-text) are
    instrumented where the CLI's wrappers cannot reach — both land
    program_analysis events with distinct fingerprints."""
    fn, _, cond, uncond, traj = problem
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path):
        null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, num_inner_steps=2,
        )
        null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, num_inner_steps=2, outer_chunk=3,
        )
    pa = {e["program"]: e for e in read_ledger(path)
          if e["event"] == "program_analysis"}
    assert set(pa) == {"null_text_fused", "null_text_chunked"}
    for e in pa.values():
        assert e["flops"] > 0 and len(e["hlo_fingerprint"]) == 16
    assert (pa["null_text_fused"]["hlo_fingerprint"]
            != pa["null_text_chunked"]["hlo_fingerprint"])


# -------------------------------------------- run history + regression --


def _write_run(path, run_id, wall_time, analyses, phases=()):
    """Synthetic ledger run: program_analysis + phase events with
    controlled values (RunLedger stamps run_start/run_end around them)."""
    led = RunLedger(path, run_id=run_id, device_info=False)
    # overwrite the auto wall_time for deterministic ordering
    led.event("run_start_patch")  # no-op marker; ordering uses run_start
    for prog, rec in analyses.items():
        led.program_analysis(prog, rec)
    for name, secs in phases:
        led.phase(name, secs)
    led.close()
    # rewrite wall_time in-place (the ledger stamped now())
    import json as _json

    lines = []
    for line in open(path):
        e = _json.loads(line)
        if e.get("event") == "run_start" and e.get("run_id") == run_id:
            e["wall_time"] = wall_time
        lines.append(_json.dumps(e))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


_ANALYSIS_A = {"flops": 1000, "bytes_accessed": 10 * 2**20,
               "temp_bytes": 100 * 2**20, "peak_hbm_bytes": 200 * 2**20,
               "hlo_instructions": 500, "hlo_fingerprint": "aaaa"}


def test_run_history_scan_series_and_baseline(tmp_path):
    from videop2p_tpu.obs import RunHistory

    d = str(tmp_path)
    _write_run(os.path.join(d, "r1.jsonl"), "r1", "2026-08-01T00:00:00Z",
               {"edit": _ANALYSIS_A}, phases=[("edit_phase", 10.0)])
    # two runs APPENDED into one file (ledgers open append-mode)
    p2 = os.path.join(d, "r2.jsonl")
    _write_run(p2, "r2", "2026-08-02T00:00:00Z", {"edit": _ANALYSIS_A})
    _write_run(p2, "r3", "2026-08-03T00:00:00Z",
               {"edit": {**_ANALYSIS_A, "temp_bytes": 120 * 2**20}})
    hist = RunHistory.scan(d)
    assert [r["run_id"] for r in hist.runs] == ["r1", "r2", "r3"]
    series = hist.series("temp_bytes")
    # keyed by (label, fingerprint): same program+fingerprint = one series
    assert set(series) == {("edit", "aaaa")}
    assert [v for _, v in series[("edit", "aaaa")]] == [
        100 * 2**20, 100 * 2**20, 120 * 2**20]
    latest = hist.latest()
    assert latest["run_id"] == "r3"
    base = hist.baseline_for(latest)
    assert base["run_id"] == "r2"


def test_regression_rules_flag_injected_regression(tmp_path):
    from videop2p_tpu.obs import evaluate_rules, extract_run, split_runs

    d = str(tmp_path)
    _write_run(os.path.join(d, "a.jsonl"), "a", "2026-08-01T00:00:00Z",
               {"edit": _ANALYSIS_A}, phases=[("p", 10.0)])
    _write_run(os.path.join(d, "b.jsonl"), "b", "2026-08-02T00:00:00Z",
               {"edit": {**_ANALYSIS_A,
                         "temp_bytes": int(_ANALYSIS_A["temp_bytes"] * 1.2),
                         "hlo_fingerprint": "bbbb"}},
               phases=[("p", 10.1)])
    base = extract_run(split_runs(read_ledger(os.path.join(d, "a.jsonl")))[0])
    new = extract_run(split_runs(read_ledger(os.path.join(d, "b.jsonl")))[0])
    # self-compare: always clean
    assert evaluate_rules(base, base)["pass"]
    res = evaluate_rules(base, new)
    assert not res["pass"]
    regs = {(v["metric"], v["program"]) for v in res["regressions"]}
    assert regs == {("temp_bytes", "edit")}  # +20% temp, phases within noise
    [v] = res["regressions"]
    assert v["delta_pct"] == 20.0
    assert v["fingerprint_changed"] is True
    # the phase verdict exists but is under threshold
    phase_v = [x for x in res["verdicts"] if x["kind"] == "phase"]
    assert phase_v and not phase_v[0]["regressed"]


def test_extract_run_tolerates_partial_events(tmp_path):
    """A torn tail (killed run) can leave half-records: extraction and
    rendering must survive events missing their payload fields."""
    from videop2p_tpu.obs import extract_run

    rec = extract_run([
        {"event": "phase"},  # no name/seconds
        {"event": "compile", "seconds": "junk"},
        {"event": "program_call", "program": "x"},
        {"event": "program_analysis"},  # no program/metrics
        {"not_even": "an event"},
    ])
    assert rec["run_id"] is None
    assert rec["phases"]["?"]["calls"] == 1
    assert "(unattributed)" in rec["programs"]


def test_obs_diff_cli_self_zero_and_regression_nonzero(tmp_path, capsys):
    """The acceptance gate: obs_diff exits 0 comparing a ledger against
    itself and nonzero on a synthetically injected +20% temp-bytes
    regression; --history mode agrees."""
    mod = _load_tool("obs_diff")
    d = str(tmp_path)
    a = os.path.join(d, "a.jsonl")
    b = os.path.join(d, "b.jsonl")
    _write_run(a, "a", "2026-08-01T00:00:00Z", {"edit": _ANALYSIS_A})
    _write_run(b, "b", "2026-08-02T00:00:00Z",
               {"edit": {**_ANALYSIS_A,
                         "temp_bytes": int(_ANALYSIS_A["temp_bytes"] * 1.2)}})
    assert mod.main(["obs_diff.py", a, a]) == 0
    out = capsys.readouterr().out
    assert "no regressions" in out
    assert mod.main(["obs_diff.py", a, b]) == 1
    out = capsys.readouterr().out
    assert "REGRESSIONS" in out and "temp_bytes" in out
    # --history picks the prior run as baseline for the latest
    assert mod.main(["obs_diff.py", "--history", d]) == 1
    # threshold scaling can wave it through
    assert mod.main(["obs_diff.py", "--threshold-scale", "3.0", a, b]) == 0
    # unreadable input is usage error, not a crash
    assert mod.main(["obs_diff.py", a, os.path.join(d, "missing.jsonl")]) == 2


def test_obs_diff_json_output_is_machine_readable(tmp_path, capsys):
    mod = _load_tool("obs_diff")
    a = str(tmp_path / "a.jsonl")
    _write_run(a, "a", "2026-08-01T00:00:00Z", {"edit": _ANALYSIS_A})
    assert mod.main(["obs_diff.py", "--json", a, a]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["pass"] is True and verdict["regressions"] == []


# --------------------------------------------------------- CLI e2e (slow) --


@pytest.mark.slow
def test_cli_full_mode_writes_acceptance_ledger(tmp_path):
    """The acceptance run: a full-mode (null-text) CLI edit with
    --telemetry/--ledger writes a JSONL holding ≥1 compile event, ≥1 phase
    event, and the decoded fused-null-text telemetry (loss curve +
    inner-steps); ledger_summary renders it without error."""
    from videop2p_tpu.cli.run_videop2p import main as p2p

    ledger_path = str(tmp_path / "acceptance_ledger.jsonl")
    inv_gif, edit_gif = p2p(
        pretrained_model_path=str(tmp_path / "no_ckpt"),
        image_path="data/rabbit",
        prompt="a rabbit is jumping",
        prompts=["a rabbit is jumping", "a origami rabbit is jumping"],
        save_name="origami", is_word_swap=False,
        video_len=2, fast=False, tiny=True, num_inner_steps=2,
        telemetry=True, ledger=ledger_path, reuse_inversion=False,
    )
    assert os.path.isfile(inv_gif) and os.path.isfile(edit_gif)
    events = read_ledger(ledger_path)
    kinds = {e["event"] for e in events}
    assert {"run_start", "compile", "phase", "telemetry", "memory",
            "run_end"} <= kinds
    null_tel = [e for e in events if e["event"] == "telemetry"
                and e["program"] == "null_text_fused"]
    assert null_tel, "fused null-text telemetry missing from the ledger"
    rec = null_tel[0]
    assert len(rec["loss_curve"]) == 50
    assert len(rec["inner_steps"]) == 50
    assert rec["inner_steps_total"] >= 50  # ≥1 inner Adam step per outer
    assert rec["latent"]["nan_total"] == 0
    phases = [e["name"] for e in events if e["event"] == "phase"]
    assert "null_text_optimization" in phases
    # ISSUE 3: every instrumented program's compile was mined into a
    # program_analysis event — including the pipeline-internal fused
    # null-text jit the CLI wrappers cannot reach
    pa = {e["program"]: e for e in events
          if e["event"] == "program_analysis"}
    assert "null_text_fused" in pa and "vae_encode" in pa
    for e in pa.values():
        assert e["flops"] > 0 and len(e["hlo_fingerprint"]) == 16
    mod = _load_summary_tool()
    text = mod.render(events)
    assert "null_text_fused" in text and "inner steps" in text
    assert "program analysis" in text
