"""Frame-attention kernel tests (CPU: chunked vs dense exactness, dispatch).

The Pallas flash path needs a real TPU; it is exercised by bench.py and the
verify drive. Here we pin the chunked kernel's exactness and the dispatch
rules the UNet relies on.
"""

import jax
import jax.numpy as jnp
import numpy as np

from videop2p_tpu.ops import (
    chunked_frame_attention,
    dense_frame_attention,
    make_frame_attention_fn,
)


def _rand_qkv(key, B=1, F=3, H=2, N=1024, D=8):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, F, H, N, D))
    k = jax.random.normal(kk, (B, H, N, D))
    v = jax.random.normal(kv, (B, H, N, D))
    return q, k, v


def test_chunked_matches_dense():
    q, k, v = _rand_qkv(jax.random.key(0))
    out_c = jax.jit(lambda q, k, v: chunked_frame_attention(q, k, v, q_chunk=256))(q, k, v)
    out_d = jax.jit(dense_frame_attention)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(out_d), atol=1e-5)


def test_chunked_grad_matches_dense():
    q, k, v = _rand_qkv(jax.random.key(1), N=512, D=4)

    def loss(fn, q):
        return jnp.sum(fn(q, k, v) ** 2)

    g_c = jax.jit(jax.grad(lambda q: loss(
        lambda q, k, v: chunked_frame_attention(q, k, v, q_chunk=128), q)))(q)
    g_d = jax.jit(jax.grad(lambda q: loss(dense_frame_attention, q)))(q)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_d), atol=1e-4)


def test_chunked_falls_back_on_indivisible():
    q, k, v = _rand_qkv(jax.random.key(2), N=96)
    out = chunked_frame_attention(q, k, v, q_chunk=512)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )


def test_dispatch_rules():
    assert make_frame_attention_fn("dense") is None
    fn = make_frame_attention_fn("chunked", min_large_tokens=1024)
    # small site → dense path
    q, k, v = _rand_qkv(jax.random.key(3), N=64)
    out = fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )
    # large site off-TPU → chunked (still exact)
    q, k, v = _rand_qkv(jax.random.key(4), N=2048, D=4)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )
    import pytest

    with pytest.raises(ValueError, match="unknown frame attention impl"):
        make_frame_attention_fn("nope")
