"""Frame-attention kernel tests (CPU: chunked vs dense exactness, dispatch).

The Pallas flash path needs a real TPU; it is exercised by bench.py and the
verify drive. Here we pin the chunked kernel's exactness and the dispatch
rules the UNet relies on.
"""

import jax
import jax.numpy as jnp
import numpy as np

from videop2p_tpu.ops import (
    chunked_frame_attention,
    dense_frame_attention,
    make_frame_attention_fn,
)


def _rand_qkv(key, B=1, F=3, H=2, N=1024, D=8):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, F, H, N, D))
    k = jax.random.normal(kk, (B, H, N, D))
    v = jax.random.normal(kv, (B, H, N, D))
    return q, k, v


def test_chunked_matches_dense():
    q, k, v = _rand_qkv(jax.random.key(0))
    out_c = jax.jit(lambda q, k, v: chunked_frame_attention(q, k, v, q_chunk=256))(q, k, v)
    out_d = jax.jit(dense_frame_attention)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(out_d), atol=1e-5)


def test_chunked_grad_matches_dense():
    q, k, v = _rand_qkv(jax.random.key(1), N=512, D=4)

    def loss(fn, q):
        return jnp.sum(fn(q, k, v) ** 2)

    g_c = jax.jit(jax.grad(lambda q: loss(
        lambda q, k, v: chunked_frame_attention(q, k, v, q_chunk=128), q)))(q)
    g_d = jax.jit(jax.grad(lambda q: loss(dense_frame_attention, q)))(q)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_d), atol=1e-4)


def test_chunked_falls_back_on_indivisible():
    q, k, v = _rand_qkv(jax.random.key(2), N=96)
    out = chunked_frame_attention(q, k, v, q_chunk=512)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )


def test_fused_matches_dense_interpret():
    """The custom Pallas kernel (frame-0-KV resident in VMEM, full-row
    softmax) must equal dense — run in interpret mode so CPU tests cover the
    kernel math; the real-TPU path is exercised by bench.py."""
    from videop2p_tpu.ops import fused_frame_attention

    q, k, v = _rand_qkv(jax.random.key(5), F=2, N=256, D=8)
    out = jax.jit(
        lambda q, k, v: fused_frame_attention(q, k, v, 128, True)
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )


def test_fused_grad_falls_back_to_chunked():
    """Differentiating through the fused kernel must agree with dense — the
    custom VJP recomputes via the chunked exact backward."""
    from videop2p_tpu.ops import fused_frame_attention

    q, k, v = _rand_qkv(jax.random.key(6), F=2, N=256, D=4)

    g_f = jax.jit(jax.grad(lambda q: jnp.sum(
        fused_frame_attention(q, k, v, 128, True) ** 2)))(q)
    g_d = jax.jit(jax.grad(lambda q: jnp.sum(
        dense_frame_attention(q, k, v) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_d), atol=1e-4)


def test_auto_dispatch_off_tpu_is_dense():
    # "auto" resolves per-backend: dense (None) on CPU, fused on TPU
    assert make_frame_attention_fn("auto") is None
    # "fused" off-TPU falls back to chunked for large sites (still exact)
    fn = make_frame_attention_fn("fused", min_large_tokens=1024)
    q, k, v = _rand_qkv(jax.random.key(7), N=2048, D=4)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )


def test_dispatch_rules():
    assert make_frame_attention_fn("dense") is None
    fn = make_frame_attention_fn("chunked", min_large_tokens=1024)
    # small site → dense path
    q, k, v = _rand_qkv(jax.random.key(3), N=64)
    out = fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )
    # large site off-TPU → chunked (still exact)
    q, k, v = _rand_qkv(jax.random.key(4), N=2048, D=4)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_frame_attention(q, k, v)), atol=1e-5
    )
    import pytest

    with pytest.raises(ValueError, match="unknown frame attention impl"):
        make_frame_attention_fn("nope")
