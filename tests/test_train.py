"""Stage-1 tuning tests: trainable-mask rule, loss descent, freeze guarantee,
lr schedules, checkpoint round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.core import DDPMScheduler
from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.pipelines import make_unet_fn
from videop2p_tpu.train import (
    TrainState,
    TuneConfig,
    count_params,
    latest_checkpoint,
    make_lr_schedule,
    make_optimizer,
    restore_checkpoint,
    save_checkpoint,
    trainable_mask,
    train_step,
    train_steps,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    latents = 0.3 * jax.random.normal(jax.random.key(0), (1, 2, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    variables = jax.jit(model.init)(jax.random.key(2), latents, jnp.asarray(0), text)
    return make_unet_fn(model), dict(variables), latents, text


def test_trainable_mask_rule(tiny):
    """Default rule: attn1.to_q, attn2.to_q and ALL of attn_temp
    (run_tuning.py:50-54,137-141)."""
    _, variables, _, _ = tiny
    params = variables["params"]
    mask = trainable_mask(params)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    on = {jax.tree_util.keystr(p) for p, v in flat if v}
    off = {jax.tree_util.keystr(p) for p, v in flat if not v}
    assert any("attn1" in p and "to_q" in p for p in on)
    assert any("attn2" in p and "to_q" in p for p in on)
    assert any("attn_temp" in p and "to_v" in p for p in on)  # whole module
    assert any("attn_temp" in p and "to_out" in p for p in on)
    assert all("attn1" not in p or "to_q" in p for p in on if "attn_temp" not in p)
    assert any("to_k" in p and "attn_temp" not in p for p in off)
    assert any("conv" in p for p in off)
    n_train = count_params(params, mask)
    n_total = count_params(params)
    assert 0 < n_train < n_total


def test_train_step_descends_and_freezes(tiny):
    fn, variables, latents, text = tiny
    params = variables["params"]
    cfg = TuneConfig(learning_rate=1e-3)
    tx = make_optimizer(cfg)
    mask = trainable_mask(params)
    state = TrainState.create(params, tx)

    step = jax.jit(
        lambda s, k: train_step(
            fn, tx, s, DDPMScheduler.create_sd(), latents, text, k
        )
    )
    key = jax.random.key(0)
    losses = []
    for i in range(8):
        # fixed key: same noise/timestep every step → loss must descend
        state, loss = step(state, key)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 8

    # frozen params bit-identical; trainable params changed
    flat0 = jax.tree_util.tree_flatten_with_path(params)[0]
    flat1 = {jax.tree_util.keystr(p): v for p, v in
             jax.tree_util.tree_flatten_with_path(state.params)[0]}
    flatm = {jax.tree_util.keystr(p): v for p, v in
             jax.tree_util.tree_flatten_with_path(mask)[0]}
    changed = unchanged = 0
    for p, v0 in flat0:
        k = jax.tree_util.keystr(p)
        same = np.array_equal(np.asarray(v0), np.asarray(flat1[k]))
        if flatm[k]:
            changed += 0 if same else 1
        else:
            assert same, f"frozen param {k} changed"
            unchanged += 1
    assert changed > 0 and unchanged > 0


@pytest.mark.slow  # ~15 s: compiles the scanned AND the sequential program
def test_train_steps_scan_matches_sequential(tiny):
    """train_steps (one lax.scan over K steps — the CLI's dispatch-batched
    loop) must reproduce K sequential train_step calls with per-step keys
    derived by absolute step index (fold_in(base, step)) — and chunking must
    therefore be boundary-invariant: 4 = 1+3 steps bit-for-bit."""
    fn, variables, latents, text = tiny
    params = variables["params"]
    tx = make_optimizer(TuneConfig(learning_rate=1e-3))
    sched = DDPMScheduler.create_sd()
    K = 4
    base = jax.random.key(7)

    state_seq = TrainState.create(params, tx)
    seq_losses = []
    for i in range(K):
        state_seq, loss = jax.jit(
            lambda s, kk: train_step(fn, tx, s, sched, latents, text, kk)
        )(state_seq, jax.random.fold_in(base, i))
        seq_losses.append(float(loss))

    state_scan = TrainState.create(params, tx)
    state_scan, losses = jax.jit(
        lambda s, kk: train_steps(fn, tx, s, sched, latents, text, kk, num_steps=K)
    )(state_scan, base)
    np.testing.assert_allclose(np.asarray(losses), np.asarray(seq_losses), rtol=1e-5)
    assert int(state_scan.step) == K
    # scanned and unrolled programs fuse differently, and Adam's g/√v
    # normalization amplifies last-ulp gradient differences while v̂ is
    # still near zero — measured divergence is ~1.4e-6 after 4 steps
    # (it was ~3e-7 with the pre-r5 flax GroupNorm's bf16-apply schedule)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6
        ),
        state_scan.trainable, state_seq.trainable,
    )

    # chunk-boundary invariance: 1 then 3 steps == 4 steps
    s2 = TrainState.create(params, tx)
    s2, l1 = train_steps(fn, tx, s2, sched, latents, text, base, num_steps=1)
    s2, l3 = train_steps(fn, tx, s2, sched, latents, text, base, num_steps=3)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(l1), np.asarray(l3)]), np.asarray(losses),
        rtol=1e-5,
    )


def test_dependent_noise_train_path(tiny):
    from videop2p_tpu.core import DependentNoiseSampler

    fn, variables, latents, text = tiny
    params = variables["params"]
    cfg = TuneConfig()
    tx = make_optimizer(cfg)
    state = TrainState.create(params, tx)
    sampler = DependentNoiseSampler.create(num_frames=2, decay_rate=0.5, window_size=2)
    state, loss = jax.jit(
        lambda s, k: train_step(
            fn, tx, s, DDPMScheduler.create_sd(), latents, text, k,
            dependent_sampler=sampler,
        )
    )(state, jax.random.key(0))
    assert np.isfinite(float(loss))


def test_gradient_accumulation_updates_every_k(tiny):
    fn, variables, latents, text = tiny
    params = variables["params"]
    cfg = TuneConfig(gradient_accumulation_steps=2, learning_rate=1e-3)
    tx = make_optimizer(cfg)
    state = TrainState.create(params, tx)
    step = jax.jit(
        lambda s, k: train_step(fn, tx, s, DDPMScheduler.create_sd(), latents, text, k)
    )
    state1, _ = step(state, jax.random.key(0))
    # after 1 micro-step no real update yet
    l0 = jax.tree_util.tree_leaves(params)
    l1 = jax.tree_util.tree_leaves(state1.params)
    assert all(np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(l0, l1))
    state2, _ = step(state1, jax.random.key(1))
    l2 = jax.tree_util.tree_leaves(state2.params)
    assert not all(np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(l0, l2))


def test_lr_schedules():
    for name in ["constant", "constant_with_warmup", "linear", "cosine"]:
        cfg = TuneConfig(lr_scheduler=name, lr_warmup_steps=10, max_train_steps=100)
        sched = make_lr_schedule(cfg)
        v0, vw, vend = float(sched(0)), float(sched(10)), float(sched(99))
        assert np.isfinite([v0, vw, vend]).all()
        if name != "constant":
            assert v0 == 0.0 or name == "constant"
        assert vw == pytest.approx(cfg.learning_rate, rel=1e-3)
    with pytest.raises(ValueError):
        make_lr_schedule(TuneConfig(lr_scheduler="nope"))


def test_checkpoint_roundtrip(tmp_path, tiny):
    fn, variables, latents, text = tiny
    params = variables["params"]
    cfg = TuneConfig()
    tx = make_optimizer(cfg)
    state = TrainState.create(params, tx)
    state, _ = jax.jit(
        lambda s, k: train_step(fn, tx, s, DDPMScheduler.create_sd(), latents, text, k)
    )(state, jax.random.key(0))

    out = str(tmp_path / "run")
    save_checkpoint(out, state, 1)
    save_checkpoint(out, state, 5)
    latest = latest_checkpoint(out)
    assert latest is not None and latest.endswith("checkpoint-5")
    restored = restore_checkpoint(latest, state)
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert latest_checkpoint(str(tmp_path / "nope")) is None
    # ISSUE 9 pin: restored leaves are jax-OWNED buffers (copied, not
    # zero-copy views of orbax/tensorstore storage), so the resume path's
    # donated train_steps carry cannot alias memory jax does not own — the
    # use-after-free showed up as garbage weights in the resumed run's
    # next checkpoint before restore_checkpoint copied
    assert all(isinstance(leaf, jax.Array)
               for leaf in jax.tree_util.tree_leaves(restored)
               if hasattr(leaf, "shape"))
    donated = jax.jit(lambda t: jax.tree.map(lambda x: x + 0, t),
                      donate_argnums=0)(restored)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(donated)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_preempt_signal_handler_sets_event_and_restores():
    """ISSUE 9 satellite: run_tuning installs SIGTERM/SIGINT handlers that
    set the preemption event (checked at every chunk boundary) and
    restores the previous handlers afterwards."""
    import signal

    from videop2p_tpu.cli import run_tuning as rt

    assert not rt._PREEMPT_EVENT.is_set()
    before = signal.getsignal(signal.SIGTERM)
    restore = rt._install_preempt_handlers()
    try:
        assert signal.getsignal(signal.SIGTERM) is rt._preempt_handler
        assert signal.getsignal(signal.SIGINT) is rt._preempt_handler
        signal.raise_signal(signal.SIGTERM)  # delivered synchronously
        assert rt._PREEMPT_EVENT.is_set()
    finally:
        rt._PREEMPT_EVENT.clear()
        restore()
    assert signal.getsignal(signal.SIGTERM) is before


def _tune_cfg(root, name, **over):
    cfg = dict(
        pretrained_model_path=str(root / f"no_ckpt_{name}"),
        output_dir=str(root / name),
        train_data={"video_path": "data/rabbit", "prompt": "a rabbit is jumping",
                    "n_sample_frames": 2, "width": 16, "height": 16},
        # no validation work: empty prompt list, no inversion
        validation_data={"prompts": [], "use_inv_latent": False},
        max_train_steps=4, steps_per_call=2, log_every=2,
        checkpointing_steps=0, validation_steps=0,
        tiny=True, mixed_precision="no", seed=0,
        gradient_checkpointing=False,
    )
    cfg.update(over)
    return cfg


@pytest.mark.slow  # ~30 s: three tiny end-to-end tuning runs
def test_tuning_preemption_checkpoint_and_bit_identical_resume(
    tmp_path, monkeypatch
):
    """ISSUE 9 satellite — preemption safety e2e: a preempted run saves a
    final checkpoint at the chunk boundary and exits WITHOUT exporting a
    pipeline; auto-resume from `latest` continues to completion and the
    tuned weights are BIT-IDENTICAL to an uninterrupted run (per-step
    noise keys derive from (run key, absolute step), so the resume
    boundary cannot change the noise sequence)."""
    import threading

    from videop2p_tpu.cli import run_tuning as rt

    # deterministic "SIGTERM already pending": the loop preempts at the
    # FIRST chunk boundary (step 2 of 4)
    monkeypatch.setattr(rt, "_PREEMPT_EVENT", threading.Event())
    rt._PREEMPT_EVENT.set()
    out_b = rt.main(**_tune_cfg(tmp_path, "interrupted"))
    ckpt = latest_checkpoint(out_b)
    assert ckpt is not None and ckpt.endswith("checkpoint-2")
    assert not os.path.isfile(os.path.join(out_b, "model_index.json"))

    # auto-resume continues 2 -> 4 and exports the pipeline
    monkeypatch.setattr(rt, "_PREEMPT_EVENT", threading.Event())
    out_b2 = rt.main(**_tune_cfg(tmp_path, "interrupted",
                                 resume_from_checkpoint="latest"))
    assert out_b2 == out_b
    weights_b = os.path.join(out_b, "unet",
                             "diffusion_pytorch_model.safetensors")
    assert os.path.isfile(weights_b)

    # the uninterrupted reference run
    out_a = rt.main(**_tune_cfg(tmp_path, "straight"))
    weights_a = os.path.join(out_a, "unet",
                             "diffusion_pytorch_model.safetensors")
    with open(weights_a, "rb") as fa, open(weights_b, "rb") as fb:
        assert fa.read() == fb.read(), (
            "resumed weights differ from the uninterrupted run — the "
            "resume boundary changed the training trajectory"
        )


@pytest.mark.slow  # ~19 s: two full UNet grad compiles (policy vs none)
def test_remat_policy_threads_through_blocks():
    """remat_policy selects a jax.checkpoint policy for the per-block remat;
    gradients must flow and match the no-policy remat numerically."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn

    x = jax.random.normal(jax.random.key(0), (1, 2, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, 16))

    grads = {}
    params = None
    for policy in (None, "dots_saveable"):
        cfg = UNet3DConfig.tiny(gradient_checkpointing=True, remat_policy=policy)
        model = UNet3DConditionModel(config=cfg)
        if params is None:
            # the param pytree is policy-independent — one init serves both
            params = jax.jit(model.init)(jax.random.key(2), x, jnp.asarray(3), text)
        fn = make_unet_fn(model)

        def loss(p):
            out, _ = fn(p, x, jnp.asarray(3), text)
            return jnp.mean(out**2)

        # jitted: eager (op-by-op) grad of even the tiny UNet costs ~minutes
        # of dispatch overhead on this host, and only jitted programs hit the
        # persistent compilation cache
        grads[policy] = jax.jit(jax.grad(loss))(params)
    a = jax.tree_util.tree_leaves(grads[None])
    b = jax.tree_util.tree_leaves(grads["dots_saveable"])
    for ga, gb in zip(a, b):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), atol=1e-5)
