"""Pipeline tests: inversion round-trip, fast-mode source replay, null-text
optimization, and the controlled edit loop end-to-end on a tiny UNet.

SURVEY §4's recommended strategy: exact contract tests on analytic fake
denoisers (where DDIM inversion must invert bit-for-bit), plus a tiny-model
end-to-end edit exercising UNet + scheduler + scan + controllers together.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from videop2p_tpu.control import make_controller
from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.pipelines import (
    ddim_inversion,
    edit_sample,
    make_unet_fn,
    null_text_optimization,
)
from videop2p_tpu.utils.tokenizers import WordTokenizer

STEPS = 10
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C)


@pytest.fixture(scope="module")
def sched():
    return DDIMScheduler.create_sd()


def const_unet(eps0):
    """Denoiser that ignores its input — DDIM inversion is then exactly
    invertible (next_step and prev_step use the identical ε)."""

    def fn(params, sample, t, text, control=None):
        return jnp.broadcast_to(eps0, sample.shape), {}

    return fn


def text_unet():
    """Denoiser whose output depends on the text embedding and latent — gives
    null-text optimization a real objective."""

    def fn(params, sample, t, text, control=None):
        bias = jnp.mean(text, axis=(1, 2))  # (B,)
        return 0.1 * sample + bias[:, None, None, None, None], {}

    return fn


@pytest.fixture(scope="module")
def tiny():
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), SHAPE)
    text = jax.random.normal(jax.random.key(1), (1, 77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(10), text)
    return make_unet_fn(model), params, cfg


def test_inversion_exact_roundtrip_const_eps(sched):
    """With an x-independent ε the forward DDIM walk must be exactly inverted
    by the reverse walk (scheduler next_step/prev_step are mutual inverses
    given the same ε — run_videop2p.py:445-463 closed forms)."""
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    eps0 = jax.random.normal(jax.random.key(1), SHAPE[1:])
    fn = const_unet(eps0)
    traj = jax.jit(
        lambda x: ddim_inversion(fn, None, sched, x, jnp.zeros((1, 77, 8)),
                                 num_inference_steps=STEPS)
    )(x0)
    assert traj.shape == (STEPS + 1,) + SHAPE
    # walk back with prev_step
    lat = traj[-1]
    ts = sched.timesteps(STEPS)
    for t in ts:
        lat = sched.prev_step(jnp.broadcast_to(eps0, lat.shape), t, lat, STEPS)
    np.testing.assert_allclose(np.asarray(lat), np.asarray(x0), atol=1e-4)


def test_edit_sample_replays_inversion_const_eps(sched):
    """edit_sample with source_uses_cfg=False (fast mode) must replay the
    inversion for the source stream (pipeline_tuneavideo.py:412-415)."""
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    eps0 = jax.random.normal(jax.random.key(1), SHAPE[1:])
    fn = const_unet(eps0)
    cond = jnp.zeros((2, 77, 8))
    uncond = jnp.ones((77, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond[:1], num_inference_steps=STEPS)
    out = jax.jit(
        lambda xt: edit_sample(
            fn, None, sched, xt, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=7.5, source_uses_cfg=False,
        )
    )(traj[-1])
    assert out.shape == (2,) + SHAPE[1:]
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(x0[0]), atol=1e-4)


def test_tiny_unet_fast_source_stream_batch_independent(sched, tiny):
    """On a real (random-init) tiny UNet the fast-mode source stream of the
    CFG batch must equal a standalone single-prompt cond-only denoise from the
    same x_T — i.e. batching other streams alongside cannot perturb the source
    (this is what makes fast-mode inversion replay exact in the reference,
    pipeline_tuneavideo.py:412-415)."""
    fn, params, cfg = tiny
    x_t = jax.random.normal(jax.random.key(3), SHAPE)
    cond1 = jax.random.normal(jax.random.key(4), (1, 77, cfg.cross_attention_dim))
    cond = jnp.concatenate([cond1, cond1 + 0.1], axis=0)
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    out2 = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond, uncond,
            num_inference_steps=STEPS, source_uses_cfg=False,
        )
    )(x_t)
    out1 = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond1, uncond,
            num_inference_steps=STEPS, source_uses_cfg=False,
        )
    )(x_t)
    np.testing.assert_allclose(np.asarray(out2[0]), np.asarray(out1[0]), atol=1e-4)


def test_null_text_optimization_improves_replay(sched):
    """Optimized per-step uncond embeddings must reconstruct the inversion
    trajectory under CFG better than the raw uncond embedding
    (the whole point of null-text inversion, run_videop2p.py:580-612)."""
    fn = text_unet()
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond, num_inference_steps=STEPS)
    uncond_seq = jax.jit(
        lambda tr: null_text_optimization(
            fn, None, sched, tr, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=7.5,
        )
    )(traj)
    assert uncond_seq.shape == (STEPS,) + uncond.shape

    def replay(null_seq):
        return edit_sample(
            fn, None, sched, traj[-1], cond, uncond,
            num_inference_steps=STEPS, guidance_scale=7.5, source_uses_cfg=True,
            null_uncond_embeddings=null_seq,
        )

    err_opt = np.mean(np.abs(np.asarray(replay(uncond_seq)[0] - x0[0])))
    err_raw = np.mean(np.abs(np.asarray(replay(None)[0] - x0[0])))
    assert err_opt < err_raw * 0.5, (err_opt, err_raw)


def test_controlled_edit_end_to_end(sched, tiny):
    """Full edit on the tiny UNet: refine controller + equalizer + LocalBlend,
    5 steps. Source stream must match the control-free run; outputs finite."""
    fn, params, cfg = tiny
    tok = WordTokenizer()
    prompts = ["a rabbit is jumping", "a origami rabbit is jumping"]
    ctx = make_controller(
        prompts, tok, num_steps=5,
        is_replace_controller=False,
        cross_replace_steps=0.8, self_replace_steps=0.6,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )
    # text embeddings must be 77-long to match the control tensors
    cond = jax.random.normal(jax.random.key(7), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    x_t = jax.random.normal(jax.random.key(8), SHAPE)

    run = jax.jit(
        lambda c: edit_sample(
            fn, params, sched, x_t, cond, uncond,
            num_inference_steps=5, ctx=c, source_uses_cfg=False,
            blend_res=(4, 4),
        )
    )
    out_ctrl = run(ctx)
    out_free = jax.jit(
        lambda: edit_sample(
            fn, params, sched, x_t, cond, uncond,
            num_inference_steps=5, source_uses_cfg=False,
        )
    )()
    assert out_ctrl.shape == (2,) + SHAPE[1:]
    assert np.isfinite(np.asarray(out_ctrl)).all()
    # the edit changes the edited stream but not the source stream
    np.testing.assert_allclose(
        np.asarray(out_ctrl[0]), np.asarray(out_free[0]), atol=1e-4
    )
    assert not np.allclose(np.asarray(out_ctrl[1]), np.asarray(out_free[1]), atol=1e-4)


def test_long_video_chunked_controlled_edit(sched):
    """The long-video working point at tiny scale (BASELINE configs 3/5 —
    24 frames; bench.py's long24 phase): invert + controlled edit with the
    query-chunked frame-attention kernel, which is the only memory-feasible
    kernel at 24 frames on one chip (dense 64²-site scores are ~19 GB).
    Chunked must agree with dense at identical params, and the blend carry /
    temporal control must shape-generalize past the 8-frame default.

    The dispatch rule falls back to dense below 1024 tokens, so at the tiny
    UNet's 64-token sites the kernel is forced in directly with a small
    q_chunk — otherwise this would compare dense against itself."""
    import functools

    from videop2p_tpu.ops.attention import chunked_frame_attention

    F_LONG = 24
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(
        config=cfg,
        frame_attention_fn=functools.partial(chunked_frame_attention, q_chunk=16),
    )
    shape = (1, F_LONG, 8, 8, 4)
    x0 = jax.random.normal(jax.random.key(0), shape)
    cond = jax.random.normal(jax.random.key(1), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), x0, jnp.asarray(10), cond[:1])
    fn = make_unet_fn(model)
    ctx = make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=3,
        is_replace_controller=False,
        cross_replace_steps=0.8, self_replace_steps=0.6,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )

    def run(fn_):
        traj = ddim_inversion(fn_, params, sched, x0, cond[:1],
                              num_inference_steps=3)
        return edit_sample(
            fn_, params, sched, traj[-1], cond, uncond,
            num_inference_steps=3, ctx=ctx, source_uses_cfg=False,
            blend_res=(4, 4),
        )

    out = jax.jit(lambda: run(fn))()
    assert out.shape == (2,) + shape[1:]
    assert np.isfinite(np.asarray(out)).all()

    # kernel equivalence at the same params: chunked == dense (exact math;
    # the tolerance covers reduce-order fp drift amplified over the scan)
    model_dense = UNet3DConditionModel(config=UNet3DConfig.tiny())
    out_dense = jax.jit(lambda: run(make_unet_fn(model_dense)))()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out_dense), atol=2e-3, rtol=1e-3
    )


def test_eta_dependent_noise_path(sched):
    """η>0 with the dependent sampler draws frame-correlated variance noise
    (dependent_ddim.py:320-334) — adjacent-frame noise correlation must be
    visible in the output difference from the η=0 path."""
    from videop2p_tpu.core import DependentNoiseSampler

    fn = const_unet(jnp.zeros(SHAPE[1:]))
    sampler = DependentNoiseSampler.create(num_frames=2, decay_rate=0.9, window_size=2)
    cond = jnp.zeros((1, 77, 8))
    uncond = jnp.zeros((77, 8))
    x_t = jax.random.normal(jax.random.key(0), SHAPE)
    out_eta = edit_sample(
        fn, None, sched, x_t, cond, uncond, num_inference_steps=STEPS,
        eta=0.5, dependent_sampler=sampler, key=jax.random.key(1),
    )
    out_det = edit_sample(
        fn, None, sched, x_t, cond, uncond, num_inference_steps=STEPS,
    )
    assert out_eta.shape == out_det.shape
    assert not np.allclose(np.asarray(out_eta), np.asarray(out_det))


def test_null_text_dependent_mode(sched):
    """Dependent mode threads AR-noise blends through every prediction
    (run_videop2p.py:465-487) and stays finite; lr clamps at 0 for >100 steps."""
    from videop2p_tpu.core import DependentNoiseSampler

    fn = text_unet()
    sampler = DependentNoiseSampler.create(num_frames=2, decay_rate=0.5, window_size=2)
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(
        fn, None, sched, x0, cond, num_inference_steps=STEPS,
        dependent_weight=0.3, dependent_sampler=sampler, key=jax.random.key(1),
    )
    out = jax.jit(
        lambda tr: null_text_optimization(
            fn, None, sched, tr, cond, uncond, num_inference_steps=STEPS,
            dependent_weight=0.3, dependent_sampler=sampler, key=jax.random.key(2),
        )
    )(traj)
    assert out.shape == (STEPS, 1, 77, 8)
    assert np.isfinite(np.asarray(out)).all()
    import pytest
    with pytest.raises(ValueError, match="requires dependent_sampler"):
        null_text_optimization(fn, None, sched, traj, cond, uncond,
                               num_inference_steps=STEPS, dependent_weight=0.3)


def test_spatial_replace_injects_source_latents(sched, tiny):
    """SpatialReplace (run_videop2p.py:235-246): while step < stop bound the
    edit stream's latents are the source stream's; afterwards they evolve
    freely, so with stop_inject=1.0 (never inject) streams differ."""
    from videop2p_tpu.control import make_spatial_replace_controller

    fn, params, cfg = tiny
    cond1 = jax.random.normal(jax.random.key(4), (1, 77, cfg.cross_attention_dim))
    cond = jnp.concatenate([cond1, cond1 + 0.5], axis=0)
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    x_t = jax.random.normal(jax.random.key(5), SHAPE)

    ctx_full = make_spatial_replace_controller(0.0, STEPS)  # inject every step
    out_full = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond, uncond,
            num_inference_steps=STEPS, ctx=ctx_full,
        )
    )(x_t)
    np.testing.assert_allclose(
        np.asarray(out_full[1]), np.asarray(out_full[0]), atol=1e-5
    )

    ctx_off = make_spatial_replace_controller(1.0, STEPS)  # never inject
    out_off = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond, uncond,
            num_inference_steps=STEPS, ctx=ctx_off,
        )
    )(x_t)
    assert not np.allclose(np.asarray(out_off[1]), np.asarray(out_off[0]), atol=1e-5)


def test_multi_frame_embeddings_match_shared(sched, tiny):
    """Per-frame ("multi") conditioning (pipeline_tuneavideo.py:366-367):
    frame-constant 4-D embeddings must reproduce the 3-D path exactly, and
    per-frame-varying embeddings must change the output."""
    fn, params, cfg = tiny
    F = SHAPE[1]
    cond = jax.random.normal(jax.random.key(6), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    x_t = jax.random.normal(jax.random.key(7), SHAPE)

    out3 = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond, uncond, num_inference_steps=STEPS,
        )
    )(x_t)
    cond4 = jnp.repeat(cond[:, None], F, axis=1)  # (P, F, 77, D)
    out4 = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond4, uncond, num_inference_steps=STEPS,
        )
    )(x_t)
    np.testing.assert_allclose(np.asarray(out4), np.asarray(out3), atol=1e-4)

    cond4v = cond4.at[:, 1:].add(0.5)  # vary later frames
    out4v = jax.jit(
        lambda xt: edit_sample(
            fn, params, sched, xt, cond4v, uncond, num_inference_steps=STEPS,
        )
    )(x_t)
    assert not np.allclose(np.asarray(out4v), np.asarray(out3), atol=1e-4)


def test_null_text_chunked_matches_full(sched):
    """outer_chunk splits the outer scan into host-level jitted chunks — the
    result must be identical to the single-scan path (watchdog workaround
    for the multi-minute SD-scale program)."""
    fn = text_unet()
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond, num_inference_steps=STEPS)
    full = null_text_optimization(
        fn, None, sched, traj, cond, uncond, num_inference_steps=STEPS,
    )
    chunked = null_text_optimization(
        fn, None, sched, traj, cond, uncond, num_inference_steps=STEPS,
        outer_chunk=4,  # 10 steps → chunks of 4, 4, 2 (uneven tail covered)
    )
    np.testing.assert_allclose(
        np.asarray(chunked), np.asarray(full), rtol=2e-5, atol=2e-6
    )


def test_cached_eps_replay_is_exact(sched, tiny):
    """DDIM next_step/prev_step are linear in (x, ε) with identical
    coefficients, so walking the inversion trajectory BACKWARD with the
    cached per-step ε recovers every latent exactly — the property behind
    the cached-source fast edit (ddim_inversion(return_eps=True)). The
    reference's fast mode re-predicts ε from the drifting latent and only
    approximately reconstructs; the cached replay is bit-tight."""
    fn, params, cfg = tiny
    x0 = 0.3 * jax.random.normal(jax.random.key(11), SHAPE)
    cond = jax.random.normal(jax.random.key(12), (1, 77, cfg.cross_attention_dim))

    traj, eps_seq = jax.jit(
        lambda p, x: ddim_inversion(
            fn, p, sched, x, cond, num_inference_steps=STEPS, return_eps=True
        )
    )(params, x0)
    assert traj.shape[0] == STEPS + 1 and eps_seq.shape[0] == STEPS

    timesteps = np.asarray(sched.timesteps(STEPS))[::-1]  # ascending walk order
    for i in range(STEPS):
        rec = sched.prev_step(eps_seq[i], timesteps[i], traj[i + 1], STEPS)
        np.testing.assert_allclose(
            np.asarray(rec), np.asarray(traj[i]), rtol=1e-5, atol=1e-6
        )
    # default call signature unchanged
    traj_only = jax.jit(
        lambda p, x: ddim_inversion(fn, p, sched, x, cond, num_inference_steps=STEPS)
    )(params, x0)
    # two separately-compiled programs (with/without the ε output) need not
    # be bitwise identical — tight tolerance, not bit equality
    np.testing.assert_allclose(
        np.asarray(traj_only), np.asarray(traj), rtol=1e-6, atol=1e-7
    )
