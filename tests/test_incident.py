"""Incident-plane tests (ISSUE 18): the always-on flight recorder
(bounded thread-safe ring teed from ``RunLedger.event``, recorder-off
path bit-exact), the :class:`IncidentManager`'s debounced declarative
triggers and atomic content-addressed capture bundles, the crash hooks
(subprocess e2e + in-process SIGUSR1), ledger-rotation interplay (the
ring keeps the recent history the rotated file shifted away), the
``fault_log`` most-recent-wins ring, and THE acceptance: a 2-replica
in-process fleet sharing ONE manager — the healthy run captures ZERO
incidents and self-compares clean through obs_diff, the chaos run
(``unavail@`` plan) trips the breaker into exactly ONE debounced bundle
whose post-mortem HTML names the trigger and a reservoir trace-id
exemplar, and the chaos ledger regresses against the healthy baseline
with exit-1 teeth.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from videop2p_tpu.obs.flight import FLIGHT_DEFAULT_CAPACITY, FlightRecorder
from videop2p_tpu.obs.incident import (
    INCIDENT_FIELDS,
    INCIDENT_TRIGGERS,
    IncidentManager,
)
from videop2p_tpu.obs.ledger import RunLedger, read_ledger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_incident_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bundles(root):
    return sorted(
        d for d in os.listdir(root)
        if d.startswith("incident_") and not d.endswith(".tmp")
        and os.path.isdir(os.path.join(root, d))
    )


# --------------------------------------------------------- flight ring --


def test_flight_ring_is_bounded_thread_safe_and_accounted():
    ring = FlightRecorder(capacity=64)
    assert ring.capacity == 64

    def hammer(worker):
        for i in range(500):
            ring.record({"event": "load", "worker": worker, "i": i})

    threads = [threading.Thread(target=hammer, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # bounded no matter the load; accounting never loses a record
    assert len(ring) == 64
    st = ring.stats()
    assert st == {"capacity": 64, "buffered": 64, "seen": 2000,
                  "dropped": 1936}
    # snapshot is oldest-first and per-worker ordered (appends are atomic)
    snap = ring.snapshot()
    assert len(snap) == 64
    for w in range(4):
        idxs = [e["i"] for e in snap if e["worker"] == w]
        assert idxs == sorted(idxs)
    # the probe measures on a scratch ring — real history is untouched
    assert ring.overhead_probe(n=64) > 0.0
    assert ring.stats()["seen"] == 2000
    # a record that can't even be appended is swallowed, never raised
    ring.record(None)  # dict-shaped or not, the plane keeps flying
    assert FlightRecorder(capacity=0).capacity == 1  # floor, not a crash
    assert FLIGHT_DEFAULT_CAPACITY == 2048


def test_ledger_flight_tee_is_bit_exact_and_mirrors_events(tmp_path):
    """The tee contract: attaching a recorder changes the written JSONL
    by NOTHING (same records, byte-identical lines modulo the monotonic
    ``t`` anchor), and the ring holds exactly the records the ledger
    wrote, most recent last."""

    def drive(led):
        led.event("fault", kind="dispatch_fail", detail="attempt=2")
        led.event("breaker", state_from="closed", state_to="open")
        for i in range(5):
            led.event("span", name="serve.dispatch", i=i)
        led.close()

    plain = RunLedger(str(tmp_path / "plain.jsonl"), run_id="r0",
                      device_info=False)
    drive(plain)

    teed = RunLedger(str(tmp_path / "teed.jsonl"), run_id="r0",
                     device_info=False)
    ring = FlightRecorder(capacity=4)
    teed.flight = ring
    drive(teed)

    def canon(path):
        out = []
        for e in read_ledger(path):
            e.pop("t", None)  # monotonic anchor: the only run-varying field
            out.append(e)
        return out

    assert canon(str(tmp_path / "plain.jsonl")) == canon(
        str(tmp_path / "teed.jsonl"))
    # the ring mirrors the written stream — INCLUDING close()'s run_end —
    # last `capacity` records, in order
    snap = ring.snapshot()
    assert [e["event"] for e in snap] == ["span"] * 3 + ["run_end"]
    assert ring.stats()["seen"] == 8 and ring.stats()["dropped"] == 4
    # and the ring dump is itself a replayable ledger
    n = ring.dump_jsonl(str(tmp_path / "ring.jsonl"))
    replay = read_ledger(str(tmp_path / "ring.jsonl"))
    assert n == 4 and [e["event"] for e in replay] == [
        e["event"] for e in snap]


def test_rotation_interplay_ring_outlives_rotated_segments(tmp_path):
    """A tiny ``max_bytes`` ledger rotates mid-run: the on-disk tail file
    only has the newest segment, but the flight ring kept the recent
    history ACROSS the seam — and the rotated chain still extracts the
    incident exactly once."""
    from videop2p_tpu.obs.history import extract_run

    led = RunLedger(str(tmp_path / "rot.jsonl"), run_id="rot",
                    device_info=False, max_bytes=2048)
    mgr = IncidentManager(str(tmp_path / "inc"), capacity=512)
    mgr.attach_ledger(led)
    for i in range(200):
        led.event("span", name="serve.queue", i=i, pad="x" * 40)
    assert led._rotations >= 1
    assert os.path.exists(str(tmp_path / "rot.1.jsonl"))

    bundle = mgr.trigger("deadline_exceeded", detail="watchdog fired")
    assert bundle is not None
    led.close()
    mgr.close()

    # the bundle's ring dump holds the full recent window, seam-free
    flight = read_ledger(os.path.join(bundle, "flight.jsonl"))
    spans = [e for e in flight if e.get("event") == "span"]
    assert spans[-1]["i"] == 199
    assert len(spans) > 100  # far more than the post-rotation tail file
    tail_only = []
    with open(str(tmp_path / "rot.jsonl")) as f:
        for line in f:
            if '"span"' in line:
                tail_only.append(line)
    assert len(spans) > len(tail_only)

    # read_ledger stitches the chain; the incident extracts exactly once
    events = read_ledger(str(tmp_path / "rot.jsonl"))
    assert sum(1 for e in events if e.get("event") == "ledger_rotated") >= 1
    run = extract_run(events)
    inc = run["incidents"]
    assert inc["incident"]["count"] == 1.0
    assert inc["incident:deadline_exceeded"]["count"] == 1.0


# ------------------------------------------------------ incident manager --


def test_incident_bundle_contents_debounce_and_degraded_probes(tmp_path):
    from videop2p_tpu.obs.tsdb import TimeSeriesStore, load_series_sidecar

    ts = TimeSeriesStore()
    for i in range(8):
        ts.add("queue_depth", float(i), float(i % 3), {"replica": "replica0"})
    mgr = IncidentManager(
        str(tmp_path / "inc"), tsdb=ts, cooldown_s=3600.0,
        cooldowns={"sigusr1": 0.0},
    )
    led = RunLedger(str(tmp_path / "led.jsonl"), run_id="unit",
                    device_info=False)
    mgr.attach_ledger(led)
    mgr.note_fingerprint("engine:unit", "fp-abc")
    mgr.register_target("engine:unit",
                        lambda: {"healthz": {"status": "ok"}, "metrics": {}})
    mgr.register_target("router:dead",
                        lambda: (_ for _ in ()).throw(OSError("conn refused")))
    mgr.register_exemplars(
        lambda: {"edit_fused": {"p99_trace_id": "tid-a", "max_trace_id":
                                "tid-b", "count": 3}})
    led.event("fault", kind="hang", detail="attempt=5")

    bundle = mgr.trigger("breaker_open", detail="closed->open",
                         extra_files={"../escape/crash.txt": "boom"},
                         trips=1)
    assert bundle is not None and os.path.isdir(bundle)
    # debounced duplicates: suppressed, counted, no second bundle
    assert mgr.trigger("breaker_open", detail="flap") is None
    assert mgr.trigger("breaker_open", detail="flap") is None
    # an independent trigger with its own 0s cooldown still fires
    assert mgr.trigger("sigusr1", detail="on demand") is not None
    assert len(_bundles(str(tmp_path / "inc"))) == 2

    files = sorted(os.listdir(bundle))
    assert files == ["crash.txt", "flight.jsonl", "manifest.json",
                     "series.npz", "targets.json"]  # basename-sanitized
    man = json.load(open(os.path.join(bundle, "manifest.json")))
    assert man["trigger"] == "breaker_open"
    assert man["fingerprints"] == {"engine:unit": "fp-abc"}
    assert man["context"] == {"trips": 1}
    assert man["exemplars"]["edit_fused"]["p99_trace_id"] == "tid-a"
    assert man["flight"]["buffered"] == 1 and man["flight_record_ns"] > 0
    assert man["bundle_id"] in os.path.basename(bundle)
    assert man["series"]["label"] == "breaker_open"
    series = load_series_sidecar(os.path.join(bundle, "series.npz"))
    assert any("queue_depth" in k for k in series)
    targets = json.load(open(os.path.join(bundle, "targets.json")))
    assert targets["engine:unit"]["healthz"]["status"] == "ok"
    assert "conn refused" in targets["router:dead"]["error"]
    flight = read_ledger(os.path.join(bundle, "flight.jsonl"))
    assert [e["event"] for e in flight] == ["fault"]

    # the mirrored ledger event carries exactly INCIDENT_FIELDS
    led.close()
    incs = [e for e in read_ledger(led.path) if e.get("event") == "incident"]
    assert len(incs) == 2  # breaker_open + sigusr1 (debounced never logs)
    assert set(incs[0]) == {"event", "t", *INCIDENT_FIELDS}
    assert incs[0]["suppressed"] == 0 and incs[0]["events"] == 1
    assert mgr.records()[0]["trigger"] == "breaker_open"
    assert mgr.summary()["by_trigger"] == {"breaker_open": 1, "sigusr1": 1}
    assert mgr.summary()["suppressed"] == {"breaker_open": 2}

    # suppressed count is carried into the NEXT bundle of that trigger
    mgr.cooldowns["breaker_open"] = 0.0
    b2 = mgr.trigger("breaker_open", detail="third")
    assert b2 is not None
    assert json.load(open(os.path.join(
        b2, "manifest.json")))["suppressed_since_last"] == 2

    # closed manager: triggers are inert, never raising
    mgr.close()
    assert mgr.trigger("crash", detail="after close") is None
    assert set(INCIDENT_TRIGGERS) >= {"breaker_open", "crash", "sigusr1"}


def test_sigusr1_on_demand_capture_and_hook_restore(tmp_path):
    prev_hook = sys.excepthook
    mgr = IncidentManager(str(tmp_path / "inc"), crash_hooks=True,
                          cooldowns={"sigusr1": 0.0})
    try:
        assert sys.excepthook is not prev_hook  # chained
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.perf_counter() + 5.0
        while not _bundles(str(tmp_path / "inc")):
            if time.perf_counter() > deadline:
                pytest.fail("SIGUSR1 capture never landed")
            time.sleep(0.01)
        bundle = os.path.join(str(tmp_path / "inc"),
                              _bundles(str(tmp_path / "inc"))[0])
        man = json.load(open(os.path.join(bundle, "manifest.json")))
        assert man["trigger"] == "sigusr1"
        assert os.path.exists(os.path.join(str(tmp_path / "inc"),
                                           "faulthandler.log"))
    finally:
        mgr.close()
    assert sys.excepthook is prev_hook  # restored, not clobbered


def test_crash_excepthook_dumps_bundle_from_subprocess(tmp_path):
    """E2E: an unhandled exception in a real interpreter writes a crash
    bundle (traceback + all-threads faulthandler dump) before the
    process dies nonzero."""
    root = str(tmp_path / "crash_inc")
    script = (
        "import sys; sys.path.insert(0, sys.argv[1])\n"
        "from videop2p_tpu.obs.incident import IncidentManager\n"
        "mgr = IncidentManager(sys.argv[2], crash_hooks=True,\n"
        "                      cooldowns={'crash': 0.0})\n"
        "raise ValueError('injected unhandled crash')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script, _REPO, root],
        capture_output=True, text=True, timeout=120.0,
    )
    assert proc.returncode != 0
    assert "injected unhandled crash" in proc.stderr  # chained prev hook ran
    names = _bundles(root)
    assert len(names) == 1
    bundle = os.path.join(root, names[0])
    man = json.load(open(os.path.join(bundle, "manifest.json")))
    assert man["trigger"] == "crash"
    assert "ValueError" in man["detail"]
    crash = open(os.path.join(bundle, "crash.txt")).read()
    assert "injected unhandled crash" in crash
    assert "faulthandler (all threads)" in crash


# ------------------------------------------------------- engine satellite --

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)

_PROMPTS = ("a rabbit is jumping", "a origami rabbit is jumping")


def test_fault_log_ring_tail_survives_thousand_faults(tmp_path):
    """ISSUE 18 satellite: ``EditEngine.fault_log`` is a most-recent-wins
    ring — after 1000 injected faults the LAST 256 entries (the ones an
    incident bundle needs) survive, not the first 256."""
    from videop2p_tpu.serve import EditEngine, ProgramSet, ProgramSpec
    from videop2p_tpu.serve.engine import _FAULT_LOG_MAX

    spec = ProgramSpec(**_SPEC_KW)
    eng = EditEngine(spec, programs=ProgramSet(spec),  # never dispatched
                     out_dir=str(tmp_path / "out"))
    try:
        for i in range(1000):
            eng._fault_event("dispatch_fail", attempt=i)
        log = list(eng.fault_log)
        assert len(log) == _FAULT_LOG_MAX == 256
        assert log[-1]["detail"] == "attempt=999"   # newest survives
        assert log[0]["detail"] == "attempt=744"    # oldest 744 evicted
        assert eng.counters["faults_injected"] == 1000  # counters: unbounded
    finally:
        eng.close()


# ------------------------------------------------- THE chaos acceptance --


@pytest.fixture(scope="module")
def programs():
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps.warm(_PROMPTS, batch_sizes=(2,))
    return ps


def _request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt=_PROMPTS[0],
              prompts=list(_PROMPTS), save_name="incident")
    kw.update(overrides)
    return EditRequest(**kw)


@pytest.mark.slow
def test_incident_acceptance_two_replica_fleet_healthy_vs_chaos(
        programs, tmp_path):
    """THE ISSUE 18 acceptance: a 2-replica in-process fleet shares ONE
    IncidentManager. Healthy run: zero incidents, zero bundles, obs_diff
    self-compare exit 0. Chaos run (replica0 under an ``unavail@`` plan):
    the breaker trips open into exactly ONE debounced bundle, replica1
    keeps serving, the post-mortem HTML names the trigger AND a reservoir
    trace-id exemplar, and the chaos ledger regresses against the healthy
    baseline through obs_diff with exit 1."""
    from videop2p_tpu.obs.tsdb import TimeSeriesStore
    from videop2p_tpu.serve import EditEngine, ProgramSpec
    from videop2p_tpu.serve.faults import FaultPlan

    obs_diff = _load_tool("obs_diff")

    # ---- healthy baseline fleet -----------------------------------------
    h_mgr = IncidentManager(str(tmp_path / "h_inc"),
                            tsdb=TimeSeriesStore())
    healthy = [
        EditEngine(ProgramSpec(**_SPEC_KW), programs=programs,
                   out_dir=str(tmp_path / f"h{i}"), tracing=True,
                   incidents=h_mgr)
        for i in range(2)
    ]
    try:
        for eng in healthy:
            r = eng.result(eng.submit(_request()), wait_s=300.0)
            assert r["status"] == "done", r.get("error")
    finally:
        for eng in healthy:
            eng.close()
    h_mgr.close()
    assert h_mgr.records() == []                      # zero incidents
    assert _bundles(str(tmp_path / "h_inc")) == []    # zero bundles
    healthy_ledger = healthy[0].ledger.path
    assert obs_diff.main(["obs_diff.py", healthy_ledger, healthy_ledger]) == 0

    # ---- chaos fleet: replica0's backend goes away ----------------------
    c_mgr = IncidentManager(str(tmp_path / "c_inc"),
                            tsdb=TimeSeriesStore())
    # dispatch ledger on replica0 (1-based): R1=1 ok (seeds the latency
    # reservoir with a trace-id exemplar) | R2=2,3 unavailable (1 retry
    # exhausted -> error, breaker failure #1) | R3=4,5 unavailable ->
    # breaker failure #2 trips OPEN -> THE incident
    sick = EditEngine(
        ProgramSpec(**_SPEC_KW), programs=programs,
        out_dir=str(tmp_path / "c0"), tracing=True, incidents=c_mgr,
        max_retries=1, retry_base_s=0.01, retry_cap_s=0.05,
        breaker_threshold=2, breaker_open_s=60.0,
        faults=FaultPlan.parse("unavail@2-999"),
    )
    well = EditEngine(ProgramSpec(**_SPEC_KW), programs=programs,
                      out_dir=str(tmp_path / "c1"), tracing=True,
                      incidents=c_mgr)
    try:
        r1 = sick.result(sick.submit(_request()), wait_s=300.0)
        assert r1["status"] == "done", r1.get("error")
        for _ in range(2):
            r = sick.result(sick.submit(_request()), wait_s=300.0)
            assert r["status"] == "error"
        assert sick.breaker.state == "open"
        # the healthy replica keeps serving through its peer's outage
        rw = well.result(well.submit(_request()), wait_s=300.0)
        assert rw["status"] == "done", rw.get("error")
    finally:
        sick.close()
        well.close()
    chaos_ledger = sick.ledger.path
    c_mgr.close()

    # exactly ONE debounced breaker bundle for the whole fleet
    names = _bundles(str(tmp_path / "c_inc"))
    assert len(names) == 1
    recs = c_mgr.records()
    assert len(recs) == 1 and recs[0]["trigger"] == "breaker_open"
    bundle = os.path.join(str(tmp_path / "c_inc"), names[0])
    man = json.load(open(os.path.join(bundle, "manifest.json")))
    assert man["trigger"] == "breaker_open"
    assert "consecutive dispatch failures" in man["detail"]
    # both replicas' fingerprints + targets made it into the one bundle
    assert len(man["fingerprints"]) == 2
    targets = json.load(open(os.path.join(bundle, "targets.json")))
    assert len(targets) == 2
    # the reservoir exemplar NAMES the trace that dispatched successfully
    exemplars = [v for v in man["exemplars"].values()
                 if v.get("p99_trace_id")]
    assert exemplars, man["exemplars"]
    tid = exemplars[0]["p99_trace_id"]
    # the flight ring captured the breaker transition itself
    flight = read_ledger(os.path.join(bundle, "flight.jsonl"))
    assert any(e.get("event") == "breaker" and e.get("state_to") == "open"
               for e in flight)

    # post-mortem HTML: names the trigger and the exemplar trace
    incident_report = _load_tool("incident_report")
    out = incident_report.write_report(bundle)
    html = open(out).read()
    assert "breaker_open" in html
    assert tid in html

    # verdict teeth: chaos regresses vs healthy; each self-compare is clean
    assert obs_diff.main(["obs_diff.py", healthy_ledger, chaos_ledger]) == 1
    assert obs_diff.main(["obs_diff.py", chaos_ledger, chaos_ledger]) == 0
