"""Streaming long-video tests (ISSUE 12): the deterministic window plan +
crossfade assembly, the atomic resumable job manifest (incl. torn-manifest
recovery from sidecars), and the streaming driver's robustness contract —
per-window fault isolation (transient chaos retried, poisoned windows
degrade to recorded passthroughs), checkpoint-then-exit, resume that skips
completed windows with zero re-inversions/compiles, and the SIGKILL
kill-and-resume acceptance with bit-identical final frames.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from videop2p_tpu.stream.manifest import JobManifest
from videop2p_tpu.stream.windows import (
    Window,
    assemble_video,
    blend_weights,
    plan_windows,
    seam_spans,
    synthetic_clip,
    window_key,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- windows ---


def test_plan_windows_geometry_and_validation():
    # marching stride with the final window anchored at total - window
    plan = plan_windows(14, 4, 1)
    assert [(w.start, w.stop) for w in plan] == \
        [(0, 4), (3, 7), (6, 10), (9, 13), (10, 14)]
    assert [w.index for w in plan] == [0, 1, 2, 3, 4]
    assert all(w.frames == 4 for w in plan)
    # the minute-of-footage counts the bench records (window 8, overlap 2)
    assert len(plan_windows(128, 8, 2)) == 21
    assert len(plan_windows(480, 8, 2)) == 80
    # one-window degenerate case
    assert plan_windows(8, 8, 2) == [Window(0, 0, 8)]
    with pytest.raises(ValueError, match="shorter than one window"):
        plan_windows(6, 8, 2)
    with pytest.raises(ValueError, match="overlap"):
        plan_windows(16, 4, 4)
    with pytest.raises(ValueError, match="window"):
        plan_windows(16, 1, 0)


def test_blend_weights_and_assembly_crossfade():
    # the ramp never reaches 0 or 1 inside the overlap
    w = blend_weights(3)
    assert np.allclose(w, [0.25, 0.5, 0.75])
    assert blend_weights(0).shape == (0,)
    plan = plan_windows(6, 4, 2)  # [0,4) + [2,6), overlap [2,4)
    a = np.zeros((4, 2, 2, 3), np.float32)
    b = np.ones((4, 2, 2, 3), np.float32)
    out = assemble_video(plan, {0: a, 1: b}, 6)
    # outside the overlap each window owns its frames; inside, the
    # closed-form crossfade (1-r)*a + r*b with r = (1/3, 2/3)
    assert np.all(out[:2] == 0.0) and np.all(out[4:] == 1.0)
    assert np.allclose(out[2], 1.0 / 3.0) and np.allclose(out[3], 2.0 / 3.0)
    with pytest.raises(ValueError, match="missing window outputs"):
        assemble_video(plan, {0: a}, 6)
    spans = seam_spans(plan)
    assert spans == [{"left": 0, "right": 1, "start": 2, "stop": 4}]


def test_synthetic_clip_deterministic_across_calls():
    a = synthetic_clip(10, 8, seed=3)
    b = synthetic_clip(10, 8, seed=3)
    assert a.shape == (10, 8, 8, 3) and a.dtype == np.uint8
    assert np.array_equal(a, b)
    assert not np.array_equal(a, synthetic_clip(10, 8, seed=4))


def test_window_key_content_addressed():
    frames = synthetic_clip(4, 8, seed=0)
    k = window_key("specfp", frames, ["a", "b"], seed=0)
    assert k == window_key("specfp", frames.copy(), ["a", "b"], seed=0)
    assert k != window_key("specfp2", frames, ["a", "b"], seed=0)
    assert k != window_key("specfp", frames[::-1], ["a", "b"], seed=0)
    assert k != window_key("specfp", frames, ["a", "c"], seed=0)
    assert k != window_key("specfp", frames, ["a", "b"], seed=1)
    assert k != window_key("specfp", frames, ["a", "b"], seed=0,
                           extra={"blend_word": ["a", "b"]})


# ------------------------------------------------------------ manifest ---


def _identity(**over):
    base = {"spec_fingerprint": "fp", "clip_sha": "c", "prompts": ["a", "b"],
            "seed": 0, "request": {}, "total_frames": 6, "window": 4,
            "overlap": 2}
    base.update(over)
    return base


def test_manifest_roundtrip_atomic_and_identity_guard(tmp_path):
    m = JobManifest(str(tmp_path / "job"), _identity())
    frames = np.random.RandomState(0).rand(4, 2, 2, 3).astype(np.float32)
    m.complete_window(0, "k0", frames, status="done", src_err=0.0,
                      store_source="fresh")
    # a fresh manifest over the same dir + identity loads the entry and
    # validates the sidecar bit-for-bit
    m2 = JobManifest(str(tmp_path / "job"), _identity())
    assert m2.load() and list(m2.entries) == [0]
    out = m2.valid_output(0)
    assert out is not None and np.array_equal(out, frames)
    # no stale temp files survive the atomic writes
    leftovers = [f for f in os.listdir(str(tmp_path / "job")) if ".tmp" in f]
    assert leftovers == []
    # a DIFFERENT identity never resumes into this job: the manifest is
    # treated as corrupt-for-this-job and the alien sidecars are rejected
    m3 = JobManifest(str(tmp_path / "job"), _identity(seed=1))
    assert not m3.load()
    assert m3.corrupt_detected == 1 and m3.entries == {}


def test_manifest_torn_file_recovers_from_sidecars(tmp_path):
    job = str(tmp_path / "job")
    m = JobManifest(job, _identity())
    frames = np.random.RandomState(1).rand(4, 2, 2, 3).astype(np.float32)
    m.complete_window(0, "k0", frames, status="done", src_err=0.0)
    m.complete_window(1, "k1", frames + 1, status="passthrough", attempts=3)
    # tear the manifest mid-document — the artifact a kill inside a
    # non-atomic writer would leave
    doc = open(m.path).read()
    with open(m.path, "w") as f:
        f.write(doc[: len(doc) // 2])
    m2 = JobManifest(job, _identity())
    assert m2.load()
    assert m2.corrupt_detected == 1 and m2.recovered_entries == 2
    assert m2.entries[0]["status"] == "done"
    assert m2.entries[1]["status"] == "passthrough"
    assert np.array_equal(m2.valid_output(0), frames)
    # recovery re-persisted a VALID manifest
    m3 = JobManifest(job, _identity())
    assert m3.load() and m3.corrupt_detected == 0


def test_manifest_bad_sidecar_forces_recompute(tmp_path):
    job = str(tmp_path / "job")
    m = JobManifest(job, _identity())
    frames = np.random.RandomState(2).rand(4, 2, 2, 3).astype(np.float32)
    entry = m.complete_window(0, "k0", frames, status="done")
    # corrupt the sidecar bytes: sha mismatch -> entry dropped, recompute
    path = os.path.join(job, entry["output"])
    with open(path, "r+b") as f:
        f.seek(200)
        f.write(b"\xff" * 32)
    m2 = JobManifest(job, _identity())
    assert m2.load()
    assert m2.valid_output(0) is None
    assert 0 not in m2.entries
    # a missing sidecar likewise
    entry = m.complete_window(1, "k1", frames, status="done")
    os.remove(os.path.join(job, entry["output"]))
    m3 = JobManifest(job, _identity())
    m3.load()
    assert m3.valid_output(1) is None


def test_manifest_corrupt_directive_tears_every_save(tmp_path):
    from videop2p_tpu.serve.faults import FaultPlan

    plan = FaultPlan.parse("corrupt:manifest")
    m = JobManifest(str(tmp_path / "job"), _identity(), faults=plan)
    frames = np.zeros((4, 2, 2, 3), np.float32)
    m.complete_window(0, "k0", frames, status="done")
    with pytest.raises(ValueError):
        json.load(open(m.path))
    assert any(i["kind"] == "store_corrupt" for i in plan.injected)
    # ...and the recovery path rebuilds from the (untorn) sidecars
    m2 = JobManifest(str(tmp_path / "job"), _identity())
    assert m2.load()
    assert m2.corrupt_detected == 1 and m2.recovered_entries == 1


# ----------------------------------------------------- streaming driver --

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)
_PROMPTS = ["a rabbit is jumping", "a origami rabbit is jumping"]


def _make_engine(root, name, **over):
    from videop2p_tpu.serve import EditEngine, ProgramSpec

    kw = dict(
        out_dir=os.path.join(str(root), f"{name}_out"),
        persist_dir=os.path.join(str(root), "inv_store"),
        ledger_path=os.path.join(str(root), f"{name}_ledger.jsonl"),
        keep_videos=True,
        max_batch=2,
        max_wait_s=0.05,
    )
    kw.update(over)
    eng = EditEngine(ProgramSpec(**_SPEC_KW), **kw)
    eng.warm(tuple(_PROMPTS), batch_sizes=(2,))
    return eng


@pytest.fixture(scope="module")
def stream_root(tmp_path_factory):
    return tmp_path_factory.mktemp("stream")


@pytest.fixture(scope="module")
def engine(stream_root):
    eng = _make_engine(stream_root, "main")
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def clip():
    return synthetic_clip(5, 16, seed=1)  # 4 windows at window=2, overlap=1


def test_stream_job_end_to_end_ledger_and_full_skip_resume(
    engine, clip, stream_root
):
    """The streaming tentpole acceptance: a 4-window job completes with
    every window edited (src_err == 0.0 throughout), per-window /
    per-seam / job-level evidence lands in the run ledger (extracted into
    the `stream` section SEAM_RULES gate), and rerunning over the same
    job dir SKIPS every window — zero requests, zero new inversions,
    bit-identical final frames."""
    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run
    from videop2p_tpu.stream import run_stream_job

    job = str(stream_root / "job_e2e")
    res = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1,
                         max_inflight=2)
    h = res.health
    assert res.complete and res.video.shape == (5, 16, 16, 3)
    assert h["windows_total"] == 4 and h["windows_done"] == 4
    assert h["windows_passthrough"] == 0 and h["windows_failed"] == 0
    assert h["src_err_max"] == 0.0
    assert h["seams"] == 3 and np.isfinite(h["seam_min_psnr"])
    assert os.path.isfile(os.path.join(job, "final.npy"))
    events = read_ledger(engine.ledger.path)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("event"), []).append(e)
    assert len(by_kind["stream_window"]) >= 4
    assert len(by_kind["stream_seam"]) >= 3
    assert by_kind["stream_health"][-1]["windows_done"] == 4
    rec = extract_run(events)
    assert rec["stream"]["stream"]["seam_min_psnr"] == pytest.approx(
        h["seam_min_psnr"]
    )

    # resume: every window validated off the manifest, nothing recomputed
    before = len(engine._requests)
    res2 = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1)
    assert res2.health["windows_skipped"] == 4
    assert res2.health["windows_done"] == 0
    assert res2.health["fresh_inversions"] == 0
    assert len(engine._requests) == before  # zero engine requests
    assert np.array_equal(res.video, res2.video)


def test_stream_resume_missing_sidecar_rehydrates_zero_compiles(
    engine, clip, stream_root
):
    """The crash-recovery acceptance (disk store hits, zero new
    inversions, zero compiles): lose one window's output sidecar and
    resume on a FRESH engine sharing the disk store — the window
    recomputes through warm programs from the persisted trajectory
    (store_source == "disk"), with no new inversion-from-frames, no
    compile, and a bit-identical final video."""
    from videop2p_tpu.stream import run_stream_job

    job = str(stream_root / "job_rehydrate")
    res = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1)
    assert res.complete
    os.remove(os.path.join(job, "windows", "w0001.npz"))

    eng2 = _make_engine(stream_root, "rehydrate")
    try:
        compiles_before = len(eng2.ledger.compile_seconds)
        res2 = run_stream_job(eng2, clip, _PROMPTS, job_dir=job, overlap=1)
        h = res2.health
        assert h["windows_skipped"] == 3 and h["windows_done"] == 1
        assert h["store_disk_hits"] == 1
        assert h["fresh_inversions"] == 0
        assert h["src_err_max"] == 0.0
        assert len(eng2.ledger.compile_seconds) == compiles_before
        assert np.array_equal(res.video, res2.video)
    finally:
        eng2.close()


def test_stream_chaos_fail2_engine_retry_completes(clip, stream_root):
    """Chaos acceptance: `fail@2` injects a transient dispatch failure
    under window 2 — the engine's RetryPolicy absorbs it and the job
    completes with every window edited, the retry on the books."""
    from videop2p_tpu.serve.faults import FaultPlan
    from videop2p_tpu.stream import run_stream_job

    plan = FaultPlan.parse("fail@2")
    eng = _make_engine(stream_root, "fail2", faults=plan, max_retries=2)
    try:
        res = run_stream_job(eng, clip, _PROMPTS,
                             job_dir=str(stream_root / "job_fail2"),
                             overlap=1, max_inflight=1)
        h = res.health
        assert res.complete and h["windows_done"] == 4
        assert h["windows_passthrough"] == 0
        assert h["src_err_max"] == 0.0
        assert eng.counters["retries"] >= 1
        assert [i["kind"] for i in plan.injected] == ["dispatch_fail"]
    finally:
        eng.close()


def test_stream_poisoned_windows_degrade_to_passthrough(clip, stream_root):
    """A window that keeps failing (an unavailable window past the
    engine's retry budget) degrades to a RECORDED passthrough — the job
    completes instead of dying, the degradations land in stream_health,
    and degrade=False makes the same poisoning fatal."""
    from videop2p_tpu.serve.faults import FaultPlan
    from videop2p_tpu.stream import run_stream_job

    eng = _make_engine(
        stream_root, "poison", faults=FaultPlan.parse("unavail@3-999"),
        max_retries=0, breaker_threshold=1000,
    )
    try:
        res = run_stream_job(eng, clip, _PROMPTS,
                             job_dir=str(stream_root / "job_poison"),
                             overlap=1, max_inflight=1, window_retries=1)
        h = res.health
        assert res.complete  # the job survives its poisoned windows
        assert h["windows_done"] == 2
        assert h["windows_passthrough"] == 2
        assert h["windows_failed"] == 2
        assert h["retries"] >= 2
        entries = res.manifest.entries
        assert sorted(e["status"] for e in entries.values()) == \
            ["done", "done", "passthrough", "passthrough"]
        # passthrough windows carry the SOURCE frames
        pt = [i for i, e in entries.items() if e["status"] == "passthrough"]
        out = res.manifest.valid_output(pt[0])
        w = [win for win in plan_windows(5, 2, 1) if win.index == pt[0]][0]
        assert np.array_equal(
            out, clip[w.start:w.stop].astype(np.float32) / 255.0
        )
        # degrade=False: the same poisoning is fatal
        with pytest.raises(RuntimeError, match="poisoned"):
            run_stream_job(eng, clip, _PROMPTS,
                           job_dir=str(stream_root / "job_poison_fatal"),
                           overlap=1, max_inflight=1, window_retries=0,
                           degrade=False)
    finally:
        eng.close()


def test_stream_manifest_corrupt_chaos_resume_recovers(
    engine, clip, stream_root
):
    """corrupt:manifest chaos tears EVERY manifest write; the next run
    detects the corruption, rebuilds the entries from the sidecars, skips
    every completed window and produces bit-identical output."""
    from videop2p_tpu.serve.faults import FaultPlan
    from videop2p_tpu.stream import run_stream_job

    job = str(stream_root / "job_corrupt")
    res = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1,
                         faults=FaultPlan.parse("corrupt:manifest"))
    assert res.complete
    with pytest.raises(ValueError):
        json.load(open(os.path.join(job, "manifest.json")))
    res2 = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1)
    h = res2.health
    assert h["manifest_corrupt"] == 1
    assert h["manifest_recovered"] == 4
    assert h["windows_skipped"] == 4 and h["fresh_inversions"] == 0
    assert np.array_equal(res.video, res2.video)


def test_stream_checkpoint_then_exit_and_resume(engine, clip, stream_root):
    """SIGTERM contract (in-process half): a stop event raised mid-job
    stops new submissions, what landed stays persisted, the health
    summary says interrupted — and the rerun completes from the
    manifest."""
    from videop2p_tpu.stream import run_stream_job

    job = str(stream_root / "job_interrupt")
    manifest_path = os.path.join(job, "manifest.json")
    stop = threading.Event()

    def watcher():
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline and not stop.is_set():
            try:
                doc = json.load(open(manifest_path))
                if any(w["status"] in ("done", "passthrough")
                       for w in doc["windows"]):
                    stop.set()
                    return
            except (OSError, ValueError):
                pass
            time.sleep(0.005)

    t = threading.Thread(target=watcher, daemon=True)
    t.start()
    res = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1,
                         max_inflight=1, stop_event=stop)
    t.join(timeout=5)
    completed = res.health["windows_done"] + res.health["windows_skipped"]
    assert completed >= 1
    if res.health["interrupted"]:
        assert res.video is None
    # the rerun finishes the job (store hits make it cheap)
    res2 = run_stream_job(engine, clip, _PROMPTS, job_dir=job, overlap=1)
    assert res2.complete
    assert res2.health["windows_skipped"] >= completed


def test_stream_driver_validation(engine, clip, stream_root):
    from videop2p_tpu.stream import run_stream_job

    no_keep = type("E", (), {"keep_videos": False})()
    with pytest.raises(ValueError, match="keep_videos"):
        run_stream_job(no_keep, clip, _PROMPTS,
                       job_dir=str(stream_root / "nokeep"))
    with pytest.raises(ValueError, match="frames must be"):
        run_stream_job(engine, clip[..., 0], _PROMPTS,
                       job_dir=str(stream_root / "badshape"))


def test_obs_diff_gates_seam_quality_drop(tmp_path):
    """The acceptance teeth: a healthy stream ledger self-compares exit 0
    through tools/obs_diff.py; an injected seam-quality drop (and a new
    passthrough degradation) exits 1 with machine-readable SEAM_RULES
    verdicts."""
    import importlib.util

    from videop2p_tpu.obs import RunLedger
    from videop2p_tpu.stream.driver import STREAM_HEALTH_FIELDS

    spec = importlib.util.spec_from_file_location(
        "obs_diff_under_stream_test",
        os.path.join(_REPO, "tools", "obs_diff.py"),
    )
    obs_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_diff)

    def write_ledger(name, **over):
        health = {k: 0 for k in STREAM_HEALTH_FIELDS}
        health.update(windows_total=6, windows_done=6, seams=5,
                      seam_min_psnr=24.0, seam_mean_psnr=30.0,
                      source_seam_min_psnr=26.0, src_err_max=0.0)
        health.update(over)
        path = str(tmp_path / name)
        with RunLedger(path) as led:
            led.event("stream_health", **health)
        return path

    healthy = write_ledger("healthy.jsonl")
    assert obs_diff.main(["obs_diff.py", healthy, healthy]) == 0
    degraded = write_ledger("degraded.jsonl", seam_min_psnr=12.0,
                            seam_mean_psnr=15.0, windows_done=5,
                            windows_passthrough=1, windows_failed=1)
    assert obs_diff.main(["obs_diff.py", healthy, degraded]) == 1
    # the drop direction matters: a seam IMPROVING never regresses
    better = write_ledger("better.jsonl", seam_min_psnr=40.0,
                          seam_mean_psnr=45.0)
    assert obs_diff.main(["obs_diff.py", healthy, better]) == 0


# ------------------------------------------------ kill-and-resume e2e ----


@pytest.mark.slow
def test_stream_sigkill_resume_bit_identical(tmp_path):
    """THE chaos acceptance (ISSUE 12): SIGKILL the streaming driver
    mid-window; the resumed job skips every completed window (no
    re-inversions of them) and the final frames are BIT-IDENTICAL to an
    uninterrupted run's."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))

    def drive(job_dir, ledger):
        return [sys.executable, os.path.join(_REPO, "tools", "stream_drive.py"),
                "--frames", "7", "--video_len", "2", "--overlap", "1",
                "--steps", "2", "--width", "16",
                "--job_dir", job_dir, "--ledger", ledger]

    kill_job = str(tmp_path / "kill_job")
    proc = subprocess.Popen(
        drive(kill_job, str(tmp_path / "led1.jsonl")), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    manifest = os.path.join(kill_job, "manifest.json")
    deadline = time.perf_counter() + 540.0
    killed = False
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            break
        try:
            doc = json.load(open(manifest))
            done = sum(1 for w in doc["windows"] if w["status"] == "done")
        except (OSError, ValueError):
            done = 0
        if done >= 2:
            proc.kill()  # SIGKILL — no cleanup, no atexit, nothing
            killed = True
            break
        time.sleep(0.1)
    proc.wait(timeout=60)
    assert killed, "driver finished before the kill window — slow the clip down"
    persisted = json.load(open(manifest))
    persisted_done = sum(1 for w in persisted["windows"]
                         if w["status"] == "done")
    assert persisted_done >= 2  # the manifest survived the SIGKILL intact

    # resume over the same job dir
    out = subprocess.run(drive(kill_job, str(tmp_path / "led2.jsonl")),
                         env=env, capture_output=True, text=True,
                         timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    health = json.loads(out.stdout.strip().splitlines()[-1])["stream_health"]
    assert health["windows_skipped"] >= persisted_done
    # zero re-inversions of completed windows: every recomputed window is
    # accounted for by the remainder, and any whose trajectory the killed
    # run already wrote through is a DISK hit, not a re-inversion
    recomputed = health["windows_total"] - health["windows_skipped"]
    assert health["fresh_inversions"] <= recomputed
    assert (health["fresh_inversions"] + health["store_disk_hits"]
            + health["store_memory_hits"]) == recomputed
    assert health["src_err_max"] == 0.0

    # uninterrupted reference run -> bit-identical final frames
    ref_job = str(tmp_path / "ref_job")
    out = subprocess.run(drive(ref_job, str(tmp_path / "led3.jsonl")),
                         env=env, capture_output=True, text=True,
                         timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    resumed = np.load(os.path.join(kill_job, "final.npy"))
    reference = np.load(os.path.join(ref_job, "final.npy"))
    assert np.array_equal(resumed, reference)
