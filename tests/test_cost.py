"""Cost & capacity plane tests (ISSUE 19): the fair-share CostModel
(attribution + conservation by construction, store-hit savings pricing),
the utilization/headroom economics in the signal engine, the measured
per-tenant device-seconds consistency between the engine and fleet
planes, the obs_diff COST_RULES teeth, the showback report, and the
tools/ CLI contract smoke (every entry point helps with exit 0 and
fails missing input with exit 2).
"""

import importlib.util
import inspect
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TOOLS = sorted(
    f[:-3] for f in os.listdir(os.path.join(_REPO, "tools"))
    if f.endswith(".py")
)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_cost_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------- CostModel ----


def test_price_dispatch_fair_share_and_conservation():
    """The attribution core: a dispatch splits evenly over padded slots,
    real slots charge requests and pad slots charge padding waste, the
    static program facts scale per slot — and the capacity books conserve
    BY CONSTRUCTION (attributed + padding = busy, residual 0)."""
    from videop2p_tpu.obs.cost import CostModel

    model = CostModel()
    model.observe_program("serve_edit_b4", {"flops": 800.0,
                                            "peak_hbm_bytes": 100.0,
                                            "argument_bytes": 64.0})
    slot = model.price_dispatch(2.0, real=3, padded=4,
                                program="serve_edit_b4",
                                singleton="serve_edit")
    assert slot["program"] == "serve_edit"
    assert slot["device_seconds"] == pytest.approx(0.5)   # 2.0 / 4 slots
    assert slot["flops"] == pytest.approx(200.0)          # 800 / 4
    assert slot["hbm_byte_seconds"] == pytest.approx(50.0)  # 100*2/4
    assert slot["padding_share"] == pytest.approx(0.25)
    cap = model.capacity(10.0)
    assert cap["busy_seconds"] == pytest.approx(2.0)
    assert cap["attributed_seconds"] == pytest.approx(1.5)
    assert cap["padding_seconds"] == pytest.approx(0.5)
    assert cap["idle_seconds"] == pytest.approx(8.0)
    assert cap["busy_fraction"] == pytest.approx(0.2)
    assert cap["padding_waste"] == pytest.approx(0.25)    # 0.5 / 2.0 busy
    assert cap["occupancy"] == pytest.approx(0.75)
    assert cap["conservation_residual_s"] == 0.0
    # singleton fallback: no static under the batched label -> the
    # singleton's statics already ARE one slot's (divide by 1)
    m2 = CostModel()
    m2.observe_program("serve_edit", {"flops": 200.0})
    s2 = m2.price_dispatch(1.0, real=1, padded=2,
                           program="serve_edit_b2", singleton="serve_edit")
    assert s2["flops"] == pytest.approx(200.0)
    assert s2["device_seconds"] == pytest.approx(0.5)
    # degenerate inputs clamp instead of raising (obs never takes the
    # engine down): padded 0 -> 1 slot, negative seconds -> 0
    s3 = CostModel().price_dispatch(-1.0, real=0, padded=0)
    assert s3["device_seconds"] == 0.0 and s3["padding_share"] == 1.0
    # junk static records are ignored, never raised on
    m3 = CostModel()
    m3.observe_program("x", None)
    m3.observe_program("x", {"flops": "bogus"})
    assert m3.static_cost("x") is None


def test_savings_measured_mean_then_static_fallback():
    """A store hit's avoided spend: the measured mean fresh-inversion
    seconds when any ran in-process, else the static serve_invert flops
    priced at the observed dispatch throughput, else 0."""
    from videop2p_tpu.obs.cost import CostModel

    model = CostModel()
    assert model.savings() == {"saved_device_seconds": 0.0,
                               "saved_flops": 0.0}
    model.note_fresh_inversion(2.0)
    model.note_fresh_inversion(4.0)
    assert model.savings()["saved_device_seconds"] == pytest.approx(3.0)
    assert model.savings()["saved_flops"] == 0.0   # no analysis landed
    model.observe_program("serve_invert", {"flops": 1000.0})
    assert model.savings()["saved_flops"] == 1000.0
    # static fallback: no measured inversion but a throughput observation
    m2 = CostModel()
    m2.observe_program("serve_invert", {"flops": 1000.0})
    m2.observe_program("serve_edit", {"flops": 500.0})
    m2.price_dispatch(1.0, real=1, padded=1, program="serve_edit")
    # throughput = 500 flops / 1 busy second -> 1000 flops cost 2 s
    assert m2.savings()["saved_device_seconds"] == pytest.approx(2.0)


def test_account_request_program_split_and_attribution_records():
    """Terminal accounting: the tenant lane gets the whole cost vector,
    an optional program split books the dispatch slot and the fresh
    inversion under their own labels, and attribution_records emits the
    engine roll-up first then tenants/programs sorted, schema-exact."""
    from videop2p_tpu.obs.cost import COST_ATTRIBUTION_FIELDS, CostModel

    model = CostModel()
    edit_part = {"device_seconds": 0.5, "flops": 100.0,
                 "hbm_byte_seconds": 5.0}
    inv_part = {"device_seconds": 1.5, "flops": 900.0,
                "hbm_byte_seconds": 9.0}
    cost = {"program": "serve_edit", "device_seconds": 2.0,
            "flops": 1000.0, "hbm_byte_seconds": 14.0,
            "queue_seconds": 0.25, "saved_device_seconds": 0.0,
            "saved_flops": 0.0}
    model.account_request(tenant="acme", cost=cost,
                          programs=[("serve_edit", edit_part),
                                    ("serve_invert", inv_part)])
    hit = dict(cost, device_seconds=0.5, flops=100.0, hbm_byte_seconds=5.0,
               saved_device_seconds=1.5, saved_flops=900.0)
    model.account_request(tenant="acme", cost=hit, store_hit=True)
    rows = model.attribution_records(10.0)
    assert rows[0]["scope"] == "engine" and rows[0]["name"] == "serve"
    by = {(r["scope"], r["name"]): r for r in rows[1:]}
    for r in rows[1:]:
        assert set(r) == set(COST_ATTRIBUTION_FIELDS)
    acme = by[("tenant", "acme")]
    assert acme["requests"] == 2.0 and acme["store_hits"] == 1.0
    assert acme["device_seconds"] == pytest.approx(2.5)
    assert acme["saved_device_seconds"] == pytest.approx(1.5)
    assert acme["cost_per_request_s"] == pytest.approx(1.25)
    # the split: serve_invert carries ONLY the inversion part, and the
    # program parts sum back to the tenant total (nothing double-booked)
    assert by[("program", "serve_invert")]["device_seconds"] == \
        pytest.approx(1.5)
    assert by[("program", "serve_edit")]["device_seconds"] == \
        pytest.approx(1.0)   # 0.5 cold slot + 0.5 hit slot
    prog_total = sum(r["device_seconds"] for (s, _), r in by.items()
                     if s == "program")
    assert prog_total == pytest.approx(acme["device_seconds"])


# --------------------------------------------- signals economics ---------


def _idle_fleet_tsdb(replicas=("replica0", "replica1"), *, capacity=True,
                     busy=0.2, cpr=0.2, waste=0.1):
    """An idle 2-replica fleet trace; optionally with the scraped
    cost-plane gauges riding along."""
    from videop2p_tpu.obs.signals import (
        S_BUSY_FRACTION,
        S_COST_PER_REQUEST,
        S_IN_FLIGHT,
        S_PADDING_WASTE,
        S_QUEUE_DEPTH,
        S_UP,
    )
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    ts = TimeSeriesStore()
    for i in range(10):
        t = float(i)
        for r in replicas:
            lab = {"replica": r}
            ts.add(S_UP, t, 1.0, lab)
            ts.add(S_QUEUE_DEPTH, t, 0.0, lab)
            ts.add(S_IN_FLIGHT, t, 0.0, lab)
            if capacity:
                ts.add(S_BUSY_FRACTION, t, busy, lab)
                ts.add(S_PADDING_WASTE, t, waste, lab)
                ts.add(S_COST_PER_REQUEST, t, cpr, lab)
    return ts


def test_capacity_signals_price_the_advice():
    """ISSUE 19: with the cost plane scraped, an idle-fleet shrink cites
    shrink-is-cheap with the idle fraction and cost-per-request; the
    record carries utilization/headroom economics; WITHOUT the cost
    plane every economic field is None and the reasons are exactly the
    pre-cost-plane ones."""
    from videop2p_tpu.obs.signals import SignalEngine

    eng = SignalEngine(_idle_fleet_tsdb(), window_scale=0.01)
    rec = eng.evaluate(9.0)
    assert rec["scale_advice"] == "shrink"
    assert rec["utilization"] == pytest.approx(0.2)
    assert rec["idle_fraction"] == pytest.approx(0.8)
    assert rec["padding_waste"] == pytest.approx(0.1)
    assert rec["cost_per_request_s"] == pytest.approx(0.2)
    # 2 up replicas at 0.2 s/request -> 10 requests/s of capacity, all
    # of it headroom (no demand)
    assert rec["capacity_rps"] == pytest.approx(10.0)
    assert rec["headroom_rps"] == pytest.approx(10.0)
    assert rec["utilization_slope"] == pytest.approx(0.0)
    assert rec["utilization_forecast"] == pytest.approx(0.2)
    assert any("shrink-is-cheap" in r and "cost_per_request" in r
               for r in rec["reasons"])
    # absent cost plane: identical advice, all-None economics, and NO
    # economic reason — pre-ISSUE-19 fleets evaluate exactly as before
    bare = SignalEngine(_idle_fleet_tsdb(capacity=False),
                        window_scale=0.01)
    rec2 = bare.evaluate(9.0)
    assert rec2["scale_advice"] == "shrink"
    for k in ("utilization", "idle_fraction", "padding_waste",
              "cost_per_request_s", "capacity_rps", "headroom_rps",
              "utilization_slope", "utilization_forecast"):
        assert rec2[k] is None, k
    assert not any("economics" in r or "shrink-is-cheap" in r
                   for r in rec2["reasons"])


def test_tenant_device_seconds_measured_plane_agrees_with_engine():
    """ISSUE 19 satellite: the fleet's per-tenant device-seconds are the
    MEASURED cost-plane counter when the collector meters it — and on a
    deterministic trace they agree exactly with the engine-side
    CostModel aggregate the counter was scraped from; without the
    series the lane falls back to the served x dispatch-p50 estimate."""
    from videop2p_tpu.obs.cost import CostModel
    from videop2p_tpu.obs.signals import (
        S_DISPATCH_P50,
        S_TENANT,
        S_UP,
        SignalEngine,
    )
    from videop2p_tpu.obs.tsdb import TimeSeriesStore

    # engine plane: tenant A finishes one 0.3 s request per tick
    model = CostModel()
    cum = []
    for _ in range(11):
        model.account_request(tenant="A",
                              cost={"program": "serve_edit",
                                    "device_seconds": 0.3})
        cum.append(model.tenant_costs()["A"]["device_seconds"])
    # fleet plane: the scraped counter is exactly that aggregate
    ts = TimeSeriesStore()
    lab = {"replica": "replica0"}
    for i in range(11):
        t = float(i)
        ts.add(S_UP, t, 1.0, lab)
        ts.add(S_DISPATCH_P50, t, 0.5, lab)
        ts.add(S_TENANT, t, float(i + 1),
               {**lab, "tenant": "A", "field": "done"})
        ts.add(S_TENANT, t, cum[i],
               {**lab, "tenant": "A", "field": "device_seconds"})
    eng = SignalEngine(ts, window_scale=0.01)
    lane = eng.evaluate(10.0)["tenants"]["A"]
    # measured: the counter's increase over the window == the engine-side
    # spend over the same requests (NOT served x p50 = 10 x 0.5 = 5.0)
    assert lane["device_seconds"] == pytest.approx(cum[-1] - cum[0])
    assert lane["device_seconds"] == pytest.approx(3.0)
    # fallback: same trace without the measured series -> the estimate
    ts2 = TimeSeriesStore()
    for i in range(11):
        t = float(i)
        ts2.add(S_UP, t, 1.0, lab)
        ts2.add(S_DISPATCH_P50, t, 0.5, lab)
        ts2.add(S_TENANT, t, float(i + 1),
                {**lab, "tenant": "A", "field": "done"})
    lane2 = SignalEngine(ts2, window_scale=0.01).evaluate(
        10.0)["tenants"]["A"]
    assert lane2["device_seconds"] == pytest.approx(10 * 0.5)


# --------------------------------------------- obs_diff COST_RULES ------


def _cost_ledger(path, *, cpr=0.2, busy=0.5, padding=0.1, idle=0.45):
    """A minimal serve-shaped ledger whose cost_attribution rows obs_diff
    extracts into the `cost` section COST_RULES gate."""
    from videop2p_tpu.obs import RunLedger

    with RunLedger(path) as led:
        led.event("cost_attribution", label="serve", scope="engine",
                  name="serve", uptime_s=10.0, busy_seconds=busy * 10,
                  attributed_seconds=busy * 10 * (1 - padding),
                  padding_seconds=busy * 10 * padding,
                  idle_seconds=idle * 10, busy_fraction=busy,
                  idle_fraction=idle, padding_waste=padding,
                  occupancy=1.0 - padding, dispatches=10, real_slots=18,
                  padded_slots=20, requests_costed=20.0,
                  cost_per_request_s=cpr, conservation_residual_s=0.0)
        led.event("cost_attribution", label="serve", scope="tenant",
                  name="A", requests=20.0, store_hits=10.0,
                  device_seconds=cpr * 20, flops=100.0,
                  hbm_byte_seconds=1.0, queue_seconds=0.5,
                  saved_device_seconds=1.0, saved_flops=50.0,
                  cost_per_request_s=cpr)
    return path


def test_obs_diff_cost_rules_teeth(tmp_path, capsys):
    """THE cost gate: self-compare exits 0; cost-per-request +50% or the
    busy fraction collapsing (utilization direction=decrease) or padding
    waste doubling all regress with exit 1 and a machine-readable verdict
    naming the metric; the improvement direction stays clean."""
    healthy = _cost_ledger(str(tmp_path / "healthy.jsonl"))
    pricier = _cost_ledger(str(tmp_path / "pricier.jsonl"), cpr=0.3)
    idler = _cost_ledger(str(tmp_path / "idler.jsonl"), busy=0.2,
                         idle=0.75)
    wasteful = _cost_ledger(str(tmp_path / "wasteful.jsonl"), padding=0.3)
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", healthy, healthy]) == 0
    capsys.readouterr()
    assert obs_diff.main(["obs_diff.py", healthy, pricier]) == 1
    assert "cost_per_request_s" in capsys.readouterr().out
    assert obs_diff.main(["obs_diff.py", healthy, idler]) == 1
    out = capsys.readouterr().out
    assert "busy_fraction" in out or "idle_fraction" in out
    assert obs_diff.main(["obs_diff.py", healthy, wasteful]) == 1
    assert "padding_waste" in capsys.readouterr().out
    # teeth point the economic way: getting cheaper is never a regression
    assert obs_diff.main(["obs_diff.py", pricier, healthy]) == 0


# ------------------------------------------------- showback report -------


def _showback_events():
    return [
        {"event": "run_start", "run_id": "r1", "t": 0.0},
        {"event": "program_analysis", "program": "serve_edit",
         "flops": 100.0, "argument_bytes": 8.0},
        {"event": "program_analysis", "program": "serve_invert",
         "flops": 900.0, "argument_bytes": 8.0},
        {"event": "cost_attribution", "label": "serve", "scope": "engine",
         "name": "serve", "uptime_s": 10.0, "busy_seconds": 4.0,
         "attributed_seconds": 3.5, "padding_seconds": 0.5,
         "idle_seconds": 6.0, "busy_fraction": 0.4, "idle_fraction": 0.6,
         "padding_waste": 0.125, "occupancy": 0.875, "dispatches": 4,
         "real_slots": 7, "padded_slots": 8, "requests_costed": 4.0,
         "cost_per_request_s": 0.875, "conservation_residual_s": 0.0},
        {"event": "cost_attribution", "label": "serve", "scope": "tenant",
         "name": "acme", "requests": 3.0, "store_hits": 2.0,
         "device_seconds": 2.625, "flops": 300.0, "hbm_byte_seconds": 2.0,
         "queue_seconds": 0.25, "saved_device_seconds": 3.125,
         "saved_flops": 1800.0, "cost_per_request_s": 0.875},
        {"event": "cost_attribution", "label": "serve", "scope": "tenant",
         "name": "default", "requests": 1.0, "store_hits": 0.0,
         "device_seconds": 0.875, "flops": 100.0, "hbm_byte_seconds": 1.0,
         "queue_seconds": 0.1, "saved_device_seconds": 0.0,
         "saved_flops": 0.0, "cost_per_request_s": 0.875},
        {"event": "cost_attribution", "label": "serve", "scope": "program",
         "name": "serve_edit", "requests": 4.0, "store_hits": 2.0,
         "device_seconds": 2.0, "flops": 400.0, "hbm_byte_seconds": 3.0,
         "queue_seconds": 0.35, "saved_device_seconds": 3.125,
         "saved_flops": 1800.0, "cost_per_request_s": 0.5},
    ]


def test_cost_report_renders_chargeback_and_savings(tmp_path):
    """The showback page: conservation sentence and waste bars for the
    engine scope, the per-tenant chargeback table sorted by spend with
    share-%% and the CACHE SAVINGS column (the amortization pin's human
    face), the per-program achieved-vs-static join — and a pre-cost-plane
    ledger renders the empty state, exit 0 end to end."""
    from videop2p_tpu.obs import RunLedger

    cost_report = _load_tool("cost_report")
    text = cost_report.render_report(_showback_events())
    assert text.startswith("<!doctype html>")
    assert "conservation" in text and "never" in text
    assert "padding waste" in text and "idle" in text
    assert "Per-tenant chargeback" in text
    # acme first (biggest spender), with its share of the attributed
    # total and the avoided device-seconds a store hit didn't re-burn
    assert text.index("acme") < text.index("default")
    assert "75.0%" in text          # 2.625 of 3.5 attributed
    assert "3.125" in text          # saved_device_seconds rendered
    assert "Per-program achieved vs static" in text
    assert "1.00x" in text          # 400 flops / 4 requests vs static 100
    # ledger -> file round-trip through main()
    path = str(tmp_path / "serve.jsonl")
    with RunLedger(path) as led:
        for e in _showback_events():
            if e["event"] != "run_start":
                led.event(e.pop("event"), **e)
    out = str(tmp_path / "showback.html")
    assert cost_report.main(["cost_report.py", path, "--out", out]) == 0
    assert "chargeback" in open(out).read()
    # pre-cost-plane ledgers: empty state, still exit 0
    empty = str(tmp_path / "old.jsonl")
    with RunLedger(empty) as led:
        led.event("serve_health", requests=1)
    assert cost_report.main(["cost_report.py", empty]) == 0
    assert "no cost_attribution" in open(
        str(tmp_path / "old_cost.html")).read()


# ------------------------------------------- tools CLI contract ----------


def test_tools_inventory_is_complete():
    """The smoke below covers every entry point: pin the inventory so a
    new tool must join the contract."""
    assert len(_TOOLS) == 19
    assert {"cost_report", "fleet_dash", "incident_report",
            "ledger_summary", "obs_diff", "probe_report",
            "serve_loadgen"} <= set(_TOOLS)


@pytest.mark.parametrize("tool", _TOOLS)
def test_tool_help_contract(tool, monkeypatch, capsys):
    """ISSUE 19 satellite: EVERY tools/*.py entry point answers --help
    with exit 0 and usage text — none of them starts a benchmark, opens
    a ledger, or crashes on the help path."""
    mod = _load_tool(tool)
    monkeypatch.setattr(sys, "argv", [f"{tool}.py", "--help"])
    sig = inspect.signature(mod.main)
    required = [p for p in sig.parameters.values()
                if p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    try:
        rc = mod.main(sys.argv) if required else mod.main()
    except SystemExit as e:   # argparse's --help path
        rc = e.code
    assert rc in (0, None)
    out = capsys.readouterr().out
    assert out.strip(), f"{tool} --help printed nothing"


@pytest.mark.parametrize("tool,argv_tail", [
    ("cost_report", ["nope.jsonl"]),
    ("edit_report", ["nope.jsonl"]),
    ("fleet_dash", ["nope.jsonl"]),
    ("incident_report", ["nope.bundle"]),
    ("ledger_summary", ["nope.jsonl"]),
    ("obs_diff", ["nope.jsonl", "nope.jsonl"]),
    ("probe_report", ["nope.jsonl"]),
    ("trace_view", ["nope.jsonl"]),
    ("xplane_top_ops", ["nope_trace_dir"]),
])
def test_tool_missing_input_exits_2(tool, argv_tail, tmp_path,
                                    monkeypatch, capsys):
    """ISSUE 19 satellite: every ledger/trace-consuming tool fails a
    missing input with exit code 2 and a diagnostic (never a traceback,
    never a zero)."""
    mod = _load_tool(tool)
    argv = [f"{tool}.py"] + [str(tmp_path / a) for a in argv_tail]
    monkeypatch.setattr(sys, "argv", argv)
    sig = inspect.signature(mod.main)
    required = [p for p in sig.parameters.values()
                if p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    try:
        rc = mod.main(argv) if required else mod.main()
    except SystemExit as e:
        rc = e.code
    assert rc == 2
    assert capsys.readouterr().err.strip(), f"{tool} failed silently"
