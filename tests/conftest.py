"""Test configuration: force a virtual 8-device CPU platform.

This is the standard way to exercise pjit/shard_map sharding without a TPU pod
(SURVEY §4): tests that need a mesh get 8 host devices; everything else just
runs on CPU for speed and determinism.

The XLA flag must be set BEFORE jax import; the platform override must happen
AFTER — this image's sitecustomize registers the `axon` TPU plugin in every
interpreter and hard-sets ``jax_platforms="axon,cpu"`` via jax.config, which
wins over the JAX_PLATFORMS env var, so only a later ``jax.config.update``
actually selects the CPU backend.

Compile-heavy tests dominate the suite's wall-clock; a persistent XLA
compilation cache makes every run after the first fast. Tests relying on
tight cross-run numerics opt into matmul precision locally via
``jax.default_matmul_precision("highest")`` instead of a global override
(which made every compile slower).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("VIDEOP2P_TEST_CACHE", "/root/.cache/videop2p_jax_test_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
