"""Test configuration: force a virtual 8-device CPU platform BEFORE jax import.

This is the standard way to exercise pjit/shard_map sharding without a TPU pod
(SURVEY §4): tests that need a mesh get 8 host devices; everything else just
runs on CPU for speed and determinism.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")
