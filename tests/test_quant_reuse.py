"""Quantized inference + cross-step feature reuse tests (ISSUE 15).

Two per-UNet-call cost levers, pinned at their contracts:

  * weight quantization (models/quant.py + models/convert.py
    ``quantize_unet_params``) — int8 storage with per-output-channel
    symmetric scales, round-trip error bounded by half a quantization
    step per channel, the first/last-layer precision islands untouched,
    and ``mode="off"`` the identity (the bit-exact pin);
  * cross-step deep-feature reuse (pipelines/reuse.py + the
    ``deep_mode`` seam in models/unet.py) — schedule grammar, the
    off-path byte-identical, ``uniform:K`` ONE compiled program (the
    schedule is a static per-step boolean in the scan's xs, never a
    second trace), and the cached source replay EXACT under both knobs
    (stream 0 is replayed from the captured trajectory, not recomputed —
    eps precision cannot touch it);
  * the quality observatory gate: quant/reuse quality metrics ride the
    same ``quality`` ledger event QUALITY_RULES diff, so a PSNR drop
    regresses a run exactly like a perf metric growing.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.models.quant import (
    QUANT_MODES,
    QuantizedTensor,
    SKIP_MODULES,
    dequantize_tree,
    fake_quant_act,
    has_quantized,
    quant_weight_dtype,
    quantize_tree,
    quantize_weight,
    validate_quant_mode,
)
from videop2p_tpu.pipelines.reuse import (
    parse_reuse_schedule,
    reuse_label,
    reuse_skip_fraction,
    validate_reuse_schedule,
)

STEPS = 5
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C)


# ------------------------------------------------------ weight quant --


def test_quantize_weight_roundtrip_error_bound():
    """Symmetric per-output-channel int8: the dequantized kernel is within
    half a quantization step of the original IN EVERY CHANNEL (scale =
    absmax/127, rounding error <= scale/2), and the full symmetric range
    is used without the asymmetric -128 code."""
    key = jax.random.key(0)
    # per-channel magnitudes spanning 3 orders so one shared scale would fail
    w = jax.random.normal(key, (3, 3, 16, 8)) * jnp.logspace(
        -2, 1, 8
    )[None, None, None, :]
    q = quantize_weight(w)
    assert isinstance(q, QuantizedTensor)
    assert q.qvalue.dtype == jnp.int8 and q.qvalue.shape == w.shape
    assert q.scale.shape == (1, 1, 1, 8)
    assert int(jnp.min(q.qvalue)) >= -127  # symmetric: -128 never emitted
    err = jnp.abs(q.dequantize() - w)
    bound = q.scale * 0.5 * (1 + 1e-6)
    assert bool(jnp.all(err <= bound))
    # the quantization is not a no-op (real rounding happened)
    assert float(jnp.max(err)) > 0.0


def test_quantize_weight_scale_is_per_channel():
    """A channel's scale is ITS absmax/127 — a hot channel cannot inflate
    a quiet channel's quantization step (the point of per-output-channel
    over per-tensor)."""
    w = jnp.stack([jnp.linspace(-1.0, 1.0, 8),
                   jnp.linspace(-100.0, 100.0, 8)], axis=-1)  # (8, 2)
    q = quantize_weight(w)
    np.testing.assert_allclose(
        np.asarray(q.scale).ravel(), [1.0 / 127.0, 100.0 / 127.0], rtol=1e-6
    )


def test_fake_quant_act_bounded_and_type_preserving():
    x = jax.random.normal(jax.random.key(1), (4, 7)).astype(jnp.bfloat16)
    y = fake_quant_act(x)
    assert y.dtype == x.dtype
    xf = x.astype(jnp.float32)
    step = float(jnp.max(jnp.abs(xf))) / 127.0
    assert float(jnp.max(jnp.abs(y.astype(jnp.float32) - xf))) <= step
    # non-float inputs (timestep indices riding a tree) pass through
    ints = jnp.arange(4)
    assert fake_quant_act(ints) is ints


def test_quantize_tree_skips_precision_islands():
    """Only matmul kernels outside SKIP_MODULES quantize; biases, norms
    and the conv_in/conv_out/time_embedding islands stay full precision
    (Q-Diffusion first/last-layer practice)."""
    tree = {
        "conv_in": {"kernel": jnp.ones((3, 3, 4, 8))},
        "time_embedding": {"dense": {"kernel": jnp.ones((8, 32))}},
        "down_blocks_0": {
            "to_q": {"kernel": jnp.ones((8, 8)), "bias": jnp.zeros((8,))},
            "norm": {"scale": jnp.ones((8,))},
        },
        "conv_out": {"kernel": jnp.ones((3, 3, 8, 4))},
    }
    qt = quantize_tree(tree)
    assert isinstance(qt["down_blocks_0"]["to_q"]["kernel"], QuantizedTensor)
    assert not isinstance(qt["conv_in"]["kernel"], QuantizedTensor)
    assert not isinstance(qt["conv_out"]["kernel"], QuantizedTensor)
    assert not isinstance(qt["time_embedding"]["dense"]["kernel"],
                          QuantizedTensor)
    assert not isinstance(qt["down_blocks_0"]["to_q"]["bias"],
                          QuantizedTensor)
    assert has_quantized(qt) and not has_quantized(tree)
    back = dequantize_tree(qt)
    assert not has_quantized(back)
    np.testing.assert_allclose(
        np.asarray(back["down_blocks_0"]["to_q"]["kernel"]),
        np.asarray(tree["down_blocks_0"]["to_q"]["kernel"]), atol=1e-2
    )


def test_quantize_unet_params_modes_and_wrapper():
    from videop2p_tpu.models.convert import quantize_unet_params

    tree = {"params": {"blk": {"attn": {"kernel": jnp.ones((8, 8))}}},
            "stats": {"x": jnp.zeros(())}}
    # off is the identity — the pinned bit-exact path
    assert quantize_unet_params(tree, mode="off") is tree
    q = quantize_unet_params(tree, mode="w8")
    assert isinstance(q["params"]["blk"]["attn"]["kernel"], QuantizedTensor)
    assert q["stats"] is tree["stats"]  # sibling collections untouched
    # bare inner tree works too
    assert has_quantized(quantize_unet_params(tree["params"], mode="w8a8"))
    with pytest.raises(ValueError, match="quant_mode"):
        quantize_unet_params(tree, mode="int4")
    assert validate_quant_mode(None) == "off"
    assert set(QUANT_MODES) == {"off", "w8", "w8a8"}
    assert quant_weight_dtype() == jnp.int8
    assert "conv_in" in SKIP_MODULES and "conv_out" in SKIP_MODULES


# --------------------------------------------------- reuse schedules --


def test_parse_reuse_schedule_grammar():
    assert parse_reuse_schedule(None, 5) is None
    assert parse_reuse_schedule("off", 5) is None
    assert parse_reuse_schedule("uniform:2", 5) == (
        True, False, True, False, True)
    assert parse_reuse_schedule("uniform:1", 3) == (True, True, True)
    assert parse_reuse_schedule("custom:0,3", 5) == (
        True, False, False, True, False)
    assert validate_reuse_schedule("", 5) == "off"
    assert validate_reuse_schedule("uniform:4", 5) == "uniform:4"


def test_parse_reuse_schedule_rejects_malformed():
    for bad, msg in [
        ("uniform:x", "integer K"),
        ("uniform:0", ">= 1"),
        ("custom:", "at least one"),
        ("custom:1,3", "start at 0"),
        ("custom:0,2,2", "strictly increasing"),
        ("custom:0,9", "outside"),
        ("every_other", "not 'off'"),
    ]:
        with pytest.raises(ValueError, match=msg):
            parse_reuse_schedule(bad, 5)
    with pytest.raises(ValueError, match="num_steps"):
        parse_reuse_schedule("uniform:2", 0)


def test_reuse_skip_fraction_and_label():
    assert reuse_skip_fraction(None) == 0.0
    assert reuse_skip_fraction(parse_reuse_schedule("uniform:2", 10)) == 0.5
    assert reuse_skip_fraction(parse_reuse_schedule("uniform:5", 10)) == 0.8
    assert reuse_label("off") == "" and reuse_label(None) == ""
    assert reuse_label("uniform:2") == "uniform2"
    assert reuse_label("custom:0,3") == "custom0_3"


# ------------------------------------------- tiny-model end-to-end --


@pytest.fixture(scope="module")
def sched():
    from videop2p_tpu.core import DDIMScheduler

    return DDIMScheduler.create_sd()


@pytest.fixture(scope="module")
def tiny():
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), SHAPE)
    text = jax.random.normal(jax.random.key(1),
                             (1, 77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample,
                                 jnp.asarray(10), text)
    return make_unet_fn(model), params, cfg


@pytest.fixture(scope="module")
def cached_edit(sched, tiny):
    """One captured inversion shared by the knob tests, plus the
    full-precision reuse-off edit output they all score against."""
    from videop2p_tpu.pipelines import ddim_inversion_captured, edit_sample

    fn, params, cfg = tiny
    x0 = 0.5 * jax.random.normal(jax.random.key(3), SHAPE)
    cond = jax.random.normal(jax.random.key(4),
                             (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    traj, cached = jax.jit(
        lambda p, x: ddim_inversion_captured(
            fn, p, sched, x, cond[:1], num_inference_steps=STEPS,
            cross_len=0, self_window=(0, 0),
        )
    )(params, x0)

    def run(p, *, reuse=None):
        return jax.jit(
            lambda pp, xt, c: edit_sample(
                fn, pp, sched, xt, cond, uncond,
                num_inference_steps=STEPS, source_uses_cfg=False,
                cached_source=c, reuse_schedule=reuse,
            )
        )(p, traj[-1], cached)

    return run, params, x0, run(params)


@pytest.mark.slow
def test_reuse_off_values_are_bit_identical(cached_edit):
    """The off pin: reuse_schedule=None and "off" take the byte-identical
    scan body — same program, same bits out."""
    run, params, x0, base = cached_edit
    np.testing.assert_array_equal(np.asarray(run(params, reuse="off")),
                                  np.asarray(base))


@pytest.mark.slow
def test_reuse_uniform_one_program_replay_exact_and_differs(cached_edit,
                                                            tmp_path):
    """uniform:2 stays ONE compiled program (the schedule is a static
    boolean lane in the scan's xs + a lax.cond in the body — exactly one
    ledger compile event for the whole edit), the source stream still
    replays EXACTLY (stream 0 is read from the captured trajectory, the
    shallow eps never touches it), and the edit stream genuinely changes
    (the shallow steps really ran the reuse path)."""
    from videop2p_tpu.obs import RunLedger, read_ledger

    run, params, x0, base = cached_edit
    path = str(tmp_path / "reuse_ledger.jsonl")
    with RunLedger(path, device_info=False):
        out = run(params, reuse="uniform:2")
    compiles = [e for e in read_ledger(path) if e["event"] == "compile"]
    assert len(compiles) == 1, compiles
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    assert not np.array_equal(np.asarray(out[1]), np.asarray(base[1]))
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_w8_edit_quality_band_and_exact_replay(cached_edit, tiny):
    """int8 weights through the SAME edit program (make_unet_fn
    dequantizes inside the trace): the quantized edit stays within a PSNR
    band of the full-precision edit — degraded, not destroyed — and the
    cached source replay is still EXACT (quantization perturbs eps, eps
    never touches the replayed stream)."""
    from videop2p_tpu.models.convert import quantize_unet_params
    from videop2p_tpu.obs.quality import psnr

    run, params, x0, base = cached_edit
    qp = quantize_unet_params(params, mode="w8")
    assert has_quantized(qp)
    out = run(qp)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    edit, ref = np.asarray(out[1]), np.asarray(base[1])
    assert not np.array_equal(edit, ref)  # quantization really engaged
    span = float(np.max(ref) - np.min(ref))
    band_db = float(psnr(jnp.asarray(edit), jnp.asarray(ref),
                         data_range=span))
    assert band_db > 15.0, f"w8 edit fell out of the quality band: {band_db} dB"


@pytest.mark.slow
def test_quant_and_reuse_stack_with_exact_replay(cached_edit):
    """Both knobs together: the cheapest configuration still replays the
    source exactly and produces a finite, distinct edit."""
    from videop2p_tpu.models.convert import quantize_unet_params

    run, params, x0, base = cached_edit
    out = run(quantize_unet_params(params, mode="w8"), reuse="uniform:2")
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    assert np.isfinite(np.asarray(out)).all()
    assert not np.array_equal(np.asarray(out[1]), np.asarray(base[1]))


# ------------------------------------------------ the quality gate --


def test_obs_diff_gates_quant_reuse_quality(tmp_path, capsys):
    """The observatory acceptance: quant/reuse quality metrics land as a
    ``quality`` ledger event and obs_diff's QUALITY_RULES gate them —
    self-compare exits 0, an injected reconstruction-PSNR drop exits 1."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_diff_under_quant_test", os.path.join(repo, "tools", "obs_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    from videop2p_tpu.obs import RunLedger

    def write(path, run_id, recon_db):
        led = RunLedger(str(path), run_id=run_id, device_info=False)
        led.event("quality", recon_psnr=recon_db, background_psnr=31.0,
                  recon_ssim=0.95, quant_mode="w8",
                  reuse_schedule="uniform:2")
        led.close()

    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    write(a, "base", 28.0)
    write(b, "quantized_drop", 20.0)  # -28% recon PSNR: past the 5% gate
    assert mod.main(["obs_diff.py", str(a), str(a)]) == 0
    assert mod.main(["obs_diff.py", str(a), str(b)]) == 1
    out = capsys.readouterr().out
    assert "recon_psnr" in out
