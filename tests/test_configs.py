"""Every shipped config must bind cleanly to its CLI entry point.

The reference treats its six tune/p2p YAML pairs as the de-facto regression
suite (SURVEY §4); here the schema contract is pinned mechanically: each
YAML parses, provides every required argument of its `main(...)`, uses only
known parameter names (a typo'd key would silently fall into **unused), and
points at a clip directory that exists for the shipped scenes.
"""

import glob
import inspect
import os

import pytest

from videop2p_tpu.cli.common import load_config
from videop2p_tpu.cli.run_tuning import main as tune_main
from videop2p_tpu.cli.run_videop2p import main as p2p_main

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(ROOT, "configs", "*.yaml")))
# referenced by the reference's configs but not shipped there either —
# data/ ships synthesized stand-ins for these two scenes
SHIPPED_CLIPS = {"car", "motorbike", "penguin_ice", "rabbit", "tiger", "bird_forest"}


def _required(fn):
    sig = inspect.signature(fn)
    return {
        n for n, p in sig.parameters.items()
        if p.default is inspect.Parameter.empty
        and p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
    }


def _known(fn):
    return set(inspect.signature(fn).parameters) - {"unused"}


@pytest.mark.parametrize("path", CONFIGS, ids=[os.path.basename(p) for p in CONFIGS])
def test_config_binds_to_entry_point(path):
    cfg = load_config(path)
    is_tune = path.endswith("-tune.yaml")
    fn = tune_main if is_tune else p2p_main
    missing = _required(fn) - set(cfg)
    assert not missing, f"{path} misses required args {missing}"
    unknown = set(cfg) - _known(fn)
    assert not unknown, f"{path} has keys no parameter consumes: {unknown}"

    clip = cfg["train_data"]["video_path"] if is_tune else cfg["image_path"]
    name = os.path.basename(clip.rstrip("/"))
    if name in SHIPPED_CLIPS:
        assert os.path.isdir(os.path.join(ROOT, clip)), f"{clip} not shipped"

    if not is_tune:
        assert len(cfg["prompts"]) >= 2
        assert cfg["prompt"] == cfg["prompts"][0], (
            f"{path}: source prompt must open the prompts list"
        )
