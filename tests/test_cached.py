"""Cached-source fast-mode tests (pipelines/cached.py).

The cached mode drops the source stream from the edit batch: its latents
replay the DDIM inversion trajectory exactly and the controllers read its
attention maps from a capture made during inversion. These tests pin:

  * the source output stream equals the inversion input x_0 EXACTLY —
    stronger than the reference's fast mode, which re-predicts ε from the
    drifting latent and reconstructs only approximately
    (/root/reference/tuneavideo/pipelines/pipeline_tuneavideo.py:412-415);
  * with no controller the cached edit streams match the live fast edit
    streams (same forwards, smaller batch);
  * the capture is aligned: the map cached for edit step i is the inversion
    forward's probabilities at (trajectory[N−1−i], t_i);
  * the capture windows are exact: maps outside the cross/self gate windows
    are provably unused (full-window capture == minimal-window capture).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from videop2p_tpu.control import make_controller
from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.models.attention import AttnControl
from videop2p_tpu.pipelines import (
    ddim_inversion,
    ddim_inversion_captured,
    edit_sample,
    make_unet_fn,
)
from videop2p_tpu.pipelines.cached import filter_site_tree, tree_bytes
from videop2p_tpu.utils.tokenizers import WordTokenizer

STEPS = 5
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C)


@pytest.fixture(scope="module")
def sched():
    return DDIMScheduler.create_sd()


@pytest.fixture(scope="module")
def tiny():
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), SHAPE)
    text = jax.random.normal(jax.random.key(1), (1, 77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(10), text)
    return make_unet_fn(model), params, cfg


@pytest.fixture(scope="module")
def ctx5():
    return make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.4, self_replace_steps=0.6,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )


def _windows(ctx, num_steps):
    """The shared gate rule (pipelines.cached.capture_windows) every
    production caller uses."""
    from videop2p_tpu.pipelines.cached import capture_windows

    return capture_windows(ctx, num_steps)


def _run_cached(fn, params, sched, x0, cond, uncond, ctx, cross_len, self_window):
    traj, cached = jax.jit(
        lambda p, x: ddim_inversion_captured(
            fn, p, sched, x, cond[:1], num_inference_steps=STEPS,
            cross_len=cross_len, self_window=self_window,
            capture_blend=ctx is not None and ctx.blend is not None,
            blend_res=(4, 4),
        )
    )(params, x0)
    out = jax.jit(
        lambda p, xt, c: edit_sample(
            fn, p, sched, xt, cond, uncond,
            num_inference_steps=STEPS, ctx=ctx, source_uses_cfg=False,
            blend_res=(4, 4), cached_source=c,
        )
    )(params, traj[-1], cached)
    return traj, cached, out


def test_cached_source_stream_is_exact_x0(sched, tiny, ctx5):
    """The cached edit's source output IS the inversion input latent — exact
    reconstruction by construction (VERDICT r3 item 1's pinned property)."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(3), SHAPE)
    cond = jax.random.normal(jax.random.key(4), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    assert 0 < c < STEPS  # the minimal window is a real prefix
    traj, cached, out = _run_cached(fn, params, sched, x0, cond, uncond, ctx5, c, sw)
    assert out.shape == (2,) + SHAPE[1:]
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    # the captured walk is the same math as the plain inversion (segmented
    # scans compile to a different XLA program — tolerance covers fusion-order
    # fp drift only)
    traj_plain = jax.jit(
        lambda p, x: ddim_inversion(fn, p, sched, x, cond[:1], num_inference_steps=STEPS)
    )(params, x0)
    np.testing.assert_allclose(np.asarray(traj), np.asarray(traj_plain), atol=1e-5)
    # the edit stream actually edits
    assert not np.allclose(np.asarray(out[1]), np.asarray(out[0]))


def test_cached_matches_live_fast_without_controller(sched, tiny):
    """With no controller the edit streams are independent of the source
    stream, so cached (2-stream batch) and live fast (3-stream batch) must
    agree stream-for-stream."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(5), SHAPE)
    cond = jax.random.normal(jax.random.key(6), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    traj, cached, out_cached = _run_cached(
        fn, params, sched, x0, cond, uncond, None, 0, (0, 0)
    )
    out_live = jax.jit(
        lambda p, xt: edit_sample(
            fn, p, sched, xt, cond, uncond,
            num_inference_steps=STEPS, source_uses_cfg=False,
        )
    )(params, traj[-1])
    np.testing.assert_allclose(
        np.asarray(out_cached[1]), np.asarray(out_live[1]), atol=1e-5
    )


def test_capture_alignment(sched, tiny):
    """cached.cross_maps[edit step i] must equal the probabilities a capture
    forward produces at (trajectory[N−1−i], t_{N−1−i} ascending) — pins the
    segment stacking + reversal."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(7), SHAPE)
    cond = jax.random.normal(jax.random.key(8), (1, 77, cfg.cross_attention_dim))
    traj, cached = jax.jit(
        lambda p, x: ddim_inversion_captured(
            fn, p, sched, x, cond, num_inference_steps=STEPS,
            cross_len=STEPS, self_window=(0, STEPS), capture_blend=False,
        )
    )(params, x0)
    ts_asc = sched.timesteps(STEPS)[::-1]
    i = 1  # edit step → inversion step j = N−1−i
    j = STEPS - 1 - i
    control = AttnControl(ctx=None, step_index=jnp.asarray(0), capture=True)
    _, store = fn(params, traj[j], jnp.asarray(ts_asc[j]), cond, control)
    # maps are STORED in bf16 (models/attention.py capture sow): the scan vs
    # eager programs' ~1e-6 fp drift can cross a bf16 rounding boundary, so
    # agreement is to one bf16 ULP (~8e-3 near 1.0), not fp32 precision
    manual_cross = filter_site_tree(store["attn_base"], "attn2")
    got = jax.tree.map(lambda a: a[i], cached.cross_maps)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-2),
        got, manual_cross,
    )
    manual_temp = filter_site_tree(store["attn_base"], "attn_temp")
    got_t = jax.tree.map(lambda a: a[i], cached.temporal_maps)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-2),
        got_t, manual_temp,
    )
    assert jax.tree.leaves(got)[0].dtype == jnp.bfloat16


def test_out_of_window_base_maps_are_unused(ctx5):
    """The exact gate property, program-identical: past the cross window /
    outside the self window, control_attention's output must not depend on
    the base map AT ALL (the alpha gate multiplies it to zero; the self gate
    selects the unedited streams) — this is what makes the clamped stale
    slices in CachedSource.base_tree_at provably dead."""
    from videop2p_tpu.control import control_attention

    c, (lo, hi) = _windows(ctx5, STEPS)
    key = jax.random.key(0)
    # cross site: (U+E)·F batch with U=E=1, F=2, H=2, Q=16, W=77
    probs = jax.nn.softmax(jax.random.normal(key, (4, 2, 16, 77)), axis=-1)
    base_a = jax.nn.softmax(jax.random.normal(jax.random.key(1), (2, 2, 16, 77)), axis=-1)
    base_b = jnp.roll(base_a, 3, axis=-1)  # different garbage

    def run_cross(step, base):
        return control_attention(
            probs, ctx5, is_cross=True, step_index=jnp.asarray(step),
            video_length=2, num_uncond=1, base_map=base)

    np.testing.assert_array_equal(
        np.asarray(run_cross(c, base_a)), np.asarray(run_cross(c, base_b)))
    assert not np.allclose(
        np.asarray(run_cross(0, base_a)), np.asarray(run_cross(0, base_b)))

    # temporal site: (U+E)·D batch, D=4, F=2
    probs_t = jax.nn.softmax(jax.random.normal(jax.random.key(2), (8, 2, 2, 2)), axis=-1)
    base_ta = jax.nn.softmax(jax.random.normal(jax.random.key(3), (4, 2, 2, 2)), axis=-1)
    base_tb = jnp.flip(base_ta, axis=-1)

    def run_temp(step, base):
        return control_attention(
            probs_t, ctx5, is_cross=False, step_index=jnp.asarray(step),
            video_length=2, num_uncond=1, base_map=base)

    np.testing.assert_array_equal(
        np.asarray(run_temp(hi, base_ta)), np.asarray(run_temp(hi, base_tb)))
    assert not np.allclose(
        np.asarray(run_temp(lo, base_ta)), np.asarray(run_temp(lo, base_tb)))


def test_minimal_windows_equal_full_capture(sched, tiny, ctx5):
    """Capturing only the gated steps must match capturing every step — the
    gates make the out-of-window base maps dead (exactness pinned
    program-identically in test_out_of_window_base_maps_are_unused; the
    tolerance here covers XLA program-difference fp drift amplified over the
    scan, not semantics)."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(9), SHAPE)
    cond = jax.random.normal(jax.random.key(10), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    _, cached_min, out_min = _run_cached(fn, params, sched, x0, cond, uncond, ctx5, c, sw)
    _, cached_full, out_full = _run_cached(
        fn, params, sched, x0, cond, uncond, ctx5, STEPS, (0, STEPS)
    )
    assert tree_bytes(cached_min.cross_maps) < tree_bytes(cached_full.cross_maps)
    np.testing.assert_allclose(np.asarray(out_min), np.asarray(out_full), atol=2e-3)


def test_cached_with_empty_windows(sched, tiny):
    """A controller with self_replace_steps=0 (or cross 0) leaves that site
    type with NO captured maps — those sites must skip the edit cleanly
    instead of mis-factoring the P−1-stream batch (r4 review finding)."""
    fn, params, cfg = tiny
    ctx0 = make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.4, self_replace_steps=0.0,  # empty self window
    )
    x0 = jax.random.normal(jax.random.key(13), SHAPE)
    cond = jax.random.normal(jax.random.key(14), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx0, STEPS)
    assert sw == (0, 0)
    traj, cached, out = _run_cached(fn, params, sched, x0, cond, uncond, ctx0, c, sw)
    assert cached.temporal_maps is None
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))

    # declared-window/tree mismatch fails loudly, not silently unedited
    from videop2p_tpu.pipelines.cached import CachedSource

    broken = CachedSource(
        src_latents=cached.src_latents, cross_maps=None, temporal_maps=None,
        blend_seq=None, cross_len=c, self_window=(0, 0),
    )
    with pytest.raises(ValueError, match="cross window"):
        edit_sample(fn, params, sched, traj[-1], cond, uncond,
                    num_inference_steps=STEPS, ctx=ctx0, source_uses_cfg=False,
                    cached_source=broken)


def test_cached_multi_frame_embeddings(sched, tiny, ctx5):
    """Per-frame ("multi") conditioning through the cached path, twice over:

    1. identical rows per frame must match the shared-embedding cached edit
       (batching consistency);
    2. per-frame-DISTINCT rows must match the LIVE fast edit with the same
       embeddings and no controller (the edit streams are then independent
       of the source stream) — this pins the per-frame ROUTING: a bug that
       collapsed conditioning to one frame would produce different outputs
       here but not in (1)."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(15), SHAPE)
    cond = jax.random.normal(jax.random.key(16), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    traj, cached, out_shared = _run_cached(
        fn, params, sched, x0, cond, uncond, ctx5, c, sw
    )
    cond_multi = jnp.repeat(cond[:, None], SHAPE[1], axis=1)  # (P, F, L, D)
    out_multi = jax.jit(
        lambda p, xt, cch: edit_sample(
            fn, p, sched, xt, cond_multi, uncond,
            num_inference_steps=STEPS, ctx=ctx5, source_uses_cfg=False,
            blend_res=(4, 4), cached_source=cch,
        )
    )(params, traj[-1], cached)
    np.testing.assert_allclose(
        np.asarray(out_shared), np.asarray(out_multi), atol=1e-5
    )

    # (2) distinct per-frame rows, no controller: cached == live per stream
    cond_distinct = cond_multi + 0.1 * jax.random.normal(
        jax.random.key(21), cond_multi.shape
    )
    _, cached0 = ddim_inversion_captured(
        fn, params, sched, x0, cond[:1], num_inference_steps=STEPS,
        cross_len=0, self_window=(0, 0),
    )
    out_c = jax.jit(
        lambda p, xt, cch: edit_sample(
            fn, p, sched, xt, cond_distinct, uncond,
            num_inference_steps=STEPS, source_uses_cfg=False, cached_source=cch,
        )
    )(params, traj[-1], cached0)
    out_l = jax.jit(
        lambda p, xt: edit_sample(
            fn, p, sched, xt, cond_distinct, uncond,
            num_inference_steps=STEPS, source_uses_cfg=False,
        )
    )(params, traj[-1])
    np.testing.assert_allclose(np.asarray(out_c[1]), np.asarray(out_l[1]), atol=1e-5)


def test_cached_spatial_replace(sched, tiny):
    """SpatialReplace through the cached path: while active, every edit
    stream's latent is overwritten with the source's (run_videop2p.py:235-246)
    — with the source read from the trajectory, an always-active injection
    makes the edit stream equal the exact reconstruction."""
    from videop2p_tpu.control import make_spatial_replace_controller

    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(17), SHAPE)
    cond = jax.random.normal(jax.random.key(18), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    ctx_sr = make_spatial_replace_controller(0.0, STEPS)  # inject every step
    traj, cached, out = _run_cached(
        fn, params, sched, x0, cond, uncond, ctx_sr, 0, (0, 0)
    )
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    # last step's injection puts the edit stream on the source's post-step
    # latent — i.e. the exact reconstruction
    np.testing.assert_allclose(np.asarray(out[1]), np.asarray(x0[0]), atol=1e-6)

    # the window BOUNDARY, pinned exactly: with injection active on all but
    # the final step, out[1] must equal one CFG denoise step applied to the
    # source's post-injection latent trajectory[1] at the last timestep —
    # an off-by-one in the gate (`<=` instead of `<`) would give x0 instead
    from videop2p_tpu.control.controllers import ControlContext
    from videop2p_tpu.utils.tokenizers import MAX_NUM_WORDS

    ctx_partial = ControlContext(
        cross_replace_alpha=jnp.zeros((STEPS + 1, 1, 1, 1, MAX_NUM_WORDS)),
        kind="empty", num_prompts=2, self_replace_range=(0, 0),
        spatial_replace_until=STEPS - 1,
    )
    _, _, out_p = _run_cached(
        fn, params, sched, x0, cond, uncond, ctx_partial, 0, (0, 0)
    )
    ts = sched.timesteps(STEPS)
    t_last = jnp.asarray(ts[-1])
    lat = traj[1]  # source latent after edit step STEPS−2 (post-injection)
    eps_u, _ = fn(params, lat, t_last, uncond[None], None)
    eps_c, _ = fn(params, lat, t_last, cond[1:], None)
    eps = eps_u + 7.5 * (eps_c - eps_u)
    expected, _ = sched.step(eps, t_last, lat, STEPS, eta=0.0, variance_noise=None)
    np.testing.assert_allclose(np.asarray(out_p[1]), np.asarray(expected[0]), atol=1e-5)
    # a `<=` gate would have injected on the final step too, making out[1]
    # BITWISE equal to x0 (the one-step denoise only approximates it)
    assert np.abs(np.asarray(out_p[1]) - np.asarray(x0[0])).max() > 0.0


def test_cached_three_prompts(sched, tiny):
    """P=3 (two edit streams) through the cached path: batch factors as
    2 uncond + 2 cond edits, both edits read the same cached base maps."""
    fn, params, cfg = tiny
    prompts = [
        "a rabbit is jumping",
        "a origami rabbit is jumping",
        "a plush rabbit is jumping",
    ]
    ctx3 = make_controller(
        prompts, WordTokenizer(), num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.4, self_replace_steps=0.6,
        # one blend-word entry PER PROMPT (a 2-entry tuple would silently
        # zip-truncate and zero the third prompt's blend alpha row)
        blend_words=(["rabbit"], ["rabbit"], ["rabbit"]),
    )
    x0 = jax.random.normal(jax.random.key(19), SHAPE)
    cond = jax.random.normal(jax.random.key(20), (3, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx3, STEPS)
    traj, cached, out = _run_cached(fn, params, sched, x0, cond, uncond, ctx3, c, sw)
    assert out.shape == (3,) + SHAPE[1:]
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x0[0]))
    # the two edit streams see different prompts and must differ
    assert not np.allclose(np.asarray(out[1]), np.asarray(out[2]))


def test_fused_helper_matches_two_call_path(sched, tiny, ctx5):
    """pipelines.cached_fast_edit (the ONE program the CLI jits and the
    bench measures) must equal captured-inversion + cached-edit as separate
    calls — same math, one dispatch."""
    from videop2p_tpu.pipelines import cached_fast_edit

    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(23), SHAPE)
    cond = jax.random.normal(jax.random.key(24), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    traj, cached, out_two = _run_cached(fn, params, sched, x0, cond, uncond, ctx5, c, sw)
    traj_f, out_f = jax.jit(
        lambda p, x: cached_fast_edit(
            fn, p, sched, x, cond[:1], cond, uncond, ctx5,
            num_inference_steps=STEPS, cross_len=c, self_window=sw,
        )
    )(params, x0)
    # fused trajectory == two-call trajectory (same walk, different XLA
    # program; tolerance covers fusion-order fp drift)
    np.testing.assert_allclose(np.asarray(traj_f), np.asarray(traj), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(out_f[0]), np.asarray(x0[0]))
    # blend_res differs between the helper (latent/4 rule) and _run_cached's
    # explicit (4,4)? — the tiny 8×8 latent's rule resolves to the same (2,2)
    # fallback site either way, so outputs must agree up to bf16-map rounding
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_two), atol=2e-3)


def test_step_subset_cached_replay_exact_and_identity(sched, tiny, ctx5):
    """ISSUE 8: the few-step cached edit from a full capture. The identity
    subset is BIT-identical to the plain path (the subset seam changes
    nothing at full count), and a 2-of-5 subset still replays the source
    exactly (stream 0 == x_0 — src_err 0.0 at any step count) while the
    edit stream genuinely takes fewer, larger steps."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(30), SHAPE)
    cond = jax.random.normal(jax.random.key(31), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    traj, cached, out_full = _run_cached(
        fn, params, sched, x0, cond, uncond, ctx5, c, sw
    )
    out_id = jax.jit(
        lambda p, xt, cch: edit_sample(
            fn, p, sched, xt, cond, uncond, num_inference_steps=STEPS,
            ctx=ctx5, source_uses_cfg=False, blend_res=(4, 4),
            cached_source=cch, step_positions=tuple(range(STEPS)),
        )
    )(params, traj[-1], cached)
    np.testing.assert_array_equal(np.asarray(out_id), np.asarray(out_full))

    pos = tuple(int(i) for i in sched.subset_positions(STEPS, 2))
    ctx2 = make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=2,
        is_replace_controller=False,
        cross_replace_steps=0.4, self_replace_steps=0.6,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )
    out2 = jax.jit(
        lambda p, xt, cch: edit_sample(
            fn, p, sched, xt, cond, uncond, num_inference_steps=2,
            ctx=ctx2, source_uses_cfg=False, blend_res=(4, 4),
            cached_source=cch, step_positions=pos,
        )
    )(params, traj[-1], cached)
    np.testing.assert_array_equal(np.asarray(out2[0]), np.asarray(x0[0]))
    assert np.isfinite(np.asarray(out2)).all()
    assert not np.allclose(np.asarray(out2[1]), np.asarray(out_full[1]))


def test_step_subset_validation(sched, tiny, ctx5):
    """The subset seam's guard rails: malformed positions, count
    mismatches, cached-less use, and gated steps mapping outside the
    captured windows all raise before any device work."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(32), SHAPE)
    cond = jax.random.normal(jax.random.key(33), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    traj, cached, _ = _run_cached(
        fn, params, sched, x0, cond, uncond, ctx5, c, sw
    )

    def run(positions, *, n, ctx=None, cch=cached):
        return edit_sample(
            fn, params, sched, traj[-1], cond, uncond,
            num_inference_steps=n, ctx=ctx, source_uses_cfg=False,
            blend_res=(4, 4), cached_source=cch, step_positions=positions,
        )

    with pytest.raises(ValueError, match="requires cached_source"):
        run((0, 2), n=2, cch=None)
    with pytest.raises(ValueError, match="strictly increasing"):
        run((0, 3, 2), n=3)
    with pytest.raises(ValueError, match="start at 0"):
        run((1, 3), n=2)
    with pytest.raises(ValueError, match="covers"):
        run((0, STEPS), n=2)
    with pytest.raises(ValueError, match="entries"):
        run((0, 2), n=3)
    # a controller whose self window maps past the captured window fails
    # loudly (a clamped read would silently edit with stale maps)
    ctx_wide = make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=2,
        is_replace_controller=False,
        cross_replace_steps=0.4, self_replace_steps=1.0,
    )
    with pytest.raises(ValueError, match="self window maps"):
        run((0, STEPS - 1), n=2, ctx=ctx_wide)


def test_cached_vs_live_controlled_delta_tracks_source_drift(sched, tiny, ctx5):
    """Quantify the cached-mode approximation WITH controllers (VERDICT r4
    item 2). The only input difference between the two paths is the source
    stream: cached replays the inversion trajectory exactly, live re-predicts
    from a drifting latent (pipeline_tuneavideo.py:412-415) — so the edited
    streams' divergence must be DRIVEN BY (and bounded by a small multiple
    of) the live source's reconstruction drift. With random weights that
    drift is large (DDIM inversion's linearization assumes a trained ε-model),
    which is exactly why the bound is relative, not absolute; bench.py
    records the same pair of numbers at SD scale
    (cached_vs_live_edit_max_abs_delta / cached_vs_live_source_max_abs_delta).
    """
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(40), SHAPE)
    cond = jax.random.normal(jax.random.key(41), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)
    traj, cached, out_c = _run_cached(fn, params, sched, x0, cond, uncond, ctx5, c, sw)
    out_l = jax.jit(
        lambda p, xt: edit_sample(
            fn, p, sched, xt, cond, uncond,
            num_inference_steps=STEPS, ctx=ctx5, source_uses_cfg=False,
            blend_res=(4, 4),
        )
    )(params, traj[-1])
    edit_delta = float(np.abs(np.asarray(out_c[1], np.float32)
                              - np.asarray(out_l[1], np.float32)).max())
    source_drift = float(np.abs(np.asarray(out_c[0], np.float32)
                                - np.asarray(out_l[0], np.float32)).max())
    # cached stream 0 is exact (pinned elsewhere), so source_drift IS the
    # live path's reconstruction error; the edit delta rides it through the
    # shared base maps. Measured at this seed: delta ~16, drift ~7.7.
    assert source_drift > 0.0
    assert edit_delta <= 5.0 * source_drift + 1e-3, (
        f"edit delta {edit_delta} not explained by source drift {source_drift}"
    )


def test_maps_budget_gate_scales_to_long_video(sched, tiny, ctx5):
    """The per-chip HBM gate (pipelines.fast.maps_budget_decision — the
    CLI's gate) must make the 24-frame long-video config take the cached
    path on a frame-sharded slice while a budget-limited single chip falls
    back to live: capture bytes grow ~linearly with frames, and shard over
    the sp axis. Shapes only (eval_shape) — no compute."""
    from videop2p_tpu.pipelines.fast import capture_shapes, maps_budget_decision

    fn, params, cfg = tiny
    c, sw = _windows(ctx5, STEPS)
    cond = jax.random.normal(jax.random.key(50), (2, 77, cfg.cross_attention_dim))

    def shapes_for(frames):
        x = jnp.zeros((1, frames, 8, 8, 4))
        return capture_shapes(
            fn, params, sched, x, cond[:1], ctx5,
            num_inference_steps=STEPS, cross_len=c, self_window=sw,
        )[1]

    s8, s24 = shapes_for(8), shapes_for(24)
    _, gb8, _ = maps_budget_decision(s8)
    _, gb24, _ = maps_budget_decision(s24)
    assert 2.0 < gb24 / gb8 < 4.0  # ~linear in frames

    # a budget sized between per-chip(sp=4) and global: single chip falls
    # back, the 4-way frame shard takes the cached path
    budget = gb24 / 2
    fits1, _, per1 = maps_budget_decision(s24, sp=1, budget_gb=budget)
    fits4, _, per4 = maps_budget_decision(s24, sp=4, budget_gb=budget)
    assert not fits1 and fits4
    assert per4 == pytest.approx(per1 / 4)


def test_float8_temporal_maps_keep_source_exact_and_edit_close(sched, tiny, ctx5):
    """The long-video budget mode stores temporal maps in float8
    (inversion.py temporal_maps_dtype). Two pinned properties: the source
    stream's replay stays BIT-exact (it is ε-based — storage precision of
    the maps cannot touch it), and the edited stream stays close to the
    full-precision-maps output (the maps only enter via the controller's
    base-map replacement)."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(60), SHAPE)
    cond = jax.random.normal(jax.random.key(61), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    c, sw = _windows(ctx5, STEPS)

    def run(tm_dtype):
        traj, cached = jax.jit(
            lambda p, x: ddim_inversion_captured(
                fn, p, sched, x, cond[:1], num_inference_steps=STEPS,
                cross_len=c, self_window=sw, capture_blend=True,
                blend_res=(4, 4), temporal_maps_dtype=tm_dtype,
            )
        )(params, x0)
        out = jax.jit(
            lambda p, xt, cc: edit_sample(
                fn, p, sched, xt, cond, uncond,
                num_inference_steps=STEPS, ctx=ctx5, source_uses_cfg=False,
                blend_res=(4, 4), cached_source=cc,
            )
        )(params, traj[-1], cached)
        return cached, out

    cached8, out8 = run(jnp.float8_e4m3fn)
    _, out16 = run(None)
    stored = {
        str(a.dtype)
        for a in jax.tree.leaves(cached8.temporal_maps)
    }
    assert stored == {"float8_e4m3fn"}
    np.testing.assert_array_equal(np.asarray(out8[0]), np.asarray(x0[0]))
    # e4m3 keeps ~2 significant digits on [0,1] probabilities; the edit
    # output moves by far less than the cached-vs-live deltas the mode
    # already discloses
    scale = float(np.abs(np.asarray(out16[1], np.float32)).mean())
    delta = float(np.abs(np.asarray(out8[1], np.float32)
                         - np.asarray(out16[1], np.float32)).max())
    assert delta <= 0.15 * max(scale, 1.0), (delta, scale)


def test_choose_cached_maps_escalates_to_float8(sched, tiny, ctx5):
    """The shared CLI/bench decision helper: full-precision first, float8
    temporal storage when bf16 overflows the per-chip budget, live
    fallback only when even float8 does."""
    from videop2p_tpu.pipelines.fast import (
        capture_shapes,
        choose_cached_maps,
        maps_budget_decision,
    )

    fn, params, cfg = tiny
    c, sw = _windows(ctx5, STEPS)
    cond = jax.random.normal(jax.random.key(62), (2, 77, cfg.cross_attention_dim))
    x = jnp.zeros((1, 24, 8, 8, 4))

    def shapes_for(dt):
        return capture_shapes(
            fn, params, sched, x, cond[:1], ctx5,
            num_inference_steps=STEPS, cross_len=c, self_window=sw,
            temporal_maps_dtype=dt,
        )[1]

    _, gb_full, _ = maps_budget_decision(shapes_for(None))
    _, gb_f8, _ = maps_budget_decision(shapes_for(jnp.float8_e4m3fn))
    assert gb_f8 < gb_full

    ok, dt, _, _ = choose_cached_maps(shapes_for, budget_gb=gb_full * 1.01)
    assert ok and dt is None  # roomy budget → full precision
    ok, dt, _, _ = choose_cached_maps(
        shapes_for, budget_gb=(gb_f8 + gb_full) / 2
    )
    assert ok and dt is not None  # between the two → float8 temporal maps
    ok, dt, _, _ = choose_cached_maps(shapes_for, budget_gb=gb_f8 * 0.5)
    assert not ok  # under even the float8 size → live fallback


def test_cached_rejects_invalid_combinations(sched, tiny):
    """cached_source is a fast-mode-only seam: official-mode CFG sources,
    stochastic eta, and per-step null embeddings all contradict the captured
    deterministic source stream and must be rejected loudly."""
    fn, params, cfg = tiny
    x0 = jax.random.normal(jax.random.key(11), SHAPE)
    cond = jax.random.normal(jax.random.key(12), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    _, cached = ddim_inversion_captured(
        fn, params, sched, x0, cond[:1], num_inference_steps=STEPS,
        cross_len=0, self_window=(0, 0),
    )
    with pytest.raises(ValueError, match="fast mode"):
        edit_sample(fn, params, sched, x0, cond, uncond,
                    num_inference_steps=STEPS, source_uses_cfg=True,
                    cached_source=cached)
    with pytest.raises(ValueError, match="eta"):
        edit_sample(fn, params, sched, x0, cond, uncond,
                    num_inference_steps=STEPS, source_uses_cfg=False,
                    eta=0.5, cached_source=cached)
    with pytest.raises(ValueError, match="null-text"):
        edit_sample(fn, params, sched, x0, cond, uncond,
                    num_inference_steps=STEPS, source_uses_cfg=False,
                    null_uncond_embeddings=jnp.zeros((STEPS, 77, cfg.cross_attention_dim)),
                    cached_source=cached)
