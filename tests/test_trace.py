"""Time-domain observability, trace half (ISSUE 6): the stdlib protobuf
wire-format reader for ``*.xplane.pb``, the timeline algebra (interval
unions, the compute/collective overlap fraction), the trace analyses,
and the ``trace_window`` capture path.

The decoder fixtures are encoded by a TEST-LOCAL stdlib protobuf writer
(varints + length-delimited fields below) — synthetic spaces with nested
planes/lines/events, metadata-id references, multi-byte varints and
zero-length strings decode to known event sets, so the reader is pinned
against the wire format itself, not against its own output. A real CPU
``jax.profiler`` capture closes the loop: the parser must walk an
actual trace without error and WITHOUT importing tensorflow.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.obs import RunLedger, read_ledger
from videop2p_tpu.obs.trace import (
    TRACE_ANALYSIS_FIELDS,
    analyze_events,
    analyze_trace_dir,
    interval_union,
    is_collective_op,
    is_device_plane,
    iter_line_events,
    load_xplanes,
    op_family,
    overlap_fraction,
    parse_xspace,
    trace_window,
    union_length,
)

# ------------------------------------------- test-local protobuf writer --
# Encodes the subset of the xplane schema the reader decodes. Deliberately
# independent code (encoder here, decoder in obs/trace.py) so a shared bug
# cannot cancel itself out — the fixtures assert on hand-computed values.


def _vint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64  # two's-complement int64, all ten bytes
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _field_varint(field: int, v: int) -> bytes:
    return _vint(field << 3) + _vint(v)


def _field_len(field: int, payload: bytes) -> bytes:
    return _vint(field << 3 | 2) + _vint(len(payload)) + payload


def _field_fixed64(field: int, raw: bytes) -> bytes:
    return _vint(field << 3 | 1) + raw


def _field_fixed32(field: int, raw: bytes) -> bytes:
    return _vint(field << 3 | 5) + raw


def _event(metadata_id: int, offset_ps: int, duration_ps: int) -> bytes:
    return (_field_varint(1, metadata_id) + _field_varint(2, offset_ps)
            + _field_varint(3, duration_ps))


def _line(name: str, timestamp_ns: int, events) -> bytes:
    buf = _field_len(2, name.encode()) + _field_varint(3, timestamp_ns)
    for ev in events:
        buf += _field_len(4, ev)
    return buf


def _event_metadata_entry(mid: int, name: str) -> bytes:
    inner = _field_varint(1, mid) + _field_len(2, name.encode())
    return _field_varint(1, mid) + _field_len(2, inner)


def _plane(name: str, lines, event_metadata) -> bytes:
    buf = _field_len(2, name.encode())
    for line in lines:
        buf += _field_len(3, line)
    for mid, nm in event_metadata.items():
        buf += _field_len(4, _event_metadata_entry(mid, nm))
    return buf


def _xspace(planes) -> bytes:
    return b"".join(_field_len(1, p) for p in planes)


def _write_trace(tmp_path, data: bytes, fname="host.xplane.pb") -> str:
    d = tmp_path / "plugins" / "profile" / "2026_08_04"
    d.mkdir(parents=True, exist_ok=True)
    (d / fname).write_bytes(data)
    return str(tmp_path)


# ------------------------------------------------------- wire decoding --


def test_parse_xspace_nested_planes_lines_events_with_metadata_refs():
    """The canonical fixture: a device plane whose events reference
    metadata ids (including a multi-byte id), a host plane that device
    iteration must skip, and absolute starts = line timestamp_ns*1000 +
    event offset_ps."""
    dev = _plane(
        "/device:TPU:0",
        lines=[
            _line("XLA Ops", timestamp_ns=1000, events=[
                _event(7, offset_ps=0, duration_ps=500),
                _event(300, offset_ps=2_000, duration_ps=1_500),
            ]),
            _line("XLA Modules", timestamp_ns=1000, events=[
                _event(7, offset_ps=0, duration_ps=4_000),
            ]),
        ],
        event_metadata={7: "fusion.42", 300: "all-reduce.1"},
    )
    host = _plane(
        "/host:CPU",
        lines=[_line("python", timestamp_ns=0,
                     events=[_event(1, 0, 99)])],
        event_metadata={1: "host_thing"},
    )
    space = parse_xspace(_xspace([dev, host]))
    assert [p["name"] for p in space["planes"]] == ["/device:TPU:0", "/host:CPU"]
    p0 = space["planes"][0]
    assert p0["event_metadata"] == {7: "fusion.42", 300: "all-reduce.1"}
    assert [l["name"] for l in p0["lines"]] == ["XLA Ops", "XLA Modules"]

    events = list(iter_line_events(space["planes"], "XLA Ops"))
    # starts are absolute ps: 1000 ns * 1000 + offset
    assert events == [
        ("fusion.42", 1_000_000, 500),
        ("all-reduce.1", 1_002_000, 1_500),
    ]
    # the host plane's line never leaks into device iteration
    assert list(iter_line_events(space["planes"], "python")) == []
    assert list(iter_line_events(space["planes"], "python",
                                 device_only=False)) == [("host_thing", 0, 99)]


def test_varint_edge_cases_multibyte_and_zero_length_strings():
    """Multi-byte varints (2-byte, 5-byte, full-64-bit), a zero-length
    plane/op name, and unknown fields of every wire type must decode or
    skip cleanly."""
    big_offset = 1 << 34  # needs 5 varint bytes
    plane = _plane(
        "",  # zero-length plane name → not a device plane
        lines=[_line("XLA Ops", timestamp_ns=128, events=[
            _event(200, offset_ps=big_offset, duration_ps=(1 << 40) + 3),
        ])],
        event_metadata={200: ""},  # zero-length op name
    )
    # splice unknown fields into the space: fixed64 (wire 1), fixed32
    # (wire 5), a varint (wire 0), and a length-delimited blob (wire 2)
    junk = (_field_fixed64(9, b"\x01" * 8) + _field_fixed32(10, b"\x02" * 4)
            + _field_varint(11, 1 << 60) + _field_len(12, b"junkpayload"))
    space = parse_xspace(junk + _xspace([plane]) + junk)
    [p] = space["planes"]
    assert p["name"] == ""
    assert not is_device_plane(p["name"])
    [ev] = list(iter_line_events([p], "XLA Ops", device_only=False))
    assert ev == ("", 128 * 1000 + big_offset, (1 << 40) + 3)


def test_truncated_varint_is_a_loud_error():
    with pytest.raises(ValueError):
        parse_xspace(b"\x0a\x05\xff\xff")  # length says 5, buffer ends


def test_load_xplanes_walks_nested_dirs(tmp_path):
    dev = _plane("/device:TPU:0",
                 lines=[_line("XLA Ops", 0, [_event(1, 0, 10)])],
                 event_metadata={1: "dot.7"})
    root = _write_trace(tmp_path, _xspace([dev]))
    planes = load_xplanes(root)
    assert len(planes) == 1
    assert list(iter_line_events(planes, "XLA Ops")) == [("dot.7", 0, 10)]


# --------------------------------------------------- timeline algebra --


def test_interval_union_cases():
    assert interval_union([]) == []
    assert interval_union([(0, 1), (1, 2)]) == [(0, 2)]  # touching merges
    assert interval_union([(0, 1), (2, 3)]) == [(0, 1), (2, 3)]
    assert interval_union([(0, 10), (2, 5)]) == [(0, 10)]  # nested
    assert interval_union([(3, 3), (5, 4)]) == []  # degenerate dropped
    assert union_length([(0, 4), (2, 8), (10, 11)]) == 9


def test_overlap_fraction_closed_forms():
    """The acceptance pins: disjoint → 0.0, contained → 1.0,
    half-overlap → 0.5; no collectives → None (not 0.0 — nothing to
    overlap is a different statement than fully exposed)."""
    assert overlap_fraction([(0, 10)], [(20, 30)]) == 0.0
    assert overlap_fraction([(0, 10)], [(2, 4)]) == 1.0
    assert overlap_fraction([(0, 10)], [(5, 15)]) == 0.5
    assert overlap_fraction([(0, 10)], []) is None
    assert overlap_fraction([], [(0, 10)]) == 0.0
    # fragmented both sides: compute covers 3 of the 4 collective units
    assert overlap_fraction(
        [(0, 2), (3, 4)], [(1, 3), (3, 5)]
    ) == pytest.approx(2 / 4)
    # overlapping collective intervals are unioned, not double-counted
    assert overlap_fraction([(0, 4)], [(0, 4), (2, 4)]) == 1.0


def test_op_family_and_collective_classification():
    assert op_family("fusion.123") == "fusion"
    assert op_family("%all-reduce.5") == "all-reduce"
    assert op_family("collective-permute-start.2") == "collective-permute"
    assert is_collective_op("all-gather.9")
    assert is_collective_op("%reduce-scatter.1")
    assert not is_collective_op("reduce.4")  # plain reduce is compute
    assert not is_collective_op("fusion.8")


# -------------------------------------------------------- analyses --


def test_analyze_events_totals_overlap_idle_families_topops():
    """Hand-computed fixture: two compute ops and one collective on a
    known timeline, plus a module envelope."""
    ops = [
        ("fusion.1", 0, 1_000_000),          # [0, 1e6)
        ("dot.2", 2_000_000, 1_000_000),     # [2e6, 3e6)
        ("all-reduce.3", 500_000, 1_000_000),  # [0.5e6, 1.5e6)
    ]
    modules = [("jit_edit", 0, 3_000_000)]
    record, arrays = analyze_events(ops, modules, name="fix",
                                    trace_dir="/tmp/t")
    assert set(TRACE_ANALYSIS_FIELDS) <= set(record)
    assert record["device_total_s"] == pytest.approx(3e6 / 1e12)
    # compute union [0,1e6)+[2e6,3e6) = 2e6; collective union = 1e6
    assert record["compute_s"] == pytest.approx(2e6 / 1e12)
    assert record["collective_s"] == pytest.approx(1e6 / 1e12)
    # compute covers [0.5e6, 1e6) of the collective → 0.5
    assert record["overlap_fraction"] == pytest.approx(0.5)
    # all events union [0, 1.5e6)+[2e6, 3e6) over span [0, 3e6) → idle 0.5e6
    assert record["span_s"] == pytest.approx(3e6 / 1e12)
    assert record["idle_s"] == pytest.approx(0.5e6 / 1e12)
    assert record["idle_max_s"] == pytest.approx(0.5e6 / 1e12)
    assert record["num_events"] == 3 and record["num_ops"] == 3
    assert record["module_total_s"] == pytest.approx(3e6 / 1e12)
    assert record["module_span_s"] == pytest.approx(3e6 / 1e12)
    assert list(record["families"])[0] in ("fusion", "dot", "all-reduce")
    assert {t["op"] for t in record["top_ops"]} == {
        "fusion.1", "dot.2", "all-reduce.3"}
    np.testing.assert_array_equal(
        arrays["trace_fix/op_is_collective"], [False, False, True])
    assert arrays["trace_fix/module_dur_ps"].tolist() == [3_000_000]


def test_analyze_events_empty_is_well_formed():
    record, arrays = analyze_events([], [], name="empty")
    assert set(TRACE_ANALYSIS_FIELDS) <= set(record)
    assert record["device_total_s"] == 0.0
    assert record["overlap_fraction"] is None
    assert record["num_events"] == 0
    assert arrays["trace_empty/op_dur_ps"].shape == (0,)


def test_analyze_trace_dir_synthetic_device_plane(tmp_path):
    dev = _plane(
        "/device:TPU:0",
        lines=[
            _line("XLA Ops", 0, [
                _event(1, 0, 2_000_000),
                _event(2, 1_000_000, 2_000_000),
            ]),
        ],
        event_metadata={1: "fusion.1", 2: "collective-permute.9"},
    )
    root = _write_trace(tmp_path, _xspace([dev]))
    record, _ = analyze_trace_dir(root, name="synthetic")
    assert record["num_events"] == 2
    # ppermute [1e6,3e6), compute [0,2e6) → 1e6 of 2e6 hidden
    assert record["overlap_fraction"] == pytest.approx(0.5)
    assert 0.0 <= record["overlap_fraction"] <= 1.0


# ------------------------------------------------ real CPU trace smoke --


def test_real_cpu_trace_parses_without_tensorflow(tmp_path):
    """Acceptance: a real ``jax.profiler`` capture decodes with the
    stdlib reader — planes walked, host events resolved through the
    metadata refs, the analyzer returns a well-formed record — and
    tensorflow is never imported."""
    tdir = str(tmp_path / "trace")
    with jax.profiler.trace(tdir):
        x = jnp.ones((128, 128))
        jax.block_until_ready(jax.jit(lambda a: jnp.tanh(a @ a))(x))
    planes = load_xplanes(tdir)
    assert planes, "capture produced no xplane protos"
    # the host plane holds real named events (python line et al.)
    named = [
        (plane["name"], line["name"], len(line["events"]))
        for plane in planes for line in plane["lines"] if line["events"]
    ]
    assert named, "no events decoded from a real capture"
    all_events = [
        ev for plane in planes for line in plane["lines"]
        for ev in line["events"]
    ]
    assert all(ev["duration_ps"] >= 0 for ev in all_events)
    record, arrays = analyze_trace_dir(tdir, name="cpu_smoke")
    assert set(TRACE_ANALYSIS_FIELDS) <= set(record)
    ov = record["overlap_fraction"]
    assert ov is None or 0.0 <= ov <= 1.0
    # the no-tensorflow claim, proven in a clean interpreter (this test
    # process may have tensorflow resident from unrelated machinery):
    # mining the real capture must work with obs.trace alone
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mod_path = os.path.join(repo, "videop2p_tpu", "obs", "trace.py")
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import importlib.util, sys\n"
        f"spec = importlib.util.spec_from_file_location('xtrace', {mod_path!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        f"rec, _ = m.analyze_trace_dir({tdir!r})\n"
        "assert 'tensorflow' not in sys.modules, 'tensorflow imported'\n"
        "assert 'jax' not in sys.modules, 'jax imported'\n"
        "print(rec['num_events'])\n"
    )
    run = subprocess.run(
        [sys.executable, str(probe)], capture_output=True, text=True,
        timeout=120, cwd=repo,
    )
    assert run.returncode == 0, run.stderr


def test_trace_window_emits_ledger_event_and_sidecar(tmp_path):
    """trace_window end-to-end on CPU: the region is captured, mined,
    and lands as ONE trace_analysis event with the pinned schema plus a
    readable .npz sidecar."""
    path = str(tmp_path / "ledger.jsonl")
    tdir = str(tmp_path / "capture")
    with RunLedger(path):
        with trace_window("edit_region", trace_dir=tdir) as target:
            assert target == tdir
            jax.block_until_ready(
                jax.jit(lambda a: a * 2 + 1)(jnp.ones((64, 64))))
    events = read_ledger(path)
    tas = [e for e in events if e["event"] == "trace_analysis"]
    assert len(tas) == 1
    ta = tas[0]
    assert set(TRACE_ANALYSIS_FIELDS) <= set(ta)
    assert ta["name"] == "edit_region" and ta["trace_dir"] == tdir
    assert os.path.isfile(ta["sidecar"])
    with np.load(ta["sidecar"]) as z:
        assert "trace_edit_region/op_dur_ps" in z.files
    # no skip event on the healthy path
    assert not [e for e in events if e["event"] == "trace_analysis_skipped"]


def test_trace_window_without_ledger_is_silent(tmp_path):
    with trace_window("orphan", trace_dir=str(tmp_path / "t")):
        jax.block_until_ready(jnp.ones(4) + 1)
    # nothing to assert beyond "no crash, no ledger required"


def test_report_auto_mines_trace_events(tmp_path):
    """ISSUE 6 satellite: a ledger holding only a PR-4 ``trace`` event
    (utils/profiling.trace recorded the dir) gets its directory mined
    into the report's "Where time goes" section at render time."""
    from videop2p_tpu.obs.report import write_report

    dev = _plane(
        "/device:TPU:0",
        lines=[_line("XLA Ops", 0, [
            _event(1, 0, 2_000_000), _event(2, 1_000_000, 2_000_000),
        ])],
        event_metadata={1: "fusion.1", 2: "all-gather.3"},
    )
    troot = _write_trace(tmp_path / "tracedir", _xspace([dev]))
    ledger = tmp_path / "ledger.jsonl"
    import json

    ledger.write_text("\n".join([
        json.dumps({"event": "run_start", "run_id": "tm", "t": 0}),
        json.dumps({"event": "trace", "t": 1.0, "name": "edit_phase",
                    "trace_dir": troot}),
    ]) + "\n")
    out = write_report(str(ledger))
    html_text = open(out).read()
    assert "Where time goes" in html_text
    assert "edit_phase" in html_text
    # a dangling trace dir must not break rendering
    ledger2 = tmp_path / "ledger2.jsonl"
    ledger2.write_text(json.dumps(
        {"event": "trace", "name": "gone", "trace_dir": str(tmp_path / "nope")}
    ) + "\n")
    assert os.path.isfile(write_report(str(ledger2)))
