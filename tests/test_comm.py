"""Distributed observability (ISSUE 5, videop2p_tpu/obs/comm.py): collective
accounting, the per-device divergence probe, and the comm regression gates
— exercised on the virtual 8-device CPU mesh conftest.py sets up.

Fast tests cover the pure host-side pieces (HLO text mining, rule
semantics, tool rendering/exit codes, backward compat with pre-comm
ledgers); the mesh-compiling tests are marked slow like the rest of
tests/test_parallel.py.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from videop2p_tpu.obs.comm import (
    COMM_ANALYSIS_FIELDS,
    DEVICE_TELEMETRY_FIELDS,
    collective_summary,
    comm_analysis_record,
    make_device_probe,
    replica_divergence,
    split_device_stats,
    summarize_device_stats,
    tree_replica_divergence,
)
from videop2p_tpu.obs.history import (
    COMM_RULES,
    evaluate_rules,
    extract_run,
    split_runs,
)
from videop2p_tpu.obs.ledger import RunLedger, read_ledger
from videop2p_tpu.parallel import make_mesh
from videop2p_tpu.parallel.ring import shard_map_compat

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_comm_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------- collective mining --


_SYNTHETIC_HLO = """\
HloModule jit_fn, is_scheduled=true, entry_computation_layout={(f32[8,16]{1,0})->f32[8,16]{1,0}}, num_partitions=4

ENTRY main {
  %p0 = f32[8,16]{1,0} parameter(0)
  %ar = f32[8,16]{1,0} all-reduce(%p0), replica_groups={}, to_apply=%add
  %cps = (f32[2,16]{1,0}, f32[2,16]{1,0}) collective-permute-start(%p0), source_target_pairs={{0,1},{1,0}}
  %cpd = f32[2,16]{1,0} collective-permute-done(%cps)
  %ag = f32[32,16]{1,0} all-gather(%p0), dimensions={0}
  ROOT %out = f32[8,16]{1,0} add(%ar, %ar)
}
"""


def test_collective_summary_counts_and_bytes():
    """Synthetic optimized-HLO text: per-kind counts and result-shape
    bytes, with the -done half of an async pair skipped so start/done
    counts once (at the start's tuple result)."""
    rec = collective_summary(_SYNTHETIC_HLO)
    assert set(rec["per_kind"]) == {
        "all-reduce", "collective-permute", "all-gather"
    }
    assert rec["per_kind"]["all-reduce"] == {"count": 1, "bytes": 8 * 16 * 4}
    # the start's TUPLE result sums both components; done contributes 0
    assert rec["per_kind"]["collective-permute"] == {
        "count": 1, "bytes": 2 * (2 * 16 * 4)
    }
    assert rec["per_kind"]["all-gather"] == {"count": 1, "bytes": 32 * 16 * 4}
    assert rec["collective_count"] == 3
    assert rec["collective_bytes"] == sum(
        s["bytes"] for s in rec["per_kind"].values()
    )
    # a module with no collectives reports clean zeros, not absence
    empty = collective_summary("ENTRY main { ROOT %x = f32[4] parameter(0) }")
    assert empty == {"collective_count": 0, "collective_bytes": 0,
                     "per_kind": {}}


# --------------------------------------------------------- rule semantics --


def _comm_run(run_id, *, bytes_=1000, count=10, divergence=0.0, peak=None):
    rec = {
        "run_id": run_id, "programs": {}, "compiles": {}, "phases": {},
        "dispatch": {}, "quality": {},
        "comm": {"edit": {"collective_bytes": bytes_,
                          "collective_count": count, "num_partitions": 8}},
        "device_memory": ({"device0": peak} if peak is not None else {}),
        "divergence": {"edit": divergence},
    }
    return rec


def test_comm_rules_gate_bytes_count_and_divergence():
    base = _comm_run("a")
    # identical runs: clean pass (divergence 0.0 passes with zero floor)
    assert evaluate_rules(base, base, COMM_RULES)["pass"]
    # +20% collective bytes trips the 15% rule; count within its 25%
    grown = _comm_run("b", bytes_=1200, count=11)
    res = evaluate_rules(base, grown, COMM_RULES)
    regs = {(v["rule"], v["program"]) for v in res["regressions"]}
    assert regs == {("comm:collective_bytes+15%", "edit")}
    # nonzero divergence fails even on SELF-compare — no baseline excuses it
    bad = _comm_run("c", divergence=1e-6)
    res = evaluate_rules(bad, bad, COMM_RULES)
    assert not res["pass"]
    [v] = res["regressions"]
    assert v["rule"] == "divergence:value!=0" and v["new"] == 1e-6
    # per-device peak HBM: +15% over the 10% threshold + 1MiB floor
    m_base = _comm_run("d", peak=100 * 2**20)
    m_new = _comm_run("e", peak=115 * 2**20)
    res = evaluate_rules(m_base, m_new, COMM_RULES)
    assert {v["rule"] for v in res["regressions"]} == {
        "device_memory:peak_bytes_in_use+10%"
    }


def test_extract_run_reads_comm_memory_divergence_events(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path, device_info=False) as led:
        led.comm_analysis("edit", {"collective_bytes": 512,
                                   "collective_count": 3,
                                   "num_partitions": 8,
                                   "per_kind": {"all-reduce": {"count": 3,
                                                               "bytes": 512}}})
        led.event("memory", supported=True, devices=[
            {"device": 0, "peak_bytes_in_use": 100},
            {"device": 1, "peak_bytes_in_use": 250},
            {"device": 1, "peak_bytes_in_use": 200},  # keep the worst
        ])
        led.divergence("train_params", 0.0)
        led.device_telemetry("edit", {"devices": 8, "divergence_max": 0.5,
                                      "divergence_final": 0.0})
    rec = extract_run(split_runs(read_ledger(path))[-1])
    assert rec["comm"]["edit"]["collective_bytes"] == 512
    # per_kind is a nested dict — only flat numerics are rule targets
    assert "per_kind" not in rec["comm"]["edit"]
    assert rec["device_memory"] == {"device0": 100.0, "device1": 250.0}
    # divergence keeps the WORST value per label across event kinds
    assert rec["divergence"] == {"train_params": 0.0, "edit": 0.5}
    res = evaluate_rules(rec, rec)
    assert not res["pass"]  # the diverged edit probe fails self-compare
    assert {v["program"] for v in res["regressions"]} == {"edit"}


def test_pre_comm_ledgers_stay_clean(tmp_path):
    """Backward compat: a pre-PR-5 ledger (no comm/memory/divergence
    events) extracts empty distributed sections and evaluates to a clean
    pass — the new rules never fire on absent data."""
    path = str(tmp_path / "old.jsonl")
    with RunLedger(path, device_info=False) as led:
        led.program_analysis("edit", {"flops": 100, "temp_bytes": 10,
                                      "hlo_fingerprint": "aa"})
        led.phase("edit_phase", 1.0)
    rec = extract_run(split_runs(read_ledger(path))[-1])
    assert rec["comm"] == {} and rec["device_memory"] == {}
    assert rec["divergence"] == {}
    assert evaluate_rules(rec, rec)["pass"]
    # extract_run of a record that predates the keys entirely (synthetic
    # old extracted dicts) — evaluate_rules tolerates missing sections
    legacy = {k: v for k, v in rec.items()
              if k not in ("comm", "device_memory", "divergence")}
    assert evaluate_rules(legacy, legacy)["pass"]


# --------------------------------------------------------------- decoders --


def test_summarize_and_split_device_stats():
    stats = {
        "device_abs_max": np.array([[1.0, 2.0], [3.0, 0.5]]),  # (steps, dev)
        "device_mean": np.array([[0.1, 0.2], [0.3, 0.4]]),
        "device_nan_count": np.array([[0, 1], [2, 0]]),
        "device_inf_count": np.array([[0, 0], [0, 0]]),
        "divergence": np.array([0.0, 0.25]),
        "abs_max": np.array([9.0, 9.0]),  # a plain telemetry channel
    }
    rest, dev = split_device_stats(stats)
    assert set(rest) == {"abs_max"}
    assert set(dev) == set(stats) - {"abs_max"}
    rec = summarize_device_stats(dev, device_ids=[0, 1])
    assert set(DEVICE_TELEMETRY_FIELDS) <= set(rec)
    assert rec["devices"] == 2
    assert rec["per_device_abs_max_peak"] == [3.0, 2.0]
    assert rec["per_device_nan_total"] == [2, 1]
    assert rec["nan_total"] == 3
    assert rec["divergence_max"] == 0.25 and rec["divergence_final"] == 0.25
    assert rec["device_ids"] == [0, 1]
    # degenerate input (killed run, empty stats): zeros, never a raise
    empty = summarize_device_stats({})
    assert empty["devices"] == 0 and empty["divergence_max"] == 0.0


# ------------------------------------------------------------ tool surface --


def _write_comm_ledger(path, run_id, *, bytes_=1000, divergence=0.0):
    with RunLedger(path, run_id=run_id, device_info=False) as led:
        led.program_analysis("edit", {"flops": 100, "temp_bytes": 10,
                                      "hlo_fingerprint": "aa"})
        led.comm_analysis("edit", {
            "collective_bytes": bytes_, "collective_count": 10,
            "num_partitions": 8,
            "per_kind": {"collective-permute": {"count": 10, "bytes": bytes_}},
        })
        led.event("memory", supported=True,
                  devices=[{"device": 0, "peak_bytes_in_use": 100 * 2**20}])
        led.divergence("edit_out", divergence)


def test_obs_diff_comm_acceptance(tmp_path, capsys):
    """The ISSUE acceptance gate: self-compare of a comm-bearing ledger
    exits 0; an injected +20% collective-bytes delta exits 1 with a
    machine-readable comm verdict; a diverged run fails even self-compare."""
    mod = _load_tool("obs_diff")
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _write_comm_ledger(a, "a")
    _write_comm_ledger(b, "b", bytes_=1200)
    assert mod.main(["obs_diff.py", a, a]) == 0
    capsys.readouterr()
    assert mod.main(["obs_diff.py", "--json", a, b]) == 1
    out = capsys.readouterr().out
    verdict = json.loads(out)
    assert not verdict["pass"]
    [reg] = verdict["regressions"]
    assert reg["rule"] == "comm:collective_bytes+15%"
    assert reg["kind"] == "comm" and reg["program"] == "edit"
    assert reg["base"] == 1000 and reg["new"] == 1200
    assert reg["delta_pct"] == 20.0
    # divergence: nonzero fails self-compare (direction="nonzero" survives
    # the tool's threshold-scaling rule rebuild)
    c = str(tmp_path / "c.jsonl")
    _write_comm_ledger(c, "c", divergence=0.125)
    assert mod.main(["obs_diff.py", "--threshold-scale", "10.0", c, c]) == 1
    text = capsys.readouterr().out
    assert "DIVERGED" in text


def test_ledger_summary_renders_comm_sections(tmp_path, capsys):
    mod = _load_tool("ledger_summary")
    path = str(tmp_path / "ledger.jsonl")
    _write_comm_ledger(path, "r")
    with RunLedger(path, run_id="r2", device_info=False) as led:
        led.device_telemetry("edit", {
            "devices": 8, "divergence_max": 0.0, "divergence_final": 0.0,
            "nan_total": 0, "per_device_abs_max_peak": [1.0] * 8,
        })
        led.event("host_phase", name="edit", seconds=2.0, process_index=0,
                  process_count=2)
        led.event("host_phase", name="edit", seconds=3.5, process_index=1,
                  process_count=2)
        led.event("program_analysis_skipped", program="vae", reason="disabled")
    assert mod.main(["ledger_summary.py", path]) == 0
    out = capsys.readouterr().out
    assert "collectives" in out and "collective-permute×10" in out
    assert "divergence max 0.0" in out
    assert "per-host phase skew" in out and "1.50" in out  # skew 3.5-2.0
    assert "program analysis skipped" in out and "vae: disabled" in out
    # a pre-comm ledger renders with none of the new sections
    old = str(tmp_path / "old.jsonl")
    with RunLedger(old, device_info=False) as led:
        led.phase("p", 1.0)
    assert mod.main(["ledger_summary.py", old]) == 0
    out = capsys.readouterr().out
    assert "collectives" not in out and "phase skew" not in out


def test_report_comm_section(tmp_path):
    from videop2p_tpu.obs.report import render_report

    events = [
        {"event": "run_start", "run_id": "r"},
        {"event": "comm_analysis", "program": "edit", "num_partitions": 8,
         "collective_count": 4, "collective_bytes": 2048,
         "per_kind": {"all-reduce": {"count": 4, "bytes": 2048}}},
        {"event": "device_telemetry", "program": "edit", "devices": 8,
         "divergence_max": 0.0, "nan_total": 0},
        {"event": "divergence", "label": "train_params", "value": 0.5},
        {"event": "host_phase", "name": "edit", "seconds": 1.0,
         "process_index": 0},
        {"event": "host_phase", "name": "edit", "seconds": 2.0,
         "process_index": 1},
    ]
    html_text = render_report(events, {})
    assert "Distributed / communication" in html_text
    assert "all-reduce×4" in html_text
    assert "DIVERGED" in html_text  # the nonzero train_params row
    assert "Per-host phase skew" in html_text
    # without the events the section is absent entirely
    assert "Distributed" not in render_report(
        [{"event": "run_start", "run_id": "r"}], {}
    )


def test_phase_skew_and_host_record():
    from videop2p_tpu.parallel import host_phase_record, phase_skew

    rec = host_phase_record("edit", 1.234567)
    assert rec["name"] == "edit" and rec["seconds"] == 1.2346
    assert rec["process_index"] == 0 and rec["process_count"] == 1
    assert isinstance(rec["hostname"], str)
    skew = phase_skew([
        {"event": "host_phase", "name": "edit", "seconds": 1.0,
         "process_index": 0},
        {"event": "host_phase", "name": "edit", "seconds": 1.5,
         "process_index": 0},  # same host: accumulates to 2.5
        {"event": "host_phase", "name": "edit", "seconds": 4.0,
         "process_index": 1},
        {"event": "phase", "name": "edit", "seconds": 99.0},  # ignored
        {"event": "host_phase", "seconds": 1.0},  # torn: no name
    ])
    assert skew == {"edit": {"hosts": 2, "min_s": 2.5, "max_s": 4.0,
                             "skew_s": 1.5, "slowest_process": 1}}


# ------------------------------------------------ mesh-compiling (slow) --


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh((1, 8, 1))


@pytest.mark.slow
def test_comm_analysis_record_ring_program(mesh8):
    """The ring-attention ppermute chain becomes a measured quantity:
    nonzero collective-permute count/bytes, the partition count, and the
    schema-stable field set (COMM_ANALYSIS_FIELDS)."""
    from videop2p_tpu.parallel import ring_attention_sharded

    B, H, S, D = 1, 2, 16, 8
    spec = NamedSharding(mesh8, P(None, None, "frames", None))
    sds = jax.ShapeDtypeStruct((B, H, S, D), jnp.float32, sharding=spec)
    jitted = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh8)
    )
    rec = comm_analysis_record(jitted.lower(sds, sds, sds).compile())
    assert rec is not None
    assert set(COMM_ANALYSIS_FIELDS) <= set(rec)
    assert rec["num_partitions"] == 8
    assert rec["collective_permute_count"] > 0
    assert rec["collective_permute_bytes"] > 0
    assert rec["collective_bytes"] >= rec["collective_permute_bytes"]
    assert len(rec["hlo_fingerprint"]) == 16
    assert rec["arg_shardings"]  # the PartitionSpec renderings


@pytest.mark.slow
def test_instrumented_jit_sharded_emits_comm_analysis(tmp_path, mesh8):
    """Sharded calls are first-class obs citizens now: a cache miss on a
    sharded program emits BOTH program_analysis (the re-lowering keeps the
    shardings, so it describes the partitioned program) and comm_analysis
    — where the pre-PR-5 code silently skipped."""
    from videop2p_tpu.obs import instrumented_jit
    from videop2p_tpu.parallel import ring_attention_sharded

    B, H, S, D = 1, 2, 16, 8
    q = jax.device_put(
        jax.random.normal(jax.random.key(0), (B, H, S, D)),
        NamedSharding(mesh8, P(None, None, "frames", None)),
    )
    f = instrumented_jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh8),
        program="ring_probe",
    )
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path, device_info=False):
        f(q, q, q)
        f(q, q, q)  # cache hit: no second analysis
    events = read_ledger(path)
    pa = [e for e in events if e["event"] == "program_analysis"]
    ca = [e for e in events if e["event"] == "comm_analysis"]
    skipped = [e for e in events if e["event"] == "program_analysis_skipped"]
    assert len(pa) == 1 and pa[0]["program"] == "ring_probe"
    assert len(ca) == 1 and ca[0]["program"] == "ring_probe"
    assert ca[0]["num_partitions"] == 8
    assert ca[0]["collective_permute_bytes"] > 0
    assert not skipped


@pytest.mark.slow
def test_replica_divergence_detects_injected_perturbation(mesh8):
    mesh = make_mesh((2, 4, 1))
    x = jnp.zeros((8,))
    # truly replicated over the data axis: divergence exactly 0.0
    div0 = replica_divergence(
        jax.device_put(x, NamedSharding(mesh, P("frames"))),
        mesh, axes=("data",), spec=P("frames"),
    )
    assert float(div0) == 0.0
    # inject a per-data-replica offset UNDER shard_map (out_specs claims
    # replication over data, the values say otherwise — exactly the bug
    # class the probe exists to catch)
    perturbed = shard_map_compat(
        lambda v: v + jax.lax.axis_index("data").astype(jnp.float32) * 0.25,
        mesh=mesh, in_specs=(P("frames"),), out_specs=P("frames"),
    )(x)
    div = replica_divergence(perturbed, mesh, axes=("data",), spec=P("frames"))
    assert float(div) == 0.25
    # no axes to check: constant 0.0 (single-replica meshes)
    assert float(replica_divergence(x, mesh, axes=())) == 0.0
    # tree form takes the worst leaf
    tree = {"a": x, "b": perturbed}
    tdiv = tree_replica_divergence(tree, mesh, axes=("data",))
    assert float(tdiv) == 0.25


@pytest.mark.slow
def test_edit_sample_device_probe_bit_exact_and_cached_replay(mesh8):
    """The probe rides the fused edit scan with the telemetry contract:
    probe-on latents are BIT-EXACT vs probe-off (sharded), divergence is
    0.0 for the replicated working point, and the cached-source replay
    keeps src_err == 0.0 with the probe active."""
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.parallel import latent_sharding, param_shardings, replicated
    from videop2p_tpu.pipelines import make_unet_fn
    from videop2p_tpu.pipelines.fast import cached_fast_edit

    mesh = make_mesh((1, 4, 2))
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    F, STEPS = 4, 2
    x0 = jax.random.normal(jax.random.key(0), (1, F, 8, 8, 4))
    cond = jax.random.normal(jax.random.key(1), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), x0, jnp.asarray(5), cond[:1])
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()
    probe = make_device_probe(mesh)
    assert probe.divergence_axes == ("tensor",)

    s_params = jax.device_put(
        params, param_shardings(mesh, params, tensor_parallel=True)
    )
    s_x0 = jax.device_put(x0, latent_sharding(mesh))
    s_cond = jax.device_put(cond, replicated(mesh))
    s_uncond = jax.device_put(uncond, replicated(mesh))

    def run(p, x, dp):
        return cached_fast_edit(
            fn, p, sched, x, cond[:1], s_cond, s_uncond, None,
            num_inference_steps=STEPS, device_probe=dp,
        )

    traj_off, out_off = jax.jit(lambda p, x: run(p, x, None))(s_params, s_x0)
    traj_on, out_on, dev = jax.jit(lambda p, x: run(p, x, probe))(
        s_params, s_x0
    )
    np.testing.assert_array_equal(np.asarray(out_off), np.asarray(out_on))
    np.testing.assert_array_equal(np.asarray(traj_off), np.asarray(traj_on))
    # the cached replay's exactness pedestal survives the probe
    src_err = float(jnp.max(jnp.abs(out_on[0] - s_x0[0])))
    assert src_err == 0.0
    host_dev = jax.device_get(dev)
    assert host_dev["device_abs_max"].shape == (STEPS, mesh.size)
    assert float(np.max(host_dev["divergence"])) == 0.0
    rec = summarize_device_stats(host_dev, probe.device_ids)
    assert rec["devices"] == mesh.size
    assert rec["divergence_max"] == 0.0 and rec["nan_total"] == 0


@pytest.mark.slow
def test_ring_variant_collective_counts_pinned(mesh8):
    """ISSUE 10 satellite 1: the unrolled rotation loop makes the static
    collective-permute counts TRUE per-pass counts, and the engineered
    schedules are pinned against the serial baseline — overlap issues
    exactly n−1 rotations (the dead final pair is gone), bidir the same
    total bytes at HALF the per-permute payload on both ICI directions."""
    from videop2p_tpu.parallel import ring_attention_sharded

    n = 8
    B, H, S, D = 1, 2, 64, 8
    spec = NamedSharding(mesh8, P(None, None, "frames", None))
    sds = jax.ShapeDtypeStruct((B, H, S, D), jnp.float32, sharding=spec)
    recs = {}
    for variant in ("serial", "overlap", "bidir"):
        jitted = jax.jit(
            lambda q, k, v, _v=variant: ring_attention_sharded(
                q, k, v, mesh8, variant=_v
            )
        )
        recs[variant] = comm_analysis_record(
            jitted.lower(sds, sds, sds).compile()
        )
    serial, overlap, bidir = (recs[v] for v in ("serial", "overlap", "bidir"))
    blk = (B * H * (S // n) * D) * 4  # one K or V block per shard, f32
    assert serial["collective_permute_count"] == 2 * n
    assert serial["collective_permute_bytes"] == 2 * n * blk
    assert overlap["collective_permute_count"] == 2 * (n - 1)
    assert overlap["collective_permute_bytes"] == 2 * (n - 1) * blk
    assert bidir["collective_permute_count"] == 4 * (n - 1)
    assert bidir["collective_permute_bytes"] == overlap["collective_permute_bytes"]
    # per-permute payload halves: both directions carry half blocks
    assert (bidir["collective_permute_bytes"] // bidir["collective_permute_count"]
            == blk // 2)


@pytest.mark.slow
def test_ring_ab_obs_diff_exit_codes(mesh8, tmp_path):
    """The ring before/after comm evidence is obs_diff-gateable: the
    serial→overlap direction passes (counts and bytes DROP), and an
    injected +20% collective-bytes bump on the same label exits 1 with a
    machine-readable comm verdict."""
    from videop2p_tpu.parallel import ring_attention_sharded

    spec = NamedSharding(mesh8, P(None, None, "frames", None))
    sds = jax.ShapeDtypeStruct((1, 2, 64, 8), jnp.float32, sharding=spec)
    recs = {}
    for variant in ("serial", "overlap"):
        jitted = jax.jit(
            lambda q, k, v, _v=variant: ring_attention_sharded(
                q, k, v, mesh8, variant=_v
            )
        )
        recs[variant] = comm_analysis_record(
            jitted.lower(sds, sds, sds).compile()
        )

    def write(path, rec):
        led = RunLedger(str(path), device_info=False)
        led.comm_analysis("ring_attention", rec)
        led.close()

    before, after = tmp_path / "before.jsonl", tmp_path / "after.jsonl"
    write(before, recs["serial"])
    write(after, recs["overlap"])
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", str(before), str(after)]) == 0
    bumped = tmp_path / "bumped.jsonl"
    write(bumped, dict(recs["serial"],
                       collective_bytes=int(recs["serial"]["collective_bytes"] * 1.2)))
    assert obs_diff.main(["obs_diff.py", str(before), str(bumped)]) == 1


@pytest.mark.slow
def test_tp_pairing_unit_halves_reduction_bytes(mesh8):
    """The Megatron row-parallel output unit: the explicit psum_scatter
    seam's reduce-scatter result bytes are the declarative all-reduce's ÷
    tp, at (near-)identical flops — the per-attention-block byte
    reduction of the pairing, measured."""
    import importlib.util as _ilu

    spec = _ilu.spec_from_file_location(
        "graft_under_comm_test", os.path.join(_REPO, "__graft_entry__.py")
    )
    graft = _ilu.module_from_spec(spec)
    spec.loader.exec_module(graft)

    mesh_tp = make_mesh((1, 1, 8))
    recs = graft._tp_unit_records(mesh_tp)
    g, s = recs["gspmd"], recs["scatter"]
    assert g["all_reduce_count"] == 1 and g["all_reduce_bytes"] > 0
    assert s["reduce_scatter_count"] == 1
    assert s["reduce_scatter_bytes"] == g["all_reduce_bytes"] // 8
    assert s["collective_bytes"] < g["collective_bytes"]
    assert g["hlo_fingerprint"] != s["hlo_fingerprint"]
