"""Time-domain observability, latency half (ISSUE 6): the bounded
percentile reservoirs (obs/timing.py), instrumented_jit's opt-in
per-dispatch execute timing, the execute_timing ledger event, the
TIMING_RULES regression gates, and the obs_diff acceptance path —
self-compare exits 0, a scaled-reservoir latency injection exits 1 with
a machine-readable verdict.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from videop2p_tpu.obs import (
    EXECUTE_TIMING_FIELDS,
    TIMING_RULES,
    LatencyReservoir,
    RunLedger,
    evaluate_rules,
    extract_run,
    instrumented_jit,
    percentile,
    read_ledger,
    split_runs,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_timing_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------- reservoirs --


def test_percentile_nearest_rank():
    data = list(range(1, 101))  # 1..100
    assert percentile(data, 50) == 50
    assert percentile(data, 95) == 95
    assert percentile(data, 99) == 99
    assert percentile(data, 100) == 100
    assert percentile(data, 0) == 1
    assert percentile([7.0], 99) == 7.0
    assert percentile([], 50) == 0.0
    # every reported value is an OBSERVED sample, never an interpolation
    assert percentile([1.0, 10.0], 50) in (1.0, 10.0)


def test_reservoir_bounded_exact_count_and_max():
    """Capacity bounds the stored samples; count and the maxima stay
    exact however many samples flow through — a tail spike can never be
    sampled away."""
    r = LatencyReservoir(capacity=8)
    for i in range(1000):
        r.add(0.001 * i, 0.002 * i)
    r.add(5.0, 9.0)  # the spike
    s = r.summary()
    assert set(EXECUTE_TIMING_FIELDS) == set(s)
    assert s["count"] == 1001
    assert s["sampled"] == 8
    assert s["dispatch_max_s"] == 5.0
    assert s["blocked_max_s"] == 9.0
    assert 0 < s["blocked_p50_s"] <= s["blocked_p99_s"] <= s["blocked_max_s"]


def test_reservoir_deterministic_and_scaled():
    def fill():
        r = LatencyReservoir(capacity=16)
        for i in range(500):
            r.add(0.01 + (i % 37) * 1e-4, 0.02 + (i % 37) * 1e-4)
        return r

    a, b = fill(), fill()
    assert a.summary() == b.summary()  # seeded RNG: identical runs agree
    scaled = a.scaled(1.5)
    sa, ss = a.summary(), scaled.summary()
    assert ss["count"] == sa["count"]
    assert ss["blocked_p50_s"] == pytest.approx(sa["blocked_p50_s"] * 1.5)
    assert ss["blocked_max_s"] == pytest.approx(sa["blocked_max_s"] * 1.5)


def test_reservoir_empty_and_invalid():
    assert LatencyReservoir().summary() is None
    with pytest.raises(ValueError):
        LatencyReservoir(capacity=0)


def test_dispatch_fraction_signals_async_overlap():
    r = LatencyReservoir()
    for _ in range(10):
        r.add(0.001, 0.1)  # returns immediately, executes 100 ms
    assert r.summary()["dispatch_fraction"] == pytest.approx(0.01)


# ------------------------------------- instrumented_jit integration --


def test_instrumented_jit_timing_on_emits_execute_timing(tmp_path):
    """--latency path: every dispatch lands in the program's reservoir,
    the close() flush emits ONE execute_timing event per program with
    the pinned schema, and program_call events carry blocked_s."""
    path = str(tmp_path / "ledger.jsonl")
    f = instrumented_jit(lambda x: x * 2 + 1, program="doubler")
    with RunLedger(path, latency=True):
        for _ in range(5):
            f(jnp.ones((8, 8)))
    events = read_ledger(path)
    et = [e for e in events if e["event"] == "execute_timing"]
    assert len(et) == 1
    assert et[0]["program"] == "doubler"
    assert set(EXECUTE_TIMING_FIELDS) <= set(et[0])
    assert et[0]["count"] == 5
    assert et[0]["blocked_p50_s"] >= et[0]["dispatch_p50_s"] * 0 and \
        et[0]["blocked_p50_s"] > 0
    calls = [e for e in events if e["event"] == "program_call"]
    assert len(calls) == 5
    assert all("blocked_s" in c and c["blocked_s"] >= c["dispatch_s"] * 0
               for c in calls)


def test_instrumented_jit_timing_off_is_bit_exact_and_silent(tmp_path):
    """Timing OFF: outputs identical to the timing-on run bit-for-bit,
    no execute_timing event, no blocked_s on program_call — and no
    block_until_ready added to the dispatch path."""
    import numpy as np

    x = jnp.linspace(0.0, 1.0, 64).reshape(8, 8)
    f_off = instrumented_jit(lambda v: jnp.tanh(v @ v), program="p_off")
    f_on = instrumented_jit(lambda v: jnp.tanh(v @ v), program="p_on")
    path_off = str(tmp_path / "off.jsonl")
    path_on = str(tmp_path / "on.jsonl")
    with RunLedger(path_off):
        out_off = f_off(x)
    with RunLedger(path_on, latency=True):
        out_on = f_on(x)
    assert np.array_equal(np.asarray(out_off), np.asarray(out_on))
    kinds_off = [e["event"] for e in read_ledger(path_off)]
    assert "execute_timing" not in kinds_off
    call_off = next(e for e in read_ledger(path_off)
                    if e["event"] == "program_call")
    assert "blocked_s" not in call_off


def test_env_var_enables_timing(tmp_path, monkeypatch):
    monkeypatch.setenv("VIDEOP2P_OBS_LATENCY", "1")
    path = str(tmp_path / "ledger.jsonl")
    f = instrumented_jit(lambda x: x + 1, program="env_timed")
    with RunLedger(path):
        f(jnp.asarray(1.0))
    et = [e for e in read_ledger(path) if e["event"] == "execute_timing"]
    assert len(et) == 1 and et[0]["program"] == "env_timed"


def test_flush_mid_run_supersedes(tmp_path):
    """An explicit mid-run flush plus the close flush: extract_run keeps
    the LAST summary, which covers every dispatch recorded so far."""
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.record_execute("prog", 0.01, 0.02)
        led.flush_execute_timing()
        led.record_execute("prog", 0.01, 0.02)
    events = read_ledger(path)
    et = [e for e in events if e["event"] == "execute_timing"]
    assert [e["count"] for e in et] == [1, 2]
    rec = extract_run(events)
    assert rec["timing"]["prog"]["count"] == 2


# ----------------------------------------------- rules + extraction --


def _timing_ledger(path, run_id, reservoir, trace_fields=None):
    led = RunLedger(path, run_id=run_id, device_info=False)
    led.event("execute_timing", program="edit", **reservoir.summary())
    if trace_fields:
        led.event("trace_analysis", **trace_fields)
    led.close()


def _base_reservoir():
    r = LatencyReservoir()
    for i in range(64):
        r.add(0.010 + (i % 7) * 1e-4, 0.100 + (i % 7) * 1e-3)
    return r


def test_extract_run_timing_and_trace_sections(tmp_path):
    path = str(tmp_path / "a.jsonl")
    _timing_ledger(path, "a", _base_reservoir(), trace_fields={
        "name": "edit_window", "trace_dir": "/tmp/t", "sidecar": "s.npz",
        "device_total_s": 1.5, "compute_s": 1.2, "collective_s": 0.4,
        "overlap_fraction": 0.75, "span_s": 2.0, "idle_s": 0.1,
        "idle_max_s": 0.05, "num_events": 100, "num_ops": 10,
        "module_total_s": 1.6, "module_span_s": 1.9,
        "families": {"fusion": 1.0}, "top_ops": [{"op": "fusion.1"}],
    })
    rec = extract_run(split_runs(read_ledger(path))[0])
    assert rec["timing"]["edit"]["count"] == 64
    assert rec["timing"]["edit"]["blocked_p50_s"] > 0
    t = rec["trace"]["edit_window"]
    assert t["overlap_fraction"] == 0.75
    # strings/arrays stay out of the numeric rule surface
    assert "families" not in t and "sidecar" not in t
    # pre-PR-6 ledgers: the sections exist and are empty
    old = extract_run([{"event": "run_start", "run_id": "old"}])
    assert old["timing"] == {} and old["trace"] == {}
    assert evaluate_rules(old, old)["pass"]


def test_timing_rules_flag_latency_and_overlap_regressions(tmp_path):
    """p50/p99 growth past 25% regresses; an overlap-fraction DROP past
    10% regresses (direction=decrease); self-compare is always clean."""
    base_res = _base_reservoir()
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    trace_a = {"name": "w", "device_total_s": 1.0, "overlap_fraction": 0.8}
    trace_b = {"name": "w", "device_total_s": 1.35, "overlap_fraction": 0.4}
    _timing_ledger(a, "a", base_res, trace_fields=trace_a)
    _timing_ledger(b, "b", base_res.scaled(1.5), trace_fields=trace_b)
    base = extract_run(split_runs(read_ledger(a))[0])
    new = extract_run(split_runs(read_ledger(b))[0])
    assert evaluate_rules(base, base)["pass"]
    res = evaluate_rules(base, new, TIMING_RULES)
    assert not res["pass"]
    regs = {(v["rule"], v["program"]) for v in res["regressions"]}
    assert ("timing:blocked_p50_s+25%", "edit") in regs
    assert ("timing:blocked_p99_s+25%", "edit") in regs
    assert ("trace:device_total_s+20%", "w") in regs
    assert ("trace:overlap_fraction-10%", "w") in regs
    # each verdict is machine-readable with base/new/delta
    for v in res["regressions"]:
        assert {"rule", "kind", "program", "metric", "base", "new",
                "regressed"} <= set(v)


def test_obs_diff_accepts_self_and_rejects_scaled_reservoir(tmp_path, capsys):
    """The ISSUE 6 acceptance gate, via the CLI: self-compare exits 0;
    the +50% scaled-reservoir injection exits 1 and the --json verdict
    names the timing rule."""
    mod = _load_tool("obs_diff")
    base_res = _base_reservoir()
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _timing_ledger(a, "a", base_res)
    _timing_ledger(b, "b", base_res.scaled(1.5))
    assert mod.main(["obs_diff.py", a, a]) == 0
    out = capsys.readouterr().out
    assert "no regressions" in out and "execute timing" in out
    assert mod.main(["obs_diff.py", "--json", a, b]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["pass"] is False
    timing_regs = [v for v in verdict["regressions"]
                   if v["kind"] == "timing"]
    assert timing_regs and all(v["regressed"] for v in timing_regs)


def test_micro_jitter_below_abs_floor_never_regresses(tmp_path):
    """The min_abs floors: a 50% swing on a 0.1 ms dispatch is host
    jitter, not a latency regression."""
    tiny = LatencyReservoir()
    for _ in range(32):
        tiny.add(0.0001, 0.0001)
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _timing_ledger(a, "a", tiny)
    _timing_ledger(b, "b", tiny.scaled(1.5))
    base = extract_run(split_runs(read_ledger(a))[0])
    new = extract_run(split_runs(read_ledger(b))[0])
    assert evaluate_rules(base, new, TIMING_RULES)["pass"]


# -------------------------------------------------- summary renderer --


def test_ledger_summary_renders_timing_and_trace_tables(tmp_path):
    mod = _load_tool("ledger_summary")
    path = str(tmp_path / "ledger.jsonl")
    _timing_ledger(path, "render", _base_reservoir(), trace_fields={
        "name": "edit_window", "device_total_s": 1.5, "compute_s": 1.2,
        "collective_s": 0.4, "overlap_fraction": 0.75, "idle_s": 0.1,
        "num_events": 100, "families": {"fusion": 1.0, "dot": 0.2},
    })
    text = mod.render(read_ledger(path))
    assert "execute timing" in text and "edit" in text
    assert "trace analysis" in text and "edit_window" in text
    assert "0.75" in text
    assert "fusion=1.000s" in text


def test_obs_diff_overlap_fraction_decrease_teeth(tmp_path, capsys):
    """ISSUE 10: overlap is now an ENGINEERED property, so its regression
    direction has CLI teeth — two ledgers identical except for a dropped
    compute/collective overlap_fraction must exit 1 through obs_diff with
    the decrease-direction trace verdict (and the improved direction,
    overlap RISING, exits 0)."""
    mod = _load_tool("obs_diff")
    res = _base_reservoir()
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    trace = {"name": "cached_pair", "device_total_s": 1.0,
             "collective_s": 0.4, "overlap_fraction": 0.8, "idle_s": 0.1}
    _timing_ledger(a, "a", res, trace_fields=trace)
    _timing_ledger(b, "b", res, trace_fields=dict(trace,
                                                  overlap_fraction=0.4))
    assert mod.main(["obs_diff.py", "--json", a, b]) == 1
    verdict = json.loads(capsys.readouterr().out)
    rules = {v["rule"] for v in verdict["regressions"]}
    assert rules == {"trace:overlap_fraction-10%"}
    # the engineered direction — overlap GROWS — is never a regression
    assert mod.main(["obs_diff.py", b, a]) == 0
