"""seq_aligner + schedules golden tests (reference semantics:
/root/reference/seq_aligner.py, /root/reference/ptp_utils.py:258-310)."""

import numpy as np

from videop2p_tpu.control import (
    get_refinement_mapper,
    get_replacement_mapper,
    get_time_words_attention_alpha,
    get_word_inds,
)
from videop2p_tpu.utils.tokenizers import WordTokenizer


def tok():
    return WordTokenizer()


def test_get_word_inds_word_and_index():
    t = tok()
    text = "a silver jeep driving down a curvy road"
    np.testing.assert_array_equal(get_word_inds(text, "jeep", t), [3])
    np.testing.assert_array_equal(get_word_inds(text, 1, t), [2])
    # repeated word: all occurrences
    np.testing.assert_array_equal(get_word_inds(text, "a", t), [1, 6])
    assert get_word_inds(text, "absent", t).size == 0


def test_refinement_mapper_identical_prompts():
    t = tok()
    m, a = get_refinement_mapper(["a cat runs", "a cat runs"], t)
    assert m.shape == (1, 77) and a.shape == (1, 77)
    # perfect alignment → identity mapper with alpha 1 everywhere
    np.testing.assert_array_equal(m[0][:5], [0, 1, 2, 3, 4])
    assert a.min() == 1.0


def test_refinement_mapper_insertion():
    t = tok()
    src = "a rabbit is jumping"
    tgt = "a origami rabbit is jumping"
    m, a = get_refinement_mapper([src, tgt], t)
    # token layout: [BOS, a, origami, rabbit, is, jumping, EOS]
    # 'origami' (pos 2) has no source counterpart → alpha 0
    assert a[0, 2] == 0.0
    # aligned words map back to their source positions
    assert m[0, 1] == 1  # 'a' → 'a'
    assert m[0, 3] == 2  # 'rabbit' → 'rabbit'
    assert m[0, 5] == 4  # 'jumping' → 'jumping'
    # padding region: identity continuation
    n_tgt = len(t.encode(tgt))
    np.testing.assert_array_equal(m[0, n_tgt:], np.arange(n_tgt, 77))
    assert np.all(a[0, n_tgt:] == 1.0)


def test_replacement_mapper_word_swap():
    t = tok()
    src = "a silver jeep driving down a road"
    tgt = "a silver bike driving down a road"
    m = get_replacement_mapper([src, tgt], t)
    assert m.shape == (1, 77, 77)
    m0 = m[0]
    # swapped word: jeep(pos 3) → bike(pos 3)
    assert m0[3, 3] == 1.0
    # all other positions identity
    diag = np.diag(m0)
    assert np.all(diag[:3] == 1.0) and np.all(diag[4:10] == 1.0)
    # each target column sums to 1 over source rows in the prompt region
    np.testing.assert_allclose(m0[:10].sum(axis=0)[:10], np.ones(10), rtol=1e-6)


def test_replacement_mapper_unequal_lengths_raises():
    t = tok()
    import pytest

    with pytest.raises(ValueError):
        get_replacement_mapper(["a cat", "a big cat"], t)


def test_time_words_alpha_default_window():
    t = tok()
    prompts = ["a cat", "a dog"]
    alpha = get_time_words_attention_alpha(prompts, 50, 0.2, t)
    assert alpha.shape == (51, 1, 1, 1, 77)
    # active for steps [0, 10), zero after
    assert np.all(alpha[:10, 0, 0, 0, :] == 1.0)
    assert np.all(alpha[10:, 0, 0, 0, :] == 0.0)


def test_time_words_alpha_per_word_override():
    t = tok()
    prompts = ["a cat runs", "a dog runs"]
    alpha = get_time_words_attention_alpha(
        prompts, 10, {"default_": 0.5, "dog": (0.0, 1.0)}, t
    )
    dog_ind = get_word_inds(prompts[1], "dog", t)[0]
    # dog stays active through all steps; others stop at step 5
    assert np.all(alpha[:, 0, 0, 0, dog_ind] == 1.0)
    other = 1  # word 'a'
    assert np.all(alpha[5:, 0, 0, 0, other] == 0.0)
