"""Legacy image-helper tests (videop2p_tpu/utils/images.py — the port of
/root/reference/ptp_utils.py:26-186): grid/annotation compositing contracts
and the 1-frame controlled text→image path on tiny models."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.utils.images import (
    init_latent,
    latent2image,
    latent2image_video,
    text2image_stable,
    text_under_image,
    view_images,
)


def test_text_under_image_extends_by_fifth():
    img = np.zeros((50, 40, 3), np.uint8)
    out = text_under_image(img, "hi")
    assert out.shape == (60, 40, 3)
    assert (out[:50] == 0).all()  # original pixels intact
    assert out.dtype == np.uint8


def test_view_images_grid_shape_and_padding(tmp_path):
    ims = [np.full((10, 10, 3), i, np.uint8) for i in (10, 20, 30)]
    pil = view_images(ims, num_rows=2, save_path=str(tmp_path / "grid.png"))
    arr = np.asarray(pil)
    # 2 rows × 2 cols (one white filler), offset = int(10 * 0.02) = 0
    assert arr.shape == (20, 20, 3)
    assert arr[0, 0, 0] == 10 and arr[0, 10, 0] == 20 and arr[10, 0, 0] == 30
    assert arr[10, 10, 0] == 255  # filler
    assert (tmp_path / "grid.png").exists()

    single = view_images(np.full((8, 8, 3), 7, np.uint8))
    assert np.asarray(single).shape == (8, 8, 3)


def test_init_latent_expands_shared_xt():
    key = jax.random.key(0)
    latent, latents = init_latent(None, 3, height=64, width=64, key=key)
    assert latent.shape == (1, 8, 8, 4)
    assert latents.shape == (3, 8, 8, 4)
    np.testing.assert_array_equal(latents[0], latents[2])
    # passthrough keeps the provided latent
    latent2, latents2 = init_latent(latent, 2)
    assert latent2 is latent and latents2.shape == (2, 8, 8, 4)
    with pytest.raises(ValueError):
        init_latent(None, 1)


@pytest.fixture(scope="module")
def tiny_vae():
    from videop2p_tpu.models.vae import AutoencoderKL, VAEConfig

    model = AutoencoderKL(config=VAEConfig.tiny())
    x = jnp.zeros((1, 16, 16, 3))
    params = model.init(jax.random.key(0), x, jax.random.key(1))
    return model, params


def test_latent2image_shapes_and_range(tiny_vae):
    vae, params = tiny_vae
    # tiny VAE has 2 resolution levels -> spatial scale factor 2
    z = 0.1 * jax.random.normal(jax.random.key(2), (2, 8, 8, 4))
    img = latent2image(vae, params, z)
    assert img.shape == (2, 16, 16, 3) and img.dtype == np.uint8

    zv = 0.1 * jax.random.normal(jax.random.key(3), (1, 3, 8, 8, 4))
    frames = latent2image_video(vae, params, zv, chunk=2)
    assert frames.shape == (3, 16, 16, 3) and frames.dtype == np.uint8


@pytest.mark.slow
def test_text2image_stable_controlled(tiny_vae):
    from videop2p_tpu.control import make_controller
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn
    from videop2p_tpu.utils.tokenizers import WordTokenizer

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    x = jnp.zeros((1, 1, 8, 8, 4))
    cond = jax.random.normal(jax.random.key(4), (2, 77, cfg.cross_attention_dim))
    params = model.init(jax.random.key(5), x, jnp.asarray(0), cond[:1])
    ctx = make_controller(
        ["a cat", "a dog"],
        WordTokenizer(),
        num_steps=3,
        is_replace_controller=True,
        cross_replace_steps=0.8,
        self_replace_steps=0.5,
    )
    images, latent = text2image_stable(
        make_unet_fn(model),
        params,
        DDIMScheduler.create_sd(),
        *tiny_vae,
        cond,
        jnp.zeros((77, cfg.cross_attention_dim)),
        ctx=ctx,
        num_inference_steps=3,
        height=16,
        width=16,
        vae_scale_factor=2,
        key=jax.random.key(6),
    )
    assert images.shape == (2, 16, 16, 3) and images.dtype == np.uint8
    assert latent.shape == (1, 8, 8, 4)


@pytest.mark.slow
def test_text2image_ldm_controlled():
    """The BERT/VQ-VAE legacy variant (ptp_utils.py:112-139): caller-supplied
    embeddings + a VQ decoder fn around the same controlled denoise scan."""
    from videop2p_tpu.control import make_controller
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn
    from videop2p_tpu.utils.images import text2image_ldm
    from videop2p_tpu.utils.tokenizers import WordTokenizer

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    x = jnp.zeros((1, 1, 8, 8, 4))
    cond = jax.random.normal(jax.random.key(4), (2, 77, cfg.cross_attention_dim))
    params = model.init(jax.random.key(5), x, jnp.asarray(0), cond[:1])
    ctx = make_controller(
        ["a cat", "a dog"], WordTokenizer(), num_steps=3,
        is_replace_controller=True,
        cross_replace_steps=0.8, self_replace_steps=0.5,
    )

    def vq_decode(z):
        # stand-in VQ decoder: nearest-upsample latents to image space
        img = jnp.repeat(jnp.repeat(z[..., :3], 2, axis=1), 2, axis=2)
        return jnp.tanh(img)

    images, latent = text2image_ldm(
        make_unet_fn(model), params, DDIMScheduler.create_sd(), vq_decode,
        cond, jnp.zeros((77, cfg.cross_attention_dim)),
        ctx=ctx, num_inference_steps=3,
        height=16, width=16, vae_scale_factor=2,
        key=jax.random.key(6),
    )
    assert images.shape == (2, 16, 16, 3) and images.dtype == np.uint8
    assert latent.shape == (1, 8, 8, 4)
