"""Fleet-serving tests (ISSUE 11): the pluggable scheduler policies
(``drain`` pinned bit-exact vs the pre-scheduler engine, ``continuous``
iteration-level admission, ``fair`` deficit-round-robin QoS with the
deficit sequence pinned), per-tenant accounting, the batch-order knob,
the multi-replica fleet (cross-replica disk store-hit with zero compile
events), the router (load balancing, breaker avoidance, fleet
aggregation, the 2-replica chaos acceptance), and the loadgen's
per-tenant workload mix.
"""

import importlib.util
import os
import threading
import time
import types

import numpy as np
import pytest

import jax

from videop2p_tpu.serve.batching import Batch, plan_batches
from videop2p_tpu.serve.sched import (
    SCHEDULER_POLICIES,
    ContinuousScheduler,
    DrainScheduler,
    FairScheduler,
    TenantConfig,
    make_scheduler,
    parse_tenants,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_sched_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _item(compat, seq, *, tenant="default", deadline_at=None, arrival_s=None):
    return types.SimpleNamespace(
        compat=compat, seq=seq, tenant=tenant, deadline_at=deadline_at,
        arrival_s=seq * 0.001 if arrival_s is None else arrival_s,
    )


# ------------------------------------------------------- tenant config --


def test_parse_tenants_syntax_and_errors():
    assert parse_tenants(None) == {}
    assert parse_tenants("") == {}
    cfg = parse_tenants("A:5,B:1")
    assert cfg["A"] == TenantConfig(weight=5)
    assert cfg["B"] == TenantConfig(weight=1)
    cfg = parse_tenants("gold:8:0,free:1:2")
    assert cfg["gold"].priority == 0 and cfg["free"].priority == 2
    cfg = parse_tenants('{"A": {"weight": 3, "deadline_s": 2.5}}')
    assert cfg["A"] == TenantConfig(weight=3, deadline_s=2.5)
    with pytest.raises(ValueError, match="name:weight"):
        parse_tenants(":5")
    with pytest.raises(ValueError, match="bad tenant spec"):
        parse_tenants("A:x")
    with pytest.raises(ValueError, match="unknown tenant config"):
        parse_tenants('{"A": {"wight": 3}}')
    with pytest.raises(ValueError, match="weight must be >= 1"):
        TenantConfig(weight=0)


# -------------------------------------------------- plan_batches order --


def test_plan_batches_default_order_unchanged_and_oldest_reorders():
    """Satellite pin: the default plan is byte-identical to the
    pre-ISSUE-11 grouping (first-seen-key chunk order), and
    ``order="oldest"`` reorders CHUNKS by their oldest member so an early
    rare-key singleton no longer delays the dominant key's batch."""
    class It:
        def __init__(self, compat, tag):
            self.compat = compat
            self.tag = tag

    # rare key "r" arrives first, then the dominant "d" flood, then a
    # second "r" straggler that lands in the first r-chunk
    items = [It("r", 0), It("d", 1), It("d", 2), It("d", 3), It("d", 4),
             It("d", 5), It("r", 6)]
    default = plan_batches(items, max_batch=4)
    assert [(p.key, [i.tag for i in p.items]) for p in default] == [
        ("r", [0, 6]), ("d", [1, 2, 3, 4]), ("d", [5]),
    ]
    oldest = plan_batches(items, max_batch=4, order="oldest")
    # same chunks, dispatch order now by oldest member: r(0) then d(1)
    # then d(5) — and with the rare head REMOVED, the dominant batch jumps
    # the singleton straggler
    assert [(p.key, [i.tag for i in p.items]) for p in oldest] == [
        ("r", [0, 6]), ("d", [1, 2, 3, 4]), ("d", [5]),
    ]
    tail = plan_batches(items[1:], max_batch=4, order="oldest")
    assert [(p.key, [i.tag for i in p.items]) for p in tail] == [
        ("d", [1, 2, 3, 4]), ("d", [5]), ("r", [6]),
    ]
    # explicit arrival values override positional order (reversed clock:
    # the d[5] singleton chunk now predates the d[1..4] chunk)
    arr = plan_batches(
        items, max_batch=4, order="oldest",
        arrival_fn=lambda it: 10 - it.tag,
    )
    assert [(p.key, [i.tag for i in p.items]) for p in arr] == [
        ("r", [0, 6]), ("d", [5]), ("d", [1, 2, 3, 4]),
    ]
    with pytest.raises(ValueError, match="first_seen.*oldest"):
        plan_batches(items, order="newest")


# --------------------------------------------------- scheduler units ----


def test_make_scheduler_factory_and_validation():
    assert set(SCHEDULER_POLICIES) == {"drain", "continuous", "fair"}
    for policy, cls in (("drain", DrainScheduler),
                        ("continuous", ContinuousScheduler),
                        ("fair", FairScheduler)):
        s = make_scheduler(policy, max_batch=2)
        assert isinstance(s, cls) and s.name == policy
        assert s.snapshot()["policy"] == policy
    with pytest.raises(ValueError, match="unknown scheduler"):
        make_scheduler("lifo")
    with pytest.raises(ValueError, match="first_seen"):
        make_scheduler("drain", order="newest")
    # drain keeps the plan boundary; continuous/fair re-collect per batch
    assert not DrainScheduler().preemptive
    assert ContinuousScheduler().preemptive and FairScheduler().preemptive


def test_drain_scheduler_plans_exactly_like_plan_batches():
    """The bit-exact compatibility baseline at the unit level: the drain
    policy's batch sequence IS plan_batches over the collected window —
    same grouping, same chunking, same padding, same order."""
    sched = DrainScheduler(max_batch=4)
    items = [_item("a", 1), _item("b", 2), _item("a", 3), _item("a", 4),
             _item("b", 5), _item("a", 6), _item("a", 7)]
    sched.add(items)
    got = []
    while True:
        plan = sched.next_plan(0.0, queue_empty=True)
        if plan is None:
            break
        got.append((plan.key, [p.seq for p in plan.items], plan.padded_size))
    want = [(b.key, [p.seq for p in b.items], b.padded_size)
            for b in plan_batches(items, max_batch=4)]
    assert got == want
    assert got == [("a", [1, 3, 4, 6], 4), ("a", [7], 1), ("b", [2, 5], 2)]
    assert sched.pending() == 0


def test_continuous_scheduler_deadline_order_and_partial_dispatch():
    now = 100.0
    sched = ContinuousScheduler(max_batch=4)
    # an urgent deadline jumps an earlier undeadlined arrival
    sched.add([_item("a", 1), _item("b", 2, deadline_at=now + 0.5),
               _item("a", 3)])
    plan = sched.next_plan(now, queue_empty=True)
    assert plan.key == "b" and [p.seq for p in plan.items] == [2]
    # remaining "a" items form a partial batch, dispatched immediately
    # because nothing else is queued
    plan = sched.next_plan(now, queue_empty=True)
    assert plan.key == "a" and [p.seq for p in plan.items] == [1, 3]
    assert plan.padded_size == 2
    assert sched.next_plan(now, queue_empty=True) is None
    # with more work already queued, a partial batch HOLDS so the queued
    # work can join the dispatch (iteration-level admission)
    sched.add([_item("a", 4)])
    assert sched.next_plan(now, queue_empty=False) is None
    sched.add([_item("a", 5), _item("a", 6), _item("a", 7)])
    plan = sched.next_plan(now, queue_empty=False)
    assert [p.seq for p in plan.items] == [4, 5, 6, 7]  # full → dispatch


def test_continuous_scheduler_bounded_formation_wait():
    sched = ContinuousScheduler(max_batch=4, max_batch_wait_s=0.2)
    sched.add([_item("a", 1, arrival_s=10.0)])
    # inside the hold window a partial batch waits for fill...
    assert sched.next_plan(10.1, queue_empty=True) is None
    # ...but the wait is BOUNDED: past it, the partial dispatches
    plan = sched.next_plan(10.25, queue_empty=True)
    assert [p.seq for p in plan.items] == [1]


def test_fair_scheduler_deficit_round_robin_pinned():
    """THE fair-queuing pin: tenants A (weight 5) and B (weight 1) under
    saturation. The DRR grant/spend sequence — and therefore the exact
    per-batch tenant interleave — is deterministic and pinned: B's lane
    gets service every round even though A outweighs it 5:1 (nonzero
    starved-tenant throughput), and the deficit counters take exactly the
    grant − spend values."""
    sched = FairScheduler(
        max_batch=4, tenants=parse_tenants("A:5,B:1"),
    )
    sched.add([_item("x", i, tenant="A") for i in range(1, 8)])     # 7 A's
    sched.add([_item("x", i, tenant="B") for i in range(101, 104)])  # 3 B's
    seq = []
    deficits = []
    while sched.pending():
        plan = sched.next_plan(0.0, queue_empty=True)
        seq.append((plan.items[0].tenant, [p.seq for p in plan.items]))
        deficits.append(dict(sched._deficit))
    # round 1: grant A+=5, B+=1 → A spends 4 (max_batch cap) then 1;
    # B spends its 1; round 2: grant again → A finishes (lane empties →
    # deficit resets), B drains on its accumulated credit
    assert seq == [
        ("A", [1, 2, 3, 4]),
        ("A", [5]),
        ("B", [101]),
        ("A", [6, 7]),
        ("B", [102]),
        ("B", [103]),
    ]
    # pinned counters after each batch (A's entry disappears when its
    # lane empties — classic DRR reset)
    assert deficits[0] == {"A": 1.0, "B": 1.0}
    assert deficits[1] == {"A": 0.0, "B": 1.0}
    assert deficits[2] == {"A": 0.0, "B": 0.0}
    assert deficits[3] == {"B": 1.0}
    assert deficits[4] == {"B": 0.0}
    assert deficits[5] == {}
    # B was served before A's backlog drained: nonzero throughput for the
    # starved low-weight tenant
    assert seq[2][0] == "B" and any(t == "A" for t, _ in seq[3:])


def test_fair_scheduler_priority_orders_within_round():
    sched = FairScheduler(max_batch=2,
                          tenants=parse_tenants("low:1:5,high:1:0"))
    sched.add([_item("x", 1, tenant="low"), _item("x", 2, tenant="high")])
    first = sched.next_plan(0.0, queue_empty=True)
    second = sched.next_plan(0.0, queue_empty=True)
    # equal weights: priority 0 scans first, but the low lane still
    # drains in the same round (no starvation)
    assert first.items[0].tenant == "high"
    assert second.items[0].tenant == "low"


def test_tenant_cycle_deterministic_weighted_mix():
    loadgen = _load_tool("serve_loadgen")
    cyc = loadgen.tenant_cycle({"A": 3, "B": 1}, 8)
    assert cyc == loadgen.tenant_cycle({"A": 3, "B": 1}, 8)  # deterministic
    assert cyc.count("A") == 6 and cyc.count("B") == 2       # exact ratio
    assert cyc[0] == "A" and "B" in cyc[:4]                  # interleaved
    assert loadgen.tenant_cycle({}, 3) == ["default"] * 3
    assert loadgen.parse_tenant_weights("A:5,B:1") == {"A": 5, "B": 1}
    with pytest.raises(ValueError, match="name:weight"):
        loadgen.parse_tenant_weights(":3")


# ------------------------------------------------ engines (tiny, CPU) ---

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)
_PROMPTS = ("a rabbit is jumping", "a origami rabbit is jumping")


@pytest.fixture(scope="module")
def programs():
    """One warm tiny ProgramSet shared by every engine in this module —
    single-host replicas share compiled programs exactly like this."""
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps.warm(_PROMPTS, batch_sizes=(2,))
    return ps


def _request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt=_PROMPTS[0],
              prompts=list(_PROMPTS), save_name="sched")
    kw.update(overrides)
    return EditRequest(**kw)


def _engine(programs, tmp_root, name, **kw):
    from videop2p_tpu.serve import EditEngine, ProgramSpec

    return EditEngine(
        ProgramSpec(**_SPEC_KW), out_dir=os.path.join(tmp_root, name),
        programs=programs, keep_videos=True, **kw,
    )


def test_drain_engine_pinned_bit_exact_vs_direct_program(programs, tmp_path):
    """THE compatibility acceptance: the drain-scheduler engine's output
    is BIT-IDENTICAL to the direct warm-program dispatch (exactly what the
    pre-scheduler engine executed per request), the cached replay keeps
    ``src_err == 0.0``, and a repeat request is a store hit with zero new
    compile events."""
    from videop2p_tpu.data import load_frame_sequence

    ps = programs
    # the golden: resolve + dispatch by hand through the same warm
    # programs the pre-refactor engine drove
    frames = load_frame_sequence("data/rabbit", size=ps.spec.width,
                                 num_frames=ps.spec.video_len)
    latents = ps.encode(ps.frames_to_video(frames), jax.random.key(0))
    _, ik = jax.random.split(jax.random.key(0))
    ctx = ps.controller(list(_PROMPTS))
    cached = ps.invert_capture(
        latents, ps.encode_prompts(list(_PROMPTS[:1])), ctx, ik
    )[1]
    golden, golden_err = ps.edit_decode(
        cached, ps.encode_prompts(list(_PROMPTS)),
        ps.encode_prompts([""])[0], ctx, latents,
    )
    eng = _engine(ps, str(tmp_path), "drain", scheduler="drain")
    try:
        assert eng.scheduler.name == "drain"
        r1 = eng.result(eng.submit(_request()), wait_s=300.0)
        assert r1["status"] == "done", r1.get("error")
        assert r1["src_err"] == 0.0 and float(golden_err) == 0.0
        assert np.array_equal(eng.videos(r1["id"]), np.asarray(golden))
        r2 = eng.result(eng.submit(_request()), wait_s=300.0)
        assert r2["status"] == "done" and r2["store_hit"] is True
        assert r2["compile_events"] == 0 and r2["src_err"] == 0.0
        assert np.array_equal(eng.videos(r2["id"]), np.asarray(golden))
        # queue-wait telemetry landed (the continuous-vs-drain metric)
        assert r2["queue_wait_s"] >= 0.0
        assert eng.health_record()["queue_wait_mean_s"] >= 0.0
    finally:
        eng.close()


def test_continuous_engine_admits_midflight_requests(programs, tmp_path):
    """The iteration-level-admission acceptance: requests arriving while
    the worker is busy join ONE later dispatch (observed batch_size > 1)
    instead of draining as singletons the way drain with a zero window
    would."""
    eng = _engine(programs, str(tmp_path), "cont", scheduler="continuous",
                  max_batch=4)
    try:
        # first request occupies the worker (fresh inversion of the clip)
        r1 = eng.submit(_request(seed=31))
        # these arrive mid-flight; the continuous policy collects them
        # together after the in-flight dispatch and batches them
        r2 = eng.submit(_request(seed=32))
        r3 = eng.submit(_request(seed=32, save_name="sched2"))
        recs = [eng.result(r, wait_s=300.0) for r in (r1, r2, r3)]
        for rec in recs:
            assert rec["status"] == "done", rec.get("error")
            assert rec["src_err"] == 0.0
        assert max(rec["batch_size"] for rec in recs) >= 2
        assert eng.health_record()["scheduler"] == "continuous"
    finally:
        eng.close()


def test_fair_engine_serves_both_tenants_with_accounting(programs, tmp_path):
    """Fair policy end-to-end: a saturating high-weight tenant cannot
    starve the low-weight one, and per-tenant outcomes land in
    health_record()/metrics() (the serve_health "tenants" map)."""
    eng = _engine(programs, str(tmp_path), "fair", scheduler="fair",
                  tenants="A:5,B:1", max_batch=2)
    try:
        rids = [eng.submit(_request(seed=41, tenant="A")) for _ in range(4)]
        rids += [eng.submit(_request(seed=41, tenant="B"))]
        rids += [eng.submit(_request(seed=41, tenant="A")) for _ in range(2)]
        recs = [eng.result(r, wait_s=300.0) for r in rids]
        for rec in recs:
            assert rec["status"] == "done", rec.get("error")
        health = eng.health_record()
        assert health["scheduler"] == "fair"
        tenants = health["tenants"]
        assert tenants["A"]["done"] == 6 and tenants["B"]["done"] == 1
        assert tenants["B"]["error_rate"] == 0.0
        assert eng.metrics()["scheduler"]["policy"] == "fair"
        # the per-tenant deadline budget applies where the request has none
        eng2 = _engine(programs, str(tmp_path), "fair2", scheduler="fair",
                       tenants='{"slow": {"weight": 1, "deadline_s": 99.0}}')
        try:
            rid = eng2.submit(_request(seed=41, tenant="slow"))
            assert eng2.poll(rid)["deadline_s"] == 99.0
            assert eng2.result(rid, wait_s=300.0)["status"] == "done"
        finally:
            eng2.close()
    finally:
        eng.close()


def test_continuous_queue_wait_below_drain_on_same_trace(programs, tmp_path):
    """The ISSUE-11 latency acceptance: on the same closed-loop trace the
    continuous policy's mean queue wait is below drain's (drain holds
    every lone request for its full admit window; continuous dispatches
    the moment the queue is idle) — recorded in the ledger and gated
    through obs_diff (self-compare exit 0)."""
    loadgen = _load_tool("serve_loadgen")
    req = _request(seed=51).to_dict()
    waits = {}
    ledgers = {}
    for policy, kw in (("drain", dict(max_wait_s=0.25)),
                       ("continuous", dict())):
        eng = _engine(programs, str(tmp_path), f"qw_{policy}",
                      scheduler=policy, **kw)
        try:
            ledger_path = str(tmp_path / f"qw_{policy}.jsonl")
            record = loadgen.run_loadgen(
                loadgen._InprocTarget(eng, timeout_s=300.0), req,
                requests=3, concurrency=1, ledger_path=ledger_path,
                meta={"scheduler": policy},
                collect_extra=lambda rec, eng=eng: [
                    {"event": "serve_health", **eng.health_record()}
                ],
            )
            assert record["done"] == 3, record
            waits[policy] = eng.health_record()["queue_wait_mean_s"]
            ledgers[policy] = ledger_path
        finally:
            eng.close()
    # drain waited its 0.25 s window per lone request; continuous ~0
    assert waits["continuous"] < waits["drain"], waits
    # the metric is in the ledger (serve_health.queue_wait_mean_s) and the
    # run gates clean through obs_diff
    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs

    for policy, path in ledgers.items():
        rel = extract_run(split_runs(read_ledger(path))[-1])["reliability"]
        assert rel["serve"]["queue_wait_mean_s"] == pytest.approx(
            waits[policy], abs=1e-3)
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", ledgers["continuous"],
                          ledgers["continuous"]]) == 0


# --------------------------------------------------- fleet + router -----


@pytest.fixture(scope="module")
def fleet(programs, tmp_path_factory):
    """Two inproc replicas over ONE shared disk inversion-store root,
    behind a router's HTTP front door."""
    from videop2p_tpu.serve import ReplicaSupervisor, Router, RouterServer

    root = tmp_path_factory.mktemp("fleet")
    sup = ReplicaSupervisor(
        programs.spec, 2, out_dir=str(root), programs=programs,
        warm_prompts=_PROMPTS, engine_kwargs=dict(keep_videos=True),
    )
    sup.start()
    router = Router(sup.urls, probe_ttl_s=0.05,
                    ledger_path=str(root / "router_ledger.jsonl"))
    server = RouterServer(router).start()
    yield sup, router, server
    server.close()
    sup.stop()


def test_cross_replica_disk_store_hit_zero_compiles(fleet):
    """THE fleet acceptance: a request inverted on replica A is a DISK
    store-hit on replica B (shared content-addressed root) — rebuilt
    through B's warm programs with src_err == 0.0, ZERO new compile
    events, no fresh inversion-from-frames, and bit-identical videos."""
    sup, _, _ = fleet
    eng_a = sup.replicas[0].engine
    eng_b = sup.replicas[1].engine
    req = _request(seed=61)
    ra = eng_a.result(eng_a.submit(req), wait_s=300.0)
    assert ra["status"] == "done", ra.get("error")
    assert ra["store_source"] == "fresh" and ra["src_err"] == 0.0
    rb = eng_b.result(eng_b.submit(_request(seed=61)), wait_s=300.0)
    assert rb["status"] == "done", rb.get("error")
    assert rb["store_hit"] is True and rb["store_source"] == "disk"
    assert rb["src_err"] == 0.0
    assert rb["compile_events"] == 0
    assert eng_b.counters["rehydrations"] == 1
    assert eng_b.counters["fresh_inversions"] == 0
    assert rb["store_key"] == ra["store_key"]
    assert np.array_equal(eng_a.videos(ra["id"]), eng_b.videos(rb["id"]))


def test_router_http_roundtrip_and_fleet_aggregation(fleet):
    from videop2p_tpu.serve.client import EngineClient, engine_available
    from videop2p_tpu.serve.router import ROUTER_HEALTH_FIELDS

    sup, router, server = fleet
    client = EngineClient(server.url)
    assert engine_available(server.url)
    health = client.healthz()
    assert health["ok"] and health["healthy"] == 2 and health["total"] == 2
    assert set(health["replicas"]) == {"replica0", "replica1"}
    rid = client.submit(_request(seed=62).to_dict())
    rec = client.wait(rid, timeout_s=300.0)
    assert rec["status"] == "done" and rec["src_err"] == 0.0
    assert rec["replica"] in ("replica0", "replica1")
    # the server-side wait endpoint proxies to the owning replica
    rec_srv = client.result(rid, wait_s=5.0)
    assert rec_srv["status"] == "done" and rec_srv["id"] == rid
    metrics = client.metrics()
    assert metrics["router"]["routed"] >= 1
    assert set(metrics["replicas"]) == {"replica0", "replica1"}
    assert metrics["requests"].get("done", 0) >= 1
    # machine-readable surfaces: 404 unknown id, 400 malformed body
    with pytest.raises(RuntimeError, match="404"):
        client.poll("feedfacefeed")
    with pytest.raises(RuntimeError, match="400"):
        client.submit({"prompt": "a", "bogus": True})
    record = router.health_record()
    assert set(ROUTER_HEALTH_FIELDS) <= set(record)
    assert record["replicas"] == 2 and record["routed"] >= 1


def test_router_chaos_sheds_to_healthy_replica(programs, tmp_path):
    """THE 2-replica chaos acceptance: replica 0 sits in a FaultPlan
    unavailable window (every dispatch raises backend-unavailable, its
    breaker trips OPEN), and the ROUTER keeps the fleet serving — success
    rate over the loadgen trace stays >= the threshold because traffic
    sheds to the healthy replica, the router's routed_around counter
    proves the avoidance, and the run's reliability (per-replica
    serve_health + router_health) gates through obs_diff exit 0 on
    self-compare."""
    from videop2p_tpu.serve import ReplicaSupervisor, Router, RouterServer

    loadgen = _load_tool("serve_loadgen")
    root = str(tmp_path)
    sup = ReplicaSupervisor(
        programs.spec, 2, out_dir=root, programs=programs,
        warm_prompts=_PROMPTS,
        engine_kwargs=dict(max_retries=0, breaker_threshold=1,
                           breaker_open_s=60.0),
        faults={0: "unavail@1-999"},
    )
    sup.start()
    router = Router(sup.urls, probe_ttl_s=0.05, suspend_s=5.0)
    server = RouterServer(router).start()
    try:
        ledger_path = str(tmp_path / "chaos.jsonl")

        def collect_extra(record):
            events = []
            for r in sup.replicas:
                events += [dict(e) for e in r.engine.fault_log]
                events.append({"event": "serve_health", "label": r.name,
                               **r.engine.health_record()})
            record["router"] = router.health_record()
            events.append({"event": "router_health", **record["router"]})
            return events

        record = loadgen.run_loadgen(
            loadgen._HttpTarget(server.url, timeout_s=300.0),
            _request(seed=63).to_dict(),
            requests=8, concurrency=2, ledger_path=ledger_path,
            meta={"target": "router-chaos"}, collect_extra=collect_extra,
        )
    finally:
        server.close()
        sup.stop()
    # the faulted replica doomed at most its pre-breaker requests; the
    # fleet stayed above threshold because the router shed to replica 1
    assert record["success_rate"] >= 0.6, record
    assert record["router"]["routed_around"] >= 1
    assert record["router"]["healthy"] == 1
    assert sup.replicas == []  # stopped
    # replica 0's breaker genuinely opened and was ledgered
    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs

    rec = extract_run(split_runs(read_ledger(ledger_path))[-1])
    rel = rec["reliability"]
    assert rel["replica0"]["breaker_trips"] >= 1
    assert rel["replica1"]["errors"] == 0
    assert rel["router"]["routed_around"] >= 1
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", ledger_path, ledger_path]) == 0


def test_router_wedged_replica_probe_timeout_routes_around(tmp_path):
    """ISSUE 12 satellite: a WEDGED replica — one that accepts TCP
    connections but never answers — must cost the router its short
    probe timeout once and then be routed AROUND, not hang the router
    thread for the full request timeout. Proxied polls against the wedge
    are bounded the same way and mark it suspect."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from videop2p_tpu.serve.router import Router

    class _Wedged(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):  # noqa: N802 — accept, then never answer
            time.sleep(60.0)

        do_POST = do_GET  # noqa: N815

    class _Healthy(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _send(self, payload):
            body = _json.dumps(payload).encode()
            self.send_response(200 if self.command == "GET" else 202)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._send({"ok": True, "status": "ok"})
            else:
                self._send({"queue_depth": 0, "in_flight": 0})

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", "0")))
            self._send({"id": "feedfacefeed"})

    wedged = ThreadingHTTPServer(("127.0.0.1", 0), _Wedged)
    healthy = ThreadingHTTPServer(("127.0.0.1", 0), _Healthy)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (wedged, healthy)]
    for t in threads:
        t.start()
    urls = [f"http://127.0.0.1:{wedged.server_address[1]}",
            f"http://127.0.0.1:{healthy.server_address[1]}"]
    router = Router(urls, timeout_s=2.0, probe_timeout_s=0.4,
                    probe_ttl_s=0.0, suspend_s=5.0, max_retries=0)
    try:
        t0 = time.perf_counter()
        out = router.submit({"prompt": "a", "prompts": ["a", "b"],
                             "image_path": "x"})
        elapsed = time.perf_counter() - t0
        # the healthy replica took it, and fast — the wedge cost one short
        # probe, not the 60 s it would happily have absorbed
        assert out["replica"] == "replica1"
        assert elapsed < 10.0, f"router hung {elapsed:.1f}s behind the wedge"
        assert router.counters["routed_around"] == 1
        health = router.healthz()
        assert health["replicas"]["replica0"]["status"] == "unreachable"
        assert health["replicas"]["replica1"]["ok"]
        # proxied poll against the wedge: bounded by the hard socket
        # timeout, surfaces as a proxy error and suspends the replica
        with router._lock:
            router._rid_map["deadbeef0000"] = router.views[0]
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="unreachable while proxying"):
            router.poll("deadbeef0000")
        assert time.perf_counter() - t0 < 10.0
        assert router.counters["proxy_errors"] == 1
        assert router.views[0].suspended
    finally:
        wedged.shutdown()
        healthy.shutdown()
        wedged.server_close()
        healthy.server_close()


def test_loadgen_per_tenant_mix_and_stats(programs, tmp_path):
    """Loadgen satellite: the --tenants weighted mix assigns tenants
    deterministically, per-tenant p50/p99 + shed-rate land in the summary
    AND the ledger (per-tenant reservoirs + the engine's per-tenant
    serve_health sub-records)."""
    loadgen = _load_tool("serve_loadgen")
    eng = _engine(programs, str(tmp_path), "mix", scheduler="fair",
                  tenants="A:3,B:1")
    try:
        ledger_path = str(tmp_path / "mix.jsonl")
        record = loadgen.run_loadgen(
            loadgen._InprocTarget(eng, timeout_s=300.0),
            _request(seed=71).to_dict(),
            requests=4, concurrency=2, ledger_path=ledger_path,
            meta={"target": "mix"},
            tenants={"A": 3, "B": 1},
            collect_extra=lambda rec: [
                {"event": "serve_health", **eng.health_record()}
            ],
        )
        assert record["done"] == 4
        per = record["tenants"]
        assert per["A"]["requests"] == 3 and per["B"]["requests"] == 1
        assert per["A"]["done"] == 3 and per["B"]["done"] == 1
        assert per["A"]["p50_s"] > 0.0 and per["A"]["shed_rate"] == 0.0
        # engine-side accounting agrees with the client-side view
        tenants = eng.health_record()["tenants"]
        assert tenants["A"]["done"] == 3 and tenants["B"]["done"] == 1
    finally:
        eng.close()
    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs

    rec = extract_run(split_runs(read_ledger(ledger_path))[-1])
    assert rec["timing"]["loadgen_request_A"]["count"] == 3
    assert rec["timing"]["loadgen_request_B"]["count"] == 1
    assert rec["reliability"]["serve:tenant:A"]["done"] == 3
