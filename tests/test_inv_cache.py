"""Inversion-product cache identity: the fingerprint must track CONTENT.

VERDICT r4 item 8 / advisor: a fingerprint keyed on (relpath, size,
mtime_ns) alone falsely HITS when bytes change under a preserved mtime
(``rsync -t`` restores, archive extraction, ``cp -p`` of a same-size file)
— silently replaying a stale inversion trajectory for different content.
The round-5 fingerprint mixes a head/tail content sample per file.
"""

import os

import numpy as np

from videop2p_tpu.utils.inv_cache import (
    content_fingerprint,
    inversion_cache_key,
    load_inversion,
    save_inversion,
)


def _write(path, data: bytes, mtime_ns: int | None = None):
    with open(path, "wb") as f:
        f.write(data)
    if mtime_ns is not None:
        os.utime(path, ns=(mtime_ns, mtime_ns))


def test_content_change_with_preserved_mtime_and_size_misses(tmp_path):
    """The advisor's exact scenario: same path, same size, same mtime,
    different bytes — the fingerprint MUST change."""
    p = tmp_path / "weights.bin"
    t = 1_700_000_000_000_000_000
    _write(str(p), b"A" * 10_000, t)
    fp_before = content_fingerprint(str(p))
    _write(str(p), b"B" * 10_000, t)  # same size, mtime restored
    assert content_fingerprint(str(p)) != fp_before


def test_tail_only_change_in_large_file_misses(tmp_path):
    """A >8 KiB file whose only change is in the LAST bytes (e.g. appended
    optimizer state overwritten in place) must still miss."""
    p = tmp_path / "shard.bin"
    t = 1_700_000_000_000_000_000
    blob = bytearray(os.urandom(1 << 20))
    _write(str(p), bytes(blob), t)
    fp_before = content_fingerprint(str(p))
    blob[-1] ^= 0xFF
    _write(str(p), bytes(blob), t)
    assert content_fingerprint(str(p)) != fp_before


def test_interior_only_change_in_large_file_misses(tmp_path):
    """A structured checkpoint shard whose only change is a mid-file tensor
    keeps its header and trailer bytes — the quarter-point samples must
    catch it."""
    p = tmp_path / "model.safetensors"
    t = 1_700_000_000_000_000_000
    blob = bytearray(os.urandom(1 << 20))
    _write(str(p), bytes(blob), t)
    fp_before = content_fingerprint(str(p))
    mid = len(blob) // 2
    blob[mid] ^= 0xFF  # one byte at the exact midpoint
    _write(str(p), bytes(blob), t)
    assert content_fingerprint(str(p)) != fp_before


def test_identical_tree_fingerprints_stably(tmp_path):
    d = tmp_path / "ckpt"
    d.mkdir()
    t = 1_700_000_000_000_000_000
    _write(str(d / "a.bin"), b"aaaa", t)
    _write(str(d / "b.bin"), b"bbbb", t)
    assert content_fingerprint(str(d)) == content_fingerprint(str(d))


def test_dir_fingerprint_ignores_own_results(tmp_path):
    """Stage-2 writes results INSIDE the checkpoint dir; a run's own outputs
    must not churn the key."""
    d = tmp_path / "ckpt"
    (d / "results_dpFalse").mkdir(parents=True)
    _write(str(d / "w.bin"), b"w" * 100)
    fp = content_fingerprint(str(d))
    _write(str(d / "results_dpFalse" / "out.gif"), b"gif")
    assert content_fingerprint(str(d)) == fp


def test_missing_path_fingerprints_distinctly(tmp_path):
    fp_missing = content_fingerprint(str(tmp_path / "nope"))
    _write(str(tmp_path / "real.bin"), b"x")
    assert content_fingerprint(str(tmp_path / "real.bin")) != fp_missing


def test_roundtrip_and_key_sensitivity(tmp_path):
    key = inversion_cache_key(clip="c", prompt="p", steps=50, ckpt="f1")
    assert key != inversion_cache_key(clip="c", prompt="p", steps=50, ckpt="f2")
    traj = np.arange(12, dtype=np.float32).reshape(3, 4)
    save_inversion(str(tmp_path), key, traj)
    hit = load_inversion(str(tmp_path), key, want_null=False)
    assert hit is not None
    np.testing.assert_array_equal(hit[0], traj)
    assert load_inversion(str(tmp_path), "feedbeef00000000", want_null=False) is None
