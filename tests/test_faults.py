"""Fault-tolerant serving tests (ISSUE 9): the deterministic fault plan,
retry policy and circuit-breaker state machine, deadline admission/expiry
and the dispatch watchdog, 429/503 HTTP semantics (incl. ``Retry-After``
and the retry-aware client), crash-recovery rehydration from the persisted
store (``src_err == 0.0``, zero new inversions), corrupt-entry detection,
``EditEngine.close()`` draining to terminal ``engine_closed``, the chaos
loadgen, and the ``FAULT_RULES`` / ``serve_health`` gate through
``tools/obs_diff.py`` (exit 0 healthy, exit 1 on injected regression).
"""

import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from videop2p_tpu.serve.faults import (
    BackendUnavailableError,
    CircuitBreaker,
    DeadlineExceeded,
    EngineUnavailable,
    FaultPlan,
    QueueFull,
    RetryPolicy,
    TransientDispatchError,
    is_transient,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_fault_test", os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ fault plan --


def test_fault_plan_parses_dsl_and_json():
    p = FaultPlan.parse("fail@2,fail@3,hang@4:1.5,unavail@6-8,corrupt:*")
    assert p.fail == frozenset({2, 3})
    assert p.hang == {4: 1.5}
    assert p.unavail == (6, 8)
    assert p.corrupt == ("*",)
    j = FaultPlan.parse(
        '{"fail": [1], "hang": {"2": 0.5}, "unavail": [3, 4], "corrupt": ["ab"]}'
    )
    assert j.fail == frozenset({1}) and j.hang == {2: 0.5}
    assert j.unavail == (3, 4) and j.corrupt == ("ab",)
    assert FaultPlan.parse(None) is None and FaultPlan.parse("  ") is None
    with pytest.raises(ValueError, match="bad fault directive"):
        FaultPlan.parse("explode@7")


def test_fault_plan_env_activation(monkeypatch):
    from videop2p_tpu.serve.faults import FAULTS_ENV

    monkeypatch.delenv(FAULTS_ENV, raising=False)
    assert FaultPlan.from_env() is None
    monkeypatch.setenv(FAULTS_ENV, "fail@1")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.fail == frozenset({1})


def test_fault_plan_counter_is_deterministic_and_observable():
    """The plan owns its 1-based attempt counter (fresh plan -> fresh
    schedule, independent of engine history) and reports every injection
    through on_inject as it fires."""
    seen = []
    p = FaultPlan.parse("fail@2,unavail@3-4")
    p.on_inject = lambda kind, **f: seen.append((kind, f.get("attempt")))
    assert p.on_dispatch() == 1  # clean
    with pytest.raises(TransientDispatchError, match="injected"):
        p.on_dispatch()
    with pytest.raises(BackendUnavailableError, match="injected"):
        p.on_dispatch()
    with pytest.raises(BackendUnavailableError):
        p.on_dispatch()
    assert p.on_dispatch() == 5  # window over
    assert seen == [("dispatch_fail", 2), ("backend_unavailable", 3),
                    ("backend_unavailable", 4)]
    assert p.attempts == 5
    # corruption matches by substring; '*' matches everything
    assert not p.corrupts("anything")
    q = FaultPlan.parse("corrupt:abc")
    assert q.corrupts("xx-abc-yy") and not q.corrupts("zzz")
    assert FaultPlan.parse("corrupt:*").corrupts("whatever")


def test_retry_policy_schedule_is_capped_and_jitter_free():
    r = RetryPolicy(max_retries=4, base_s=0.1, cap_s=0.45)
    assert r.schedule() == [0.1, 0.2, 0.4, 0.45]
    assert r.schedule() == r.schedule()  # deterministic by construction
    assert RetryPolicy(max_retries=0).schedule() == []


def test_is_transient_classification():
    assert is_transient(TransientDispatchError("injected"))
    assert is_transient(BackendUnavailableError("injected"))
    assert is_transient(RuntimeError("backend UNAVAILABLE: socket closed"))
    assert not is_transient(DeadlineExceeded("budget burned"))
    assert not is_transient(ValueError("bad request shape"))


# ------------------------------------------------------- circuit breaker --


def test_circuit_breaker_state_machine():
    """The pinned lifecycle: closed -> (threshold failures) -> open ->
    (open_s elapses) -> half_open -> probe success -> closed; a half-open
    probe FAILURE re-opens immediately."""
    transitions = []
    b = CircuitBreaker(threshold=2, open_s=0.15,
                       on_transition=lambda a, z, **k: transitions.append((a, z)))
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed" and b.consecutive_failures == 1
    b.record_failure()
    assert b.state == "open" and not b.allow() and b.trips == 1
    assert 0.0 < b.retry_after_s() <= 0.15
    time.sleep(0.2)
    assert b.state == "half_open" and b.allow()  # the probe admission
    b.record_failure()  # probe failed -> re-open right away
    assert b.state == "open" and b.trips == 2
    time.sleep(0.2)
    assert b.state == "half_open"
    b.record_success()  # probe succeeded -> automatic recovery
    assert b.state == "closed" and b.consecutive_failures == 0
    assert transitions == [("closed", "open"), ("open", "half_open"),
                           ("half_open", "open"), ("open", "half_open"),
                           ("half_open", "closed")]
    snap = b.snapshot()
    assert snap["state"] == "closed" and snap["trips"] == 2
    assert snap["retry_after_s"] == 0.0 and snap["threshold"] == 2


# ------------------------------------------------- rules + obs_diff gate --


def _health_events(run_id, **over):
    health = {
        "requests": 4, "done": 4, "errors": 0, "deadline_exceeded": 0,
        "engine_closed": 0, "shed": 0, "rejected_unavailable": 0,
        "error_rate": 0.0, "shed_rate": 0.0, "breaker_trips": 0,
        "retries": 0, "faults_injected": 0, "rehydrations": 0,
        "fresh_inversions": 1, "store_corrupt": 0,
    }
    health.update(over)
    return [{"event": "run_start", "run_id": run_id,
             "wall_time": f"2026-08-04T00:00:0{run_id[-1]}Z"},
            {"event": "serve_health", **health}]


def test_fault_rules_gate_reliability_regressions():
    from videop2p_tpu.obs.history import (
        DEFAULT_RULES,
        FAULT_RULES,
        evaluate_rules,
        extract_run,
    )

    assert all(r in DEFAULT_RULES for r in FAULT_RULES)
    base = extract_run(_health_events("a"))
    assert base["reliability"]["serve"]["error_rate"] == 0.0
    # identical runs self-compare clean (threshold rules, no nonzero trap)
    assert evaluate_rules(base, base, FAULT_RULES)["pass"]
    bad = extract_run(_health_events(
        "b", done=2, errors=1, deadline_exceeded=1, shed=2,
        error_rate=0.5, shed_rate=0.33, breaker_trips=1,
    ))
    res = evaluate_rules(base, bad, FAULT_RULES)
    assert not res["pass"]
    regressed = {v["rule"] for v in res["regressions"]}
    assert {"reliability:error_rate+10%", "reliability:shed_rate+10%",
            "reliability:breaker_trips+0%",
            "reliability:deadline_exceeded+0%"} <= regressed
    # pre-PR-9 ledgers extract an empty reliability section and evaluate
    # clean against anything
    old = extract_run([{"event": "run_start", "run_id": "old"}])
    assert old["reliability"] == {}
    assert evaluate_rules(old, base, FAULT_RULES)["pass"]


def test_obs_diff_renders_reliability_table_with_exit_teeth(tmp_path, capsys):
    """CI satellite: obs_diff renders the reliability table and its exit
    code has teeth — 0 on a healthy self-compare, 1 when the new run's
    serve_health regressed."""
    base_p = str(tmp_path / "base.jsonl")
    bad_p = str(tmp_path / "bad.jsonl")
    with open(base_p, "w") as f:
        for e in _health_events("a"):
            f.write(json.dumps(e) + "\n")
    with open(bad_p, "w") as f:
        for e in _health_events("b", done=2, errors=2, error_rate=0.5,
                                breaker_trips=2, retries=3):
            f.write(json.dumps(e) + "\n")
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", base_p, base_p]) == 0
    capsys.readouterr()
    assert obs_diff.main(["obs_diff.py", "--json", base_p, bad_p]) == 1
    out = capsys.readouterr()
    assert "reliability (serve_health" in out.err
    assert "breaker_trips" in out.err
    verdict = json.loads(out.out)
    rules = {v["rule"] for v in verdict["regressions"]}
    assert "reliability:error_rate+10%" in rules
    assert "reliability:breaker_trips+0%" in rules


def test_fault_and_breaker_ledger_events(tmp_path):
    """RunLedger.fault/.breaker convenience methods round-trip with the
    pinned field sets (a `kind` FIELD must not collide with the event
    kind — the positional-only signature)."""
    from videop2p_tpu.obs import RunLedger, read_ledger
    from videop2p_tpu.serve.faults import (
        BREAKER_EVENT_FIELDS,
        FAULT_EVENT_FIELDS,
    )

    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        led.fault("dispatch_fail", detail="attempt=2")
        led.breaker("closed", "open", consecutive_failures=3, trips=1)
    by_kind = {e["event"]: e for e in read_ledger(path)}
    f = by_kind["fault"]
    assert set(FAULT_EVENT_FIELDS) <= set(f)
    assert f["kind"] == "dispatch_fail" and f["detail"] == "attempt=2"
    b = by_kind["breaker"]
    assert set(BREAKER_EVENT_FIELDS) <= set(b)
    assert (b["state_from"], b["state_to"]) == ("closed", "open")
    assert b["consecutive_failures"] == 3 and b["trips"] == 1


# ----------------------------------------------------- client-side bits --


def test_client_terminal_statuses_match_engine():
    from videop2p_tpu.serve.engine import TERMINAL_STATUSES

    # the client hardcodes the tuple (it must stay importable without
    # jax) — this pin keeps the two in sync
    assert TERMINAL_STATUSES == ("done", "error", "deadline_exceeded",
                                 "engine_closed")


def test_client_retry_delay_honors_retry_after_with_cap():
    from videop2p_tpu.serve.client import EngineClient

    c = EngineClient("http://x", retries=3, backoff_s=0.25, backoff_cap_s=2.0)
    assert c._retry_delay_s(0, "1") == 1.0        # server hint wins
    assert c._retry_delay_s(0, "999") == 2.0      # ... bounded by the cap
    assert c._retry_delay_s(0, None) == 0.25      # deterministic fallback
    assert c._retry_delay_s(2, None) == 1.0       # 0.25 * 2^2
    assert c._retry_delay_s(5, "garbage") == 2.0  # unparseable -> fallback+cap


def test_edit_request_deadline_validation():
    from videop2p_tpu.serve import EditRequest

    ok = EditRequest(image_path="x", prompt="a", prompts=["a", "b"],
                     deadline_s=1.5)
    ok.validate()
    assert "deadline_s" in ok.to_dict()
    for bad in (0, -1.0, True):
        with pytest.raises(ValueError, match="deadline_s"):
            EditRequest(image_path="x", prompt="a", prompts=["a", "b"],
                        deadline_s=bad).validate()


# --------------------------------------------------------- engine level --

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)

_PROMPTS = ("a rabbit is jumping", "a origami rabbit is jumping")


@pytest.fixture(scope="module")
def programs():
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps.warm(_PROMPTS, batch_sizes=(2,))
    return ps


@pytest.fixture()
def make_engine(programs, tmp_path):
    """Engine factory over the shared warm ProgramSet (no compiles inside
    tests — compile_events pins stay meaningful); closes leftovers."""
    from videop2p_tpu.serve import EditEngine, ProgramSpec

    made = []

    def _make(**kw):
        kw.setdefault("out_dir", str(tmp_path / f"out{len(made)}"))
        eng = EditEngine(ProgramSpec(**_SPEC_KW), programs=programs,
                         keep_videos=True, **kw)
        made.append(eng)
        return eng

    yield _make
    for eng in made:
        eng.close()


def _request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt=_PROMPTS[0],
              prompts=list(_PROMPTS), save_name="chaos")
    kw.update(overrides)
    return EditRequest(**kw)


def test_chaos_acceptance_engine_survives_injected_outage(make_engine,
                                                          tmp_path):
    """THE acceptance criterion: under an injected fault plan (2 transient
    dispatch failures, 1 hang past the watchdog budget, 1 backend-
    unavailable window) the engine fails ONLY the doomed requests with
    machine-readable statuses, trips and automatically recovers the
    breaker, keeps serving healthy requests end-to-end — and the whole
    run gates through FAULT_RULES via tools/obs_diff.py: healthy
    self-compare exit 0, healthy-vs-chaos exit 1."""
    # healthy baseline session (its ledger is the obs_diff baseline)
    healthy = make_engine()
    h = healthy.result(healthy.submit(_request()), wait_s=300.0)
    assert h["status"] == "done", h.get("error")
    healthy_ledger = healthy.ledger.path
    healthy.close()

    # dispatch-attempt ledger (1-based): R1=1 ok | R2=2,3 transient fail,
    # 4 ok (retries absorb) | R3=5 hang -> watchdog | R4=6,7,8 unavailable
    # (retries exhausted -> error; breaker failure #2 trips OPEN) |
    # R5 rejected 503 | R6=9 ok (half-open probe -> recovery)
    eng = make_engine(
        max_retries=2, retry_base_s=0.01, retry_cap_s=0.05,
        breaker_threshold=2, breaker_open_s=0.4, dispatch_timeout_s=0.75,
        faults=FaultPlan.parse("fail@2,fail@3,hang@5:5.0,unavail@6-8"),
    )
    r1 = eng.result(eng.submit(_request()), wait_s=300.0)
    assert r1["status"] == "done", r1.get("error")

    r2 = eng.result(eng.submit(_request()), wait_s=300.0)
    assert r2["status"] == "done", r2.get("error")
    assert r2["dispatch_attempts"] == 3  # two injected failures absorbed
    assert eng.counters["retries"] == 2
    assert eng.breaker.state == "closed"  # recovered within retries

    r3 = eng.result(eng.submit(_request()), wait_s=300.0)
    assert r3["status"] == "deadline_exceeded"
    assert "watchdog" in r3["error"]
    assert eng.breaker.state == "closed"  # 1 failure < threshold 2

    r4 = eng.result(eng.submit(_request()), wait_s=300.0)
    assert r4["status"] == "error" and "injected" in r4["error"]
    assert eng.breaker.state == "open"  # consecutive failure #2 tripped

    with pytest.raises(EngineUnavailable, match="breaker open") as ei:
        eng.submit(_request())
    assert ei.value.retry_after_s is not None and ei.value.retry_after_s > 0

    time.sleep(0.45)  # open window elapses -> half-open probe admission
    r6 = eng.result(eng.submit(_request()), wait_s=300.0)
    assert r6["status"] == "done", r6.get("error")
    assert eng.breaker.state == "closed" and eng.breaker.trips == 1
    assert r6["store_hit"] is True and r6["src_err"] == 0.0

    health = eng.health_record()
    from videop2p_tpu.serve.faults import SERVE_HEALTH_FIELDS

    # the ISSUE-11 QoS fields ride alongside the numeric pins: the
    # scheduler policy name and the per-tenant sub-records
    assert set(health) == set(SERVE_HEALTH_FIELDS) | {"scheduler", "tenants"}
    assert health["scheduler"] == "drain"
    assert health["done"] == 3 and health["errors"] == 1
    assert health["deadline_exceeded"] == 1
    assert health["rejected_unavailable"] == 1
    assert health["breaker_trips"] == 1 and health["faults_injected"] >= 4
    kinds = [e.get("kind") for e in eng.fault_log if e["event"] == "fault"]
    assert {"dispatch_fail", "hang", "watchdog_timeout",
            "backend_unavailable", "retry"} <= set(kinds)
    assert any(e["event"] == "breaker" for e in eng.fault_log)
    chaos_ledger = eng.ledger.path
    eng.close()

    # the ledgers gate through FAULT_RULES: healthy self-compare clean,
    # healthy-vs-chaos regresses (threshold-scale 20 neuters latency
    # jitter but cannot save a 0 -> nonzero reliability delta)
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", healthy_ledger, healthy_ledger]) == 0
    assert obs_diff.main(["obs_diff.py", "--threshold-scale", "20",
                          healthy_ledger, chaos_ledger]) == 1


def test_restart_rehydration_serves_from_disk(make_engine, tmp_path):
    """Crash recovery: kill-and-restart the engine over the same
    persist_dir — the repeat identical request is a DISK hit rebuilt
    through the warm inversion program: src_err == 0.0, zero compile
    events, zero new inversions-from-frames."""
    persist = str(tmp_path / "inv_store")
    a = make_engine(persist_dir=persist)
    ra = a.result(a.submit(_request()), wait_s=300.0)
    assert ra["status"] == "done" and ra["store_source"] == "fresh"
    assert a.counters["fresh_inversions"] == 1
    videos_a = a.videos(ra["id"])
    a.close()  # the "kill": device LRU gone, disk layer survives

    b = make_engine(persist_dir=persist)
    rb = b.result(b.submit(_request()), wait_s=300.0)
    assert rb["status"] == "done", rb.get("error")
    assert rb["store_hit"] is True and rb["store_source"] == "disk"
    assert rb["src_err"] == 0.0
    assert rb["compile_events"] == 0
    assert b.counters["rehydrations"] == 1
    assert b.counters["fresh_inversions"] == 0
    assert b.store.stats()["disk_hits"] == 1
    # the rebuild is bit-identical, not merely exact-replay
    assert np.array_equal(videos_a, b.videos(rb["id"]))
    # second repeat is now a resident hit (rehydration re-populated the LRU)
    rc = b.result(b.submit(_request()), wait_s=300.0)
    assert rc["store_source"] == "memory"


def test_corrupt_store_entry_detected_falls_back_fresh(make_engine, tmp_path):
    """store-corrupt-entry injection: the rehydration load detects the
    poisoned trajectory and falls back to a fresh inversion — the request
    still completes exactly (never serves garbage)."""
    persist = str(tmp_path / "inv_store")
    a = make_engine(persist_dir=persist)
    assert a.result(a.submit(_request()), wait_s=300.0)["status"] == "done"
    a.close()

    c = make_engine(persist_dir=persist, faults=FaultPlan.parse("corrupt:*"))
    rc = c.result(c.submit(_request()), wait_s=300.0)
    assert rc["status"] == "done", rc.get("error")
    assert rc["store_source"] == "fresh" and rc["src_err"] == 0.0
    assert c.store.disk_corrupt == 1 and c.counters["rehydrations"] == 0
    assert c.counters["faults_injected"] >= 1
    assert any(e.get("kind") == "store_corrupt" for e in c.fault_log)
    assert c.health_record()["store_corrupt"] == 1


def test_deadline_expires_while_queued(make_engine):
    """Deadline admission/expiry: a request whose budget burns in the
    queue (the worker is wedged on an injected hang) fails with terminal
    deadline_exceeded without any device work spent on it."""
    eng = make_engine(faults=FaultPlan.parse("hang@1:1.0"),
                      dispatch_timeout_s=5.0)
    slow = eng.submit(_request())
    time.sleep(0.1)  # the worker picks `slow` up and hangs
    doomed = eng.submit(_request(seed=3, deadline_s=0.2))
    rec = eng.result(doomed, wait_s=60.0)
    assert rec["status"] == "deadline_exceeded"
    assert "expired" in rec["error"]
    assert eng.result(slow, wait_s=60.0)["status"] == "done"


def test_backpressure_sheds_submits_and_close_drains(make_engine):
    """429 + engine_closed semantics: over max_queue in-flight, submits
    raise QueueFull with the depth; close() fails still-queued requests
    with terminal engine_closed (never stranded pending); submits after
    close raise EngineUnavailable."""
    eng = make_engine(max_queue=2, max_wait_s=0.01,
                      faults=FaultPlan.parse("hang@1:1.0"),
                      dispatch_timeout_s=10.0)
    a = eng.submit(_request())
    time.sleep(0.15)  # worker takes `a`, admit window closes, then hangs
    b = eng.submit(_request(seed=1))
    with pytest.raises(QueueFull, match="admit queue full") as qi:
        eng.submit(_request(seed=2))
    assert qi.value.depth == 2 and qi.value.limit == 2
    assert eng.counters["shed"] == 1
    eng.close(drain_s=0.0)
    ra, rb = eng.poll(a), eng.poll(b)
    assert ra["status"] == "done"  # in-flight dispatch always completes
    assert rb["status"] == "engine_closed"
    assert "engine closed" in rb["error"]
    with pytest.raises(EngineUnavailable, match="closed"):
        eng.submit(_request())
    assert eng.health_record()["engine_closed"] == 1


def test_http_429_503_semantics_and_retry_after(make_engine):
    """HTTP layer: breaker-open submits are 503 with a Retry-After header
    and retry_after_s in the body; queue-full submits are 429 with the
    queue depth in the body; /healthz reports degraded while the breaker
    is not closed; the retry-aware client rides Retry-After through the
    open window and succeeds on the half-open probe."""
    from videop2p_tpu.serve.client import EngineClient
    from videop2p_tpu.serve.http import make_server

    eng = make_engine(max_retries=0, breaker_threshold=1, breaker_open_s=0.6,
                      faults=FaultPlan.parse("unavail@1-1"))
    server = make_server(eng).start()
    try:
        url = server.url
        fail_fast = EngineClient(url, retries=0)
        r1 = fail_fast.submit(_request().to_dict())
        rec = fail_fast.wait(r1, timeout_s=60.0)
        assert rec["status"] == "error"  # injected unavailable, no retries
        assert eng.breaker.state == "open"
        # degraded healthz while the breaker is not closed
        health = fail_fast.healthz()
        assert health["ok"] is True and health["status"] == "degraded"
        assert health["breaker"]["state"] == "open"
        # raw 503 surface: Retry-After header + machine-readable body
        body = json.dumps(_request().to_dict()).encode()
        req = urllib.request.Request(url + "/v1/edits", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(req, timeout=10)
        assert he.value.code == 503
        assert int(he.value.headers["Retry-After"]) >= 1
        payload = json.loads(he.value.read())
        assert "breaker open" in payload["error"]
        assert payload["retry_after_s"] > 0
        # the retry-aware client backs off through the window and lands on
        # the half-open probe (which closes the breaker)
        patient = EngineClient(url, retries=3, backoff_s=0.3,
                               backoff_cap_s=1.0)
        rid = patient.submit(_request().to_dict())
        rec = patient.wait(rid, timeout_s=300.0)
        assert rec["status"] == "done"
        assert eng.breaker.state == "closed"
        assert patient.healthz()["status"] == "ok"
    finally:
        server.close()

    # 429 surface needs a wedged queue — its own engine
    eng2 = make_engine(max_queue=1, max_wait_s=0.01,
                       faults=FaultPlan.parse("hang@1:1.0"),
                       dispatch_timeout_s=10.0)
    server2 = make_server(eng2).start()
    try:
        c = EngineClient(server2.url, retries=0)
        c.submit(_request().to_dict())
        req = urllib.request.Request(
            server2.url + "/v1/edits",
            data=json.dumps(_request(seed=9).to_dict()).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(req, timeout=10)
        assert he.value.code == 429
        payload = json.loads(he.value.read())
        assert "queue full" in payload["error"]
        assert payload["queue_depth"] == 1 and payload["max_queue"] == 1
        with pytest.raises(RuntimeError, match="429"):
            c.submit(_request(seed=10).to_dict())
    finally:
        server2.close()


def test_metrics_expose_queue_breaker_and_counters(make_engine):
    eng = make_engine()
    assert eng.result(eng.submit(_request()), wait_s=300.0)["status"] == "done"
    m = eng.metrics()
    assert m["queue_depth"] == 0 and m["in_flight"] == 0
    assert m["max_queue"] == 64
    assert m["breaker"]["state"] == "closed" and m["breaker"]["trips"] == 0
    assert {"shed", "rejected_unavailable", "retries", "faults_injected",
            "rehydrations", "fresh_inversions"} <= set(m["counters"])
    assert "disk_hits" in m["store"] and "disk_corrupt" in m["store"]


def test_chaos_loadgen_writes_gateable_reliability_ledger(programs, tmp_path):
    """Satellite: the loadgen chaos mode drives the engine under an
    injected plan, classifies sheds apart from errors, asserts the
    healthy-request success rate, and writes the engine's fault/breaker
    events + serve_health into its own obs_diff-gateable ledger."""
    from videop2p_tpu.serve import EditEngine, ProgramSpec

    loadgen = _load_tool("serve_loadgen")
    eng = EditEngine(
        ProgramSpec(**_SPEC_KW), programs=programs,
        out_dir=str(tmp_path / "lg_out"),
        max_retries=1, retry_base_s=0.01,
        faults=FaultPlan.parse("fail@2,fail@3"),  # R2 exhausts its 1 retry
    )
    try:
        target = loadgen._InprocTarget(eng, timeout_s=300.0)
        ledger_path = str(tmp_path / "chaos_loadgen.jsonl")

        def collect_extra(record):
            return [dict(e) for e in eng.fault_log] + [
                {"event": "serve_health", **eng.health_record()}
            ]

        record = loadgen.run_loadgen(
            target, _request().to_dict(),
            requests=4, concurrency=1, ledger_path=ledger_path,
            meta={"target": "chaos-test"}, collect_extra=collect_extra,
        )
    finally:
        eng.close()
    assert record["done"] == 3 and record["errors"] == 1
    assert record["success_rate"] == 0.75
    assert record["shed"] == 0

    from videop2p_tpu.obs import read_ledger
    from videop2p_tpu.obs.history import extract_run, split_runs

    runs = split_runs(read_ledger(ledger_path))
    rec = extract_run(runs[-1])
    rel = rec["reliability"]["serve"]
    assert rel["errors"] == 1.0 and rel["faults_injected"] == 2.0
    kinds = [e.get("kind") for e in runs[-1] if e.get("event") == "fault"]
    assert "dispatch_fail" in kinds and "retry" in kinds
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", ledger_path, ledger_path]) == 0


def test_loadgen_rejects_faults_over_http():
    loadgen = _load_tool("serve_loadgen")
    with pytest.raises(SystemExit):
        loadgen.main(["--url", "http://localhost:1", "--faults", "fail@1"])
