"""Distributed-layer tests on the virtual 8-device CPU mesh (SURVEY §4's
standard fake-pod recipe, set up in conftest.py).

Covers: mesh construction, ring attention exactness vs dense attention,
sequence-parallel UNet forward equivalence, and the sharded train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow
from jax.sharding import NamedSharding, PartitionSpec as P

from videop2p_tpu.parallel import (
    AXIS_FRAMES,
    latent_sharding,
    make_mesh,
    param_shardings,
    replicated,
    ring_attention_sharded,
    text_sharding,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh((1, 8, 1))


def test_make_mesh_validates():
    with pytest.raises(ValueError, match="devices"):
        make_mesh((3, 1, 1))
    m = make_mesh((2, 4, 1))
    assert m.shape == {"data": 2, "frames": 4, "tensor": 1}


def test_ring_attention_matches_dense(mesh8):
    B, H, S, D = 2, 3, 16, 8  # S=16 over 8 shards → 2 per shard
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, H, S, D))
    k = jax.random.normal(kk, (B, H, S, D))
    v = jax.random.normal(kv, (B, H, S, D))

    out_ring = ring_attention_sharded(q, k, v, mesh8, axis_name=AXIS_FRAMES)
    scale = D**-0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    out_dense = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense), atol=1e-5)


def test_ring_attention_bf16(mesh8):
    B, H, S, D = 1, 2, 8, 4
    q = jax.random.normal(jax.random.key(0), (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, S, D), jnp.bfloat16)
    out = ring_attention_sharded(q, k, v, mesh8, axis_name=AXIS_FRAMES)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()


def test_sequence_parallel_unet_forward(mesh8):
    """The full UNet forward under jit with the frame axis sharded across the
    8-device mesh must equal the single-device result — XLA inserts the
    frame-0 KV broadcast and temporal-attention gathers (SURVEY §5.7)."""
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    B, F = 1, 8
    sample = jax.random.normal(jax.random.key(0), (B, F, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (B, 7, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(5), text)

    out_single = jax.jit(model.apply)(params, sample, jnp.asarray(5), text)

    sharded_sample = jax.device_put(sample, latent_sharding(mesh8))
    sharded_text = jax.device_put(text, text_sharding(mesh8))
    sharded_params = jax.device_put(params, replicated(mesh8))
    out_sharded = jax.jit(
        model.apply, out_shardings=latent_sharding(mesh8)
    )(sharded_params, sharded_sample, jnp.asarray(5), sharded_text)
    np.testing.assert_allclose(
        np.asarray(out_single), np.asarray(out_sharded), atol=2e-4
    )


def test_sharded_train_step(mesh8):
    """train_step jitted over the mesh with frame-sharded latents: loss must
    match the unsharded step bit-for-better-than-bf16 tolerance (the psum the
    reference does via accelerator.gather, run_tuning.py:322)."""
    from videop2p_tpu.core import DDPMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import make_unet_fn
    from videop2p_tpu.train import TrainState, TuneConfig, make_optimizer, train_step

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    latents = 0.3 * jax.random.normal(jax.random.key(0), (1, 8, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    variables = jax.jit(model.init)(jax.random.key(2), latents, jnp.asarray(0), text)
    fn = make_unet_fn(model)
    params = dict(variables)["params"]
    tx = make_optimizer(TuneConfig())
    state = TrainState.create(params, tx)
    sched = DDPMScheduler.create_sd()

    step = jax.jit(lambda s, lat, txt, k: train_step(fn, tx, s, sched, lat, txt, k))
    _, loss_single = step(state, latents, text, jax.random.key(3))

    s_state = jax.device_put(state, replicated(mesh8))
    s_lat = jax.device_put(latents, latent_sharding(mesh8))
    s_txt = jax.device_put(text, text_sharding(mesh8))
    new_state, loss_sharded = step(s_state, s_lat, s_txt, jax.random.key(3))
    np.testing.assert_allclose(float(loss_single), float(loss_sharded), rtol=1e-4)
    assert int(new_state.step) == 1


def test_param_shardings_tensor_parallel(mesh8):
    """Tensor-parallel rules: qkv kernels column-shard, to_out row-shards,
    everything else replicates."""
    mesh = make_mesh((1, 4, 2))
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), (1, 2, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    variables = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(0), text)
    params = dict(variables)["params"]
    shardings = param_shardings(mesh, params, tensor_parallel=True)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    specs = {jax.tree_util.keystr(p): s.spec for p, s in flat}
    qs = [s for k, s in specs.items() if "to_q" in k and "kernel" in k]
    outs = [s for k, s in specs.items() if "attn" in k and "to_out" in k and "kernel" in k]
    convs = [s for k, s in specs.items() if "conv" in k]
    assert all(s == P(None, "tensor") for s in qs) and qs
    assert all(s == P("tensor", None) for s in outs) and outs
    assert all(s == P() for s in convs) and convs
    # all kernels placeable
    jax.device_put(params, shardings)


def test_ring_temporal_unet_forward(mesh8):
    """UNet forward with ring attention at the temporal sites over the
    frame-sharded mesh must equal the dense single-device forward (the
    temporal_attention_fn seam, models/attention.py)."""
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.parallel import make_ring_temporal_fn

    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    B, F = 1, 8
    sample = jax.random.normal(jax.random.key(0), (B, F, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (B, 7, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(5), text)
    out_dense = jax.jit(model.apply)(params, sample, jnp.asarray(5), text)

    model_ring = model.clone(temporal_attention_fn=make_ring_temporal_fn(mesh8))
    s_sample = jax.device_put(sample, latent_sharding(mesh8))
    s_text = jax.device_put(text, text_sharding(mesh8))
    s_params = jax.device_put(params, replicated(mesh8))
    out_ring = jax.jit(
        model_ring.apply, out_shardings=latent_sharding(mesh8)
    )(s_params, s_sample, jnp.asarray(5), s_text)
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_ring), atol=2e-4
    )


def test_sharded_frame_attention_matches_dense(mesh8):
    """The shard_map frame-attention wrapper (queries split over frames,
    frame-0 K/V replicated) must equal the single-device kernel — both at the
    raw-kernel level and through the UNet's frame_attention_fn seam. This is
    the path that carries the fused Pallas kernel onto the sharded mesh."""
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.ops import dense_frame_attention
    from videop2p_tpu.parallel import make_sharded_frame_attention_fn

    # raw kernel: realistic token count so the dispatch path is exercised
    B, F, H, N, D = 1, 8, 2, 1024, 8
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (B, F, H, N, D))
    k = jax.random.normal(kk, (B, H, N, D))
    v = jax.random.normal(kv, (B, H, N, D))
    fn = make_sharded_frame_attention_fn(mesh8)
    out_s = jax.jit(fn)(
        jax.device_put(q, NamedSharding(mesh8, P(None, "frames"))),
        jax.device_put(k, replicated(mesh8)),
        jax.device_put(v, replicated(mesh8)),
    )
    out_d = jax.jit(dense_frame_attention)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d), atol=2e-5)

    # through the UNet seam: sharded forward == unsharded forward
    cfg = UNet3DConfig.tiny(frame_attention="dense")
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), (1, 8, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(5), text)
    out_dense = jax.jit(model.apply)(params, sample, jnp.asarray(5), text)
    model_sf = model.clone(frame_attention_fn=make_sharded_frame_attention_fn(mesh8))
    out_sharded = jax.jit(
        model_sf.apply, out_shardings=latent_sharding(mesh8)
    )(
        jax.device_put(params, replicated(mesh8)),
        jax.device_put(sample, latent_sharding(mesh8)),
        jnp.asarray(5),
        jax.device_put(text, text_sharding(mesh8)),
    )
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_sharded), atol=2e-4
    )


def test_sharded_controlled_edit_matches_unsharded(mesh8):
    """The full attention-controlled edit (refine + equalizer + LocalBlend)
    jitted over the frame-sharded mesh must match the single-device edit —
    the Stage-2 --mesh path (cli/run_videop2p.py)."""
    from videop2p_tpu.control import make_controller
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import edit_sample, make_unet_fn
    from videop2p_tpu.utils.tokenizers import WordTokenizer

    mesh = make_mesh((1, 4, 2))
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    F, STEPS = 4, 3
    x_t = jax.random.normal(jax.random.key(0), (1, F, 8, 8, 4))
    cond = jax.random.normal(jax.random.key(1), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), x_t, jnp.asarray(5), cond[:1])
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()
    ctx = make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.8, self_replace_steps=0.6,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )

    def run(p, xt, c, u):
        return edit_sample(
            fn, p, sched, xt, c, u, num_inference_steps=STEPS, ctx=ctx,
            source_uses_cfg=False, blend_res=(4, 4),
        )

    out_single = jax.jit(run)(params, x_t, cond, uncond)

    s_params = jax.device_put(
        params, param_shardings(mesh, params, tensor_parallel=True)
    )
    s_xt = jax.device_put(x_t, latent_sharding(mesh))
    s_cond = jax.device_put(cond, replicated(mesh))
    s_uncond = jax.device_put(uncond, replicated(mesh))
    out_sharded = jax.jit(run)(s_params, s_xt, s_cond, s_uncond)
    np.testing.assert_allclose(
        np.asarray(out_single), np.asarray(out_sharded), atol=2e-4
    )


def test_sharded_cached_source_edit_matches_unsharded(mesh8):
    """The cached-source fast mode (pipelines/cached.py) under a (1,4,2)
    frames×tensor mesh: GSPMD shards the capture trees (cross maps over the
    frame axis, temporal maps over spatial positions) with no shard_map
    changes; sharded must match unsharded, and the source replay must stay
    bit-exact even sharded."""
    from videop2p_tpu.control import make_controller
    from videop2p_tpu.core import DDIMScheduler
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.pipelines import (
        ddim_inversion_captured,
        edit_sample,
        make_unet_fn,
    )
    from videop2p_tpu.pipelines.cached import capture_windows
    from videop2p_tpu.utils.tokenizers import WordTokenizer

    mesh = make_mesh((1, 4, 2))
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    F, STEPS = 4, 3
    x0 = jax.random.normal(jax.random.key(0), (1, F, 8, 8, 4))
    cond = jax.random.normal(jax.random.key(1), (2, 77, cfg.cross_attention_dim))
    uncond = jnp.zeros((77, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), x0, jnp.asarray(5), cond[:1])
    fn = make_unet_fn(model)
    sched = DDIMScheduler.create_sd()
    ctx = make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"],
        WordTokenizer(), num_steps=STEPS,
        is_replace_controller=False,
        cross_replace_steps=0.8, self_replace_steps=0.6,
        blend_words=(["rabbit"], ["rabbit"]),
        equalizer_params={"words": ["origami"], "values": [2.0]},
    )
    c, sw = capture_windows(ctx, STEPS)

    def invcap(p, x):
        return ddim_inversion_captured(
            fn, p, sched, x, cond[:1], num_inference_steps=STEPS,
            cross_len=c, self_window=sw, capture_blend=True, blend_res=(4, 4),
        )

    def edit(p, xt, cch):
        return edit_sample(
            fn, p, sched, xt, cond, uncond, num_inference_steps=STEPS,
            ctx=ctx, source_uses_cfg=False, blend_res=(4, 4), cached_source=cch,
        )

    traj1, cc1 = jax.jit(invcap)(params, x0)
    out1 = jax.jit(edit)(params, traj1[-1], cc1)

    s_params = jax.device_put(
        params, param_shardings(mesh, params, tensor_parallel=True)
    )
    s_x0 = jax.device_put(x0, latent_sharding(mesh))
    traj2, cc2 = jax.jit(invcap)(s_params, s_x0)
    out2 = jax.jit(edit)(s_params, traj2[-1], cc2)

    # capture maps are STORED in bf16 (models/attention.py): the sharded and
    # unsharded programs' fp drift rounds to different bf16 ULPs in the maps,
    # which the 3-step edit amplifies to ~1e-3 — tolerance covers that, not
    # any semantic divergence
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=2e-3)
    # the replay exactness survives sharding
    np.testing.assert_array_equal(np.asarray(out2[0]), np.asarray(s_x0[0]))

    # the long-video budget mode's float8 temporal storage must partition
    # identically (GSPMD treats the narrow dtype like any other): sharded
    # f8 matches unsharded f8, and replay exactness is dtype-independent
    def invcap8(p, x):
        return ddim_inversion_captured(
            fn, p, sched, x, cond[:1], num_inference_steps=STEPS,
            cross_len=c, self_window=sw, capture_blend=True, blend_res=(4, 4),
            temporal_maps_dtype=jnp.float8_e4m3fn,
        )

    traj18, cc18 = jax.jit(invcap8)(params, x0)
    out18 = jax.jit(edit)(params, traj18[-1], cc18)
    traj28, cc28 = jax.jit(invcap8)(s_params, s_x0)
    out28 = jax.jit(edit)(s_params, traj28[-1], cc28)
    np.testing.assert_allclose(np.asarray(out18), np.asarray(out28), atol=2e-3)
    np.testing.assert_array_equal(np.asarray(out28[0]), np.asarray(s_x0[0]))


def test_sharded_group_norm_matches_reference(mesh8):
    """The shard_map GroupNorm wrapper (VERDICT r5 next-round #5): the
    fused one-pass kernel runs per-shard on sample-split slabs and must
    match the two-pass reference — directly and through the TpuGroupNorm
    ``group_norm_fn`` seam; uncovered sites return None (→ XLA fallback)."""
    from videop2p_tpu.models.layers import TpuGroupNorm
    from videop2p_tpu.ops.groupnorm import group_norm_reference
    from videop2p_tpu.parallel import make_sharded_group_norm_fn

    fn = make_sharded_group_norm_fn(mesh8, impl="interpret")
    N, rows, C = 8, 256, 32  # 8 samples over 8 shards, VMEM-sized slab
    x2 = jax.random.normal(jax.random.key(0), (N, rows, C))
    scale = jax.random.normal(jax.random.key(1), (C,))
    bias = jax.random.normal(jax.random.key(2), (C,))
    y = fn(x2, scale, bias, num_groups=4, eps=1e-5, act="silu")
    assert y is not None
    ref = group_norm_reference(x2, scale, bias, num_groups=4, eps=1e-5,
                               act="silu")
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=2e-5)

    # uncovered sites: sample axis not divisible by the shard count (the
    # frame-pooled resnet slabs), or a slab the VMEM gate refuses — None,
    # and the caller falls back to the two-pass math
    assert fn(x2[:3], scale, bias, num_groups=4, eps=1e-5, act="none") is None
    odd = jax.random.normal(jax.random.key(3), (8, 100, 32))
    assert fn(odd, scale, bias, num_groups=4, eps=1e-5, act="none") is None
    # an impl that disables the kernel covers nothing
    off = make_sharded_group_norm_fn(mesh8, impl="xla")
    assert off(x2, scale, bias, num_groups=4, eps=1e-5, act="none") is None

    # through the module seam, jitted with the sample axis sharded:
    # sharded == unsharded with the kernel active in interpret mode
    gn = TpuGroupNorm(num_groups=4, epsilon=1e-5, act="silu",
                      group_norm_fn=fn)
    x = jax.random.normal(jax.random.key(4), (N, 16, 16, C))
    variables = gn.init(jax.random.key(5), x)
    ref_mod = TpuGroupNorm(num_groups=4, epsilon=1e-5, act="silu", impl="xla")
    y_ref = jax.jit(ref_mod.apply)(variables, x)
    s_x = jax.device_put(
        x, NamedSharding(mesh8, P(("data", "frames"), None, None, None))
    )
    y_sharded = jax.jit(gn.apply)(jax.device_put(variables, replicated(mesh8)),
                                  s_x)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_sharded), atol=2e-5
    )


def test_setup_mesh_wires_sharded_group_norm():
    """setup_mesh no longer forces group_norm='xla' on sharded meshes — it
    wires the shard_map GroupNorm seam instead, leaving the config knob
    untouched (the kernel decision now lives in the seam)."""
    import jax.numpy as jnp

    from videop2p_tpu.cli.common import build_models, setup_mesh

    bundle = build_models(None, tiny=True, dtype=jnp.float32)
    assert bundle.unet.config.group_norm == "auto"
    assert bundle.unet.group_norm_fn is None
    mesh = setup_mesh(bundle, "1,4,2", 8)
    assert mesh.shape == {"data": 1, "frames": 4, "tensor": 2}
    assert bundle.unet.group_norm_fn is not None
    assert bundle.unet.config.group_norm == "auto"  # knob not clobbered


def test_hybrid_mesh_single_slice_and_distributed_noop():
    """make_hybrid_mesh on one slice equals the plain reshape;
    initialize_distributed is a no-op without multi-host config."""
    from videop2p_tpu.parallel import initialize_distributed, make_hybrid_mesh

    assert initialize_distributed() == 0
    m = make_hybrid_mesh(1, 4, 2)
    assert m.shape == {"data": 1, "frames": 4, "tensor": 2}
    with pytest.raises(ValueError, match="needs"):
        make_hybrid_mesh(2, 4, 2)


def _dense_reference(q, k, v):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1),
                      v.astype(jnp.float32))


def test_ring_variants_match_dense(mesh8):
    """ISSUE 10 satellite: every rotation schedule — the serial baseline,
    the double-buffered n−1 default, and the bidirectional split-halves
    variant — must match dense attention at the existing ring tolerance."""
    from videop2p_tpu.parallel import RING_VARIANTS

    B, H, S, D = 2, 3, 16, 8
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, H, S, D))
    k = jax.random.normal(kk, (B, H, S, D))
    v = jax.random.normal(kv, (B, H, S, D))
    dense = _dense_reference(q, k, v)
    for variant in RING_VARIANTS:
        out = ring_attention_sharded(q, k, v, mesh8, axis_name=AXIS_FRAMES,
                                     variant=variant)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5, err_msg=variant)
    with pytest.raises(ValueError, match="variant"):
        ring_attention_sharded(q, k, v, mesh8, variant="bogus")


def test_ring_variants_odd_shards_and_odd_halves():
    """Odd shard counts (a 5-device sub-mesh) and an odd per-shard
    sequence length (unequal bidirectional halves) stay exact."""
    from videop2p_tpu.parallel import RING_VARIANTS

    mesh5 = make_mesh((1, 5, 1), devices=jax.devices()[:5])
    B, H, S, D = 1, 2, 15, 4  # 3 frames per shard: odd halves for bidir
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (B, H, S, D))
    k = jax.random.normal(kk, (B, H, S, D))
    v = jax.random.normal(kv, (B, H, S, D))
    dense = _dense_reference(q, k, v)
    for variant in RING_VARIANTS:
        out = ring_attention_sharded(q, k, v, mesh5, variant=variant)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5, err_msg=variant)


def test_ring_variants_bf16(mesh8):
    """bf16 inputs: fp32 accumulators inside, bf16 out, finite — and a
    1-frame-per-shard bidir degenerates to overlap instead of failing."""
    from videop2p_tpu.parallel import RING_VARIANTS

    B, H, S, D = 1, 2, 8, 4  # 1 frame per shard on the 8-wide mesh
    for variant in RING_VARIANTS:
        q = jax.random.normal(jax.random.key(0), (B, H, S, D), jnp.bfloat16)
        k = jax.random.normal(jax.random.key(1), (B, H, S, D), jnp.bfloat16)
        v = jax.random.normal(jax.random.key(2), (B, H, S, D), jnp.bfloat16)
        out = ring_attention_sharded(q, k, v, mesh8, variant=variant)
        assert out.dtype == jnp.bfloat16, variant
        assert np.isfinite(np.asarray(out, dtype=np.float32)).all(), variant


def test_megatron_out_dot_unit():
    """make_megatron_out_dot: the explicit psum_scatter row-parallel matmul
    equals the plain dot, and non-matching patterns fall back to it."""
    from videop2p_tpu.parallel import make_megatron_out_dot

    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    dot = make_megatron_out_dot(mesh)
    dn = (((2,), (0,)), ((), ()))
    lhs = jax.random.normal(jax.random.key(0), (2, 8, 16))
    rhs = jax.random.normal(jax.random.key(1), (16, 6))
    # the scatter path exists under jit (partial-auto shard_map needs a
    # surrounding trace on legacy jax); eager calls fall back to plain dot
    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda l, r: dot(l, r, dn))(lhs, rhs)),
        np.asarray(lhs @ rhs), atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(dot(lhs, rhs, dn)), np.asarray(lhs @ rhs), atol=1e-5
    )
    # fallback: token axis not divisible by tp → plain dot, still exact
    lhs_odd = jax.random.normal(jax.random.key(2), (2, 7, 16))
    np.testing.assert_allclose(
        np.asarray(dot(lhs_odd, rhs, dn)), np.asarray(lhs_odd @ rhs),
        atol=1e-5,
    )
    # batched dims → fallback (no shard_map pattern for them)
    dn_batched = (((2,), (1,)), ((0,), (0,)))
    lhs_b = jax.random.normal(jax.random.key(3), (2, 8, 16))
    rhs_b = jax.random.normal(jax.random.key(4), (2, 16, 6))
    np.testing.assert_allclose(
        np.asarray(dot(lhs_b, rhs_b, dn_batched)),
        np.asarray(jax.lax.dot_general(lhs_b, rhs_b, dn_batched)), atol=1e-5,
    )


def test_megatron_unet_forward_matches_gspmd(mesh8):
    """The tensor-parallel UNet forward with the explicit psum_scatter
    output seam must match both the declarative GSPMD forward and the
    unsharded single-device forward."""
    from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
    from videop2p_tpu.parallel import make_megatron_out_dot

    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), (1, 2, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    params = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(5), text)
    out_ref = jax.jit(model.apply)(params, sample, jnp.asarray(5), text)

    s_params = jax.device_put(
        params, param_shardings(mesh, params, tensor_parallel=True)
    )
    s_sample = jax.device_put(sample, latent_sharding(mesh))
    s_text = jax.device_put(text, text_sharding(mesh))
    model_m = model.clone(row_parallel_dot=make_megatron_out_dot(mesh))
    out_m = jax.jit(model_m.apply, out_shardings=latent_sharding(mesh))(
        s_params, s_sample, jnp.asarray(5), s_text
    )
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_m),
                               atol=2e-4)


def test_setup_mesh_ring_and_tp_knobs():
    """setup_mesh validates and wires the new schedule knobs: a bad ring
    variant / tp_collectives raises, and psum_scatter on a tp>1 mesh
    threads the row_parallel_dot seam into the UNet."""
    from videop2p_tpu.cli.common import build_models, setup_mesh

    bundle = build_models(None, tiny=True, dtype=jnp.float32)
    with pytest.raises(ValueError, match="ring_variant"):
        setup_mesh(bundle, "1,4,2", 8, ring_variant="bogus")
    with pytest.raises(ValueError, match="tp_collectives"):
        setup_mesh(bundle, "1,4,2", 8, tp_collectives="bogus")
    assert bundle.unet.row_parallel_dot is None
    mesh = setup_mesh(bundle, "1,4,2", 8, ring_variant="bidir",
                      tp_collectives="psum_scatter")
    assert mesh.shape == {"data": 1, "frames": 4, "tensor": 2}
    assert bundle.unet.row_parallel_dot is not None
    assert bundle.unet.temporal_attention_fn is not None
