"""Functional controller tests on synthetic attention tensors
(reference semantics: /root/reference/run_videop2p.py:286-410)."""

import jax
import jax.numpy as jnp
import numpy as np

from videop2p_tpu.control import ControlContext, control_attention, make_controller
from videop2p_tpu.control.controllers import get_equalizer
from videop2p_tpu.utils.tokenizers import WordTokenizer

P, F, H, Q, W = 2, 2, 2, 4, 77
STEPS = 10


def _probs(key, b):
    x = jax.random.uniform(key, (b, H, Q, W))
    return x / x.sum(-1, keepdims=True)


def _ctx(**kw):
    t = WordTokenizer()
    defaults = dict(
        is_replace_controller=False,
        cross_replace_steps=0.8,
        self_replace_steps=0.5,
    )
    defaults.update(kw)
    return make_controller(
        ["a rabbit is jumping", "a origami rabbit is jumping"], t, STEPS, **defaults
    ), t


def test_uncond_half_untouched():
    ctx, _ = _ctx()
    probs = _probs(jax.random.PRNGKey(0), 2 * P * F)
    out = control_attention(probs, ctx, is_cross=True, step_index=jnp.asarray(0), video_length=F)
    np.testing.assert_array_equal(np.asarray(out[: P * F]), np.asarray(probs[: P * F]))
    assert not np.allclose(np.asarray(out[P * F :]), np.asarray(probs[P * F :]))


def test_none_context_is_identity():
    probs = _probs(jax.random.PRNGKey(1), 2 * P * F)
    out = control_attention(probs, None, is_cross=True, step_index=jnp.asarray(0), video_length=F)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(probs))


def test_refine_matches_reference_math():
    ctx, _ = _ctx()
    probs = _probs(jax.random.PRNGKey(2), 2 * P * F)
    step = jnp.asarray(0)
    out = control_attention(probs, ctx, is_cross=True, step_index=step, video_length=F)

    p = np.asarray(probs).reshape(2, P, F, H, Q, W)
    base, repl = p[1, 0], p[1, 1]
    mapper = np.asarray(ctx.refine_mapper[0])
    alphas = np.asarray(ctx.refine_alphas[0])
    gathered = base[..., mapper]  # (F,H,Q,W)
    refined = gathered * alphas + repl * (1 - alphas)
    alpha_words = np.asarray(ctx.cross_replace_alpha)[0, 0, 0, 0]  # (77,)
    expected = refined * alpha_words + (1 - alpha_words) * repl

    got = np.asarray(out).reshape(2, P, F, H, Q, W)[1, 1]
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-7)
    # base stream passes through
    np.testing.assert_allclose(np.asarray(out).reshape(2, P, F, H, Q, W)[1, 0], base, rtol=1e-6)


def test_cross_replace_alpha_gates_late_steps():
    ctx, _ = _ctx(cross_replace_steps=0.2)
    probs = _probs(jax.random.PRNGKey(3), 2 * P * F)
    late = control_attention(probs, ctx, is_cross=True, step_index=jnp.asarray(STEPS - 1), video_length=F)
    # with alpha=0 everywhere, edit stream is untouched
    np.testing.assert_allclose(np.asarray(late), np.asarray(probs), rtol=1e-6)


def test_replace_controller_word_swap():
    t = WordTokenizer()
    ctx = make_controller(
        ["a silver jeep driving", "a silver bike driving"],
        t,
        STEPS,
        is_replace_controller=True,
        cross_replace_steps=1.0,
        self_replace_steps=0.5,
    )
    probs = _probs(jax.random.PRNGKey(4), 2 * P * F)
    out = control_attention(probs, ctx, is_cross=True, step_index=jnp.asarray(0), video_length=F)
    p = np.asarray(probs).reshape(2, P, F, H, Q, W)
    base = p[1, 0]
    mapper = np.asarray(ctx.replace_mapper[0])
    expected = np.einsum("fhqw,wn->fhqn", base, mapper)
    got = np.asarray(out).reshape(2, P, F, H, Q, W)[1, 1]
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-7)


def test_reweight_scales_words():
    t = WordTokenizer()
    prompts = ["a rabbit jumping", "a origami rabbit jumping"]
    eq_params = {"words": ["origami"], "values": [4.0]}
    ctx = make_controller(
        prompts, t, STEPS,
        is_replace_controller=False,
        cross_replace_steps=1.0,
        self_replace_steps=0.5,
        equalizer_params=eq_params,
    )
    eq = get_equalizer(prompts[1], ["origami"], [4.0], t)
    assert eq[0, 2] == 4.0 and eq[0, 1] == 1.0

    probs = _probs(jax.random.PRNGKey(5), 2 * P * F)
    out = control_attention(probs, ctx, is_cross=True, step_index=jnp.asarray(0), video_length=F)
    p = np.asarray(probs).reshape(2, P, F, H, Q, W)
    base = p[1, 0]
    mapper = np.asarray(ctx.refine_mapper[0])
    alphas = np.asarray(ctx.refine_alphas[0])
    refined = base[..., mapper] * alphas + p[1, 1] * (1 - alphas)
    expected = refined * np.asarray(eq)[0]
    got = np.asarray(out).reshape(2, P, F, H, Q, W)[1, 1]
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-7)


def test_equalizer_rejects_unmatched_word():
    """Satellite (ISSUE 4): a word that tokenizes to no position used to
    no-op silently (``eq[:, []] = val``) — the requested reweight never
    happened. Now it raises with the word in the message."""
    import pytest

    t = WordTokenizer()
    with pytest.raises(ValueError, match="'unicorn'"):
        get_equalizer("a rabbit jumping", ["unicorn"], [4.0], t)
    # the controller surface propagates the same failure
    with pytest.raises(ValueError, match="'unicorn'"):
        make_controller(
            ["a rabbit jumping", "a origami rabbit jumping"], t, STEPS,
            is_replace_controller=False, cross_replace_steps=1.0,
            self_replace_steps=0.5,
            equalizer_params={"words": ["unicorn"], "values": [4.0]},
        )


def test_equalizer_rejects_length_mismatch():
    """Satellite (ISSUE 4): ``zip(words, values)`` used to silently
    truncate a words/values length mismatch."""
    import pytest

    t = WordTokenizer()
    with pytest.raises(ValueError, match="length mismatch"):
        get_equalizer("a origami rabbit", ["origami", "rabbit"], [4.0], t)
    # scalar-vs-string normalization still works symmetrically
    eq = get_equalizer("a origami rabbit", "origami", 4.0, t)
    assert eq[0, 2] == 4.0


def test_temporal_replace_window():
    ctx, _ = _ctx(self_replace_steps=0.5)  # active for steps [0, 5)
    D = 4
    probs = _probs(jax.random.PRNGKey(6), 2 * P * D)  # (B, H, Q=4, W=77) stands in for (B,H,F,F)
    probs = probs[..., :4]  # (B, H, 4, 4) square temporal maps over 4 frames
    early = control_attention(probs, ctx, is_cross=False, step_index=jnp.asarray(0), video_length=4)
    late = control_attention(probs, ctx, is_cross=False, step_index=jnp.asarray(5), video_length=4)

    p = np.asarray(probs).reshape(2, P, D, probs.shape[1], 4, 4)
    e = np.asarray(early).reshape(2, P, D, probs.shape[1], 4, 4)
    # early: edit stream replaced by base
    np.testing.assert_allclose(e[1, 1], p[1, 0], rtol=1e-6)
    # late: untouched
    np.testing.assert_allclose(np.asarray(late), np.asarray(probs), rtol=1e-6)


def test_control_attention_jittable_under_scan():
    ctx, _ = _ctx()
    probs = _probs(jax.random.PRNGKey(7), 2 * P * F)

    def body(carry, step):
        out = control_attention(probs, ctx, is_cross=True, step_index=step, video_length=F)
        return carry, out.sum()

    _, sums = jax.lax.scan(body, 0.0, jnp.arange(STEPS))
    assert sums.shape == (STEPS,)


def test_control_attention_asymmetric_uncond_layout():
    """Fast mode drops the source-uncond stream: with U = P−1 uncond streams
    the conditional edit must be identical to the symmetric layout's."""
    ctx, _ = _ctx()
    probs = _probs(jax.random.PRNGKey(8), 2 * P * F)
    sym = control_attention(
        probs, ctx, is_cross=True, step_index=jnp.asarray(2), video_length=F
    )
    # strip the source-uncond stream (stream 0) from the batch
    asym_in = probs.reshape(2 * P, F, *probs.shape[1:])[1:].reshape(
        -1, *probs.shape[1:]
    )
    asym = control_attention(
        asym_in, ctx, is_cross=True, step_index=jnp.asarray(2), video_length=F,
        num_uncond=P - 1,
    )
    np.testing.assert_allclose(
        np.asarray(asym).reshape(2 * P - 1, F, *probs.shape[1:])[P - 1 :],
        np.asarray(sym).reshape(2 * P, F, *probs.shape[1:])[P:],
        rtol=1e-6,
    )


def test_spatial_replace_controller_is_attention_noop():
    from videop2p_tpu.control import make_spatial_replace_controller

    ctx = make_spatial_replace_controller(0.8, STEPS)
    assert ctx.spatial_replace_until == int((1 - 0.8) * STEPS)
    probs = _probs(jax.random.PRNGKey(9), 2 * P * F)
    out = control_attention(
        probs, ctx, is_cross=True, step_index=jnp.asarray(0), video_length=F
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(probs))
