"""Weight-converter tests.

The strongest check available offline: the CLIP importer is validated
NUMERICALLY against the real transformers torch model (same random weights →
same hidden states). The UNet mapping is validated by round-trip
(flax → torch-layout → flax is the identity) plus the temporal-keep-init
inflation rule; the VAE by round-trip through its own exporter-free path
(synthetic torch dict built from the inverse name map).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from videop2p_tpu.models import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.models.clip import CLIPTextConfig, CLIPTextEncoder
from videop2p_tpu.models.convert import (
    clip_params_from_torch,
    unet3d_params_from_torch,
    unet3d_params_to_torch,
    vae_params_from_torch,
)
from videop2p_tpu.models.vae import AutoencoderKL, VAEConfig


def test_clip_matches_transformers_torch():
    """Import random torch CLIPTextModel weights; flax forward must equal the
    torch forward to float tolerance."""
    import torch
    from transformers import CLIPTextConfig as HFConfig, CLIPTextModel

    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=2, max_position_embeddings=77,
        hidden_act="quick_gelu",
    )
    torch_model = CLIPTextModel(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in torch_model.state_dict().items()}

    cfg = CLIPTextConfig.tiny()
    model = CLIPTextEncoder(config=cfg)
    ids = np.array([[49, 3, 7, 12, 99] + [100] * 72], dtype=np.int32) % 128
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.asarray(ids))
    )["params"]
    params = clip_params_from_torch(sd, abstract)

    out_flax = model.apply({"params": params}, jnp.asarray(ids))
    with torch.no_grad():
        out_torch = torch_model(torch.tensor(ids, dtype=torch.long)).last_hidden_state
    np.testing.assert_allclose(
        np.asarray(out_flax), out_torch.numpy(), atol=2e-5
    )


@pytest.fixture(scope="module")
def tiny_unet_params():
    cfg = UNet3DConfig.tiny()
    model = UNet3DConditionModel(config=cfg)
    sample = jax.random.normal(jax.random.key(0), (1, 2, 8, 8, 4))
    text = jax.random.normal(jax.random.key(1), (1, 7, cfg.cross_attention_dim))
    variables = jax.jit(model.init)(jax.random.key(2), sample, jnp.asarray(0), text)
    return cfg, model, dict(variables)["params"], sample, text


def test_unet_roundtrip_identity(tiny_unet_params):
    cfg, model, params, _, _ = tiny_unet_params
    sd = unet3d_params_to_torch(params)
    # all torch keys use diffusers-style dotted names
    assert any(k.startswith("down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q") for k in sd)
    assert any("ff.net.0.proj" in k for k in sd)
    assert any("ff.net.2" in k for k in sd)
    assert any("time_embedding.linear_1" in k for k in sd)
    assert any("attn_temp" in k for k in sd)  # 3-D export keeps temporal keys
    restored, report = unet3d_params_from_torch(sd, params)
    assert report["kept_init"] == [] and report["unused"] == []
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(restored)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))


def test_unet_2d_inflation_keeps_temporal_init(tiny_unet_params):
    """Dropping the temporal keys from the state dict (= a genuine 2-D SD
    checkpoint) must keep fresh inits exactly for attn_temp/norm_temp
    (unet.py:446-448) and load everything else."""
    cfg, model, params, sample, text = tiny_unet_params
    sd = unet3d_params_to_torch(params)
    sd_2d = {k: v for k, v in sd.items() if "attn_temp" not in k and "norm_temp" not in k}
    # perturb all 2-D weights so "loaded" is distinguishable from "kept"
    sd_2d = {k: v + 1.0 for k, v in sd_2d.items()}
    restored, report = unet3d_params_from_torch(sd_2d, params)
    assert len(report["kept_init"]) > 0
    assert all("attn_temp" in p or "norm_temp" in p for p in report["kept_init"])
    flat_orig = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    for path, leaf in jax.tree_util.tree_flatten_with_path(restored)[0]:
        p = jax.tree_util.keystr(path)
        orig = np.asarray(flat_orig[path])
        if "attn_temp" in p or "norm_temp" in p:
            np.testing.assert_array_equal(np.asarray(leaf), orig, err_msg=p)
        else:
            assert not np.allclose(np.asarray(leaf), orig), p


def test_unet_missing_key_raises(tiny_unet_params):
    cfg, model, params, _, _ = tiny_unet_params
    sd = unet3d_params_to_torch(params)
    del sd["conv_in.weight"]
    with pytest.raises(KeyError, match="conv_in"):
        unet3d_params_from_torch(sd, params)


def test_vae_import_both_attention_namings():
    cfg = VAEConfig.tiny()
    model = AutoencoderKL(config=cfg)
    x = jax.random.normal(jax.random.key(0), (2, 16, 16, 3))
    variables = jax.jit(model.init)(jax.random.key(1), x, jax.random.key(2))
    params = dict(variables)["params"]

    # build a synthetic torch dict via the inverse of the importer's name map
    from videop2p_tpu.models.convert import _vae_flax_to_torch
    from flax import traverse_util

    flat = traverse_util.flatten_dict(params)
    sd = {}
    for path, leaf in flat.items():
        key, kind = _vae_flax_to_torch(path)
        arr = np.asarray(leaf)
        if arr.ndim == 4:
            arr = np.transpose(arr, (3, 2, 0, 1))
        elif kind == "dense" and arr.ndim == 2:
            arr = np.transpose(arr)
        sd[key] = arr
    restored = vae_params_from_torch(sd, params)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(restored)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))

    # 0.11-era attention names (query/key/value/proj_attn) also accepted
    sd_old = {}
    for k, v in sd.items():
        k = (
            k.replace(".to_q.", ".query.")
            .replace(".to_k.", ".key.")
            .replace(".to_v.", ".value.")
            .replace(".to_out.0.", ".proj_attn.")
        )
        sd_old[k] = v
    restored_old = vae_params_from_torch(sd_old, params)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(restored)[0],
        jax.tree_util.tree_flatten_with_path(restored_old)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))


def test_vae_encode_decode_shapes():
    from videop2p_tpu.models.vae import decode_video, encode_video

    cfg = VAEConfig.tiny()
    model = AutoencoderKL(config=cfg)
    video = jax.random.uniform(jax.random.key(0), (1, 3, 16, 16, 3)) * 2 - 1
    variables = jax.jit(model.init)(
        jax.random.key(1), video[:, 0], jax.random.key(2)
    )
    z = encode_video(model, variables, video, jax.random.key(3))
    assert z.shape == (1, 3, 8, 8, cfg.latent_channels)  # one downsample level
    z_mean = encode_video(model, variables, video, jax.random.key(4), sample=False)
    z_mean2 = encode_video(model, variables, video, jax.random.key(5), sample=False)
    np.testing.assert_array_equal(np.asarray(z_mean), np.asarray(z_mean2))
    out = decode_video(model, variables, z, chunk=2)
    assert out.shape == video.shape
    assert np.isfinite(np.asarray(out)).all()

    # sequential (lax.map) decode must match the unrolled loop exactly —
    # including a non-dividing remainder (here 3 frames, chunk 2: one full
    # chunk + a tail call) and under jit (its reason to exist: unrolled
    # chunks schedule concurrently inside a larger program and stack their
    # decoder temporaries)
    out_seq = jax.jit(
        lambda v, x: decode_video(model, v, x, chunk=2, sequential=True)
    )(variables, z)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_seq), atol=1e-5)


def test_pipeline_dir_roundtrip(tmp_path, tiny_unet_params):
    """save_pipeline -> load_pipeline reproduces the UNet params and config
    (the reference's save_pretrained / from_pretrained contract,
    run_tuning.py:387-393, run_videop2p.py:101-114)."""
    from videop2p_tpu.models.pipeline_io import load_pipeline, save_pipeline

    cfg, model, params, sample, text = tiny_unet_params
    out = str(tmp_path / "ckpt")
    save_pipeline(out, cfg, {"params": params},
                  scheduler_config={"beta_schedule": "scaled_linear"})
    loaded = load_pipeline(out, load_vae=False, load_text_encoder=False,
                           frame_attention=cfg.frame_attention)
    assert loaded.unet.config.block_out_channels == cfg.block_out_channels
    assert loaded.inflation_report["kept_init"] == []  # 3-D ckpt: all loaded
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(loaded.unet_params["params"])[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), err_msg=str(pa))
    # and the loaded model runs
    out_arr = jax.jit(loaded.unet.apply)(loaded.unet_params, sample, jnp.asarray(3), text)
    ref_arr = jax.jit(model.apply)({"params": params}, sample, jnp.asarray(3), text)
    np.testing.assert_allclose(np.asarray(out_arr), np.asarray(ref_arr), atol=1e-5)


def test_unet3d_matches_torch_reference():
    """Golden numerical parity: a hand-built torch mirror of the reference
    UNet3D (tests/torch_ref.py, semantics from
    /root/reference/tuneavideo/models/*) produces a diffusers-layout state
    dict; importing it through convert.unet3d_params_from_torch must make the
    flax forward equal the torch forward. This backs the converter beyond
    round-trip consistency (a consistent-but-wrong mapping would fail here)."""
    import torch

    from tests.torch_ref import TorchUNet3D

    cfg = UNet3DConfig.tiny()
    torch.manual_seed(0)
    tmodel = TorchUNet3D(cfg).eval()
    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}

    model = UNet3DConditionModel(config=cfg)
    B, F, S = 1, 2, 8
    x = np.random.RandomState(0).randn(B, F, S, S, cfg.in_channels).astype(np.float32)
    ctx = np.random.RandomState(1).randn(B, 7, cfg.cross_attention_dim).astype(np.float32)
    t = np.array([317], dtype=np.int32)

    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx))
    )["params"]
    params, report = unet3d_params_from_torch(sd, abstract)
    assert report["kept_init"] == [], report["kept_init"]
    assert report["unused"] == [], report["unused"]

    out_flax = model.apply({"params": params}, jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx))
    with torch.no_grad():
        out_torch = tmodel(
            torch.tensor(np.transpose(x, (0, 4, 1, 2, 3))),
            torch.tensor(t),
            torch.tensor(ctx),
        )
    out_torch = np.transpose(out_torch.numpy(), (0, 2, 3, 4, 1))
    np.testing.assert_allclose(np.asarray(out_flax), out_torch, atol=5e-5)


def test_vae_matches_torch_reference():
    """Golden numerical parity for the VAE importer: encode moments and the
    decode image from the hand-built torch AutoencoderKL (tests/torch_ref.py)
    must match the flax model after vae_params_from_torch."""
    import torch

    from tests.torch_ref import TorchVAE

    cfg = VAEConfig.tiny()
    torch.manual_seed(1)
    tvae = TorchVAE(cfg).eval()
    sd = {k: v.detach().numpy() for k, v in tvae.state_dict().items()}

    model = AutoencoderKL(config=cfg)
    x = np.random.RandomState(2).randn(2, 16, 16, cfg.in_channels).astype(np.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.asarray(x), jax.random.key(1))
    )
    params = vae_params_from_torch(sd, variables["params"])

    mean, logvar = model.apply(
        {"params": params}, jnp.asarray(x), method=model.encode
    )
    with torch.no_grad():
        moments = tvae.encode_moments(torch.tensor(np.transpose(x, (0, 3, 1, 2))))
        t_mean, t_logvar = moments.chunk(2, dim=1)
        z = t_mean  # decode the mean latent
        t_img = tvae.decode(z)
    np.testing.assert_allclose(
        np.asarray(mean), np.transpose(t_mean.numpy(), (0, 2, 3, 1)), atol=5e-5
    )
    np.testing.assert_allclose(
        np.asarray(logvar),
        np.clip(np.transpose(t_logvar.numpy(), (0, 2, 3, 1)), -30, 20),
        atol=5e-5,
    )
    img = model.apply({"params": params}, mean, method=model.decode)
    np.testing.assert_allclose(
        np.asarray(img), np.transpose(t_img.numpy(), (0, 2, 3, 1)), atol=5e-5
    )


def test_attention_maps_match_torch_reference():
    """BASELINE's 'pixel-identical attention maps': the head-averaged
    cross-attention probabilities the flax UNet sows into its store must
    match the torch mirror's probabilities at every controlled site."""
    import torch

    from tests import torch_ref
    from videop2p_tpu.pipelines.stores import flatten_store

    cfg = UNet3DConfig.tiny()
    torch.manual_seed(7)
    tmodel = torch_ref.TorchUNet3D(cfg).eval()
    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}

    model = UNet3DConditionModel(config=cfg)
    B, F, S = 1, 2, 8
    x = np.random.RandomState(3).randn(B, F, S, S, cfg.in_channels).astype(np.float32)
    ctx = np.random.RandomState(4).randn(B, 7, cfg.cross_attention_dim).astype(np.float32)
    t = np.array([123], dtype=np.int32)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx))
    )["params"]
    params, _ = unet3d_params_from_torch(sd, abstract)

    _, store = model.apply(
        {"params": params}, jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx),
        mutable=["attn_store"],
    )
    flax_maps = {
        path: np.asarray(leaf)
        for path, leaf in flatten_store(dict(store)["attn_store"])
        if "attn2" in path
    }
    assert flax_maps, "no cross maps sown"

    # capture the torch mirror's cross-attention probabilities per site
    torch_maps = []

    class ProbeAttention(torch_ref._Attention):
        def attend(self, q, k, v):
            b, n, c = q.shape
            h, d = self.heads, c // self.heads
            qh = q.reshape(b, n, h, d).transpose(1, 2)
            kh = k.reshape(b, k.shape[1], h, d).transpose(1, 2)
            vh = v.reshape(b, v.shape[1], h, d).transpose(1, 2)
            sim = torch.einsum("bhqd,bhkd->bhqk", qh, kh) * d**-0.5
            probs = sim.float().softmax(dim=-1).to(q.dtype)
            torch_maps.append(probs.mean(dim=1).detach().numpy())
            out = torch.einsum("bhqk,bhkd->bhqd", probs, vh)
            return out.transpose(1, 2).reshape(b, n, c)

    for mod in tmodel.modules():
        if isinstance(mod, torch_ref.BasicTransformerBlock):
            probe = ProbeAttention(
                mod.attn2.to_q.in_features, mod.attn2.to_k.in_features, mod.attn2.heads
            )
            probe.load_state_dict(mod.attn2.state_dict())
            mod.attn2 = probe
    with torch.no_grad():
        tmodel(
            torch.tensor(np.transpose(x, (0, 4, 1, 2, 3))),
            torch.tensor(t), torch.tensor(ctx),
        )

    assert len(torch_maps) == len(flax_maps)
    # flax store is tree-ordered (down → mid → up by name); torch hooks fire
    # in forward order — the same order for this architecture
    for (path, fm), tm in zip(sorted(flax_maps.items()), _forward_order(torch_maps, flax_maps)):
        np.testing.assert_allclose(fm, tm, atol=2e-5, err_msg=path)


def _forward_order(torch_maps, flax_maps):
    """Map torch forward-order probes to the flax store's tree order."""
    # forward order: down_blocks_0, mid, up_blocks_1; tree (alphabetical)
    # order: down_blocks_0, mid_block, up_blocks_1 — identical here
    return torch_maps


def test_exported_state_dict_loads_into_torch_reference(tiny_unet_params):
    """Stage-1's export must be consumable by the reference architecture:
    the torch mirror load_state_dict()s our exported dict strictly, and its
    forward matches the flax forward."""
    import torch

    from tests.torch_ref import TorchUNet3D

    cfg, model, params, sample, text = tiny_unet_params
    sd = unet3d_params_to_torch(params)
    tmodel = TorchUNet3D(cfg)
    missing, unexpected = tmodel.load_state_dict(
        {k: torch.tensor(np.ascontiguousarray(v)) for k, v in sd.items()}, strict=True
    )
    assert not missing and not unexpected
    tmodel.eval()
    t = np.array([42], dtype=np.int32)
    out_flax = model.apply(
        {"params": params}, sample, jnp.asarray(t), text
    )
    with torch.no_grad():
        out_torch = tmodel(
            torch.tensor(np.transpose(np.asarray(sample), (0, 4, 1, 2, 3))),
            torch.tensor(t),
            torch.tensor(np.asarray(text)),
        )
    np.testing.assert_allclose(
        np.asarray(out_flax),
        np.transpose(out_torch.numpy(), (0, 2, 3, 4, 1)),
        atol=5e-4,  # f32 reduction-order noise at flax-init weight scales
    )


# ---------------------------------------------------------------------- #
# SD-1.5 full key-manifest coverage (ISSUE 3 satellite; VERDICT r5 #6)
# ---------------------------------------------------------------------- #


def _torch_manifest_entry(path, leaf_shape):
    """(torch_key, torch-layout shape) for one flax param path — the
    inverse of convert's import transforms, matching the real diffusers
    layout (conv kernels OIHW, dense weights transposed, SD-1.x
    transformer proj_in/proj_out stored as 1×1 convs)."""
    from videop2p_tpu.models.convert import _flax_path_to_torch

    torch_key, kind = _flax_path_to_torch(path)
    if kind == "conv":
        kh, kw, ci, co = leaf_shape
        return torch_key, (co, ci, kh, kw)
    if kind == "dense":
        ci, co = leaf_shape
        if path[-2] in ("proj_in", "proj_out") and not any(
            t.startswith("blocks_") for t in path
        ):
            return torch_key, (co, ci, 1, 1)
        return torch_key, (co, ci)
    return torch_key, tuple(leaf_shape)


@pytest.fixture(scope="module")
def sd15_manifest():
    """The FULL SD-1.5 UNet topology (UNet3DConfig.sd15()) as abstract flax
    params plus the enumerated torch key manifest. Arrays are zero-stride
    broadcast views — the manifest costs shape metadata, not 3.4 GB."""
    from flax import traverse_util

    cfg = UNet3DConfig.sd15()
    model = UNet3DConditionModel(config=cfg)
    abstract = jax.eval_shape(
        model.init, jax.random.key(0),
        jax.ShapeDtypeStruct((1, 2, 64, 64, 4), jnp.bfloat16),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((1, 77, 768), jnp.bfloat16),
    )["params"]
    # bf16 target leaves halve the materialized import (shapes are what the
    # manifest tests; dtype is the caller's choice in convert)
    abstract = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16), abstract
    )
    flat = traverse_util.flatten_dict(abstract)
    manifest, temporal_manifest, temporal_paths = {}, {}, []
    for path, leaf in flat.items():
        torch_key, tshape = _torch_manifest_entry(path, tuple(leaf.shape))
        arr = np.broadcast_to(np.zeros((), np.float32), tshape)
        pstr = "/".join(path)
        if "attn_temp" in pstr or "norm_temp" in pstr:
            temporal_paths.append(pstr)
            temporal_manifest[torch_key] = arr
            continue
        # bijection: no two flax params may claim the same torch key
        assert torch_key not in manifest, torch_key
        manifest[torch_key] = arr
    return abstract, flat, manifest, temporal_manifest, temporal_paths


def test_sd15_2d_manifest_fully_consumed_and_initialized(sd15_manifest):
    """A genuine SD-1.5 2-D checkpoint manifest: every torch key consumed,
    every flax param initialized, and EXACTLY the temporal params keep
    their fresh init (the reference's '_temp.'-keys rule, unet.py:446-448).
    686 keys — the diffusers SD-1.5 UNet state-dict size — pinned so a
    mapping drift cannot silently shrink coverage."""
    abstract, flat, manifest, _, temporal_paths = sd15_manifest
    assert len(manifest) == 686
    assert len(temporal_paths) == 112
    params, report = unet3d_params_from_torch(manifest, abstract)
    assert report["unused"] == []
    assert sorted(report["kept_init"]) == sorted(temporal_paths)
    # spot-pin known diffusers keys (layout included) against drift
    assert manifest["conv_in.weight"].shape == (320, 4, 3, 3)
    assert manifest["time_embedding.linear_1.weight"].shape == (1280, 320)
    assert manifest[
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q.weight"
    ].shape == (320, 320)
    assert manifest["down_blocks.0.attentions.0.proj_in.weight"].shape == (
        320, 320, 1, 1)  # SD-1.x stores transformer projections as 1×1 convs
    # every non-temporal flax param came out initialized at the right shape
    from flax import traverse_util

    out_flat = traverse_util.flatten_dict(params)
    assert set(out_flat) == set(flat)
    for path, leaf in out_flat.items():
        pstr = "/".join(path)
        if "attn_temp" in pstr or "norm_temp" in pstr:
            continue  # kept-init: abstract leaves pass through unrealized
        assert isinstance(leaf, np.ndarray), pstr
        assert tuple(leaf.shape) == tuple(flat[path].shape), pstr


def test_sd15_tuned_3d_manifest_loads_without_kept_init(sd15_manifest):
    """A tuned Stage-1 checkpoint DOES carry the temporal keys — through
    the same path nothing may fall back to fresh init and nothing may go
    unconsumed."""
    abstract, _, manifest, temporal_manifest, temporal_paths = sd15_manifest
    assert len(temporal_manifest) == len(temporal_paths)
    full = {**manifest, **temporal_manifest}
    params, report = unet3d_params_from_torch(full, abstract,
                                              strict_missing=True)
    assert report["kept_init"] == []
    assert report["unused"] == []
