"""Tracing + SLO tests (ISSUE 14): the span model and traceparent
propagation (tolerant parsing, concurrent emission without torn lines),
ledger rotation chains, the Prometheus exposition golden format, the
engine's full request-lifecycle spans (tracing OFF pinned bit-exact, ON
yielding the queue/resolve/dispatch/decode critical path under one
trace), the 2-replica router round trip (router + replica ledgers join
into ONE causal tree via tools/trace_view.py), the loadgen's per-tenant
queue-wait attribution, and the SLO engine's error-budget math with
obs_diff's exit-1 teeth on budget burn and segment-tail regressions.
"""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from videop2p_tpu.obs import RunLedger, read_ledger
from videop2p_tpu.obs.spans import (
    SPAN_EVENT_FIELDS,
    SPAN_SEGMENTS,
    Tracer,
    format_traceparent,
    make_span_id,
    make_trace_id,
    parse_traceparent,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_tracing_test",
        os.path.join(_REPO, "tools", f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------ span model / header ---


def test_traceparent_round_trip_and_tolerant_parse():
    tid, sid = make_trace_id(), make_span_id()
    assert len(tid) == 32 and len(sid) == 16
    header = format_traceparent(tid, sid)
    assert header == f"00-{tid}-{sid}-01"
    assert parse_traceparent(header) == (tid, sid)
    # malformed headers from foreign clients degrade to "fresh trace",
    # never to an error — every rejection returns None
    for bad in (None, "", 42, "garbage", "00-short-span-01",
                f"00-{tid}-{sid}",            # too few parts
                f"00-{'z' * 32}-{sid}-01",    # non-hex trace
                f"00-{'0' * 32}-{sid}-01",    # all-zeros trace (W3C invalid)
                f"00-{tid}-{'0' * 16}-01"):   # all-zeros span
        assert parse_traceparent(bad) is None, bad


def test_disabled_tracer_is_inert(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path) as led:
        tracer = Tracer(led, enabled=False)
        assert tracer.enabled is False
        assert tracer.emit("serve.request", trace_id=make_trace_id(),
                           span_id=make_span_id()) is None
    assert not any(e["event"] == "span" for e in read_ledger(path))
    # no ledger at all forces disabled even when asked for
    assert Tracer(None, enabled=True).enabled is False


def test_span_event_schema_and_attrs(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    tid, root = make_trace_id(), make_span_id()
    with RunLedger(path) as led:
        tracer = Tracer(led, enabled=True)
        fields = tracer.emit("serve.dispatch", trace_id=tid, span_id=root,
                             duration_s=0.1234567, batch_size=3)
        assert fields["duration_s"] == 0.123457  # rounded to 6
    spans = [e for e in read_ledger(path) if e["event"] == "span"]
    assert len(spans) == 1
    assert set(SPAN_EVENT_FIELDS) <= set(spans[0])
    assert spans[0]["trace_id"] == tid and spans[0]["span_id"] == root
    assert spans[0]["parent_id"] is None and spans[0]["status"] == "ok"
    assert spans[0]["batch_size"] == 3          # attrs ride along
    assert isinstance(spans[0]["wall_ns"], int) and spans[0]["wall_ns"] > 0


def test_concurrent_span_emission_no_torn_lines(tmp_path):
    """8 threads × 25 spans through ONE tracer: every line parses, every
    span arrives exactly once, and the per-thread parent links survive —
    the ledger lock is the only serialization point."""
    path = str(tmp_path / "ledger.jsonl")
    n_threads, n_spans = 8, 25
    roots = {}
    with RunLedger(path) as led:
        tracer = Tracer(led, enabled=True)

        def worker(t):
            tid, root = make_trace_id(), make_span_id()
            roots[t] = (tid, root)
            tracer.emit("serve.request", trace_id=tid, span_id=root)
            for i in range(n_spans - 1):
                tracer.emit("serve.dispatch", trace_id=tid,
                            span_id=make_span_id(), parent_id=root,
                            duration_s=0.001 * i, idx=i)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    spans = [e for e in read_ledger(path) if e["event"] == "span"]
    assert len(spans) == n_threads * n_spans
    by_trace = {}
    for s in spans:
        assert set(SPAN_EVENT_FIELDS) <= set(s)
        by_trace.setdefault(s["trace_id"], []).append(s)
    assert len(by_trace) == n_threads
    for tid, root in roots.values():
        tspans = by_trace[tid]
        assert len(tspans) == n_spans
        children = [s for s in tspans if s["parent_id"] is not None]
        assert all(s["parent_id"] == root for s in children)
        assert sorted(s["idx"] for s in children) == list(range(n_spans - 1))


# ------------------------------------------------------ rotation (b) ---


def test_ledger_rotation_chain_and_marker(tmp_path):
    """RunLedger(max_bytes=...) rotates to <stem>.1.jsonl logrotate-style,
    stamps a ledger_rotated marker into the fresh file, and read_ledger
    replays the whole chain oldest-first as one stream."""
    path = str(tmp_path / "serve_ledger.jsonl")
    with RunLedger(path, max_bytes=1200) as led:
        for i in range(60):
            led.event("tick", seq=i, pad="x" * 40)
    rotated = sorted(p.name for p in tmp_path.glob("serve_ledger.*.jsonl"))
    assert rotated, "no rotation happened — lower max_bytes"
    assert "serve_ledger.1.jsonl" in rotated
    assert os.path.getsize(path) <= 1200 + 512  # live file stays bounded
    events = read_ledger(path)
    markers = [e for e in events if e["event"] == "ledger_rotated"]
    assert len(markers) == len(rotated)
    for m in markers:
        assert m["previous"].endswith(".1.jsonl")
        assert m["rotated_bytes"] > 0 and m["index"] >= 1
    # the chain replays every tick exactly once, in write order
    seqs = [e["seq"] for e in events if e["event"] == "tick"]
    assert seqs == list(range(60))
    # markers in the newest rotated segments carry ASCENDING indices
    idx = [m["index"] for m in markers]
    assert idx == sorted(idx) == list(range(1, len(markers) + 1))


def test_run_history_scan_skips_rotated_segments(tmp_path):
    """RunHistory.scan reads rotated chains through the base ledger only —
    scanning <stem>.N.jsonl directly would double-count every run."""
    from videop2p_tpu.obs.history import RunHistory

    path = str(tmp_path / "ledger.jsonl")
    with RunLedger(path, max_bytes=600) as led:
        for i in range(40):
            led.event("tick", seq=i, pad="y" * 30)
    assert list(tmp_path.glob("ledger.*.jsonl"))  # rotation happened
    hist = RunHistory.scan(str(tmp_path))
    assert len(hist.runs) == 1  # one run, not one per segment


# --------------------------------------------------- prometheus (a) ----


def test_prometheus_golden_format():
    """Byte-for-byte pin of the text exposition (version 0.0.4): sorted
    metrics, one # HELP + # TYPE pair per metric (ISSUE 17), labeled
    fan-out sections, bools as 1/0, non-finite literals, strings
    skipped."""
    from videop2p_tpu.obs.prom import (
        PROMETHEUS_CONTENT_TYPE,
        render_prometheus,
    )

    def _hdr(name):
        return (f"# HELP {name} videop2p /metrics gauge.\n"
                f"# TYPE {name} gauge\n")

    metrics = {
        "warm": True,
        "spec": "abc123",                    # identity string: skipped
        "queue_depth": 2,
        # ISSUE 19: the cost plane's capacity section rides the generic
        # nested-dict render as videop2p_capacity_* gauges
        "capacity": {"busy_fraction": 0.25, "padding_waste": 0.5},
        "compile": {"events": 4, "total_s": 1.25},
        "requests": {"done": 3, "error": 1},
        "tenants": {"a": {"error_rate": 0.0, "requests": 2,
                          "device_seconds": 1.5}},
        # ISSUE 20: the router's per-replica probe verdicts — quarantined
        # bool becomes a 1/0 gauge, the probe_status string is skipped
        "replicas": {"r0": {"healthy": True, "requests": {"done": 3},
                            "nan_gauge": float("nan"),
                            "probe_status": "pass",
                            "quarantined": False}},
        "inf_gauge": float("inf"),
    }
    assert render_prometheus(metrics) == (
        _hdr("videop2p_capacity_busy_fraction")
        + "videop2p_capacity_busy_fraction 0.25\n"
        + _hdr("videop2p_capacity_padding_waste")
        + "videop2p_capacity_padding_waste 0.5\n"
        + _hdr("videop2p_compile_events")
        + "videop2p_compile_events 4\n"
        + _hdr("videop2p_compile_total_s")
        + "videop2p_compile_total_s 1.25\n"
        + _hdr("videop2p_inf_gauge")
        + "videop2p_inf_gauge +Inf\n"
        + _hdr("videop2p_queue_depth")
        + "videop2p_queue_depth 2\n"
        + _hdr("videop2p_replica_healthy")
        + 'videop2p_replica_healthy{replica="r0"} 1\n'
        + _hdr("videop2p_replica_nan_gauge")
        + 'videop2p_replica_nan_gauge{replica="r0"} NaN\n'
        + _hdr("videop2p_replica_quarantined")
        + 'videop2p_replica_quarantined{replica="r0"} 0\n'
        + _hdr("videop2p_replica_requests_total")
        + 'videop2p_replica_requests_total{replica="r0",status="done"} 3\n'
        + _hdr("videop2p_requests_total")
        + 'videop2p_requests_total{status="done"} 3\n'
        + 'videop2p_requests_total{status="error"} 1\n'
        + _hdr("videop2p_tenant_device_seconds")
        + 'videop2p_tenant_device_seconds{tenant="a"} 1.5\n'
        + _hdr("videop2p_tenant_error_rate")
        + 'videop2p_tenant_error_rate{tenant="a"} 0\n'
        + _hdr("videop2p_tenant_requests")
        + 'videop2p_tenant_requests{tenant="a"} 2\n'
        + _hdr("videop2p_warm")
        + "videop2p_warm 1\n"
    )
    assert render_prometheus({}) == ""
    assert "version=0.0.4" in PROMETHEUS_CONTENT_TYPE


# ------------------------------------------------------ SLO engine -----


def test_slo_budget_burn_math_and_absent_metric_skip():
    from videop2p_tpu.obs.slo import SLO_REPORT_FIELDS, SLOSpec, evaluate_slos

    specs = (
        SLOSpec("availability", "reliability", "serve", "error_rate",
                target=0.01, mode="rate_max"),
        SLOSpec("served_p99", "timing", "e2e", "blocked_p99_s",
                target=10.0, mode="value_max"),
        SLOSpec("seam_psnr", "stream", "stream", "seam_min_psnr",
                target=15.0, mode="value_min"),
        SLOSpec("absent", "timing", "nope", "blocked_p99_s", target=1.0),
    )
    record = {
        "reliability": {"serve": {"error_rate": 0.02}},   # 2× the budget
        "timing": {"e2e": {"blocked_p99_s": 5.0}},        # half the budget
        "stream": {"stream": {"seam_min_psnr": 30.0}},    # 2× the floor
    }
    results = {r["name"]: r for r in evaluate_slos(record, specs)}
    assert "absent" not in results  # missing metric SKIPS, never fakes
    for r in results.values():
        assert set(SLO_REPORT_FIELDS) == set(r)
    assert results["availability"]["budget_burn"] == pytest.approx(2.0)
    assert results["availability"]["compliant"] is False
    assert results["served_p99"]["budget_burn"] == pytest.approx(0.5)
    assert results["served_p99"]["compliant"] is True
    # value_min burns as target/actual: more headroom = less burn
    assert results["seam_psnr"]["budget_burn"] == pytest.approx(0.5)
    assert results["seam_psnr"]["compliant"] is True


def test_obs_diff_gates_slo_burn_and_segment_tail(tmp_path):
    """THE gate acceptance: obs_diff exits 0 on self-compare, 1 when the
    candidate burns >25% more error budget, and 1 when one critical-path
    segment's p99 regresses — naming WHICH stage moved."""
    from videop2p_tpu.obs.slo import emit_slo_reports

    def write(path, *, err_scale=1.0, seg_scale=1.0):
        with RunLedger(str(path)) as led:
            tracer = Tracer(led, enabled=True)
            tid = make_trace_id()
            for i in range(8):
                for name in SPAN_SEGMENTS:
                    scale = seg_scale if name == "serve.dispatch" else 1.0
                    tracer.emit(name, trace_id=tid, span_id=make_span_id(),
                                duration_s=scale * (0.05 + 0.01 * i))
            emit_slo_reports(led, {
                "reliability": {"serve": {"error_rate": 0.004 * err_scale}},
            })
        return str(path)

    base = write(tmp_path / "base.jsonl")
    burn = write(tmp_path / "burn.jsonl", err_scale=3.0)
    seg = write(tmp_path / "seg.jsonl", seg_scale=2.0)
    obs_diff = _load_tool("obs_diff")
    assert obs_diff.main(["obs_diff.py", base, base]) == 0
    assert obs_diff.main(["obs_diff.py", base, burn]) == 1
    assert obs_diff.main(["obs_diff.py", base, seg]) == 1


# ------------------------------------------------- trace_view tool -----


def test_trace_view_joins_ledgers_into_one_tree(tmp_path, capsys):
    """Spans scattered across TWO ledgers (a router's and a replica's)
    join into one causal tree keyed on trace_id, with the critical-path
    split summed from the segment spans."""
    tid = make_trace_id()
    root, mid = make_span_id(), make_span_id()
    with RunLedger(str(tmp_path / "router.jsonl")) as led:
        Tracer(led, enabled=True).emit(
            "router.submit", trace_id=tid, span_id=root,
            duration_s=0.5, replica="r0")
    with RunLedger(str(tmp_path / "replica.jsonl")) as led:
        tr = Tracer(led, enabled=True)
        tr.emit("serve.request", trace_id=tid, span_id=mid,
                parent_id=root, duration_s=0.4)
        tr.emit("serve.dispatch", trace_id=tid, span_id=make_span_id(),
                parent_id=mid, duration_s=0.3)
        tr.emit("stray", trace_id=make_trace_id(), span_id=make_span_id())

    trace_view = _load_tool("trace_view")
    paths = [str(tmp_path / "router.jsonl"), str(tmp_path / "replica.jsonl")]
    assert trace_view.main(["--json"] + paths) == 0
    doc = json.loads(capsys.readouterr().out)
    joined = [t for t in doc["traces"] if t["trace_id"] == tid]
    assert len(joined) == 1 and len(joined[0]["spans"]) == 3
    assert joined[0]["segments"] == {"dispatch": pytest.approx(0.3)}
    assert doc["segment_percentiles"]["dispatch"]["count"] == 1
    # the tree renders with the router span as root
    assert trace_view.main(["--trace", tid[:8]] + paths) == 0
    out = capsys.readouterr().out
    assert "router.submit" in out and out.index("router.submit") < \
        out.index("serve.request") < out.index("serve.dispatch")
    # zero spans is "tracing was off", not breakage; unreadable input is
    assert trace_view.main([str(tmp_path / "router.jsonl"),
                            "--trace", "ffff"]) == 0
    capsys.readouterr()
    assert trace_view.main([str(tmp_path / "missing.jsonl")]) == 2


# --------------------------------------------- engine lifecycle (CPU) ---

_SPEC_KW = dict(checkpoint=None, tiny=True, width=16, video_len=2, steps=2)
_PROMPTS = ("a rabbit is jumping", "a origami rabbit is jumping")


@pytest.fixture(scope="module")
def programs():
    from videop2p_tpu.serve import ProgramSet, ProgramSpec

    ps = ProgramSet(ProgramSpec(**_SPEC_KW))
    ps.warm(_PROMPTS, batch_sizes=(2,))
    return ps


def _request(**overrides):
    from videop2p_tpu.serve import EditRequest

    kw = dict(image_path="data/rabbit", prompt=_PROMPTS[0],
              prompts=list(_PROMPTS), save_name="traced")
    kw.update(overrides)
    return EditRequest(**kw)


def _engine(programs, tmp_root, name, **kw):
    from videop2p_tpu.serve import EditEngine, ProgramSpec

    return EditEngine(
        ProgramSpec(**_SPEC_KW), out_dir=os.path.join(tmp_root, name),
        programs=programs, keep_videos=True, **kw,
    )


def test_engine_tracing_off_bit_exact_and_on_full_lifecycle(
        programs, tmp_path):
    """THE single-engine acceptance: tracing OFF writes zero span events
    and the result record carries no trace fields (bit-exact off path);
    tracing ON yields the SAME video with the full lifecycle under one
    trace — a serve.request root and every critical-path segment."""
    off = _engine(programs, str(tmp_path), "off")
    try:
        r_off = off.result(off.submit(_request(seed=7)), wait_s=300.0)
        assert r_off["status"] == "done", r_off.get("error")
        v_off = off.videos(r_off["id"])
    finally:
        off.close()
    assert "trace_id" not in r_off
    assert not any(e["event"] == "span"
                   for e in read_ledger(off.ledger.path))

    on = _engine(programs, str(tmp_path), "on", tracing=True, slo=True)
    try:
        rid = on.submit(_request(seed=7))
        r_on = on.result(rid, wait_s=300.0)
        assert r_on["status"] == "done", r_on.get("error")
        v_on = on.videos(r_on["id"])
    finally:
        on.close()
    assert np.array_equal(v_off, v_on)  # tracing never touches the math
    tid = r_on["trace_id"]
    assert len(tid) == 32
    spans = [e for e in read_ledger(on.ledger.path) if e["event"] == "span"]
    mine = [s for s in spans if s["trace_id"] == tid]
    names = {s["name"] for s in mine}
    assert set(SPAN_SEGMENTS) <= names  # queue/resolve/dispatch/decode
    assert "serve.request" in names and "serve.batch" in names
    roots = [s for s in mine if s["name"] == "serve.request"]
    assert len(roots) == 1 and roots[0]["status"] == "done"
    assert roots[0]["span_id"] == r_on["span_id"]
    # every lifecycle span parents onto the request root
    for s in mine:
        if s["name"] in SPAN_SEGMENTS:
            assert s["parent_id"] == roots[0]["span_id"]
    # the close()-time SLO evaluation landed compliant objectives
    reports = [e for e in read_ledger(on.ledger.path)
               if e["event"] == "slo_report"]
    assert {r["name"] for r in reports} >= {"availability"}
    assert all(r["compliant"] for r in reports)
    # ... and history extracts both new sections for the diff gates
    from videop2p_tpu.obs.history import extract_run, split_runs

    rec = extract_run(split_runs(read_ledger(on.ledger.path))[-1])
    assert set(rec["segments"]) == set(SPAN_SEGMENTS.values())
    assert rec["slo"]["availability"]["compliant"] == 1.0


def test_engine_continues_caller_traceparent(programs, tmp_path):
    """An inbound traceparent re-parents the whole request tree under the
    caller's trace — the cross-hop join contract."""
    caller_tid, caller_span = make_trace_id(), make_span_id()
    eng = _engine(programs, str(tmp_path), "cont", tracing=True)
    try:
        rid = eng.submit(
            _request(seed=9),
            traceparent=format_traceparent(caller_tid, caller_span))
        rec = eng.result(rid, wait_s=300.0)
        assert rec["status"] == "done", rec.get("error")
        assert rec["trace_id"] == caller_tid
        # malformed header degrades to a fresh trace, not an error
        rid2 = eng.submit(_request(seed=10), traceparent="bogus-header")
        rec2 = eng.result(rid2, wait_s=300.0)
        assert rec2["status"] == "done" and len(rec2["trace_id"]) == 32
        assert rec2["trace_id"] != caller_tid
    finally:
        eng.close()
    spans = [e for e in read_ledger(eng.ledger.path)
             if e["event"] == "span" and e["trace_id"] == caller_tid]
    roots = [s for s in spans if s["name"] == "serve.request"]
    assert len(roots) == 1 and roots[0]["parent_id"] == caller_span


# ------------------------------------------ fleet round trip (HTTP) -----


@pytest.fixture(scope="module")
def traced_fleet(programs, tmp_path_factory):
    """Two inproc replicas (tracing ON) behind a tracing router's HTTP
    front door — the 2-replica acceptance fixture."""
    from videop2p_tpu.serve import ReplicaSupervisor, Router, RouterServer

    root = tmp_path_factory.mktemp("traced_fleet")
    sup = ReplicaSupervisor(
        programs.spec, 2, out_dir=str(root), programs=programs,
        warm_prompts=_PROMPTS,
        engine_kwargs=dict(keep_videos=True, tracing=True),
    )
    sup.start()
    router = Router(sup.urls, probe_ttl_s=0.05, tracing=True,
                    ledger_path=str(root / "router_ledger.jsonl"))
    server = RouterServer(router).start()
    yield sup, router, server
    server.close()
    sup.stop()


def test_router_replica_traceparent_round_trip(traced_fleet, tmp_path,
                                               capsys):
    """THE fleet acceptance: a traced request through the router's real
    HTTP hop produces router AND replica spans sharing one trace_id, and
    trace_view joins the ledgers into one tree with the segment split."""
    from videop2p_tpu.serve.client import EngineClient

    sup, router, server = traced_fleet
    client = EngineClient(server.url)
    tids = []
    for seed in (21, 22):
        tid, sid = make_trace_id(), make_span_id()
        rid = client.submit({**_request(seed=seed).to_dict()},
                            traceparent=format_traceparent(tid, sid))
        rec = client.wait(rid, timeout_s=300.0)
        assert rec["status"] == "done", rec.get("error")
        tids.append(tid)

    router_spans = [e for e in read_ledger(router.ledger.path)
                    if e["event"] == "span"]
    assert {s["trace_id"] for s in router_spans} >= set(tids)
    replica_ledgers = [r.engine.ledger.path for r in sup.replicas]
    replica_spans = [e for p in replica_ledgers for e in read_ledger(p)
                     if e["event"] == "span"]
    for tid in tids:
        mine = [s for s in replica_spans if s["trace_id"] == tid]
        names = {s["name"] for s in mine}
        assert set(SPAN_SEGMENTS) <= names and "serve.request" in names
        # the replica's root hangs off the ROUTER's span — the HTTP hop
        # carried the re-parented traceparent, not the caller's
        rspan = next(s for s in router_spans if s["trace_id"] == tid)
        root = next(s for s in mine if s["name"] == "serve.request")
        assert root["parent_id"] == rspan["span_id"]

    trace_view = _load_tool("trace_view")
    assert trace_view.main(
        ["--json", router.ledger.path] + replica_ledgers) == 0
    doc = json.loads(capsys.readouterr().out)
    joined = {t["trace_id"]: t for t in doc["traces"]}
    for tid in tids:
        assert len(joined[tid]["ledgers"]) >= 2   # the JOIN happened
    assert set(doc["segment_percentiles"]) == set(SPAN_SEGMENTS.values())

    # satellite (a) rides the same fleet: both tiers serve the Prometheus
    # exposition over real HTTP
    text = client.metrics_prometheus()
    assert "# TYPE videop2p_replica_requests_total gauge" in text
    assert 'videop2p_replica_in_flight{replica="replica0"} 0' in text
    # ISSUE 20 satellite (b): the per-replica quarantine verdict rides
    # the same exposition (no prober wired → nobody quarantined)
    assert 'videop2p_replica_quarantined{replica="replica0"} 0' in text
    rtext = EngineClient(sup.urls[0]).metrics_prometheus()
    assert "# TYPE videop2p_queue_depth gauge" in rtext


def test_loadgen_per_tenant_queue_wait_and_slo(programs, tmp_path):
    """Satellite (c): the loadgen threads the engine's queue_wait_s into
    per-tenant reservoirs — starvation shows up per lane — and --slo
    lands slo_report events in the loadgen ledger."""
    loadgen = _load_tool("serve_loadgen")
    eng = _engine(programs, str(tmp_path), "lg",
                  scheduler="fair", tenants="A:3,B:1", tracing=True)
    try:
        record = loadgen.run_loadgen(
            loadgen._InprocTarget(eng, timeout_s=300.0),
            _request().to_dict(),
            requests=4, concurrency=2,
            ledger_path=str(tmp_path / "lg.jsonl"),
            meta={"target": "test"}, tenants={"A": 3, "B": 1},
            tracing=True, slo=True,
        )
    finally:
        eng.close()
    assert record["done"] == 4
    for t in ("A", "B"):
        assert record["tenants"][t]["queue_wait_p99_s"] is not None
        assert record["tenants"][t]["queue_wait_p99_s"] >= 0.0
    events = read_ledger(str(tmp_path / "lg.jsonl"))
    assert [e for e in events if e["event"] == "span"]
    reports = {e["name"]: e for e in events if e["event"] == "slo_report"}
    assert {"availability", "deadline_miss_rate"} <= set(reports)
    assert all(r["compliant"] for r in reports.values())
    # the e2e reservoir carries its exemplar trace ids (tracing was on)
    timing = [e for e in events if e["event"] == "execute_timing"
              and e["program"] == "loadgen_request"]
    assert timing and timing[-1]["max_trace_id"]
