"""Scan-fused mixed-precision null-text inversion (pipelines/inversion.py
``null_text_optimization_fused`` + the ``null_text_precision`` knob).

CPU-runnable gates for the official-mode perf work:

  * mixed-vs-fp32 reconstruction parity, pinned as a PSNR band on the same
    replay the bench's ``official_fixed3_recon_psnr_db`` measures;
  * the fused single-dispatch program is the host-chunked program
    (identical outputs, fewer dispatches);
  * the fused loop's on-device early stop takes no more inner Adam steps
    than a faithful host-Python-loop-with-break reference;
  * the official-mode e2e record schema (bench.official_e2e_records) is
    exercised off-TPU — keys stable, values null when unmeasured;
  * CachedSource float8 upcast follows the sibling captured maps' dtype
    (ADVICE r5 item 1).

Fake denoisers keep everything eager-CPU-fast (the SURVEY §4 strategy).
"""

import importlib.util
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.pipelines import (
    ddim_inversion,
    edit_sample,
    null_text_optimization,
    null_text_optimization_fused,
    official_edit,
)

STEPS = 8
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C)
GUIDANCE = 7.5


@pytest.fixture(scope="module")
def sched():
    return DDIMScheduler.create_sd()


def text_unet():
    """Denoiser whose output depends on the text embedding and latent — a
    real objective for the optimization, computed in the INPUT dtype (so
    the mixed knob's bf16 boundary cast genuinely changes the forward)."""

    def fn(params, sample, t, text, control=None):
        bias = jnp.mean(text, axis=(1, 2))  # (B,)
        return 0.1 * sample + bias[:, None, None, None, None], {}

    return fn


@pytest.fixture(scope="module")
def problem(sched):
    fn = text_unet()
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond, num_inference_steps=STEPS)
    return fn, x0, cond, uncond, traj


def _recon_psnr(sched, fn, traj, cond, uncond, null_seq, x0):
    """PSNR of the CFG replay driven by the optimized embeddings — the same
    reconstruction the bench's official_fixed3_recon_psnr_db gates."""
    out = edit_sample(
        fn, None, sched, traj[-1], cond, uncond[0],
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        source_uses_cfg=True, null_uncond_embeddings=null_seq,
    )
    mse = float(jnp.mean((out[0] - x0[0]).astype(jnp.float32) ** 2))
    span = float(jnp.max(x0) - jnp.min(x0))
    return 10 * math.log10(span * span / max(mse, 1e-12))


def test_mixed_precision_recon_within_fp32_psnr_band(sched, problem):
    """The knob's contract: bf16 forwards with fp32 scheduler/Adam/loss
    islands must reconstruct within a few dB of the fp32 path (and both
    must massively beat the unoptimized raw-uncond replay)."""
    fn, x0, cond, uncond, traj = problem
    seqs = {}
    for precision in ("fp32", "mixed"):
        seqs[precision] = null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_precision=precision,
        )
    psnr_fp32 = _recon_psnr(sched, fn, traj, cond, uncond, seqs["fp32"], x0)
    psnr_mixed = _recon_psnr(sched, fn, traj, cond, uncond, seqs["mixed"], x0)
    psnr_raw = _recon_psnr(sched, fn, traj, cond, uncond, None, x0)
    assert psnr_fp32 > psnr_raw + 6.0, (psnr_fp32, psnr_raw)
    assert psnr_mixed > psnr_raw + 6.0, (psnr_mixed, psnr_raw)
    # the parity band: mixed stays within 3 dB of fp32 on the same replay
    assert psnr_mixed > psnr_fp32 - 3.0, (psnr_mixed, psnr_fp32)
    # ... and the mixed path really ran a different (bf16-boundary) forward
    assert not np.allclose(np.asarray(seqs["mixed"]), np.asarray(seqs["fp32"]))


def test_fused_program_matches_host_chunked(sched, problem):
    """One jitted donated-carry dispatch == the host-chunked program, for
    both precision modes (the structural change must not move numbers)."""
    fn, _, cond, uncond, traj = problem
    for precision in ("fp32", "mixed"):
        chunked = null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_precision=precision, outer_chunk=3,
        )
        # donate=False: the module-scope trajectory is reused across tests
        fused, stats = null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_precision=precision, donate=False, return_stats=True,
        )
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(chunked), rtol=2e-5, atol=2e-6
        )
        assert stats["final_loss"].shape == (STEPS,)
        assert stats["inner_steps"].shape == (STEPS,)
        assert stats["inner_steps"].dtype == jnp.int32


def _host_loop_reference(fn, sched, traj, cond, uncond, *, num_inner_steps,
                         epsilon=1e-5):
    """The reference's Python-loop-with-break null-text optimization
    (run_videop2p.py:580-612), eager on host: compute loss → backprop →
    Adam step → break when the pre-update loss cleared the threshold.
    Returns (per-step inner update counts, final embeddings sequence)."""
    adam = optax.adam(1.0)
    timesteps = np.asarray(sched.timesteps(STEPS))
    latent_cur = traj[-1]
    u = uncond.astype(jnp.float32)
    counts, seq = [], []
    for i in range(STEPS):
        t = timesteps[i]
        latent_prev = traj[STEPS - i - 1]
        lr = max(1e-2 * (1.0 - i / 100.0), 0.0)
        thresh = epsilon + i * 2e-5
        eps_cond = fn(None, latent_cur, t, cond, None)[0]

        def loss_fn(u_):
            eps_u = fn(None, latent_cur, t, u_, None)[0]
            eps = eps_u + GUIDANCE * (eps_cond - eps_u)
            prev_rec = sched.prev_step(eps, t, latent_cur, STEPS)
            return jnp.mean((prev_rec - latent_prev) ** 2)

        opt_state = adam.init(u)
        n = 0
        for _ in range(num_inner_steps):
            loss, grads = jax.value_and_grad(loss_fn)(u)
            updates, opt_state = adam.update(grads, opt_state, u)
            u = optax.apply_updates(u, jax.tree.map(lambda g: lr * g, updates))
            n += 1
            if float(loss) < thresh:
                break
        counts.append(n)
        seq.append(u)
        eps_u = fn(None, latent_cur, t, u, None)[0]
        eps = eps_u + GUIDANCE * (eps_cond - eps_u)
        latent_cur = sched.prev_step(eps, t, latent_cur, STEPS)
    return np.asarray(counts), jnp.stack(seq)


def test_fused_early_stop_takes_no_more_steps_than_host_loop(sched, problem):
    """The on-device convergence predicate must stop at least as early as
    the host loop it replaces — a fused loop that silently burns extra
    inner steps would eat the dispatch win it exists for."""
    fn, _, cond, uncond, traj = problem
    # ε chosen so the predicate genuinely fires on this problem: some outer
    # steps converge in a few inner updates, others saturate the bound —
    # a threshold nothing reaches would make the comparison vacuous
    eps = 2.0
    host_counts, host_seq = _host_loop_reference(
        fn, sched, traj, cond, uncond, num_inner_steps=10, epsilon=eps
    )
    _, stats = null_text_optimization_fused(
        fn, None, sched, traj, cond, uncond,
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        num_inner_steps=10, epsilon=eps, donate=False, return_stats=True,
    )
    fused_counts = np.asarray(stats["inner_steps"])
    assert (fused_counts <= host_counts).all(), (fused_counts, host_counts)
    assert fused_counts.min() < 10, fused_counts  # early stop fired...
    assert fused_counts.max() == 10, fused_counts  # ...and the bound binds


def test_precision_knob_validation(sched, problem):
    fn, _, cond, uncond, traj = problem
    with pytest.raises(ValueError, match="null_text_precision"):
        null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, null_text_precision="bf16",
        )
    with pytest.raises(ValueError, match="null_text_precision"):
        null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, null_text_precision="fp16",
        )


def test_official_edit_matches_split_flow(sched, problem):
    """official_edit (null-text + controlled CFG edit as ONE program) must
    equal the split flow that surfaces the embeddings on host."""
    fn, _, cond_src, uncond, traj = problem
    cond_all = jnp.concatenate([cond_src, cond_src + 0.2], axis=0)
    null_seq = null_text_optimization(
        fn, None, sched, traj, cond_src, uncond,
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
    )
    split = edit_sample(
        fn, None, sched, traj[-1], cond_all, uncond[0],
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        source_uses_cfg=True, null_uncond_embeddings=null_seq,
    )
    fused, stats = official_edit(
        fn, None, sched, traj, cond_all, uncond[0],
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        donate=False, return_null_stats=True,
    )
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(split), rtol=2e-5, atol=2e-6
    )
    assert stats["inner_steps"].shape == (STEPS,)


def test_inner_step_counts_thread_through_chunked_path(sched, problem):
    """return_inner_steps composes with outer_chunk (the counts concatenate
    across chunks in order)."""
    fn, _, cond, uncond, traj = problem
    full = null_text_optimization(
        fn, None, sched, traj, cond, uncond,
        num_inference_steps=STEPS, return_inner_steps=True,
    )
    chunked = null_text_optimization(
        fn, None, sched, traj, cond, uncond,
        num_inference_steps=STEPS, return_inner_steps=True, outer_chunk=3,
    )
    np.testing.assert_array_equal(np.asarray(full[1]), np.asarray(chunked[1]))
    assert full[1].shape == (STEPS,)


# ------------------------------------------------- bench record schema --


def test_official_e2e_records_schema_off_tpu():
    """The official-mode record schema must be emittable with null values
    (a run where a variant — or the whole extended bench — never measured)
    and carry consistent numbers when everything did."""
    spec = importlib.util.spec_from_file_location(
        "bench_schema_under_test",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    keys = {
        "official_edit_e2e_fp32_s", "official_edit_e2e_mixed_s",
        "null_text_inner_step_fp32_ms", "null_text_inner_step_mixed_ms",
        "official_vs_baseline_fp32", "official_vs_baseline_mixed",
    }
    # off-TPU: nothing measured — keys present, every value null
    empty = bench.official_e2e_records(None, None)
    assert set(empty) == keys
    assert all(v is None for v in empty.values())

    # one variant measured: its triple is populated, the other stays null
    partial = bench.official_e2e_records(
        10.0, 14.0, null_mixed_s=60.0, inner_steps=150
    )
    assert partial["official_edit_e2e_mixed_s"] == 84.0
    assert partial["null_text_inner_step_mixed_ms"] == 400.0
    assert partial["official_vs_baseline_mixed"] == round(600.0 / 84.0, 2)
    assert partial["official_edit_e2e_fp32_s"] is None
    assert partial["null_text_inner_step_fp32_ms"] is None

    both = bench.official_e2e_records(
        10.0, 14.0, null_fp32_s=203.0, null_mixed_s=60.0, inner_steps=150
    )
    assert both["official_edit_e2e_fp32_s"] == 227.0
    assert both["official_vs_baseline_fp32"] == round(600.0 / 227.0, 2)


# ------------------------------------------- cached.py float8 upcast --


def test_float8_upcast_follows_sibling_dtype():
    """base_tree_at must upcast float8 temporal maps to the SIBLING captured
    maps' dtype — fp32 cross maps ⇒ fp32 temporal reads (not a hardcoded
    bf16 that silently narrows an fp32 run), bf16 siblings ⇒ bf16, and a
    temporal-only capture falls back to fp32."""
    from videop2p_tpu.pipelines.cached import CachedSource

    f8 = jnp.float8_e4m3fn
    src = jnp.zeros((4, 1, 2, 4, 4, 4))
    temporal = {"block": {"attn_temp": {"probs": jnp.ones((3, 2, 1, 2, 2), f8)}}}

    for sibling_dtype in (jnp.float32, jnp.bfloat16):
        cross = {"block": {"attn2": {"probs": jnp.ones((2, 2, 1, 4, 8), sibling_dtype)}}}
        cached = CachedSource(
            src_latents=src, cross_maps=cross, temporal_maps=temporal,
            cross_len=2, self_window=(0, 3),
        )
        tree = cached.base_tree_at(jnp.asarray(0))
        got = tree["block"]["attn_temp"]["probs"].dtype
        assert got == sibling_dtype, (got, sibling_dtype)
        # the wide sibling itself is untouched
        assert tree["block"]["attn2"]["probs"].dtype == sibling_dtype

    only_temporal = CachedSource(
        src_latents=src, temporal_maps=temporal, self_window=(0, 3),
    )
    tree = only_temporal.base_tree_at(jnp.asarray(1))
    assert tree["block"]["attn_temp"]["probs"].dtype == jnp.float32
