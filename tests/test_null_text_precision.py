"""Scan-fused mixed-precision null-text inversion (pipelines/inversion.py
``null_text_optimization_fused`` + the ``null_text_precision`` knob).

CPU-runnable gates for the official-mode perf work:

  * mixed-vs-fp32 reconstruction parity, pinned as a PSNR band on the same
    replay the bench's ``official_fixed3_recon_psnr_db`` measures;
  * the fused single-dispatch program is the host-chunked program
    (identical outputs, fewer dispatches);
  * the fused loop's on-device early stop takes no more inner Adam steps
    than a faithful host-Python-loop-with-break reference;
  * the official-mode e2e record schema (bench.official_e2e_records) is
    exercised off-TPU — keys stable, values null when unmeasured;
  * CachedSource float8 upcast follows the sibling captured maps' dtype
    (ADVICE r5 item 1).

Fake denoisers keep everything eager-CPU-fast (the SURVEY §4 strategy).
"""

import importlib.util
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from videop2p_tpu.core import DDIMScheduler
from videop2p_tpu.pipelines import (
    ddim_inversion,
    edit_sample,
    null_text_optimization,
    null_text_optimization_fused,
    official_edit,
)

STEPS = 8
SHAPE = (1, 2, 8, 8, 4)  # (B, F, h, w, C)
GUIDANCE = 7.5


@pytest.fixture(scope="module")
def sched():
    return DDIMScheduler.create_sd()


def text_unet():
    """Denoiser whose output depends on the text embedding and latent — a
    real objective for the optimization, computed in the INPUT dtype (so
    the mixed knob's bf16 boundary cast genuinely changes the forward)."""

    def fn(params, sample, t, text, control=None):
        bias = jnp.mean(text, axis=(1, 2))  # (B,)
        return 0.1 * sample + bias[:, None, None, None, None], {}

    return fn


@pytest.fixture(scope="module")
def problem(sched):
    fn = text_unet()
    x0 = jax.random.normal(jax.random.key(0), SHAPE)
    cond = 0.3 * jnp.ones((1, 77, 8))
    uncond = jnp.zeros((1, 77, 8))
    traj = ddim_inversion(fn, None, sched, x0, cond, num_inference_steps=STEPS)
    return fn, x0, cond, uncond, traj


def _recon_psnr(sched, fn, traj, cond, uncond, null_seq, x0):
    """PSNR of the CFG replay driven by the optimized embeddings — the same
    reconstruction the bench's official_fixed3_recon_psnr_db gates."""
    out = edit_sample(
        fn, None, sched, traj[-1], cond, uncond[0],
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        source_uses_cfg=True, null_uncond_embeddings=null_seq,
    )
    mse = float(jnp.mean((out[0] - x0[0]).astype(jnp.float32) ** 2))
    span = float(jnp.max(x0) - jnp.min(x0))
    return 10 * math.log10(span * span / max(mse, 1e-12))


def test_mixed_precision_recon_within_fp32_psnr_band(sched, problem):
    """The knob's contract: bf16 forwards with fp32 scheduler/Adam/loss
    islands must reconstruct within a few dB of the fp32 path (and both
    must massively beat the unoptimized raw-uncond replay)."""
    fn, x0, cond, uncond, traj = problem
    seqs = {}
    for precision in ("fp32", "mixed"):
        seqs[precision] = null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_precision=precision,
        )
    psnr_fp32 = _recon_psnr(sched, fn, traj, cond, uncond, seqs["fp32"], x0)
    psnr_mixed = _recon_psnr(sched, fn, traj, cond, uncond, seqs["mixed"], x0)
    psnr_raw = _recon_psnr(sched, fn, traj, cond, uncond, None, x0)
    assert psnr_fp32 > psnr_raw + 6.0, (psnr_fp32, psnr_raw)
    assert psnr_mixed > psnr_raw + 6.0, (psnr_mixed, psnr_raw)
    # the parity band: mixed stays within 3 dB of fp32 on the same replay
    assert psnr_mixed > psnr_fp32 - 3.0, (psnr_mixed, psnr_fp32)
    # ... and the mixed path really ran a different (bf16-boundary) forward
    assert not np.allclose(np.asarray(seqs["mixed"]), np.asarray(seqs["fp32"]))


def test_fused_program_matches_host_chunked(sched, problem):
    """One jitted donated-carry dispatch == the host-chunked program, for
    both precision modes (the structural change must not move numbers)."""
    fn, _, cond, uncond, traj = problem
    for precision in ("fp32", "mixed"):
        chunked = null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_precision=precision, outer_chunk=3,
        )
        # donate=False: the module-scope trajectory is reused across tests
        fused, stats = null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_precision=precision, donate=False, return_stats=True,
        )
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(chunked), rtol=2e-5, atol=2e-6
        )
        assert stats["final_loss"].shape == (STEPS,)
        assert stats["inner_steps"].shape == (STEPS,)
        assert stats["inner_steps"].dtype == jnp.int32


def _host_loop_reference(fn, sched, traj, cond, uncond, *, num_inner_steps,
                         epsilon=1e-5):
    """The reference's Python-loop-with-break null-text optimization
    (run_videop2p.py:580-612), eager on host: compute loss → backprop →
    Adam step → break when the pre-update loss cleared the threshold.
    Returns (per-step inner update counts, final embeddings sequence)."""
    adam = optax.adam(1.0)
    timesteps = np.asarray(sched.timesteps(STEPS))
    latent_cur = traj[-1]
    u = uncond.astype(jnp.float32)
    counts, seq = [], []
    for i in range(STEPS):
        t = timesteps[i]
        latent_prev = traj[STEPS - i - 1]
        lr = max(1e-2 * (1.0 - i / 100.0), 0.0)
        thresh = epsilon + i * 2e-5
        eps_cond = fn(None, latent_cur, t, cond, None)[0]

        def loss_fn(u_):
            eps_u = fn(None, latent_cur, t, u_, None)[0]
            eps = eps_u + GUIDANCE * (eps_cond - eps_u)
            prev_rec = sched.prev_step(eps, t, latent_cur, STEPS)
            return jnp.mean((prev_rec - latent_prev) ** 2)

        opt_state = adam.init(u)
        n = 0
        for _ in range(num_inner_steps):
            loss, grads = jax.value_and_grad(loss_fn)(u)
            updates, opt_state = adam.update(grads, opt_state, u)
            u = optax.apply_updates(u, jax.tree.map(lambda g: lr * g, updates))
            n += 1
            if float(loss) < thresh:
                break
        counts.append(n)
        seq.append(u)
        eps_u = fn(None, latent_cur, t, u, None)[0]
        eps = eps_u + GUIDANCE * (eps_cond - eps_u)
        latent_cur = sched.prev_step(eps, t, latent_cur, STEPS)
    return np.asarray(counts), jnp.stack(seq)


def test_fused_early_stop_takes_no_more_steps_than_host_loop(sched, problem):
    """The on-device convergence predicate must stop at least as early as
    the host loop it replaces — a fused loop that silently burns extra
    inner steps would eat the dispatch win it exists for."""
    fn, _, cond, uncond, traj = problem
    # ε chosen so the predicate genuinely fires on this problem: some outer
    # steps converge in a few inner updates, others saturate the bound —
    # a threshold nothing reaches would make the comparison vacuous
    eps = 2.0
    host_counts, host_seq = _host_loop_reference(
        fn, sched, traj, cond, uncond, num_inner_steps=10, epsilon=eps
    )
    _, stats = null_text_optimization_fused(
        fn, None, sched, traj, cond, uncond,
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        num_inner_steps=10, epsilon=eps, donate=False, return_stats=True,
    )
    fused_counts = np.asarray(stats["inner_steps"])
    assert (fused_counts <= host_counts).all(), (fused_counts, host_counts)
    assert fused_counts.min() < 10, fused_counts  # early stop fired...
    assert fused_counts.max() == 10, fused_counts  # ...and the bound binds


def test_precision_knob_validation(sched, problem):
    fn, _, cond, uncond, traj = problem
    with pytest.raises(ValueError, match="null_text_precision"):
        null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, null_text_precision="bf16",
        )
    with pytest.raises(ValueError, match="null_text_precision"):
        null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, null_text_precision="fp16",
        )


# ---------------------------------------- amortized / hybrid modes --------


def test_mode_knob_validation_lists_valid_modes(sched, problem):
    """ISSUE 8 satellite: an unknown null_text_mode raises a ValueError
    naming every valid mode (the null_text_precision pattern), on both the
    plain and the fused entry points."""
    fn, _, cond, uncond, traj = problem
    for bad in ("npi", "OPTIMIZE", ""):
        with pytest.raises(ValueError, match="optimize.*amortized.*hybrid"):
            null_text_optimization(
                fn, None, sched, traj, cond, uncond,
                num_inference_steps=STEPS, null_text_mode=bad,
            )
    with pytest.raises(ValueError, match="optimize.*amortized.*hybrid"):
        null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, null_text_mode="closed_form",
        )
    with pytest.raises(ValueError, match="hybrid_inner_steps"):
        null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, null_text_mode="hybrid",
            hybrid_inner_steps=0,
        )


def test_amortized_and_hybrid_recon_parity_band(sched, problem):
    """The tentpole's quality contract: the closed-form amortized mode and
    the joint-refinement hybrid must reconstruct within a few dB of the
    optimize mode on the SAME CFG replay (and massively beat the raw
    uncond), while taking 0 / K inner Adam steps instead of 10×."""
    fn, x0, cond, uncond, traj = problem
    seqs, stats = {}, {}
    for mode in ("optimize", "amortized", "hybrid"):
        seqs[mode], stats[mode] = null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_mode=mode, donate=False, return_stats=True,
        )
    psnr = {m: _recon_psnr(sched, fn, traj, cond, uncond, s, x0)
            for m, s in seqs.items()}
    psnr_raw = _recon_psnr(sched, fn, traj, cond, uncond, None, x0)
    for mode in ("amortized", "hybrid"):
        assert psnr[mode] > psnr_raw + 6.0, (mode, psnr, psnr_raw)
        # the parity band: the cheap modes stay within 3 dB of optimize
        assert psnr[mode] > psnr["optimize"] - 3.0, (mode, psnr)
    # the structural claims: zero inner Adam steps amortized, K=3 hybrid,
    # and the loss record is the same reconstruction objective (finite,
    # comparable across modes)
    assert (np.asarray(stats["amortized"]["inner_steps"]) == 0).all()
    assert (np.asarray(stats["hybrid"]["inner_steps"]) == 3).all()
    for mode in ("amortized", "hybrid"):
        assert np.isfinite(np.asarray(stats[mode]["final_loss"])).all()
    # amortized really is the closed form: uncond := cond at every step
    np.testing.assert_array_equal(
        np.asarray(seqs["amortized"]),
        np.broadcast_to(np.asarray(cond, np.float32),
                        (STEPS,) + cond.shape),
    )


def test_new_modes_fused_matches_chunked(sched, problem):
    """ISSUE 8 satellite: fused == chunked for the NEW modes too — the
    amortized scan chunks like the optimize scan, and the hybrid joint
    refinement is step-independent (absolute-index keys), so slicing the
    step axis must not move numbers."""
    fn, _, cond, uncond, traj = problem
    for mode in ("amortized", "hybrid"):
        chunked = null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_mode=mode, outer_chunk=3,
            return_losses=True, return_inner_steps=True,
        )
        fused, fstats = null_text_optimization_fused(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_mode=mode, donate=False, return_stats=True,
        )
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(chunked[0]), rtol=2e-5, atol=2e-6
        )
        np.testing.assert_allclose(
            np.asarray(fstats["final_loss"]), np.asarray(chunked[1]),
            rtol=2e-5, atol=2e-7,
        )
        np.testing.assert_array_equal(
            np.asarray(fstats["inner_steps"]), np.asarray(chunked[2])
        )


def test_official_edit_mode_knob_matches_split_flow(sched, problem):
    """official_edit(null_text_mode=...) must equal the split flow driven
    by the same mode's embedding sequence — the fused official program and
    the library path cannot drift per mode."""
    fn, _, cond_src, uncond, traj = problem
    cond_all = jnp.concatenate([cond_src, cond_src + 0.2], axis=0)
    for mode in ("amortized", "hybrid"):
        null_seq = null_text_optimization(
            fn, None, sched, traj, cond_src, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_mode=mode,
        )
        split = edit_sample(
            fn, None, sched, traj[-1], cond_all, uncond[0],
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            source_uses_cfg=True, null_uncond_embeddings=null_seq,
        )
        fused, stats = official_edit(
            fn, None, sched, traj, cond_all, uncond[0],
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_mode=mode, donate=False, return_null_stats=True,
        )
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(split), rtol=2e-5, atol=2e-6
        )
        expected_inner = 0 if mode == "amortized" else 3
        assert (np.asarray(stats["inner_steps"]) == expected_inner).all()


def test_cheap_modes_pass_quality_rules_via_obs_diff(
    sched, problem, tmp_path
):
    """The ISSUE 8 acceptance gate, end to end: write the optimize-mode
    reconstruction's quality record as the baseline ledger and each cheap
    mode's (amortized, hybrid) as the new run, then tools/obs_diff.py must
    exit 0 — the substitutes' reconstruction parity clears QUALITY_RULES
    machine-checkably (and a fabricated recon drop exits 1, proving the
    gate has teeth)."""
    import importlib.util

    from videop2p_tpu.obs import RunLedger
    from videop2p_tpu.obs.quality import edit_quality_record

    fn, x0, cond, uncond, traj = problem

    def recon01(null_seq):
        out = edit_sample(
            fn, None, sched, traj[-1], cond, uncond[0],
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            source_uses_cfg=True, null_uncond_embeddings=null_seq,
        )
        lo, hi = float(jnp.min(x0)), float(jnp.max(x0))
        to01 = lambda v: (jnp.clip(v, lo, hi) - lo) / max(hi - lo, 1e-9)  # noqa: E731
        return np.asarray(to01(out[0])), np.asarray(to01(x0[0]))

    ledgers = {}
    for mode in ("optimize", "amortized", "hybrid"):
        null_seq = null_text_optimization(
            fn, None, sched, traj, cond, uncond,
            num_inference_steps=STEPS, guidance_scale=GUIDANCE,
            null_text_mode=mode,
        )
        recon, src = recon01(null_seq)
        summary, _ = edit_quality_record(src, recon, recon)
        path = str(tmp_path / f"{mode}.jsonl")
        with RunLedger(path) as led:
            led.event("quality", program="edit_quality", **summary)
        ledgers[mode] = path

    spec = importlib.util.spec_from_file_location(
        "obs_diff_under_null_test",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "obs_diff.py"),
    )
    obs_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_diff)
    for mode in ("amortized", "hybrid"):
        assert obs_diff.main(
            ["obs_diff.py", ledgers["optimize"], ledgers[mode]]
        ) == 0, mode
    # teeth: a fabricated recon drop far below the BASELINE must regress
    # (exit 1) — the rule gates against the optimize run's value
    import json as _json

    dropped = str(tmp_path / "dropped.jsonl")
    with open(ledgers["amortized"]) as f, open(dropped, "w") as g:
        for line in f:
            e = _json.loads(line)
            if e.get("event") == "quality":
                e["recon_psnr"] = float(e["recon_psnr"]) - 40.0
            g.write(_json.dumps(e) + "\n")
    assert obs_diff.main(
        ["obs_diff.py", ledgers["optimize"], dropped]
    ) == 1


def test_official_edit_matches_split_flow(sched, problem):
    """official_edit (null-text + controlled CFG edit as ONE program) must
    equal the split flow that surfaces the embeddings on host."""
    fn, _, cond_src, uncond, traj = problem
    cond_all = jnp.concatenate([cond_src, cond_src + 0.2], axis=0)
    null_seq = null_text_optimization(
        fn, None, sched, traj, cond_src, uncond,
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
    )
    split = edit_sample(
        fn, None, sched, traj[-1], cond_all, uncond[0],
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        source_uses_cfg=True, null_uncond_embeddings=null_seq,
    )
    fused, stats = official_edit(
        fn, None, sched, traj, cond_all, uncond[0],
        num_inference_steps=STEPS, guidance_scale=GUIDANCE,
        donate=False, return_null_stats=True,
    )
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(split), rtol=2e-5, atol=2e-6
    )
    assert stats["inner_steps"].shape == (STEPS,)


def test_inner_step_counts_thread_through_chunked_path(sched, problem):
    """return_inner_steps composes with outer_chunk (the counts concatenate
    across chunks in order)."""
    fn, _, cond, uncond, traj = problem
    full = null_text_optimization(
        fn, None, sched, traj, cond, uncond,
        num_inference_steps=STEPS, return_inner_steps=True,
    )
    chunked = null_text_optimization(
        fn, None, sched, traj, cond, uncond,
        num_inference_steps=STEPS, return_inner_steps=True, outer_chunk=3,
    )
    np.testing.assert_array_equal(np.asarray(full[1]), np.asarray(chunked[1]))
    assert full[1].shape == (STEPS,)


# ------------------------------------------------- bench record schema --


def test_official_e2e_records_schema_off_tpu():
    """The official-mode record schema must be emittable with null values
    (a run where a variant — or the whole extended bench — never measured)
    and carry consistent numbers when everything did."""
    spec = importlib.util.spec_from_file_location(
        "bench_schema_under_test",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    keys = {
        "official_edit_e2e_fp32_s", "official_edit_e2e_mixed_s",
        "official_edit_e2e_amortized_s", "official_edit_e2e_hybrid_s",
        "null_text_inner_step_fp32_ms", "null_text_inner_step_mixed_ms",
        "official_vs_baseline_fp32", "official_vs_baseline_mixed",
        "official_vs_baseline_amortized", "official_vs_baseline_hybrid",
    }
    # off-TPU: nothing measured — keys present, every value null
    empty = bench.official_e2e_records(None, None)
    assert set(empty) == keys
    assert all(v is None for v in empty.values())

    # one variant measured: its triple is populated, the others stay null
    partial = bench.official_e2e_records(
        10.0, 14.0, null_mixed_s=60.0, inner_steps=150
    )
    assert partial["official_edit_e2e_mixed_s"] == 84.0
    assert partial["null_text_inner_step_mixed_ms"] == 400.0
    assert partial["official_vs_baseline_mixed"] == round(600.0 / 84.0, 2)
    assert partial["official_edit_e2e_fp32_s"] is None
    assert partial["null_text_inner_step_fp32_ms"] is None
    assert partial["official_edit_e2e_amortized_s"] is None
    assert partial["official_vs_baseline_hybrid"] is None

    both = bench.official_e2e_records(
        10.0, 14.0, null_fp32_s=203.0, null_mixed_s=60.0,
        null_amortized_s=3.0, null_hybrid_s=12.0, inner_steps=150,
    )
    assert both["official_edit_e2e_fp32_s"] == 227.0
    assert both["official_vs_baseline_fp32"] == round(600.0 / 227.0, 2)
    assert both["official_edit_e2e_amortized_s"] == 27.0
    assert both["official_vs_baseline_amortized"] == round(600.0 / 27.0, 2)
    assert both["official_edit_e2e_hybrid_s"] == 36.0


def test_null_text_flop_records_guarantee_3x_reduction():
    """The per-mode flop accounting (bench.null_text_flop_records): built
    from straight-line unit analyses with the disclosed loop structure, at
    the official defaults (I=10, K=3) the hybrid reduction is ≥3× for ANY
    inner/forward cost ratio ≥1 and the amortized reduction is far larger."""
    spec = importlib.util.spec_from_file_location(
        "bench_flops_under_test",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    for inner_over_fwd in (1.0, 2.0, 3.0, 10.0):
        f = 1e9
        rec = bench.null_text_flop_records(f, inner_over_fwd * f)
        assert rec["null_text_flops_reduction_amortized"] >= 3.0
        assert rec["null_text_flops_reduction_hybrid"] >= 3.0, rec
        # the totals follow the disclosed formulas exactly
        assert rec["null_text_total_flops_amortized"] == 50 * f
        assert rec["null_text_total_flops_optimize"] == 50 * (
            2 * f + 10 * inner_over_fwd * f
        )
        assert rec["null_text_total_flops_hybrid"] == 50 * (
            f + 3 * inner_over_fwd * f
        )
    # the record is schema-stable (bench_details.json keys)
    assert {
        "null_text_unit_fwd_flops", "null_text_unit_inner_flops",
        "null_text_flop_params",
        "null_text_total_flops_optimize", "null_text_total_flops_amortized",
        "null_text_total_flops_hybrid",
        "null_text_flops_reduction_amortized",
        "null_text_flops_reduction_hybrid",
    } == set(bench.null_text_flop_records(1.0, 1.0))


# ------------------------------------------- cached.py float8 upcast --


def test_float8_upcast_follows_sibling_dtype():
    """base_tree_at must upcast float8 temporal maps to the SIBLING captured
    maps' dtype — fp32 cross maps ⇒ fp32 temporal reads (not a hardcoded
    bf16 that silently narrows an fp32 run), bf16 siblings ⇒ bf16, and a
    temporal-only capture falls back to fp32."""
    from videop2p_tpu.pipelines.cached import CachedSource

    f8 = jnp.float8_e4m3fn
    src = jnp.zeros((4, 1, 2, 4, 4, 4))
    temporal = {"block": {"attn_temp": {"probs": jnp.ones((3, 2, 1, 2, 2), f8)}}}

    for sibling_dtype in (jnp.float32, jnp.bfloat16):
        cross = {"block": {"attn2": {"probs": jnp.ones((2, 2, 1, 4, 8), sibling_dtype)}}}
        cached = CachedSource(
            src_latents=src, cross_maps=cross, temporal_maps=temporal,
            cross_len=2, self_window=(0, 3),
        )
        tree = cached.base_tree_at(jnp.asarray(0))
        got = tree["block"]["attn_temp"]["probs"].dtype
        assert got == sibling_dtype, (got, sibling_dtype)
        # the wide sibling itself is untouched
        assert tree["block"]["attn2"]["probs"].dtype == sibling_dtype

    only_temporal = CachedSource(
        src_latents=src, temporal_maps=temporal, self_window=(0, 3),
    )
    tree = only_temporal.base_tree_at(jnp.asarray(1))
    assert tree["block"]["attn_temp"]["probs"].dtype == jnp.float32
