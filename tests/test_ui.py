"""UI-layer tests: upload flow (fake HF API), trainer config building,
experiment discovery, and inference latent validation — no gradio or network
needed (the modules gate those imports)."""

import json
import os

import numpy as np
import pytest

from videop2p_tpu.ui import ModelUploader, Trainer, UploadTarget, Uploader, find_exp_dirs


class FakeApi:
    def __init__(self, token=None, fail_create=False):
        self.token = token
        self.fail_create = fail_create
        self.calls = []

    def whoami(self):
        return {"name": "testuser"}

    def delete_repo(self, repo_id, repo_type=None):
        self.calls.append(("delete", repo_id))

    def create_repo(self, repo_id, repo_type=None, private=None):
        if self.fail_create:
            raise RuntimeError("409 Conflict: repo exists")
        self.calls.append(("create", repo_id, private))

    def upload_folder(self, *, repo_id, folder_path, path_in_repo, repo_type):
        self.calls.append(("upload", repo_id, folder_path))


def make_uploader(cls=Uploader, token="tok", **api_kwargs):
    api = FakeApi(**api_kwargs)
    up = cls(token, api_factory=lambda t: api)
    return up, api


def test_upload_personal_profile_defaults_org_to_whoami(tmp_path):
    up, api = make_uploader()
    msg = up.upload(str(tmp_path), "my-model")
    assert "huggingface.co/testuser/my-model" in msg
    assert ("create", "testuser/my-model", True) in api.calls
    assert ("upload", "testuser/my-model", str(tmp_path)) in api.calls


def test_upload_delete_existing_and_errors_surface(tmp_path):
    up, api = make_uploader(fail_create=True)
    msg = up.upload(str(tmp_path), "m", delete_existing_repo=True)
    assert ("delete", "testuser/m") in api.calls
    assert "409" in msg  # API error becomes the status message

    with pytest.raises(ValueError):
        up.upload("", "m")
    with pytest.raises(ValueError):
        up.upload(str(tmp_path), "")


def test_model_uploader_routing_and_slugify(tmp_path):
    exp = tmp_path / "My Experiment_2024"
    exp.mkdir()
    up, api = make_uploader(ModelUploader)
    msg = up.upload_model(str(exp), "", UploadTarget.MODEL_LIBRARY.value)
    # name defaults to the dir name, slugified; library org routes the repo
    assert "Video-P2P-library/my-experiment_2024" in msg

    up2, api2 = make_uploader(ModelUploader)
    up2.upload_model(str(exp), "Name With Spaces", UploadTarget.PERSONAL_PROFILE.value)
    assert ("create", "testuser/name-with-spaces", True) in api2.calls

    with pytest.raises(ValueError, match="unknown upload target"):
        up.upload_model(str(exp), "x", "Nowhere")


def test_trainer_config_schemas(tmp_path):
    t = Trainer(experiments_dir=str(tmp_path / "exp"), checkpoint_dir=str(tmp_path / "ck"))
    cfg = t.build_tune_config(
        video_path="data/rabbit", training_prompt="a rabbit",
        validation_prompt="an origami rabbit", base_model=str(tmp_path / "base"),
        output_dir=str(tmp_path / "out"), n_steps=7,
    )
    # the reference's Stage-1 schema keys (configs/rabbit-jump-tune.yaml)
    assert cfg["max_train_steps"] == 7
    assert cfg["train_data"]["prompt"] == "a rabbit"
    assert cfg["validation_data"]["prompts"] == ["an origami rabbit"]
    assert cfg["trainable_modules"] == ["attn1.to_q", "attn2.to_q", "attn_temp"]

    p2p = t.build_p2p_config(
        output_dir=str(tmp_path / "out"), video_path="data/rabbit",
        training_prompt="a rabbit is jumping",
        editing_prompt="a origami rabbit is jumping",
        blend_word_src="rabbit", blend_word_tgt="rabbit", eq_word="origami",
    )
    assert p2p["prompts"][1] == "a origami rabbit is jumping"
    assert p2p["blend_word"] == ["rabbit", "rabbit"]
    assert p2p["eq_params"] == {"words": ["origami"], "values": [2.0]}
    assert p2p["is_word_swap"] is False  # different prompt lengths


def test_find_exp_dirs_orders_by_mtime(tmp_path):
    for i, name in enumerate(["a", "b"]):
        d = tmp_path / name
        d.mkdir()
        (d / "model_index.json").write_text(json.dumps({}))
        os.utime(d / "model_index.json", (1000 + i, 1000 + i))
    dirs = find_exp_dirs(str(tmp_path))
    assert [os.path.basename(d) for d in dirs] == ["b", "a"]
    assert find_exp_dirs(str(tmp_path / "missing")) == []


def test_inference_rejects_mismatched_inv_latent(tmp_path, monkeypatch):
    """A stored inversion latent whose shape doesn't match the request must be
    ignored (fresh-noise fallback), not silently sampled from."""
    from videop2p_tpu.ui.inference import InferencePipeline

    pipe = InferencePipeline()
    pipe.checkpoint_dir = str(tmp_path)
    inv_dir = tmp_path / "inv_latents"
    inv_dir.mkdir()
    np.save(inv_dir / "ddim_latent-100.npy", np.zeros((1, 4, 8, 8, 4), np.float32))
    got = pipe._latest_inv_latent()
    assert got.shape == (1, 4, 8, 8, 4)
    # run() would reject it for a 2-frame request; check the guard directly
    expected = (1, 2, 8, 8, 4)
    assert tuple(got.shape) != expected


def test_trainer_run_p2p_prefers_engine_then_falls_back(tmp_path, monkeypatch):
    """ISSUE 7 satellite: with a healthy serving engine the Edit tab's
    run_p2p never spawns a subprocess; an unavailable/failed engine falls
    back to the subprocess CLI path unchanged."""
    import videop2p_tpu.ui.inference as inference_mod

    t = Trainer(experiments_dir=str(tmp_path / "exp"),
                checkpoint_dir=str(tmp_path / "ck"))
    exp = tmp_path / "exp" / "demo"
    exp.mkdir(parents=True)
    launches = []
    monkeypatch.setattr(
        t, "_launch", lambda *a, **k: (launches.append(a), 0)[1]
    )
    kw = dict(output_dir=str(exp), video_path="data/rabbit",
              training_prompt="a rabbit is jumping",
              editing_prompt="a origami rabbit is jumping")

    served = []
    monkeypatch.setattr(
        inference_mod, "edit_via_engine",
        lambda url, cfg, **k: (served.append((url, cfg)), "served.gif")[1],
    )
    out = t.run_p2p(engine_url="http://fake:8000", **kw)
    assert out == exp.as_posix()
    assert served and not launches  # engine handled it, no subprocess
    url, cfg = served[0]
    assert url == "http://fake:8000"
    assert cfg["prompts"][1] == "a origami rabbit is jumping"

    # engine says "fall back" (None) -> the subprocess path runs
    monkeypatch.setattr(inference_mod, "edit_via_engine",
                        lambda url, cfg, **k: None)
    t.run_p2p(engine_url="http://fake:8000", **kw)
    assert len(launches) == 1
    # no engine configured at all -> straight to subprocess
    monkeypatch.delenv("VIDEOP2P_SERVE_URL", raising=False)
    t.run_p2p(**kw)
    assert len(launches) == 2


def test_edit_via_engine_fallback_semantics(monkeypatch):
    """edit_via_engine returns None (= use the subprocess) for an absent
    engine, a failed request, or an error record — and the gif path on
    success."""
    import videop2p_tpu.serve.client as client_mod
    from videop2p_tpu.ui.inference import edit_via_engine

    cfg = {"image_path": "data/rabbit", "prompt": "a",
           "prompts": ["a", "b"], "save_name": "x",
           "pretrained_model_path": "ignored", "video_len": 8}
    assert edit_via_engine(None, cfg) is None
    monkeypatch.setattr(client_mod, "engine_available", lambda url, **k: False)
    assert edit_via_engine("http://down", cfg) is None

    class FakeClient:
        def __init__(self, url, **k):
            self.url = url

        def submit(self, request):
            # engine-irrelevant fields were stripped before the wire
            assert "pretrained_model_path" not in request
            assert "video_len" not in request
            return "abc123"

        def wait(self, rid, **k):
            return {"status": "done", "edit_gif": "/srv/out.gif",
                    "total_s": 0.1, "store_hit": True, "compile_events": 0}

    monkeypatch.setattr(client_mod, "engine_available", lambda url, **k: True)
    monkeypatch.setattr(client_mod, "EngineClient", FakeClient)
    assert edit_via_engine("http://up", cfg) == "/srv/out.gif"

    class ErrorClient(FakeClient):
        def wait(self, rid, **k):
            return {"status": "error", "error": "boom"}

    monkeypatch.setattr(client_mod, "EngineClient", ErrorClient)
    assert edit_via_engine("http://up", cfg) is None


def test_metrics_logger_jsonl(tmp_path):
    from videop2p_tpu.utils.metrics import MetricsLogger

    with MetricsLogger(str(tmp_path), use_tensorboard=False) as m:
        m.log(1, {"train_loss": 0.5, "lr": 3e-5})
        m.log(2, {"train_loss": 0.25, "lr": 3e-5})
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    assert [l["step"] for l in lines] == [1, 2]
    assert lines[1]["train_loss"] == 0.25
    assert all("wall_s" in l for l in lines)


def test_bundle_make_scheduler_uses_checkpoint_config():
    from videop2p_tpu.cli.common import ModelBundle

    b = ModelBundle(
        unet=None, unet_params={}, vae=None, vae_params=None,
        text_encoder=None, text_params=None, tokenizer=None,
        random_init=True, source_dir=None,
        scheduler_config={"steps_offset": 1, "beta_schedule": "scaled_linear",
                          "beta_start": 0.00085, "beta_end": 0.012},
    )
    assert b.make_scheduler().steps_offset == 1
    b2 = ModelBundle(
        unet=None, unet_params={}, vae=None, vae_params=None,
        text_encoder=None, text_params=None, tokenizer=None,
        random_init=True, source_dir=None,
    )
    assert b2.make_scheduler().steps_offset == 0  # SD default fallback
