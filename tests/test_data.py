"""Data-layer tests: single-video dataset sampling/normalization and the
Stage-2 frame loader's crop semantics (reference dataset.py + load_512_seq,
run_videop2p.py:413-440)."""

import numpy as np
import pytest
from PIL import Image

from videop2p_tpu.data import SingleVideoDataset, load_frame_sequence
from videop2p_tpu.data.dataset import _numeric_sort


@pytest.fixture()
def frame_dir(tmp_path):
    # 12 numbered frames, non-square (80×60), each a solid gray = its index
    for i in range(1, 13):
        arr = np.full((60, 80, 3), i * 10, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"{i}.jpg", quality=95)
    return str(tmp_path)


def test_numeric_sort_matches_reference():
    names = [f"{i}.jpg" for i in range(1, 12)]
    import random

    shuffled = names[:]
    random.Random(0).shuffle(shuffled)
    # '10.jpg' must come after '9.jpg' (int sort, not lexicographic —
    # dataset.py:37)
    assert _numeric_sort(shuffled) == names


def test_dataset_sampling_and_range(frame_dir):
    ds = SingleVideoDataset(
        video_path=frame_dir, prompt="p", width=16, height=16,
        n_sample_frames=4, sample_start_idx=1, sample_frame_rate=2,
    )
    assert len(ds) == 1
    clip = ds.load()
    assert clip.shape == (4, 16, 16, 3)
    assert clip.min() >= -1.0 and clip.max() <= 1.0
    # frames 2, 4, 6, 8 (1-based names; start 1, stride 2) → gray 20,40,60,80
    means = ((clip.mean(axis=(1, 2, 3)) + 1) * 127.5).round()
    np.testing.assert_allclose(means, [20, 40, 60, 80], atol=2)

    with pytest.raises(ValueError, match="exceed"):
        SingleVideoDataset(
            video_path=frame_dir, prompt="p", n_sample_frames=8,
            sample_start_idx=0, sample_frame_rate=2,
        ).load()


def test_frame_sequence_center_square_crop(frame_dir):
    seq = load_frame_sequence(frame_dir, size=32, num_frames=3)
    assert seq.shape == (3, 32, 32, 3)
    assert seq.dtype == np.uint8
    # solid frames survive the crop+resize as the same gray value
    np.testing.assert_allclose(seq[1].mean(), 20, atol=2)

    # edge-crop args remove rows/cols before the square crop
    seq2 = load_frame_sequence(frame_dir, size=16, num_frames=1, left=10, top=5)
    assert seq2.shape == (1, 16, 16, 3)
